"""Arm-by-arm on-chip profiling of the fused ingest step.

Round-4 diagnostic: the first post-rework real-chip stream measured
~2.2 s per 57k-span step vs the ~150 ms the round-3 cost model
predicts. This script times each arm of ingest_step in isolation at
the same shapes so the pathology has a name before we fix it.

Usage (chip must be otherwise idle — NOTES_r03 §7):
    python scripts/profile_ingest.py [--cap-log2 22] [--traces 16384]

Every timing uses jax.device_get of a scalar as the barrier
(block_until_ready is not reliable through the tunnel).
"""

import argparse
import sys
import time
from functools import partial

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cap-log2", type=int, default=22)
    ap.add_argument("--traces", type=int, default=16384)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--batch-spans-sweep", default="",
                    help="comma-separated span counts: re-template the "
                         "full step at each batch size and time it "
                         "(the r12 batch-escalation knee finder, e.g. "
                         "57344,114688,229376,458752)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from bench import _tpu_config, _make_template

    print("backend:", jax.default_backend(), flush=True)

    def timeit(name, fn, *a, reps=args.reps, sync=None, **kw):
        # warmup (compile)
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        s = sync(out) if sync else jax.device_get(
            jax.tree_util.tree_leaves(out)[0]
        )
        t1 = time.perf_counter()
        times = []
        for _ in range(reps):
            t2 = time.perf_counter()
            out = fn(*a, **kw)
            s = sync(out) if sync else jax.device_get(
                jax.tree_util.tree_leaves(out)[0]
            )
            times.append(time.perf_counter() - t2)
        del s
        print(f"{name:42s} compile+1st {t1 - t0:8.3f}s   "
              f"steady {min(times) * 1e3:9.1f} ms", flush=True)
        return out

    # 0. dispatch floor today
    one = jnp.ones((8, 128), jnp.float32)
    f_triv = jax.jit(lambda x: x * 2.0 + 1.0)
    timeit("dispatch floor (trivial jit)", f_triv, one, reps=10)

    config = _tpu_config(args.cap_log2, 1024, False)
    store = TpuSpanStore(config)
    db0, fused_chain, pad_spans = _make_template(store, 1024, args.traces)
    state = dev.init_state(config)
    state = jax.device_put(state)
    print(f"shapes: P={pad_spans} PA={db0.ann_ts.shape[0]} "
          f"PB={db0.bann_key_id.shape[0]} cap=2^{args.cap_log2}",
          flush=True)

    c = config
    S = c.max_services
    P = db0.trace_id.shape[0]
    PA = db0.ann_ts.shape[0]
    PB = db0.bann_key_id.shape[0]
    b = db0
    mask = jnp.arange(P) < b.n_spans
    mask_a = jnp.arange(PA) < b.n_anns
    mask_b = jnp.arange(PB) < b.n_banns

    # 1. full single ingest step (donate state copy each call would free
    # it; use a non-donated wrapper so we can repeat on the same state)
    step_once = jax.jit(lambda s, d: dev.ingest_step.__wrapped__(s, d))
    state2 = timeit(
        "ingest_step FULL (1 step)", step_once, state, b,
        sync=lambda s: float(jax.device_get(s.counters["spans_seen"])),
    )
    del state2

    # 2. ring writes only
    def ring_only(st, bb):
        gids = st.write_pos + jnp.arange(P, dtype=jnp.int64)
        slots = (gids % c.capacity).astype(jnp.int32)
        widx = jnp.where(mask, slots, c.capacity)
        outs = []
        for col in ("trace_id", "span_id", "parent_id", "name_id",
                    "ts_cs", "ts_cr", "ts_sr", "ts_ss", "duration"):
            outs.append(getattr(st, col).at[widx].set(
                getattr(bb, col), mode="drop"))
        return outs[0].sum()

    timeit("ring column writes (9 cols)", jax.jit(ring_only), state, b)

    # 3. span_tab insert (4-round scatter-min probe)
    def tab_only(st, bb):
        skey = dev._mix48(bb.trace_id, bb.span_id)
        tab = dev._tab_insert(st.span_tab, skey, bb.service_id, mask)
        return tab.sum()

    timeit("span_tab insert (hash join build)", jax.jit(tab_only), state, b)

    # 4. resolve links + window fold
    def dep_only(st, bb):
        skey = dev._mix48(bb.trace_id, bb.span_id)
        tab = dev._tab_insert(st.span_tab, skey, bb.service_id, mask)
        resolved, link_id, pending, ckey = dev._resolve_links(
            tab, bb.trace_id, bb.span_id, bb.parent_id, bb.service_id,
            bb.service_id, bb.duration, mask, mask & bb.has_parent, S,
        )
        w, wts = dev._window_fold(
            st.dep_window, st.dep_window_ts, bb.duration, link_id,
            resolved, bb.ts_first, bb.ts_last, S,
        )
        return w.sum()

    timeit("dep join (insert+resolve+fold)", jax.jit(dep_only), state, b)

    # 5. combined candidate index write (the concat + rank-sort + scatter)
    from zipkin_tpu.store.device import (
        StoreConfig, _bucket_of, _mixb, _verify_of, _span_host_range,
        FIRST_USER_ANNOTATION_ID,
    )

    def cand_only(st, bb):
        lay, _, _ = c.idx_layout
        a_host = bb.ann_service_id
        a_idx_ok = mask_a & (a_host >= 0) & (a_host < S)
        span_gid_of_ann = st.write_pos + bb.ann_span_idx.astype(jnp.int64)
        gid_a = jnp.where(a_idx_ok, span_gid_of_ann, -1)
        ts_a = bb.ts_last[bb.ann_span_idx]

        def seg(fam, local_bucket, gid, verify, ts, ok):
            b_base, s_base, n_b, depth = lay[fam]
            lb = jnp.clip(local_bucket, 0, n_b - 1)
            n = lb.shape[0]
            return fam, (
                lb.astype(jnp.int32) + jnp.int32(b_base),
                lb.astype(jnp.int64) * depth + jnp.int64(s_base),
                jnp.full(n, depth, jnp.int32),
                jnp.asarray(gid, jnp.int64),
                jnp.asarray(verify, jnp.int64),
                jnp.asarray(ts, jnp.int64),
                ok,
            )

        segments = [seg(StoreConfig.CAND_SVC, a_host, gid_a, a_host,
                        ts_a, a_idx_ok)]
        ann_name_lc_i = bb.name_lc_id[bb.ann_span_idx]
        nm_ok = a_idx_ok & (ann_name_lc_i >= 0)
        nm_mix = _mixb([a_host, ann_name_lc_i])
        segments.append(seg(
            StoreConfig.CAND_NAME, _bucket_of(nm_mix, c.name_buckets),
            gid_a, _verify_of(nm_mix), ts_a, nm_ok,
        ))
        hmin, hmax = _span_host_range(a_host, bb.ann_span_idx, a_idx_ok, P)
        h1 = hmin[bb.ann_span_idx]
        h2 = hmax[bb.ann_span_idx]
        v_ok = (
            mask_a & (bb.ann_value_id >= FIRST_USER_ANNOTATION_ID)
            & (bb.ann_value_id < jnp.int32(1 << 30))
        )
        for h, extra in ((h1, None), (h2, h2 != h1)):
            ok = v_ok & (h >= 0) & (h < S)
            if extra is not None:
                ok &= extra
            mix = _mixb([h, bb.ann_value_id])
            segments.append(seg(
                StoreConfig.CAND_ANN, _bucket_of(mix, c.ann_buckets),
                jnp.where(ok, span_gid_of_ann, -1), _verify_of(mix),
                ts_a, ok,
            ))
        span_gid_of_bann = st.write_pos + bb.bann_span_idx.astype(jnp.int64)
        bh1 = hmin[bb.bann_span_idx]
        bh2 = hmax[bb.bann_span_idx]
        bk_idx_ok = mask_b & (bb.bann_key_id >= 0)
        ts_b = bb.ts_last[bb.bann_span_idx]
        no_val = jnp.full(PB, -1, jnp.int32)
        for h, val, extra in (
            (bh1, bb.bann_value_id, None),
            (bh2, bb.bann_value_id, bh2 != bh1),
            (bh1, no_val, None), (bh2, no_val, bh2 != bh1),
        ):
            ok = bk_idx_ok & (h >= 0) & (h < S)
            if extra is not None:
                ok &= extra
            mix = _mixb([h, bb.bann_key_id, val])
            segments.append(seg(
                StoreConfig.CAND_BANN, _bucket_of(mix, c.bann_buckets),
                jnp.where(ok, span_gid_of_bann, -1), _verify_of(mix),
                ts_b, ok,
            ))
        fams = [f for f, _ in segments]
        assert (fams[0] == StoreConfig.CAND_SVC
                and StoreConfig.CAND_SVC not in fams[1:]), fams
        n_cand_rows = sum(p[0].shape[0] for _, p in segments)
        # Trace-membership segments trail in the SAME unified pass (the
        # r6 arena merge): one rank sort + scatter block for all seven
        # families — this arm now measures the whole index write.
        tb = _bucket_of(_mixb([bb.trace_id]), c.trace_buckets)
        tmix = _verify_of(_mixb([bb.trace_id]))
        gids = st.write_pos + jnp.arange(P, dtype=jnp.int64)
        a_gids = st.ann_write_pos + jnp.arange(PA, dtype=jnp.int64)
        bb_gids = st.bann_write_pos + jnp.arange(PB, dtype=jnp.int64)
        NC = StoreConfig.N_CAND_FAMILIES
        segments.append(seg(NC + StoreConfig.TR_SPAN, tb, gids, tmix,
                            bb.ts_last, mask))
        segments.append(seg(NC + StoreConfig.TR_ANN, tb[bb.ann_span_idx],
                            a_gids, tmix[bb.ann_span_idx], ts_a, mask_a))
        segments.append(seg(NC + StoreConfig.TR_BANN,
                            tb[bb.bann_span_idx], bb_gids,
                            tmix[bb.bann_span_idx], ts_b, mask_b))
        cat = [jnp.concatenate(parts)
               for parts in zip(*(p for _, p in segments))]
        out = dev._index_write(
            st.cand_idx, st.cand_pos, st.cand_wm, st.key_tab, st.key_wm,
            st.ann_poison, *cat,
            keyed_from=segments[0][1][0].shape[0],
            n_cand_rows=n_cand_rows, n_cand_buckets=c.cand_layout[1],
            poison_bucket=a_host, poison_gid=span_gid_of_ann,
            poison_ok=a_idx_ok & (a_host != h1) & (a_host != h2),
        )
        return out[0].sum()

    timeit("unified index write (cand+trace, concat+sort+scatter)",
           jax.jit(cand_only), state, b)

    # 7. histogram/counter scatter-adds
    def hist_only(st, bb):
        from zipkin_tpu.store.device import _scatter_add, svc_histogram
        from zipkin_tpu.ops import quantile as Q
        hist = svc_histogram(st)
        svc_ok = mask & (bb.service_id >= 0) & (bb.service_id < S) \
            & (bb.duration >= 0)
        bidx = Q.bucket_index(hist, bb.duration.astype(jnp.float32))
        g = jnp.clip(bb.service_id, 0, S - 1)
        out = _scatter_add(
            st.svc_hist,
            jnp.where(svc_ok, g * c.quantile_buckets + bidx, -1),
            jnp.ones(P, jnp.int32), False,
        )
        return out.sum()

    timeit("svc_hist scatter-add", jax.jit(hist_only), state, b)

    # 8. CMS + HLL
    def sketch_only(st, bb):
        from zipkin_tpu.ops import hll, cms
        from zipkin_tpu.store.device import _scatter_add, dev_split64
        t_hi, t_lo = dev_split64(bb.trace_id)
        regs = hll.update(hll.HyperLogLog(st.hll_traces), t_hi, t_lo,
                          valid=mask).registers
        sk = cms.CountMin(st.cms_trace_spans)
        cms_idx = cms._indices(sk, t_hi, t_lo)
        cms_flat = cms_idx + (
            jnp.arange(c.cms_depth, dtype=jnp.int32) * c.cms_width
        )[:, None]
        cms_flat = jnp.where(mask[None, :], cms_flat, -1).reshape(-1)
        out = _scatter_add(
            st.cms_trace_spans, cms_flat,
            jnp.ones(c.cms_depth * P, jnp.int32), False,
        )
        return out.sum() + regs.sum()

    timeit("HLL + CMS update", jax.jit(sketch_only), state, b)

    # 8b. micro-arms for the remaining _index_write costs: which gather
    # shape is cheapest for the old-entry read, what the rank sort
    # costs alone, and what one full-width war costs.
    NR = 4 * PA + 4 * PB  # concatenated candidate rows
    M_ROWS = config.cand_layout[2]
    # Hash-scattered indices: production gidx values are bucket slots,
    # not sequential — a sequential arm would let the gather coalesce
    # into reads the real access pattern never gets.
    gidx = ((jnp.arange(NR, dtype=jnp.int64) * 2654435761)
            % M_ROWS).astype(jnp.int32)
    ent = jnp.zeros((M_ROWS, 3), jnp.int64)

    def g_cols(e, ix):
        return (e[:, 0][ix] + e[:, 1][ix] + e[:, 2][ix]).sum()

    def g_rows(e, ix):
        return e[ix].sum()

    def g_planes(e, ix):
        p = dev._p32(e)  # [M, 3, 2]
        acc = 0
        for cdx in range(3):
            for pl in range(2):
                acc += p[:, cdx, pl][ix].astype(jnp.int64).sum()
        return acc

    timeit(f"old-entry gather: 3 col i64 ({NR} rows)",
           jax.jit(g_cols), ent, gidx)
    timeit("old-entry gather: row [N,3] i64", jax.jit(g_rows), ent, gidx)
    timeit("old-entry gather: 6 plane i32", jax.jit(g_planes), ent, gidx)

    bkt = (jnp.arange(NR, dtype=jnp.int64) * 2654435761) % (1 << 16)

    def ranks_only(bb):
        return dev._fifo_ranks(bb, jnp.ones(NR, bool), 1 << 16).sum()

    timeit("fifo ranks (sort+cummax+unsort)", jax.jit(ranks_only), bkt)

    wmv = jnp.full(1 << 16, dev.I64_MIN, jnp.int64)

    def war_only(w, bb):
        return dev._war_max64(
            w, bb.astype(jnp.int32), jnp.arange(NR, dtype=jnp.int64),
            jnp.ones(NR, bool),
        ).sum()

    timeit("war_max64 full width", jax.jit(war_only), wmv, bkt)

    # 8c. r12 rank-path arms: the argsort rank vs the segmented
    # counting rank at the step's REAL concatenated shape + bucket
    # count. Counting is scratch-bounded — when no block fits at this
    # geometry the arm reports so (the step then statically keeps
    # argsort; see device.rank_block_for / docs/PERFORMANCE.md).
    n_b_total = config.idx_layout[1]
    rbkt = ((jnp.arange(NR, dtype=jnp.int64) * 2654435761)
            % n_b_total).astype(jnp.int32)
    rvalid = jnp.ones(NR, bool)

    def arg_ranks(bb):
        return dev._fifo_ranks(bb, rvalid, n_b_total).sum()

    timeit(f"rank path: argsort ({NR} rows, {n_b_total} buckets)",
           jax.jit(arg_ranks), rbkt)
    blk = dev.rank_block_for(NR, n_b_total)
    if blk:
        def cnt_ranks(bb):
            return dev._fifo_ranks_counting(bb, rvalid, n_b_total,
                                            blk).sum()

        timeit(f"rank path: counting (block {blk})",
               jax.jit(cnt_ranks), rbkt)
    else:
        print(f"rank path: counting infeasible at {NR} rows x "
              f"{n_b_total} buckets (scratch budget); step keeps "
              "argsort here", flush=True)

    # 8d. r12 arena-scatter arms: the 6-plane XLA scatter vs the fused
    # pallas claim+scatter, at a geometry whose arena fits VMEM (the
    # kernel's own support boundary — the full-size arena stays on the
    # XLA path by the NOTES_r06 §3 roofline).
    from zipkin_tpu.ops import pallas_kernels as PK

    small_nb, small_depth = 1 << 10, 32
    small_S = small_nb * small_depth
    if PK.arena_scatter_supported(small_S, small_nb):
        NS = min(NR, 1 << 17)
        ent = jnp.zeros((small_S, 3), jnp.int64)
        sb = ((jnp.arange(NS, dtype=jnp.int64) * 2654435761)
              % small_nb).astype(jnp.int32)
        svals = jnp.stack([jnp.arange(NS, dtype=jnp.int64)] * 3, -1)
        sval = jnp.ones(NS, bool)
        sbase = jnp.zeros(NS, jnp.int32)
        sslot0 = sb.astype(jnp.int64) * small_depth
        sdep = jnp.full(NS, small_depth, jnp.int32)

        def xla_scatter(e):
            rank = dev._fifo_ranks(sb, sval, small_nb)
            slot = sslot0.astype(jnp.int32) + (rank % small_depth)
            keep = sval & (rank >= 0)
            return dev._uset_cols64(e, slot, svals, keep).sum()

        def pallas_scatter(e):
            return PK.arena_claim_scatter(
                e, sb, sbase, sslot0, sdep, svals, sval,
                n_buckets=small_nb).sum()

        timeit(f"arena scatter: XLA rank+6-plane ({NS} rows)",
               jax.jit(xla_scatter), ent)
        timeit("arena scatter: pallas claim+scatter (VMEM arena)",
               jax.jit(pallas_scatter), ent)

    # 9a. r12 batch escalation: re-template the full step at each
    # requested batch size and time it — spans/s per batch_spans is
    # the scatter-amortization curve whose knee picks the new
    # StoreConfig.batch_spans / bench --batch-spans default (the old
    # 16384-trace optimum predates the PR 4 pipeline overlap).
    sweep = [int(x) for x in args.batch_spans_sweep.split(",") if x]
    from bench import SPT

    for bs in sweep:
        traces_n = max(1, bs // SPT)
        if traces_n * SPT > (1 << args.cap_log2) // 2:
            print(f"batch_spans {bs}: exceeds half-ring budget at "
                  f"cap 2^{args.cap_log2}; skipped", flush=True)
            continue
        db_s, _, pad_s = _make_template(store, 1024, traces_n)
        st_s = jax.device_put(dev.init_state(config))
        step_s = jax.jit(
            lambda s, d: dev.ingest_step.__wrapped__(s, d))
        t0 = time.perf_counter()
        out_s = step_s(st_s, db_s)
        jax.device_get(out_s.counters["spans_seen"])
        t1 = time.perf_counter()
        times = []
        for _ in range(args.reps):
            t2 = time.perf_counter()
            out_s = step_s(st_s, db_s)
            jax.device_get(out_s.counters["spans_seen"])
            times.append(time.perf_counter() - t2)
        best = min(times)
        print(f"batch_spans {pad_s:7d}: compile+1st {t1 - t0:7.3f}s  "
              f"steady {best * 1e3:9.1f} ms  "
              f"({pad_s / best / 1e3:8.1f}k spans/s)", flush=True)
        del st_s, out_s, db_s

    # 9. chain scaling: is scan amortization working?
    for k in (1, 4, 18):
        st2 = dev.init_state(config)
        st2 = jax.device_put(st2)
        stp = jnp.int64(0)
        fc = fused_chain
        t0 = time.perf_counter()
        st2, stp = fc(st2, b, stp, k, jnp.bool_(False))
        _ = float(jax.device_get(st2.counters["spans_seen"]))
        t1 = time.perf_counter()
        st2, stp = fc(st2, b, stp, k, jnp.bool_(False))
        _ = float(jax.device_get(st2.counters["spans_seen"]))
        t2 = time.perf_counter()
        print(f"fused_chain k={k:3d}: compile+1st {t1 - t0:8.3f}s  "
              f"steady {(t2 - t1) * 1e3:9.1f} ms  "
              f"({(t2 - t1) * 1e3 / k:7.1f} ms/step)", flush=True)
        del st2

    print("done", flush=True)


if __name__ == "__main__":
    main()
