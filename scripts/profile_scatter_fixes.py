"""On-chip A/B of scatter strategies (round-4 perf diagnosis step 2).

Methodology: the ~80-120ms dispatch floor through the tunnel swamps
single-launch timings, so every candidate op is chained K times inside
ONE jitted program (output feeds the next iteration's input, values
perturbed by the loop counter so nothing hoists) and the reported
number is (wall - floor) / K. x64 is on (zipkin_tpu import), matching
the real store's dtypes.
"""

import sys
import time

sys.path.insert(0, ".")

import zipkin_tpu  # noqa: F401  (enables x64 like the real workload)
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

P = 114688
CAP = 1 << 22
S, QB = 1024, 256
K = 16


def chain_timeit(name, step, init, reps=3):
    """step: (carry, i) -> carry, jitted; runs K times per launch."""

    @jax.jit
    def run(carry):
        def body(i, c):
            return step(c, i)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, carry)

    out = run(init)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(out)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    per = (min(times)) / K * 1e3
    print(f"{name:56s} {per:9.2f} ms/op", flush=True)
    return per


def main():
    print("backend:", jax.default_backend(), "x64:",
          jax.config.jax_enable_x64, flush=True)
    rng = np.random.default_rng(0)

    floor = chain_timeit(
        "floor probe (x*2+1, K-chained)",
        lambda c, i: c * 2.0 + 1.0,
        jnp.ones((8, 128), jnp.float32),
    )

    slots = jnp.asarray(np.arange(P) % CAP, jnp.int32)
    mask = jnp.asarray(rng.random(P) < 0.98)
    col = jnp.asarray(rng.integers(0, 1 << 40, size=P), jnp.int64)
    ring0 = jax.device_put(jnp.zeros(CAP + 1, jnp.int64))

    def mk(v, i):
        return v ^ i.astype(jnp.int64)

    # single i64 ring column write, three ways
    chain_timeit(
        "ring col set: baseline (shared OOB dup)",
        lambda r, i: r.at[jnp.where(mask, slots, CAP)].set(
            mk(col, i), mode="drop"),
        ring0,
    )
    arange_p = jnp.arange(P, dtype=jnp.int32)
    chain_timeit(
        "ring col set: unique_indices (distinct OOB)",
        lambda r, i: r.at[
            jnp.where(mask, slots, CAP + arange_p)
        ].set(mk(col, i), mode="drop", unique_indices=True),
        ring0,
    )
    chain_timeit(
        "ring col set: unique+sorted",
        lambda r, i: r.at[jnp.where(mask, slots, CAP)].set(
            mk(col, i), mode="drop", unique_indices=True,
            indices_are_sorted=True),
        ring0,
    )

    # scatter-ADD into svc_hist geometry
    hidx = jnp.asarray(rng.integers(0, S * QB, size=P), jnp.int32)
    hidx = jnp.where(jnp.asarray(rng.random(P) < 0.97), hidx, -1)
    hist0 = jax.device_put(jnp.zeros(S * QB + 1, jnp.int32))
    ones = jnp.ones(P, jnp.int32)

    chain_timeit(
        "hist add 114k rows: XLA scatter-add",
        lambda h, i: h.at[jnp.where(hidx >= 0, hidx, S * QB)
                          ].add(ones + i * 0, mode="drop"),
        hist0,
    )

    from zipkin_tpu.ops.pallas_kernels import flat_histogram

    def pallas_step(h, i):
        d = flat_histogram(hidx, (ones + i * 0).astype(jnp.float32),
                           S * QB)
        return h + d.astype(jnp.int32)[: S * QB + 1].at[S * QB].set(0) \
            if False else h.at[:S * QB].add(d.astype(jnp.int32))

    chain_timeit("hist add 114k rows: pallas VMEM kernel", pallas_step,
                 hist0)

    # sort+segment+one-unique-scatter
    def sortseg(h, i):
        idx = jnp.where(hidx >= 0, hidx, S * QB)
        order = jnp.argsort(idx)
        si = idx[order]
        cum = jnp.cumsum(jnp.ones(P, jnp.int32))
        nxt = jnp.concatenate([si[1:], jnp.full(1, -7, si.dtype)])
        run_end = si != nxt
        # total per run = cum at run end minus cum at previous run end
        end_cum = jnp.where(run_end, cum, 0)
        prev = jax.lax.cummax(
            jnp.concatenate([jnp.zeros(1, jnp.int32), end_cum[:-1]]))
        total = jnp.where(run_end, cum - prev, 0) * (1 + i * 0)
        tgt = jnp.where(run_end, si, S * QB)
        return h.at[tgt].add(total, mode="drop", unique_indices=False)

    chain_timeit("hist add 114k rows: sort+segsum+scatter", sortseg,
                 hist0)

    # index entries: [N,2] i64 rows, four ways
    NI = 8 * P
    M = 1 << 23
    e2_0 = jax.device_put(jnp.zeros((M + 1, 2), jnp.int64))
    ef_0 = jax.device_put(jnp.zeros(2 * (M + 1), jnp.int64))
    eidx = jnp.asarray(rng.choice(M, size=NI, replace=False), jnp.int32)
    vals = jnp.asarray(rng.integers(0, 1 << 40, size=(NI, 2)), jnp.int64)

    chain_timeit(
        "idx write 917k [N,2]: baseline",
        lambda e, i: e.at[eidx].set(vals ^ i.astype(jnp.int64),
                                    mode="drop"),
        e2_0,
    )
    chain_timeit(
        "idx write 917k [N,2]: unique_indices",
        lambda e, i: e.at[eidx].set(vals ^ i.astype(jnp.int64),
                                    mode="drop", unique_indices=True),
        e2_0,
    )
    chain_timeit(
        "idx write 917k flat 2x1-D unique",
        lambda e, i: e.at[2 * eidx].set(
            vals[:, 0] ^ i.astype(jnp.int64), mode="drop",
            unique_indices=True,
        ).at[2 * eidx + 1].set(
            vals[:, 1] ^ i.astype(jnp.int64), mode="drop",
            unique_indices=True,
        ),
        ef_0,
    )

    # scatter in sorted-index order (gather vals through the sort)
    sorder = jnp.argsort(eidx)
    sidx = eidx[sorder]
    svals = vals[sorder]
    chain_timeit(
        "idx write 917k [N,2]: pre-sorted unique+sorted",
        lambda e, i: e.at[sidx].set(svals ^ i.astype(jnp.int64),
                                    mode="drop", unique_indices=True,
                                    indices_are_sorted=True),
        e2_0,
    )

    # scatter-add small target: bucket counters (cnt/pos pattern)
    NB = 98304
    bidx = jnp.asarray(rng.integers(0, NB, size=NI), jnp.int32)
    cnt0 = jax.device_put(jnp.zeros(NB + 1, jnp.int32))
    chain_timeit(
        "bucket cnt add 917k rows -> 98k buckets: XLA",
        lambda h, i: h.at[bidx].add(jnp.ones(NI, jnp.int32) + i * 0,
                                    mode="drop"),
        cnt0,
    )

    def cnt_sortseg(h, i):
        order = jnp.argsort(bidx)
        si = bidx[order]
        cum = jnp.cumsum(jnp.ones(NI, jnp.int32))
        nxt = jnp.concatenate([si[1:], jnp.full(1, -7, si.dtype)])
        run_end = si != nxt
        end_cum = jnp.where(run_end, cum, 0)
        prev = jax.lax.cummax(
            jnp.concatenate([jnp.zeros(1, jnp.int32), end_cum[:-1]]))
        total = jnp.where(run_end, cum - prev, 0) * (1 + i * 0)
        tgt = jnp.where(run_end, si, NB)
        return h.at[tgt].add(total, mode="drop")

    chain_timeit("bucket cnt add 917k rows: sort+segsum", cnt_sortseg,
                 cnt0)

    # scatter-min (span_tab probe round)
    T = 1 << 22
    tslot = jnp.asarray(rng.integers(0, T, size=P), jnp.int32)
    tval = jnp.asarray(rng.integers(0, 1 << 62, size=P), jnp.int64)
    tab0 = jax.device_put(jnp.full(T, (1 << 63) - 1, jnp.int64))
    chain_timeit(
        "span_tab probe round 114k: scatter-min",
        lambda t, i: t.at[tslot].min(tval ^ i.astype(jnp.int64),
                                     mode="drop"),
        tab0,
    )
    chain_timeit(
        "span_tab probe round 114k: scatter-min unique(lie-free dedup "
        "assumed)",
        lambda t, i: t.at[tslot].min(tval ^ i.astype(jnp.int64),
                                     mode="drop", unique_indices=True),
        tab0,
    )

    # gather cost for comparison (tab lookup reads)
    chain_timeit(
        "gather 114k from 4M table",
        lambda t, i: t.at[tslot].min(
            t[(tslot + i) % T], mode="drop", unique_indices=True),
        tab0,
    )

    # big sort cost at index-write row count
    skey = jnp.asarray(rng.integers(0, 1 << 62, size=NI), jnp.int64)

    def sort_step(c, i):
        out = jnp.sort(skey ^ i.astype(jnp.int64))
        return c + out[0] * 0 + out[-1] * 0

    chain_timeit("argsortable i64 sort 917k rows", sort_step,
                 jnp.int64(0))

    print(f"(floor was {floor:.2f} ms/op amortized)", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
