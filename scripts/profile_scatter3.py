"""Round 3 of the scatter diagnosis: which primitive is fast on BIG
(HBM-resident) targets? Everything chained in-program (K=16) and every
output fully consumed (sum folded into the carry) so XLA can't DCE any
arm — the flaw that understated the first arm profile.
"""

import sys
import time

sys.path.insert(0, ".")

import zipkin_tpu  # noqa: F401  x64 on
import jax
import jax.numpy as jnp
import numpy as np

P = 114688
NI = 8 * P
M = 1 << 23  # 8M-row big target
CAP = 1 << 22
K = 16


def chain_timeit(name, step, init, reps=3):
    @jax.jit
    def run(carry):
        def body(i, c):
            return step(c, i)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, carry)

    out = run(init)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(out)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    print(f"{name:58s} {min(times) / K * 1e3:9.2f} ms/op", flush=True)


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)
    floor_init = jnp.ones((8, 128), jnp.float32)
    chain_timeit("floor (x*2+1)", lambda c, i: c * 2.0 + 1.0, floor_init)

    eidx = jnp.asarray(rng.choice(M, size=NI, replace=False), jnp.int32)
    v1 = jnp.asarray(rng.integers(0, 1 << 40, size=NI), jnp.int64)
    big64 = jax.device_put(jnp.zeros(M + 1, jnp.int64))
    big32 = jax.device_put(jnp.zeros(M + 1, jnp.int32))
    bigf = jax.device_put(jnp.zeros(M + 1, jnp.float32))

    # 1. scatter-ADD on big i64 target
    chain_timeit(
        "ADD i64 917k -> 8M",
        lambda t, i: t.at[eidx].add(v1 ^ i.astype(jnp.int64),
                                    mode="drop"),
        big64,
    )
    # 2. scatter-ADD big i32
    v1_32 = v1.astype(jnp.int32)
    chain_timeit(
        "ADD i32 917k -> 8M",
        lambda t, i: t.at[eidx].add(v1_32 + i, mode="drop"),
        big32,
    )
    # 3. scatter-ADD big f32
    v1_f = (v1 & jnp.int64(0xFFFFF)).astype(jnp.float32)
    chain_timeit(
        "ADD f32 917k -> 8M",
        lambda t, i: t.at[eidx].add(v1_f + i.astype(jnp.float32),
                                    mode="drop"),
        bigf,
    )
    # 4. scatter-SET i32 on big target
    chain_timeit(
        "SET i32 917k -> 8M (unique)",
        lambda t, i: t.at[eidx].set(v1_32 + i, mode="drop",
                                    unique_indices=True),
        big32,
    )
    # 5. scatter-SET i64 1-D (reference point from round 2: ~100ns/row)
    chain_timeit(
        "SET i64 917k -> 8M (unique)",
        lambda t, i: t.at[eidx].set(v1 ^ i.astype(jnp.int64),
                                    mode="drop", unique_indices=True),
        big64,
    )
    # 6. SET-via-ADD-delta: gather old, add (new - old), unique indices
    def set_via_add(t, i):
        new = v1 ^ i.astype(jnp.int64)
        old = t[eidx]
        return t.at[eidx].add(new - old, mode="drop",
                              unique_indices=True)
    chain_timeit("SET i64 917k via gather+ADD-delta", set_via_add, big64)

    # 7. gather 917k from big i64
    acc0 = jnp.zeros((), jnp.int64)
    gsrc = jax.device_put(
        jnp.asarray(rng.integers(0, 1 << 40, size=M), jnp.int64))
    chain_timeit(
        "gather 917k from 8M i64 (sum-consumed)",
        lambda c, i: c + gsrc[(eidx + i) % M].sum(),
        acc0,
    )
    # 8. true sort cost, output fully consumed
    skey = jnp.asarray(rng.integers(0, 1 << 62, size=NI), jnp.int64)
    chain_timeit(
        "sort i64 917k (sum-consumed)",
        lambda c, i: c + jnp.sort(skey ^ i.astype(jnp.int64)).sum(),
        acc0,
    )
    chain_timeit(
        "argsort i64 917k (sum-consumed)",
        lambda c, i: c + jnp.argsort(skey ^ i.astype(jnp.int64)).sum(),
        acc0,
    )
    chain_timeit(
        "sort i64 114k (sum-consumed)",
        lambda c, i: c + jnp.sort(skey[:P] ^ i.astype(jnp.int64)).sum(),
        acc0,
    )

    # 9. ring write via two dynamic_update_slices (wrap-safe roll trick):
    # roll batch so the wrap point is at the batch boundary, then 1 DUS
    # when no wrap. Compare a col write P=114k.
    ring = jax.device_put(jnp.zeros(CAP, jnp.int64))
    colP = v1[:P]

    def ring_dus(t, i):
        start = (i.astype(jnp.int64) * P) % CAP
        # single DUS with wrap handled by lax.rem start (P divides CAP
        # here, the bench case: batches never straddle — clamp form)
        return jax.lax.dynamic_update_slice(
            t, colP ^ i.astype(jnp.int64), (start,))
    chain_timeit("ring col write via DUS (114k i64)", ring_dus, ring)

    # 10. masked-set variant of DUS: set only valid rows (pad rows must
    # not write) — gather old window, where(mask), DUS back.
    maskP = jnp.asarray(rng.random(P) < 0.98)

    def ring_dus_masked(t, i):
        start = (i.astype(jnp.int64) * P) % CAP
        old = jax.lax.dynamic_slice(t, (start,), (P,))
        merged = jnp.where(maskP, colP ^ i.astype(jnp.int64), old)
        return jax.lax.dynamic_update_slice(t, merged, (start,))
    chain_timeit("ring col write via masked DUS", ring_dus_masked, ring)

    # 11. SET [N,3] i64 -> one flat ADD-delta on 3M flat rows
    vals3 = jnp.stack([v1, v1 ^ 77, v1 ^ 123], axis=-1)
    big3 = jax.device_put(jnp.zeros(((M + 1) * 3,), jnp.int64))

    def set3_via_add(t, i):
        new = (vals3 ^ i.astype(jnp.int64)).reshape(-1)
        fidx = (3 * eidx[:, None]
                + jnp.arange(3, dtype=jnp.int32)[None, :]).reshape(-1)
        old = t[fidx]
        return t.at[fidx].add(new - old, mode="drop",
                              unique_indices=True)
    chain_timeit("SET [917k,3] i64 via flat ADD-delta", set3_via_add,
                 big3)

    print("done", flush=True)


if __name__ == "__main__":
    main()
