"""Round 5: the last unknowns before the ingest scatter rewrite —
i32 scatter-min (fp-war viability), gather costs by dtype/layout, and
the log-doubling segmented cummax.
"""

import sys
import time

sys.path.insert(0, ".")

import zipkin_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

P = 114688
NI = 8 * P
M = 1 << 23
K = 16


def chain_timeit(name, step, init, reps=3):
    @jax.jit
    def run(carry):
        def body(i, c):
            return step(c, i)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, carry)

    out = run(init)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(out)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    print(f"{name:58s} {min(times) / K * 1e3:9.2f} ms/op", flush=True)


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)
    chain_timeit("floor", lambda c, i: c * 2.0 + 1.0,
                 jnp.ones((8, 128), jnp.float32))

    eidx = jnp.asarray(rng.integers(0, M, size=NI), jnp.int32)
    v32 = jnp.asarray(rng.integers(0, 1 << 30, size=NI), jnp.int32)
    big32 = jax.device_put(
        jnp.full(M + 1, (1 << 31) - 1, jnp.int32))

    chain_timeit(
        "MIN i32 917k -> 8M (dup indices)",
        lambda t, i: t.at[eidx].min(v32 ^ i, mode="drop"),
        big32,
    )
    chain_timeit(
        "MAX i32 917k -> 8M (dup indices)",
        lambda t, i: t.at[eidx].max(v32 ^ i, mode="drop"),
        big32,
    )
    # smaller row count (the span_tab P-row case)
    chain_timeit(
        "MIN i32 114k -> 4M",
        lambda t, i: t.at[eidx[:P] % (1 << 22)].min(v32[:P] ^ i,
                                                    mode="drop"),
        jax.device_put(jnp.full((1 << 22) + 1, (1 << 31) - 1, jnp.int32)),
    )

    # gathers
    acc32 = jnp.zeros((), jnp.int64)
    acc64 = jnp.zeros((), jnp.int64)
    src32 = jax.device_put(
        jnp.asarray(rng.integers(0, 1 << 30, size=M), jnp.int32))
    src64 = jax.device_put(
        jnp.asarray(rng.integers(0, 1 << 60, size=M), jnp.int64))
    chain_timeit(
        "gather i32 917k from 8M",
        lambda c, i: c + src32[(eidx + i) % M].sum(),
        acc32,
    )
    chain_timeit(
        "gather i64 917k from 8M",
        lambda c, i: c + src64[(eidx + i) % M].sum(),
        acc64,
    )
    chain_timeit(
        "gather i64-as-2-plane-i32 917k from 8M",
        lambda c, i: c + jax.lax.bitcast_convert_type(
            jax.lax.bitcast_convert_type(src64, jnp.int32)
            .reshape(-1)[
                (2 * ((eidx + i) % M))[:, None]
                + jnp.arange(2, dtype=jnp.int32)[None, :]
            ], jnp.int64).sum(),
        acc64,
    )
    chain_timeit(
        "gather i64 114k from 8M",
        lambda c, i: c + src64[(eidx[:P] + i) % M].sum(),
        acc64,
    )

    # log-doubling segmented cummax over 917k i64 (run-end extraction)
    bidx = jnp.asarray(rng.integers(0, 98304, size=NI), jnp.int32)
    v64 = jnp.asarray(rng.integers(0, 1 << 60, size=NI), jnp.int64)

    def seg_logdouble(c, i):
        order = jnp.argsort(bidx)
        sb = bidx[order]
        sv = (v64 ^ i.astype(jnp.int64))[order]
        segid = sb  # sorted -> segment id IS the bucket
        vals = sv
        d = 1
        while d < NI:
            shifted = jnp.concatenate(
                [jnp.full(d, jnp.int64(-(1 << 62))), vals[:-d]])
            same = jnp.concatenate(
                [jnp.zeros(d, bool), segid[d:] == segid[:-d]])
            vals = jnp.where(same, jnp.maximum(vals, shifted), vals)
            d *= 2
        return c + vals.sum()

    chain_timeit("segmax i64 917k: argsort+log-doubling (20 steps)",
                 seg_logdouble, acc64)

    print("done", flush=True)


if __name__ == "__main__":
    main()
