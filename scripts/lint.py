#!/usr/bin/env python
"""graftlint entry point — the repo's concurrency/JAX-hazard analyzer.

    python scripts/lint.py                  # gate against the baseline
    python scripts/lint.py --write-baseline # accept current findings
    python scripts/lint.py --fix-annotations
    python scripts/lint.py --list-rules

See docs/STATIC_ANALYSIS.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from zipkin_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
