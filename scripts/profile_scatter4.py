"""Round 4: pick the exact i64->i32 decomposition for the hot scatters.
Chained (K=16), outputs consumed, floor printed for subtraction.
"""

import sys
import time

sys.path.insert(0, ".")

import zipkin_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

P = 114688
NI = 8 * P
M = 1 << 23
K = 16


def chain_timeit(name, step, init, reps=3):
    @jax.jit
    def run(carry):
        def body(i, c):
            return step(c, i)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, carry)

    out = run(init)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(out)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    print(f"{name:58s} {min(times) / K * 1e3:9.2f} ms/op", flush=True)


def b32(x):
    """i64 array -> (..., 2) i32 bit-planes (free bitcast)."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def b64(x):
    """(..., 2) i32 bit-planes -> i64 (free bitcast)."""
    return jax.lax.bitcast_convert_type(x, jnp.int64)


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)
    chain_timeit("floor", lambda c, i: c * 2.0 + 1.0,
                 jnp.ones((8, 128), jnp.float32))

    eidx = jnp.asarray(rng.choice(M, size=NI, replace=False), jnp.int32)
    v1 = jnp.asarray(rng.integers(0, 1 << 62, size=NI), jnp.int64)
    big64 = jax.device_put(jnp.zeros(M + 1, jnp.int64))

    # A. bitcast planes, ONE 2-D i32 scatter of [N,2] rows
    def set_2d(t, i):
        planes = b32(t)                       # [M+1, 2] i32
        vals = b32(v1 ^ i.astype(jnp.int64))  # [N, 2] i32
        planes = planes.at[eidx].set(vals, mode="drop",
                                     unique_indices=True)
        return b64(planes)
    chain_timeit("SET i64 917k: bitcast + one [N,2] i32 scatter",
                 set_2d, big64)

    # B. bitcast planes, TWO 1-D i32 scatters on strided slices
    def set_planes(t, i):
        planes = b32(t)
        vals = b32(v1 ^ i.astype(jnp.int64))
        lo = planes[:, 0].at[eidx].set(vals[:, 0], mode="drop",
                                       unique_indices=True)
        hi = planes[:, 1].at[eidx].set(vals[:, 1], mode="drop",
                                       unique_indices=True)
        return b64(jnp.stack([lo, hi], axis=-1))
    chain_timeit("SET i64 917k: two strided 1-D i32 scatters",
                 set_planes, big64)

    # C. flat planes layout: target stored as i32[2*(M+1)], interleaved
    bigflat = jax.device_put(jnp.zeros(2 * (M + 1), jnp.int32))

    def set_flat(t, i):
        vals = b32(v1 ^ i.astype(jnp.int64)).reshape(-1)  # [2N]
        fidx = (2 * eidx[:, None] + jnp.arange(2, dtype=jnp.int32)
                ).reshape(-1)
        return t.at[fidx].set(vals, mode="drop", unique_indices=True)
    chain_timeit("SET i64 917k: interleaved flat i32 (2N rows)",
                 set_flat, bigflat)

    # D. [N,3] i64 entries row: one [N,6] i32 scatter into [M,6]
    vals3 = jnp.stack([v1, v1 ^ 77, v1 ^ 123], axis=-1)
    big3 = jax.device_put(jnp.zeros((M + 1, 3), jnp.int64))

    def set3_2d(t, i):
        planes = b32(t).reshape(M + 1, 6)
        vals = b32(vals3 ^ i.astype(jnp.int64)).reshape(NI, 6)
        planes = planes.at[eidx].set(vals, mode="drop",
                                     unique_indices=True)
        return jax.lax.bitcast_convert_type(
            planes.reshape(M + 1, 3, 2), jnp.int64)
    chain_timeit("SET [917k,3] i64: one [N,6] i32 scatter", set3_2d,
                 big3)

    # E. small-target i64 scatter-max (the wm arrays): 917k -> 98k
    NB = 98304
    bidx = jnp.asarray(rng.integers(0, NB, size=NI), jnp.int32)
    wm0 = jax.device_put(jnp.full(NB + 1, -(1 << 62), jnp.int64))
    chain_timeit(
        "MAX i64 917k -> 98k small target (current wm path)",
        lambda t, i: t.at[bidx].max(v1 ^ i.astype(jnp.int64),
                                    mode="drop"),
        wm0,
    )

    # F. wm via sort+segment-max+unique set (sort key: bucket<<? no —
    # lexsort-free: single key = bucket*2^40 + (val>>22) approx is
    # lossy; do exact two-pass: sort by bucket only, segmax via cummax
    # over runs of the gathered values)
    def wm_sortseg(t, i):
        v = v1 ^ i.astype(jnp.int64)
        order = jnp.argsort(bidx)
        sb = bidx[order]
        sv = v[order]
        first = jnp.concatenate(
            [jnp.ones(1, bool), sb[1:] != sb[:-1]])
        segid = jnp.cumsum(first.astype(jnp.int32)) - 1
        # running max within segment: cummax reset at segment starts
        neg = jnp.int64(-(1 << 62))
        run = jax.lax.associative_scan(
            jnp.maximum,
            jnp.where(first, sv, jnp.maximum(sv, neg)))
        # associative_scan(max) without reset is wrong across segments;
        # instead compute segment max via scatter-free trick: reverse
        # trick needs segment ops — fall back to a masked scan:
        # max within segment = cummax of (value keyed by segid) using
        # the monotone-segid property: cummax of (segid<<62 | ...) no.
        # Pragmatic: one small i64 scatter-max over DEDUPED run ends is
        # NB-bounded rows; measure gather+set of run-END rows instead:
        nxt = jnp.concatenate([sb[1:], jnp.full(1, -7, sb.dtype)])
        run_end = sb != nxt
        tgt = jnp.where(run_end, sb, NB)
        old = t[jnp.clip(tgt, 0, NB)]
        merged = jnp.maximum(old, run)
        planes = b32(t)
        mv = b32(merged)
        lo = planes[:, 0].at[tgt].set(mv[:, 0], mode="drop",
                                      unique_indices=True)
        hi = planes[:, 1].at[tgt].set(mv[:, 1], mode="drop",
                                      unique_indices=True)
        return b64(jnp.stack([lo, hi], axis=-1))
    chain_timeit("MAX i64 917k -> 98k: sort+runend+i32 set (approx)",
                 wm_sortseg, wm0)

    # G. scatter-add i64 small target (pos/cnt are i32 already; check
    # i64 counters)
    chain_timeit(
        "ADD i64 917k -> 98k small target",
        lambda t, i: t.at[bidx].add(v1 ^ i.astype(jnp.int64),
                                    mode="drop"),
        wm0,
    )

    # H. lexsort-equivalent: single sort of (bucket<<42 | row) then
    # gather — what _fifo_ranks already does; time segmented cummax via
    # the sort order (the building block for exact wm)
    def segmax_exact(c, i):
        v = v1 ^ i.astype(jnp.int64)
        order = jnp.argsort(
            (bidx.astype(jnp.int64) << 42)
            | jnp.arange(NI, dtype=jnp.int64))
        sb = bidx[order]
        sv = v[order]
        first = jnp.concatenate([jnp.ones(1, bool), sb[1:] != sb[:-1]])
        # exact segmented cummax: scan with reset via (flag, value) pair
        def comb(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb, vb, jnp.maximum(va, vb))
        _, run = jax.lax.associative_scan(comb, (first, sv))
        return c + run.sum()
    chain_timeit("exact segmented cummax over 917k (assoc_scan pair)",
                 segmax_exact, jnp.int64(0))

    print("done", flush=True)


if __name__ == "__main__":
    main()
