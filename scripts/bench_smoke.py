"""CPU-runnable smoke bench: one JSON line of perf-structure evidence.

Three things the full bench (bench.py) can only prove on real hardware
are provable structurally on any backend, every CI run:

1. **Fused-ingest timing** at small N — a regression canary, not a
   throughput claim (CPU ms/step moves with the machine; the JSON
   carries it for trending).
2. **Index-family op counts** — the whole r5→r6 tentpole is "fewer
   scatter/gather launches per ingest step" (the unified index arena:
   one rank-sort + one entry scatter block + ONE shared watermark
   scatter for all seven families). Per-kernel overhead dominates on
   the target device class (NOTES_r03 §3), so the SCATTER COUNT of the
   compiled step is the portable proxy for the TPU win, and the tier-1
   lane asserts it doesn't creep back up (tests/test_bench_smoke.py).
3. **Batched-query scaling** — k queries through one
   ``get_trace_ids_multi`` launch vs k singular calls; the read-path
   dispatch-floor amortization the query coalescer rides on.

Usage:  python scripts/bench_smoke.py [--spans 7000] [--k 8]
Emits exactly one JSON line on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _count_ops(stablehlo_text: str) -> dict:
    """Scatter/gather/sort census of a jitted function's StableHLO
    lowering — backend-INDEPENDENT (the CPU backend fuses scatters out
    of its optimized HLO, so compiled-module counts aren't portable),
    and exactly the structural quantity the unified-arena work drove
    down: how many scatter/sort ops the ingest step ISSUES per batch.
    r5 split-design baseline at the smoke shapes: 101 scatters /
    6 sorts / 80 gathers; the r6 unified arena shipped 95 / 5 / 79;
    the r12 counting-sort rank path ships 95 / 4 / 79 (ceilings
    centralized in zipkin_tpu.store.census — the one place the tier-1
    gate reads them from). One shared counter (dev.
    stablehlo_op_census) backs this gate AND the runtime
    TpuSpanStore.step_census observable, so they can never drift."""
    from zipkin_tpu.store.device import stablehlo_op_census

    return stablehlo_op_census(stablehlo_text)


def run_archive() -> dict:
    """Cold-tier phase: capture -> compact -> cold query, with the
    memory store as the identity oracle. Proves on every CI run that
    (a) eviction capture adds ZERO ops to the fused ingest step (its
    lowering census with a sink attached equals the plain store's),
    (b) a 4x-ring ingest leaves every evicted span answerable, and
    (c) zone-map pruning actually skips segments. Also times the
    capture overhead (ingest with sink vs without, same spans) and the
    cold trace-fetch latency."""
    import numpy as np

    from zipkin_tpu.columnar.schema import SpanBatch
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore
    from zipkin_tpu.store.memory import InMemorySpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    config = dev.StoreConfig(
        capacity=1 << 8, ann_capacity=1 << 10, bann_capacity=1 << 9,
        max_services=32, max_span_names=64, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=6,
        quantile_buckets=256,
    )
    n_spans = 4 * config.capacity
    traces = generate_traces(n_traces=n_spans // 4, max_depth=3,
                             n_services=8)
    spans = [s for t in traces for s in t][:n_spans]
    chunk = 128

    # Warm the jit cache on a scratch store so neither timed run pays
    # compilation (the overhead delta is the measurement, not the
    # compile).
    warm = TpuSpanStore(config)
    for i in range(0, len(spans), chunk):
        warm.apply(spans[i:i + chunk])

    # Baseline: same spans, no sink.
    plain = TpuSpanStore(config)
    t0 = time.perf_counter()
    for i in range(0, len(spans), chunk):
        plain.apply(spans[i:i + chunk])
    plain_s = time.perf_counter() - t0

    hot = TpuSpanStore(config)
    tiered = TieredSpanStore(hot, params=ArchiveParams.for_config(
        config, compact_fanin=2, small_span_limit=config.capacity,
        bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6,
    ))
    oracle = InMemorySpanStore()
    t0 = time.perf_counter()
    for i in range(0, len(spans), chunk):
        tiered.apply(spans[i:i + chunk])
    tiered_s = time.perf_counter() - t0
    oracle.apply(spans)

    # The fused step's lowering with the sink ATTACHED — must census
    # identically to the plain store's (capture is a separate launch).
    db = dev.make_device_batch(
        SpanBatch.empty(0, 0, 0),
        name_lc_id=np.zeros(0, np.int32),
        indexable=np.zeros(0, bool),
        pad_spans=256, pad_anns=512, pad_banns=256,
    )
    ops_plain = _count_ops(
        dev.ingest_step.lower(plain.state, db).as_text())
    ops_tiered = _count_ops(
        dev.ingest_step.lower(hot.state, db).as_text())

    # Identity vs oracle across the whole history (incl. evicted).
    tids = sorted({s.trace_id for s in spans})
    sample = tids[:4] + tids[len(tids) // 2:len(tids) // 2 + 4] \
        + tids[-4:]
    end_ts = 1 << 60
    t0 = time.perf_counter()
    fetch_ok = all(
        tiered.get_spans_by_trace_ids([t])
        == oracle.get_spans_by_trace_ids([t]) for t in sample
    )
    cold_fetch_s = time.perf_counter() - t0
    svc = sorted(oracle.get_all_service_names())[0]
    ids_ok = (
        tiered.get_trace_ids_by_name(svc, None, end_ts, 10 * n_spans)
        == oracle.get_trace_ids_by_name(svc, None, end_ts,
                                        10 * n_spans)
    )
    dur_ok = (tiered.get_traces_duration(sample)
              == oracle.get_traces_duration(sample))
    pruned0 = tiered.archive.c_pruned.value
    first_ts = min(s.first_timestamp for s in spans
                   if s.first_timestamp is not None)
    tiered.get_trace_ids_by_name(svc, None, first_ts + 1, 4)
    c = tiered.counters()
    return {
        "spans": len(spans),
        "capture_overhead_pct": round(
            100.0 * (tiered_s - plain_s) / plain_s, 1),
        "ingest_plain_s": round(plain_s, 3),
        "ingest_tiered_s": round(tiered_s, 3),
        "cold_fetch_ms_per_trace": round(
            cold_fetch_s / len(sample) * 1e3, 2),
        "segments_written": int(c["archive_segments_written"]),
        "compactions": int(c["archive_compactions"]),
        "segments_pruned": int(
            tiered.archive.c_pruned.value - pruned0),
        "cold_spans": int(c["archive_cold_spans"]),
        "cold_compression_ratio": round(
            c["archive_cold_raw_bytes"]
            / max(c["archive_cold_bytes"], 1.0), 2),
        "identical": bool(fetch_ok and ids_ok and dur_ok),
        "step_census_with_capture": ops_tiered,
        "step_census_plain": ops_plain,
    }


def run_pipeline(depth: int = 4) -> dict:
    """Pipelined-ingest phase: the same spans driven through the
    serial write path (inline capture sealing) and through the
    three-stage pipeline (async sealer), proving on every CI run that
    (a) the pipelined drive lands a BITWISE identical device state and
    an identical cold tier, (b) a warmed pipeline performs ZERO jit
    recompiles (pow2 staging buckets only hit cached entries), (c)
    H2D staging adds zero ops to the fused step's lowering, and (d)
    ingest never stalled on capture sealing (stall counter stays 0 at
    a generous backlog — deliberate backpressure is exercised in
    tests/test_pipeline.py instead). Overlap efficiency is reported as
    stage-busy-seconds / wall: > 1.0 means host encode + staging
    genuinely overlapped device compute (expect ~1.0 on the CPU
    backend, where "device compute" shares the host)."""
    import jax
    import numpy as np

    from zipkin_tpu.columnar.schema import SpanBatch
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    # Same geometry as run_archive so this phase reuses its jit cache.
    config = dev.StoreConfig(
        capacity=1 << 8, ann_capacity=1 << 10, bann_capacity=1 << 9,
        max_services=32, max_span_names=64, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=6,
        quantile_buckets=256,
    )
    # 2 ring turns: enough to lap the ring and seal several capture
    # windows (the gates are identity / recompiles / census / stall,
    # not throughput), at half the archive phase's drive cost.
    n_spans = 2 * config.capacity
    traces = generate_traces(n_traces=n_spans // 4, max_depth=3,
                             n_services=8)
    spans = [s for t in traces for s in t][:n_spans]
    chunk = 128

    def build(backlog):
        hot = TpuSpanStore(config)
        hot.capture_backlog = backlog
        return hot, TieredSpanStore(hot, params=ArchiveParams.for_config(
            config, compact_fanin=2, small_span_limit=config.capacity,
            bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6,
        ))

    def drive(tiered):
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            tiered.apply(spans[i:i + chunk])
        return time.perf_counter() - t0

    # Warm every jit the measured PIPELINED drive will hit (ingest,
    # sweep, bucket close, capture — staged device-resident arguments
    # key their own jit cache rows, distinct from host-numpy ones, see
    # dev.stage_batch), so the recompile gate below is a true
    # steady-state zero. The serial side needs no warm drive of its
    # own here: run() calls run_archive() first, which streams this
    # exact config/chunk geometry serially three times (standalone
    # run_pipeline callers just see compile time inside serial_s —
    # nothing is gated on it).
    warm_ph, warm_pt = build(64)
    warm_ph.start_pipeline(depth)
    drive(warm_pt)
    warm_ph.drain_pipeline()
    warm_pt.close()

    serial_hot, serial_t = build(0)
    serial_s = drive(serial_t)

    pipe_hot, pipe_t = build(64)
    compiles0 = dev.compile_count()
    pipe = pipe_hot.start_pipeline(depth)
    t0 = time.perf_counter()
    for i in range(0, len(spans), chunk):
        pipe_t.apply(spans[i:i + chunk])
    pipe_hot.drain_pipeline()
    pipe_hot.seal_barrier()
    pipelined_s = time.perf_counter() - t0
    recompiles = dev.compile_count() - compiles0
    encode_s = pipe.h_encode.sum
    stage_s = pipe.h_stage.sum
    commit_s = pipe.h_commit.sum
    pipe_hot.stop_pipeline()
    sealer = pipe_hot._sealer
    capture_stall_s = float(sealer.c_stall.value) if sealer else 0.0

    flat_a, _ = jax.tree_util.tree_flatten(serial_hot.state)
    flat_b, _ = jax.tree_util.tree_flatten(pipe_hot.state)
    identical = len(flat_a) == len(flat_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(flat_a, flat_b)
    )
    cs, cp = serial_t.counters(), pipe_t.counters()
    identical = (identical
                 and cs["archive_cold_spans"] == cp["archive_cold_spans"]
                 and cs["archive_segments_written"]
                 == cp["archive_segments_written"])

    # Staging must be invisible to the compiler: the fused step lowers
    # IDENTICALLY from device_put-staged arrays and host numpy arrays.
    db = dev.make_device_batch(
        SpanBatch.empty(0, 0, 0), name_lc_id=np.zeros(0, np.int32),
        indexable=np.zeros(0, bool),
        pad_spans=256, pad_anns=512, pad_banns=256,
    )
    ops_host = _count_ops(
        dev.ingest_step.lower(serial_hot.state, db).as_text())
    ops_staged = _count_ops(
        dev.ingest_step.lower(pipe_hot.state,
                              dev.stage_batch(db)).as_text())
    serial_t.close()
    pipe_t.close()
    return {
        "spans": len(spans),
        "depth": depth,
        "serial_ingest_s": round(serial_s, 3),
        "pipelined_ingest_s": round(pipelined_s, 3),
        "speedup": round(serial_s / pipelined_s, 2) if pipelined_s
        else 0,
        "overlap_efficiency": round(
            (encode_s + stage_s + commit_s) / pipelined_s, 2)
        if pipelined_s else 0,
        "encode_s": round(encode_s, 3),
        "stage_s": round(stage_s, 3),
        "commit_s": round(commit_s, 3),
        "capture_stall_s": round(capture_stall_s, 4),
        "windows_sealed": int(sealer.c_sealed.value) if sealer else 0,
        "recompiles_after_warmup": int(recompiles),
        "identical": bool(identical),
        "staging_census_equal": ops_host == ops_staged,
    }


def run_wal() -> dict:
    """Durability phase (r10 tentpole): the same spans driven through
    a plain store (the throughput baseline AND the uncrashed oracle)
    and through WAL-attached stores at the group-commit default and at
    fsync=off, proving on every CI run that (a) a full-log replay into
    a fresh store lands a BITWISE identical device state (the
    ack-after-append contract's other half: what was journaled is
    exactly what recovery rebuilds), (b) journaling adds ZERO jit
    recompiles in steady state and replay adds zero more (replay
    re-pads through the same pow2 buckets the drive compiled), and
    (c) the append overhead stays inside the acceptance budget (<= 10%
    at the group-commit default; fsync=off reproduces the no-WAL
    throughput). Overheads are paired per-round ratios, min over four
    interleaved rounds — the structural gates (identity/recompiles)
    are exact, the ratios are trend data on a noisy CPU."""
    import os
    import shutil
    import tempfile

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.testing.crash import states_bitwise_equal
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.wal import WriteAheadLog, recover

    config = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )
    traces = generate_traces(n_traces=2000, max_depth=3, n_services=16)
    spans = [s for t in traces for s in t][:5000]
    chunk = 128

    def drive(store):
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        return time.perf_counter() - t0

    root = tempfile.mkdtemp(prefix="wal-smoke-")
    try:
        n_dir = [0]

        def build(fsync):
            store = TpuSpanStore(config)
            if fsync is not None:
                n_dir[0] += 1
                d = os.path.join(root, f"wal-{fsync}-{n_dir[0]}")
                store.attach_wal(WriteAheadLog(d, fsync=fsync))
            return store

        drive(TpuSpanStore(config))  # jit warm-up (uncounted)
        compiles0 = dev.compile_count()
        # Interleaved rounds with PAIRED ratios: host noise (GC,
        # allocator warmth, machine load) drifts over seconds and
        # swamps the per-record append cost, so each round drives the
        # three configs back-to-back under the same conditions and the
        # overhead is the round's WAL/baseline ratio — load drift
        # cancels within a round where a ratio of cross-round floors
        # would pair a lucky-fast baseline against unlucky WAL drives.
        # The min over rounds is the least-noise estimate of the
        # intrinsic overhead (the structural gates are exact; the
        # ratios remain trend data on a noisy CI host).
        rounds = []
        last = {}
        for _ in range(4):
            times = {}
            for fsync in (None, "interval", "off"):
                store = build(fsync)
                times[fsync] = drive(store)
                prev = last.get(fsync)
                if prev is not None and prev.wal is not None:
                    prev.wal.close()
                last[fsync] = store
            rounds.append(times)
        base_s = min(r[None] for r in rounds)
        interval_s = min(r["interval"] for r in rounds)
        off_s = min(r["off"] for r in rounds)
        overhead_interval = min(
            r["interval"] / r[None] for r in rounds) - 1.0
        overhead_off = min(r["off"] / r[None] for r in rounds) - 1.0
        oracle, s_int, s_off = last[None], last["interval"], last["off"]
        steady_recompiles = dev.compile_count() - compiles0

        wal_stats = s_int.wal.stats()
        wal_dir = s_int.wal.directory
        s_int.wal.sync()
        s_int.wal.close()

        # Full-log replay into a FRESH store == the uncrashed oracle.
        compiles1 = dev.compile_count()
        wal2 = WriteAheadLog(wal_dir, fsync="off")
        t0 = time.perf_counter()
        rec, rstats = recover(
            None, wal2, fresh_store=lambda: TpuSpanStore(config))
        recovery_s = time.perf_counter() - t0
        replay_recompiles = dev.compile_count() - compiles1
        identical = states_bitwise_equal(oracle.state, rec.state)
        wal2.close()
        s_off.wal.close()
        return {
            "spans": len(spans),
            "baseline_ingest_s": round(base_s, 3),
            "wal_interval_ingest_s": round(interval_s, 3),
            "wal_off_ingest_s": round(off_s, 3),
            "append_overhead_interval": round(overhead_interval, 3),
            "append_overhead_off": round(overhead_off, 3),
            "steady_state_recompiles": int(steady_recompiles),
            "replay_recompiles": int(replay_recompiles),
            "replay_identical": bool(identical),
            "replayed_records": rstats["replayed_records"],
            "recovery_s": round(recovery_s, 3),
            "replay_spans_per_s": round(
                rstats["replayed_spans"] / max(rstats["replay_s"],
                                               1e-9), 1),
            "wal_bytes_per_span": round(
                wal_stats["wal_bytes"] / len(spans), 1),
            "wal_segments": wal_stats["wal_segments"],
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_query() -> dict:
    """Resident-query-engine phase (r11 tentpole): the three-tier read
    path (query/engine.py) proven structurally on every CI run:
    (a) sketch-tier answers (catalogs, quantiles, top-k, HLL) are
    IDENTICAL to the device read path's while costing zero device
    round-trips — p50 is gated in single-digit ms even on CPU;
    (b) the steady-state query loop performs ZERO jit recompiles (the
    resident programs stay resident); (c) a cache hit returns answers
    bitwise-equal to the cold computation, and an ingest commit
    invalidates precisely (the frontier-keyed re-answer matches a
    fresh store read). Index-tier latency is trend data on CPU (the
    ~110 ms dispatch floor this engine kills is a device-class
    property), but its p99 rides the JSON for the TPU bench to gate."""
    from zipkin_tpu import obs
    from zipkin_tpu.query.engine import QueryEngine
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    config = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )
    traces = generate_traces(n_traces=1200, max_depth=3, n_services=16)
    spans = [s for t in traces for s in t][:3000]
    store = TpuSpanStore(config)
    for i in range(0, len(spans), 128):
        store.apply(spans[i:i + 128])
    reg = obs.Registry()
    engine = QueryEngine(store, window_s=0.0, registry=reg)
    svcs = sorted(store.get_all_service_names())
    qs = [0.5, 0.95, 0.99]

    # Sketch-tier identity: every answer bitwise-equals the device
    # read path's (the conformance half of the sketch-tier contract).
    ident = engine.get_all_service_names() == store.get_all_service_names()
    for s in svcs:
        ident = ident and (
            engine.get_span_names(s) == store.get_span_names(s)
            and engine.service_duration_quantiles(s, qs)
            == store.service_duration_quantiles(s, qs)
            and engine.top_annotations(s) == store.top_annotations(s)
            and engine.top_binary_keys(s) == store.top_binary_keys(s)
        )
    ident = ident and (engine.estimated_unique_traces()
                       == store.estimated_unique_traces())

    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    queries = [("name", s, None, end_ts, 10) for s in svcs[:8]]
    engine.executor.run(queries)  # warm the multi-probe jit rows

    # Steady state: sketch + index loops must add ZERO compiles —
    # across the ingest jits AND the resident query programs
    # (dev.query_compile_count, the kernels the executor dispatches).
    compiles0 = dev.compile_count() + dev.query_compile_count()
    sk = obs.LatencySketch("q_sketch_s", "sketch-tier serve",
                           quantiles=(0.5, 0.99))
    for _ in range(40):
        t0 = time.perf_counter()
        engine.service_duration_quantiles(svcs[0], qs)
        engine.top_annotations(svcs[1 % len(svcs)])
        engine.get_all_service_names()
        sk.observe((time.perf_counter() - t0) / 3.0)
    ix = obs.LatencySketch("q_index_s", "index-tier dispatch",
                           quantiles=(0.5, 0.99))
    for _ in range(20):
        t0 = time.perf_counter()
        engine.executor.run(queries)  # cache-bypassing resident path
        ix.observe(time.perf_counter() - t0)
    recompiles = (dev.compile_count() + dev.query_compile_count()
                  - compiles0)

    # Cache: hit answers bitwise-equal to the cold computation, and an
    # ingest commit invalidates precisely (frontier advance).
    def ids(rows):
        return [[(i.trace_id, i.timestamp) for i in r] for r in rows]

    hits0 = engine.c_hits.value
    cold = ids(engine.get_trace_ids_multi(queries))
    warm = ids(engine.get_trace_ids_multi(queries))
    cache_hit_ok = (warm == cold
                    and engine.c_hits.value - hits0 >= len(queries))
    store.apply(spans[:256])  # frontier advances
    after = ids(engine.get_trace_ids_multi(queries))
    fresh = ids(store.get_trace_ids_multi(queries))
    invalidation_ok = after == fresh
    sks, ixs = sk.snapshot(), ix.snapshot()
    return {
        "spans": len(spans),
        "sketch_identical": bool(ident),
        "sketch_p50_ms": round(sks["p50"] * 1e3, 3),
        "sketch_p99_ms": round(sks["p99"] * 1e3, 3),
        "index_p50_ms": round(ixs["p50"] * 1e3, 3),
        "index_p99_ms": round(ixs["p99"] * 1e3, 3),
        "steady_recompiles": int(recompiles),
        "cache_hit_identical": bool(cache_hit_ok),
        "cache_invalidation_exact": bool(invalidation_ok),
        "cache_hits": int(engine.c_hits.value),
        "cache_misses": int(engine.c_misses.value),
        "sketch_answers": int(engine.c_sketch.value),
    }


def run_ingest_structure() -> dict:
    """Ingest-roofline phase (r12 tentpole): the three structural
    claims behind the batch-escalation / counting-sort / pallas work,
    proven on every CI run:

    (a) the counting-sort rank path's fused-step lowering carries
        strictly fewer stablehlo.sort ops than the argsort path's (the
        portable proxy for the deleted O(N log N) entry cost) while
        issuing no extra scatters/gathers — store-level bitwise
        identity between the two paths is the fuzz suite's job
        (tests/test_rank_paths.py), not re-driven here;
    (b) a batch-escalated geometry (StoreConfig.batch_spans) driven
        through the three-stage pipeline performs ZERO steady-state
        jit recompiles once warmed — escalation changes pad buckets,
        not compile-cache churn;
    (c) the stage-1 sketch-mirror COO delta (riding the hot encode
        path since r11) adds at most MAX_MIRROR_DELTA_RATIO to the
        encode stage, measured as paired per-round ratios (min over
        rounds — the WAL phase's noise discipline)."""
    import numpy as np

    from zipkin_tpu.store import census, device as dev
    from zipkin_tpu.store.base import should_index
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.columnar.schema import SpanBatch

    base = dict(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )
    cfg_arg = dev.StoreConfig(**base, rank_path="argsort",
                              batch_spans=256)
    cfg_cnt = dev.StoreConfig(**base, rank_path="counting",
                              batch_spans=256)
    # The escalated batch geometry: same store, bigger launches (the
    # ring guards clamp at capacity//2 = 512 — this IS the escalation
    # ceiling for the smoke ring).
    cfg_big = dev.StoreConfig(**base, rank_path="counting",
                              batch_spans=512)
    traces = generate_traces(n_traces=440, max_depth=3, n_services=16)
    spans = [s for t in traces for s in t][:1280]

    def drive(store, slice_spans=512):
        for i in range(0, len(spans), slice_spans):
            store.apply(spans[i:i + slice_spans])
        return store

    # Per-path census: lowering only — the trace also records each
    # config's active rank path (dev.active_paths), no drive needed.
    db = dev.make_device_batch(
        SpanBatch.empty(0, 0, 0), name_lc_id=np.zeros(0, np.int32),
        indexable=np.zeros(0, bool),
        pad_spans=256, pad_anns=1024, pad_banns=512,
    )
    census_arg = _count_ops(
        dev.ingest_step.lower(dev.init_state(cfg_arg), db).as_text())
    census_cnt = _count_ops(
        dev.ingest_step.lower(dev.init_state(cfg_cnt), db).as_text())

    # Batch escalation through the pipeline: warm the escalated
    # geometry end-to-end (staged device args key their own jit rows),
    # then gate steady-state recompiles at ZERO across a fresh
    # pipelined drive of the same geometry.
    warm = TpuSpanStore(cfg_big)
    warm.start_pipeline(4)
    drive(warm)
    warm.drain_pipeline()
    warm.stop_pipeline()
    meas = TpuSpanStore(cfg_big)
    compiles0 = dev.compile_count()
    meas.start_pipeline(4)
    t0 = time.perf_counter()
    drive(meas)
    meas.drain_pipeline()
    escalated_s = time.perf_counter() - t0
    recompiles = dev.compile_count() - compiles0
    meas.stop_pipeline()
    c_meas = meas.counters()
    warm.close()
    meas.close()

    # Sketch-mirror stage-1 cost: paired encode-vs-delta rounds over
    # the SAME launch groups (host-only — no device work — so the
    # probe uses a bigger span set than the drives: per-group fixed
    # delta costs then sit against a steady-state encode denominator
    # instead of dominating a tiny one). The first pass warms the
    # dictionaries; measured rounds are steady-state re-encodes.
    # cfg_big's 512-span chunks: the deployment geometry the delta
    # actually rides at (bigger launches amortize its per-group fixed
    # cost — measuring at tiny chunks would overstate it).
    probe = TpuSpanStore(cfg_big)
    m_traces = generate_traces(n_traces=900, max_depth=3,
                               n_services=16)
    m_spans = [s for t in m_traces for s in t][:2560]

    def encode_parts():
        parts = []
        for part in probe._chunk_by_trace(m_spans):
            batch = probe.codec.encode(part)
            indexable = np.fromiter(
                (should_index(s) for s in part), bool, len(part))
            name_lc = probe._name_lc_ids(batch)
            parts.extend(probe._chunk_columnar(batch, name_lc,
                                               indexable))
        return parts

    groups = list(probe._plan_units(encode_parts()))  # warm dicts
    ratios, enc_ms, delta_ms = [], [], []
    for _ in range(3):
        # The FULL stage-1 body writers pay (encode + index bits +
        # chunking + pow2 padding + the mirror delta, exactly what
        # _apply_pipelined runs under the encode lock)...
        t0 = time.perf_counter()
        groups = list(probe._plan_units(encode_parts()))
        for g in groups:
            probe._pad_unit(g)  # includes delta_of
        stage_s = time.perf_counter() - t0
        # ...vs the delta alone; ratio = delta / stage-without-delta.
        t0 = time.perf_counter()
        for g in groups:
            probe.sketch_mirror.delta_of(g)
        d_s = time.perf_counter() - t0
        ratios.append(d_s / max(stage_s - d_s, 1e-9))
        enc_ms.append((stage_s - d_s) * 1e3)
        delta_ms.append(d_s * 1e3)
    probe.close()
    return {
        "spans": len(spans),
        "census_argsort": census_arg,
        "census_counting": census_cnt,
        "rank_path_argsort_cfg": dev.active_paths(cfg_arg).get(
            "rank", ()),
        "rank_path_counting_cfg": dev.active_paths(cfg_cnt).get(
            "rank", ()),
        "rank_path_counting": c_meas["rank_path_counting"],
        "scatter_path_pallas": c_meas["scatter_path_pallas"],
        "batch_spans_geometries": [cfg_cnt.batch_spans,
                                   cfg_big.batch_spans],
        "escalated_batch_spans_limit": c_meas["batch_spans_limit"],
        "recompiles_after_batch_escalation": int(recompiles),
        "escalated_pipelined_s": round(escalated_s, 3),
        "mirror_delta_ratio": round(min(ratios), 4),
        "mirror_delta_ms": round(min(delta_ms), 2),
        "encode_ms": round(min(enc_ms), 2),
        "mirror_budget": census.MAX_MIRROR_DELTA_RATIO,
    }


def run_windows() -> dict:
    """Windowed-analytics phase (r13 tentpole), tier-1 gates:

    (a) census arithmetic — the windowed arena's fused-step cost is
        EXACTLY the gated bump (census.MAX_STEP_* = BASE + WINDOW_BUMP
        with the window on; the window-off lowering at the BASE
        counts, which is also the library-default lowering the main
        stream gates), so the feature can't silently grow;
    (b) mirror-vs-device BITWISE identity of all four window arrays
        after a multi-bucket drive (incl. error spans), serial AND
        pipelined;
    (c) zero steady-state recompiles with the window update fused
        (same drive twice through warmed shapes);
    (d) the sketch-tier windowed reads (quantiles / burn / heatmap)
        answer with zero device dispatches and the quantile lands
        inside the documented solver rank tolerance vs the exact span
        durations."""
    import numpy as np

    import jax

    from zipkin_tpu.aggregate import windows as win
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore

    cfg = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512, rank_path="counting",
        window_seconds=60, window_buckets=8,
    )
    rng = np.random.default_rng(42)
    eps = [Endpoint(1 + i, 80, f"wsvc{i}") for i in range(4)]
    base = 1_700_000_000_000_000

    def gen(n, seed_off=0):
        out = []
        for i in range(n):
            ep = eps[(i + seed_off) % 4]
            t0 = base + int(rng.integers(0, 5 * 60_000_000))
            d = int(rng.lognormal(7.0, 1.3)) + 1
            anns = [Annotation(t0, "sr", ep),
                    Annotation(t0 + d, "ss", ep)]
            if i % 9 == 0:
                anns.append(Annotation(t0 + 1, "error", ep))
            out.append(Span(i // 3 + 1, f"wop{i % 4}", i + 1, None,
                            tuple(anns), ()))
        return out

    spans = gen(600)

    def drive(store, pipelined):
        if pipelined:
            store.start_pipeline(4)
        for i in range(0, len(spans), 200):
            store.apply(spans[i:i + 200])
        if pipelined:
            store.drain_pipeline()
            store.stop_pipeline()

    def win_state(store):
        st = store.state
        return jax.device_get(
            (st.win_epoch, st.win_counts, st.win_sums, st.win_mm))

    serial = TpuSpanStore(cfg)
    drive(serial, False)
    piped = TpuSpanStore(cfg)
    drive(piped, True)
    dev_arrays = win_state(serial)
    mir = serial.sketch_mirror
    mirror_bitwise = all(
        np.array_equal(a, b) for a, b in zip(
            dev_arrays,
            (mir.win_epoch, mir.win_counts, mir.win_sums, mir.win_mm)))
    piped_bitwise = all(
        np.array_equal(a, b)
        for a, b in zip(dev_arrays, win_state(piped)))

    # (c) zero steady-state recompiles across a re-drive of warmed
    # shapes with the window update fused into the step.
    compiles0 = dev.compile_count()
    redrive = TpuSpanStore(cfg)
    drive(redrive, False)
    recompiles = dev.compile_count() - compiles0

    # (d) sketch-tier reads — pure host math; gate the solver's rank.
    # p50 over warmed calls, matching the r11 sketch-tier gate: the
    # first call pays one-time numpy/solver warmup, not serve cost.
    est = serial.windowed_quantiles("wsvc1", [0.5, 0.99])
    samples = []
    for _ in range(20):
        t0 = time.perf_counter()
        serial.windowed_quantiles("wsvc1", [0.5, 0.99])
        samples.append(time.perf_counter() - t0)
    q_ms = sorted(samples)[len(samples) // 2] * 1e3
    burn = serial.slo_burn("wsvc1", objective=0.99)
    heat = serial.latency_heatmap("wsvc1", bands=6)
    durs = np.sort([
        s.duration for s in spans
        if (s.service_name or "") == "wsvc1" and s.duration is not None
    ])
    rank_err = (abs(np.searchsorted(durs, est[0])
                    / max(len(durs) - 1, 1) - 0.5)
                if est else float("inf"))

    # (a) census arithmetic: window-on vs window-off lowerings.
    from zipkin_tpu.columnar.schema import SpanBatch

    db = dev.make_device_batch(
        SpanBatch.empty(0, 0, 0), name_lc_id=np.zeros(0, np.int32),
        indexable=np.zeros(0, bool),
        pad_spans=256, pad_anns=1024, pad_banns=512,
    )
    census_on = _count_ops(
        dev.ingest_step.lower(dev.init_state(cfg), db).as_text())
    census_off = _count_ops(dev.ingest_step.lower(
        dev.init_state(cfg._replace(window_seconds=0)), db).as_text())

    for s in (serial, piped, redrive):
        s.close()
    return {
        "census_window_on": census_on,
        "census_window_off": census_off,
        "mirror_bitwise": bool(mirror_bitwise),
        "pipelined_bitwise": bool(piped_bitwise),
        "recompiles_steady_state": int(recompiles),
        "windowed_quantile_ms": round(q_ms, 3),
        "quantile_rank_err": round(float(rank_err), 4),
        "solver_rank_tol": win.SOLVER_RANK_TOL,
        "burn_total": burn["windows"][0]["total"],
        "burn_errors": burn["windows"][0]["errors"],
        "heatmap_columns": len(heat["bucketStartsTs"]),
        "window_spans_folded": int(mir.win_spans_total),
        "window_errors_folded": int(mir.win_errors_total),
    }


def run_paged() -> dict:
    """Paged-layout phase (r19 tentpole), tier-1 gates:

    (a) census arithmetic — the paged fused-step lowering costs
        EXACTLY the gated bump (census.expected_census("+PAGED"); the
        ring lowering stays at BASE), so the layout can't silently
        grow the step;
    (b) ring-vs-paged BITWISE query parity on a skewed (zipf trace
        size) stream — per-trace reads AND id lookups answer
        identically through both layouts;
    (c) zero steady-state recompiles driving the paged layout through
        the ingest pipeline (same stream twice through warmed shapes);
    (d) skewed-workload ingest rate through the paged planner (a
        regression canary; the ≥2x retention-per-byte claim needs the
        full bench's eviction arm — bench.py bench_paged)."""
    import numpy as np

    import jax  # noqa: F401 — device_get via stores below

    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store import census
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore

    cfg_ring = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512, rank_path="counting",
    )
    cfg_paged = cfg_ring._replace(layout="paged", page_rows=128)

    # Skewed stream: zipf trace sizes, 1-span polls to 64-span batch
    # traces interleaved — the shape the paged layout exists for.
    rng = np.random.default_rng(7)
    eps = [Endpoint(1 + i, 80, f"psvc{i}") for i in range(4)]
    base = 1_700_000_000_000_000
    spans = []
    tid = 1
    while len(spans) < 700:
        size = min(int(rng.zipf(1.6)), 64)
        ep = eps[tid % 4]
        for j in range(size):
            t0 = base + tid * 1000 + j
            spans.append(Span(
                tid, f"pop{j % 4}", tid * 1000 + j + 1, None,
                (Annotation(t0, "sr", ep),
                 Annotation(t0 + 7, "ss", ep)), ()))
        tid += 1
    tids = list(range(1, tid))
    end_ts = base + tid * 1000 + 10_000

    def drive(store, pipelined=False):
        if pipelined:
            store.start_pipeline(4)
        for i in range(0, len(spans), 200):
            store.apply(spans[i:i + 200])
        if pipelined:
            store.drain_pipeline()
            store.stop_pipeline()

    ring = TpuSpanStore(cfg_ring)
    drive(ring)
    t0 = time.perf_counter()
    paged = TpuSpanStore(cfg_paged)
    drive(paged)
    paged_first_s = time.perf_counter() - t0

    # (b) bitwise parity: whole-trace reads and id lookups. One
    # batched sweep covers every trace (one launch per store); the
    # single-trace path is sampled — per-tid exhaustion lives in
    # tests/test_paged.py's slow lane.
    parity = (
        ring.get_spans_by_trace_ids(tids)
        == paged.get_spans_by_trace_ids(tids)) and all(
        ring.get_spans_by_trace_ids([t]) ==
        paged.get_spans_by_trace_ids([t])
        for t in tids[::8])
    key = lambda x: (x.trace_id, x.timestamp)  # noqa: E731
    ids_parity = all(
        sorted(ring.get_trace_ids_by_name(f"psvc{i}", None, end_ts,
                                          200), key=key)
        == sorted(paged.get_trace_ids_by_name(f"psvc{i}", None, end_ts,
                                              200), key=key)
        for i in range(4))

    # (c) zero steady-state recompiles through the pipeline: warm the
    # pipelined (device-staged) jit shapes by re-driving the already
    # -compared paged store, then a FRESH store must compile nothing.
    drive(paged, pipelined=True)
    compiles0 = dev.compile_count()
    steady = TpuSpanStore(cfg_paged)
    t0 = time.perf_counter()
    drive(steady, pipelined=True)
    skew_s = time.perf_counter() - t0
    recompiles = dev.compile_count() - compiles0

    # (a) census arithmetic: paged-on vs ring lowering at the smoke
    # shapes — exact equality against the lowering table rows.
    census_on = steady.step_census(256, 1024, 512)
    census_off = ring.step_census(256, 1024, 512)
    es, eo, eg = census.expected_census("+PAGED")
    bs, bo, bg = census.expected_census()

    pstats = steady.counters()
    for s in (ring, paged, steady):
        s.close()
    return {
        "census_paged_on": census_on,
        "census_paged_off": census_off,
        "census_expected_on": {"scatter": es, "sort": eo, "gather": eg},
        "census_expected_off": {"scatter": bs, "sort": bo,
                                "gather": bg},
        "query_parity_bitwise": bool(parity),
        "ids_parity_bitwise": bool(ids_parity),
        "recompiles_steady_state": int(recompiles),
        "skewed_spans_per_s": round(len(spans) / skew_s, 1),
        "first_drive_s": round(paged_first_s, 2),
        "pages_active": int(pstats["pages_active"]),
        "pages_free": int(pstats["pages_free"]),
        "page_reclaims_total": int(pstats["page_reclaims_total"]),
    }


def run_replication() -> dict:
    """WAL-shipped replication phase (r15 tentpole), proven
    structurally on every CI run: (a) a device-free ReplicaSpanStore
    fed only shipped WAL records over the real framed-TCP ship path
    answers the sketch tier BITWISE identical to the primary at the
    same applied frontier (mirror arrays equal element-for-element;
    catalog/quantile/top-k/HLL/trace-read answers equal) — while
    performing ZERO jit compiles (it is device-free by construction,
    and the warm standby replays into already-compiled shapes);
    (b) a warm standby fed the same stream lands a state bitwise equal
    to the primary's, and promoting it (the failover RTO) is
    measured; (c) the follower kept its lag bounded under full ingest
    load and caught up to lag 0 at the drained frontier, with the
    un-fetched tail pinned against truncation by its cursor."""
    import os  # noqa: F401 — tempdir cleanup below
    import shutil
    import tempfile

    from zipkin_tpu.replicate import (
        Follower,
        ReplicaTarget,
        ShipClient,
        ShipServer,
        StandbyTarget,
        WalShipper,
    )
    from zipkin_tpu.replicate.protocol import config_from_dict
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import TieredSpanStore
    from zipkin_tpu.store.replica import ReplicaSpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.testing.crash import states_bitwise_equal
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.wal import WriteAheadLog

    # The run_wal geometry — the ingest-step compiles are shared, so
    # this phase's primary AND standby drives hit warm jit caches.
    config = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )
    # 2560 = 20 aligned chunks: enough to lap the 1<<10 ring several
    # times (captures + cold segments on both drives) while keeping
    # the phase's three drives inside the tier-1 wall budget.
    traces = generate_traces(n_traces=2000, max_depth=3, n_services=16)
    spans = [s for t in traces for s in t][:2560]
    chunk = 128
    root = tempfile.mkdtemp(prefix="replication-smoke-")
    server = None
    followers = []
    stores = []
    try:
        # Warm-up: the EXACT stream through an identical (discarded)
        # tiered store compiles every pad bucket and capture-window
        # variant the real drive will hit, so the compile-count delta
        # below is attributable to replication alone.
        warm = TieredSpanStore(TpuSpanStore(config))
        for i in range(0, len(spans), chunk):
            warm.apply(spans[i:i + chunk])

        primary = TieredSpanStore(TpuSpanStore(config))
        wal = WriteAheadLog(os.path.join(root, "wal"), fsync="off")
        primary.attach_wal(wal)
        shipper = WalShipper(primary)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        port = server.server_address[1]
        server.serve_in_thread()

        # Chunk-ALIGNED split: a half boundary off the chunk grid would
        # shift every second-half chunk boundary off the warm drive's
        # (different ann-count pads -> a spurious "recompile").
        half = (len(spans) // 2 // chunk) * chunk
        for i in range(0, half, chunk):
            primary.apply(spans[i:i + chunk])
        compiles0 = dev.compile_count() + dev.query_compile_count()

        rc = ShipClient("127.0.0.1", port, "smoke-replica",
                        mode="replica")
        replica = ReplicaSpanStore(config_from_dict(
            rc.connect()["config"]))
        stores.append(replica)
        f_rep = Follower(ReplicaTarget(replica), rc,
                         poll_interval_s=0.002).start()
        followers.append(f_rep)
        sc = ShipClient("127.0.0.1", port, "smoke-standby",
                        mode="standby")
        sc.connect()
        standby = TpuSpanStore(config)
        f_sby = Follower(StandbyTarget(standby), sc,
                         poll_interval_s=0.002).start()
        followers.append(f_sby)

        # Load phase: keep ingesting while the followers stream.
        max_lag = 0
        for i in range(half, len(spans), chunk):
            primary.apply(spans[i:i + chunk])
            max_lag = max(max_lag, f_rep.lag_records())
        wal.sync()
        # Failover clock starts at the primary's last write: RTO =
        # standby applies the remaining durable tail + promote.
        t0 = time.perf_counter()
        sby_up = f_sby.drain(60.0)
        promoted = f_sby.promote()
        rto_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        rep_up = f_rep.drain(60.0)
        catch_up_s = time.perf_counter() - t0
        caught_up = sby_up and rep_up
        standby_bitwise = states_bitwise_equal(
            primary.hot.state, promoted.state)
        # Measured HERE — after the whole replication stream applied
        # but before the agreement reads (the primary's query kernels
        # compile on their first use in this geometry; those are read
        # compiles, not replication's).
        replication_compiles = (dev.compile_count()
                                + dev.query_compile_count()
                                - compiles0)

        # Replica agreement at the drained frontier.
        hot = primary.hot
        a_p = hot.ensure_sketch_mirror().arrays()
        a_r = replica.sketch_mirror.arrays()
        import numpy as np

        mirror_bitwise = all(
            np.array_equal(x, y) for x, y in zip(a_p, a_r))
        svcs = sorted(primary.get_all_service_names())
        end_ts = 1 << 62
        tids = sorted({s.trace_id for s in spans[::97]})[:24]
        agree = replica.get_all_service_names() == set(svcs)
        for svc in svcs[:4]:
            agree &= (replica.service_duration_quantiles(
                svc, [0.5, 0.95, 0.99])
                == primary.service_duration_quantiles(
                    svc, [0.5, 0.95, 0.99]))
            agree &= (replica.top_annotations(svc)
                      == primary.top_annotations(svc))
            agree &= (replica.top_binary_keys(svc)
                      == primary.top_binary_keys(svc))
            agree &= (replica.get_trace_ids_by_name(
                svc, None, end_ts, 10)
                == primary.get_trace_ids_by_name(svc, None, end_ts,
                                                 10))
        agree &= (replica.estimated_unique_traces()
                  == primary.estimated_unique_traces())
        agree &= (replica.get_spans_by_trace_ids(tids)
                  == primary.get_spans_by_trace_ids(tids))
        agree &= (replica.traces_exist(tids)
                  == primary.traces_exist(tids))
        agree &= (replica.get_traces_duration(tids)
                  == primary.get_traces_duration(tids))

        # Sketch-tier latency off the replica (pure numpy).
        from zipkin_tpu import obs

        sk = obs.LatencySketch("bench_replica_sketch_seconds",
                               "replica sketch-tier serve")
        for i in range(60):
            t0 = time.perf_counter()
            replica.service_duration_quantiles(
                svcs[i % len(svcs)], [0.5, 0.99])
            sk.observe(time.perf_counter() - t0)
        p50_ms = sk.snapshot()["p50"] * 1e3

        status = shipper.status()
        cursors = wal.cursors()
        return {
            "spans": len(spans),
            "records_shipped": int(
                status["followers"]["smoke-replica"]["shippedRecords"]),
            "shipped_bytes": int(
                status["followers"]["smoke-replica"]["shippedBytes"]),
            "replica_mirror_bitwise": bool(mirror_bitwise),
            "replica_answers_identical": bool(agree),
            "replication_recompiles": int(replication_compiles),
            "standby_bitwise": bool(standby_bitwise),
            "failover_rto_s": round(max(rto_s, 1e-4), 4),
            "max_lag_records": int(max_lag),
            "caught_up": bool(caught_up),
            "catch_up_s": round(catch_up_s, 3),
            "replica_sketch_p50_ms": round(p50_ms, 3),
            "follower_cursor_pinned": bool(
                cursors.get("smoke-replica", 0) >= 1),
        }
    finally:
        for f in followers:
            f.close()
        for s in stores:
            s.close()
        if server is not None:
            server.shutdown()
        shutil.rmtree(root, ignore_errors=True)


def run_sharded() -> dict:
    """Multi-chip sharded-serving phase (r16 tentpole): a 2-shard
    fleet on the host-simulated mesh must (a) fuse concurrent API
    reads through the cross-shard dispatcher into collective launches
    whose answers are BITWISE identical to serialized execution, (b)
    serve that burst with ZERO jit recompiles (the mapped kernels are
    resident; the dispatcher only changes who launches them), and (c)
    answer the fleet sketch tier bitwise against a single-device
    oracle fed the same spans — name-aligned histogram rows (the two
    codecs may assign dictionary ids in different orders; values per
    service must still match exactly) and identical HLL registers."""
    import threading

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from zipkin_tpu.parallel.shard import ShardedSpanStore
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    devs = jax.devices()
    if len(devs) < 2:
        # Standalone invocation on a true single-device backend: the
        # tier-1 lane always has the 8-device virtual mesh (conftest
        # exports XLA_FLAGS before spawning this script).
        return {"skipped": "single-device backend"}
    mesh = Mesh(np.array(devs[:2]), axis_names=("shard",))
    config = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=64, max_span_names=128, max_annotation_values=512,
        max_binary_keys=128, cms_width=1 << 10, hll_p=8,
        quantile_buckets=128,
    )
    spans = [
        s for t in generate_traces(n_traces=48, max_depth=3,
                                   n_services=16,
                                   rng=np.random.default_rng(16))
        for s in t
    ]
    # A generous micro-window: every barrier-released reader must land
    # in ONE batch even on a loaded CI host (the launch-count gate in
    # tests/test_bench_smoke.py rides on it); production deployments
    # run single-digit-ms windows (main/example.py --query-window-ms).
    store = ShardedSpanStore(mesh, config, dispatch_window_s=0.5)
    single = TpuSpanStore(config)
    try:
        t0 = time.perf_counter()
        store.apply(spans)
        ingest_s = time.perf_counter() - t0
        single.apply(spans)
        svcs = sorted(store.get_all_service_names())[:4]
        end_ts = 2**62

        # Warm every kernel the burst hits, then drain the window so
        # the recompile/launch deltas below measure steady state only.
        for svc in svcs:
            store.service_duration_quantiles(svc, [0.5, 0.99])
            store.get_trace_ids_by_name(svc, None, end_ts, 10)
        store.get_trace_ids_multi(
            [("name", svc, None, end_ts, 10) for svc in svcs])
        store.dispatcher.drain()

        barrier = threading.Barrier(9)
        results: dict = {}
        errors: list = []

        def cat_worker(i, svc):
            try:
                barrier.wait()
                results[i] = store.service_duration_quantiles(
                    svc, [0.5, 0.99])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        def ids_worker(i, svc):
            try:
                barrier.wait()
                results[i] = [
                    (r.trace_id, r.timestamp)
                    for r in store.get_trace_ids_by_name(
                        svc, None, end_ts, 10)]
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        threads = (
            [threading.Thread(target=cat_worker, args=(i, svcs[i]))
             for i in range(4)]
            + [threading.Thread(target=ids_worker, args=(4 + i, svcs[i]))
               for i in range(4)]
        )
        for t in threads:
            t.start()
        compiles0 = dev.compile_count()
        launches0 = store.collective_launches()
        t0 = time.perf_counter()
        barrier.wait()
        for t in threads:
            t.join(timeout=120.0)
        burst_s = time.perf_counter() - t0
        burst_launches = store.collective_launches() - launches0
        recompiles = dev.compile_count() - compiles0

        # Serialized identity: each query re-issued alone must answer
        # exactly what it answered inside the fused burst.
        identical = not errors and all(
            results[i] == store.service_duration_quantiles(
                svcs[i], [0.5, 0.99])
            for i in range(4)
        ) and all(
            results[4 + i] == [
                (r.trace_id, r.timestamp)
                for r in store.get_trace_ids_by_name(
                    svcs[i], None, end_ts, 10)]
            for i in range(4)
        )

        # Fleet sketch tier vs the single-device oracle, name-aligned.
        fleet = store.ensure_sketch_mirror()
        oracle = single.ensure_sketch_mirror()
        names = sorted(single.get_all_service_names())
        rows_ok = bool(names) and all(
            np.array_equal(
                fleet.hist_row(store.dicts.services.get(n)),
                oracle.hist_row(single.dicts.services.get(n)))
            for n in names
        )
        hll_ok = np.array_equal(fleet.hll_registers(),
                                oracle.hll_registers())
        names_ok = set(names) == set(store.get_all_service_names())

        dstats = store.dispatcher.stats()
        return {
            "shards": store.n,
            "spans": len(spans),
            "ingest_spans_per_s": round(len(spans) / ingest_s, 1),
            "burst_reads": 8,
            "burst_ms": round(burst_s * 1e3, 2),
            "burst_launches": int(burst_launches),
            "steady_state_recompiles": int(recompiles),
            "dispatcher_batches": dstats["batches"],
            "dispatcher_launches_saved": dstats["launches_saved"],
            "identical": bool(identical),
            "errors": errors[:4],
            "fleet_hist_rows_bitwise": bool(rows_ok),
            "fleet_hll_bitwise": bool(hll_ok),
            "service_names_identical": bool(names_ok),
        }
    finally:
        store.close()


def run_fleet_obs() -> dict:
    """Fleet-observability phase (r17 tentpole), proven on every CI
    run: (a) a live primary+follower ship pair under ingest lands ONE
    causally-linked self-trace spanning encode → WAL append → fsync →
    ship → follower apply in the primary's own store, parent ids
    verified; (b) the federated ``/metrics?fleet=1`` merge carries
    both processes' samples label-distinguished with values bitwise
    identical to each process's own scrape; (c) the stall watchdog
    fires on an injected parked-fsync error and clears when the error
    does; (d) self-tracing at the production sampling cadence costs
    ≤5% ingest wall time (paired min-of-N, lineage on vs off) and adds
    ZERO new device launches in steady state (compile-count delta 0,
    fused-step census equality)."""
    import os
    import shutil
    import tempfile

    from zipkin_tpu import obs
    from zipkin_tpu.obs import fleet as fobs
    from zipkin_tpu.replicate import (
        Follower,
        ReplicaTarget,
        ShipClient,
        ShipServer,
        WalShipper,
    )
    from zipkin_tpu.replicate.protocol import config_from_dict
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.replica import ReplicaSpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import ColumnarTraceGen, generate_traces
    from zipkin_tpu.wal import WriteAheadLog

    # run_replication's geometry: every ingest-step compile this phase
    # needs is already warm by the time it runs.
    config = dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )
    traces = generate_traces(n_traces=1000, max_depth=3, n_services=16)
    spans = [s for t in traces for s in t][:1280]
    chunk = 128
    root = tempfile.mkdtemp(prefix="fleet-obs-smoke-")
    server = None
    follower = None
    stores = []
    wals = []
    try:
        # -- (a) live ship pair: one causally-linked trace ------------
        reg = obs.Registry()
        primary = TpuSpanStore(config)
        stores.append(primary)
        wal = WriteAheadLog(os.path.join(root, "wal-pair"), fsync="off")
        wals.append(wal)
        primary.attach_wal(wal)
        tracker = fobs.LineageTracker(primary.apply, registry=reg,
                                      sample_every=1)
        primary.attach_lineage(tracker)
        shipper = WalShipper(primary, registry=reg, tracker=tracker)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        port = server.server_address[1]
        server.serve_in_thread()

        freg = obs.Registry()
        rc = ShipClient("127.0.0.1", port, "smoke-fleet-replica",
                        mode="replica")
        replica = ReplicaSpanStore(config_from_dict(
            rc.connect()["config"]), background_compaction=False)
        stores.append(replica)
        flin = fobs.FollowerLineage("smoke-fleet-replica",
                                    mode="replica", registry=freg)
        follower = Follower(ReplicaTarget(replica), rc,
                            registry=freg, lineage=flin)
        for i in range(0, len(spans), chunk):
            primary.apply(spans[i:i + chunk])
        wal.sync()
        deadline = time.perf_counter() + 60.0
        while (replica.applied_seq() < wal.last_seq
               and time.perf_counter() < deadline):
            follower.step()
        follower.step()  # backhaul the buffered apply spans + metrics
        tracker.flush()
        wal.sync()

        want = {"ingest unit", "wal append", "wal fsync", "ship",
                "replica apply"}
        trace_roundtrip = False
        parent_ids_ok = False
        for itid in primary.get_trace_ids_by_name(
                "zipkin-tpu", None, 1 << 62, 64):
            trace = primary.get_spans_by_trace_ids([itid.trace_id])[0]
            names = {s.name for s in trace}
            if not (want <= names):
                continue
            trace_roundtrip = True
            roots = [s for s in trace
                     if s.name == "ingest unit" and s.parent_id is None]
            parent_ids_ok = bool(roots) and all(
                s.parent_id == roots[0].id
                and s.trace_id == roots[0].trace_id
                for s in trace if s.name in want - {"ingest unit"})
            break

        # -- (b) federation merge: bitwise vs own scrapes -------------
        fleet = fobs.FleetObs(
            role="primary", registry=reg, tracker=tracker,
            remote_sources=shipper.fleet_sources,
            replication=shipper.status)
        fed = fleet.federated_text()
        labels_ok = ('role="primary"' in fed
                     and 'follower="smoke-fleet-replica"' in fed)

        def _vals(text):
            out = []
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    name = line.split("{")[0].split(" ")[0]
                    out.append((name, line.rsplit(" ", 1)[1]))
            return sorted(out)

        # The follower's snapshot was pushed over FETCH meta; its
        # samples in the merged view must format exactly as its own
        # scrape does (values may have advanced since the push, so
        # compare a fresh snapshot rendered through the fed path).
        snap = fobs.registry_snapshot(freg)
        fed_solo = fobs.render_federated([((), snap)])
        federation_bitwise = _vals(fed_solo) == _vals(freg.render_text())
        visible_lag_recorded = (
            "zipkin_replication_visible_lag_seconds" in fed
            and flin.lag_seconds() is not None)

        # -- (c) watchdog fires on an injected fsync stall ------------
        rec_ring = fobs.FlightRecorder()
        wd = fobs.Watchdog(recorder=rec_ring, registry=reg)
        wd.add_probe("wal_fsync", fobs.fsync_parked_probe(wal))
        ok_before = wd.check()["ready"]
        wal._sync_error = RuntimeError("injected fsync stall")
        fired = wd.check()
        wal._sync_error = None
        cleared = wd.check()
        watchdog_fired = (ok_before and not fired["ready"]
                          and "injected fsync stall"
                          in fired["reasons"][0]["reason"])
        watchdog_cleared = bool(cleared["ready"] and len(rec_ring) == 2)

        # -- (d) overhead + zero new device launches ------------------
        def drive(store):
            t0 = time.perf_counter()
            for i in range(0, len(spans), chunk):
                store.apply(spans[i:i + chunk])
            return time.perf_counter() - t0

        off = TpuSpanStore(config)
        stores.append(off)
        wal_off = WriteAheadLog(os.path.join(root, "wal-off"),
                                fsync="off")
        wals.append(wal_off)
        off.attach_wal(wal_off)
        on = TpuSpanStore(config)
        stores.append(on)
        wal_on = WriteAheadLog(os.path.join(root, "wal-on"),
                               fsync="off")
        wals.append(wal_on)
        on.attach_wal(wal_on)
        trk_on = fobs.LineageTracker(on.apply, registry=obs.Registry())
        on.attach_lineage(trk_on)  # production cadence (1-in-64)
        drive(off), drive(on)  # warm every pad bucket both will hit
        compiles0 = dev.compile_count() + dev.query_compile_count()
        t_off = min(drive(off) for _ in range(3))
        t_on = min(drive(on) for _ in range(3))
        lineage_compiles = (dev.compile_count()
                            + dev.query_compile_count() - compiles0)
        overhead_ratio = t_on / t_off if t_off > 0 else 0.0
        def _census(store):
            db = dev.make_device_batch(
                *ColumnarTraceGen(store.dicts, n_services=8)
                .next_batch(8),
                pad_spans=512, pad_anns=1024, pad_banns=512)
            return _count_ops(
                dev.ingest_step.lower(store.state, db).as_text())

        census_on = _census(on)
        census_off = _census(off)

        return {
            "spans": len(spans),
            "trace_roundtrip": bool(trace_roundtrip),
            "parent_ids_ok": bool(parent_ids_ok),
            "federation_labels_ok": bool(labels_ok),
            "federation_bitwise": bool(federation_bitwise),
            "visible_lag_recorded": bool(visible_lag_recorded),
            "watchdog_fired": bool(watchdog_fired),
            "watchdog_cleared": bool(watchdog_cleared),
            "overhead_ratio": round(overhead_ratio, 4),
            "lineage_on_s": round(t_on, 4),
            "lineage_off_s": round(t_off, 4),
            "lineage_steady_state_compiles": int(lineage_compiles),
            "census_equal": census_on == census_off,
            "fleet_processes": len(fleet.status()["processes"]),
        }
    finally:
        if follower is not None:
            follower.close()
        if server is not None:
            server.shutdown()
            server.server_close()
        for s in stores:
            close = getattr(s, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        for w in wals:
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        shutil.rmtree(root, ignore_errors=True)


def run_lint() -> dict:
    """graftlint phase (tier-1 gated): the concurrency/JAX-hazard
    analyzer (zipkin_tpu/analysis, docs/STATIC_ANALYSIS.md) over the
    whole package against the checked-in baseline. Zero NEW findings
    is the gate — the lock-order/guarded-by/sync-under-lock/jit
    conventions the write path depends on stay machine-checked on
    every CI run, inside the analyzer's 30s budget."""
    import os

    from zipkin_tpu.analysis import ALL_RULES, analyze, load_project
    from zipkin_tpu.analysis import baseline as lint_baseline

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.perf_counter()
    project = load_project([os.path.join(repo, "zipkin_tpu")], repo)
    findings = analyze(project)
    base_path = os.path.join(repo, "graftlint-baseline.json")
    if os.path.exists(base_path):
        new, stale = lint_baseline.diff(
            findings, lint_baseline.load(base_path))
    else:
        new, stale = findings, []
    return {
        "files": len(project.modules),
        "locks": len(project.locks),
        "rules": len(ALL_RULES),
        "findings_total": len(findings),
        "findings_new": len(new),
        "stale_baseline_entries": len(stale),
        "new": [f.render() for f in new[:20]],
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }


def run(total_spans: int = 7000, k_queries: int = 8) -> dict:
    import numpy as np  # noqa: F401  (kept: smoke envs import-check it)

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import ColumnarTraceGen

    config = dev.StoreConfig(
        capacity=1 << 12, ann_capacity=1 << 13, bann_capacity=1 << 12,
        max_services=64, max_span_names=128, max_annotation_values=512,
        max_binary_keys=128, cms_width=1 << 12, hll_p=8,
        quantile_buckets=512,
        # Pin the counting rank path: the op-count gate below is the
        # COUNTING path's census (95/4/79 ceilings). "auto" would pick
        # argsort on the CPU CI backend (backend-aware policy,
        # dev.rank_mode) and gate the wrong lowering.
        rank_path="counting",
    )
    store = TpuSpanStore(config)
    gen = ColumnarTraceGen(store.dicts, n_services=32, n_span_names=64,
                           spans_per_trace=7)
    batch_traces = 64
    pad_s, pad_a, pad_b = 512, 1024, 512
    dbs = []
    n_batches = max(1, total_spans // (batch_traces * 7))
    for _ in range(n_batches):
        batch, name_lc, indexable = gen.next_batch(batch_traces)
        dbs.append(dev.make_device_batch(
            batch, name_lc, indexable,
            pad_spans=pad_s, pad_anns=pad_a, pad_banns=pad_b,
        ))

    # Op-count census of the fused step's lowering (the compile below
    # shares the jit cache, so this adds a trace, not a compile). The
    # telemetry counter block must stay a pure read: its lowering may
    # contain NO scatter/sort, and the step census is taken with the
    # obs layer fully wired — together they prove the device counter
    # fetch adds zero passes (tests/test_bench_smoke.py gates both).
    state = store.state
    ops = _count_ops(dev.ingest_step.lower(state, dbs[0]).as_text())
    cb_ops = _count_ops(dev.counter_block.lower(state).as_text())

    # Fused-ingest timing (compile excluded: first step warms). The
    # warm-up step's spans are excluded from the rate — spans_seen is
    # snapshotted before t0 so the numerator matches the timed window.
    # The timed loop stays ASYNC (dispatch pipelining included), the
    # r6 methodology — ingest_spans_per_s remains trend-comparable.
    state = dev.ingest_step(state, dbs[0])
    import jax

    warm = int(jax.device_get(state.counters["spans_seen"]))
    t0 = time.perf_counter()
    for db in dbs:
        state = dev.ingest_step(state, db)
    seen = int(jax.device_get(state.counters["spans_seen"]))
    dt = time.perf_counter() - t0
    total = seen - warm
    # Telemetry sketch pass: a SEPARATE loop, synced per step
    # (device_get is the reliable barrier), so the per-step p50/p99
    # never perturbs the throughput window above.
    from zipkin_tpu import obs

    step_sketch = obs.LatencySketch(
        "bench_ingest_step_seconds", "per-step wall time")
    for db in dbs:
        ts_step = time.perf_counter()
        state = dev.ingest_step(state, db)
        jax.device_get(state.write_pos)
        step_sketch.observe(time.perf_counter() - ts_step)
    seen = int(jax.device_get(state.counters["spans_seen"]))
    store.adopt_state(state, spans_written=seen)

    # Batched-query scaling: k singular launches vs one multi launch.
    end_ts = int(jax.device_get(state.ts_max)) + 1
    svcs = sorted(store.get_all_service_names())
    queries = [
        ("name", svcs[i % len(svcs)], None, end_ts, 10)
        for i in range(k_queries)
    ]

    def serial():
        return [store.get_trace_ids_by_name(q[1], q[2], q[3], q[4])
                for q in queries]

    def batched():
        return store.get_trace_ids_multi(queries)

    serial(), batched()  # warm both paths' compile caches
    t0 = time.perf_counter()
    want = serial()
    serial_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    got = batched()
    batched_s = time.perf_counter() - t0
    identical = [
        [(i.trace_id, i.timestamp) for i in ids] for ids in got
    ] == [
        [(i.trace_id, i.timestamp) for i in ids] for ids in want
    ]

    step_ms = {
        k: (round(v * 1e3, 3) if k in ("sum", "mean", "stddev", "p50",
                                       "p99") and v == v else v)
        for k, v in step_sketch.snapshot().items()
    }
    from zipkin_tpu.store import census

    return {
        "metric": "bench_smoke",
        "archive": run_archive(),
        "pipeline": run_pipeline(),
        "wal": run_wal(),
        "query": run_query(),
        "ingest_structure": run_ingest_structure(),
        "windows": run_windows(),
        "paged": run_paged(),
        "replication": run_replication(),
        "sharded": run_sharded(),
        "fleet_obs": run_fleet_obs(),
        "lint": run_lint(),
        # The main stream runs the library default (window arena OFF),
        # so its step census gates at the BASE ceilings; the windows
        # phase gates the window-on lowering at BASE + WINDOW_BUMP.
        "census_ceilings": {
            "scatter": census.BASE_STEP_SCATTERS,
            "sort": census.BASE_STEP_SORTS,
            "gather": census.BASE_STEP_GATHERS,
        },
        "spans": total,
        "ingest_spans_per_s": round(total / dt, 1),
        "ingest_ms_per_batch": round(dt / len(dbs) * 1e3, 2),
        "step_scatters": ops["scatter"],
        "step_gathers": ops["gather"],
        "step_sorts": ops["sort"],
        "telemetry": {
            "counter_block": store.counter_block(),
            "counter_block_scatters": cb_ops["scatter"],
            "counter_block_sorts": cb_ops["sort"],
            "ingest_step_ms": step_ms,
        },
        "multi_query": {
            "k": k_queries,
            "serial_ms": round(serial_s * 1e3, 2),
            "batched_ms": round(batched_s * 1e3, 2),
            "speedup": round(serial_s / batched_s, 2) if batched_s else 0,
            "identical": identical,
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spans", type=int, default=7000)
    ap.add_argument("--k", type=int, default=8)
    args = ap.parse_args()
    print(json.dumps(run(args.spans, args.k)), flush=True)


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
