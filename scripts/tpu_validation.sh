#!/usr/bin/env bash
# Run the full TPU validation ladder the moment the axon tunnel answers.
# Order matters: cheap compile probes first (fail fast, nothing queued),
# then the full benchmark (which itself runs the kernel comparison and
# the automatic 1B-span attempt). ONE process touches the TPU at a time
# (NOTES_r03 §7) — do not run anything else against the chip while this
# is in flight.
set -u
cd "$(dirname "$0")/.."
STAMP=$(date +%Y%m%d_%H%M%S)
OUT=/tmp/tpu_validation_$STAMP
mkdir -p "$OUT"
echo "== 1/3 pallas Mosaic compile probe =="
timeout 600 python - <<'EOF' 2>&1 | tee "$OUT/pallas_probe.log"
import jax, jax.numpy as jnp
print("platform:", jax.devices()[0].platform)
from zipkin_tpu.ops.pallas_kernels import flat_histogram
import numpy as np
idx = jnp.asarray(np.random.default_rng(0).integers(0, 2048, size=4096), jnp.int32)
w = jnp.ones(4096, jnp.float32)
out = flat_histogram(idx, w, 2048)
print("pallas flat_histogram compiled+ran:", float(out.sum()))
EOF
echo "== 2/3 index exactness at bench shapes (quick stream) =="
timeout 2400 python bench.py --spans 2e7 2>&1 | tee "$OUT/bench_quick.log" | tail -3
echo "== 3/3 full benchmark (100M + compare + 1B attempt) =="
timeout 14400 python bench.py 2>&1 | tee "$OUT/bench_full.log" | tail -3
echo "artifacts in $OUT"
