"""Ingest throughput benchmark (BASELINE.md config #2 scaled to runtime).

Streams ColumnarTraceGen batches through the fused device ingest_step
and reports spans/sec, compared against the reference-shaped CPU path
(python object spans → InMemorySpanStore.apply — the in-process
analogue of the JVM collector's hot write path).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time


def bench_tpu_ingest(total_spans: int = 2_000_000, batch_traces: int = 8192):
    import jax
    import numpy as np

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import ColumnarTraceGen

    config = dev.StoreConfig(
        capacity=1 << 20, ann_capacity=1 << 21, bann_capacity=1 << 20,
        max_services=256, max_span_names=1024, max_annotation_values=2048,
        max_binary_keys=256, cms_width=1 << 16, hll_p=14,
        quantile_buckets=1024,
    )
    store = TpuSpanStore(config)
    gen = ColumnarTraceGen(store.dicts, n_services=256, n_span_names=1024,
                           spans_per_trace=7)
    spt = gen.spans_per_trace
    pad_spans = batch_traces * spt
    # Pre-generate a rotation of host batches so generation cost doesn't
    # pollute the device measurement.
    dbs = []
    for _ in range(4):
        batch, name_lc, indexable = gen.next_batch(batch_traces)
        dbs.append(dev.make_device_batch(
            batch, name_lc, indexable,
            pad_spans=pad_spans, pad_anns=2 * pad_spans, pad_banns=pad_spans,
        ))
    state = store.state
    # Warmup/compile.
    state = dev.ingest_step(state, dbs[0])
    jax.block_until_ready(state.counters["spans_seen"])

    n_steps = max(1, total_spans // pad_spans)
    t0 = time.perf_counter()
    for i in range(n_steps):
        state = dev.ingest_step(state, dbs[i % len(dbs)])
    jax.block_until_ready(state.counters["spans_seen"])
    dt = time.perf_counter() - t0
    return (n_steps * pad_spans) / dt


def bench_cpu_reference(total_spans: int = 20_000):
    from zipkin_tpu.store.memory import InMemorySpanStore
    from zipkin_tpu.tracegen import generate_traces

    traces = generate_traces(n_traces=max(1, total_spans // 20), max_depth=5)
    spans = [s for t in traces for s in t][:total_spans]
    store = InMemorySpanStore()
    t0 = time.perf_counter()
    for i in range(0, len(spans), 500):
        store.apply(spans[i:i + 500])
    dt = time.perf_counter() - t0
    return len(spans) / dt


def main():
    import sys

    smoke = "--smoke" in sys.argv
    if smoke:
        tpu_rate = bench_tpu_ingest(total_spans=200_000, batch_traces=1024)
        cpu_rate = bench_cpu_reference(total_spans=2_000)
    else:
        tpu_rate = bench_tpu_ingest()
        cpu_rate = bench_cpu_reference()
    print(json.dumps({
        "metric": "ingest_throughput",
        "value": round(tpu_rate, 1),
        "unit": "spans/sec",
        "vs_baseline": round(tpu_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
