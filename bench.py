"""BASELINE.md benchmark harness — all five configs, one JSON line.

Configs (BASELINE.md / BASELINE.json):
  #1 CPU reference path: tracegen spans -> SQL store (the anormdb role,
     store/sql.py) — ingest rate, index-query latency, and the
     incremental dependency-aggregation job (AnormAggregator.scala:32-90
     semantics). This is the honest ``vs_baseline`` denominator.
  #2 TPU ingest: stream N spans (default 100M+) of 1k-service tracegen
     traffic through the fused device ingest_step at ring capacity 2^22,
     with the production dependency-archive policy running in-loop.
  #3 dep-link queries: get_dependencies() p50/p99 off the streaming bank.
  #4 per-service latency percentiles (p50/p95/p99) off the device
     log-histogram, p50/p99 latency.
  #5 cardinality (HLL distinct traces) + top-k annotations, p50/p99.
  Plus the read path VERDICT cares about: get_trace_ids by service /
  span name / annotation / binary value, durations, and whole-trace
  materialization, each timed wall-clock through the public SpanStore
  API (device kernel + host decode — what an API call pays) — and the
  batched-query phase (bench_batched_queries): k queries through one
  get_trace_ids_multi launch vs k singular dispatches, the
  dispatch-floor amortization the API's query coalescer rides.

Span stream: one device-resident template batch, re-stamped ON DEVICE
each step (trace/span/parent ids XOR a per-step salt — preserving the
join structure — and timestamps shifted forward), so 100M *distinct*
spans stream at device rate without host generation in the loop.

Usage:
  python bench.py                  # full run (real TPU, ~100M spans)
  python bench.py --smoke          # small shapes (CI / CPU)
  python bench.py --compare-kernels  # + XLA vs pallas scatter ingest
  python bench.py --spans 2e8      # override stream length

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"detail": {...}} — value is TPU ingest spans/sec, vs_baseline is
against the SQL CPU reference path (config #1).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

import numpy as np


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:8.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()

SPT = 7  # spans per generated trace


def _pctl(samples_ms):
    a = np.asarray(samples_ms, np.float64)
    return {
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
    }


def _timeit(fn, reps: int, warmup: int = 2):
    for _ in range(warmup):
        fn()
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1e3)
    return _pctl(out)


# ---------------------------------------------------------------------------
# Config #1 — CPU reference path (SQL store, the anormdb role)
# ---------------------------------------------------------------------------


def bench_sql_baseline(total_spans: int = 10_000):
    from zipkin_tpu.aggregate.job import IncrementalAggregator
    from zipkin_tpu.store.sql import SqliteSpanStore
    from zipkin_tpu.tracegen import generate_traces

    traces = generate_traces(
        n_traces=max(1, total_spans // 8), max_depth=5, n_services=10
    )
    spans = [s for t in traces for s in t][:total_spans]
    store = SqliteSpanStore()
    t0 = time.perf_counter()
    for i in range(0, len(spans), 500):
        store.apply(spans[i:i + 500])
    ingest_s = time.perf_counter() - t0
    svc = sorted(store.get_all_service_names())[0]
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    q_ids = _timeit(
        lambda: store.get_trace_ids_by_name(svc, None, end_ts, 10), reps=20
    )
    q_ann = _timeit(
        lambda: store.get_trace_ids_by_annotation(svc, "custom", None,
                                                  end_ts, 10),
        reps=10, warmup=1,
    )
    agg = IncrementalAggregator()
    t0 = time.perf_counter()
    agg.offer(spans)
    dep_job_s = time.perf_counter() - t0
    store.close()
    return {
        "spans": len(spans),
        "ingest_spans_per_s": round(len(spans) / ingest_s, 1),
        "q_trace_ids_by_service": q_ids,
        "q_trace_ids_by_annotation": q_ann,
        "dep_job_spans_per_s": round(len(spans) / dep_job_s, 1),
        "dep_links": len(agg.result().links),
    }


# ---------------------------------------------------------------------------
# Configs #2-#5 — the TPU store at scale
# ---------------------------------------------------------------------------


def _tpu_config(capacity_log2: int, n_services: int, use_pallas: bool,
                rank_path: str = "auto"):
    from zipkin_tpu.store import device as dev

    # Index sizing for the benchmark's UNIFORM key space (1k services x
    # 2k span names => ~2M live (host, name) pairs; the default derived
    # geometry caps far below that):
    # - (service, span-name) family slots ~2x the annotation ring, so in
    #   steady state everything a bucket displaced is already evicted
    #   and the per-key displaced-gid gate holds (the tr_wm sizing rule,
    #   store/device.py) — by-name queries answer from the index instead
    #   of the O(ring) scan;
    # - per-key cursor table ~2x the live key count, so claims don't
    #   saturate and sparse pairs keep their records.
    # Cost at capacity 2^22: ~+330MB name family, ~+66MB key table.
    big = capacity_log2 >= 20
    return dev.StoreConfig(
        capacity=1 << capacity_log2,
        ann_capacity=1 << (capacity_log2 + 1),
        bann_capacity=1 << capacity_log2,
        max_services=n_services,
        max_span_names=2048,
        max_annotation_values=4096,
        max_binary_keys=1024,
        cms_width=1 << 16,
        hll_p=14,
        quantile_buckets=2048,
        use_pallas=use_pallas,
        rank_path=rank_path,
        idx_name_buckets=(1 << 16) if big else 0,
        idx_name_depth=256 if big else 0,
        # ~4x the live key count: the i32-fingerprint claims (probes=3)
        # fail ~load^3, so load 0.25 keeps ~98%+ of keys recorded and
        # by-name queries on the fast path. i32 fps made slots half
        # price (~34MB table + ~67MB watermarks at 2^23).
        idx_key_slots=(1 << 23) if big else 0,
        # One dependency bucket closes per half ring (~2M spans): 64
        # time-tagged banks keep ~128M spans of windowed dependency
        # resolution before older windows fold into the all-time tail
        # (the hourly-Dependencies-rows fidelity at stream scale;
        # +1.0GB at S=1024, within the 16GB budget).
        dep_buckets=64 if big else 16,
    )


def _make_template(store, n_services: int, batch_traces: int):
    """One device-resident template batch + the jitted per-step restamp."""
    import jax
    import jax.numpy as jnp

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.tracegen import ColumnarTraceGen

    from functools import partial

    gen = ColumnarTraceGen(
        store.dicts, n_services=n_services, n_span_names=2048,
        spans_per_trace=SPT, topology=True,
    )
    batch, name_lc, indexable = gen.next_batch(batch_traces)
    pad_spans = batch_traces * SPT
    db0 = dev.make_device_batch(
        batch, name_lc, indexable,
        pad_spans=pad_spans, pad_anns=2 * pad_spans, pad_banns=pad_spans,
    )
    db0 = jax.device_put(db0)

    def restamp(db, step):
        """Restamp the template ON DEVICE (salt/delta derived from a
        device-carried step counter — a host scalar per step would pay a
        tunnel round trip each). XOR keeps span_id = trace_id ^ node and
        the parent join structure intact; time advances one minute per
        batch.

        The salt is splitmix64(step): a multiplicative salt correlates
        with the golden-multiplied template trace ids and produces
        structured cross-batch id collisions (~1 in 700 rows, measured),
        which fabricate cross-trace parent joins in the benchmark data.
        """
        s = (step + 1).astype(jnp.uint64)
        s = (s ^ (s >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
        s = (s ^ (s >> 27)) * jnp.uint64(0x94D049BB133111EB)
        salt = (s ^ (s >> 31)).astype(jnp.int64)
        delta = step * jnp.int64(60_000_000)

        def shift(ts):
            return jnp.where(ts >= 0, ts + delta, ts)

        return db._replace(
            trace_id=db.trace_id ^ salt,
            span_id=db.span_id ^ salt,
            parent_id=jnp.where(db.has_parent, db.parent_id ^ salt,
                                jnp.int64(0)),
            ts_cs=shift(db.ts_cs), ts_cr=shift(db.ts_cr),
            ts_sr=shift(db.ts_sr), ts_ss=shift(db.ts_ss),
            ts_first=shift(db.ts_first), ts_last=shift(db.ts_last),
            ann_ts=shift(db.ann_ts),
        )

    @partial(jax.jit, donate_argnums=(0, 2), static_argnums=(3,))
    def fused_chain(state, db, step, k, do_close):
        """k restamp+ingest steps per LAUNCH via lax.scan: one ~100ms
        dispatch amortizes over k batches (~5-7ms per scan iteration,
        NOTES_r03 §3) instead of being paid per batch — the dispatch-
        floor attack VERDICT r3 item 3 asked for. ``do_close`` folds the
        dependency-bucket close (the archive-cadence launch) into the
        same dispatch: lax.cond executes one branch at runtime, so a
        False close is near-free and a True one saves a whole call
        floor."""
        state = jax.lax.cond(
            do_close, dev.dep_close_bucket.__wrapped__, lambda s: s,
            state,
        )

        def body(carry, _):
            st, stp = carry
            st = dev.ingest_step.__wrapped__(st, restamp(db, stp))
            return (st, stp + 1), None

        (state, step), _ = jax.lax.scan(
            body, (state, step), None, length=k
        )
        return state, step

    return db0, fused_chain, pad_spans


def _hlo_stats(jitfn, *args):
    """Instruction/fusion/sort counts of the compiled module's entry
    computation — the op-count evidence NOTES_r03 §4 tracked by hand.
    Uses the AOT lowering path, which shares the jit compile cache, so
    this costs one (cached) compile, not two."""
    try:
        txt = jitfn.lower(*args).compile().as_text()
        entry, depth, counts = False, 0, {"instr": 0, "fusion": 0,
                                          "sort": 0}
        for line in txt.splitlines():
            s = line.strip()
            if s.startswith("ENTRY "):
                entry, depth = True, 0
            if not entry:
                continue
            depth += s.count("{") - s.count("}")
            if " = " in s:
                counts["instr"] += 1
                if " fusion(" in s:
                    counts["fusion"] += 1
                if " sort(" in s:
                    counts["sort"] += 1
            if depth <= 0 and "}" in s and counts["instr"]:
                break
        return (f"{counts['instr']} entry instrs, "
                f"{counts['fusion']} fusions, {counts['sort']} sorts")
    except Exception as e:  # noqa: BLE001 — diagnostics only
        return f"hlo stats unavailable: {e!r}"


def _telemetry_block(store) -> dict:
    """Per-stage telemetry for the BENCH json: the store's device
    counter block plus every non-empty latency sketch registered in the
    process registry (stage p50/p99 summaries)."""
    from zipkin_tpu import obs

    out = {}
    cb = getattr(store, "counter_block", None)
    if callable(cb):
        try:
            out["counter_block"] = cb()
        except Exception as e:  # telemetry must never sink a bench
            out["counter_block_error"] = str(e)
    sketches = {}
    for m in obs.default_registry().collect():
        if isinstance(m, obs.LatencySketch):
            items = ([(m.name, m)] if not m.labelnames else [
                (f"{m.name}{dict(labels)}", child)
                for labels, child in m._child_items()
            ])
            for name, sk in items:
                if sk.count:
                    sketches[name] = sk.snapshot()
    if sketches:
        out["sketches"] = sketches
    return out


def bench_tpu_stream(total_spans: int, capacity_log2: int = 22,
                     n_services: int = 1024, batch_traces: int = 16384,
                     use_pallas: bool = False, rank_path: str = "auto"):
    """Stream ``total_spans`` through the fused ingest (config #2) and
    return (store-with-final-state, ingest stats)."""
    import jax
    import jax.numpy as jnp

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore

    config = _tpu_config(capacity_log2, n_services, use_pallas,
                         rank_path)
    store = TpuSpanStore(config)
    cap = config.capacity
    # One launch must never outrun the archive cadence (one dependency-
    # bucket close per half ring) nor wrap the ring within itself: the
    # whole stream loop is built on spans_per_call <= cap/2. Clamp
    # oversized --batch-traces instead of silently corrupting state.
    max_traces = max(1, (cap // 2) // SPT)
    if batch_traces > max_traces:
        _log(f"stream: --batch-traces {batch_traces} exceeds half-ring "
             f"budget; clamped to {max_traces}")
        batch_traces = max_traces
    db0, fused_chain, pad_spans = _make_template(
        store, n_services, batch_traces
    )
    # Chain length: as many batches per launch as fit HALF the ring
    # (the archive cadence closes a dependency bucket once per half
    # capacity, and a single launch must not outrun it), capped at 32.
    chain = max(1, min(32, (cap // 2) // pad_spans))
    spans_per_call = chain * pad_spans

    def sync(x):
        # A real barrier: device_get forces the D2H round trip.
        # block_until_ready on tunneled devices has been observed to
        # return before queued work executes, which would credit the
        # stream with dispatch time only.
        return float(jax.device_get(x))

    # Warm the compile caches on a throwaway state (donated away) and
    # record the compiled step's HLO shape (op-count discipline,
    # NOTES_r03 §4: per-kernel overhead prices every extra instruction).
    _log(f"stream: compiling (capacity 2^{capacity_log2}, "
         f"{n_services} services, chain {chain}, pallas={use_pallas})")
    wstate = dev.init_state(config)
    hlo = _hlo_stats(fused_chain, wstate, db0, jnp.int64(0), chain,
                     jnp.bool_(False))
    wstate, wstep = fused_chain(wstate, db0, jnp.int64(0), chain,
                                jnp.bool_(True))
    sync(wstate.counters["spans_seen"])
    _log(f"stream: ingest (+fused bucket close) compiled ({hlo})")
    del wstate, wstep

    state = store.state
    step = jnp.int64(0)
    wp = archived = 0
    n_calls = max(1, total_spans // spans_per_call)
    archive_runs = 0
    t0 = time.perf_counter()
    for i in range(n_calls):
        # Production archive policy (TpuSpanStore._maybe_archive), at
        # launch granularity: one chained launch ingests spans_per_call
        # spans (<= cap/2 by construction). The bucket close rides the
        # SAME launch via the fused do_close flag.
        do_close = wp + spans_per_call - archived > cap
        if do_close:
            archived = min(
                wp, max(wp + spans_per_call - cap, wp - cap // 2)
            )
            archive_runs += 1
        state, step = fused_chain(state, db0, step, chain,
                                  jnp.bool_(do_close))
        wp += spans_per_call
        if (i + 1) % 8 == 0:
            # True barrier every 8 launches: bounds the async queue
            # depth and keeps the measured rate honest.
            sync(state.counters["spans_seen"])
    seen = sync(state.counters["spans_seen"])
    dt = time.perf_counter() - t0
    total = n_calls * spans_per_call
    assert seen == total, (seen, total)
    _log(f"stream: {total} spans in {dt:.1f}s "
         f"({total / dt / 1e6:.1f}M spans/s, "
         f"{archive_runs} archive passes, chain {chain})")

    # Hand the streamed state to the store so the public query API
    # (device kernels + host decode) serves the read benchmarks.
    store.adopt_state(state, spans_written=wp, archived=archived)
    stats = {
        "spans": total,
        "spans_per_s": round(total / dt, 1),
        "wall_s": round(dt, 2),
        "ring_capacity": cap,
        "services": n_services,
        "batch_spans": pad_spans,
        "chain": chain,
        "archive_runs": archive_runs,
        "use_pallas": use_pallas,
        # Active kernel paths (r12): which rank / arena-scatter
        # implementations the compiled steps took — "auto"/"counting"
        # degrade statically (wm_shift == 0, scratch budget, VMEM
        # fit), so the record must say what actually ran.
        "rank_path": dev.active_paths(config).get("rank", ()),
        "scatter_path": dev.active_paths(config).get("scatter", ()),
        # Per-stage telemetry: the device counter block (one fused
        # fetch — ring occupancy/laps, poison census, ingest counters)
        # rides the BENCH json so remote runs surface the same
        # observables /metrics serves live (docs/OBSERVABILITY.md).
        "telemetry": _telemetry_block(store),
    }
    return store, stats


def bench_tpu_queries(store, reps: int = 12):
    """Configs #3-#5 + the get_trace_ids read path, through the public
    SpanStore API (wall-clock: device kernel + host materialization)."""
    _log("queries: starting")
    state = store.state
    end_ts = int(state.ts_max) + 1
    S = store.config.max_services
    rng = np.random.default_rng(7)
    svcs = [f"svc-{i:04d}" for i in rng.integers(0, S, size=reps * 2)]
    it = iter(range(10**9))

    def next_svc():
        return svcs[next(it) % len(svcs)]

    out = {}
    out["q_trace_ids_by_service"] = _timeit(
        lambda: store.get_trace_ids_by_name(next_svc(), None, end_ts, 10),
        reps=reps,
    )
    out["q_trace_ids_by_span_name"] = _timeit(
        lambda: store.get_trace_ids_by_name(
            next_svc(), f"op-{next(it) % 2048:04d}", end_ts, 10
        ),
        reps=reps,
    )
    out["q_trace_ids_by_annotation"] = _timeit(
        lambda: store.get_trace_ids_by_annotation(
            next_svc(), "some custom annotation", None, end_ts, 10
        ),
        reps=max(5, reps // 2),
    )
    out["q_trace_ids_by_binary_value"] = _timeit(
        lambda: store.get_trace_ids_by_annotation(
            next_svc(), "http.uri", b"/api/widgets", end_ts, 10
        ),
        reps=max(5, reps // 2),
    )

    # Trace materialization + durations on ids a query actually returned.
    seed_ids = []
    for _ in range(20):
        seed_ids.extend(
            i.trace_id
            for i in store.get_trace_ids_by_name(next_svc(), None, end_ts, 10)
        )
        if len(seed_ids) >= 100:
            break
    seed_ids = seed_ids[:100] or [1]
    out["q_get_trace"] = _timeit(
        lambda: store.get_spans_by_trace_ids(
            [seed_ids[next(it) % len(seed_ids)]]
        ),
        reps=reps,
    )
    out["q_durations_100"] = _timeit(
        lambda: store.get_traces_duration(seed_ids), reps=max(5, reps // 2)
    )

    # Config #3: dependency links off the streaming bank.
    deps = store.get_dependencies()
    out["dep_links"] = len(deps.links)
    out["q_dependencies"] = _timeit(
        lambda: store.get_dependencies(), reps=max(5, reps // 2)
    )
    # Config #4: per-service latency percentiles.
    out["q_quantiles"] = _timeit(
        lambda: store.service_duration_quantiles(next_svc(), [0.5, 0.95, 0.99]),
        reps=reps,
    )
    # Config #5: top-k + cardinality.
    out["q_top_annotations"] = _timeit(
        lambda: store.top_annotations(next_svc(), 10), reps=reps
    )
    out["q_hll_cardinality"] = _timeit(
        lambda: store.estimated_unique_traces(), reps=reps
    )
    out["est_unique_traces"] = round(store.estimated_unique_traces(), 1)
    out["q_service_names"] = _timeit(
        lambda: store.get_all_service_names(), reps=max(5, reps // 2)
    )
    worst = max(
        v["p99_ms"] for k, v in out.items()
        if isinstance(v, dict) and "p99_ms" in v
    )
    out["worst_query_p99_ms"] = worst
    _log(f"queries: done (worst p99 {worst:.0f}ms)")
    return out


def bench_batched_queries(store, ks=(1, 4, 16, 64), reps: int = 5):
    """The query dispatch-floor amortization (r6 read-side tentpole):
    k concurrent API queries ride ONE ``get_trace_ids_multi`` launch
    (the tier QueryService's cross-request coalescer feeds) instead of
    k ~100 ms dispatches. Per k: wall-clock of k serial singular calls
    vs one batched call, identity of the results, and the implied
    aggregate queries/s — the scaling-with-batch-size evidence the
    acceptance gate asks for (batched < 0.5 x serial at k >= 4 on
    dispatch-floor-dominated hardware)."""
    _log("batched-queries: starting")
    state = store.state
    end_ts = int(state.ts_max) + 1
    S = store.config.max_services
    rng = np.random.default_rng(23)
    out = {}
    for k in ks:
        svcs = [f"svc-{i:04d}" for i in rng.integers(0, S, size=k)]
        queries = [("name", s, None, end_ts, 10) for s in svcs]

        def serial():
            return [store.get_trace_ids_by_name(s, None, end_ts, 10)
                    for s in svcs]

        def batched():
            return store.get_trace_ids_multi(queries)

        t_serial = _timeit(serial, reps=reps, warmup=1)
        t_batched = _timeit(batched, reps=reps, warmup=1)
        identical = [
            [(i.trace_id, i.timestamp) for i in ids] for ids in serial()
        ] == [
            [(i.trace_id, i.timestamp) for i in ids] for ids in batched()
        ]
        ratio = (t_batched["p50_ms"] / t_serial["p50_ms"]
                 if t_serial["p50_ms"] else 0.0)
        out[f"k{k}"] = {
            "serial": t_serial, "batched": t_batched,
            "batched_over_serial_p50": round(ratio, 3),
            "batched_queries_per_s": round(
                k / (t_batched["p50_ms"] / 1e3), 1
            ) if t_batched["p50_ms"] else 0.0,
            "identical": identical,
        }
        _log(f"batched-queries: k={k} serial p50 "
             f"{t_serial['p50_ms']:.1f}ms batched p50 "
             f"{t_batched['p50_ms']:.1f}ms identical={identical}")
    return out


def bench_query_engine(store, reps: int = 20, concurrency: int = 8):
    """Resident query engine (r11 tentpole, query/engine.py): the
    ~105-115 ms per-request dispatch floor every query family paid at
    1B spans (BENCH_1B.json), attacked on three tiers. Measures, on
    the live streamed store:

    - sketch tier: quantiles / top-k / HLL / catalogs off the host
      mirror — target p50 < 10 ms (acceptance gate; they are numpy
      reads, so this also proves the mirror resync path after the
      bench's adopt_state);
    - index tier: trace-id reads through the standing executor under
      ``concurrency`` concurrent callers — target p99 < 50 ms (one
      launch + one D2H shared per micro-batch vs one per request);
    - cache tier: repeat-read latency + bitwise hit==cold identity;
    - zero steady-state recompiles across all of it (the resident
      programs stay resident).

    Sketch answers are cross-checked against the device read path on
    every rep (0 mismatches required, like the memory-oracle gates)."""
    import threading

    from zipkin_tpu.query.engine import QueryEngine

    _log("query-engine: starting")
    engine = QueryEngine(store, registry=_obs().Registry())
    state = store.state
    end_ts = int(state.ts_max) + 1
    S = store.config.max_services
    rng = np.random.default_rng(11)
    svcs = [f"svc-{i:04d}" for i in rng.integers(0, S, size=64)]
    it = iter(range(10**9))

    def next_svc():
        return svcs[next(it) % len(svcs)]

    engine.get_all_service_names()  # resync the mirror (one fetch)

    # Cross-check first, UNTIMED: the device read path costs the very
    # dispatch floor the sketch tier avoids, so it must never sit
    # inside the measured round (the p50 < 10ms gate would otherwise
    # be structurally unreachable on a device store).
    mismatches = 0
    for _ in range(reps):
        s = next_svc()
        if (engine.service_duration_quantiles(s, [0.5, 0.95, 0.99])
                != store.service_duration_quantiles(s, [0.5, 0.95,
                                                        0.99])):
            mismatches += 1
        if engine.top_annotations(s) != store.top_annotations(s):
            mismatches += 1
        if (engine.estimated_unique_traces()
                != store.estimated_unique_traces()):
            mismatches += 1

    def sketch_round():
        s = next_svc()
        engine.service_duration_quantiles(s, [0.5, 0.95, 0.99])
        engine.top_annotations(s)
        engine.estimated_unique_traces()
        engine.get_all_service_names()

    out = {"sketch": _timeit(sketch_round, reps=reps)}
    out["sketch"]["p50_ms"] = round(out["sketch"]["p50_ms"] / 4, 3)
    out["sketch"]["p99_ms"] = round(out["sketch"]["p99_ms"] / 4, 3)

    # Warm the multi-probe jit rows for every batch size the
    # concurrent drive can produce (1..concurrency requests per
    # micro-batch) plus the cache phase's fixed 8-query batch (its
    # pad-8 shape is otherwise unwarmed when --smoke drops
    # concurrency below 8): the p99 must measure dispatch, not
    # compiles — compiles are gated separately at zero AFTER this.
    for n in sorted(set(range(1, concurrency + 1)) | {8}):
        engine.executor.run(
            [("name", next_svc(), None, end_ts, 10)] * n)
    compiles0 = dev_compile_count()  # ingest + resident query jits

    # Index tier under concurrency: every caller's per-request latency
    # while `concurrency` threads hammer the standing executor.
    lat_ms: list = []
    lock = threading.Lock()

    def caller(n):
        mine = []
        for _ in range(reps):
            q = [("name", next_svc(), None, end_ts, 10)]
            t0 = time.perf_counter()
            engine.executor.run(q)  # cache-bypassing resident path
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            lat_ms.extend(mine)

    threads = [threading.Thread(target=caller, args=(i,))
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out["index_concurrent"] = {**_pctl(lat_ms),
                               "concurrency": concurrency}
    ex = engine.executor
    out["index_concurrent"]["launches_saved"] = ex.launches_saved
    out["index_concurrent"]["max_batch"] = ex.max_batch

    # Cache tier: cold vs hit, bitwise identity.
    queries = [("name", f"svc-{i:04d}", None, end_ts, 10)
               for i in range(8)]

    def ids(rows):
        return [[(i.trace_id, i.timestamp) for i in r] for r in rows]

    cold = ids(engine.get_trace_ids_multi(queries))
    out["cache_hit"] = _timeit(
        lambda: engine.get_trace_ids_multi(queries), reps=reps)
    hit_identical = ids(engine.get_trace_ids_multi(queries)) == cold
    out["sketch_mismatches"] = mismatches
    out["cache_hit_identical"] = bool(hit_identical)
    out["steady_recompiles"] = dev_compile_count() - compiles0
    out["meets_sketch_p50_target"] = out["sketch"]["p50_ms"] < 10.0
    out["meets_index_p99_target"] = (
        out["index_concurrent"]["p99_ms"] < 50.0)
    _log(f"query-engine: sketch p50 {out['sketch']['p50_ms']:.2f}ms "
         f"index-concurrent p99 "
         f"{out['index_concurrent']['p99_ms']:.1f}ms "
         f"cache-hit p50 {out['cache_hit']['p50_ms']:.2f}ms "
         f"recompiles {out['steady_recompiles']} "
         f"mismatches {mismatches}")
    return out


def _obs():
    from zipkin_tpu import obs

    return obs


def dev_compile_count() -> int:
    from zipkin_tpu.store import device as dev

    return dev.compile_count() + dev.query_compile_count()


def bench_exactness(store, n_queries: int = 24,
                    budget_s: float | None = None):
    """On-device index-vs-scan exactness (VERDICT r3 item 7): the same
    live store answers each sampled query through the index fast path
    AND with force_scan pinned; results must match id-for-id whenever
    the index claimed trust (when it degraded, both paths ran the same
    scan — trivially equal, still asserted).

    ``budget_s`` bounds the phase wall-clock: each force_scan replay is
    O(ring) (~15 s/check at the 100M config), and round 4 spent 771 s
    here — 13 minutes of the driver window re-proving what the suite
    proves structurally. Checks are interleaved across the query types
    (the durations/get_spans trace-membership pair runs FIRST — it is
    the only coverage those paths get), so an exhausted budget still
    leaves every path checked."""
    t_start = time.perf_counter()
    state = store.state
    end_ts = int(state.ts_max) + 1
    S = store.config.max_services
    rng = np.random.default_rng(11)
    svcs = [f"svc-{i:04d}" for i in rng.integers(0, S, size=n_queries)]
    checked = mismatches = 0
    detail = []
    budget_hit = False

    def over_budget():
        nonlocal budget_hit
        if budget_s is not None and (
                time.perf_counter() - t_start > budget_s):
            budget_hit = True
        return budget_hit

    def cmp(tag, fast, slow):
        nonlocal checked, mismatches
        checked += 1
        f = [(i.trace_id, i.timestamp) for i in fast]
        s = [(i.trace_id, i.timestamp) for i in slow]
        if f != s:
            mismatches += 1
            detail.append({"query": tag, "index": f[:5], "scan": s[:5]})

    # Trace membership first: durations through gid buckets vs full
    # scan — these two checks are the only exactness coverage the
    # trace-family paths get, so they must land inside any budget.
    ids = store.get_trace_ids_by_name(svcs[0], None, end_ts, 10)
    tids = [i.trace_id for i in ids][:10]
    if tids:
        checked += 1
        if (store.get_traces_duration(tids)
                != store.get_traces_duration(tids, force_scan=True)):
            mismatches += 1
            detail.append({"query": "durations"})
        checked += 1
        f = store.get_spans_by_trace_ids(tids)
        s = store.get_spans_by_trace_ids(tids, force_scan=True)
        if f != s:
            mismatches += 1
            detail.append({"query": "get_spans"})
    for i, svc in enumerate(svcs):
        if over_budget():
            break
        cmp(f"service:{svc}",
            store.get_trace_ids_by_name(svc, None, end_ts, 10),
            store.get_trace_ids_by_name(svc, None, end_ts, 10,
                                        force_scan=True))
        if over_budget():
            break
        if i % 3 == 0:
            name = f"op-{i % 2048:04d}"
            cmp(f"name:{svc}/{name}",
                store.get_trace_ids_by_name(svc, name, end_ts, 10),
                store.get_trace_ids_by_name(svc, name, end_ts, 10,
                                            force_scan=True))
        if i % 3 == 1:
            cmp(f"ann:{svc}",
                store.get_trace_ids_by_annotation(
                    svc, "some custom annotation", None, end_ts, 10),
                store.get_trace_ids_by_annotation(
                    svc, "some custom annotation", None, end_ts, 10,
                    force_scan=True))
        if i % 3 == 2:
            cmp(f"bann:{svc}",
                store.get_trace_ids_by_annotation(
                    svc, "http.uri", b"/api/widgets", end_ts, 10),
                store.get_trace_ids_by_annotation(
                    svc, "http.uri", b"/api/widgets", end_ts, 10,
                    force_scan=True))
    out = {"checked": checked, "mismatches": mismatches,
           "index_hits": store.index_hits,
           "scan_fallbacks": store.index_fallbacks,
           "wall_s": round(time.perf_counter() - t_start, 1)}
    if budget_hit:
        out["budget_exhausted_s"] = budget_s
    if detail:
        out["mismatch_detail"] = detail[:4]
    _log(f"exactness: {checked} checks, {mismatches} mismatches, "
         f"{store.index_hits} index hits / "
         f"{store.index_fallbacks} fallbacks"
         + (f" (budget {budget_s:.0f}s exhausted)" if budget_hit else ""))
    return out


def _bounded(fn, timeout_s: float, label: str):
    """Run ``fn`` on a daemon thread with a deadline. On timeout the
    thread is abandoned (a wedged tunnel transfer is uninterruptible
    from Python) and a timeout record returned; callers must schedule
    bounded work LAST so an abandoned device operation can't block
    later device work."""
    import threading

    result = {}

    def run():
        try:
            result["value"] = fn()
        except Exception as e:  # noqa: BLE001
            result["error"] = repr(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        _log(f"{label}: still running after {timeout_s:.0f}s — "
             "abandoned (wedged transfer?)")
        return {"timed_out_s": timeout_s}
    if "error" in result:
        return {"error": result["error"]}
    return result.get("value")


def bench_archive(total_spans: int = 100_000):
    """Cold-tier phase: stream ~4 ring turns through a TieredSpanStore
    (store/archive) and measure what the paging layer costs and buys —
    capture overhead vs an identical sink-less store (same spans, warm
    jit cache), cold trace-fetch latency over EVICTED traces, segment
    compression ratio, and identity vs the memory oracle on a sample.
    The ring is sized to total_spans/4 so the stream laps it ~4x."""
    import numpy as np  # noqa: F401

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore
    from zipkin_tpu.store.memory import InMemorySpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    cap = 1 << max(9, (total_spans // 4).bit_length() - 1)
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
    )
    _log(f"archive phase: ring 2^{cap.bit_length() - 1}, "
         f"{total_spans} spans (~4 laps)")
    spans = []
    while len(spans) < total_spans:
        spans.extend(
            s for t in generate_traces(
                n_traces=max(total_spans // 5, 64), max_depth=3,
                n_services=32,
            ) for s in t
        )
    spans = spans[:total_spans]
    chunk = 1024

    def stream(store):
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        return time.perf_counter() - t0

    stream(TpuSpanStore(config))  # jit warm-up (uncounted)
    plain_s = stream(TpuSpanStore(config))
    hot = TpuSpanStore(config)
    tiered = TieredSpanStore(
        hot, params=ArchiveParams.for_config(config))
    tiered_s = stream(tiered)

    oracle = InMemorySpanStore()
    oracle.apply(spans)
    tids = sorted({s.trace_id for s in spans})
    sample = tids[:3] + tids[len(tids) // 2:len(tids) // 2 + 3] \
        + tids[-3:]
    t0 = time.perf_counter()
    identical = all(
        tiered.get_spans_by_trace_ids([t])
        == oracle.get_spans_by_trace_ids([t]) for t in sample
    )
    cold_fetch_s = time.perf_counter() - t0
    c = tiered.counters()
    return {
        "spans": len(spans),
        "ring_capacity": cap,
        "ingest_plain_s": round(plain_s, 2),
        "ingest_tiered_s": round(tiered_s, 2),
        "capture_overhead_pct": round(
            100.0 * (tiered_s - plain_s) / plain_s, 1),
        "cold_fetch_ms_per_trace": round(
            cold_fetch_s / len(sample) * 1e3, 2),
        "segments_written": int(c["archive_segments_written"]),
        "compactions": int(c["archive_compactions"]),
        "segments_live": int(c["archive_segments_live"]),
        "cold_spans": int(c["archive_cold_spans"]),
        "cold_mb": round(c["archive_cold_bytes"] / 1e6, 2),
        "cold_compression_ratio": round(
            c["archive_cold_raw_bytes"]
            / max(c["archive_cold_bytes"], 1.0), 2),
        "capture_latency": tiered.archive.h_capture.snapshot(),
        "cold_query_latency": tiered.archive.h_cold_query.snapshot(),
        "identical_vs_oracle": bool(identical),
    }


def bench_pipeline(total_spans: int = 100_000, depth: int = 8,
                   capture_backlog: int = 64):
    """Pipelined-ingest phase (r9 tentpole): the same span stream
    driven through the serial write path (inline capture sealing) and
    through the three-stage pipeline (encode ∥ H2D staging ∥ device
    compute, async eviction sealer). On real hardware the interesting
    numbers are the spans/s delta (how much host encode + staging +
    capture sealing the pipeline hides behind device compute) and the
    overlap efficiency (stage-busy seconds / wall, > 1 means true
    overlap); equality of the device counter blocks plus a sample
    query double-checks identity cheaply (the bitwise-leaf proof runs
    on the CPU mesh every CI run — tests/test_pipeline.py)."""
    import numpy as np  # noqa: F401

    import jax

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    cap = 1 << max(9, (total_spans // 4).bit_length() - 1)
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
    )
    _log(f"pipeline phase: ring 2^{cap.bit_length() - 1}, "
         f"{total_spans} spans, depth {depth}")
    spans = []
    while len(spans) < total_spans:
        spans.extend(
            s for t in generate_traces(
                n_traces=max(total_spans // 5, 64), max_depth=3,
                n_services=32,
            ) for s in t
        )
    spans = spans[:total_spans]
    chunk = 1024

    def build(backlog):
        hot = TpuSpanStore(config)
        hot.capture_backlog = backlog
        return hot, TieredSpanStore(
            hot, params=ArchiveParams.for_config(config))

    def stream(store):
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        drain = getattr(store, "drain_pipeline", None)
        if drain is not None:
            drain()
            store.seal_barrier()
        return time.perf_counter() - t0

    # Warm BOTH modes' jit cache rows (staged device args key their
    # own entries — dev.stage_batch).
    _, warm_t = build(0)
    stream(warm_t)
    wh, wt = build(capture_backlog)
    wh.start_pipeline(depth)
    stream(wt)
    wt.close()

    sh, st = build(0)
    serial_s = stream(st)
    ph, pt = build(capture_backlog)
    compiles0 = dev.compile_count()
    pipe = ph.start_pipeline(depth)
    pipelined_s = stream(pt)
    recompiles = dev.compile_count() - compiles0
    encode_s, stage_s, commit_s = (
        pipe.h_encode.sum, pipe.h_stage.sum, pipe.h_commit.sum)
    stall_s = float(pipe.c_stall.value)
    ph.stop_pipeline()
    sealer = ph._sealer
    cb_serial = {k: v for k, v in sh.counter_block().items()}
    cb_piped = {k: v for k, v in ph.counter_block().items()}
    svc = sorted(pt.get_all_service_names())[0]
    end_ts = int(jax.device_get(ph.state.ts_max)) + 1
    same_query = (
        pt.get_trace_ids_by_name(svc, None, end_ts, 50)
        == st.get_trace_ids_by_name(svc, None, end_ts, 50)
    )
    out = {
        "spans": len(spans),
        "depth": depth,
        "capture_backlog": capture_backlog,
        "serial_spans_per_s": round(len(spans) / serial_s, 1),
        "pipelined_spans_per_s": round(len(spans) / pipelined_s, 1),
        "speedup": round(serial_s / pipelined_s, 3),
        "overlap_efficiency": round(
            (encode_s + stage_s + commit_s) / pipelined_s, 2),
        "encode_s": round(encode_s, 3),
        "stage_s": round(stage_s, 3),
        "commit_s": round(commit_s, 3),
        "prefetch_stall_s": round(stall_s, 3),
        "capture_stall_s": round(
            float(sealer.c_stall.value) if sealer else 0.0, 3),
        "windows_sealed": int(sealer.c_sealed.value) if sealer else 0,
        "recompiles_after_warmup": int(recompiles),
        "counter_blocks_identical": cb_serial == cb_piped,
        "sample_query_identical": bool(same_query),
        "ingest_dispatch_ms": _sketch_ms(ph._h_dispatch),
        "ingest_true_step_ms": _sketch_ms(ph._h_ingest),
    }
    st.close()
    pt.close()
    return out


def bench_durability(total_spans: int = 100_000):
    """Durability phase (r10 tentpole, zipkin_tpu.wal): what the
    write-ahead log costs on the ingest path and buys at recovery.
    Measures the same span stream through a plain store (baseline +
    oracle) and through WAL-attached stores at each fsync policy
    (group-commit interval = the daemon default, off, and per-batch at
    a quarter of the stream — per-append fsync is the worst case and
    needs no full-length drive to characterize), then closes the log,
    reopens it cold, and times a full-log recovery into a fresh store,
    gating bitwise identity against the uncrashed oracle. Process-
    death coverage is tests/test_crash.py; this phase puts NUMBERS on
    the contract: append overhead per policy, WAL bytes/span on disk,
    recovery spans/s."""
    import shutil
    import tempfile

    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.testing.crash import states_bitwise_equal
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.wal import WriteAheadLog, recover

    cap = 1 << max(12, total_spans.bit_length() - 1)
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
    )
    _log(f"durability phase: {total_spans} spans, ring 2^"
         f"{cap.bit_length() - 1}")
    spans = []
    while len(spans) < total_spans:
        spans.extend(
            s for t in generate_traces(
                n_traces=max(total_spans // 5, 64), max_depth=3,
                n_services=32,
            ) for s in t
        )
    spans = spans[:total_spans]
    chunk = 1024

    def stream(store, n=None):
        sub = spans if n is None else spans[:n]
        t0 = time.perf_counter()
        for i in range(0, len(sub), chunk):
            store.apply(sub[i:i + chunk])
        return time.perf_counter() - t0

    root = tempfile.mkdtemp(prefix="wal-bench-")
    try:
        stream(TpuSpanStore(config))  # jit warm-up (uncounted)
        oracle = TpuSpanStore(config)
        base_s = stream(oracle)

        def wal_drive(fsync, n=None, tag=""):
            store = TpuSpanStore(config)
            wal = WriteAheadLog(
                os.path.join(root, f"wal-{fsync}{tag}"), fsync=fsync)
            store.attach_wal(wal)
            dt = stream(store, n)
            wal.sync()
            return store, wal, dt

        s_int, wal_int, interval_s = wal_drive("interval")
        _, wal_off, off_s = wal_drive("off")
        n_batch = max(chunk, total_spans // 4)
        _, wal_b, batch_s = wal_drive("batch", n=n_batch)
        base_batch_s = base_s * n_batch / total_spans

        wal_stats = wal_int.stats()
        wal_dir = wal_int.directory
        for w in (wal_int, wal_off, wal_b):
            w.close()

        # Cold recovery: reopen the log (open-time torn-tail scan
        # included) and replay everything into a fresh store.
        t0 = time.perf_counter()
        wal2 = WriteAheadLog(wal_dir, fsync="off")
        rec, rstats = recover(
            None, wal2, fresh_store=lambda: TpuSpanStore(config))
        recovery_s = time.perf_counter() - t0
        identical = states_bitwise_equal(oracle.state, rec.state)
        wal2.close()
        append_ms = _sketch_ms(wal_int.h_append)
        return {
            "spans": total_spans,
            "baseline_ingest_s": round(base_s, 2),
            "wal_interval_ingest_s": round(interval_s, 2),
            "wal_off_ingest_s": round(off_s, 2),
            "wal_batch_ingest_s": round(batch_s, 2),
            "wal_batch_spans": n_batch,
            "append_overhead_interval_pct": round(
                100.0 * (interval_s - base_s) / base_s, 1),
            "append_overhead_off_pct": round(
                100.0 * (off_s - base_s) / base_s, 1),
            "append_overhead_batch_pct": round(
                100.0 * (batch_s - base_batch_s) / base_batch_s, 1),
            "wal_mb": round(wal_stats["wal_bytes"] / 1e6, 2),
            "wal_bytes_per_span": round(
                wal_stats["wal_bytes"] / total_spans, 1),
            "wal_segments": wal_stats["wal_segments"],
            "recovery_s": round(recovery_s, 2),
            "recovery_spans_per_s": round(
                rstats["replayed_spans"] / max(rstats["replay_s"],
                                               1e-9), 1),
            "replayed_records": rstats["replayed_records"],
            "recovered_identical": bool(identical),
            "wal_append_ms": append_ms,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _sketch_ms(sketch) -> dict:
    """Latency sketch snapshot with the time keys scaled to ms."""
    return {
        k: (round(v * 1e3, 3)
            if k in ("sum", "mean", "stddev", "p50", "p99") and v == v
            else v)
        for k, v in sketch.snapshot().items()
    }


def bench_windows(total_spans: int = 200_000):
    """Windowed-analytics phase (r13 tentpole, aggregate/windows.py):
    what the (service × time-bucket) Moments-sketch arena costs on the
    fused ingest step and what it buys at read time. Measures (a) the
    window-on vs window-off spans/s delta — the arena's 5 extra
    scatters riding the step (store/census.py r13 bump); (b) serve
    p50/p99 for windowed_quantiles / slo_burn / latency_heatmap, all
    answered from the host mirror cells with ZERO device dispatches;
    (c) mirror-vs-device bitwise identity of the four window arrays;
    (d) exactness — windowed error/total counts equal an exact span
    scan (cell sums are exact) and the quantile estimate's rank error
    vs the true duration distribution stays inside SOLVER_RANK_TOL."""
    import numpy as np

    import jax

    from zipkin_tpu.aggregate import windows as win
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore

    cap = 1 << max(10, total_spans.bit_length() - 1)
    n_services = 16
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
        window_seconds=60, window_buckets=64,
    )
    _log(f"windows phase: ring 2^{cap.bit_length() - 1}, "
         f"{total_spans} spans, arena {config.max_services}x"
         f"{config.window_buckets}")
    rng = np.random.default_rng(13)
    eps = [Endpoint(1 + i, 80, f"wsvc{i:02d}") for i in range(n_services)]
    base = 1_700_000_000_000_000
    # Spread first-timestamps over half the ring's retention so dozens
    # of time buckets are live; ~8% of spans carry the "error"
    # annotation convention.
    span_us = config.window_us * (config.window_buckets // 2)
    offs = rng.integers(0, span_us, total_spans)
    durs = (np.exp(rng.normal(7.0, 1.3, total_spans)).astype(np.int64)
            + 1)
    spans = []
    for i in range(total_spans):
        ep = eps[i % n_services]
        t0 = base + int(offs[i])
        anns = [Annotation(t0, "sr", ep),
                Annotation(t0 + int(durs[i]), "ss", ep)]
        if i % 12 == 0:
            anns.append(Annotation(t0 + 1, "error", ep))
        spans.append(Span(i // 4 + 1, f"op{i % 8}", i + 1, None,
                          tuple(anns), ()))
    chunk = 1024

    def stream(store):
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        return time.perf_counter() - t0

    # (a) fused-step cost: warm both lowerings, then time each.
    cfg_off = config._replace(window_seconds=0)
    stream(TpuSpanStore(cfg_off))
    warm_on = TpuSpanStore(config)
    stream(warm_on)
    off_s = stream(TpuSpanStore(cfg_off))
    store = TpuSpanStore(config)
    on_s = stream(store)

    # (c) bitwise identity of the arena vs its mirror twins.
    st = store.state
    dev_arrays = jax.device_get(
        (st.win_epoch, st.win_counts, st.win_sums, st.win_mm))
    mir = store.sketch_mirror
    bitwise = all(np.array_equal(a, b) for a, b in zip(
        dev_arrays,
        (mir.win_epoch, mir.win_counts, mir.win_sums, mir.win_mm)))

    # (b) serve latency: all three endpoints off the mirror cells.
    svc = "wsvc01"
    qs = [0.5, 0.95, 0.99]
    lat = {"windowed_quantiles": [], "slo_burn": [], "latency_heatmap": []}
    store.windowed_quantiles(svc, qs)  # one-time numpy/solver warmup
    for _ in range(40):
        t0 = time.perf_counter()
        est = store.windowed_quantiles(svc, qs)
        lat["windowed_quantiles"].append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        burn = store.slo_burn(svc, objective=0.99)
        lat["slo_burn"].append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        heat = store.latency_heatmap(svc, bands=12)
        lat["latency_heatmap"].append(time.perf_counter() - t0)

    def pctls(samples):
        a = np.sort(samples)
        return {"p50_ms": round(float(a[len(a) // 2]) * 1e3, 3),
                "p99_ms": round(float(a[int(len(a) * 0.99)]) * 1e3, 3)}

    # (d) exactness vs the raw span stream.
    mine = [s for s in spans if (s.service_name or "") == svc]
    exact_durs = np.sort([s.duration for s in mine
                          if s.duration is not None])
    rank_err = max(
        abs(np.searchsorted(exact_durs, e) / max(len(exact_durs) - 1, 1)
            - q)
        for q, e in zip(qs, est))
    exact_errors = sum(
        1 for s in mine
        if any(a.value == "error" for a in s.annotations))
    widest = max(burn["windows"], key=lambda w: w["windowSeconds"])
    counts_exact = (widest["total"] == len(mine)
                    and widest["errors"] == exact_errors)
    out = {
        "spans": len(spans),
        "window_seconds": config.window_seconds,
        "window_buckets": config.window_buckets,
        "window_off_spans_per_s": round(len(spans) / off_s, 1),
        "window_on_spans_per_s": round(len(spans) / on_s, 1),
        "arena_overhead_pct": round((on_s / off_s - 1.0) * 100.0, 2),
        "mirror_bitwise_identical": bool(bitwise),
        "live_cells": int(mir.window_live_cells()),
        "heatmap_columns": len(heat["bucketStartsTs"]),
        "burn_error_counts_exact": bool(counts_exact),
        "quantile_rank_err": round(float(rank_err), 4),
        "solver_rank_tol": win.SOLVER_RANK_TOL,
        **{k: pctls(v) for k, v in lat.items()},
    }
    warm_on.close()
    store.close()
    return out


def bench_paged(total_spans: int = 100_000):
    """Paged-layout phase (r19 tentpole, store/paged): the end of the
    skew tax. Trace sizes in production are zipf — 1-span polls next
    to 10k-span batch jobs — and a FIFO ring must over-provision for
    the p99 trace because a long-running trace's early spans get
    overwritten by unrelated churn, leaving partial traces that
    occupy rows yet answer no complete-trace query. The paged layout
    reclaims at page granularity with trace-granular LRW (a writing
    trace keeps its whole chain fresh), so active traces stay WHOLE.

    Arms:
    (a) skewed retention — a zipf session mix (concurrent long-lived
        traces, sizes 1..10k clipped to the pool) streamed to several
        ring laps through BOTH layouts at EQUAL device memory; the
        metric is complete-trace spans retained per device byte
        (spans of traces the store still answers IN FULL), paged/ring
        ratio — the acceptance gate is >= 2x;
    (b) uniform ingest — contiguous fixed-size traces, serial and
        pipelined spans/s for both layouts; the planner must cost
        < 10% vs ring;
    (c) skewed ingest rate through the paged planner, plus the
        page-pool counters at end of stream."""
    import numpy as np

    import jax

    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore

    cap = 1 << max(12, total_spans.bit_length() - 3)
    page_rows = 64
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
        rank_path="counting",
    )
    cfg_paged = config._replace(layout="paged", page_rows=page_rows)
    _log(f"paged phase: pool 2^{cap.bit_length() - 1} x2, "
         f"{total_spans} spans, page_rows={page_rows}")
    rng = np.random.default_rng(19)
    eps = [Endpoint(1 + i, 80, f"psvc{i:02d}") for i in range(8)]
    base = 1_700_000_000_000_000

    # (a) the skewed session stream: SESSIONS concurrent long-lived
    # traces (batch jobs dribbling spans), zipf-tailed sizes floored
    # so every session's span SPREAD exceeds the ring window (~cap
    # rows) while the total active footprint fits the page pool —
    # plus a 5% stream of 1-span polls (the other end of the zipf).
    # The FIFO ring holds a window full of session partials it can
    # never answer whole; the paged store's trace-granular LRW keeps
    # the active sessions complete at the same device memory.
    SESSIONS = 16
    lo, hi = cap // 10, min(10_000, cap // 5)
    sizes = np.clip(rng.zipf(1.2, total_spans), lo, hi)
    emitted: dict = {}
    spans = []
    next_tid = 1
    next_size = iter(sizes.tolist())
    active = []
    for _ in range(SESSIONS):
        active.append([next_tid, int(next(next_size)), 0])
        next_tid += 1
    churn = rng.random(total_spans) < 0.05
    picks = rng.integers(0, SESSIONS, total_spans)
    poll_tid = 1_000_000_000
    for i in range(total_spans):
        t0 = base + i * 10
        if churn[i]:
            ep = eps[poll_tid % 8]
            spans.append(Span(poll_tid, "poll", poll_tid * 8 + 1, None,
                              (Annotation(t0, "sr", ep),
                               Annotation(t0 + 3, "ss", ep)), ()))
            emitted[poll_tid] = 1
            poll_tid += 1
            continue
        sess = active[int(picks[i])]
        tid, size, done = sess
        ep = eps[tid % 8]
        spans.append(Span(tid, f"op{done % 8}", tid * 100_000 + done + 1,
                          None, (Annotation(t0, "sr", ep),
                                 Annotation(t0 + 7, "ss", ep)), ()))
        emitted[tid] = done + 1
        sess[2] = done + 1
        if sess[2] >= size:
            active[int(picks[i])] = [next_tid, int(next(next_size)), 0]
            next_tid += 1
    chunk = 512

    def stream(store, pipelined=False):
        if pipelined:
            store.start_pipeline(8)
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        if pipelined:
            store.drain_pipeline()
            store.stop_pipeline()
        return time.perf_counter() - t0

    def complete_spans(store) -> int:
        """Spans belonging to traces the store still answers IN FULL
        (count == every span emitted for that tid). Partial traces
        credit zero — they are the skew tax."""
        total = 0
        tids = sorted(emitted)
        for i in range(0, len(tids), 128):
            batch = tids[i:i + 128]
            for trace in store.get_spans_by_trace_ids(batch):
                if not trace:
                    continue
                tid = trace[0].trace_id
                if len(trace) == emitted[tid]:
                    total += len(trace)
        return total

    ring = TpuSpanStore(config)
    stream(ring)
    paged = TpuSpanStore(cfg_paged)
    skew_first_s = stream(paged)
    state_bytes = int(sum(
        a.size * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(ring.state)))
    ring_complete = complete_spans(ring)
    paged_complete = complete_spans(paged)
    pstats = paged.counters()
    ring.close()

    # (c) skewed ingest rate at warmed shapes.
    steady = TpuSpanStore(cfg_paged)
    skew_s = stream(steady)
    steady.close()

    # (b) uniform arm: contiguous 8-span traces, both layouts, serial
    # and pipelined (the planner rides stage 1, overlapped with the
    # device step exactly like encode).
    uni = []
    for t in range(total_spans // 8):
        ep = eps[t % 8]
        for j in range(8):
            t0 = base + t * 100 + j
            uni.append(Span(t + 1, f"uop{j}", t * 10 + j + 1, None,
                            (Annotation(t0, "sr", ep),
                             Annotation(t0 + 5, "ss", ep)), ()))

    def udrive(cfg, pipelined):
        store = TpuSpanStore(cfg)
        if pipelined:
            store.start_pipeline(8)
        t0 = time.perf_counter()
        for i in range(0, len(uni), chunk):
            store.apply(uni[i:i + chunk])
        if pipelined:
            store.drain_pipeline()
            store.stop_pipeline()
        dt = time.perf_counter() - t0
        store.close()
        return len(uni) / dt

    udrive(config, False)       # warm both lowerings
    udrive(cfg_paged, False)
    ring_uni = udrive(config, False)
    paged_uni = udrive(cfg_paged, False)
    udrive(config, True)
    udrive(cfg_paged, True)
    ring_uni_pipe = udrive(config, True)
    paged_uni_pipe = udrive(cfg_paged, True)

    out = {
        "spans": len(spans),
        "capacity": cap,
        "page_rows": page_rows,
        "sessions": SESSIONS,
        "session_spans_min_max": [int(lo), int(hi)],
        "ring_laps": round(len(spans) / cap, 1),
        "state_bytes": state_bytes,
        "ring_complete_spans": int(ring_complete),
        "paged_complete_spans": int(paged_complete),
        "ring_spans_per_mb": round(ring_complete * (1 << 20)
                                   / state_bytes, 1),
        "paged_spans_per_mb": round(paged_complete * (1 << 20)
                                    / state_bytes, 1),
        "retention_ratio": round(paged_complete
                                 / max(1, ring_complete), 2),
        "skewed_spans_per_s": round(len(spans) / skew_s, 1),
        "skewed_first_drive_spans_per_s": round(
            len(spans) / skew_first_s, 1),
        "uniform_ring_spans_per_s": round(ring_uni, 1),
        "uniform_paged_spans_per_s": round(paged_uni, 1),
        "uniform_overhead_pct": round(
            (ring_uni / paged_uni - 1.0) * 100.0, 2),
        "uniform_pipelined_ring_spans_per_s": round(ring_uni_pipe, 1),
        "uniform_pipelined_paged_spans_per_s": round(paged_uni_pipe, 1),
        "pages_active": int(pstats["pages_active"]),
        "pages_free": int(pstats["pages_free"]),
        "page_reclaims_total": int(pstats["page_reclaims_total"]),
    }
    paged.close()
    return out


def bench_replication(total_spans: int = 100_000, n_replicas: int = 3):
    """Replication phase (r15 tentpole, zipkin_tpu.replicate): what
    WAL shipping buys and costs. One WAL-attached tiered primary
    streams while (a) N device-free replicas and (b) one warm standby
    follow over the real framed-TCP ship path. Measures: replica
    staleness lag under full ingest load (records and seconds),
    failover RTO (standby drains the durable tail + promotes, bitwise
    vs the primary), aggregate sketch-tier queries/s across the
    replica fleet (the horizontal read-scaling claim), and per-replica
    apply rate (the ceiling on how fast a CPU can follow one chip)."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from zipkin_tpu.replicate import (
        Follower,
        ReplicaTarget,
        ShipClient,
        ShipServer,
        StandbyTarget,
        WalShipper,
    )
    from zipkin_tpu.replicate.protocol import config_from_dict
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.archive import TieredSpanStore
    from zipkin_tpu.store.replica import ReplicaSpanStore
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.testing.crash import states_bitwise_equal
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.wal import WriteAheadLog

    cap = 1 << max(12, total_spans.bit_length() - 2)
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
    )
    _log(f"replication phase: {total_spans} spans, {n_replicas} "
         f"device-free replicas + 1 warm standby")
    spans = []
    while len(spans) < total_spans:
        spans.extend(
            s for t in generate_traces(
                n_traces=max(total_spans // 5, 64), max_depth=3,
                n_services=32,
            ) for s in t
        )
    spans = spans[:total_spans]
    chunk = 1024
    root = tempfile.mkdtemp(prefix="replication-bench-")
    followers = []
    replicas = []
    server = None
    try:
        primary = TieredSpanStore(TpuSpanStore(config))
        wal = WriteAheadLog(os.path.join(root, "wal"), fsync="off")
        primary.attach_wal(wal)
        shipper = WalShipper(primary)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        port = server.server_address[1]
        server.serve_in_thread()

        for r in range(n_replicas):
            c = ShipClient("127.0.0.1", port, f"bench-replica-{r}",
                           mode="replica")
            replica = ReplicaSpanStore(config_from_dict(
                c.connect()["config"]))
            replicas.append(replica)
            followers.append(Follower(
                ReplicaTarget(replica), c,
                poll_interval_s=0.002).start())
        sc = ShipClient("127.0.0.1", port, "bench-standby",
                        mode="standby")
        sc.connect()
        standby = TpuSpanStore(config)
        f_sby = Follower(StandbyTarget(standby), sc,
                         poll_interval_s=0.002)
        followers.append(f_sby.start())

        # Full-load stream with lag sampling per batch.
        lags = []
        t0 = time.perf_counter()
        for i in range(0, len(spans), chunk):
            primary.apply(spans[i:i + chunk])
            lags.append(max(f.lag_records() for f in followers))
        ingest_s = time.perf_counter() - t0
        wal.sync()
        records_total = wal.last_seq
        s_per_record = ingest_s / max(records_total, 1)

        # Failover RTO: standby applies the durable tail + promotes.
        t0 = time.perf_counter()
        sby_ok = f_sby.drain(300.0)
        promoted = f_sby.promote()
        rto_s = time.perf_counter() - t0
        standby_bitwise = states_bitwise_equal(
            primary.hot.state, promoted.state)

        t0 = time.perf_counter()
        reps_ok = all(f.drain(300.0) for f in followers[:-1])
        replica_catch_up_s = time.perf_counter() - t0

        # Bitwise agreement at the drained frontier (replica 0 stands
        # for the fleet: all applied the identical record stream).
        a_p = primary.hot.ensure_sketch_mirror().arrays()
        mirror_bitwise = all(
            all(np.array_equal(x, y)
                for x, y in zip(a_p, rep.sketch_mirror.arrays()))
            for rep in replicas
        )
        svcs = sorted(primary.get_all_service_names())
        agree = all(
            rep.service_duration_quantiles(svc, [0.5, 0.99])
            == primary.service_duration_quantiles(svc, [0.5, 0.99])
            for rep in replicas for svc in svcs[:3]
        )

        # Aggregate replica read throughput: one thread per replica
        # hammers the sketch tier (the dashboard-fanout shape).
        reads_per_thread = 400
        counts = [0] * len(replicas)

        def read_loop(idx):
            rep = replicas[idx]
            for i in range(reads_per_thread):
                svc = svcs[i % len(svcs)]
                rep.service_duration_quantiles(svc, [0.5, 0.99])
                rep.top_annotations(svc)
                rep.estimated_unique_traces()
                counts[idx] += 3

        threads = [threading.Thread(target=read_loop, args=(i,))
                   for i in range(len(replicas))]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fleet_s = time.perf_counter() - t0
        fleet_qps = sum(counts) / fleet_s

        lag_arr = np.asarray(lags[1:] or [0], np.int64)
        rep0 = replicas[0]
        return {
            "spans": total_spans,
            "replicas": n_replicas,
            "records_shipped": int(records_total),
            "primary_ingest_spans_per_s": round(
                total_spans / ingest_s, 1),
            "lag_records_max": int(lag_arr.max()),
            "lag_records_p50": int(np.median(lag_arr)),
            "lag_seconds_max": round(
                float(lag_arr.max()) * s_per_record, 3),
            "replica_catch_up_s": round(replica_catch_up_s, 3),
            "replica_apply_spans_per_s": round(
                rep0.spans_applied
                / max(ingest_s + replica_catch_up_s, 1e-9), 1),
            "failover_rto_s": round(max(rto_s, 1e-4), 4),
            "standby_bitwise": bool(standby_bitwise),
            "standby_caught_up": bool(sby_ok),
            "replicas_caught_up": bool(reps_ok),
            "mirror_bitwise_all_replicas": bool(mirror_bitwise),
            "sketch_answers_identical": bool(agree),
            "fleet_sketch_queries_per_s": round(fleet_qps, 1),
            "fleet_read_threads": len(replicas),
            "shipped_mb_per_follower": round(
                shipper.status()["followers"]
                ["bench-replica-0"]["shippedBytes"] / 1e6, 2),
        }
    finally:
        for f in followers:
            try:
                f.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for rep in replicas:
            rep.close()
        if server is not None:
            server.shutdown()
        shutil.rmtree(root, ignore_errors=True)


def bench_multichip(total_spans: int = 200_000,
                    n_shards: Optional[int] = None):
    """Multi-chip sharded serving phase (r16 tentpole,
    zipkin_tpu.parallel.shard): what the fleet buys over one chip.
    One span stream is driven through (a) a single-device store and
    (b) an N-shard ``ShardedSpanStore`` over the same per-shard
    geometry — spans/s per chip and scaling efficiency come straight
    from the pair. The read side measures aggregate queries/s under
    concurrent API load twice: serialized (one reader, one collective
    launch per query — the pre-dispatcher deployment) vs batched
    (eight readers through the cross-shard dispatcher, one launch per
    micro-window), with bitwise-identical answers required, plus the
    launch count the dispatcher saved. On the CPU harness the
    absolute rates are trend numbers; the scaling ratio, the launch
    arithmetic, and the identity bits are the portable evidence."""
    import threading

    import jax

    from zipkin_tpu.parallel.shard import ShardedSpanStore
    from zipkin_tpu.store import device as dev
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu.tracegen import generate_traces

    devs = jax.devices()
    if len(devs) < 2:
        return {"skipped": f"needs >=2 devices, have {len(devs)}"}
    from jax.sharding import Mesh

    n = n_shards or min(len(devs), 8)
    cap = 1 << max(12, total_spans.bit_length() - 2)
    config = dev.StoreConfig(
        capacity=cap, ann_capacity=4 * cap, bann_capacity=2 * cap,
        max_services=64, max_span_names=256,
        max_annotation_values=512, max_binary_keys=64,
        cms_width=1 << 12, hll_p=10, quantile_buckets=512,
    )
    _log(f"multichip phase: {total_spans} spans, {n} shards")
    spans = []
    while len(spans) < total_spans:
        spans.extend(
            s for t in generate_traces(
                n_traces=max(total_spans // 10, 64), max_depth=3,
                n_services=32,
            ) for s in t
        )
    spans = spans[:total_spans]
    chunk = 2048

    def stream(store):
        # First chunk warms the compile; timed from the second on.
        store.apply(spans[:chunk])
        t0 = time.perf_counter()
        for i in range(chunk, len(spans), chunk):
            store.apply(spans[i:i + chunk])
        return (len(spans) - chunk) / (time.perf_counter() - t0)

    single = TpuSpanStore(config)
    single_rate = stream(single)
    del single

    mesh = Mesh(np.array(devs[:n]), axis_names=("shard",))
    fleet = ShardedSpanStore(mesh, config, dispatch_window_s=0.004)
    try:
        fleet_rate = stream(fleet)
        with fleet.pipelined(depth=8):
            t0 = time.perf_counter()
            for i in range(0, len(spans), chunk):
                fleet.apply(spans[i:i + chunk])
        piped_rate = len(spans) / (time.perf_counter() - t0)

        # Read side: the same mixed query set, serialized then batched.
        svcs = sorted(fleet.get_all_service_names())[:8]
        end_ts = 2**62
        queries = [("q", svc) if i % 2 else ("ids", svc)
                   for i, svc in enumerate(svcs * 8)]

        def run_one(kind, svc):
            if kind == "q":
                return fleet.service_duration_quantiles(svc, [0.5, 0.99])
            return [(r.trace_id, r.timestamp)
                    for r in fleet.get_trace_ids_by_name(
                        svc, None, end_ts, 10)]

        for kind, svc in queries[:len(svcs) * 2]:
            run_one(kind, svc)  # warm both kernel families
        fleet.dispatcher.drain()

        launches0 = fleet.collective_launches()
        t0 = time.perf_counter()
        serialized = [run_one(*q) for q in queries]
        serial_s = time.perf_counter() - t0
        serial_launches = fleet.collective_launches() - launches0

        n_threads = 8
        per = len(queries) // n_threads
        batched: list = [None] * len(queries)
        barrier = threading.Barrier(n_threads + 1)

        def reader(t_idx):
            barrier.wait()
            for j in range(t_idx * per, (t_idx + 1) * per):
                batched[j] = run_one(*queries[j])

        threads = [threading.Thread(target=reader, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        launches0 = fleet.collective_launches()
        t0 = time.perf_counter()
        barrier.wait()
        for t in threads:
            t.join()
        batched_s = time.perf_counter() - t0
        batched_launches = fleet.collective_launches() - launches0
        dstats = fleet.dispatcher.stats()

        return {
            "shards": n,
            "spans": total_spans,
            "single_chip_spans_per_s": round(single_rate, 1),
            "fleet_spans_per_s": round(fleet_rate, 1),
            "fleet_pipelined_spans_per_s": round(piped_rate, 1),
            "fleet_spans_per_s_per_chip": round(fleet_rate / n, 1),
            "scaling_efficiency": round(
                fleet_rate / (single_rate * n), 3),
            "queries": len(queries),
            "serialized_qps": round(len(queries) / serial_s, 1),
            "batched_qps": round(len(queries) / batched_s, 1),
            "read_speedup": round(serial_s / batched_s, 2),
            "serialized_launches": int(serial_launches),
            "batched_launches": int(batched_launches),
            "dispatcher_launches_saved": dstats["launches_saved"],
            "dispatcher_max_batch": dstats["max_batch"],
            "answers_identical": serialized == batched,
        }
    finally:
        fleet.close()


def bench_checkpoint(store):
    """Checkpoint at bench scale (VERDICT r3 item 8): snapshot the
    streamed store, restore it, and require bit-identical answers to a
    small query set across the save/load boundary."""
    import shutil
    import tempfile

    from zipkin_tpu import checkpoint as ckpt
    from zipkin_tpu.store.tpu import TpuSpanStore

    state = store.state
    end_ts = int(state.ts_max) + 1
    S = store.config.max_services
    svcs = [f"svc-{i:04d}" for i in
            np.random.default_rng(13).integers(0, S, size=6)]

    def answers(st):
        out = []
        for svc in svcs:
            out.append([(i.trace_id, i.timestamp)
                        for i in st.get_trace_ids_by_name(
                            svc, None, end_ts, 10)])
        deps = st.get_dependencies()
        out.append(sorted(
            (l.parent, l.child, l.duration_moments.count)
            for l in deps.links
        )[:200])
        out.append(round(st.estimated_unique_traces(), 1))
        return out

    before = answers(store)
    # Per-run mkdtemp (unpredictable, 0700) under a fixed parent; stale
    # siblings from abandoned (watchdog-timed-out) runs — which never
    # reach this function's finally-rmtree — are swept here instead. A
    # fixed world-known path would let another local user pre-create or
    # symlink the target of our rmtree+writes (advisor r4).
    parent = os.path.join(tempfile.gettempdir(),
                          f"zk_bench_ckpt_{os.getuid()}")
    os.makedirs(parent, mode=0o700, exist_ok=True)
    st = os.lstat(parent)
    import stat as stat_mod
    if (st.st_uid != os.getuid()
            or not stat_mod.S_ISDIR(st.st_mode)
            or stat_mod.S_IMODE(st.st_mode) & 0o022):
        # Pre-created by someone else (sticky /tmp lets any user claim
        # the predictable name): don't sweep or reuse it — a foreign
        # parent owner could swap the snapshot dir between save and
        # load. Fall back to a fresh private tree, no leak-reclaim.
        parent = None
        path = tempfile.mkdtemp(prefix="zk_bench_ckpt_")
    else:
        for stale in os.listdir(parent):
            shutil.rmtree(os.path.join(parent, stale),
                          ignore_errors=True)
        path = tempfile.mkdtemp(dir=parent)
    try:
        t0 = time.perf_counter()
        # Chunked + resumable D2H: <=64MB slabs, each under its own
        # deadline with one retry; a wedged slab costs a bounded wait
        # and the staged leaves survive for the next attempt (r4: one
        # monolithic 544MB device_get hung >70 min).
        xfer = ckpt.save(store, path, chunk_deadline_s=240,
                         slab_retries=1)
        save_s = time.perf_counter() - t0
        size_mb = sum(
            f.stat().st_size for f in __import__("pathlib").Path(path)
            .rglob("*") if f.is_file()
        ) / 1e6
        t0 = time.perf_counter()
        restored = ckpt.load(path)
        load_s = time.perf_counter() - t0
        assert isinstance(restored, TpuSpanStore)
        after = answers(restored)
        del restored
    finally:
        shutil.rmtree(path, ignore_errors=True)
        # A wedged chunked save leaves its staged leaves beside the
        # path; this bench's paths are per-run mkdtemp names, so the
        # stage can never be resumed — reclaim it.
        shutil.rmtree(path + ".staging", ignore_errors=True)
    out = {
        "save_s": round(save_s, 2), "load_s": round(load_s, 2),
        "snapshot_mb": round(size_mb, 1),
        "query_parity": before == after,
        "d2h": xfer,
    }
    _log(f"checkpoint: save {save_s:.1f}s, load {load_s:.1f}s, "
         f"{size_mb:.0f}MB, parity={before == after}")
    return out


def preflight_backend(timeout_s: float = 90.0):
    """Bounded accelerator probe: initialize the default jax backend in a
    SUBPROCESS and run one tiny computation, with a hard timeout.

    A wedged axon tunnel makes ``jax.devices()`` block indefinitely in
    whatever process first touches it (NOTES_r03 §7); round 3's bench sat
    through a 25-minute backend-init hang before its except-clause fired.
    Probing in a killable child bounds that to ``timeout_s`` and leaves
    THIS process's jax uninitialized, so on failure we can still flip to
    the CPU platform and produce device-path evidence.

    Returns (ok, info_str). ok means: an accelerator platform initialized
    and executed an op within the timeout.
    """
    import subprocess

    code = (
        "import jax, jax.numpy as jnp; d = jax.devices(); "
        "print('PLATFORM', d[0].platform, len(d), flush=True); "
        "print('SUM', float(jnp.ones(8).sum()), flush=True)"
    )
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout_s:.0f}s (wedged tunnel?)"
    dt = time.perf_counter() - t0
    tail = (proc.stdout or "").strip().splitlines()
    if proc.returncode != 0:
        return False, f"probe rc={proc.returncode}: {tail[-1] if tail else ''}"
    plat = ""
    for line in tail:
        if line.startswith("PLATFORM "):
            plat = line.split()[1]
    if plat in ("", "cpu"):
        return False, f"no accelerator platform registered (got {plat!r})"
    return True, f"{plat} ok in {dt:.1f}s"


def bench_compare_kernels(total_spans: int = 10_000_000):
    """XLA scatter vs pallas VMEM-resident histogram ingest, same stream
    (the measured decision VERDICT r2 asked for)."""
    out = {}
    for use_pallas in (False, True):
        try:
            _, stats = bench_tpu_stream(
                total_spans, capacity_log2=20, n_services=256,
                batch_traces=8192, use_pallas=use_pallas,
            )
            out["pallas" if use_pallas else "xla"] = stats["spans_per_s"]
        except Exception as e:  # pallas may not lower on this backend
            out["pallas" if use_pallas else "xla"] = f"error: {e}"
    if all(isinstance(v, (int, float)) for v in out.values()):
        out["winner"] = "pallas" if out["pallas"] > out["xla"] else "xla"
    return out


def bench_ingest_matrix(spans_per_arm: int, smoke: bool = False):
    """Ingest-roofline round-2 evidence (r12): spans/s per
    (batch_spans, sort-path, scatter-path) arm, so the next on-chip
    run can pick the batch-escalation knee and certify the >=300k
    spans/s single-chip gate at the 100M config with the kernel
    choices named in the record.

    Three arm families, each a short fused-ingest stream:

    - **batch escalation** at the cert geometry (cap 2^22): sweep the
      template batch through {0.5x, 1x, 2x, 4x} of the r5-era 114688-
      span optimum — the PR 4 pipeline removed the host stalls that
      set it, so the scatter-amortization knee must be re-measured;
    - **sort path** at a mid geometry (cap 2^16, batch_traces=512 →
      ~3.6k spans ≈ ~57k concatenated index ROWS per launch) where
      the counting-rank scratch fits: argsort vs counting, same
      stream (at the cert geometry counting statically degrades to
      argsort — the scratch arithmetic in docs/PERFORMANCE.md — so
      the comparison is only measurable here);
    - **scatter path** at a small geometry (cap 2^12) where the
      unified arena fits VMEM: XLA plane scatters vs the fused pallas
      claim+scatter kernel (ops/pallas_kernels.arena_claim_scatter).

    Every arm records the ACTIVE paths (dev.active_paths), not just
    the requested ones — "auto"/"counting"/pallas degrade statically
    and the record must say what ran."""
    if smoke:
        arms = [
            dict(capacity_log2=14, n_services=64, batch_traces=256,
                 rank_path="argsort"),
            dict(capacity_log2=14, n_services=64, batch_traces=256,
                 rank_path="counting"),
            dict(capacity_log2=12, n_services=64, batch_traces=128,
                 use_pallas=True),
        ]
    else:
        arms = [
            # (a) batch escalation at the cert geometry.
            dict(batch_traces=8192),
            dict(batch_traces=16384),
            dict(batch_traces=32768),
            dict(batch_traces=65536),
            # (b) sort path, mid geometry (counting engages here).
            dict(capacity_log2=16, n_services=64, batch_traces=512,
                 rank_path="argsort"),
            dict(capacity_log2=16, n_services=64, batch_traces=512,
                 rank_path="counting"),
            # (c) scatter path, VMEM-resident arena geometry.
            dict(capacity_log2=12, n_services=64, batch_traces=128),
            dict(capacity_log2=12, n_services=64, batch_traces=128,
                 use_pallas=True),
        ]
    out = []
    for arm in arms:
        label = ",".join(f"{k}={v}" for k, v in sorted(arm.items()))
        try:
            store, stats = bench_tpu_stream(spans_per_arm, **arm)
            store = None  # free HBM before the next arm compiles
            out.append({
                "arm": arm,
                "batch_spans": stats["batch_spans"],
                "spans_per_s": stats["spans_per_s"],
                "rank_path": stats["rank_path"],
                "scatter_path": stats["scatter_path"],
                "chain": stats["chain"],
            })
            _log(f"matrix arm [{label}]: "
                 f"{stats['spans_per_s'] / 1e3:.1f}k spans/s "
                 f"(rank={stats['rank_path']}, "
                 f"scatter={stats['scatter_path']})")
        except Exception as e:  # noqa: BLE001 — one arm, not the phase
            out.append({"arm": arm, "error": repr(e)})
            _log(f"matrix arm [{label}] failed: {e!r}")
    return out


def _make_emitter(detail, get_ingest, get_sql):
    """The one-line JSON record, emitted INCREMENTALLY: printed+flushed
    after every completed phase (and mirrored to BENCH_PARTIAL.json), so
    a driver-window kill at ANY point still leaves the last phase's
    complete record on stdout. Rounds 3 and 4 both lost their headline
    numbers to an end-of-process-only print (r3: dead tunnel zero; r4:
    rc 124 mid-phase with stream+queries already measured — VERDICT r4
    missing #1). The driver parses the LAST JSON line; each emission is
    a complete, strictly-richer record."""
    def emit(phase):
        ingest, sql = get_ingest(), get_sql()
        detail["phases_complete"] = phase
        rec = {
            "metric": "ingest_throughput",
            "value": ingest["spans_per_s"] if ingest else 0.0,
            "unit": "spans/sec",
            "vs_baseline": (
                round(ingest["spans_per_s"] / sql["ingest_spans_per_s"],
                      2) if ingest and sql else 0.0
            ),
            "detail": detail,
        }
        line = json.dumps(rec)
        print(line, flush=True)
        try:
            with open("BENCH_PARTIAL.json", "w") as f:
                f.write(line + "\n")
        except OSError:
            pass
    return emit


def main():
    # SIGUSR1 → stack dump on stderr (the tunnel can block a device call
    # indefinitely; this makes a stall diagnosable from outside).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--compare-kernels", action="store_true")
    ap.add_argument("--spans", type=float, default=None,
                    help="TPU stream length (default 1e8, smoke 2e5)")
    ap.add_argument("--preflight-timeout", type=float, default=90.0,
                    help="seconds to wait for accelerator backend init")
    ap.add_argument("--batch-traces", type=int, default=16384,
                    help="traces per template batch in the full config "
                         "(x7 spans; larger batches shrink the per-scan-"
                         "iteration floor share — tune on real hardware)")
    ap.add_argument("--batch-spans", type=int, default=0,
                    help="batch escalation: template batch size in "
                         "SPANS (overrides --batch-traces, rounded "
                         "down to whole traces; the half-ring guard "
                         "still clamps — see bench_ingest_matrix for "
                         "the sweep that picks the knee)")
    ap.add_argument("--use-pallas", action="store_true",
                    help="route the main stream through the pallas "
                         "kernels (histogram adds always; the fused "
                         "arena claim+scatter when the arena fits "
                         "VMEM — the record says which path ran)")
    ap.add_argument("--rank-path", default="auto",
                    choices=("auto", "argsort", "counting"),
                    help="index-write FIFO rank implementation for "
                         "the main stream (bitwise-identical paths; "
                         "counting degrades to argsort where its "
                         "scratch can't fit — recorded either way)")
    ap.add_argument("--no-ingest-matrix", action="store_true",
                    help="skip the (batch_spans, sort-path, scatter-"
                         "path) arm matrix phase")
    ap.add_argument("--pipeline-depth", type=int, default=8,
                    help="prefetch depth for the pipelined-ingest "
                         "phase (bounded stage-1 queue)")
    ap.add_argument("--exactness-budget", type=float, default=120.0,
                    help="wall-clock budget (s) for the index-vs-scan "
                         "exactness phase in full runs (each force_scan "
                         "replay is O(ring); round 4 spent 771s here)")
    args = ap.parse_args()

    detail = {}
    # Bounded backend preflight BEFORE anything touches jax in this
    # process: a dead tunnel costs at most --preflight-timeout, then the
    # harness degrades to CPU (smoke shapes, for the full config) so the
    # record always carries device-path evidence — never a bare zero, and
    # never a multi-minute hang inside backend init (both happened in r3).
    ok, info = preflight_backend(args.preflight_timeout)
    detail["backend_preflight"] = info
    if not ok:
        _log(f"backend preflight failed ({info}); forcing CPU platform")
        import jax

        jax.config.update("jax_platforms", "cpu")
        if not args.smoke:
            args.smoke = True
            detail["fallback_cpu_smoke"] = True
    else:
        _log(f"backend preflight: {info}")

    # Persistent compilation cache: cold compiles at bench shapes cost
    # ~5 min (NOTES_r03 §7); repeated runs (retries, the 1B follow-up
    # stream, post-outage re-runs) should pay it once per machine.
    try:
        import jax

        # User-private location (NOT the world-writable temp dir, where
        # a predictable path could be pre-created by another user).
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "zipkin_tpu_jax"),
        )
    except Exception as e:  # noqa: BLE001 — best-effort optimization
        _log(f"compilation cache unavailable: {e!r}")

    # The SQL CPU reference: it needs no device, so even a dead TPU
    # backend still yields a valid one-line JSON result instead of an
    # empty benchmark record.
    sql = bench_sql_baseline(total_spans=2_000 if args.smoke else 10_000)
    detail["config1_sql_cpu_reference"] = sql
    ingest = None
    emit = _make_emitter(detail, lambda: ingest, lambda: sql)
    try:
        batch_traces = (max(1, args.batch_spans // SPT)
                        if args.batch_spans > 0 else args.batch_traces)
        if args.smoke:
            store, ingest = bench_tpu_stream(
                int(args.spans or 2e5), capacity_log2=16, n_services=64,
                batch_traces=min(batch_traces, 1024),
                use_pallas=args.use_pallas, rank_path=args.rank_path,
            )
        else:
            store, ingest = bench_tpu_stream(
                int(args.spans or 1e8), batch_traces=batch_traces,
                use_pallas=args.use_pallas, rank_path=args.rank_path,
            )
        detail["config2_tpu_ingest"] = ingest
        emit("stream")
        detail["tpu_queries"] = bench_tpu_queries(
            store, reps=5 if args.smoke else 12
        )
        emit("stream+queries")
        detail["batched_queries"] = bench_batched_queries(
            store, ks=(1, 4, 16) if args.smoke else (1, 4, 16, 64),
            reps=3 if args.smoke else 5,
        )
        emit("stream+queries+batched")
        # Resident query engine (r11 tentpole): sketch-tier p50 /
        # concurrent index-tier p99 / cache-hit identity against the
        # p50<10ms & p99<50ms acceptance targets, with sketch answers
        # cross-checked against the device path on every rep.
        detail["query_engine"] = _bounded(
            lambda: bench_query_engine(
                store, reps=8 if args.smoke else 20,
                concurrency=4 if args.smoke else 8),
            timeout_s=600, label="query-engine")
        emit("stream+queries+batched+engine")
        detail["index_exactness"] = bench_exactness(
            store, n_queries=9 if args.smoke else 24,
            budget_s=None if args.smoke else args.exactness_budget,
        )
        emit("stream+queries+exactness")
        # Cold-tier paging layer (store/archive): capture overhead and
        # cold-query latency at ~4 ring laps. Bounded separately from
        # the main stream (its own small ring), so a failure here
        # can't strand the already-emitted core phases.
        detail["archive_cold_tier"] = _bounded(
            lambda: bench_archive(
                int(2e4) if args.smoke else int(4e5)),
            timeout_s=900, label="archive")
        emit("stream+queries+exactness+archive")
        # Pipelined ingest (r9 tentpole): serial vs three-stage
        # pipelined drive of the same stream, capture sealing async.
        # Bounded like the archive phase — a failure here must not
        # strand the already-emitted core phases.
        detail["pipelined_ingest"] = _bounded(
            lambda: bench_pipeline(
                int(2e4) if args.smoke else int(4e5),
                depth=args.pipeline_depth),
            timeout_s=900, label="pipeline")
        emit("stream+queries+exactness+archive+pipeline")
        # Durability (r10 tentpole, zipkin_tpu.wal): append overhead
        # per fsync policy, WAL bytes/span, cold recovery rate, and
        # bitwise recovered==oracle identity. Bounded like its
        # neighbors — a failure here must not strand the core phases.
        detail["durability_wal"] = _bounded(
            lambda: bench_durability(
                int(2e4) if args.smoke else int(2e5)),
            timeout_s=900, label="durability")
        emit("stream+queries+exactness+archive+pipeline+durability")
        # Windowed analytics (r13 tentpole, aggregate/windows.py):
        # arena fold overhead on the fused step, mirror-served
        # quantile/burn/heatmap latency, bitwise + exactness checks.
        # Bounded like its neighbors — a failure here must not strand
        # the core phases.
        detail["windowed_analytics"] = _bounded(
            lambda: bench_windows(
                int(2e4) if args.smoke else int(2e5)),
            timeout_s=900, label="windows")
        emit("stream+queries+exactness+archive+pipeline+durability"
             "+windows")
        # Paged span layout (r19 tentpole, store/paged): complete-
        # trace spans retained per device byte on a zipf session mix
        # (the >=2x skew-tax acceptance arm) + the uniform-ingest
        # planner overhead. Bounded like its neighbors.
        detail["paged_layout"] = _bounded(
            lambda: bench_paged(
                int(2e4) if args.smoke else int(2e5)),
            timeout_s=900, label="paged")
        emit("stream+queries+exactness+archive+pipeline+durability"
             "+windows+paged")
        # WAL-shipped replication (r15 tentpole, zipkin_tpu.replicate):
        # replica staleness lag under full ingest load, failover RTO,
        # aggregate sketch-tier queries/s across the device-free
        # replica fleet, bitwise agreement at the drained frontier.
        # Bounded like its neighbors.
        detail["replication"] = _bounded(
            lambda: bench_replication(
                int(2e4) if args.smoke else int(2e5),
                n_replicas=2 if args.smoke else 3),
            timeout_s=900, label="replication")
        emit("stream+queries+exactness+archive+pipeline+durability"
             "+windows+replication")
        # Multi-chip sharded serving (r16 tentpole, parallel/shard):
        # spans/s-per-chip scaling vs one chip, aggregate read q/s
        # serialized vs dispatcher-batched with the launch counts and
        # the bitwise-identity bit. Skips itself (one JSON key) on a
        # single-device backend; bounded like its neighbors.
        detail["multichip"] = _bounded(
            lambda: bench_multichip(
                int(2e4) if args.smoke else int(2e5)),
            timeout_s=900, label="multichip")
        emit("stream+queries+exactness+archive+pipeline+durability"
             "+windows+replication+multichip")
        # Ingest roofline round 2 (r12 tentpole): spans/s per
        # (batch_spans, sort-path, scatter-path) arm — the evidence
        # the batch-escalation knee and the >=300k spans/s cert read
        # from. Short per-arm streams, bounded, after the core emits
        # (the r4 lesson: never let an extra-credit phase strand the
        # headline record).
        if not args.no_ingest_matrix:
            detail["ingest_matrix"] = _bounded(
                lambda: bench_ingest_matrix(
                    int(1e5) if args.smoke else int(1e7),
                    smoke=args.smoke),
                timeout_s=2400, label="ingest-matrix")
            emit("core+matrix")
        # The XLA-vs-pallas kernel decision was measured and recorded in
        # round 4 (xla 158.6k vs pallas 155.0k spans/s, NOTES_r04 §3);
        # re-measuring it on every full run cost two extra compile+
        # stream cycles and was exactly where the round-4 driver window
        # ran out. It now runs only on explicit request.
        if args.compare_kernels:
            detail["compare_kernels"] = bench_compare_kernels(
                total_spans=int(2e5) if args.smoke else int(1e7)
            )
            emit("stream+queries+exactness+compare")
        # Checkpoint-at-scale runs under a watchdog: the snapshot's
        # multi-hundred-MB device_get has been observed to wedge
        # indefinitely on an aged tunnel (round 4: a 100M-config save
        # hung >70 min after completing in ~6 min earlier the same
        # day). A hung transfer must cost a bounded wait and one
        # missing sub-record — never the whole benchmark (whose
        # headline record is already emitted above either way).
        # Budget: a HEALTHY 100M-config save+load+replay measured
        # ~320s (r4: save 202s / load 119s) on a good tunnel and ~6
        # min mid-degradation — 1200s covers a merely-slow tunnel
        # (misclassifying one as wedged would also suppress the 1B
        # attempt below) while still halving round 4's 25-min cap.
        ck = _bounded(lambda: bench_checkpoint(store), timeout_s=1200,
                      label="checkpoint")
        detail["checkpoint_at_scale"] = ck
        emit("core+checkpoint")
        ck_wedged = isinstance(ck, dict) and "timed_out_s" in ck
        # The BASELINE north star: 1B spans ingested and queried on one
        # chip. Attempt it automatically whenever the measured 100M
        # throughput makes 1e9 tractable (>= 0.7M spans/s ⇒ <= ~24 min
        # of streaming) — so an unattended end-of-round run carries the
        # evidence, not just a hand-driven session. Skipped when the
        # checkpoint watchdog fired: a wedged tunnel would strand the
        # (unbounded) 1e9 stream behind the abandoned transfer.
        if (not args.smoke and args.spans is None and not ck_wedged
                and ingest["spans_per_s"] >= 7e5):
            store = None  # free HBM before the 1e9 stream
            _log(f"1B attempt: {ingest['spans_per_s'] / 1e6:.2f}M "
                 f"spans/s makes 1e9 tractable; streaming")
            try:
                # Extra-credit run: its failure must not mark the
                # completed core benchmark as a TPU-path failure.
                store1b, stats1b = bench_tpu_stream(
                    int(1e9), batch_traces=args.batch_traces
                )
                detail["config2b_1B_ingest"] = stats1b
                emit("core+1B-stream")
                detail["tpu_queries_1B"] = bench_tpu_queries(
                    store1b, reps=8
                )
                emit("core+1B-stream+1B-queries")
                detail["exactness_1B"] = bench_exactness(
                    store1b, n_queries=12,
                    budget_s=args.exactness_budget,
                )
                del store1b
            except Exception as e:  # noqa: BLE001
                _log(f"1B attempt failed: {e!r}")
                detail["tpu_1b_error"] = repr(e)
    except Exception as e:  # noqa: BLE001 — emit a record either way
        _log(f"TPU path failed: {e!r}")
        detail["tpu_error"] = repr(e)
    # The final line must stay truthful about how far the run got: on
    # the failure path, re-emitting "all" would claim phases that never
    # ran (the driver parses the LAST line).
    if "tpu_error" in detail:
        emit(f"aborted-after:{detail.get('phases_complete', 'none')}")
    else:
        emit("all")


if __name__ == "__main__":
    main()
