"""All-in-one daemon: collector + device store + query + HTTP API.

Usage:
    python -m zipkin_tpu.main.example --port 9411 [--seed-traces 10]
        [--sample-rate 1.0] [--adaptive-target N] [--checkpoint DIR]
        [--memory-store]

Reference shape: zipkin-example's Main (scribe receiver + store + query
+ web in one process) and zipkin-deployment-collector's sampler wiring.
"""

from __future__ import annotations

import argparse
import signal
import threading
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9411)
    p.add_argument("--scribe-port", type=int, default=9410,
                   help="framed-thrift Scribe.Log TCP port (0 disables)")
    p.add_argument("--memory-store", action="store_true",
                   help="use the in-memory reference store instead of TPU")
    p.add_argument("--shards", type=int, default=0,
                   help="serve from an N-shard ShardedSpanStore over the "
                        "device mesh (0 = single-device store); needs N "
                        "visible devices — use --platform cpu with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                        "to simulate")
    p.add_argument("--capacity", type=int, default=1 << 16,
                   help="span ring capacity (device store)")
    p.add_argument("--layout", default="ring",
                   choices=("ring", "paged"),
                   help="span-plane layout: 'ring' = the FIFO ring "
                        "(default); 'paged' = fixed-size device pages "
                        "with per-trace chaining and LRW page reclaim, "
                        "so one hot 10k-span trace can't evict a "
                        "thousand cold 1-span traces "
                        "(docs/STORAGE_TIERS.md; single-device stores "
                        "only; echoed at /vars/layout)")
    p.add_argument("--page-rows", type=int, default=128,
                   help="rows per page for --layout paged (power of "
                        "two dividing --capacity; 128 keeps the "
                        "Pallas gather lane-aligned — echoed at "
                        "/vars/pageRows)")
    p.add_argument("--batch-spans", type=int, default=0,
                   help="ingest batch escalation: max spans per device "
                        "launch (0 = the store's legacy 4096 default; "
                        "the ring guards still clamp to capacity/2 — "
                        "see docs/PERFORMANCE.md for picking the knee)")
    p.add_argument("--use-pallas", action="store_true",
                   help="route ingest scatter-adds (and, when the "
                        "index arena fits VMEM, the fused claim+"
                        "scatter) through the pallas kernels instead "
                        "of XLA scatter; the active path is reported "
                        "in counters()/metrics (scatter_path_pallas)")
    p.add_argument("--rank-path", default="auto",
                   choices=("auto", "argsort", "counting"),
                   help="index-write FIFO rank implementation (both "
                        "are bitwise-identical; auto picks the "
                        "counting sort when its scratch fits — "
                        "docs/PERFORMANCE.md)")
    p.add_argument("--window-seconds", type=int, default=60,
                   help="windowed-analytics time-bucket width for the "
                        "(service × time) Moments-sketch arena behind "
                        "/api/windowed_quantiles, /api/slo_burn and "
                        "/api/latency_heatmap (0 disables the arena; "
                        "echoed at /vars/windowSeconds — "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--window-buckets", type=int, default=64,
                   help="windowed-analytics ring length: retention is "
                        "window_seconds × window_buckets of cells per "
                        "service; stale slots self-clear on reuse "
                        "(echoed at /vars/windowBuckets)")
    p.add_argument("--sample-rate", type=float, default=1.0)
    p.add_argument("--adaptive-target", type=float, default=0.0,
                   help="target stored spans/minute; 0 disables adaptive")
    p.add_argument("--queue-max", type=int, default=500)
    p.add_argument("--queue-workers", type=int, default=10)
    p.add_argument("--no-self-trace-ingest", action="store_true",
                   help="disable the per-ingest-step zipkin-tpu self "
                        "spans (API-request self-tracing stays on; "
                        "see docs/OBSERVABILITY.md)")
    p.add_argument("--no-fleet-obs", action="store_true",
                   help="disable the fleet-observability surface: "
                        "batch-lineage tracing (WAL-stamped causal "
                        "spans across ship/apply), metrics federation "
                        "(/metrics?fleet=1, /api/fleet), and the stall "
                        "watchdog behind /api/health + /debug/events "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--lineage-sample-every", type=int, default=0,
                   help="trace 1-in-N launch units end-to-end through "
                        "WAL append → fsync → ship → follower apply "
                        "(0 = the default 64; 1 traces every unit — "
                        "bench/debug only)")
    p.add_argument("--cold-tier", action="store_true",
                   help="capture ring evictions into the compressed "
                        "segment archive and federate queries across "
                        "hot + cold (store/archive; single-device "
                        "stores only)")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   help="pipelined ingest: overlap host encode + H2D "
                        "staging with device compute behind a bounded "
                        "prefetch queue of this depth (0 = serial "
                        "write path; on --shards N the pipeline feeds "
                        "every shard's fused commit — see "
                        "docs/INGEST_PIPELINE.md)")
    p.add_argument("--capture-backlog", type=int, default=4,
                   help="cold-tier async sealer: bound on pulled-but-"
                        "unsealed eviction capture windows; a full "
                        "backlog is the only way capture can stall "
                        "ingest (0 = seal inline on the write path)")
    p.add_argument("--wal-dir", default=None,
                   help="write-ahead log dir: journal every ingest "
                        "batch before commit, replay the tail at boot, "
                        "and switch scribe/kafka receivers to "
                        "ack-after-durable-append; on --shards N this "
                        "is a per-shard + group-commit-epoch log tree "
                        "(see docs/DURABILITY.md, docs/SHARDING.md)")
    p.add_argument("--wal-fsync", default="interval",
                   choices=("batch", "interval", "off"),
                   help="WAL fsync policy: per-batch, group-commit "
                        "interval (default), or off (page-cache only)")
    p.add_argument("--wal-fsync-interval", type=float, default=0.05,
                   help="group-commit fsync cadence in seconds "
                        "(--wal-fsync interval)")
    p.add_argument("--wal-segment-bytes", type=int, default=64 << 20,
                   help="roll WAL segment files at this size; whole "
                        "segments are deleted once a checkpoint "
                        "covers them")
    p.add_argument("--wal-retain-bytes", type=int, default=0,
                   help="shipping retention floor: keep at least this "
                        "many newest WAL bytes on disk even when a "
                        "checkpoint covers them, so reconnecting "
                        "followers catch up from the log instead of "
                        "re-anchoring (0 = truncate everything "
                        "covered; registered follower cursors always "
                        "pin regardless — docs/REPLICATION.md)")
    p.add_argument("--ship-port", type=int, default=0,
                   help="serve sealed WAL records to replication "
                        "followers on this framed-TCP port (0 "
                        "disables; requires --wal-dir — "
                        "docs/REPLICATION.md)")
    p.add_argument("--follow", default=None, metavar="HOST:PORT",
                   help="run as a replication follower of the primary "
                        "at HOST:PORT instead of a collector daemon: "
                        "no ingest ports open, reads serve from the "
                        "replicated store, staleness is exposed at "
                        "/api/replication")
    p.add_argument("--follow-mode", default="replica",
                   choices=("replica", "standby"),
                   help="follower role: 'replica' = device-free CPU "
                        "read replica (SketchMirror + cold segments, "
                        "no TPU); 'standby' = full device store "
                        "replaying through the normal commit body, "
                        "ready for failover")
    p.add_argument("--follow-poll-ms", type=float, default=20.0,
                   help="follower fetch-poll cadence when the primary "
                        "has nothing new (each fetch is also the ack "
                        "that advances the primary's retention pin)")
    p.add_argument("--follower-name", default=None,
                   help="stable follower identity for the primary's "
                        "cursor registry (default: <mode>-<hostname> — "
                        "STABLE across restarts, so a restarted "
                        "follower reuses its retention pin instead of "
                        "leaking a dead one; set explicitly when "
                        "running several same-mode followers per host)")
    p.add_argument("--query-window-ms", type=float, default=None,
                   help="resident query executor micro-batch window "
                        "(ms): how long an idle-entry request waits "
                        "for company before its coalesced device "
                        "launch (default: 2 ms on device stores, 0 on "
                        "the memory store; runtime-adjustable via "
                        "/vars/queryWindowMs — docs/QUERY_ENGINE.md)")
    p.add_argument("--seed-traces", type=int, default=0,
                   help="generate N synthetic traces at startup")
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint dir: restore at boot, save on exit "
                        "and every --checkpoint-interval seconds")
    p.add_argument("--checkpoint-interval", type=float, default=300.0)
    p.add_argument("--platform", default=None, choices=("cpu", "tpu"),
                   help="force the jax backend (a sitecustomize-"
                        "registered accelerator plugin wins over "
                        "JAX_PLATFORMS, so an env var is not enough)")
    return p


def build_app(args):
    from zipkin_tpu.api.server import ApiServer
    from zipkin_tpu.ingest.collector import Collector
    from zipkin_tpu.query.service import QueryService
    from zipkin_tpu.sampler.adaptive import AdaptiveConfig
    from zipkin_tpu.sampler.core import Sampler

    if args.checkpoint and args.memory_store:
        raise SystemExit(
            "--checkpoint requires a device store (the in-memory "
            "reference store has no snapshot support)"
        )
    if args.layout != "ring":
        # The paged planner is per-store host state; the sharded
        # store's stacked states have no per-shard planner yet, and
        # the memory store has no device layout at all.
        if args.memory_store:
            raise SystemExit(
                "--layout paged requires a device store (the "
                "in-memory reference store has no span planes)"
            )
        if args.shards:
            raise SystemExit(
                "--layout paged requires the single-device store "
                "(the sharded store's per-shard page planner is not "
                "wired yet)"
            )
    store = None
    if args.checkpoint:
        from zipkin_tpu import checkpoint

        if checkpoint.exists(args.checkpoint):
            # A sharded snapshot restores a ShardedSpanStore (shard
            # count from the snapshot; must match --shards if given).
            # exists() includes the .old mid-swap fallback — booting
            # FRESH after a crashed save would replay the WAL tail
            # against empty dictionaries (lineage error at best,
            # silent loss of checkpoint-covered spans at worst).
            # config_defaults: a pre-rev-14 snapshot (no window keys)
            # restores with an EMPTY window arena at the flag
            # geometry; a rev-14+ snapshot's saved geometry wins.
            store = checkpoint.load(args.checkpoint, config_defaults={
                "window_seconds": args.window_seconds,
                "window_buckets": args.window_buckets,
            })
            n = getattr(store, "n", 0)
            if args.shards and n != args.shards:
                raise SystemExit(
                    f"checkpoint has {n or 1} shard(s); --shards "
                    f"{args.shards} does not match"
                )
    if store is None:
        if args.memory_store:
            from zipkin_tpu.store.memory import InMemorySpanStore

            store = InMemorySpanStore()
            # Exact-scan windowed analytics use the same bucket width
            # the device arena would (0 keeps the 60s default — the
            # scan path has no arena to disable).
            if args.window_seconds > 0:
                store.window_seconds = args.window_seconds
        elif args.shards:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            from zipkin_tpu.parallel.shard import ShardedSpanStore
            from zipkin_tpu.store.device import StoreConfig

            devices = jax.devices()
            if len(devices) < args.shards:
                raise SystemExit(
                    f"--shards {args.shards} but only {len(devices)} "
                    f"devices visible (see --shards help)"
                )
            mesh = Mesh(np.array(devices[:args.shards]),
                        axis_names=("shard",))
            # Windowed analytics runs per shard (the fused step bumps
            # every shard's cell census); reads merge the shard
            # mirrors' arenas lazily into the fleet view
            # (store/mirror.FleetMirror) with zero device round-trips
            # — docs/SHARDING.md.
            store = ShardedSpanStore(
                mesh, StoreConfig(
                    capacity=args.capacity,
                    batch_spans=args.batch_spans,
                    use_pallas=args.use_pallas,
                    rank_path=args.rank_path,
                    window_seconds=args.window_seconds,
                    window_buckets=args.window_buckets,
                ),
                dispatch_window_s=(
                    args.query_window_ms / 1000.0
                    if args.query_window_ms is not None else 0.0),
            )
        else:
            from zipkin_tpu.store.device import StoreConfig
            from zipkin_tpu.store.tpu import TpuSpanStore

            store = TpuSpanStore(StoreConfig(
                capacity=args.capacity,
                batch_spans=args.batch_spans,
                use_pallas=args.use_pallas,
                rank_path=args.rank_path,
                window_seconds=args.window_seconds,
                window_buckets=args.window_buckets,
                layout=args.layout,
                page_rows=args.page_rows,
            ))
    if args.cold_tier:
        if hasattr(store, "archive"):
            # Restored tiered checkpoint: already wrapped, but the
            # daemon still wants compaction off the ingest write path.
            store.archive.start_compactor()
        else:
            if args.memory_store or getattr(store, "n", 0):
                raise SystemExit(
                    "--cold-tier requires the single-device store "
                    "(the sharded store's per-shard capture is not "
                    "wired yet)"
                )
            from zipkin_tpu.store.archive import TieredSpanStore

            store = TieredSpanStore(store, background_compaction=True)
    # The async capture sealer takes effect the first time a capture
    # window is pulled, so the knob just needs to be set before writes.
    hot = getattr(store, "hot", store)
    if hasattr(hot, "capture_backlog"):
        hot.capture_backlog = max(0, args.capture_backlog)
    if args.wal_dir:
        if not hasattr(hot, "attach_wal"):
            raise SystemExit(
                "--wal-dir requires a device store (the in-memory "
                "reference store has no journaled commit path)"
            )
        from zipkin_tpu.wal import ShardedWal, WriteAheadLog, replay_into

        n_shards = getattr(hot, "n", 0)
        if n_shards:
            # Per-shard segment logs + a group-commit epoch log: one
            # journal entry per fused launch unit, recovery replays
            # only COMPLETE epochs (wal/sharded.py).
            if args.ship_port or args.wal_retain_bytes:
                raise SystemExit(
                    "--ship-port/--wal-retain-bytes are single-log "
                    "features; the sharded group-commit log does not "
                    "ship to followers yet"
                )
            wal = ShardedWal(
                args.wal_dir, n_shards, fsync=args.wal_fsync,
                interval_s=args.wal_fsync_interval,
                segment_bytes=args.wal_segment_bytes,
            )
        else:
            wal = WriteAheadLog(
                args.wal_dir, fsync=args.wal_fsync,
                interval_s=args.wal_fsync_interval,
                segment_bytes=args.wal_segment_bytes,
                retain_bytes=args.wal_retain_bytes,
            )
        # Boot-time recovery: the checkpoint (restored above, or a
        # fresh store) is the base; every WAL record past its applied
        # sequence replays through the normal ingest path — capture,
        # sealing, and sweep cadence included — BEFORE the collector's
        # pipeline starts and the ports open.
        hot.attach_wal(wal)
        stats = replay_into(store, wal)
        if stats["replayed_records"]:
            print(f"wal: replayed {stats['replayed_records']} records "
                  f"({stats['replayed_spans']} spans) in "
                  f"{stats['replay_s']}s")
    adaptive = (
        AdaptiveConfig(target_store_rate=args.adaptive_target)
        if args.adaptive_target > 0 else None
    )
    collector = Collector(
        store, sampler=Sampler(args.sample_rate), adaptive=adaptive,
        max_queue=args.queue_max, concurrency=args.queue_workers,
        self_trace=not args.no_self_trace_ingest,
        pipeline_depth=args.pipeline_depth,
    )
    tracker = None
    watchdog = None
    recorder = None
    if not args.no_fleet_obs:
        from zipkin_tpu import obs
        from zipkin_tpu.obs import fleet as fobs

        reg = obs.default_registry()
        # Batch-lineage tracing: spans land through store.apply so they
        # live in the system's own store (and ride the WAL/ship path
        # like any span). attach_lineage is a no-op journal-wise until
        # a single-log WAL is attached; the sharded group-commit log
        # does not stamp lineage yet, but the tracker still collects
        # dispatcher + API-parented spans there.
        tracker = fobs.LineageTracker(
            store.apply, registry=reg,
            sample_every=args.lineage_sample_every or None)
        if hasattr(hot, "attach_lineage"):
            hot.attach_lineage(tracker)
        disp = getattr(hot, "dispatcher", None)
        if disp is not None:
            disp.span_sink = tracker
        recorder = fobs.FlightRecorder()
        watchdog = fobs.Watchdog(recorder=recorder, registry=reg)
        watchdog.add_probe("pipeline", fobs.pipeline_stall_probe(hot))
        watchdog.add_probe("sealer", fobs.sealer_backlog_probe(hot))
        wal_obj = getattr(store, "wal", None)
        if wal_obj is not None and hasattr(wal_obj, "sync_error"):
            watchdog.add_probe("wal_fsync",
                               fobs.fsync_parked_probe(wal_obj))
        if disp is not None:
            watchdog.add_probe("dispatcher",
                               fobs.dispatcher_stuck_probe(disp))
    shipper = None
    if args.ship_port:
        if getattr(store, "wal", None) is None:
            raise SystemExit("--ship-port requires --wal-dir (sealed "
                             "WAL records are what gets shipped)")
        from zipkin_tpu.replicate import WalShipper

        shipper = WalShipper(store, tracker=tracker)
        if watchdog is not None:
            from zipkin_tpu.obs import fleet as fobs

            def _worst_follower_lag():
                st = shipper.status()
                lags = [f["lagRecords"]
                        for f in st.get("followers", {}).values()]
                return {"lagRecords": max(lags) if lags else 0}

            watchdog.add_probe(
                "follower_lag",
                fobs.follower_lag_probe(_worst_follower_lag))
    fleet = None
    if not args.no_fleet_obs:
        from zipkin_tpu import obs
        from zipkin_tpu.obs import fleet as fobs

        fleet = fobs.FleetObs(
            role="primary", registry=obs.default_registry(),
            tracker=tracker, watchdog=watchdog, recorder=recorder,
            remote_sources=(shipper.fleet_sources
                            if shipper is not None else None),
            replication=(shipper.status
                         if shipper is not None else None),
        )
    window_s = (args.query_window_ms / 1000.0
                if args.query_window_ms is not None else None)
    api = ApiServer(
        QueryService(store, coalesce_window_s=window_s), collector,
        replication=shipper.status if shipper is not None else None,
        fleet=fleet,
    )
    return store, collector, api, shipper


def build_follower_app(args):
    """Follower daemon (--follow): connect to the primary's ship port,
    build the local store from the primary's config, and serve the
    read API from it — no ingest ports, no collector. Returns
    (store, follower, api)."""
    import socket as _socket

    from zipkin_tpu.api.server import ApiServer
    from zipkin_tpu.query.service import QueryService
    from zipkin_tpu.replicate import (
        Follower,
        ReplicaTarget,
        ShipClient,
        StandbyTarget,
    )
    from zipkin_tpu.replicate.protocol import config_from_dict

    host, _, port = args.follow.rpartition(":")
    if not host or not port.isdigit():
        raise SystemExit(f"--follow wants HOST:PORT, got {args.follow!r}")
    # No PID in the default: the name keys the primary's retention pin,
    # and a per-process name would leak one pinned cursor per restart
    # (truncation blocked at the dead cursor forever).
    name = args.follower_name or (
        f"{args.follow_mode}-{_socket.gethostname()}")
    client = ShipClient(host, int(port), name, mode=args.follow_mode)
    hello = client.connect()
    config = config_from_dict(hello["config"])
    if args.follow_mode == "standby":
        from zipkin_tpu.store.tpu import TpuSpanStore

        store = None
        if args.checkpoint:
            from zipkin_tpu import checkpoint

            # Anchor bootstrap for a standby is a CHECKPOINT of the
            # primary lineage: the shipped tail replays on top of it
            # exactly like crash recovery would.
            if checkpoint.exists(args.checkpoint):
                store = checkpoint.load(args.checkpoint)
        if store is None:
            store = TpuSpanStore(config)
        target = StandbyTarget(store)
    else:
        from zipkin_tpu.store.replica import ReplicaSpanStore

        store = ReplicaSpanStore(config)
        target = ReplicaTarget(store)
    lineage = None
    fleet = None
    if not args.no_fleet_obs:
        from zipkin_tpu import obs
        from zipkin_tpu.obs import fleet as fobs

        reg = obs.default_registry()
        lineage = fobs.FollowerLineage(name, mode=args.follow_mode,
                                       registry=reg)
    follower = Follower(target, client,
                        poll_interval_s=args.follow_poll_ms / 1000.0,
                        lineage=lineage)
    if lineage is not None:
        recorder = fobs.FlightRecorder()
        watchdog = fobs.Watchdog(recorder=recorder, registry=reg)
        watchdog.add_probe("replication_lag",
                           fobs.follower_lag_probe(follower.status))
        fleet = fobs.FleetObs(
            role=args.follow_mode, name=name, registry=reg,
            follower=lineage, watchdog=watchdog, recorder=recorder,
            replication=follower.status,
        )
    window_s = (args.query_window_ms / 1000.0
                if args.query_window_ms is not None else None)
    api = ApiServer(
        QueryService(store, coalesce_window_s=window_s), None,
        replication=follower.status,
        fleet=fleet,
    )
    return store, follower, api


def seed(collector, n_traces: int) -> None:
    from zipkin_tpu.tracegen import generate_traces

    for spans in generate_traces(n_traces=n_traces):
        collector.accept(spans)
    collector.flush()


def follower_main(args) -> None:
    """The --follow serving loop: read-only API over the replicated
    store; SIGTERM/SIGINT stop the follower cleanly (a standby with
    --checkpoint snapshots on the same cadence as a primary, so its
    own recovery base stays fresh)."""
    from zipkin_tpu.api.server import make_server, serve_forever_in_thread

    store, follower, api = build_follower_app(args)
    follower.start()
    server = make_server(api, args.host, args.port)
    serve_forever_in_thread(server)
    print(f"zipkin-tpu {args.follow_mode} following {args.follow}, "
          f"serving reads on {args.host}:{args.port}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    can_checkpoint = (args.follow_mode == "standby" and args.checkpoint)
    last_ckpt = time.time()
    try:
        while not stop.is_set():
            stop.wait(1.0)
            err = follower.error()
            if err is not None and not follower.status()["connected"]:
                # Transient disconnects retry inside the loop; only a
                # terminal lineage error lands here with the thread
                # stopped.
                if follower._thread is None or not \
                        follower._thread.is_alive():
                    print(f"follower stopped: {err!r}")
                    break
            if (can_checkpoint
                    and time.time() - last_ckpt
                    > args.checkpoint_interval):
                from zipkin_tpu import checkpoint

                # Captured BEFORE the save: the snapshot covers at
                # least this frontier (records applied mid-save only
                # push the manifest higher), so acking it after a
                # successful save is always conservative.
                seq = follower.target.applied_seq()
                checkpoint.save(store, args.checkpoint)
                # The standby's retention ack is its CHECKPOINTED
                # frontier — only now may the primary truncate the
                # covered records (replicate/follow.StandbyTarget).
                follower.target.note_checkpointed(seq)
                last_ckpt = time.time()
    finally:
        server.shutdown()
        follower.close()
        if can_checkpoint:
            try:
                from zipkin_tpu import checkpoint

                checkpoint.save(store, args.checkpoint)
            except Exception:
                import traceback

                traceback.print_exc()
        store.close()


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.follow:
        follower_main(args)
        return
    store, collector, api, shipper = build_app(args)
    if args.seed_traces:
        seed(collector, args.seed_traces)

    from zipkin_tpu.api.server import make_server, serve_forever_in_thread

    server = make_server(api, args.host, args.port)
    serve_forever_in_thread(server)
    ship_srv = None
    if shipper is not None:
        from zipkin_tpu.replicate import ShipServer

        ship_srv = ShipServer(shipper, args.host, args.ship_port)
        ship_srv.serve_in_thread()
    scribe_srv = None
    if args.scribe_port:
        from zipkin_tpu.ingest.receiver import ScribeReceiver
        from zipkin_tpu.ingest.scribe_server import ScribeServer

        # Ack contract: with a WAL, scribe's OK means "durably
        # appended" — the receiver processes synchronously through the
        # durable entries instead of acking from the async queue.
        if getattr(store, "wal", None) is not None:
            receiver = ScribeReceiver(
                collector.ingest_durable,
                process_thrift=collector.ingest_thrift_durable,
            )
        else:
            receiver = ScribeReceiver(
                collector.accept,
                process_thrift=collector.accept_thrift,
            )
        scribe_srv = ScribeServer(receiver, args.host, args.scribe_port)
        scribe_srv.serve_in_thread()
    print(f"zipkin-tpu example serving on {args.host}:{args.port}"
          + (f" (scribe tcp :{args.scribe_port})" if scribe_srv else "")
          + (f" (wal-ship tcp :{args.ship_port})" if ship_srv else ""))

    stop = threading.Event()
    # SIGINT and SIGTERM share the graceful-save path: both land in
    # the ordered shutdown below (drain → seal → WAL-fsync →
    # checkpoint) instead of an interpreter teardown mid-write.
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    def checkpoint_now():
        if args.checkpoint:
            from zipkin_tpu import checkpoint

            checkpoint.save(store, args.checkpoint)

    last_ckpt = time.time()
    try:
        while not stop.is_set():
            stop.wait(1.0)
            collector.control_tick()
            if (args.checkpoint
                    and time.time() - last_ckpt > args.checkpoint_interval):
                checkpoint_now()
                last_ckpt = time.time()
    finally:
        # Graceful-save ordering (docs/DURABILITY.md): stop intake
        # first, then drain-pipeline → seal-barrier → WAL-fsync
        # (collector.flush enforces that order), THEN checkpoint — so
        # the snapshot's sealed frontier and applied WAL sequence
        # cover everything accepted, and its success truncates the
        # covered log segments. close() comes last.
        if scribe_srv is not None:
            scribe_srv.shutdown()
        if ship_srv is not None:
            ship_srv.shutdown()
        server.shutdown()
        try:
            collector.flush()
        except Exception:
            # A failed drain must not block the checkpoint — but it
            # must be SEEN (graftlint swallowed-exception).
            import traceback

            traceback.print_exc()
        try:
            checkpoint_now()
        except Exception:
            # A failed final save (disk full, suspect store) must not
            # skip the drain/fsync below: the WAL still covers what
            # the snapshot was meant to, so close() losing its final
            # fsync would be the only way to actually lose data here.
            import traceback

            traceback.print_exc()
        collector.close()
        if shipper is not None:
            shipper.close()
        if api.fleet is not None and api.fleet.tracker is not None:
            # Flush buffered lineage spans before the WAL's final
            # fsync so the self-trace tail is durable too.
            try:
                api.fleet.tracker.flush()
            except Exception:
                import traceback

                traceback.print_exc()
        wal = getattr(store, "wal", None)
        if wal is not None:
            wal.close()


if __name__ == "__main__":
    main()
