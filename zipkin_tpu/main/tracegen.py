"""tracegen main: write synthetic traces, then query everything back.

The end-to-end smoke of the whole pipeline (tracegen/Main.scala:40-117):
generate → scribe-encode → receiver decode → collector → store, then
exercise every read API and print what came back. Exits non-zero if any
read comes back empty.
"""

from __future__ import annotations

import argparse
import sys


def run(n_traces: int = 5, max_depth: int = 7, use_tpu: bool = True,
        verbose: bool = True) -> bool:
    from zipkin_tpu.ingest.collector import Collector
    from zipkin_tpu.ingest.receiver import ScribeReceiver
    from zipkin_tpu.query.request import QueryRequest
    from zipkin_tpu.query.service import QueryService
    from zipkin_tpu.tracegen import generate_traces
    from zipkin_tpu.wire.thrift import span_to_scribe_message

    if use_tpu:
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        store = TpuSpanStore(StoreConfig(
            capacity=1 << 12, ann_capacity=1 << 14, bann_capacity=1 << 13,
            max_services=64, max_span_names=512, max_annotation_values=1024,
            max_binary_keys=128, cms_width=1 << 12, hll_p=10,
            quantile_buckets=1024,
        ))
    else:
        from zipkin_tpu.store.memory import InMemorySpanStore

        store = InMemorySpanStore()
    collector = Collector(store)
    receiver = ScribeReceiver(collector.accept)
    query = QueryService(store)

    traces = generate_traces(n_traces=n_traces, max_depth=max_depth)
    for spans in traces:
        entries = [("zipkin", span_to_scribe_message(s)) for s in spans]
        code = receiver.log(entries)
        assert code.name == "OK", code
    collector.flush()

    def say(*a):
        if verbose:
            print(*a)

    ok = True
    services = query.get_service_names()
    say(f"services: {sorted(services)}")
    ok &= bool(services)
    for svc in sorted(services)[:3]:
        names = query.get_span_names(svc)
        say(f"  spans[{svc}]: {sorted(names)[:5]}")
        resp = query.get_trace_ids(QueryRequest(svc, end_ts=10**18, limit=10))
        say(f"  trace ids[{svc}]: {list(resp.trace_ids)[:5]}")
        if resp.trace_ids:
            got = query.get_traces_by_ids(resp.trace_ids[:3])
            summaries = query.get_trace_summaries_by_ids(resp.trace_ids[:3])
            combos = query.get_trace_combos_by_ids(resp.trace_ids[:3])
            say(f"  fetched {len(got)} traces, {len(summaries)} summaries, "
                f"{len(combos)} combos")
            ok &= bool(got) and bool(summaries) and bool(combos)
    deps = query.get_dependencies()
    say(f"dependency links: {len(deps.links)}")
    if use_tpu:
        ok &= bool(deps.links)
    total = sum(len(t) for t in traces)
    say(f"wrote {total} spans across {len(traces)} traces -> "
        + ("OK" if ok else "FAILED"))
    return bool(ok)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--traces", type=int, default=5)
    p.add_argument("--max-depth", type=int, default=7)
    p.add_argument("--memory-store", action="store_true")
    args = p.parse_args(argv)
    ok = run(args.traces, args.max_depth, use_tpu=not args.memory_store)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
