"""Composed entry points (the zipkin-example / zipkin-deployment-* mains).

- ``zipkin_tpu.main.example``: everything in one process — collector +
  TPU store + query + HTTP API + optional tracegen seed
  (zipkin-example/.../Main.scala).
- ``zipkin_tpu.main.tracegen``: generate traces, push them through the
  collector, then read them back through every query API
  (zipkin-tracegen/.../Main.scala:40-117).

Flags are argparse (the TwitterServer-flags analogue); every flag has
the reference's default where one exists.
"""
