"""Trace-id sampling: the vectorized threshold test.

Reference semantics (zipkin-sampler/.../Sampler.scala:39-48): keep a
trace iff ``rate == 1`` or ``t > Long.MaxValue * (1 - rate)`` where ``t``
is ``abs(traceId)`` (with ``Long.MinValue`` mapped to ``Long.MaxValue``).
Because trace ids are uniform random 64-bit ints, this passes an
unbiased ``rate`` fraction and is *consistent*: every collector makes
the same decision for the same trace id at the same rate.

The debug override (SpanSamplerFilter.scala:40-47: spans with the debug
flag always pass) is part of ``sample_mask``.

The float→threshold conversion happens once on the host in float64
(``rate_to_threshold``); the device compares 64-bit ints exactly, so no
TPU float64 is needed.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp

LONG_MAX = (1 << 63) - 1
LONG_MIN = -(1 << 63)


def rate_to_threshold(rate: float) -> int:
    """Host: sample rate in [0,1] → int64 threshold (exclusive lower bound)."""
    rate = min(max(float(rate), 0.0), 1.0)
    # float64 LONG_MAX rounds to 2^63; clamp back into int64 range.
    return min(int(LONG_MAX * (1.0 - rate)), LONG_MAX)


def sample_mask(trace_ids, debug, threshold):
    """Device: keep-mask for a batch.

    ``trace_ids`` int64, ``debug`` bool, ``threshold`` int64 scalar from
    ``rate_to_threshold`` (0 keeps everything).
    """
    tids = jnp.asarray(trace_ids, jnp.int64)
    t = jnp.where(tids == LONG_MIN, LONG_MAX, jnp.abs(tids))
    return jnp.asarray(debug, bool) | (threshold <= 0) | (t > threshold)


class Sampler:
    """Host-side stateful wrapper with counters (Sampler.scala:27).

    The rate is a plain attribute (the Var analogue); the adaptive
    controller updates it.
    """

    def __init__(self, rate: float = 1.0):
        self.rate = rate
        self.allowed = 0  # guarded-by: lock
        self.denied = 0  # guarded-by: lock
        # Counters are bumped from every collector worker thread; an
        # unlocked read-modify-write loses increments under concurrency
        # and skews the adaptive controller's inputs.
        self.lock = threading.Lock()  # lock-order: 80 sampler

    @property
    def threshold(self) -> int:
        return rate_to_threshold(self.rate)

    def count(self, allowed: int, denied: int) -> None:
        """Thread-safe bulk counter update (fast-path batches)."""
        with self.lock:
            self.allowed += allowed
            self.denied += denied

    def snapshot(self):
        """(allowed, denied) under the lock — the metrics read path
        (the collector's gauges read these from the exposition thread
        while workers bump them; graftlint guarded-by)."""
        with self.lock:
            return self.allowed, self.denied

    def decide(self, trace_id: int) -> bool:
        """Pure threshold test, no counters, no lock — batch callers
        fold their decisions into one count() per batch instead of
        taking the lock once per span."""
        if self.rate >= 1.0:
            return True
        t = LONG_MAX if trace_id == LONG_MIN else abs(trace_id)
        return t > self.threshold

    def __call__(self, trace_id: int) -> bool:
        allow = self.decide(trace_id)
        self.count(int(allow), int(not allow))
        return allow
