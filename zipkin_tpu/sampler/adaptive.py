"""Adaptive sample-rate controller as a pure-function pipeline.

Reference: AdaptiveSampler.scala:59-71 — the calculator is the chain
``RequestRateCheck → SufficientDataCheck → ValidDataCheck → OutlierCheck
→ CalculateSampleRate (→ IsLeaderCheck → CooldownCheck)``, each an
``Option[A] => Option[B]``. Here each stage is a pure function over
``Optional`` values, so everything is unit-testable without any
coordination infrastructure — the same decomposition the reference's
tests rely on (AdaptiveSamplerTest.scala:26-50).

Differences by design (SURVEY.md §3.5): there is no ZooKeeper. The
controller runs on the single Python controller process (the "leader" by
construction), and the global store rate comes from the device ingest
counters — summed across shards with a psum/sum rather than a ZK group
snapshot (GlobalSampleRateUpdater's role, AdaptiveSampler.scala:204-237).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence


def request_rate_check(vals: Optional[Sequence[float]], target_rate: float
                       ) -> Optional[Sequence[float]]:
    """Pass only when a positive target store rate is configured
    (RequestRateCheck, AdaptiveSampler.scala:239)."""
    return vals if (vals is not None and target_rate > 0) else None


def sufficient_data_check(vals: Optional[Sequence[float]], required: int
                          ) -> Optional[Sequence[float]]:
    """Pass only with a full-enough window (SufficientDataCheck :259)."""
    return vals if (vals is not None and len(vals) >= required) else None


def valid_data_check(vals: Optional[Sequence[float]]
                     ) -> Optional[Sequence[float]]:
    """Pass only when every datum is non-negative (ValidDataCheck :276)."""
    return vals if (vals is not None and all(v >= 0 for v in vals)) else None


def outlier_check(vals: Optional[Sequence[float]], target_rate: float,
                  required_points: int, threshold: float = 0.15
                  ) -> Optional[Sequence[float]]:
    """Pass only when the last ``required_points`` data all deviate from
    the target by more than ``threshold`` (OutlierCheck :311): the rate
    only moves when the flow is *persistently* off-target."""
    if vals is None or len(vals) < required_points:
        return None
    tail = list(vals)[-required_points:]
    if all(abs(v - target_rate) > target_rate * threshold for v in tail):
        return vals
    return None


def discounted_average(vals: Sequence[float], discount: float = 0.9) -> float:
    """Recency-weighted mean; vals[-1] is the newest sample
    (DiscountedAverage, AdaptiveSampler.scala:332)."""
    newest_first = list(reversed(list(vals)))
    weights = [discount**i for i in range(len(newest_first))]
    return sum(w * v for w, v in zip(weights, newest_first)) / sum(weights)


def calculate_sample_rate(
    vals: Optional[Sequence[float]],
    current_rate: float,
    target_store_rate: float,
    threshold: float = 0.05,
    max_rate: float = 1.0,
) -> Optional[float]:
    """Linear controller (CalculateSampleRate :344-390):

        new = current * target_store_rate / current_store_rate

    clamped to ``max_rate``; suppressed when the relative change is below
    ``threshold`` (5%) so the fleet isn't churned by noise."""
    if vals is None:
        return None
    cur_store_rate = discounted_average(vals)
    if cur_store_rate <= 0:
        return None
    new_rate = min(max_rate, current_rate * target_store_rate / cur_store_rate)
    change = abs(current_rate - new_rate) / current_rate
    return new_rate if change >= threshold else None


def cooldown_check(value, now_s: float, last_update_s: Optional[float],
                   period_s: float):
    """Rate updates at most once per ``period_s`` (CooldownCheck :293)."""
    if value is None:
        return None
    if last_update_s is not None and now_s - last_update_s < period_s:
        return None
    return value


@dataclass
class AdaptiveConfig:
    """Flag parity with AdaptiveSampler.scala:33-57 (seconds, not Durations)."""

    target_store_rate: float = 0.0  # spans/minute to admit; 0 = disabled
    update_freq_s: float = 30.0
    window_s: float = 30 * 60.0
    sufficient_window_s: float = 10 * 60.0
    outlier_window_s: float = 5 * 60.0
    outlier_threshold: float = 0.15
    change_threshold: float = 0.05
    max_rate: float = 1.0
    cooldown_s: float = 0.0

    @property
    def window_len(self) -> int:
        return max(1, int(self.window_s / self.update_freq_s))

    @property
    def sufficient_len(self) -> int:
        return max(1, int(self.sufficient_window_s / self.update_freq_s))

    @property
    def outlier_len(self) -> int:
        return max(1, int(self.outlier_window_s / self.update_freq_s))


class AdaptiveSampleRateController:
    """Single-controller loop: feed store rates, get rate updates.

    ``observe(store_rate, now_s)`` is called every ``update_freq_s`` with
    the global spans/minute admitted (from device counters, psum-ed
    across shards). Returns the new sample rate when the pipeline decides
    to move, else None. ``rate`` always holds the current value.
    """

    def __init__(self, config: AdaptiveConfig, initial_rate: float = 1.0):
        self.config = config
        self.rate = initial_rate
        self.buffer: List[float] = []  # AtomicRingBuffer analogue
        self.last_update_s: Optional[float] = None

    def observe(self, store_rate: float, now_s: float) -> Optional[float]:
        c = self.config
        self.buffer.append(float(store_rate))
        if len(self.buffer) > c.window_len:
            self.buffer = self.buffer[-c.window_len:]
        vals: Optional[Sequence[float]] = list(self.buffer)
        vals = request_rate_check(vals, c.target_store_rate)
        vals = sufficient_data_check(vals, c.sufficient_len)
        vals = valid_data_check(vals)
        vals = outlier_check(vals, c.target_store_rate, c.outlier_len,
                             c.outlier_threshold)
        new_rate = calculate_sample_rate(
            vals, self.rate, c.target_store_rate, c.change_threshold, c.max_rate
        )
        new_rate = cooldown_check(new_rate, now_s, self.last_update_s,
                                  c.cooldown_s)
        if new_rate is not None:
            self.rate = new_rate
            self.last_update_s = now_s
        return new_rate


class FlowEstimator:
    """spans/minute from a monotonically increasing span counter — the
    FlowReportingFilter analogue (AdaptiveSampler.scala:151-174), reading
    the device ``spans_seen`` counter instead of wrapping the pipeline."""

    def __init__(self):
        self._last_count: Optional[float] = None
        self._last_ts: Optional[float] = None

    def observe(self, total_spans: float, now_s: float) -> Optional[float]:
        if self._last_count is None or now_s <= self._last_ts:
            self._last_count, self._last_ts = total_spans, now_s
            return None
        per_min = (total_spans - self._last_count) * 60.0 / (now_s - self._last_ts)
        self._last_count, self._last_ts = total_spans, now_s
        return per_min
