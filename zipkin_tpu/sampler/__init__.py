"""Sampling: vectorized trace-id sampler + adaptive rate controller.

Reference parity: zipkin-sampler (Sampler.scala:27, SpanSamplerFilter.scala:30,
AdaptiveSampler.scala:59-71) re-designed for the TPU runtime: the
threshold test runs vectorized on device inside the ingest step, and the
control loop is a single-controller pure-function pipeline fed by
globally psum-able device counters — no ZooKeeper.
"""

from zipkin_tpu.sampler.core import (  # noqa: F401
    Sampler,
    rate_to_threshold,
    sample_mask,
)
from zipkin_tpu.sampler.adaptive import (  # noqa: F401
    AdaptiveConfig,
    AdaptiveSampleRateController,
    calculate_sample_rate,
    cooldown_check,
    discounted_average,
    outlier_check,
    request_rate_check,
    sufficient_data_check,
    valid_data_check,
)
