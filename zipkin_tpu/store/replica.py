"""ReplicaSpanStore — a device-free CPU read replica fed by shipped WAL.

The disaggregated-serving split (docs/REPLICATION.md): one chip owns
the write path; any number of plain-CPU replicas own dashboard reads.
A replica replays the primary's journaled stage-1 launch groups
(wal/record.py) into exactly two host structures and nothing else:

- the **SketchMirror** (store/mirror.py) — numpy twins of the device's
  lifetime aggregates AND the windowed Moments-sketch arena. The
  mirror's ``delta_of`` is a pure host function of the record's
  columns, and its integer folds are the same adds/maxes the fused
  device step scatters — so a replica's mirror is BITWISE the
  primary's device arrays at the same applied WAL sequence. The whole
  sketch tier (catalogs, quantiles, top-k, HLL cardinality, windowed
  quantiles / SLO burn / latency heatmaps) answers from it with no TPU
  anywhere.

- a **cold-tier SegmentDirectory** (store/archive/) — every record's
  batches seal into an immutable zone-mapped segment (gids = the
  primary's global write positions, assigned identically by replay
  order), compacted by the background size-tiering. Row reads and
  index queries run the ColdQueries mixin — the SAME zone-prune +
  memory-oracle-match code the TieredSpanStore's cold half runs — so
  trace reads agree with the primary's hot+cold federation wherever
  both still retain the rows (the replica's retention is
  ``retain_spans``; the primary's is its cold tier).

Writes are refused (``ReplicaReadOnlyError``): the replica's only
writer is the replication follower (replicate/follow.py) calling
``apply_record``. Records must arrive in sequence — the dictionary
delta chain (wal/record.py) makes any gap or reorder a hard
``WalReplayError`` rather than silent divergence. Staleness is
explicit: ``applied_seq`` is the replica's frontier and
``write_frontier()`` keys the resident query engine's result cache, so
a cached answer is never served across an apply.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from zipkin_tpu.columnar.encode import SpanCodec, to_signed64
from zipkin_tpu.columnar.schema import SpanBatch
from zipkin_tpu.concurrency import RWLock
from zipkin_tpu.models.span import Span
from zipkin_tpu.ops import hll
from zipkin_tpu.ops import quantile as Q
from zipkin_tpu.store.analytics import WindowedAnalytics
from zipkin_tpu.store.archive.coldquery import (
    ColdQueries,
    durations_from_bounds,
)
from zipkin_tpu.store.archive.directory import (
    ArchiveParams,
    SegmentDirectory,
)
from zipkin_tpu.store.archive.segment import seal_segment
from zipkin_tpu.store.base import (
    IndexedTraceId,
    ReadSpanStore,
    StorageException,
    TraceIdDuration,
)
from zipkin_tpu.store.mirror import SketchMirror
from zipkin_tpu.wal.record import (
    WalReplayError,
    apply_dict_deltas,
    decode_unit,
)


class ReplicaReadOnlyError(StorageException):
    """A write reached a read replica: replicas are fed ONLY by the
    replication follower's ``apply_record``. Route writes to the
    primary."""


def concat_batch_parts(parts: Sequence[Tuple]) -> SpanBatch:
    """One SpanBatch from a launch unit's (batch, name_lc, indexable)
    parts, annotation span indices rebased — the replica seals one
    segment per WAL record instead of one per chunk."""
    batches = [b for b, _, _ in parts]
    if len(batches) == 1:
        return batches[0]
    out = {}
    for col in SpanBatch.SPAN_COLUMNS:
        out[col] = np.concatenate([getattr(b, col) for b in batches])
    offs = np.cumsum([0] + [b.n_spans for b in batches])
    for cols, idx_col in ((SpanBatch.ANN_COLUMNS, "ann_span_idx"),
                          (SpanBatch.BANN_COLUMNS, "bann_span_idx")):
        for col in cols:
            if col == idx_col:
                out[col] = np.concatenate([
                    getattr(b, col) + off
                    for b, off in zip(batches, offs)
                ]).astype(np.int32)
            else:
                out[col] = np.concatenate(
                    [getattr(b, col) for b in batches])
    return SpanBatch(**out)


class ReplicaSpanStore(WindowedAnalytics, ColdQueries, ReadSpanStore):
    """See the module docstring. Thread-safe: ``apply_record`` runs on
    the follower thread; reads run on API threads under the read half
    of the same RWLock discipline the device stores use."""

    def __init__(self, config, codec: Optional[SpanCodec] = None,
                 params: Optional[ArchiveParams] = None,
                 registry=None, retain_spans: int = 0,
                 background_compaction: bool = True):
        from zipkin_tpu import obs

        self.config = config
        self.codec = codec or SpanCodec()
        self.params = params or ArchiveParams.for_config(config)
        reg = registry or obs.default_registry()
        self._registry = reg
        self.archive = SegmentDirectory(self.params, self.codec,
                                        registry=reg)
        if background_compaction:
            # Inline compaction would run its deflate merge inside the
            # apply write-lock hold and stall every reader behind it.
            self.archive.start_compactor()
        self.sketch_mirror = SketchMirror(config,
                                          dicts=self.codec.dicts)
        # Replica retention: drop whole segments older than this many
        # spans behind the applied frontier (0 = keep everything).
        self.retain_spans = max(0, int(retain_spans))
        # Serializes appliers (the follower is single-threaded, but
        # anchor adoption and tests may race it).
        self._lock = threading.Lock()  # lock-order: 12 replica-apply
        # Guards the visible (segments, mirror, frontier) triple:
        # apply_record publishes under write, reads snapshot under
        # read — the frontier can never move mid-read, which is what
        # makes the engine's frontier-keyed cache sound here.
        self._rw = RWLock()  # lock-order: 40 commit
        # _wp is mutated only by the (single) applier under _lock and
        # published under _rw.write; the applier's own pre-publish read
        # (gid assignment) is safe under _lock alone, so the stricter
        # of the two guards can't be declared without false positives.
        self._wp = 0
        self._applied_seq = 0  # guarded-by: _rw.write
        self._step_seq = 0  # guarded-by: _rw.write
        self.ttls: Dict[int, float] = {}  # guarded-by: _lock
        self.records_applied = 0  # guarded-by: _rw.write
        self.spans_applied = 0  # guarded-by: _rw.write

    @property
    def dicts(self):
        return self.codec.dicts

    # -- replication write path (follower thread only) ------------------

    def applied_seq(self) -> int:
        with self._rw.read():
            return self._applied_seq

    def apply_record(self, seq: int, payload: bytes) -> int:
        """Fold one shipped WAL record in; returns its span count.
        Records must arrive in sequence order; an already-applied
        sequence is skipped idempotently (reconnect overlap), a gap is
        a lineage error (the dictionary delta chain would desync)."""
        with self._lock:
            with self._rw.read():
                applied = self._applied_seq
            if seq <= applied:
                return 0
            if applied and seq != applied + 1:
                raise WalReplayError(
                    f"replica at seq {applied} was shipped seq {seq} — "
                    f"records must arrive without gaps")
            group, before, deltas = decode_unit(payload)
            apply_dict_deltas(self.dicts, before, deltas)
            delta = self.sketch_mirror.delta_of(group)
            batch = concat_batch_parts(group)
            n = batch.n_spans
            gid_lo = self._wp
            gids = np.arange(gid_lo, gid_lo + n, dtype=np.int64)
            spans = self.codec.decode(batch)
            seg = seal_segment(
                self.archive.next_id(), batch, gids, spans,
                self.dicts, self.params, gid_lo, gid_lo + n,
            )
            from zipkin_tpu.store.base import (
                MAX_TTL_ENTRIES,
                prune_ttls,
            )

            for tid in np.unique(batch.trace_id):
                self.ttls.setdefault(int(tid), 1.0)
            prune_ttls(self.ttls, MAX_TTL_ENTRIES)
            with self._rw.write():
                self.archive.append(seg, cache=(batch, gids, spans))
                self.sketch_mirror.apply(delta)
                self._wp += n
                self._applied_seq = seq
                self._step_seq += 1
                self.records_applied += 1
                self.spans_applied += n
                if self.retain_spans:
                    self.archive.drop_below(self._wp - self.retain_spans)
            return n

    def adopt_anchor(self, applied_seq: int, wp: int,
                     dict_values: Dict[str, list],
                     arrays: Sequence[np.ndarray]) -> None:
        """Bootstrap from a primary anchor (replicate/ship.anchor_of):
        adopt the dictionary values in id order and the mirror arrays
        as of ``applied_seq``. The replica's sketch tier is then exact
        from genesis; row/segment coverage starts at the anchor
        (documented in docs/REPLICATION.md)."""
        from zipkin_tpu.wal.record import DICT_NAMES, load_value

        with self._lock:
            for name in DICT_NAMES:
                d = getattr(self.dicts, name)
                values = dict_values.get(name, [])
                for pos, item in enumerate(values):
                    value = load_value(item)
                    if pos < len(d):
                        existing = d.decode(pos + d._first_id)
                        if existing != value:
                            raise WalReplayError(
                                f"anchor dictionary '{name}' entry "
                                f"{pos} is {value!r} but the replica "
                                f"has {existing!r} — wrong lineage")
                        continue
                    got = d.encode(value)
                    if got != pos + d._first_id:
                        raise WalReplayError(
                            f"anchor dictionary '{name}' assigned id "
                            f"{got} for entry {pos} — wrong lineage")
            self.sketch_mirror.adopt(*arrays)
            with self._rw.write():
                self._wp = int(wp)
                self._applied_seq = int(applied_seq)
                self._step_seq += 1

    # -- visibility hooks (ColdQueries) ---------------------------------
    # The mixin defaults (plain directory snapshot/prune) are exactly
    # right here: the replica has no seal barrier to interpose —
    # sealing is synchronous inside apply_record.

    # -- query-engine hooks ---------------------------------------------

    def write_frontier(self) -> Tuple[int, int]:
        with self._rw.read():
            return (self._step_seq, 0)

    def ensure_sketch_mirror(self) -> SketchMirror:
        return self.sketch_mirror

    def _svc_id(self, service_name: str) -> Optional[int]:
        return self.dicts.services.get(service_name.lower())

    # -- sketch-tier reads (mirror ≡ primary device arrays) -------------

    def get_all_service_names(self) -> Set[str]:
        d = self.dicts.services
        with self._rw.read():
            present = self.sketch_mirror.service_presence()
            cold = self.cold_service_ids()
        out = {
            d.decode(i) for i in np.flatnonzero(present)
            if i < len(d) and d.decode(i)
        }
        out.update(
            name for i in cold if i < len(d) and (name := d.decode(i))
        )
        return out

    def get_span_names(self, service: str) -> Set[str]:
        svc = self._svc_id(service)
        if svc is None:
            return set()
        with self._rw.read():
            if svc < self.config.max_services:
                row = self.sketch_mirror.name_row(svc) > 0
                d = self.dicts.span_names
                out = {
                    d.decode(i) for i in np.flatnonzero(row)
                    if i < len(d) and d.decode(i)
                }
            else:
                out = set()
            # Segment rows cover overflow services (no mirror row can
            # represent them) and pre-mirror-anchor names.
            out.update(self.cold_span_names(service))
        return out

    def service_duration_quantiles(self, service: str,
                                   qs: Sequence[float]
                                   ) -> Optional[List[float]]:
        svc = self._svc_id(service)
        if svc is None:
            return None
        c = self.config
        gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
        with self._rw.read():
            if svc < c.max_services:
                counts = self.sketch_mirror.hist_row(svc)
            else:
                return self.cold_duration_quantiles(service, qs)
        return Q.quantiles_host(counts, gamma, 1.0, qs)

    @staticmethod
    def _top_row(row, dictionary, k: int):
        order = np.argsort(-row)[:k]
        return [
            (dictionary.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(dictionary)
        ]

    def top_annotations(self, service: str, k: int = 10):
        svc = self._svc_id(service)
        if svc is None or svc >= self.config.max_services:
            return []
        with self._rw.read():
            row = self.sketch_mirror.ann_value_row(svc)
        return self._top_row(row, self.dicts.annotations, k)

    def top_binary_keys(self, service: str, k: int = 10):
        svc = self._svc_id(service)
        if svc is None or svc >= self.config.max_services:
            return []
        with self._rw.read():
            row = self.sketch_mirror.bann_key_row(svc)
        return self._top_row(row, self.dicts.binary_keys, k)

    def estimated_unique_traces(self) -> float:
        with self._rw.read():
            regs = self.sketch_mirror.hll_registers()
        return float(hll.estimate(hll.HyperLogLog(regs)))

    # -- row reads (segments; ColdQueries semantics == memory oracle) ---

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        if not trace_ids:
            return set()
        qids = {to_signed64(t): t for t in trace_ids}
        with self._rw.read():
            return self.cold_traces_exist(qids)

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]
                               ) -> List[List[Span]]:
        if not trace_ids:
            return []
        qids = {to_signed64(t) for t in trace_ids}
        with self._rw.read():
            rows = self.cold_rows_for_traces(qids)
        by_tid = {
            tid: [span for _, span in sorted(found.items())]
            for tid, found in rows.items()
        }
        return [
            by_tid[to_signed64(t)] for t in trace_ids
            if by_tid.get(to_signed64(t))
        ]

    def get_traces_duration(self, trace_ids: Sequence[int]
                            ) -> List[TraceIdDuration]:
        if not trace_ids:
            return []
        canon = {to_signed64(t): t for t in trace_ids}
        with self._rw.read():
            bounds = self.cold_duration_bounds(canon, {})
        return durations_from_bounds(trace_ids, bounds)

    def get_trace_ids_by_name(self, service_name: str,
                              span_name: Optional[str], end_ts: int,
                              limit: int) -> List[IndexedTraceId]:
        with self._rw.read():
            return self._cold_ids_by_name(service_name, span_name,
                                          end_ts, limit)

    def get_trace_ids_by_annotation(self, service_name: str,
                                    annotation: str,
                                    value: Optional[bytes], end_ts: int,
                                    limit: int) -> List[IndexedTraceId]:
        with self._rw.read():
            return self._cold_ids_by_annotation(
                service_name, annotation, value, end_ts, limit)

    def get_time_to_live(self, trace_id: int) -> float:
        with self._lock:
            return self.ttls[to_signed64(trace_id)]

    # -- refused writes --------------------------------------------------

    def apply(self, spans) -> None:
        raise ReplicaReadOnlyError(
            "read replica: writes go to the primary")

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        raise ReplicaReadOnlyError(
            "read replica: pin/TTL mutations go to the primary")

    # -- telemetry / lifecycle ------------------------------------------

    def counters(self) -> Dict[str, float]:
        with self._rw.read():
            out = {
                "replica_applied_seq": float(self._applied_seq),
                "replica_records_applied": float(self.records_applied),
                "replica_spans_applied": float(self.spans_applied),
                "replica_wp": float(self._wp),
            }
        out.update(self.archive.stats())
        out["window_spans"] = float(self.sketch_mirror.win_spans_total)
        out["window_errors"] = float(
            self.sketch_mirror.win_errors_total)
        return out

    def stored_span_count(self) -> float:
        with self._rw.read():
            return float(self.spans_applied)

    def close(self) -> None:
        self.archive.stop_compactor()
        self.archive.close()
