"""Pipelined ingest + asynchronous eviction sealing (host side).

The fused ingest step is device work, but r5/1B profiling showed the
*host* half of every batch — thrift decode, columnar encode,
``should_index``, name-lc interning, ``make_device_batch`` padding and
the implicit H2D copy — running serially on one thread inside the
writer critical section, and PR 3's eviction capture stalling the
write path entirely (D2H pull + deflate seal inline) on every ring
lap. This module overlaps all of it, in the staging-buffer spirit of
DrJAX's MapReduce overlap and Ragged Paged Attention's paged staging
discipline (PAPERS.md):

``IngestPipeline`` — a three-stage software pipeline over the store's
write path (see docs/INGEST_PIPELINE.md):

1. **produce** (caller threads, under the store's encode lock):
   encode + index-policy bits + pow2 padding — everything that needs
   the dictionaries but not the device — feeding a bounded prefetch
   queue whose depth is the ONLY backpressure on writers;
2. **stage** (one thread): ``jax.device_put`` of the padded chunk
   into device memory while the previous fused step is still
   executing under JAX async dispatch; the stage→commit queue is
   bounded at 2 (double buffering);
3. **commit** (one thread): the eviction-capture trigger, then the
   donating state swap under ``store._rw.write()`` — the write lock
   is held for dispatch only, never for encode or H2D.

Batches flow through the queues strictly FIFO and the pads are the
same pow2 buckets the serial path uses, so a pipelined drive lands a
final device state BITWISE IDENTICAL to the serial path's (gated in
tests/test_pipeline.py and bench_smoke's pipeline phase) and hits the
same jit cache entries (zero steady-state recompiles,
``device.compile_count``).

``EvictionSealer`` — takes eviction capture off the critical path.
The write path still issues the read-only ``capture_eviction_rows``
launch synchronously (the captured-before-overwrite ordering
invariant lives there), but the resulting DEVICE arrays are handed to
this background thread for the D2H fetch, deflate compression, and
``ArchiveDirectory.append``. The bounded in-flight queue is the only
thing that can stall ingest (surfaced as the capture-backlog gauge +
stall counter); cold reads run behind ``TpuSpanStore.seal_barrier``
so a segment is never invisible to the query that needs it.

Error semantics match the serial write path's per-batch failures: a
worker failure parks the error, the failed item is dropped (counted
done, so blocked producers always unblock), and the parked error
re-raises ONCE on the next feed/submit/drain — failing that caller's
apply() exactly as an inline failure would — after which the stage
keeps processing. A transient fault (full disk during a seal, a
suspect store during a commit) therefore costs the batches that hit
it, never a permanently wedged store; the collector's queue counts
the surfaced failures like any other write error.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import NamedTuple, Optional

import jax

from zipkin_tpu.store import device as dev

_STOP = object()


class IngestUnit(NamedTuple):
    """One committed launch's worth of work: a padded DeviceBatch
    (stacked along a leading axis when ``chained``) plus the host
    bookkeeping the commit stage needs. ``n_parts`` is the number of
    chunker parts inside (the sweep-cadence increment). ``wal_seq``
    is the unit's write-ahead-log sequence (None when no WAL is
    attached); the commit stage advances the store's applied frontier
    to it inside the same write-lock hold as the donating swap, so a
    checkpoint cut is always consistent with its manifest sequence.
    ``sketch`` is the unit's host sketch-mirror delta (store/mirror):
    computed in stage 1 from the same columns the device scatters,
    folded into the mirror inside the commit's write-lock hold —
    the query engine's zero-dispatch tier is never behind the
    committed frontier."""

    db: object
    n_spans: int
    n_anns: int
    n_banns: int
    n_parts: int
    chained: bool
    wal_seq: Optional[int] = None
    sketch: Optional[object] = None
    # Sharded units only (parallel/shard.ShardedSpanStore): max spans
    # any shard's part carries, precomputed HOST-side in stage 1 —
    # ShardedStore.ingest requires it so the commit hold never syncs.
    incoming: Optional[int] = None
    # Paged layout only (store/paged.PagePlanner): (lo, hi) gid ranges
    # of the pages this unit reclaims — the commit stage pulls them
    # through the eviction sink BEFORE the launch (per-page
    # captured-before-overwrite). Empty for ring units.
    reclaims: tuple = ()


class _StageBase:
    """Shared fed/done accounting: every item fed is eventually counted
    done exactly once (processed or dropped-on-error), so ``drain``
    and blocked producers always terminate."""

    def __init__(self):
        self._cond = threading.Condition()  # lock-order: 65 stage
        self._fed = 0  # guarded-by: _cond
        self._done = 0  # guarded-by: _cond
        self._error: Optional[BaseException] = None  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # Monotonic timestamp of the last forward progress (an item
        # completing, or work arriving at an idle stage) — the stall
        # watchdog's probe reads the age (obs.fleet).
        self._last_progress = time.monotonic()  # guarded-by: _cond

    @property
    def error(self) -> Optional[BaseException]:
        """Peek at the parked worker error without clearing it
        (TpuSpanStore.stop_pipeline re-raises it after stop)."""
        with self._cond:
            return self._error

    def take_error(self) -> Optional[BaseException]:
        """Pop the parked worker error (if any). Surfacing CLEARS it —
        one failed batch fails one caller, then the stage keeps
        working, mirroring the serial path's per-batch failures."""
        with self._cond:
            err, self._error = self._error, None
            return err

    def _check_feedable(self) -> None:
        err = self.take_error()
        if err is not None:
            raise err
        with self._cond:
            if self._closed:
                raise RuntimeError("pipeline stage is stopped")
            if self._done == self._fed:
                # Idle → busy: the stall clock starts at arrival, not
                # at the last completion before the idle gap.
                self._last_progress = time.monotonic()
            self._fed += 1

    def _mark_done(self) -> None:
        with self._cond:
            self._done += 1
            self._last_progress = time.monotonic()
            self._cond.notify_all()

    def progress_age_s(self) -> float:
        """Seconds since this stage last made forward progress while
        holding queued work; 0.0 when idle. The watchdog's pipeline/
        sealer stall signal — a large age with a non-empty queue means
        a wedged worker, not backpressure."""
        with self._cond:
            if self._done >= self._fed:
                return 0.0
            return max(0.0, time.monotonic() - self._last_progress)

    def _park_error(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc

    def _wait_idle(self) -> None:
        with self._cond:
            while self._done < self._fed:
                self._cond.wait(timeout=0.5)

    def drain(self) -> None:
        """Block until everything fed BEFORE this call is processed;
        re-raises (and clears) a parked worker error — the item that
        errored was dropped, not silently retried. Draining to a
        snapshot target, not to empty, keeps drain() terminating under
        sustained concurrent feeding (a checkpoint save must not chase
        live writers forever)."""
        with self._cond:
            target = self._fed
            while self._done < target:
                self._cond.wait(timeout=0.5)
        err = self.take_error()
        if err is not None:
            raise err

    def _unregister(self, registry, metrics) -> None:
        for m in metrics:
            if registry.get(m.name) is m:
                registry.unregister(m.name)


class IngestPipeline(_StageBase):
    """Three-stage ingest pipeline over one TpuSpanStore (see module
    docstring). Created by ``TpuSpanStore.start_pipeline``; writers
    call ``feed`` (stage 1's tail), readers are untouched — they
    snapshot ``store.state`` under the read lock exactly as before."""

    def __init__(self, store, depth: int = 8, registry=None,
                 stage_buffers: int = 2):
        from zipkin_tpu import obs

        super().__init__()
        self._store = store
        self.depth = max(1, int(depth))
        self._prefetch: "queue.Queue" = queue.Queue(maxsize=self.depth)
        # Staged (device-resident) units in flight: 2 = classic double
        # buffering (one committing, one staging). Batch-escalated
        # deployments (StoreConfig.batch_spans, r12) may raise it so a
        # long device step never starves the H2D stage, at the cost of
        # stage_buffers x batch_spans of staged device memory.
        self.stage_buffers = max(1, int(stage_buffers))
        self._staged: "queue.Queue" = queue.Queue(
            maxsize=self.stage_buffers)
        # Stage-2 H2D hook: a sharded store places units over its mesh
        # (ShardedSpanStore.stage_unit); the single-device store keeps
        # the plain transfer.
        self._stage = getattr(store, "stage_unit", None) or dev.stage_batch
        reg = registry or obs.default_registry()
        self._registry = reg
        self.h_encode = reg.register(obs.LatencySketch(
            "zipkin_store_pipeline_encode_seconds",
            "Stage 1 per apply/write_thrift call: columnar encode + "
            "index bits + pow2 padding (outside the write lock)"))
        self.h_stage = reg.register(obs.LatencySketch(
            "zipkin_store_pipeline_stage_seconds",
            "Stage 2 per unit: H2D device_put of the padded batch"))
        self.h_commit = reg.register(obs.LatencySketch(
            "zipkin_store_pipeline_commit_seconds",
            "Stage 3 per unit: capture trigger + donating dispatch "
            "under the write lock"))
        self.g_depth = reg.register(obs.Gauge(
            "zipkin_store_pipeline_prefetch_depth",
            "Padded units waiting in the ingest prefetch queue",
            fn=lambda: float(self._prefetch.qsize())))
        self.c_stall = reg.register(obs.Counter(
            "zipkin_store_pipeline_stall_seconds_total",
            "Seconds writers blocked on a full prefetch queue "
            "(pipeline backpressure)"))
        self.c_units = reg.register(obs.Counter(
            "zipkin_store_pipeline_units_total",
            "Launch units fed through the ingest pipeline"))
        self._stager = threading.Thread(
            target=self._stage_loop, name="zipkin-ingest-stage",
            daemon=True)
        self._committer = threading.Thread(
            target=self._commit_loop, name="zipkin-ingest-commit",
            daemon=True)
        self._stager.start()
        self._committer.start()

    # -- stage 1 tail (caller threads) ----------------------------------

    def feed(self, unit: IngestUnit) -> float:
        """Enqueue one padded unit; blocks when the prefetch queue is
        full (the designed writer backpressure). Returns the seconds
        spent blocked so stage-1 timing can exclude them."""
        self._check_feedable()
        # Only a put against an already-full queue is backpressure;
        # elapsed time on a non-full put is just lock contention and
        # must not read as a stall on a loaded machine.
        full = self._prefetch.full()
        t0 = time.perf_counter()
        self._prefetch.put(unit)
        stall = (time.perf_counter() - t0) if full else 0.0
        if stall > 1e-4:
            self.c_stall.inc(stall)
        self.c_units.inc()
        return stall

    # -- stage 2: H2D staging -------------------------------------------

    def _stage_loop(self) -> None:
        while True:
            item = self._prefetch.get()
            if item is _STOP:
                self._staged.put(_STOP)
                return
            try:
                t0 = time.perf_counter()
                item = item._replace(db=self._stage(item.db))
                self.h_stage.observe(time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001 — parked, re-raised
                self._park_error(e)
                self._mark_done()  # drop this unit; keep flowing
                continue
            self._staged.put(item)

    # -- stage 3: commit ------------------------------------------------

    def _commit_loop(self) -> None:
        store = self._store
        while True:
            item = self._staged.get()
            if item is _STOP:
                return
            try:
                t0 = time.perf_counter()
                store._commit_unit(item)
                self.h_commit.observe(time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001 — parked, re-raised
                # This unit's spans are dropped (mirrors untouched, so
                # ring invariants hold and a failed capture pull is
                # retried by the next unit's trigger) — the same cost a
                # serial per-batch failure has.
                self._park_error(e)
            finally:
                self._mark_done()

    # -- lifecycle ------------------------------------------------------

    def stop(self) -> None:
        """Drain (best effort), stop both workers, unregister gauges.
        Never raises — callers that care about a parked error read
        ``.error`` (TpuSpanStore.stop_pipeline re-raises it)."""
        with self._cond:
            self._closed = True
        self._wait_idle()
        self._prefetch.put(_STOP)
        self._stager.join(timeout=30.0)
        self._committer.join(timeout=30.0)
        self._unregister(self._registry, (
            self.h_encode, self.h_stage, self.h_commit, self.g_depth,
            self.c_stall, self.c_units,
        ))

    def queued(self) -> int:
        return self._prefetch.qsize() + self._staged.qsize()


class EvictionSealer(_StageBase):
    """Background seal stage for eviction capture: D2H fetch + deflate
    + directory append off the write path. The capture PULL stays
    synchronous in ``TpuSpanStore._capture_window`` (ordering
    invariant); this thread only ever touches capture OUTPUT arrays,
    which no ingest step donates — so it needs no store lock."""

    def __init__(self, store, backlog: int = 4, registry=None):
        from zipkin_tpu import obs

        super().__init__()
        self._store = store
        self.backlog = max(1, int(backlog))
        self._q: "queue.Queue" = queue.Queue(maxsize=self.backlog)
        reg = registry or obs.default_registry()
        self._registry = reg
        self.g_backlog = reg.register(obs.Gauge(
            "zipkin_store_capture_backlog",
            "Pulled-but-unsealed eviction capture windows in flight",
            fn=lambda: float(self._q.qsize())))
        self.c_stall = reg.register(obs.Counter(
            "zipkin_store_capture_stall_seconds_total",
            "Seconds the write path blocked on a full capture-seal "
            "backlog (sealer backpressure)"))
        self.c_sealed = reg.register(obs.Counter(
            "zipkin_store_capture_windows_sealed_total",
            "Capture windows sealed into cold segments"))
        self.c_errors = reg.register(obs.Counter(
            "zipkin_store_capture_seal_errors_total",
            "Capture windows whose async seal failed (window lost "
            "from the cold tier; error re-raised on the write path)"))
        self._worker = threading.Thread(
            target=self._loop, name="zipkin-capture-seal", daemon=True)
        self._worker.start()

    def submit(self, n_s: int, n_a: int, n_b: int,
               s_m, a_m, b_m, lo: int, hi: int,
               pull_s: float) -> None:
        """Hand one pulled window (device-resident row matrices) to
        the sealer. Blocks when ``backlog`` windows are in flight —
        the ONLY way capture can stall ingest. Raises a parked error
        from an earlier failed seal (matching the inline path, where a
        sink failure surfaced on the write path that triggered it)."""
        self._check_feedable()
        full = self._q.full()  # see IngestPipeline.feed: full-at-entry
        t0 = time.perf_counter()
        self._q.put((n_s, n_a, n_b, s_m, a_m, b_m, lo, hi, pull_s))
        stall = time.perf_counter() - t0
        if full and stall > 1e-4:
            self.c_stall.inc(stall)

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            try:
                self._seal(item)
                self.c_sealed.inc()
            except BaseException as e:  # noqa: BLE001 — parked, re-raised
                # The window is LOST from the cold tier (its rows may
                # already be overwritten in the rings) — counted, and
                # the error fails the next write/barrier ONCE; later
                # windows still seal. _sealed_upto is not advanced, so
                # a checkpoint cut never claims the hole.
                self.c_errors.inc()
                self._park_error(e)
            finally:
                self._mark_done()

    def _seal(self, item) -> None:
        from zipkin_tpu.store.tpu import mats_to_batch

        n_s, n_a, n_b, s_m, a_m, b_m, lo, hi, pull_s = item
        t0 = time.perf_counter()
        host = jax.device_get((s_m, a_m, b_m))
        batch, gids = mats_to_batch(n_s, n_a, n_b, *host)
        sink = self._store.eviction_sink
        if sink is None:
            # Sink detached with windows still in flight: no segment
            # was written, so the frontier must NOT advance — leaving
            # the hole visible keeps a later checkpoint cut from
            # claiming a window the cold tier never got.
            return
        from zipkin_tpu.testing.crash import kill_point

        kill_point("mid-seal")
        sink(batch, gids, lo, hi,
             pull_s + (time.perf_counter() - t0))
        self._store._note_sealed(lo, hi)

    def stop(self) -> None:
        """Seal everything in flight, then stop. Never raises."""
        with self._cond:
            self._closed = True
        self._wait_idle()
        self._q.put(_STOP)
        self._worker.join(timeout=30.0)
        self._unregister(self._registry, (
            self.g_backlog, self.c_stall, self.c_sealed, self.c_errors,
        ))

    def queued(self) -> int:
        return self._q.qsize()

    def at_capacity(self) -> bool:
        """True when the in-flight window queue is full — the next
        capture submit will stall the write path (the watchdog's
        sealer-backlog signal)."""
        return self._q.qsize() >= self.backlog
