"""Cold-tier segment store: capture ring evictions into immutable
compressed columnar segments with sketch zone-maps, and federate
queries across the hot device ring and the cold segments.

The reference keeps the full TTL window in Cassandra behind the same
SpanStore trait; the TPU build's device ring holds ~2^22 rows and laps
hundreds of times during a 1B-span run — every overwritten span was
gone forever. This package is the TPU-native equivalent of the warm
backend: a host-side tier built from the repo's own mergeable sketches
(per-segment moment/quantile summaries in the spirit of
arXiv:1803.01969; time/space sketch disaggregation, arXiv:2503.13515),
so cold segments answer aggregate and pruning questions without
decompressing rows.

- ``sketches`` — numpy twins of the ops/ hash + sketch primitives
  (bloom / CMS / HLL / log-histogram), all mergeable monoids.
- ``segment`` — the immutable segment format: deflate-compressed
  SpanBatch column planes + a zone-map header.
- ``directory`` — the segment list + the background compactor that
  merges small segments (zone maps merge monoidally, no re-scan).
- ``tiered`` — ``TieredSpanStore``: the full SpanStore SPI over
  hot ring + cold segments, pruning segments by zone-map before any
  row decode.
"""

from zipkin_tpu.store.archive.directory import (  # noqa: F401
    ArchiveParams,
    SegmentDirectory,
)
from zipkin_tpu.store.archive.segment import (  # noqa: F401
    Segment,
    ZoneMap,
    merge_segments,
    seal_segment,
)
from zipkin_tpu.store.archive.tiered import TieredSpanStore  # noqa: F401

__all__ = [
    "ArchiveParams",
    "Segment",
    "SegmentDirectory",
    "TieredSpanStore",
    "ZoneMap",
    "merge_segments",
    "seal_segment",
]
