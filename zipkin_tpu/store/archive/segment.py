"""Immutable compressed columnar segments + sketch zone-maps.

A segment is the cold-tier unit: the SpanBatch column planes of one
capture window (plus the per-row global ids), deflate-compressed per
column, under a zone-map header built from the repo's sketch
primitives. The zone map answers "can this segment possibly contain a
match?" without touching the compressed rows:

- ``ts_last_min``/``ts_last_max`` — index queries filter on a span's
  last timestamp (`<= end_ts`), so a segment whose minimum valid last
  timestamp exceeds ``end_ts`` can be skipped outright.
- ``service_ids`` — exact set of (annotation-host) service ids present;
  the bitmap role, exact because the dictionary keeps ids dense.
- ``key_cms`` — one count-min over tagged (service, key) pairs: span
  names, user annotation values, binary keys, (binary key, value)
  pairs. CMS never under-counts, so a zero is a proof of absence.
- ``trace_bloom`` — trace-id membership (no false negatives).
- ``hll`` — distinct trace ids (cold-tier cardinality telemetry).
- ``dur_hist`` — per-service duration log-histograms in the exact
  ops.quantile geometry of the device svc_hist, so hot and cold rows
  merge by ``+`` and quantiles read through the same
  ``quantiles_host``.

All header parts are monoids (OR / + / max / set-union / min-max), so
the compactor merges zone maps without re-scanning rows. Segments are
immutable once sealed; ``to_bytes``/``from_bytes`` give the durable
form the checkpoint manifest references. The ``dict_sizes`` high-water
tuple records how much of each shared dictionary the segment's ids
reference — the "dictionary delta" boundary a restore validates
(dictionaries are append-only, so ids below the mark decode forever).
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from zipkin_tpu.columnar.encode import _norm_value
from zipkin_tpu.columnar.schema import SpanBatch
from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.ops.hashing import np_mix_keys64
from zipkin_tpu.store.archive import sketches as SK

_MAGIC = b"ZSEG1"
_DEFLATE_LEVEL = 1  # same tradeoff as checkpoint._savez_fast

# Zone-key tags: one CMS, four key spaces.
TAG_NAME = 1  # (service, lowercased span-name id)
TAG_ANN = 2  # (service, user annotation value id)
TAG_BKEY = 3  # (service, binary key id)
TAG_BVAL = 4  # (service, binary key id, binary value id)

_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)

_COLS: Tuple[str, ...] = (
    SpanBatch.SPAN_COLUMNS + SpanBatch.ANN_COLUMNS
    + SpanBatch.BANN_COLUMNS
)


def zone_key(tag: int, svc: int, a: int, b: int = 0) -> np.ndarray:
    """Tagged key tuple → well-dispersed int64 (np_mix_keys64 so any
    future device-side probe of the same tuple hashes identically)."""
    return np_mix_keys64([
        np.asarray([tag], np.int64), np.asarray([svc], np.int64),
        np.asarray([a], np.int64), np.asarray([b], np.int64),
    ]).view(np.int64)


@dataclass(frozen=True)
class ZoneMap:
    # ts_first_min is informational time-range metadata (segment
    # inspection, and the lower bound a future start_ts-windowed query
    # would prune on); today's SpanStore surface filters only on
    # end_ts, so the active time probe is may_match_end_ts below.
    ts_first_min: int
    ts_last_min: int
    ts_last_max: int
    service_ids: frozenset
    key_cms: np.ndarray  # [depth, width] int32
    trace_bloom: np.ndarray  # [bits/8] uint8
    hll: np.ndarray  # [2^p] int32
    dur_hist: Dict[int, np.ndarray]  # svc_id -> [B] int64
    hist_gamma: float
    hist_buckets: int

    def merge(self, other: "ZoneMap") -> "ZoneMap":
        """Monoidal merge — the compactor's whole zone-map cost."""
        assert self.key_cms.shape == other.key_cms.shape
        assert self.trace_bloom.shape == other.trace_bloom.shape
        assert self.hll.shape == other.hll.shape
        assert (self.hist_gamma == other.hist_gamma
                and self.hist_buckets == other.hist_buckets)
        hist = {k: v.copy() for k, v in self.dur_hist.items()}
        for k, v in other.dur_hist.items():
            if k in hist:
                hist[k] = hist[k] + v
            else:
                hist[k] = v.copy()
        return ZoneMap(
            ts_first_min=min(self.ts_first_min, other.ts_first_min),
            ts_last_min=min(self.ts_last_min, other.ts_last_min),
            ts_last_max=max(self.ts_last_max, other.ts_last_max),
            service_ids=self.service_ids | other.service_ids,
            key_cms=SK.cms_merge(self.key_cms, other.key_cms),
            trace_bloom=SK.bloom_merge(self.trace_bloom,
                                       other.trace_bloom),
            hll=SK.hll_merge(self.hll, other.hll),
            dur_hist=hist,
            hist_gamma=self.hist_gamma,
            hist_buckets=self.hist_buckets,
        )

    # -- pruning probes (False == provably no match) --------------------

    def may_contain_trace(self, tid: int) -> bool:
        return SK.bloom_contains(self.trace_bloom, tid)

    def may_contain_key(self, tag: int, svc: int, a: int,
                        b: int = 0) -> bool:
        return SK.cms_query(self.key_cms, int(zone_key(tag, svc, a,
                                                       b)[0])) > 0

    def may_match_end_ts(self, end_ts: int) -> bool:
        """Index queries require span.last_ts <= end_ts; if even the
        SMALLEST valid last timestamp exceeds it, nothing matches."""
        return self.ts_last_min <= end_ts


@dataclass(frozen=True)
class Segment:
    seg_id: int
    gid_lo: int
    gid_hi: int  # capture range [gid_lo, gid_hi) this segment covers
    n_spans: int
    n_anns: int
    n_banns: int
    zone: ZoneMap
    cols: Dict[str, bytes]  # column name -> deflate blob (incl "gids")
    col_meta: Dict[str, Tuple[str, int]]  # name -> (dtype str, length)
    dict_sizes: Tuple[int, ...]  # dictionary high-water marks at seal
    raw_bytes: int
    comp_bytes: int

    def column(self, name: str) -> np.ndarray:
        """Decompress ONE column plane — membership probes and other
        single-column reads pay one zlib stream, not a row decode."""
        dtype, n = self.col_meta[name]
        return np.frombuffer(
            zlib.decompress(self.cols[name]), np.dtype(dtype)
        )[:n].copy()

    def decode(self) -> Tuple[SpanBatch, np.ndarray]:
        """(SpanBatch, gids) — the full row-decompression path."""
        batch = SpanBatch(**{c: self.column(c) for c in _COLS})
        return batch, self.column("gids")

    # -- durable form ---------------------------------------------------

    def to_bytes(self) -> bytes:
        header = {
            "seg_id": self.seg_id, "gid_lo": self.gid_lo,
            "gid_hi": self.gid_hi, "n_spans": self.n_spans,
            "n_anns": self.n_anns, "n_banns": self.n_banns,
            "dict_sizes": list(self.dict_sizes),
            "raw_bytes": self.raw_bytes, "comp_bytes": self.comp_bytes,
            "zone": {
                "ts_first_min": self.zone.ts_first_min,
                "ts_last_min": self.zone.ts_last_min,
                "ts_last_max": self.zone.ts_last_max,
                "service_ids": sorted(self.zone.service_ids),
                "hist_gamma": self.zone.hist_gamma,
                "hist_buckets": self.zone.hist_buckets,
                "cms_shape": list(self.zone.key_cms.shape),
                "hll_size": int(self.zone.hll.size),
                "bloom_bytes": int(self.zone.trace_bloom.size),
                "hist_svcs": sorted(self.zone.dur_hist),
            },
            "col_meta": {k: [v[0], v[1]]
                         for k, v in self.col_meta.items()},
            "col_order": sorted(self.cols),
        }
        hdr = json.dumps(header).encode("utf-8")
        parts = [_MAGIC, struct.pack(">I", len(hdr)), hdr]
        # Zone arrays ride as deflate blobs after the header, in a
        # fixed order, each length-prefixed.
        zone_blobs = [
            zlib.compress(np.ascontiguousarray(a).tobytes(),
                          _DEFLATE_LEVEL)
            for a in (
                self.zone.key_cms, self.zone.trace_bloom, self.zone.hll,
                *[self.zone.dur_hist[s]
                  for s in sorted(self.zone.dur_hist)],
            )
        ]
        for blob in zone_blobs:
            parts.append(struct.pack(">I", len(blob)))
            parts.append(blob)
        for name in header["col_order"]:
            blob = self.cols[name]
            parts.append(struct.pack(">I", len(blob)))
            parts.append(blob)
        return b"".join(parts)

    @staticmethod
    def from_bytes(data: bytes) -> "Segment":
        if data[:5] != _MAGIC:
            raise ValueError("not a segment blob")
        (hlen,) = struct.unpack(">I", data[5:9])
        header = json.loads(data[9:9 + hlen].decode("utf-8"))
        off = 9 + hlen

        def blob():
            nonlocal off
            (n,) = struct.unpack(">I", data[off:off + 4])
            off += 4
            b = data[off:off + n]
            off += n
            return b

        z = header["zone"]
        depth, width = z["cms_shape"]
        cms = np.frombuffer(zlib.decompress(blob()),
                            np.int32).reshape(depth, width).copy()
        bloom = np.frombuffer(zlib.decompress(blob()), np.uint8).copy()
        hll = np.frombuffer(zlib.decompress(blob()), np.int32).copy()
        hist = {}
        for svc in z["hist_svcs"]:
            hist[int(svc)] = np.frombuffer(
                zlib.decompress(blob()), np.int64).copy()
        cols = {name: blob() for name in header["col_order"]}
        zone = ZoneMap(
            ts_first_min=z["ts_first_min"],
            ts_last_min=z["ts_last_min"], ts_last_max=z["ts_last_max"],
            service_ids=frozenset(z["service_ids"]),
            key_cms=cms, trace_bloom=bloom, hll=hll, dur_hist=hist,
            hist_gamma=z["hist_gamma"], hist_buckets=z["hist_buckets"],
        )
        return Segment(
            seg_id=header["seg_id"], gid_lo=header["gid_lo"],
            gid_hi=header["gid_hi"], n_spans=header["n_spans"],
            n_anns=header["n_anns"], n_banns=header["n_banns"],
            zone=zone, cols=cols,
            col_meta={k: (v[0], v[1])
                      for k, v in header["col_meta"].items()},
            dict_sizes=tuple(header["dict_sizes"]),
            raw_bytes=header["raw_bytes"],
            comp_bytes=header["comp_bytes"],
        )


def _zone_from_rows(batch: SpanBatch, gids: np.ndarray, spans, dicts,
                    params) -> ZoneMap:
    """Build a zone map from one capture window.

    Column-plane parts (bloom / HLL / ts range / per-service duration
    histograms) come straight from the columns; the key CMS needs each
    span's full service set crossed with its names/annotations (the
    memory-oracle match rule: a span matches under ANY of its
    annotation-host services), which is exact only from the decoded
    spans — the caller decodes once and shares the spans with the
    query cache.
    """
    cms = SK.cms_init(params.cms_depth, params.cms_width)
    bloom = SK.bloom_init(params.bloom_bits)
    hll = SK.hll_init(params.hll_p)
    SK.bloom_add(bloom, batch.trace_id)
    SK.hll_add(hll, batch.trace_id)
    tsf = batch.ts_first[batch.ts_first >= 0]
    tsl = batch.ts_last[batch.ts_last >= 0]
    hist: Dict[int, np.ndarray] = {}
    dur_ok = (batch.service_id >= 0) & (batch.duration >= 0)
    for svc in np.unique(batch.service_id[dur_ok]):
        row = np.zeros(params.hist_buckets, np.int64)
        SK.hist_add(row, batch.duration[dur_ok
                                        & (batch.service_id == svc)],
                    params.hist_gamma)
        hist[int(svc)] = row
    svc_ids = set(int(s) for s in np.unique(batch.service_id)
                  if s >= 0)
    keys: List[int] = []
    for span in spans:
        svcs = [dicts.services.get(n) for n in span.service_names]
        svcs = [s for s in svcs if s is not None]
        svc_ids.update(svcs)
        if not svcs:
            continue
        name_lc = (dicts.span_names.get(span.name.lower())
                   if span.name else None)
        ann_vals = {dicts.annotations.get(a.value)
                    for a in span.annotations
                    if a.value not in CORE_ANNOTATIONS}
        bkeys = {}
        for b in span.binary_annotations:
            kid = dicts.binary_keys.get(b.key)
            if kid is None:
                continue
            vid = dicts.binary_values.get(
                _norm_value(b.value, b.annotation_type))
            bkeys.setdefault(kid, set()).add(vid)
        for svc in svcs:
            if name_lc is not None:
                keys.append(int(zone_key(TAG_NAME, svc, name_lc)[0]))
            for av in ann_vals:
                if av is not None:
                    keys.append(int(zone_key(TAG_ANN, svc, av)[0]))
            for kid, vids in bkeys.items():
                keys.append(int(zone_key(TAG_BKEY, svc, kid)[0]))
                for vid in vids:
                    if vid is not None:
                        keys.append(int(zone_key(TAG_BVAL, svc, kid,
                                                 vid)[0]))
    SK.cms_add(cms, np.asarray(keys, np.int64))
    return ZoneMap(
        ts_first_min=int(tsf.min()) if tsf.size else _I64_MAX,
        ts_last_min=int(tsl.min()) if tsl.size else _I64_MAX,
        ts_last_max=int(tsl.max()) if tsl.size else _I64_MIN,
        service_ids=frozenset(svc_ids),
        key_cms=cms, trace_bloom=bloom, hll=hll, dur_hist=hist,
        hist_gamma=params.hist_gamma, hist_buckets=params.hist_buckets,
    )


def _compress_cols(batch: SpanBatch, gids: np.ndarray):
    cols: Dict[str, bytes] = {}
    meta: Dict[str, Tuple[str, int]] = {}
    raw = comp = 0
    for name in _COLS + ("gids",):
        arr = gids if name == "gids" else getattr(batch, name)
        arr = np.ascontiguousarray(arr)
        blob = zlib.compress(arr.tobytes(), _DEFLATE_LEVEL)
        cols[name] = blob
        meta[name] = (arr.dtype.str, int(arr.shape[0]))
        raw += arr.nbytes
        comp += len(blob)
    return cols, meta, raw, comp


def seal_segment(seg_id: int, batch: SpanBatch, gids: np.ndarray,
                 spans, dicts, params, gid_lo: int,
                 gid_hi: int) -> Segment:
    """Freeze one capture window into an immutable segment."""
    cols, meta, raw, comp = _compress_cols(batch, gids)
    zone = _zone_from_rows(batch, gids, spans, dicts, params)
    return Segment(
        seg_id=seg_id, gid_lo=gid_lo, gid_hi=gid_hi,
        n_spans=batch.n_spans, n_anns=batch.n_annotations,
        n_banns=batch.n_binary, zone=zone, cols=cols, col_meta=meta,
        dict_sizes=(len(dicts.services), len(dicts.span_names),
                    len(dicts.annotations), len(dicts.binary_keys),
                    len(dicts.binary_values), len(dicts.endpoints)),
        raw_bytes=raw, comp_bytes=comp,
    )


def merge_segments(seg_id: int, segs: Sequence[Segment]) -> Segment:
    """Compaction merge: concat rows (span_idx refs rebased by
    SpanBatch.concat), merge zone maps MONOIDALLY — no re-scan of span
    objects, the whole point of mergeable sketch headers."""
    assert len(segs) >= 2
    segs = sorted(segs, key=lambda s: s.gid_lo)
    batch, gids = segs[0].decode()
    zone = segs[0].zone
    for s in segs[1:]:
        b2, g2 = s.decode()
        batch = batch.concat(b2)
        gids = np.concatenate([gids, g2])
        zone = zone.merge(s.zone)
    cols, meta, raw, comp = _compress_cols(batch, gids)
    return Segment(
        seg_id=seg_id, gid_lo=segs[0].gid_lo, gid_hi=segs[-1].gid_hi,
        n_spans=batch.n_spans, n_anns=batch.n_annotations,
        n_banns=batch.n_binary, zone=zone, cols=cols, col_meta=meta,
        dict_sizes=tuple(max(t) for t in zip(*[s.dict_sizes
                                               for s in segs])),
        raw_bytes=raw, comp_bytes=comp,
    )
