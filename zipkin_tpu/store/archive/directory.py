"""Segment directory + background compactor.

The directory owns the cold tier's segment list (sorted by gid range,
immutable entries), the decoded-row cache the query paths share, and
the compaction policy: whenever ``compact_fanin`` consecutive segments
are each below ``small_span_limit`` rows, the oldest such run merges
into one (rows concatenated, zone maps merged monoidally) — the
log-structured size-tiering that keeps the per-query segment count
O(log total) instead of O(captures). Compaction runs inline after each
append by default (deterministic for tests); ``start_compactor()``
moves it to a background thread for deployments where capture latency
matters.

Telemetry rides the obs registry: segments written / compacted /
pruned counters, live-segment and cold-span gauges, and a cold-scan
latency sketch the tiered reads observe into.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, NamedTuple, Optional

from zipkin_tpu.store.archive.segment import Segment, merge_segments


class ArchiveParams(NamedTuple):
    """Fixed sketch geometry — merge requires equal shapes, so the
    directory pins these for its lifetime (checkpoint restores them).

    ``hist_gamma``/``hist_buckets`` default to the device svc_hist
    geometry (StoreConfig.quantile_alpha/quantile_buckets) via
    ``ArchiveParams.for_config`` so hot and cold histogram rows merge
    by ``+``."""

    bloom_bits: int = 1 << 16
    cms_depth: int = 4
    cms_width: int = 1 << 12
    hll_p: int = 10
    hist_buckets: int = 2048
    hist_gamma: float = (1.0 + 0.01) / (1.0 - 0.01)
    compact_fanin: int = 4
    small_span_limit: int = 1 << 18

    @staticmethod
    def for_config(config, **overrides) -> "ArchiveParams":
        gamma = (1.0 + config.quantile_alpha) / (1.0 - config.quantile_alpha)
        base = ArchiveParams(
            hist_buckets=config.quantile_buckets, hist_gamma=gamma,
            # Small segments arrive every half ring; merge them until
            # they pass ~2 ring turns of rows.
            small_span_limit=max(2 * config.capacity, 1024),
        )
        return base._replace(**overrides)


class SegmentDirectory:
    # Decoded (batch, gids, spans) cached for the most recent segments
    # a query touched — cold reads decode a segment at most once per
    # generation of the cache. Bounded by COUNT and by (approximate)
    # BYTES: at production geometry one compacted segment decodes to
    # multi-GB of rows + Span objects, so an entry-count bound alone
    # would quietly pin several of those in host memory.
    DECODE_CACHE = 8
    DECODE_CACHE_BYTES = 256 << 20

    def __init__(self, params: ArchiveParams, codec,
                 registry=None):
        from zipkin_tpu import obs

        self.params = params
        self.codec = codec
        self._lock = threading.Lock()  # lock-order: 55 archive-dir
        self._segments: List[Segment] = []
        self._next_id = 0  # guarded-by: _lock
        self._decoded: Dict[int, tuple] = {}
        self._compactor: Optional[threading.Thread] = None
        self._compactor_stop = threading.Event()
        reg = registry or obs.default_registry()
        self._registry = reg
        self.c_written = reg.register(obs.Counter(
            "zipkin_archive_segments_written_total",
            "Cold-tier segments sealed from eviction captures"))
        self.c_compacted = reg.register(obs.Counter(
            "zipkin_archive_compactions_total",
            "Compaction merges executed (N small segments -> 1)"))
        self.c_pruned = reg.register(obs.Counter(
            "zipkin_archive_segments_pruned_total",
            "Segments skipped by zone-map pruning before row decode"))
        self.g_live = reg.register(obs.Gauge(
            "zipkin_archive_segments_live",
            "Segments currently in the directory",
            fn=lambda: float(len(self._segments))))
        self.g_cold_spans = reg.register(obs.Gauge(
            "zipkin_archive_cold_spans",
            "Span rows held by the cold tier",
            fn=self._cold_spans))
        self.h_cold_query = reg.register(obs.LatencySketch(
            "zipkin_archive_cold_query_seconds",
            "Cold-tier scan latency per federated read"))
        self.h_capture = reg.register(obs.LatencySketch(
            "zipkin_archive_capture_seconds",
            "Eviction capture latency (device pull + seal)"))

    # -- bookkeeping ----------------------------------------------------

    def close(self) -> None:
        """Unregister this directory's metrics: gauge closures hold the
        directory alive and a later directory's registration would
        otherwise silently shadow a dead one's counters (the registry
        is last-wins)."""
        for m in (self.c_written, self.c_compacted, self.c_pruned,
                  self.g_live, self.g_cold_spans, self.h_cold_query,
                  self.h_capture):
            # Only drop the registration if it is still OURS — a newer
            # directory may have re-registered the name already.
            if self._registry.get(m.name) is m:
                self._registry.unregister(m.name)

    def _cold_spans(self) -> float:
        return float(sum(s.n_spans for s in self._segments))

    def next_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id - 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._segments)

    def snapshot(self) -> List[Segment]:
        with self._lock:
            return list(self._segments)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            segs = list(self._segments)
        return {
            "archive_segments_live": float(len(segs)),
            "archive_segments_written": float(self.c_written.value),
            "archive_compactions": float(self.c_compacted.value),
            "archive_segments_pruned": float(self.c_pruned.value),
            "archive_cold_spans": float(sum(s.n_spans for s in segs)),
            "archive_cold_bytes": float(sum(s.comp_bytes for s in segs)),
            "archive_cold_raw_bytes": float(sum(s.raw_bytes
                                                for s in segs)),
        }

    # -- mutation -------------------------------------------------------

    def append(self, segment: Segment, cache: Optional[tuple] = None
               ) -> None:
        """Add a freshly sealed segment (sorted by gid range) and run
        one inline compaction pass unless a background compactor owns
        that job. ``cache`` optionally pre-seeds the decode cache with
        the (batch, gids, spans) the sealer already materialized."""
        with self._lock:
            self._segments.append(segment)
            self._segments.sort(key=lambda s: s.gid_lo)
            if cache is not None:
                self._cache_put(segment.seg_id, cache,
                                3 * segment.raw_bytes)
        self.c_written.inc()
        if self._compactor is None:
            self.compact_once()

    def restore(self, segments: List[Segment], next_id: int) -> None:
        """Checkpoint restore: adopt an already-built segment list."""
        with self._lock:
            self._segments = sorted(segments, key=lambda s: s.gid_lo)
            self._next_id = next_id
            self._decoded.clear()

    def drop_below(self, gid_upto: int) -> int:
        """Drop whole segments whose rows all precede ``gid_upto`` —
        the read replica's retention bound (store/replica.py). The
        tiered primary never calls this: its cold tier IS the
        retention. Returns the number of segments dropped."""
        with self._lock:
            dropped = [s for s in self._segments if s.gid_hi <= gid_upto]
            if not dropped:
                return 0
            self._segments = [s for s in self._segments
                              if s.gid_hi > gid_upto]
            for s in dropped:
                self._decoded.pop(s.seg_id, None)
        return len(dropped)

    # -- compaction -----------------------------------------------------

    def _find_run(self) -> Optional[List[Segment]]:
        p = self.params
        run: List[Segment] = []
        for seg in self._segments:
            if seg.n_spans <= p.small_span_limit:
                run.append(seg)
                if len(run) >= p.compact_fanin:
                    return run
            else:
                run = []
        return None

    def compact_once(self) -> bool:
        """Merge one run of small segments; True if a merge happened."""
        with self._lock:
            run = self._find_run()
            if run is None:
                return False
            seg_id = self._next_id
            self._next_id += 1
        # Merge OUTSIDE the lock (decompress + recompress is the bulk
        # of the work); immutability makes the stale-read window safe —
        # the replace below re-checks membership.
        merged = merge_segments(seg_id, run)
        with self._lock:
            ids = {s.seg_id for s in run}
            if not ids.issubset({s.seg_id for s in self._segments}):
                return False  # lost a race with another compactor pass
            self._segments = [s for s in self._segments
                              if s.seg_id not in ids]
            self._segments.append(merged)
            self._segments.sort(key=lambda s: s.gid_lo)
            for sid in ids:
                self._decoded.pop(sid, None)
        self.c_compacted.inc()
        return True

    def start_compactor(self, interval_s: float = 1.0) -> None:
        """Move compaction to a background thread (deployment mode)."""
        if self._compactor is not None:
            return

        def loop():
            while not self._compactor_stop.wait(interval_s):
                while self.compact_once():
                    pass

        self._compactor = threading.Thread(target=loop, daemon=True)
        self._compactor.start()

    def stop_compactor(self) -> None:
        if self._compactor is None:
            return
        self._compactor_stop.set()
        self._compactor.join(timeout=5.0)
        self._compactor = None
        self._compactor_stop.clear()

    # -- decoded-row cache ----------------------------------------------

    def _cache_put(self, seg_id: int, value: tuple,
                   nbytes: int) -> None:
        self._decoded[seg_id] = (value, nbytes)
        while len(self._decoded) > 1 and (
                len(self._decoded) > self.DECODE_CACHE
                or sum(b for _, b in self._decoded.values())
                > self.DECODE_CACHE_BYTES):
            self._decoded.pop(next(iter(self._decoded)))

    def decoded(self, segment: Segment) -> tuple:
        """(SpanBatch, gids, List[Span]) for a segment, cached."""
        with self._lock:
            got = self._decoded.get(segment.seg_id)
            if got is not None:
                return got[0]
        batch, gids = segment.decode()
        spans = self.codec.decode(batch)
        value = (batch, gids, spans)
        with self._lock:
            # Span objects cost a few x the column bytes; 3x raw is a
            # serviceable estimate for the bound's purpose.
            self._cache_put(segment.seg_id, value,
                            3 * segment.raw_bytes)
        return value

    # -- pruning helper -------------------------------------------------

    def pruned_scan(self, probe: Callable[[Segment], bool]
                    ) -> List[Segment]:
        """Segments surviving ``probe`` (True = may match); skipped
        segments count into the pruning telemetry."""
        out = []
        for seg in self.snapshot():
            if probe(seg):
                out.append(seg)
            else:
                self.c_pruned.inc()
        return out
