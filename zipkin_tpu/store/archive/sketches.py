"""Host (numpy) twins of the ops/ sketch primitives for zone maps.

Segments are sealed on the host from already-pulled columns, so their
zone-map sketches run in numpy — but with the SAME hash family as the
device sketches (murmur3 fmix32 composed per seed, ops/hashing.py) and
the same bucket math as ops.quantile, so values and failure modes stay
familiar and a host sketch could be folded against a device one where
geometries match. Every sketch here is a monoid:

- bloom: bitwise OR          - CMS: elementwise +
- HLL: elementwise max       - log-histogram: elementwise +

which is exactly what the compactor needs to merge segment headers
without re-scanning rows.
"""

from __future__ import annotations

import math

import numpy as np

from zipkin_tpu.ops.hashing import split64

_U32 = np.uint32
GOLDEN32 = _U32(0x9E3779B9)


def np_fmix32(h: np.ndarray) -> np.ndarray:
    """murmur3 finalizer on uint32 arrays — bit-identical to
    ops.hashing.fmix32."""
    h = np.asarray(h, _U32)
    with np.errstate(over="ignore"):
        h = h ^ (h >> _U32(16))
        h = h * _U32(0x85EBCA6B)
        h = h ^ (h >> _U32(13))
        h = h * _U32(0xC2B2AE35)
        h = h ^ (h >> _U32(16))
    return h


def np_hash2_32(hi, lo, seed: int) -> np.ndarray:
    """Seeded 64→32-bit hash — bit-identical to ops.hashing.hash2_32."""
    with np.errstate(over="ignore"):
        s = _U32(seed) * GOLDEN32 + _U32(1)
        h = np_fmix32(np.asarray(lo, _U32) ^ s)
        h = np_fmix32(h ^ np.asarray(hi, _U32) ^ (s * _U32(0x85EBCA6B)))
    return h


def np_clz32(x: np.ndarray) -> np.ndarray:
    """Leading zeros of uint32 (vectorized) — twin of ops.hashing.clz32."""
    x = np.asarray(x, _U32)
    n = np.zeros(x.shape, np.int32)
    zero = x == 0
    with np.errstate(over="ignore"):
        for bits, mask in ((16, 0xFFFF0000), (8, 0xFF000000),
                           (4, 0xF0000000), (2, 0xC0000000),
                           (1, 0x80000000)):
            hi_clear = (x & _U32(mask)) == 0
            n = np.where(hi_clear, n + bits, n)
            x = np.where(hi_clear, x << _U32(bits), x)
    return np.where(zero, np.int32(32), n)


# -- bloom filter (trace-id membership) -------------------------------------


def bloom_init(n_bits: int) -> np.ndarray:
    assert n_bits % 8 == 0 and n_bits & (n_bits - 1) == 0
    return np.zeros(n_bits // 8, np.uint8)


BLOOM_HASHES = 4


def _bloom_indices(keys: np.ndarray, n_bits: int) -> np.ndarray:
    """[BLOOM_HASHES, n] bit indices via double hashing (h1 + i*h2)."""
    hi, lo = split64(np.asarray(keys, np.int64))
    h1 = np_hash2_32(hi, lo, 11)
    h2 = np_hash2_32(hi, lo, 12) | _U32(1)
    rows = np.arange(BLOOM_HASHES, dtype=_U32)[:, None]
    with np.errstate(over="ignore"):
        return ((h1[None, :] + rows * h2[None, :])
                & _U32(n_bits - 1)).astype(np.int64)


def bloom_add(bits: np.ndarray, keys) -> None:
    """In-place add (builders only touch unsealed arrays)."""
    keys = np.asarray(keys, np.int64)
    if keys.size == 0:
        return
    idx = _bloom_indices(keys, bits.size * 8).reshape(-1)
    np.bitwise_or.at(bits, idx >> 3,
                     (np.uint8(1) << (idx & 7).astype(np.uint8)))


def bloom_contains(bits: np.ndarray, key: int) -> bool:
    """No false negatives; false-positive rate ~(1-e^(-kn/m))^k."""
    idx = _bloom_indices(np.asarray([key], np.int64), bits.size * 8)[:, 0]
    sel = (bits[idx >> 3] >> (idx & 7).astype(np.uint8)) & np.uint8(1)
    return bool(sel.all())


def bloom_merge(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return a | b


# -- count-min (key-pair presence/frequency) --------------------------------


def cms_init(depth: int, width: int) -> np.ndarray:
    assert width & (width - 1) == 0
    return np.zeros((depth, width), np.int32)


def _cms_indices(counts: np.ndarray, hi, lo) -> np.ndarray:
    """[depth, n] — same row-hash family as ops.cms._indices."""
    depth, width = counts.shape
    rows = np.arange(depth, dtype=_U32)[:, None]
    with np.errstate(over="ignore"):
        h = np_hash2_32(hi[None, :], lo[None, :], 0) ^ (
            np_hash2_32(hi[None, :], lo[None, :], 1)
            * (rows * _U32(2) + _U32(1))
        )
    return (h & _U32(width - 1)).astype(np.int64)


def cms_add(counts: np.ndarray, keys) -> None:
    keys = np.asarray(keys, np.int64)
    if keys.size == 0:
        return
    hi, lo = split64(keys)
    idx = _cms_indices(counts, hi, lo)
    flat = idx + (np.arange(counts.shape[0], dtype=np.int64)
                  * counts.shape[1])[:, None]
    np.add.at(counts.reshape(-1), flat.reshape(-1),
              np.ones(flat.size, np.int32))


def cms_query(counts: np.ndarray, key: int) -> int:
    """Min over rows — never underestimates (0 proves absence)."""
    hi, lo = split64(np.asarray([key], np.int64))
    idx = _cms_indices(counts, hi, lo)[:, 0]
    return int(counts[np.arange(counts.shape[0]), idx].min())


def cms_merge(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return a + b


# -- HyperLogLog (distinct trace ids per segment) ---------------------------


def hll_init(p: int) -> np.ndarray:
    return np.zeros(1 << p, np.int32)


def hll_add(regs: np.ndarray, keys) -> None:
    """Same (index, rank) hash pair as ops.hll.update."""
    keys = np.asarray(keys, np.int64)
    if keys.size == 0:
        return
    hi, lo = split64(keys)
    idx = (np_hash2_32(hi, lo, 101) & _U32(regs.size - 1)).astype(np.int64)
    rank = np_clz32(np_hash2_32(hi, lo, 202)) + 1
    np.maximum.at(regs, idx, rank)


def hll_merge(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.maximum(a, b)


def hll_estimate(regs: np.ndarray) -> float:
    """ops.hll.estimate on host data (same small-range correction)."""
    m = regs.size
    if m == 16:
        alpha = 0.673
    elif m == 32:
        alpha = 0.697
    elif m == 64:
        alpha = 0.709
    else:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    raw = alpha * m * m / np.exp2(-regs.astype(np.float64)).sum()
    zeros = float((regs == 0).sum())
    if raw <= 2.5 * m and zeros > 0:
        return float(m * math.log(m / max(zeros, 1.0)))
    return float(raw)


# -- log-histogram (duration quantiles, ops.quantile geometry) --------------


def hist_bucket_index(values: np.ndarray, n_buckets: int, gamma: float,
                      min_value: float = 1.0) -> np.ndarray:
    """Twin of ops.quantile.bucket_index (float32 like the device)."""
    v = np.asarray(values, np.float32)
    scaled = np.log(np.maximum(v, np.float32(min_value))
                    / np.float32(min_value))
    idx = np.ceil(scaled / np.float32(math.log(gamma)))
    return np.clip(idx.astype(np.int32), 0, n_buckets - 1)


def hist_add(counts: np.ndarray, values, gamma: float,
             min_value: float = 1.0) -> None:
    values = np.asarray(values)
    if values.size == 0:
        return
    idx = hist_bucket_index(values, counts.size, gamma, min_value)
    np.add.at(counts, idx, np.int64(1))
