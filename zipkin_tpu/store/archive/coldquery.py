"""Cold-tier query mixin: zone-map-pruned reads over a SegmentDirectory.

The query half of the cold tier, factored out of ``TieredSpanStore``
so two hosts share ONE implementation of the pruning + oracle-match
semantics:

- ``TieredSpanStore`` (store/archive/tiered.py) — cold answers unioned
  with the hot device ring's;
- ``ReplicaSpanStore`` (store/replica.py) — a device-free read replica
  whose ENTIRE row store is segments sealed from shipped WAL records.

Host contract: ``self.archive`` (a SegmentDirectory), ``self.dicts``
(the DictionarySet that encoded the rows), and ``self._segments()`` /
``self._pruned(probe)`` — snapshot hooks the host implements so it can
interpose its visibility barrier (the tiered store waits on the hot
store's seal barrier; the replica snapshots under its apply lock).

Candidate semantics are the memory-oracle's (store/memory.py match
functions over decoded rows) behind exact zone-map pruning (service
bitmap, tagged-key CMS, ts range, trace bloom) — bit-for-bit the
reference store's answers, without decoding pruned segments.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from zipkin_tpu.models.span import Span
from zipkin_tpu.ops.quantile import quantiles_host
from zipkin_tpu.store.archive import sketches as SK
from zipkin_tpu.store.archive.segment import (
    TAG_ANN,
    TAG_BKEY,
    TAG_BVAL,
    TAG_NAME,
)
from zipkin_tpu.store.base import (
    IndexedTraceId,
    TraceIdDuration,
    dedup_rank_limit,
    resolve_annotation_query,
)
from zipkin_tpu.store.memory import (
    match_spans_by_annotation,
    match_spans_by_name,
)


class ColdQueries:
    """Zone-pruned segment reads (see module docstring for the host
    contract)."""

    # -- visibility hooks (hosts override to add their barrier) ---------

    def _segments(self):
        return self.archive.snapshot()

    def _pruned(self, probe):
        return self.archive.pruned_scan(probe)

    # -- catalogs -------------------------------------------------------

    def cold_service_ids(self) -> Set[int]:
        """Service ids present in any cold segment, from zone-map
        metadata alone (host memory, no decompression) — the sketch
        tier's cold half of getAllServiceNames (exact: zone service
        sets are exact per segment, see archive/segment.py)."""
        out: Set[int] = set()
        for seg in self._segments():
            out.update(seg.zone.service_ids)
        return out

    def cold_span_names(self, service: str) -> Set[str]:
        """Span names of ``service`` over decoded cold rows (segments
        without the service pruned by the exact zone service set)."""
        out: Set[str] = set()
        svc = self.dicts.services.get(service.lower())
        if svc is None:
            return out
        for seg in self._pruned(
                lambda s: svc in s.zone.service_ids):
            _, _, spans = self.archive.decoded(seg)
            out.update(
                s.name for s in match_spans_by_name(
                    spans, service, None, (1 << 62))
                if s.name
            )
        return out

    # -- trace reads ----------------------------------------------------

    def _cold_segments_for_traces(self, qids: Set[int]):
        return self._pruned(
            lambda seg: any(seg.zone.may_contain_trace(t) for t in qids)
        )

    def cold_rows_for_traces(self, qids: Set[int],
                             rows: Optional[Dict[int, Dict[int, Span]]]
                             = None) -> Dict[int, Dict[int, Span]]:
        """{signed trace id: {gid: span}} over matching cold rows,
        merged INTO ``rows`` (cold copy wins on gid overlap: captured
        before any ring could drop its annotation rows)."""
        from zipkin_tpu.columnar.encode import to_signed64

        rows = {} if rows is None else rows
        for seg in self._cold_segments_for_traces(qids):
            batch, gids, spans = self.archive.decoded(seg)
            hit = np.isin(batch.trace_id,
                          np.fromiter(qids, np.int64, len(qids)))
            for i in np.flatnonzero(hit):
                span = spans[int(i)]
                rows.setdefault(to_signed64(span.trace_id), {})[
                    int(gids[i])] = span
        return rows

    def cold_traces_exist(self, qids: Dict[int, int]) -> Set:
        """Resolve {signed id: original id} membership against the
        trace-id columns alone (no row decode); consumes resolved
        entries from ``qids`` and returns the original ids found."""
        found = set()
        for seg in self._cold_segments_for_traces(set(qids)):
            if not qids:
                break
            tid_col = seg.column("trace_id")
            stids = np.fromiter(qids, np.int64, len(qids))
            for stid in stids[np.isin(stids, tid_col)]:
                found.add(qids.pop(int(stid)))
        return found

    def cold_duration_bounds(self, canon: Dict[int, int],
                             bounds: Dict[int, list]) -> Dict[int, list]:
        """Widen {original id: [min_ts, max_ts]} with the cold rows'
        timestamp bounds (column-only read, one membership pass)."""
        stids = np.fromiter(canon, np.int64, len(canon))
        for seg in self._cold_segments_for_traces(set(canon)):
            tid_col = seg.column("trace_id")
            hit = np.isin(tid_col, stids)
            if not hit.any():
                continue
            tid_hit = tid_col[hit]
            tsf_hit = seg.column("ts_first")[hit]
            tsl_hit = seg.column("ts_last")[hit]
            for stid in np.unique(tid_hit):
                orig = canon[int(stid)]
                m = tid_hit == stid
                tsf = tsf_hit[m]
                tsl = tsl_hit[m]
                ts = np.concatenate([tsf[tsf >= 0], tsl[tsl >= 0]])
                if not ts.size:
                    continue
                b = bounds.setdefault(orig, [int(ts.min()),
                                             int(ts.max())])
                b[0] = min(b[0], int(ts.min()))
                b[1] = max(b[1], int(ts.max()))
        return bounds

    # -- index reads ----------------------------------------------------

    def _cold_ids_by_name(self, service_name: str,
                          span_name: Optional[str], end_ts: int,
                          limit: int) -> List[IndexedTraceId]:
        dicts = self.dicts
        svc = dicts.services.get(service_name.lower())
        if svc is None or limit <= 0:
            return []
        name_lc = (dicts.span_names.get(span_name.lower())
                   if span_name is not None else None)
        if span_name is not None and name_lc is None:
            return []

        def probe(seg):
            z = seg.zone
            if svc not in z.service_ids or not z.may_match_end_ts(end_ts):
                return False
            if name_lc is not None and not z.may_contain_key(
                    TAG_NAME, svc, name_lc):
                return False
            return True

        return self._cold_match(
            probe,
            lambda spans: match_spans_by_name(
                spans, service_name, span_name, end_ts),
            limit,
        )

    def _cold_ids_by_annotation(self, service_name: str, annotation: str,
                                value: Optional[bytes], end_ts: int,
                                limit: int) -> List[IndexedTraceId]:
        from zipkin_tpu.models.constants import CORE_ANNOTATIONS

        dicts = self.dicts
        if annotation in CORE_ANNOTATIONS or limit <= 0:
            return []
        svc = dicts.services.get(service_name.lower())
        if svc is None:
            return []
        resolved = resolve_annotation_query(dicts, annotation, value)
        if resolved is None:
            return []
        ann_value, bann_key, bann_value, bann_value2 = resolved

        def probe(seg):
            z = seg.zone
            if svc not in z.service_ids or not z.may_match_end_ts(end_ts):
                return False
            if value is not None:
                return any(
                    v >= 0 and z.may_contain_key(TAG_BVAL, svc,
                                                 bann_key, v)
                    for v in (bann_value, bann_value2)
                )
            may = False
            if ann_value >= 0:
                may = z.may_contain_key(TAG_ANN, svc, ann_value)
            if not may and bann_key >= 0:
                may = z.may_contain_key(TAG_BKEY, svc, bann_key)
            return may

        return self._cold_match(
            probe,
            lambda spans: match_spans_by_annotation(
                spans, service_name, annotation, value, end_ts),
            limit,
        )

    def _cold_match(self, probe, matcher, limit: int
                    ) -> List[IndexedTraceId]:
        import time

        t0 = time.perf_counter()
        cands = []
        for seg in self._pruned(probe):
            _, _, spans = self.archive.decoded(seg)
            cands.extend(
                (s.trace_id, s.last_timestamp) for s in matcher(spans)
                if s.last_timestamp is not None
            )
        self.archive.h_cold_query.observe(time.perf_counter() - t0)
        return dedup_rank_limit(cands, limit)

    # -- cold-only sketch answers (no row decompression) ----------------

    def cold_duration_quantiles(self, service: str, qs: Sequence[float]
                                ) -> Optional[List[float]]:
        """Per-service latency quantiles over cold rows, answered from
        segment zone-map histograms alone (same ops.quantile geometry
        as the device svc_hist)."""
        svc = self.dicts.services.get(service.lower())
        if svc is None:
            return None
        counts = None
        for seg in self._segments():
            row = seg.zone.dur_hist.get(svc)
            if row is not None:
                counts = row if counts is None else counts + row
        if counts is None:
            return None
        return quantiles_host(counts, self.archive.params.hist_gamma,
                              1.0, list(qs))

    def cold_estimated_unique_traces(self) -> float:
        """Distinct-trace estimate over the cold tier from merged
        segment HLLs."""
        regs = None
        for seg in self._segments():
            regs = (seg.zone.hll if regs is None
                    else SK.hll_merge(regs, seg.zone.hll))
        if regs is None:
            return 0.0
        return SK.hll_estimate(regs)


def union_topk(limit: int, *tiers) -> List[IndexedTraceId]:
    """Re-rank the union of per-tier top-``limit`` lists — exact: a
    trace absent from BOTH per-tier top lists is outranked by ``limit``
    distinct traces globally (the topk_ids_with_escalation argument
    applied across tiers)."""
    return dedup_rank_limit(
        [(i.trace_id, i.timestamp) for ids in tiers for i in ids],
        limit,
    )


def durations_from_bounds(trace_ids, bounds: Dict[int, list]
                          ) -> List[TraceIdDuration]:
    return [
        TraceIdDuration(t, bounds[t][1] - bounds[t][0], bounds[t][0])
        for t in trace_ids if t in bounds
    ]
