"""TieredSpanStore: the full SpanStore SPI over hot ring + cold segments.

Tiering contract (what makes the federation exact):

- Every span row carries a global id (gid). The hot tier is the device
  ring: rows with gid in [write_pos - capacity, write_pos). The cold
  tier covers gids [0, captured_upto): the capture hook in
  TpuSpanStore pulls every row BEFORE any of the three rings (span /
  annotation / binary) can overwrite it, so a captured copy is always
  COMPLETE (its annotation rows were still resident at capture time)
  and the two tiers overlap only in rows that exist identically in
  both. Row-level reads therefore dedupe by gid, preferring the cold
  copy (the ring twin may have lost side-table rows to the
  faster-lapping annotation rings).

- Index reads union each tier's top-``limit`` candidate list and
  re-rank: a trace absent from BOTH per-tier top lists is outranked by
  ``limit`` distinct traces globally (the topk_ids_with_escalation
  argument applied across tiers), so the union is the true global
  top-``limit``.

- Cold candidates come from zone-map pruning (service bitmap, tagged
  key CMS, ts range, trace bloom) followed by the memory-oracle match
  functions (store/memory.py) over decoded rows — bit-for-bit the
  reference semantics, including spans long evicted from the device.

- Lifetime streaming aggregates (dependency banks, per-service
  histograms, HLL, top-k counters) survive eviction ON DEVICE, so
  those queries delegate to the hot store; the cold tier additionally
  answers them from segment sketches alone (``cold_*`` methods) —
  quantiles and cardinality without decompressing a single row.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Set

from zipkin_tpu.columnar.encode import to_signed64
from zipkin_tpu.models.span import Span
from zipkin_tpu.store.archive.coldquery import (
    ColdQueries,
    durations_from_bounds,
    union_topk,
)
from zipkin_tpu.store.archive.directory import (
    ArchiveParams,
    SegmentDirectory,
)
from zipkin_tpu.store.archive.segment import seal_segment
from zipkin_tpu.store.base import (
    IndexedTraceId,
    SpanStore,
    TraceIdDuration,
    apply_pin_merges,
    fill_pin,
)


class TieredSpanStore(ColdQueries, SpanStore):
    """Federates a TpuSpanStore (hot) with a SegmentDirectory (cold).
    The cold read half (zone pruning + oracle-match semantics) lives in
    the shared ColdQueries mixin (store/archive/coldquery.py) — the
    device-free ReplicaSpanStore runs the identical code over segments
    sealed from shipped WAL records."""

    def __init__(self, hot, params: Optional[ArchiveParams] = None,
                 directory: Optional[SegmentDirectory] = None,
                 registry=None, background_compaction: bool = False):
        self.hot = hot
        self.params = params or ArchiveParams.for_config(hot.config)
        self.archive = directory or SegmentDirectory(
            self.params, hot.codec, registry=registry)
        self.captures = 0
        hot.eviction_sink = self._capture_sink
        if background_compaction:
            self.archive.start_compactor()

    # -- capture --------------------------------------------------------

    def _capture_sink(self, batch, gids, gid_lo: int, gid_hi: int,
                      pull_s: float) -> None:
        """Called from the hot write path with one capture window's
        pulled columns; seals a segment and hands it to the directory
        (which may compact inline)."""
        t0 = time.perf_counter()
        spans = self.hot.codec.decode(batch)
        seg = seal_segment(
            self.archive.next_id(), batch, gids, spans,
            self.hot.dicts, self.params, gid_lo, gid_hi,
        )
        self.archive.append(seg, cache=(batch, gids, spans))
        self.captures += 1
        self.archive.h_capture.observe(
            pull_s + (time.perf_counter() - t0))

    # -- writes (delegate; capture rides the hot write path) ------------

    def apply(self, spans: Sequence[Span]) -> None:
        self.hot.apply(spans)

    def write_thrift(self, payload: bytes, sample_threshold: int = 0):
        return self.hot.write_thrift(payload, sample_threshold)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        # Same TTL/pin bookkeeping as the hot store, but pin
        # materialization reads THROUGH the tiers so pinning an
        # already-evicted trace banks its cold rows too.
        hot = self.hot
        tid = to_signed64(trace_id)
        with hot._lock:
            hot.ttls[tid] = ttl_seconds
            hot._bump_read_epoch()
            pin = ttl_seconds > hot.DEFAULT_TTL_S
            if not pin:
                hot.pins.unpin(tid)
        if pin:
            fill_pin(hot.pins, hot._lock, tid, lambda: (
                self.get_spans_by_trace_ids([trace_id]) or [[]])[0])
            with hot._lock:
                hot._bump_read_epoch()  # bank filled: reads widened

    def get_time_to_live(self, trace_id: int) -> float:
        return self.hot.get_time_to_live(trace_id)

    def write_frontier(self):
        """The hot store's commit frontier keys the result cache for
        the WHOLE federation: cold-tier content only changes through
        hot commits (capture windows are pulled inside the committing
        write's lock hold, and cold reads run behind seal_barrier), so
        a fixed hot frontier pins the federated answer too."""
        return self.hot.write_frontier()

    @property
    def dicts(self):
        """The dictionary set that encoded every tier's rows (the
        ColdQueries mixin resolves query names against it)."""
        return self.hot.dicts

    def capture_now(self) -> None:
        """Flush everything resident-but-uncaptured into a segment."""
        self.hot.capture_now()

    def close(self) -> None:
        # Hot store first: it drains the ingest pipeline (committing
        # accepted batches, which may trigger final captures) and then
        # the capture sealer — only after that is detaching the sink
        # safe (a pending async seal still needs it).
        self.hot.close()
        self.archive.stop_compactor()
        self.archive.close()
        self.hot.eviction_sink = None

    # -- pipelined-ingest passthrough (the pipeline lives on the hot
    # store; collector/daemon wiring sees one store object) ------------

    def start_pipeline(self, depth: Optional[int] = None):
        return self.hot.start_pipeline(depth)

    def drain_pipeline(self) -> None:
        self.hot.drain_pipeline()

    def stop_pipeline(self, raise_errors: bool = True) -> None:
        self.hot.stop_pipeline(raise_errors)

    def seal_barrier(self) -> None:
        self.hot.seal_barrier()

    # -- write-ahead log passthrough (the journal hook lives on the hot
    # store's write path; capture/seal replays ride it) ----------------

    @property
    def wal(self):
        return self.hot.wal

    def attach_wal(self, wal) -> None:
        self.hot.attach_wal(wal)

    def wal_sync(self) -> None:
        self.hot.wal_sync()

    # -- row reads ------------------------------------------------------

    def _segments(self):
        """Directory snapshot behind the hot store's seal barrier:
        with an async sealer a capture window can be pulled (rows
        possibly already overwritten in the rings) but not yet
        appended — a cold read that skipped the barrier could miss
        rows neither tier still serves."""
        self.hot.seal_barrier()
        return self.archive.snapshot()

    def _pruned(self, probe):
        """Zone-pruned scan behind the seal barrier (see _segments)."""
        self.hot.seal_barrier()
        return self.archive.pruned_scan(probe)

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]
                               ) -> List[List[Span]]:
        if not trace_ids:
            return []
        hot = self.hot
        qids = {to_signed64(t) for t in trace_ids}
        rows: Dict[int, Dict[int, Span]] = {}
        for gid, span in hot.get_trace_rows(trace_ids):
            rows.setdefault(to_signed64(span.trace_id), {})[gid] = span
        t0 = time.perf_counter()
        # Cold copy wins on gid overlap: captured before any ring
        # could drop its annotation rows.
        self.cold_rows_for_traces(qids, rows)
        self.archive.h_cold_query.observe(time.perf_counter() - t0)
        by_tid = {
            tid: [span for _, span in sorted(found.items())]
            for tid, found in rows.items()
        }
        with hot._lock:
            apply_pin_merges(hot.pins, by_tid, trace_ids, to_signed64)
        return [
            by_tid[to_signed64(t)] for t in trace_ids
            if by_tid.get(to_signed64(t))
        ]

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        if not trace_ids:
            return set()
        found = self.hot.traces_exist(trace_ids)
        missing = [t for t in trace_ids if t not in found]
        if not missing:
            return found
        qids = {to_signed64(t): t for t in missing}
        t0 = time.perf_counter()
        found |= self.cold_traces_exist(qids)
        self.archive.h_cold_query.observe(time.perf_counter() - t0)
        return found

    def get_traces_duration(self, trace_ids: Sequence[int]
                            ) -> List[TraceIdDuration]:
        if not trace_ids:
            return []
        bounds: Dict[int, list] = {}
        for d in self.hot.get_traces_duration(trace_ids):
            bounds[d.trace_id] = [d.start_timestamp,
                                  d.start_timestamp + d.duration]
        canon = {to_signed64(t): t for t in trace_ids}
        t0 = time.perf_counter()
        self.cold_duration_bounds(canon, bounds)
        self.archive.h_cold_query.observe(time.perf_counter() - t0)
        return durations_from_bounds(trace_ids, bounds)

    # -- index reads (cold halves come from the ColdQueries mixin) ------

    @staticmethod
    def _union(limit: int, *tiers) -> List[IndexedTraceId]:
        """Re-rank the union of per-tier top-``limit`` lists — exact
        (see the module docstring's cross-tier top-k argument)."""
        return union_topk(limit, *tiers)

    def get_trace_ids_by_name(self, service_name: str,
                              span_name: Optional[str], end_ts: int,
                              limit: int) -> List[IndexedTraceId]:
        return self._union(
            limit,
            self.hot.get_trace_ids_by_name(service_name, span_name,
                                           end_ts, limit),
            self._cold_ids_by_name(service_name, span_name, end_ts,
                                   limit),
        )

    def get_trace_ids_by_annotation(self, service_name: str,
                                    annotation: str,
                                    value: Optional[bytes], end_ts: int,
                                    limit: int) -> List[IndexedTraceId]:
        return self._union(
            limit,
            self.hot.get_trace_ids_by_annotation(
                service_name, annotation, value, end_ts, limit),
            self._cold_ids_by_annotation(service_name, annotation,
                                         value, end_ts, limit),
        )

    def get_trace_ids_multi(self, queries) -> List[List[IndexedTraceId]]:
        """Hot probes ride the device's one-launch batched path; each
        query then unions its cold candidates."""
        hot_res = self.hot.get_trace_ids_multi(queries)
        out = []
        for q, hot_ids in zip(queries, hot_res):
            if q[0] == "name":
                _, svc, name, end_ts, limit = q
                cold = self._cold_ids_by_name(svc, name, end_ts, limit)
            else:
                _, svc, ann, value, end_ts, limit = q
                cold = self._cold_ids_by_annotation(svc, ann, value,
                                                    end_ts, limit)
            out.append(self._union(q[-1], hot_ids, cold))
        return out

    # -- catalogs -------------------------------------------------------

    def get_all_service_names(self) -> Set[str]:
        out = self.hot.get_all_service_names()
        d = self.hot.dicts.services
        for seg in self._segments():
            out.update(
                name for i in seg.zone.service_ids
                if i < len(d) and (name := d.decode(i))
            )
        return out

    def get_span_names(self, service: str) -> Set[str]:
        out = self.hot.get_span_names(service)
        out.update(self.cold_span_names(service))
        return out

    # -- lifetime aggregates (device streaming state; see module doc) ---

    def get_dependencies(self, start_ts: Optional[int] = None,
                         end_ts: Optional[int] = None):
        return self.hot.get_dependencies(start_ts, end_ts)

    def archive_now(self) -> None:
        self.hot.archive_now()

    def service_duration_quantiles(self, service: str,
                                   qs: Sequence[float]):
        return self.hot.service_duration_quantiles(service, qs)

    def top_annotations(self, service: str, k: int = 10):
        return self.hot.top_annotations(service, k)

    def top_binary_keys(self, service: str, k: int = 10):
        return self.hot.top_binary_keys(service, k)

    def estimated_unique_traces(self) -> float:
        return self.hot.estimated_unique_traces()

    def stored_span_count(self):
        return self.hot.stored_span_count()

    # -- cold-only sketch answers: cold_duration_quantiles /
    # cold_estimated_unique_traces come from the ColdQueries mixin ------

    # -- telemetry ------------------------------------------------------

    def counters(self) -> Dict[str, float]:
        out = dict(self.hot.counters())
        out.update(self.archive.stats())
        out["archive_captures"] = float(self.captures)
        return out
