"""Pluggable span storage: SPI + in-memory reference + TPU columnar store."""

from zipkin_tpu.store.base import (  # noqa: F401
    IndexedTraceId,
    ReadSpanStore,
    SpanStore,
    StorageException,
    TraceIdDuration,
    WriteSpanStore,
)
from zipkin_tpu.store.memory import InMemorySpanStore  # noqa: F401
