"""SpanStore SPI.

Parity targets (reference):
- ``SpanStore = WriteSpanStore with ReadSpanStore`` —
  zipkin-common/.../storage/SpanStore.scala:26,56,71
- ``IndexedTraceId`` / ``TraceIdDuration`` — storage/Index.scala:29,26
- ``FanoutWriteSpanStore`` — SpanStore.scala:38

The API is array-friendly: every read returns plain python data, every write
takes a batch of spans; implementations may be host-resident (memory) or
device-resident (TPU columnar + sketches). Synchronous by design — the
async boundary in this framework lives in the ingest queue
(zipkin_tpu.ingest.queue), not in the store.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Set

from zipkin_tpu.models.span import Span

# Reference default TTLs (CassieSpanStore.scala:47-48).
DEFAULT_SPAN_TTL_S = 7 * 24 * 3600
DEFAULT_INDEX_TTL_S = 3 * 24 * 3600
TTL_TOP = float("inf")


class StorageException(RuntimeError):
    """Raised by stores on write/read failure (storage/util SpanStoreException)."""


def as_bytes(v) -> bytes:
    """Canonical byte form of a binary-annotation value for comparisons."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    return bytes(v)


@dataclass(frozen=True)
class IndexedTraceId:
    """A trace id with the index timestamp that matched (Index.scala:29)."""

    trace_id: int
    timestamp: int


@dataclass(frozen=True)
class TraceIdDuration:
    """Trace duration in µs + start timestamp (Index.scala:26)."""

    trace_id: int
    duration: int
    start_timestamp: int


def should_index(span: Span) -> bool:
    """Skip indexing client-side spans attributed to the literal service
    "client" (SpanStore.scala:66-67)."""
    return not (span.is_client_side() and "client" in span.service_names)


class PinBank:
    """Host-side eviction-exempt storage for pinned traces.

    The reference's setTimeToLive actually extends storage retention
    (SpanStore.scala:66; web pin → Handlers.scala:490). The device ring
    evicts by wraparound regardless of TTL, so pinning a trace
    materializes its spans into this bank at pin time, keeps the bank
    fresh as later spans of the trace arrive, and trace-id read paths
    union it with ring results — the pinned trace stays fully readable
    after the ring has lapped it. Unpinning drops the entry.

    Banks dedup on append (transport retries re-deliver spans) and are
    bounded per trace by MAX_SPANS_PER_TRACE — the maxTraceCols guard
    (CassieSpanStore.scala:50) applied to pinned retention.
    """

    MAX_SPANS_PER_TRACE = 100_000

    def __init__(self):
        self._pins = {}
        self._seen = {}  # tid -> set of banked spans (dedup)

    def __bool__(self) -> bool:
        return bool(self._pins)

    def __contains__(self, tid: int) -> bool:
        return tid in self._pins

    def pin(self, tid: int, spans) -> None:
        out, seen = [], set()
        for s in spans:
            if s not in seen and len(out) < self.MAX_SPANS_PER_TRACE:
                out.append(s)
                seen.add(s)
        self._pins[tid] = out
        self._seen[tid] = seen

    def unpin(self, tid: int) -> None:
        self._pins.pop(tid, None)
        self._seen.pop(tid, None)

    def get(self, tid: int):
        return self._pins.get(tid)

    def tids(self):
        return set(self._pins)

    def items(self):
        return self._pins.items()

    def note_write(self, key_of, spans) -> None:
        """Append incoming spans of already-pinned traces — post-pin
        arrivals must survive eviction too. Idempotent per span."""
        if not self._pins:
            return
        for s in spans:
            tid = key_of(s.trace_id)
            bank = self._pins.get(tid)
            if bank is None:
                continue
            seen = self._seen[tid]
            if s not in seen and len(bank) < self.MAX_SPANS_PER_TRACE:
                bank.append(s)
                seen.add(s)

    def merge(self, tid: int, ring_spans):
        """Union bank + ring rows for one trace: bank spans (inserted
        earlier) first, then ring spans whose span id isn't banked.

        Dedup is by span id, not object equality: a ring row whose
        annotations were evicted from their own ring decodes as a
        partial twin of the banked span — every post-pin arrival is
        banked by note_write, so a ring copy sharing a banked id is
        redundant (or partial) by construction."""
        bank = self._pins.get(tid)
        if not bank:
            return list(ring_spans)
        seen_ids = {s.id for s in bank}
        return list(bank) + [s for s in ring_spans if s.id not in seen_ids]


# Bound on a store's host TTL map (pins + recent traces); ring/segment
# eviction has no host-side hook, so pruning happens on insert. Shared
# by the device, sharded, and replica stores.
MAX_TTL_ENTRIES = 1 << 20


def prune_ttls(ttls: dict, max_entries: int) -> None:
    """Drop oldest non-pinned TTL entries beyond the bound (ring
    eviction is the real retention; pinned entries — ttl > 1.0 —
    survive). Shared by the single-device and sharded stores."""
    excess = len(ttls) - max_entries
    if excess <= 0:
        return
    for tid in list(ttls):
        if excess <= 0:
            break
        if ttls[tid] <= 1.0:
            del ttls[tid]
            excess -= 1


def fill_pin(pins: PinBank, lock, tid: int, fetch_spans) -> None:
    """Pin-materialization with the TOCTOU window closed: open the bank
    under ``lock`` FIRST (so concurrent writes bank their arrivals via
    note_write), then fetch the ring snapshot outside the lock, then
    union both under the lock."""
    with lock:
        if tid in pins:
            return
        pins.pin(tid, [])
    found = fetch_spans()
    with lock:
        banked = pins.get(tid)
        if banked is None:  # unpinned while fetching
            return
        seen = set(banked)
        pins.pin(tid, list(banked) + [s for s in found if s not in seen])


def service_scan_only(svc_id: int, config) -> bool:
    """True when a resolved service id overflows the store's service
    capacity (dictionary id >= max_services): such services exist only
    in the raw ring columns — no index family, histogram, or key record
    can represent them — so the index fast path would return a trusted
    EMPTY while the scan finds their spans. Every device-store query
    path must route these to the scan (slower, never wrong)."""
    return svc_id >= config.max_services


def resolve_annotation_query(dicts, annotation: str, value):
    """Dictionary-id resolution for get_trace_ids_by_annotation, shared
    by the single-device and sharded stores. Returns
    (ann_value, bann_key, bann_value, bann_value2) with -1 sentinels,
    or None when nothing in the dictionaries can match."""
    bann_key = dicts.binary_keys.get(annotation)
    bann_key = -1 if bann_key is None else bann_key
    if value is not None:
        # Value given: only binary annotations with that exact value
        # match. The dictionary keys values in their original python
        # form, so probe both the bytes and the decoded-str shape.
        ann_value = -1
        vb = as_bytes(value)
        bann_value = dicts.binary_values.get(vb)
        try:
            bann_value2 = dicts.binary_values.get(vb.decode("utf-8"))
        except UnicodeDecodeError:
            bann_value2 = None
        bann_value = -1 if bann_value is None else bann_value
        bann_value2 = -1 if bann_value2 is None else bann_value2
        if (bann_value < 0 and bann_value2 < 0) or bann_key < 0:
            return None
    else:
        ann_value = dicts.annotations.get(annotation)
        ann_value = -1 if ann_value is None else ann_value
        bann_value = bann_value2 = -1
        if ann_value < 0 and bann_key < 0:
            return None
    return ann_value, bann_key, bann_value, bann_value2


def topk_ids_with_escalation(limit: int, k_max: int, fetch,
                             k0: int = 64) -> List["IndexedTraceId"]:
    """Escalating candidate fetch for index queries: ``fetch(k)``
    returns (candidates [(tid, ts)...], truncated) off the device top-k
    kernel; when dedup-by-trace can't fill ``limit`` AND the candidate
    window was full (a hot trace may have crowded it), re-query with
    k×8. Exact: any trace absent from a candidate window ranks below
    every candidate in it, so ``limit`` distinct found traces are the
    true top ``limit``."""
    k = min(max(k0, 4 * limit), max(k_max, 1))
    while True:
        candidates, truncated = fetch(k)
        ids = dedup_rank_limit(candidates, limit)
        if len(ids) >= limit or not truncated or k >= k_max:
            return ids
        k = min(k * 8, k_max)


def index_topk_or_none(limit: int, k: int, candidates, complete,
                       watermark) -> Optional[List["IndexedTraceId"]]:
    """The index trust gate as a pure function over an already-fetched
    bucket window of ``k`` candidate slots; None means the window can't
    be trusted and the caller must scan. Shared by the per-query path
    (index_first_topk) and the batched multi-probe path
    (TpuSpanStore.get_trace_ids_multi)."""
    ids = dedup_rank_limit(candidates, limit)
    if len(ids) >= limit:
        # A complete bucket's top candidates are exact; a wrapped one's
        # are exact iff nothing displaced could outrank the limit-th.
        if complete or ids[-1].timestamp >= watermark:
            return ids
    elif complete and len(candidates) < k:
        # Every entry the bucket has ever held was inside the top-k
        # window: the underfull result is the true, full answer.
        return ids
    return None


def index_first_topk(limit: int, k_max: int, index_fetch,
                     scan_fetch, stats=None) -> List["IndexedTraceId"]:
    """Index fast path with scan fallback, the shared read policy of the
    device stores. ``index_fetch(k)`` reads an O(depth) index bucket and
    returns (candidates, complete, watermark, window):

    - ``complete`` — the bucket never wrapped, so it holds every entry
      ever written for the key: the result is exact, full stop.
    - otherwise the bucket holds its newest entries, and ``watermark``
      is the max ts ever displaced from it: the result is exact iff the
      limit-th ranked candidate still sits at or above the watermark
      (every span the index no longer holds ranks at or below it).
    - ``window`` — the number of candidate slots the kernel ACTUALLY
      returned (it may clamp the requested k to its bucket geometry).
      The underfull-equals-complete claim compares against this, never
      against the requested k: a kernel-truncated window full of
      candidates must read as saturated, not underfull (a saturated
      window silently cut real candidates — the bug the 3-store oracle
      parity drive caught in the two-bucket binary-value probe).

    A complete bucket whose top-k window saturated gets ONE retry at
    full bucket depth (the kernel clamps the oversized request to its
    geometry) — an O(depth) read that usually proves the answer without
    the O(ring) scan when a hot key's entries crowd the window.

    Anything else falls back to the O(ring) scan kernel's escalation.
    Near-monotonic traffic (the normal case: spans arrive roughly in
    timestamp order) keeps wrapped buckets trusted; shuffled arrival
    degrades to the scan, never to a wrong answer.

    ``stats`` (optional) is any object with ``index_hits`` /
    ``index_fallbacks`` counters — the accounting hook /metrics reads
    (TpuSpanStore passes itself)."""
    k = limit * 8
    candidates, complete, watermark, window = index_fetch(k)
    ids = index_topk_or_none(limit, min(k, window), candidates,
                             complete, watermark)
    if (ids is None and complete and 0 < k <= window
            and len(candidates) >= k):
        # window >= k: the first read was top-k-truncated, not
        # bucket-clamped — a full-depth reread can actually add rows.
        k = 1 << 20
        candidates, complete, watermark, window = index_fetch(k)
        ids = index_topk_or_none(limit, min(k, window), candidates,
                                 complete, watermark)
    if ids is not None:
        if stats is not None:
            stats.index_hits += 1
        return ids
    if stats is not None:
        stats.index_fallbacks += 1
    return topk_ids_with_escalation(limit, k_max, scan_fetch)


def dedup_rank_limit(candidates, limit: int) -> List["IndexedTraceId"]:
    """One IndexedTraceId per trace id (max timestamp wins), sorted by
    timestamp descending, truncated to ``limit`` — the dedup-before-limit
    semantics every store's index queries share."""
    best = {}
    for tid, ts in candidates:
        if ts > best.get(tid, -1):
            best[tid] = ts
    ranked = sorted(best.items(), key=lambda kv: kv[1], reverse=True)
    return [IndexedTraceId(t, ts) for t, ts in ranked[:limit]]


def apply_pin_merges(pins: PinBank, by_tid: dict, trace_ids, key_of) -> None:
    """Union each requested pinned trace's bank into ``by_tid`` in place.
    Callers hold whatever lock guards ``pins``."""
    if not pins:
        return
    for tid in trace_ids:
        stid = key_of(tid)
        if stid in pins:
            merged = pins.merge(stid, by_tid.get(stid, []))
            if merged:
                by_tid[stid] = merged


def escalate_cap(n: int, k: int, cap: int) -> int:
    """Grow a static gather cap ×8 until it covers ``n`` (bounded by the
    ring capacity) — shared by the single-store and sharded trace reads
    so their compile-cache keys stay aligned."""
    while n > k:
        k = min(k * 8, cap)
    return k


GATHER_K0 = 4096


def gather_with_escalation(config, fetch, k0: int = GATHER_K0):
    """Run a device trace-row gather with cap escalation: ``fetch(k_s,
    k_a, k_b)`` returns (n_s, n_a, n_b, payload); retried with ×8 caps
    until the counts fit (bounded by the ring capacities). Shared retry
    policy of the single-store and sharded whole-trace reads."""
    k_s = min(k0, config.capacity)
    k_a = min(2 * k0, config.ann_capacity)
    k_b = min(k0, config.bann_capacity)
    while True:
        n_s, n_a, n_b, payload = fetch(k_s, k_a, k_b)
        if n_s <= k_s and n_a <= k_a and n_b <= k_b:
            return payload
        k_s = escalate_cap(n_s, k_s, config.capacity)
        k_a = escalate_cap(n_a, k_a, config.ann_capacity)
        k_b = escalate_cap(n_b, k_b, config.bann_capacity)


def index_gather_with_escalation(config, nq: int, fetch):
    """Cap-escalating retry for the trace-membership gather fast path,
    shared by the single-device and sharded stores (same reasoning as
    gather_with_escalation: one policy, aligned compile caches).
    ``fetch(k_s, k_a, k_b)`` returns (exact, n_s, n_a, n_b, payload);
    returns the payload, or None the moment any queried bucket fails
    its exactness gate (callers then run the scan gather). Caps are
    bounded by nq x the per-family bucket depths — the most candidates
    the buckets can hold for the request."""
    c = config
    max_s = min(nq * c.TRACE_SPAN_DEPTH, c.capacity)
    max_a = min(nq * c.TRACE_ANN_DEPTH, c.ann_capacity)
    max_b = min(nq * c.TRACE_BANN_DEPTH, c.bann_capacity)
    k_s = min(GATHER_K0, max_s)
    k_a = min(2 * GATHER_K0, max_a)
    k_b = min(GATHER_K0, max_b)
    while True:
        exact, n_s, n_a, n_b, payload = fetch(k_s, k_a, k_b)
        if not exact:
            return None
        if n_s <= k_s and n_a <= k_a and n_b <= k_b:
            return payload
        k_s = escalate_cap(n_s, k_s, max_s)
        k_a = escalate_cap(n_a, k_a, max_a)
        k_b = escalate_cap(n_b, k_b, max_b)


def pinned_duration(trace_id: int, bank, existing=None):
    """TraceIdDuration over a pinned trace's banked spans, widened by
    any ring result (partial eviction leaves the ring narrower)."""
    ts = []
    for s in bank or ():
        if s.first_timestamp is not None:
            ts.append(s.first_timestamp)
            ts.append(s.last_timestamp)
    if existing is not None:
        ts.append(existing.start_timestamp)
        ts.append(existing.start_timestamp + existing.duration)
    if not ts:
        return existing
    return TraceIdDuration(trace_id, max(ts) - min(ts), min(ts))


def exist_from_duration_mat(canon, qids, present_row, pins: PinBank, lock):
    """traces_exist result from the stacked durations kernel's present
    row, unioned with requested pinned traces (shared by both stores)."""
    out = {
        canon[int(q)] for q, present in zip(qids, present_row) if present
    }
    with lock:
        if pins:
            out |= {
                orig for stid, orig in canon.items()
                if stid in pins and pins.get(stid)
            }
    return out


def durations_from_mat(trace_ids, canon, qids, mat, pins: PinBank, lock):
    """get_traces_duration result from the stacked durations kernel
    output [4, nq], with pin-bank widening (shared by both stores)."""
    by_tid = {
        canon[int(q)]: TraceIdDuration(canon[int(q)], int(mx - mn), int(mn))
        for q, f, mn, mx in zip(qids, mat[1], mat[2], mat[3])
        if f
    }
    with lock:
        if pins:
            for stid, orig in canon.items():
                if stid not in pins:
                    continue
                d = pinned_duration(orig, pins.get(stid), by_tid.get(orig))
                if d is not None:
                    by_tid[orig] = d
    return [by_tid[t] for t in trace_ids if t in by_tid]


class StoreSuspectError(RuntimeError):
    """The store's device state may still be read by an orphaned
    transfer thread (a slab-save timeout abandoned a wedged
    ``device_get``); donating writes must not run until the orphan is
    joined (ADVICE r5 checkpoint hazard)."""


_SUSPECT_LOCK = threading.Lock()  # lock-order: 83 suspect-flag


class SuspectGuard:
    # -- suspect protocol (checkpoint slab-timeout hazard) --------------
    # A deadline-bounded checkpoint save that times out leaves its
    # device_get running on an abandoned daemon thread, which may still
    # be READING the state buffers after the save's read lock releases.
    # A donating ingest step (or a fresh save's consistent cut) racing
    # that orphan reads/writes freed-or-reused buffers. checkpoint.save
    # stamps the store via mark_suspect(); every donating write path
    # calls ensure_writable() first, which joins the orphans (bounded)
    # and either clears the flag or raises StoreSuspectError.
    _suspect = False

    def mark_suspect(self, orphan=None) -> None:
        """Flag the device state as possibly-shared with an orphaned
        reader thread; ``orphan`` is the abandoned Thread when known."""
        with _SUSPECT_LOCK:
            self._suspect = True
            if orphan is not None:
                if not hasattr(self, "_suspect_orphans"):
                    self._suspect_orphans = []
                self._suspect_orphans.append(orphan)

    @property
    def suspect(self) -> bool:
        return self._suspect

    def ensure_writable(self, wait_s: float = 0.0) -> None:
        """No-op unless suspect. Joins each known orphan for up to
        ``wait_s``; the flag clears only if EVERY currently-recorded
        orphan is finished at re-check time (a concurrent save timeout
        may have appended a new orphan while we joined the snapshot),
        else StoreSuspectError. A suspect store with no recorded
        orphans can only be cleared explicitly (clear_suspect) or by a
        process restart."""
        if not self._suspect:
            return
        with _SUSPECT_LOCK:
            orphans = list(getattr(self, "_suspect_orphans", ()))
        for t in orphans:
            t.join(wait_s)
        with _SUSPECT_LOCK:
            if not self._suspect:
                return
            current = getattr(self, "_suspect_orphans", [])
            alive = [t for t in current if t.is_alive()]
            if alive or not current:
                if hasattr(self, "_suspect_orphans"):
                    self._suspect_orphans[:] = alive
                raise StoreSuspectError(
                    "store state may be shared with an orphaned "
                    "device_get reader (slab-save timeout); retry after "
                    "the transfer un-wedges or restart the process"
                )
            self._suspect_orphans[:] = []
            self._suspect = False

    def clear_suspect(self) -> None:
        """Operator override: declare the orphan dealt with."""
        with _SUSPECT_LOCK:
            self._suspect = False
            if hasattr(self, "_suspect_orphans"):
                self._suspect_orphans[:] = []


class WriteSpanStore(SuspectGuard, abc.ABC):
    @abc.abstractmethod
    def apply(self, spans: Sequence[Span]) -> None:
        """Store a batch of spans."""

    @abc.abstractmethod
    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        """Pin/extend a trace's retention (SpanStore.scala:66)."""

    def stored_span_count(self) -> Optional[float]:
        """Total spans ever admitted, from the store's own counters —
        the adaptive sampler's flow source (the device ``spans_seen``
        counter on the TPU store; psum-ed across shards when sharded —
        replacing the reference's ZK group sum,
        AdaptiveSampler.scala:204-237). None = unknown; callers fall
        back to host-side accounting."""
        return None

    def close(self) -> None:
        pass


class ReadSpanStore(abc.ABC):
    # -- resident query engines (query/engine.py) ----------------------
    # Engines register here so lifecycle owners that only hold the
    # store (Collector.flush/close, checkpoint.save) can join the
    # executor thread into the ordered drain→seal→fsync→checkpoint
    # sequence without knowing the query layer.

    def register_query_engine(self, engine) -> None:
        self.__dict__.setdefault("_query_engines", []).append(engine)

    def query_engines(self):
        return list(self.__dict__.get("_query_engines", ()))

    @abc.abstractmethod
    def get_time_to_live(self, trace_id: int) -> float:
        ...

    @abc.abstractmethod
    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        ...

    @abc.abstractmethod
    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]) -> List[List[Span]]:
        """Found traces only; absent ids are dropped from the result."""

    def get_spans_by_trace_id(self, trace_id: int) -> List[Span]:
        found = self.get_spans_by_trace_ids([trace_id])
        return found[0] if found else []

    @abc.abstractmethod
    def get_trace_ids_by_name(
        self,
        service_name: str,
        span_name: Optional[str],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        ...

    @abc.abstractmethod
    def get_trace_ids_by_annotation(
        self,
        service_name: str,
        annotation: str,
        value: Optional[bytes],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        ...

    def get_trace_ids_multi(self, queries) -> List[List[IndexedTraceId]]:
        """Resolve several independent trace-id queries at once. Each
        query is a tuple:

        - ``("name", service_name, span_name_or_None, end_ts, limit)``
        - ``("annotation", service_name, annotation, value_or_None,
          end_ts, limit)``

        The generic implementation loops over the singular methods;
        device stores override it to fold every query's index probe
        into a single kernel launch (the batched analogue of the
        reference resolving a request's slices with separate index
        reads, ThriftQueryService.scala:166-196)."""
        out: List[List[IndexedTraceId]] = []
        for q in queries:
            if q[0] == "name":
                _, svc, name, end_ts, limit = q
                out.append(
                    self.get_trace_ids_by_name(svc, name, end_ts, limit)
                )
            else:
                _, svc, ann, value, end_ts, limit = q
                out.append(self.get_trace_ids_by_annotation(
                    svc, ann, value, end_ts, limit
                ))
        return out

    @abc.abstractmethod
    def get_traces_duration(self, trace_ids: Sequence[int]) -> List[TraceIdDuration]:
        ...

    @abc.abstractmethod
    def get_all_service_names(self) -> Set[str]:
        ...

    @abc.abstractmethod
    def get_span_names(self, service: str) -> Set[str]:
        ...


class SpanStore(WriteSpanStore, ReadSpanStore, abc.ABC):
    """The unified store interface (SpanStore.scala:26)."""


class FanoutWriteSpanStore(WriteSpanStore):
    """Replicate writes to several stores (SpanStore.scala:38)."""

    def __init__(self, *stores: WriteSpanStore):
        self.stores = stores

    def apply(self, spans: Sequence[Span]) -> None:
        for s in self.stores:
            s.apply(spans)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        for s in self.stores:
            s.set_time_to_live(trace_id, ttl_seconds)

    def close(self) -> None:
        for s in self.stores:
            s.close()
