"""SpanStore SPI.

Parity targets (reference):
- ``SpanStore = WriteSpanStore with ReadSpanStore`` —
  zipkin-common/.../storage/SpanStore.scala:26,56,71
- ``IndexedTraceId`` / ``TraceIdDuration`` — storage/Index.scala:29,26
- ``FanoutWriteSpanStore`` — SpanStore.scala:38

The API is array-friendly: every read returns plain python data, every write
takes a batch of spans; implementations may be host-resident (memory) or
device-resident (TPU columnar + sketches). Synchronous by design — the
async boundary in this framework lives in the ingest queue
(zipkin_tpu.ingest.queue), not in the store.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional, Sequence, Set

from zipkin_tpu.models.span import Span

# Reference default TTLs (CassieSpanStore.scala:47-48).
DEFAULT_SPAN_TTL_S = 7 * 24 * 3600
DEFAULT_INDEX_TTL_S = 3 * 24 * 3600
TTL_TOP = float("inf")


class StorageException(RuntimeError):
    """Raised by stores on write/read failure (storage/util SpanStoreException)."""


def as_bytes(v) -> bytes:
    """Canonical byte form of a binary-annotation value for comparisons."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    return bytes(v)


@dataclass(frozen=True)
class IndexedTraceId:
    """A trace id with the index timestamp that matched (Index.scala:29)."""

    trace_id: int
    timestamp: int


@dataclass(frozen=True)
class TraceIdDuration:
    """Trace duration in µs + start timestamp (Index.scala:26)."""

    trace_id: int
    duration: int
    start_timestamp: int


def should_index(span: Span) -> bool:
    """Skip indexing client-side spans attributed to the literal service
    "client" (SpanStore.scala:66-67)."""
    return not (span.is_client_side() and "client" in span.service_names)


class WriteSpanStore(abc.ABC):
    @abc.abstractmethod
    def apply(self, spans: Sequence[Span]) -> None:
        """Store a batch of spans."""

    @abc.abstractmethod
    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        """Pin/extend a trace's retention (SpanStore.scala:66)."""

    def stored_span_count(self) -> Optional[float]:
        """Total spans ever admitted, from the store's own counters —
        the adaptive sampler's flow source (the device ``spans_seen``
        counter on the TPU store; psum-ed across shards when sharded —
        replacing the reference's ZK group sum,
        AdaptiveSampler.scala:204-237). None = unknown; callers fall
        back to host-side accounting."""
        return None

    def close(self) -> None:
        pass


class ReadSpanStore(abc.ABC):
    @abc.abstractmethod
    def get_time_to_live(self, trace_id: int) -> float:
        ...

    @abc.abstractmethod
    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        ...

    @abc.abstractmethod
    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]) -> List[List[Span]]:
        """Found traces only; absent ids are dropped from the result."""

    def get_spans_by_trace_id(self, trace_id: int) -> List[Span]:
        found = self.get_spans_by_trace_ids([trace_id])
        return found[0] if found else []

    @abc.abstractmethod
    def get_trace_ids_by_name(
        self,
        service_name: str,
        span_name: Optional[str],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        ...

    @abc.abstractmethod
    def get_trace_ids_by_annotation(
        self,
        service_name: str,
        annotation: str,
        value: Optional[bytes],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        ...

    @abc.abstractmethod
    def get_traces_duration(self, trace_ids: Sequence[int]) -> List[TraceIdDuration]:
        ...

    @abc.abstractmethod
    def get_all_service_names(self) -> Set[str]:
        ...

    @abc.abstractmethod
    def get_span_names(self, service: str) -> Set[str]:
        ...


class SpanStore(WriteSpanStore, ReadSpanStore, abc.ABC):
    """The unified store interface (SpanStore.scala:26)."""


class FanoutWriteSpanStore(WriteSpanStore):
    """Replicate writes to several stores (SpanStore.scala:38)."""

    def __init__(self, *stores: WriteSpanStore):
        self.stores = stores

    def apply(self, spans: Sequence[Span]) -> None:
        for s in self.stores:
            s.apply(spans)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        for s in self.stores:
            s.set_time_to_live(trace_id, ttl_seconds)

    def close(self) -> None:
        for s in self.stores:
            s.close()
