"""Host page planner for the paged span layout (r19).

The ring's skew tax is geometric: one global FIFO means a 10k-span
batch trace and a 1-span health poll compete for the same slot window,
so keeping a slow trace complete requires provisioning the whole ring
for churn-rate x trace-lifetime. The paged layout (the "Ragged Paged
Attention" design, PAPERS.md) carves the SAME span arena into
``capacity / page_rows`` fixed pages allocated from a free list:

- big traces (>= page_rows/2 spans in a unit, or already holding an
  open page) get EXCLUSIVE pages chained per trace — their rows are
  block-contiguous for the Pallas page gather and survive together;
- small traces share a communal open page (a 1-span poll costs one
  row, not a page) — page rows are validated per (slot, epoch) at read
  time, so sharing is free;
- reclaim takes the least-recently-WRITTEN non-open page, captures its
  rows through the cold-tier path, splices it out of every owner's
  chain, and hands it back with a fresh epoch.

gids stay epoch-encoded: ``gid = page_epoch * capacity + slot`` with
``slot = page * page_rows + offset``, so ``slot == gid % capacity``
and every ring-scan liveness check in store/device.py works unchanged.

Everything here is a PURE function of the unit stream (chunk trace-id
sequences in feed order), which is what keeps WAL replay and the crash
harness bitwise: replaying the same units re-derives the same claims.
The ``recent``/``note_seq`` memo covers the pipelined-save window where
stage-1 planning runs ahead of the device frontier — a checkpoint's
planner snapshot may include units the gathered state hasn't applied
yet, and replay must REUSE those recorded claims instead of
re-planning them on top of the snapshot.

Concurrency: one planner lock, ordered after the encode lock (stage-1
plans while holding store._lock) and before the capture/commit locks.
"""

from __future__ import annotations

import threading
from collections import Counter, OrderedDict, deque
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

# Units planned while a checkpoint was in flight must be replayable
# from the snapshot: keep this many recent unit plans keyed by WAL seq
# (>= any sane pipeline depth + stage buffers).
RECENT_PLANS = 64

# A trace addressable through the page table spans at most
# config.page_max_chain pages; beyond that it stays correct but its
# reads fall back to the exact ring scan (bounded host memory).


class ChunkPlan(NamedTuple):
    span_slot: np.ndarray       # i32 [n_spans]
    span_gid: np.ndarray        # i64 [n_spans]
    reclaim_pages: np.ndarray   # i32 [k] pages this chunk invalidates


class UnitPlan(NamedTuple):
    chunks: Tuple[ChunkPlan, ...]
    # (lo, hi) gid ranges of every page the unit reclaims — captured by
    # TpuSpanStore._capture_pages BEFORE the unit's launch so the
    # captured-before-overwrite invariant holds per page.
    reclaims: Tuple[Tuple[int, int], ...]


class _Trace:
    __slots__ = ("chain", "live", "overflowed")

    def __init__(self):
        self.chain: List[Tuple[int, int]] = []  # (page, epoch)
        self.live = 0
        self.overflowed = False


class PagePlanner:
    """Deterministic free-list page allocator + per-trace page table.

    All mutable fields below are guarded-by: _lock (plan_unit runs
    under the store encode lock as well; queries and metrics take only
    the planner lock).
    """

    def __init__(self, config):
        if not config.paged_enabled:
            raise ValueError("PagePlanner requires layout='paged'")
        R = int(config.page_rows)
        cap = int(config.capacity)
        if R < 8 or (R & (R - 1)) != 0:
            raise ValueError("page_rows must be a power of two >= 8")
        if cap % R != 0:
            raise ValueError("capacity must be a multiple of page_rows")
        n_pages = cap // R
        if n_pages < 8:
            raise ValueError(
                "paged layout needs >= 8 pages "
                f"(capacity {cap} / page_rows {R} = {n_pages})")
        self.config = config
        self.R = R
        self.capacity = cap
        self.n_pages = n_pages
        self.max_chain = int(config.page_max_chain)
        self.big_thresh = max(1, R // 2)
        # At most this many traces keep an open exclusive page; past it
        # the least-recently-written open page is closed (stays active
        # and reclaimable — no data moves).
        self.max_open = max(1, n_pages // 4)
        self._lock = threading.Lock()  # lock-order: 15 paged-planner
        # ---- page pool (guarded-by: _lock) ----
        self.free = deque(range(n_pages))
        self.page_epoch = [-1] * n_pages     # -1 = free
        self.page_fill = [0] * n_pages
        self.page_touch = [0] * n_pages      # last-write stamp
        self.page_owners: List[List[int]] = [[] for _ in range(n_pages)]
        self._owner_sets: List[set] = [set() for _ in range(n_pages)]
        self.open_shared: Optional[int] = None
        self.open_excl: Dict[int, int] = {}  # tid -> page
        self.traces: Dict[int, _Trace] = {}
        self.epoch_next = 0
        self.touch_next = 1
        self.reclaims_total = 0
        # ---- WAL replay memo (guarded-by: _lock) ----
        self.last_seq = 0
        self.recent: "OrderedDict[int, UnitPlan]" = OrderedDict()
        self._pending: Optional[UnitPlan] = None

    # -- planning ------------------------------------------------------

    def plan_unit(self, chunk_tids: List[np.ndarray],
                  wal_seq: Optional[int] = None) -> UnitPlan:
        """Assign a (slot, gid) pair to every span of every chunk and
        decide which pages the unit reclaims. ``chunk_tids`` is the
        per-chunk trace-id column (valid rows only), in feed order.
        During WAL replay ``wal_seq`` selects a recorded plan for units
        the snapshot already planned (seq <= last_seq) — state is NOT
        mutated for those."""
        with self._lock:
            if wal_seq is not None and wal_seq <= self.last_seq:
                plan = self.recent.get(wal_seq)
                if plan is None:
                    raise KeyError(
                        f"paged plan for WAL seq {wal_seq} fell out of "
                        f"the {RECENT_PLANS}-unit replay memo")
                return plan
            unit_touched: set = set()
            reclaims: List[Tuple[int, int]] = []
            chunks = []
            for tids in chunk_tids:
                chunks.append(
                    self._plan_chunk(np.asarray(tids), unit_touched,
                                     reclaims))
            plan = UnitPlan(tuple(chunks), tuple(reclaims))
            self._pending = plan
            if wal_seq is not None:
                self._note_seq_locked(wal_seq)
            return plan

    def note_seq(self, wal_seq: int) -> None:
        """Key the plan made by the immediately preceding plan_unit to
        its WAL seq (the store calls this right after _journal_group,
        still under the encode lock — append order == feed order)."""
        with self._lock:
            self._note_seq_locked(wal_seq)

    def _note_seq_locked(self, wal_seq: int) -> None:
        if self._pending is None:
            return
        self.recent[wal_seq] = self._pending
        self._pending = None
        self.last_seq = max(self.last_seq, wal_seq)
        while len(self.recent) > RECENT_PLANS:
            self.recent.popitem(last=False)

    def _plan_chunk(self, tids: np.ndarray, unit_touched: set,
                    reclaims: List[Tuple[int, int]]) -> ChunkPlan:
        n = len(tids)
        slots = np.empty(n, np.int32)
        gids = np.empty(n, np.int64)
        counts = Counter(int(t) for t in tids)
        chunk_reclaims: List[int] = []
        R = self.R
        # Trace-granular LRW: a WRITING trace refreshes its whole live
        # chain before this chunk claims pages, so reclaim prefers
        # pages of IDLE traces over earlier pages of still-active ones.
        # This is the retention win over the FIFO ring — a long-running
        # trace's old spans survive wrap as long as it keeps writing —
        # and it stays deterministic from the unit stream (insertion-
        # ordered iteration, monotone stamps), which WAL replay needs.
        for tid in counts:
            ent = self.traces.get(tid)
            if ent is None:
                continue
            for page, epoch in ent.chain:
                if self.page_epoch[page] == epoch:
                    self.page_touch[page] = self.touch_next
                    self.touch_next += 1
        for i in range(n):
            tid = int(tids[i])
            big = tid in self.open_excl or counts[tid] >= self.big_thresh
            if big:
                page = self.open_excl.get(tid)
                if page is None or self.page_fill[page] >= R:
                    page = self._claim(unit_touched, reclaims,
                                       chunk_reclaims)
                    self._open_excl_put(tid, page)
            else:
                page = self.open_shared
                if page is None or self.page_fill[page] >= R:
                    page = self._claim(unit_touched, reclaims,
                                       chunk_reclaims)
                    self.open_shared = page
            j = self.page_fill[page]
            self.page_fill[page] = j + 1
            slots[i] = page * R + j
            gids[i] = self.page_epoch[page] * self.capacity + page * R + j
            self.page_touch[page] = self.touch_next
            self.touch_next += 1
            unit_touched.add(page)
            if tid not in self._owner_sets[page]:
                self._owner_sets[page].add(tid)
                self.page_owners[page].append(tid)
            self._track(tid, page, self.page_epoch[page])
        return ChunkPlan(slots, gids,
                         np.asarray(chunk_reclaims, np.int32))

    def _track(self, tid: int, page: int, epoch: int) -> None:
        ent = self.traces.get(tid)
        if ent is None:
            ent = self.traces[tid] = _Trace()
        key = (page, epoch)
        if key not in ent.chain:
            ent.chain.append(key)
            ent.live += 1
            if len(ent.chain) > self.max_chain:
                # Stop page-addressing this trace: its reads fall back
                # to the exact ring scan until its pages all die.
                ent.chain.pop(0)
                ent.overflowed = True

    def _open_excl_put(self, tid: int, page: int) -> None:
        self.open_excl[tid] = page
        if len(self.open_excl) > self.max_open:
            victim = min(
                self.open_excl,
                key=lambda t: (self.page_touch[self.open_excl[t]], t),
            )
            if victim != tid:
                del self.open_excl[victim]
            else:  # pragma: no cover - max_open >= 1 keeps tid
                self.open_excl.pop(
                    next(iter(k for k in self.open_excl if k != tid)),
                    None)

    def _claim(self, unit_touched: set, reclaims, chunk_reclaims) -> int:
        if self.free:
            page = self.free.popleft()
        else:
            page = self._pick_victim(unit_touched)
            self._reclaim(page, reclaims, chunk_reclaims)
        self.page_epoch[page] = self.epoch_next
        self.epoch_next += 1
        self.page_fill[page] = 0
        self.page_owners[page] = []
        self._owner_sets[page] = set()
        self.page_touch[page] = self.touch_next
        self.touch_next += 1
        unit_touched.add(page)
        return page

    def _pick_victim(self, unit_touched: set) -> int:
        """Least-recently-written active page that is neither open nor
        already touched by this unit (its rows must be capturable
        BEFORE the unit's launch). The paged span budget in
        store/tpu.py bounds per-unit page demand well under the pool,
        so a candidate always exists for conforming units."""
        open_set = set(self.open_excl.values())
        if self.open_shared is not None:
            open_set.add(self.open_shared)
        best = -1
        best_touch = None
        for p in range(self.n_pages):
            if self.page_epoch[p] < 0 or p in open_set \
                    or p in unit_touched:
                continue
            t = self.page_touch[p]
            if best_touch is None or t < best_touch:
                best, best_touch = p, t
        if best < 0:
            raise RuntimeError(
                "page pool exhausted within one unit — unit exceeds "
                "the paged span budget (store bug)")
        return best

    def _reclaim(self, page: int, reclaims, chunk_reclaims) -> None:
        old_e = self.page_epoch[page]
        lo = old_e * self.capacity + page * self.R
        reclaims.append((lo, lo + self.R))
        chunk_reclaims.append(page)
        for tid in self.page_owners[page]:
            ent = self.traces.get(tid)
            if ent is None:
                continue
            try:
                ent.chain.remove((page, old_e))
            except ValueError:
                pass  # entry was dropped by a max_chain overflow
            ent.live -= 1
            if ent.live <= 0:
                del self.traces[tid]
                self.open_excl.pop(tid, None)
        self.reclaims_total += 1

    # -- reads ---------------------------------------------------------

    def chains_for(self, qids):
        """(pages i32 [K], epochs i64 [K]) covering every page any of
        ``qids`` has live rows in, deduped (small traces share pages).
        Returns None when any queried trace overflowed its chain —
        caller must use the exact ring-scan gather. Traces unknown to
        the planner have no live rows and contribute nothing."""
        with self._lock:
            pages: List[int] = []
            epochs: List[int] = []
            seen: set = set()
            for tid in qids:
                ent = self.traces.get(int(tid))
                if ent is None:
                    continue
                if ent.overflowed:
                    return None
                for (p, e) in ent.chain:
                    if p not in seen:
                        seen.add(p)
                        pages.append(p)
                        epochs.append(e)
            return (np.asarray(pages, np.int32),
                    np.asarray(epochs, np.int64))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            n_free = len(self.free)
            return {
                "pages_free": n_free,
                "pages_active": self.n_pages - n_free,
                "page_reclaims": self.reclaims_total,
            }

    # -- checkpoint ----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able planner state for the rev-18 checkpoint meta,
        including the recent-plan memo (units planned ahead of the
        gathered device frontier replay from here)."""
        with self._lock:
            return {
                "free": list(self.free),
                "epoch": list(self.page_epoch),
                "fill": list(self.page_fill),
                "touch": list(self.page_touch),
                "owners": [list(o) for o in self.page_owners],
                "open_shared": self.open_shared,
                "open_excl": [[t, p] for t, p in self.open_excl.items()],
                "traces": [
                    [t, [[p, e] for p, e in ent.chain], ent.live,
                     bool(ent.overflowed)]
                    for t, ent in self.traces.items()
                ],
                "epoch_next": self.epoch_next,
                "touch_next": self.touch_next,
                "reclaims_total": self.reclaims_total,
                "last_seq": self.last_seq,
                "recent": [
                    [seq, [
                        [c.span_slot.tolist(), c.span_gid.tolist(),
                         c.reclaim_pages.tolist()] for c in plan.chunks
                    ], [list(r) for r in plan.reclaims]]
                    for seq, plan in self.recent.items()
                ],
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            self.free = deque(int(p) for p in snap["free"])
            self.page_epoch = [int(e) for e in snap["epoch"]]
            self.page_fill = [int(f) for f in snap["fill"]]
            self.page_touch = [int(t) for t in snap["touch"]]
            self.page_owners = [[int(t) for t in o]
                                for o in snap["owners"]]
            self._owner_sets = [set(o) for o in self.page_owners]
            self.open_shared = (
                None if snap["open_shared"] is None
                else int(snap["open_shared"]))
            self.open_excl = {int(t): int(p)
                              for t, p in snap["open_excl"]}
            self.traces = {}
            for t, chain, live, over in snap["traces"]:
                ent = _Trace()
                ent.chain = [(int(p), int(e)) for p, e in chain]
                ent.live = int(live)
                ent.overflowed = bool(over)
                self.traces[int(t)] = ent
            self.epoch_next = int(snap["epoch_next"])
            self.touch_next = int(snap["touch_next"])
            self.reclaims_total = int(snap["reclaims_total"])
            self.last_seq = int(snap["last_seq"])
            self.recent = OrderedDict()
            for seq, chunks, reclaims in snap.get("recent", []):
                self.recent[int(seq)] = UnitPlan(
                    tuple(
                        ChunkPlan(np.asarray(s, np.int32),
                                  np.asarray(g, np.int64),
                                  np.asarray(r, np.int32))
                        for s, g, r in chunks),
                    tuple((int(lo), int(hi)) for lo, hi in reclaims),
                )
            self._pending = None

    def rebuild(self, row_gid: np.ndarray, trace_col: np.ndarray,
                wal_applied: int = 0) -> None:
        """Reconstruct the page table from device columns — the compat
        path for snapshots without planner meta (adopt_state, or a
        paged config pointed at a state saved another way). Partial
        pages are NOT reopened (their tails are wasted until reclaim),
        and chain order is epoch order — reads stay exact either way
        because page rows verify per (slot, epoch)."""
        cap, R = self.capacity, self.R
        with self._lock:
            self.free = deque()
            self.open_shared = None
            self.open_excl = {}
            self.traces = {}
            self.recent = OrderedDict()
            self._pending = None
            self.last_seq = int(wal_applied)
            per_trace: Dict[int, List[Tuple[int, int]]] = {}
            max_epoch = -1
            order = []
            for p in range(self.n_pages):
                rows = np.asarray(row_gid[p * R:(p + 1) * R])
                live = rows >= 0
                if not live.any():
                    self.page_epoch[p] = -1
                    self.page_fill[p] = 0
                    self.page_owners[p] = []
                    self._owner_sets[p] = set()
                    self.free.append(p)
                    continue
                e = int(rows[live][0]) // cap
                max_epoch = max(max_epoch, e)
                self.page_epoch[p] = e
                self.page_fill[p] = int(np.nonzero(live)[0][-1]) + 1
                tids = [int(t) for t in
                        np.asarray(trace_col[p * R:(p + 1) * R])[live]]
                owners: List[int] = []
                oset: set = set()
                for t in tids:
                    if t not in oset:
                        oset.add(t)
                        owners.append(t)
                self.page_owners[p] = owners
                self._owner_sets[p] = oset
                order.append((e, p))
                for t in owners:
                    per_trace.setdefault(t, []).append((p, e))
            order.sort()
            for i, (_, p) in enumerate(order):
                self.page_touch[p] = i + 1
            self.touch_next = len(order) + 1
            self.epoch_next = max_epoch + 1
            for t, chain in per_trace.items():
                ent = _Trace()
                ent.chain = sorted(chain, key=lambda pe: pe[1])
                ent.live = len(ent.chain)
                if len(ent.chain) > self.max_chain:
                    ent.chain = ent.chain[-self.max_chain:]
                    ent.overflowed = True
                self.traces[t] = ent
