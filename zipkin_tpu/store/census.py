"""The ONE home of the fused-ingest StableHLO census ceilings.

Per-kernel overhead dominates the target device class (NOTES_r03 §3),
so the scatter/gather/sort counts of the compiled ingest step are the
portable proxy for its TPU cost — the r6 unified index arena exists to
hold them down, and the tier-1 lane gates them every CI run. These
ceilings used to live as three hard-coded copies (bench_smoke docs,
the tier-1 test, the notes); a path change now updates exactly one
number here, consumed by ``scripts/bench_smoke.py`` and
``tests/test_bench_smoke.py``.

History of the measured counts at the smoke shapes:

- r5 split index design: 101 scatters / 6 sorts / 80 gathers;
- r6 unified arena:       95 / 5 / 79;
- r12 counting-sort rank:  95 / 4 / 79 — the ``_fifo_ranks`` argsort
  is replaced by a segmented counting rank (one duplicate-index
  scatter-add + cumsum + one gather, spending exactly the scatter and
  gather the argsort path's unsort freed), deleting the last hot-path
  ``stablehlo.sort`` the index write owned. The argsort path remains
  selectable (``StoreConfig.rank_path``) and bitwise-identical; its
  lowering sits at ARGSORT_STEP_SORTS.
- r13 windowed arena:     +5 scatters / +0 sorts / +2 gathers — the
  EXPLICIT GATED BUMP that buys the windowed Moments-sketch
  (service × time-bucket) cell grid inside the fused step
  (aggregate/windows.py) when ``window_seconds > 0``: +2 scatters
  +1 gather for the exact epoch plane-war, +1 i32 count scatter (3P
  rows), +1 i64 power-sum scatter (4P rows — the only
  serialized-class scatter the feature adds), +1 i32 min/max
  scatter-max (2P rows), +1 gather for the live-epoch check. The
  arena is OPT-IN at the library layer (``StoreConfig`` default 0 —
  the daemon turns it on via ``--window-seconds``), so the BASE
  lowering stays 95/4/79 and the window-on lowering sits exactly at
  BASE + WINDOW_BUMP (bench_smoke's windows phase gates both).

Raise a ceiling only with a note here explaining what bought the
extra launches.
"""

# Fused-step BASE ceilings: the default (window-off) lowering, gated
# in tier-1 against the main smoke stream (tests/test_bench_smoke.py).
BASE_STEP_SCATTERS = 95
BASE_STEP_SORTS = 4
BASE_STEP_GATHERS = 79

# The r13 windowed-arena bump (window_seconds > 0): the gated extra
# launches the feature is allowed to spend inside the fused step.
WINDOW_BUMP_SCATTERS = 5
WINDOW_BUMP_GATHERS = 2

# Overall ceilings — the window-on lowering (every optional path
# engaged); bench_smoke's windows phase gates the on-lowering at
# EXACTLY these counts.
MAX_STEP_SCATTERS = BASE_STEP_SCATTERS + WINDOW_BUMP_SCATTERS
MAX_STEP_SORTS = BASE_STEP_SORTS
MAX_STEP_GATHERS = BASE_STEP_GATHERS + WINDOW_BUMP_GATHERS

# The argsort rank path's sort count — the pre-r12 ceiling, still the
# expected lowering when rank_path="argsort" (or the wm_shift == 0 /
# scratch-infeasible fallbacks) is active.
ARGSORT_STEP_SORTS = 5

# Stage-1 sketch-mirror budget: the host COO delta (store/mirror,
# riding the hot encode path since r11) may add at most this fraction
# to the encode stage — bench_smoke's ingest-structure phase measures
# it paired and the tier-1 test gates it.
MAX_MIRROR_DELTA_RATIO = 0.05
