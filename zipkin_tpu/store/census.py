"""The ONE home of the fused-ingest StableHLO census ceilings.

Per-kernel overhead dominates the target device class (NOTES_r03 §3),
so the scatter/gather/sort counts of the compiled ingest step are the
portable proxy for its TPU cost — the r6 unified index arena exists to
hold them down, and the tier-1 lane gates them every CI run. These
ceilings used to live as three hard-coded copies (bench_smoke docs,
the tier-1 test, the notes); a path change now updates exactly one
number here, consumed by ``scripts/bench_smoke.py`` and
``tests/test_bench_smoke.py``.

History of the measured counts at the smoke shapes:

- r5 split index design: 101 scatters / 6 sorts / 80 gathers;
- r6 unified arena:       95 / 5 / 79;
- r12 counting-sort rank:  95 / 4 / 79 — the ``_fifo_ranks`` argsort
  is replaced by a segmented counting rank (one duplicate-index
  scatter-add + cumsum + one gather, spending exactly the scatter and
  gather the argsort path's unsort freed), deleting the last hot-path
  ``stablehlo.sort`` the index write owned. The argsort path remains
  selectable (``StoreConfig.rank_path``) and bitwise-identical; its
  lowering sits at ARGSORT_STEP_SORTS.

Raise a ceiling only with a NOTES entry explaining what bought the
extra launches.
"""

# Fused-step ceilings (the tier-1 gate, tests/test_bench_smoke.py).
MAX_STEP_SCATTERS = 95
MAX_STEP_SORTS = 4
MAX_STEP_GATHERS = 79

# The argsort rank path's sort count — the pre-r12 ceiling, still the
# expected lowering when rank_path="argsort" (or the wm_shift == 0 /
# scratch-infeasible fallbacks) is active.
ARGSORT_STEP_SORTS = 5

# Stage-1 sketch-mirror budget: the host COO delta (store/mirror,
# riding the hot encode path since r11) may add at most this fraction
# to the encode stage — bench_smoke's ingest-structure phase measures
# it paired and the tier-1 test gates it.
MAX_MIRROR_DELTA_RATIO = 0.05
