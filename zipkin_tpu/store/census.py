"""The ONE home of the fused-ingest StableHLO census ceilings.

Per-kernel overhead dominates the target device class (NOTES_r03 §3),
so the scatter/gather/sort counts of the compiled ingest step are the
portable proxy for its TPU cost — the r6 unified index arena exists to
hold them down, and the tier-1 lane gates them every CI run. These
ceilings used to live as three hard-coded copies (bench_smoke docs,
the tier-1 test, the notes); a path change now updates exactly one
number here, consumed by ``scripts/bench_smoke.py`` and
``tests/test_bench_smoke.py``.

r19 restructures the constants into a LOWERING TABLE: the base counts
plus one explicit gated bump per optional layout/feature, so a new
layout cannot ride ungated — adding one REQUIRES adding its ``+NAME``
row here, and ``expected_census`` composes any feature combination
(bench_smoke's windows and paged phases both gate at exact equality
against the composed row).

History of the measured counts at the smoke shapes:

- r5 split index design: 101 scatters / 6 sorts / 80 gathers;
- r6 unified arena:       95 / 5 / 79;
- r12 counting-sort rank:  95 / 4 / 79 — the ``_fifo_ranks`` argsort
  is replaced by a segmented counting rank (one duplicate-index
  scatter-add + cumsum + one gather, spending exactly the scatter and
  gather the argsort path's unsort freed), deleting the last hot-path
  ``stablehlo.sort`` the index write owned. The argsort path remains
  selectable (``StoreConfig.rank_path``) and bitwise-identical; its
  lowering sits at ARGSORT_STEP_SORTS.
- r13 windowed arena:     +5 scatters / +0 sorts / +2 gathers — the
  EXPLICIT GATED BUMP that buys the windowed Moments-sketch
  (service × time-bucket) cell grid inside the fused step
  (aggregate/windows.py) when ``window_seconds > 0``: +2 scatters
  +1 gather for the exact epoch plane-war, +1 i32 count scatter (3P
  rows), +1 i64 power-sum scatter (4P rows — the only
  serialized-class scatter the feature adds), +1 i32 min/max
  scatter-max (2P rows), +1 gather for the live-epoch check. The
  arena is OPT-IN at the library layer (``StoreConfig`` default 0 —
  the daemon turns it on via ``--window-seconds``), so the BASE
  lowering stays 95/4/79 and the window-on lowering sits exactly at
  BASE + WINDOW_BUMP (bench_smoke's windows phase gates both).
- r19 paged layout:       +2 scatters / +0 sorts / +2 gathers —
  ``layout="paged"`` (store/paged): the reclaimed-page row_gid
  invalidation is ONE i64 ring write (= 2 i32 plane scatters through
  the same _uset discipline as every other plane pair), and the
  side-ring index segments gather their owning span's planner gid
  from the batch column (+1 gather each for ann/bann) instead of
  deriving it from write_pos arithmetic. Slot/gid assignment itself
  moves HOST-side into the page planner, so the step spends nothing
  on allocation. Additive with the window bump (measured: paged+win
  == BASE + WINDOW + PAGED exactly).

Raise a ceiling only with a note here explaining what bought the
extra launches.
"""

# The per-layout lowering table: (scatters, sorts, gathers) — "BASE"
# is the default ring/window-off lowering; every "+NAME" row is the
# explicit gated bump one optional feature may spend inside the fused
# step. New layouts MUST add a row (test_bench_smoke gates the table's
# composed rows at exact equality, so an ungated path shows up as a
# census mismatch, not a silent regression).
LOWERING_TABLE = {
    "BASE": (95, 4, 79),
    "+WINDOW": (5, 0, 2),   # r13 windowed Moments-sketch arena
    "+PAGED": (2, 0, 2),    # r19 paged span layout
}


def expected_census(*bumps: str):
    """(scatters, sorts, gathers) ceiling for BASE plus the named
    bumps, e.g. ``expected_census("+WINDOW", "+PAGED")``. Unknown bump
    names raise — the "can't ride ungated" contract."""
    s, o, g = LOWERING_TABLE["BASE"]
    for b in bumps:
        if b == "BASE":
            continue
        bs, bo, bg = LOWERING_TABLE[b]
        s, o, g = s + bs, o + bo, g + bg
    return s, o, g


# Fused-step BASE ceilings: the default (window-off) lowering, gated
# in tier-1 against the main smoke stream (tests/test_bench_smoke.py).
BASE_STEP_SCATTERS, BASE_STEP_SORTS, BASE_STEP_GATHERS = (
    LOWERING_TABLE["BASE"])

# The r13 windowed-arena bump (window_seconds > 0): the gated extra
# launches the feature is allowed to spend inside the fused step.
WINDOW_BUMP_SCATTERS, _, WINDOW_BUMP_GATHERS = LOWERING_TABLE["+WINDOW"]

# The r19 paged-layout bump (layout="paged"): see the history note.
PAGED_BUMP_SCATTERS, _, PAGED_BUMP_GATHERS = LOWERING_TABLE["+PAGED"]

# Overall ceilings — every optional path engaged (window + paged);
# bench_smoke's feature phases gate each on-lowering at EXACTLY its
# composed table row, so these are pure upper bounds for coarse gates.
MAX_STEP_SCATTERS, MAX_STEP_SORTS, MAX_STEP_GATHERS = expected_census(
    "+WINDOW", "+PAGED")

# The argsort rank path's sort count — the pre-r12 ceiling, still the
# expected lowering when rank_path="argsort" (or the wm_shift == 0 /
# scratch-infeasible fallbacks) is active.
ARGSORT_STEP_SORTS = 5

# Stage-1 sketch-mirror budget: the host COO delta (store/mirror,
# riding the hot encode path since r11) may add at most this fraction
# to the encode stage — bench_smoke's ingest-structure phase measures
# it paired and the tier-1 test gates it.
MAX_MIRROR_DELTA_RATIO = 0.05
