"""Host-mirrored monoid sketches: the query engine's zero-dispatch tier.

The fused ingest step maintains six LIFETIME aggregate arrays on the
device — per-service duration log-histogram, annotation-host service
counts, span-name presence, top-annotation / top-binary-key count
matrices, and the distinct-trace HyperLogLog. Every one of them is a
monoid updated by a masked scatter-add (or scatter-max) over the
batch's columns, and every input to that scatter is ALREADY ON THE
HOST in stage 1 of the write path (the encoded ``SpanBatch`` plus the
``name_lc``/``indexable`` sidecars). So the aggregates can be mirrored
host-side for free: stage 1 computes a tiny COO delta per launch unit
(``SketchMirror.delta_of``), and the commit stage folds it in inside
the SAME write-lock hold as the donating device swap
(``TpuSpanStore._commit_unit``) — the mirror is never behind the
store's write frontier, and answering quantiles / top-k / cardinality
/ catalog queries costs ZERO device round-trips (the ~110 ms dispatch
floor the resident query engine exists to kill; see
docs/QUERY_ENGINE.md).

Exactness contract: the mirror's arrays are numerically IDENTICAL to
the device arrays — same dtypes (int32 counts, so overflow behavior
matches), same masks (the ``a_svc_ok``/``np_ok``/``av_ok``/``bk_ok``
predicates of ``dev._ingest_core``), same bucket math
(``ops.quantile.bucket_index`` float32 twin), and the same murmur3
hash family for the HLL (``store.archive.sketches``
``np_hash2_32``/``np_clz32``, seeds 101/202 like ``ops.hll.update``).
tests/test_query_engine.py gates sketch-tier answers bitwise against
the device read path.

After a state swap the mirror didn't see (checkpoint restore,
``adopt_state``) it is marked COLD and lazily resynced from the device
arrays in one batched fetch (``TpuSpanStore.ensure_sketch_mirror``) —
exact by construction, since mirror state ≡ device state.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Sequence, Tuple

import numpy as np

from zipkin_tpu.aggregate import windows as win
from zipkin_tpu.models.constants import FIRST_USER_ANNOTATION_ID
from zipkin_tpu.ops.hashing import split64
from zipkin_tpu.store.archive.sketches import (
    hist_bucket_index,
    np_clz32,
    np_hash2_32,
)

_U32 = np.uint32


class SketchDelta(NamedTuple):
    """One launch unit's aggregate increments in COO form (flat indices
    into each mirror array; every index is pre-masked — invalid rows
    are already dropped, mirroring the device's ``where(ok, idx, -1)``
    scatter convention). ``win`` carries the windowed-arena rows
    PER CHUNK (a chained unit runs one device step per chunk and the
    epoch war is stateful, so chunks must fold in launch order)."""

    hist_idx: np.ndarray  # flat into svc_hist [S*B]
    svc_idx: np.ndarray  # into ann_svc_counts [S]
    name_idx: np.ndarray  # flat into name_presence [S*N]
    av_idx: np.ndarray  # flat into ann_value_counts [S*A]
    bk_idx: np.ndarray  # flat into bann_key_counts [S*K]
    hll_idx: np.ndarray  # HLL register indices
    hll_rank: np.ndarray  # matching ranks (scatter-max)
    win: Tuple[win.WindowUpdate, ...] = ()  # per-chunk window rows


class SketchMirror:
    """Host twins of the device's lifetime aggregate arrays (see module
    docstring). Thread-safe: ``apply`` runs on the commit path,
    ``adopt`` on a resync, readers on API threads."""

    def __init__(self, config, dicts=None):
        self.config = config
        c = config
        self.gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
        self._lock = threading.Lock()  # lock-order: 50 mirror
        self._warm = True  # a fresh store's zeros are warm; guarded-by: _lock
        S = c.max_services
        self.svc_hist = np.zeros((S, c.quantile_buckets), np.int32)  # guarded-by: _lock
        self.ann_svc_counts = np.zeros(S, np.int32)  # guarded-by: _lock
        self.name_presence = np.zeros((S, c.max_span_names), np.int32)  # guarded-by: _lock
        self.ann_value_counts = np.zeros(
            (S, c.max_annotation_values), np.int32)  # guarded-by: _lock
        self.bann_key_counts = np.zeros((S, c.max_binary_keys), np.int32)  # guarded-by: _lock
        self.hll_traces = np.zeros(1 << c.hll_p, np.int32)  # guarded-by: _lock
        # Windowed Moments-sketch arena twins (aggregate/windows.py):
        # same dtypes/fills as the device arrays, folded by the same
        # integer adds/maxes → bitwise-equal cells. ``dicts`` resolves
        # the "error" annotation/key ids for the per-span error bit
        # (None = no dictionary ⇒ no error detection).
        self.dicts = dicts
        Wn = c.win_slots
        self.win_epoch = np.full(Wn, -1, np.int64)  # guarded-by: _lock
        self.win_counts = np.zeros((S, Wn, win.N_COUNT_FIELDS), np.int32)  # guarded-by: _lock
        self.win_sums = np.zeros((S, Wn, win.N_SUM_FIELDS), np.int64)  # guarded-by: _lock
        self.win_mm = np.full((S, Wn, win.N_MM_FIELDS), win.I32_MIN,
                              np.int32)  # guarded-by: _lock
        # Process-lifetime monotonic fold counters (the
        # zipkin_window_* Prometheus families): unaffected by ring
        # self-clears or adoption resyncs, so scrapes never regress.
        self.win_spans_total = 0
        self.win_errors_total = 0

    # -- state ----------------------------------------------------------

    @property
    def warm(self) -> bool:
        with self._lock:
            return self._warm

    def mark_cold(self) -> None:
        """The device state was swapped without a delta (checkpoint
        restore, adopt_state): the mirror must resync before serving."""
        with self._lock:
            self._warm = False

    def adopt(self, svc_hist, ann_svc_counts, name_presence,
              ann_value_counts, bann_key_counts, hll_traces,
              win_epoch=None, win_counts=None, win_sums=None,
              win_mm=None) -> None:
        """Resync from already-fetched device arrays. Callers fetch
        under the store's READ lock (so no commit's delta can be
        concurrent with the snapshot) and adopt after — a delta from a
        LATER commit applying after this simply lands on top. The
        window arena rides the same snapshot (the lifetime fold
        counters don't: they are process-monotonic by contract)."""
        with self._lock:
            self.svc_hist = np.array(svc_hist, np.int32)
            self.ann_svc_counts = np.array(ann_svc_counts, np.int32)
            self.name_presence = np.array(name_presence, np.int32)
            self.ann_value_counts = np.array(ann_value_counts, np.int32)
            self.bann_key_counts = np.array(bann_key_counts, np.int32)
            self.hll_traces = np.array(hll_traces, np.int32)
            if win_epoch is not None:
                self.win_epoch = np.array(win_epoch, np.int64)
                self.win_counts = np.array(win_counts, np.int32)
                self.win_sums = np.array(win_sums, np.int64)
                self.win_mm = np.array(win_mm, np.int32)
            self._warm = True

    # -- write path ------------------------------------------------------

    def delta_of(self, group) -> SketchDelta:
        """COO delta for one planned launch group (stage 1, host side):
        ``group`` is the ``_plan_units`` list of (SpanBatch, name_lc,
        indexable) parts. Pure function — no lock, no device.

        LAYOUT-INDEPENDENT by contract: this reads batch CONTENT
        columns only (ids, services, durations, annotations) — never
        row placement (write_pos arithmetic, or the paged layout's
        span_slot/span_gid planner columns), so ring and paged stores
        fed the same stream build bitwise-equal mirrors.
        tests/test_paged.py gates this (mirror arrays compared
        element-for-element across layouts)."""
        c = self.config
        S = c.max_services
        hist_parts, svc_parts, name_parts, av_parts, bk_parts = (
            [], [], [], [], [])
        hll_i_parts, hll_r_parts = [], []
        for batch, name_lc, indexable in group:
            b = batch
            # Per-service duration histogram (svc_ok in _ingest_core).
            svc = np.asarray(b.service_id, np.int64)
            ok = (svc >= 0) & (svc < S) & (b.duration >= 0)
            if ok.any():
                bidx = hist_bucket_index(
                    b.duration[ok], c.quantile_buckets, self.gamma, 1.0)
                hist_parts.append(svc[ok] * c.quantile_buckets + bidx)
            # Distinct-trace HLL (seeds 101/202, ops.hll.update).
            tid = np.asarray(b.trace_id, np.int64)
            if tid.size:
                hi, lo = split64(tid)
                # Register-count mask from CONFIG, not the live array:
                # delta_of is stage 1's lock-free pure function, and
                # reading a _lock-guarded array here (even just .size)
                # would break that contract (graftlint guarded-by).
                hll_i_parts.append(
                    (np_hash2_32(hi, lo, 101)
                     & _U32((1 << c.hll_p) - 1)).astype(np.int64))
                hll_r_parts.append(
                    (np_clz32(np_hash2_32(hi, lo, 202)) + 1).astype(
                        np.int32))
            # Annotation-host aggregates.
            a_svc = np.asarray(b.ann_service_id, np.int64)
            a_ok = (a_svc >= 0) & (a_svc < S)
            if a_ok.any():
                svc_parts.append(a_svc[a_ok])
                aidx = b.ann_span_idx
                # Span-name presence: indexable ann-hosted spans with a
                # resolved (and representable) name (np_ok).
                name = np.asarray(b.name_id, np.int64)[aidx]
                name_lc_a = np.asarray(name_lc, np.int64)[aidx]
                ixa = np.asarray(indexable, bool)[aidx]
                np_ok = (a_ok & ixa & (name_lc_a >= 0) & (name >= 0)
                         & (name < c.max_span_names))
                if np_ok.any():
                    name_parts.append(
                        a_svc[np_ok] * c.max_span_names + name[np_ok])
                # Top annotations (user annotations only — av_ok).
                av = np.asarray(b.ann_value_id, np.int64)
                av_ok = (a_ok & (av >= FIRST_USER_ANNOTATION_ID)
                         & (av < c.max_annotation_values))
                if av_ok.any():
                    av_parts.append(
                        a_svc[av_ok] * c.max_annotation_values
                        + av[av_ok])
            # Top binary keys (bk_ok).
            bk_svc = np.asarray(b.bann_service_id, np.int64)
            bk = np.asarray(b.bann_key_id, np.int64)
            bk_ok = ((bk_svc >= 0) & (bk_svc < S) & (bk >= 0)
                     & (bk < c.max_binary_keys))
            if bk_ok.any():
                bk_parts.append(
                    bk_svc[bk_ok] * c.max_binary_keys + bk[bk_ok])

        def cat(parts):
            return (np.concatenate(parts) if parts
                    else np.zeros(0, np.int64))

        return SketchDelta(
            cat(hist_parts), cat(svc_parts), cat(name_parts),
            cat(av_parts), cat(bk_parts), cat(hll_i_parts),
            (np.concatenate(hll_r_parts) if hll_r_parts
             else np.zeros(0, np.int32)),
            win=self._window_updates(group),
        )

    def _window_updates(self, group):
        """Per-chunk windowed-arena rows — one WindowUpdate per launch
        chunk, pre-masked exactly like the device step's w_ok (the
        chained unit runs one step per chunk, and the epoch war is
        stateful, so apply() folds them in order)."""
        c = self.config
        if not c.window_enabled:
            return ()
        ea, eb = (win.error_ids(self.dicts) if self.dicts is not None
                  else (-1, -1))
        return tuple(
            win.plan_window_update(
                batch, win.span_error_flags(batch, ea, eb), c)
            for batch, _, _ in group
        )

    def apply(self, delta: SketchDelta) -> None:  # called-under: _rw.write
        """Fold one unit's delta in — called from the commit stage
        INSIDE the store's write-lock hold, immediately before the
        frontier bump, so sketch-tier reads at frontier F always
        include every commit ≤ F."""
        with self._lock:
            np.add.at(self.svc_hist.reshape(-1), delta.hist_idx,
                      np.int32(1))
            np.add.at(self.ann_svc_counts, delta.svc_idx, np.int32(1))
            np.add.at(self.name_presence.reshape(-1), delta.name_idx,
                      np.int32(1))
            np.add.at(self.ann_value_counts.reshape(-1), delta.av_idx,
                      np.int32(1))
            np.add.at(self.bann_key_counts.reshape(-1), delta.bk_idx,
                      np.int32(1))
            np.maximum.at(self.hll_traces, delta.hll_idx,
                          delta.hll_rank)
            for u in delta.win:
                spans, errs = win.apply_window_update(
                    u, self.win_epoch, self.win_counts,
                    self.win_sums, self.win_mm)
                self.win_spans_total += spans
                self.win_errors_total += errs

    # -- reads (engine sketch tier) --------------------------------------

    def service_presence(self) -> np.ndarray:
        with self._lock:
            return self.ann_svc_counts > 0

    def name_row(self, svc: int) -> np.ndarray:
        with self._lock:
            return self.name_presence[svc].copy()

    def hist_row(self, svc: int) -> np.ndarray:
        with self._lock:
            return self.svc_hist[svc].copy()

    def ann_value_row(self, svc: int) -> np.ndarray:
        with self._lock:
            return self.ann_value_counts[svc].copy()

    def bann_key_row(self, svc: int) -> np.ndarray:
        with self._lock:
            return self.bann_key_counts[svc].copy()

    def hll_registers(self) -> np.ndarray:
        with self._lock:
            return self.hll_traces.copy()

    def window_row(self, svc: int):
        """(epoch, counts[svc], sums[svc], mm[svc]) copies — one
        service's windowed cells for the analytics read path."""
        with self._lock:
            return (self.win_epoch.copy(), self.win_counts[svc].copy(),
                    self.win_sums[svc].copy(), self.win_mm[svc].copy())

    def window_arrays(self):
        """Snapshot of the full window arena (bitwise gates + the
        all-service heatmap)."""
        with self._lock:
            return (self.win_epoch.copy(), self.win_counts.copy(),
                    self.win_sums.copy(), self.win_mm.copy())

    def window_live_cells(self) -> int:
        """Occupied (service, bucket) cells — the
        zipkin_window_cells_active gauge."""
        with self._lock:
            return int(((self.win_counts[:, :, 0] > 0)
                        & (self.win_epoch >= 0)[None, :]).sum())

    def arrays(self) -> Sequence[np.ndarray]:
        """Snapshot of every mirrored array (conformance tests compare
        these bitwise against the device state)."""
        with self._lock:
            return (self.svc_hist.copy(), self.ann_svc_counts.copy(),
                    self.name_presence.copy(),
                    self.ann_value_counts.copy(),
                    self.bann_key_counts.copy(), self.hll_traces.copy(),
                    self.win_epoch.copy(), self.win_counts.copy(),
                    self.win_sums.copy(), self.win_mm.copy())


class FleetMirror:
    """Lazily merged fleet view over N per-shard ``SketchMirror`` twins
    — the sharded store's zero-dispatch sketch tier.

    Every lifetime aggregate is a monoid, so the fleet value is the
    shard values folded by the SAME reduction the in-graph collectives
    use: integer sums for the count arrays (psum), elementwise max for
    the HLL registers (pmax). Integer adds are order-independent, so
    the host fold is bitwise-equal to the device collective.

    The windowed arena needs the epoch rule, not a plain sum: shards
    rotate slot ``w`` independently (each shard's epoch war runs on its
    own ingest), so a slot's merged epoch is the max over shards, and
    only shards AT that epoch contribute counts/sums (a shard still on
    an older epoch received no spans for the newer window — its slot
    holds a different, dead window). min/max cells fold by
    ``np.maximum`` over the contributing shards (I32_MIN fill loses to
    any real value). This is exactly the single-store value: every span
    landed on exactly one shard, and integer adds commute.

    The merge is rebuilt only when ``version_fn()`` (the store's commit
    frontier) moves — steady-state reads are dict lookups into a cached
    ``SketchMirror``, zero device traffic and zero re-merges."""

    def __init__(self, config, mirrors, version_fn):
        self.config = config
        self.gamma = mirrors[0].gamma if mirrors else (
            (1.0 + config.quantile_alpha) / (1.0 - config.quantile_alpha))
        self._mirrors = list(mirrors)
        self._version_fn = version_fn
        # Rank BELOW the shard mirrors' 50: the refresh calls
        # ``SketchMirror.arrays()`` (which takes each mirror's lock)
        # while holding this one.
        self._lock = threading.Lock()  # lock-order: 48 fleet-mirror
        self._merged = None  # guarded-by: _lock
        self._merged_version = None  # guarded-by: _lock

    @property
    def warm(self) -> bool:
        return all(m.warm for m in self._mirrors)

    def mark_cold(self) -> None:
        for m in self._mirrors:
            m.mark_cold()
        with self._lock:
            self._merged = None
            self._merged_version = None

    def _merge_locked(self) -> "SketchMirror":  # called-under: _lock
        version = self._version_fn()
        if (self._merged is not None
                and self._merged_version == version):
            return self._merged
        snaps = [m.arrays() for m in self._mirrors]
        out = SketchMirror(self.config)
        (out.svc_hist, out.ann_svc_counts, out.name_presence,
         out.ann_value_counts, out.bann_key_counts) = (
            sum(np.asarray(s[i]) for s in snaps)
            for i in range(5)
        )
        out.hll_traces = np.maximum.reduce([s[5] for s in snaps])
        if self.config.window_enabled and snaps:
            epochs = np.stack([s[6] for s in snaps])  # [n, Wn]
            merged_epoch = epochs.max(axis=0)
            live = epochs == merged_epoch[None, :]  # [n, Wn]
            counts = np.stack([s[7] for s in snaps])  # [n, S, Wn, f]
            sums = np.stack([s[8] for s in snaps])
            mm = np.stack([s[9] for s in snaps])
            mask = live[:, None, :, None]
            out.win_epoch = merged_epoch
            out.win_counts = np.where(mask, counts, 0).sum(
                axis=0, dtype=counts.dtype)
            out.win_sums = np.where(mask, sums, 0).sum(
                axis=0, dtype=sums.dtype)
            out.win_mm = np.where(mask, mm, win.I32_MIN).max(axis=0)
        self._merged = out
        self._merged_version = version
        return out

    def _view(self) -> "SketchMirror":
        with self._lock:
            return self._merge_locked()

    # Lifetime fold counters: plain sums over the shard mirrors (each
    # span folded into exactly one shard's arena).
    @property
    def win_spans_total(self) -> int:
        return sum(m.win_spans_total for m in self._mirrors)

    @property
    def win_errors_total(self) -> int:
        return sum(m.win_errors_total for m in self._mirrors)

    # -- SketchMirror reader surface (engine sketch tier) ---------------

    def service_presence(self) -> np.ndarray:
        return self._view().ann_svc_counts > 0

    def name_row(self, svc: int) -> np.ndarray:
        return self._view().name_presence[svc].copy()

    def hist_row(self, svc: int) -> np.ndarray:
        return self._view().svc_hist[svc].copy()

    def ann_value_row(self, svc: int) -> np.ndarray:
        return self._view().ann_value_counts[svc].copy()

    def bann_key_row(self, svc: int) -> np.ndarray:
        return self._view().bann_key_counts[svc].copy()

    def hll_registers(self) -> np.ndarray:
        return self._view().hll_traces.copy()

    def window_row(self, svc: int):
        v = self._view()
        return (v.win_epoch.copy(), v.win_counts[svc].copy(),
                v.win_sums[svc].copy(), v.win_mm[svc].copy())

    def window_arrays(self):
        v = self._view()
        return (v.win_epoch.copy(), v.win_counts.copy(),
                v.win_sums.copy(), v.win_mm.copy())

    def window_live_cells(self) -> int:
        v = self._view()
        return int(((v.win_counts[:, :, 0] > 0)
                    & (v.win_epoch >= 0)[None, :]).sum())

    def arrays(self) -> Sequence[np.ndarray]:
        v = self._view()
        return (v.svc_hist.copy(), v.ann_svc_counts.copy(),
                v.name_presence.copy(), v.ann_value_counts.copy(),
                v.bann_key_counts.copy(), v.hll_traces.copy(),
                v.win_epoch.copy(), v.win_counts.copy(),
                v.win_sums.copy(), v.win_mm.copy())
