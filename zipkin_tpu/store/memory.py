"""In-memory reference SpanStore.

Parity target: ``InMemorySpanStore`` (zipkin-common/.../storage/SpanStore.scala:128).
This is the correctness oracle the conformance suite and the TPU store are
checked against. Deliberate deviations from the reference's *in-memory*
store, each matching what its *real* backends (Cassandra/anormdb) do
instead:

- indexed-id results are deduplicated by trace id (keeping the trace's
  most recent matching span) and sorted by timestamp descending before
  the limit is applied (the reference in-memory store truncates per-span
  in insertion order; its query layer uniques ids afterwards — deduping
  before the limit keeps one hot trace from crowding out the rest);
- binary-annotation *keys* match annotation queries even without a value
  (Cassandra writes AnnotationsIndex rows for binary-annotation keys,
  CassieSpanStore.scala:168-251);
- the end_ts filter compares the span's last timestamp uniformly (the
  reference in-memory store mixes first/last between the two paths);
- empty span names and empty service names are not indexed
  (CassieSpanStore skips them on write).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set

from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.models.span import Span
from zipkin_tpu.store.base import (
    IndexedTraceId,
    SpanStore,
    TraceIdDuration,
    as_bytes,
    should_index,
)


def match_spans_by_name(spans, service_name: str,
                        span_name: Optional[str], end_ts: int
                        ) -> List[Span]:
    """The reference store's name-index match over a plain span list —
    module-level so the cold-tier segment scan
    (store/archive/tiered.py) applies EXACTLY the oracle's semantics to
    decoded segment rows (one definition, zero drift)."""
    name = service_name.lower()
    matched = [
        s for s in spans if should_index(s) and name in s.service_names
    ]
    if span_name is not None:
        wanted = span_name.lower()
        matched = [s for s in matched if s.name.lower() == wanted]
    return [
        s for s in matched
        if s.last_timestamp is not None and s.last_timestamp <= end_ts
    ]


def match_spans_by_annotation(spans, service_name: str, annotation: str,
                              value: Optional[bytes], end_ts: int
                              ) -> List[Span]:
    """Annotation-index match over a plain span list (see
    match_spans_by_name for why this is module-level)."""
    if annotation in CORE_ANNOTATIONS:
        return []
    name = service_name.lower()
    candidates = [
        s for s in spans if should_index(s) and name in s.service_names
    ]
    matched = []
    for s in candidates:
        if s.last_timestamp is None or s.last_timestamp > end_ts:
            continue
        if value is not None:
            ok = any(
                b.key == annotation and as_bytes(b.value) == value
                for b in s.binary_annotations
            )
        else:
            ok = any(a.value == annotation for a in s.annotations) or any(
                b.key == annotation for b in s.binary_annotations
            )
        if ok:
            matched.append(s)
    return matched


class InMemorySpanStore(SpanStore):
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: 10 encode
        self.spans: List[Span] = []
        self.ttls: Dict[int, float] = {}
        # Windowed-analytics time-bucket width (s) for the exact-scan
        # heatmap — the daemon sets it from --window-seconds so a
        # memory-store deployment serves the same grid granularity a
        # device store would at the same flags.
        self.window_seconds = 60

    # -- writes ---------------------------------------------------------

    def apply(self, spans: Sequence[Span]) -> None:
        with self._lock:
            for span in spans:
                self.ttls[span.trace_id] = 1.0
            self.spans.extend(spans)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        with self._lock:
            self.ttls[trace_id] = ttl_seconds

    # -- reads (all under the same lock as writes, like the reference's
    #    synchronized `call`, SpanStore.scala:131) ------------------------

    def get_time_to_live(self, trace_id: int) -> float:
        with self._lock:
            return self.ttls[trace_id]

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        with self._lock:
            present = {s.trace_id for s in self.spans}
        return present & set(trace_ids)

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]) -> List[List[Span]]:
        with self._lock:
            snapshot = list(self.spans)
        out = []
        for tid in trace_ids:
            found = [s for s in snapshot if s.trace_id == tid]
            if found:
                out.append(found)
        return out

    def _spans_for_service(self, name: str) -> List[Span]:
        name = name.lower()
        with self._lock:
            snapshot = list(self.spans)
        return [s for s in snapshot if should_index(s) and name in s.service_names]

    def get_trace_ids_by_name(
        self,
        service_name: str,
        span_name: Optional[str],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        with self._lock:
            snapshot = list(self.spans)
        return _dedup_limit(
            match_spans_by_name(snapshot, service_name, span_name, end_ts),
            limit,
        )

    def get_trace_ids_by_annotation(
        self,
        service_name: str,
        annotation: str,
        value: Optional[bytes],
        end_ts: int,
        limit: int,
    ) -> List[IndexedTraceId]:
        # Core annotations are not indexed (SpanStore.scala:199).
        with self._lock:
            snapshot = list(self.spans)
        return _dedup_limit(
            match_spans_by_annotation(
                snapshot, service_name, annotation, value, end_ts
            ),
            limit,
        )

    def get_traces_duration(self, trace_ids: Sequence[int]) -> List[TraceIdDuration]:
        with self._lock:
            snapshot = list(self.spans)
        out = []
        for tid in trace_ids:
            ts = []
            for s in snapshot:
                if s.trace_id == tid:
                    if s.first_timestamp is not None:
                        ts.append(s.first_timestamp)
                    if s.last_timestamp is not None:
                        ts.append(s.last_timestamp)
            if ts:
                out.append(TraceIdDuration(tid, max(ts) - min(ts), min(ts)))
        return out

    def stored_span_count(self) -> float:
        with self._lock:
            return float(len(self.spans))

    def counters(self) -> Dict[str, float]:
        """Minimal store-stage counters (the /metrics hook every
        backend answers; the TPU store serves its device counter block
        through the same shape)."""
        with self._lock:
            return {
                "spans_stored": float(len(self.spans)),
                "traces_stored": float(
                    len({s.trace_id for s in self.spans})
                ),
            }

    def get_all_service_names(self) -> Set[str]:
        with self._lock:
            snapshot = list(self.spans)
        return {n for s in snapshot for n in s.service_names if n}

    def get_span_names(self, service: str) -> Set[str]:
        return {s.name for s in self._spans_for_service(service) if s.name}

    # -- windowed analytics: the exact-scan oracle -----------------------
    # Host-backend twins of the device store's windowed Moments-sketch
    # reads (aggregate/windows.py), answered by scanning the raw span
    # list: EXACT values with the SAME attribution rules — owning
    # service (span.service_name, what the columnar encoder puts in
    # service_id), first_timestamp for time bucketing, and the "error"
    # annotation-value / binary-key convention. tests/test_windows.py
    # uses these as the memory oracle the sketch answers are gated
    # against; the API serves them for --memory-store parity.

    @staticmethod
    def _is_error_span(s: Span) -> bool:
        return (any(a.value == "error" for a in s.annotations)
                or any(b.key == "error" for b in s.binary_annotations))

    def _windowed_spans(self, service: str, start_us, end_us):
        service = service.lower()
        with self._lock:
            snapshot = list(self.spans)
        out = []
        for s in snapshot:
            svc = s.service_name
            ts = s.first_timestamp
            if svc is None or svc.lower() != service or ts is None:
                continue
            if start_us is not None and ts < start_us:
                continue
            if end_us is not None and ts >= end_us:
                continue
            out.append(s)
        return out

    def windowed_quantiles(self, service: str, qs,
                           start_us=None, end_us=None):
        durs = sorted(
            s.duration for s in self._windowed_spans(
                service, start_us, end_us)
            if s.duration is not None and s.duration >= 0)
        if not durs:
            return None
        n = len(durs)
        return [
            float(durs[min(int(round(
                min(max(q, 0.0), 1.0) * (n - 1))), n - 1)])
            for q in qs
        ]

    def slo_burn(self, service: str, objective: float = None,
                 windows_s=None, now_us=None):
        from zipkin_tpu.aggregate import windows as win_mod

        objective = (win_mod.DEFAULT_OBJECTIVE if objective is None
                     else float(objective))
        windows_s = list(windows_s or win_mod.DEFAULT_BURN_WINDOWS_S)
        if now_us is None:
            ts = [s.first_timestamp
                  for s in self._windowed_spans(service, None, None)]
            now_us = (max(ts) + 1) if ts else 0
        budget = max(1.0 - objective, 1e-9)
        out = []
        for w_s in windows_s:
            spans = self._windowed_spans(
                service, int(now_us) - int(w_s) * 1_000_000,
                int(now_us))
            total = len(spans)
            errors = sum(1 for s in spans if self._is_error_span(s))
            rate = (errors / total) if total else 0.0
            out.append({
                "windowSeconds": int(w_s),
                "total": total,
                "errors": errors,
                "errorRate": rate,
                "burnRate": rate / budget,
            })
        return {"serviceName": service, "objective": objective,
                "nowTs": int(now_us), "windows": out}

    def latency_heatmap(self, service: str, start_us=None, end_us=None,
                        bands: int = None, bucket_s: int = None):
        """Exact grid: spans bucketed by first_timestamp // bucket_s
        (default: the store's window_seconds), durations histogrammed
        over ``bands`` log-spaced bands."""
        import math

        from zipkin_tpu.aggregate import windows as win_mod

        bands = int(bands or win_mod.DEFAULT_HEATMAP_BANDS)
        bucket_s = int(bucket_s or self.window_seconds or 60)
        spans = self._windowed_spans(service, start_us, end_us)
        bucket_us = int(bucket_s) * 1_000_000
        by_bucket: Dict[int, list] = {}
        for s in spans:
            by_bucket.setdefault(s.first_timestamp // bucket_us,
                                 []).append(s)
        buckets = sorted(by_bucket)
        durs = [s.duration for s in spans
                if s.duration is not None and s.duration >= 0]
        lo = math.log(max(min(durs), 1.0)) if durs else 0.0
        hi = math.log(max(max(durs), 1.0) + 1.0) if durs else 1.0
        if hi <= lo:
            hi = lo + 1.0
        edges = [math.exp(lo + (hi - lo) * i / bands)
                 for i in range(bands + 1)]
        grid = []
        for b in buckets:
            row = [0.0] * bands
            for s in by_bucket[b]:
                if s.duration is None or s.duration < 0:
                    continue
                v = max(float(s.duration), 1.0)
                i = min(int((math.log(v) - lo) / (hi - lo) * bands),
                        bands - 1)
                row[max(i, 0)] += 1.0
            grid.append(row)
        return {
            "serviceName": service,
            "bucketSeconds": int(bucket_s),
            "bucketStartsTs": [b * bucket_us for b in buckets],
            "bandEdgesMicros": [round(e, 1) for e in edges],
            "cells": grid,
            "totals": [len(by_bucket[b]) for b in buckets],
            "errors": [
                sum(1 for s in by_bucket[b] if self._is_error_span(s))
                for b in buckets
            ],
        }


def _dedup_limit(matched: List[Span], limit: int) -> List[IndexedTraceId]:
    from zipkin_tpu.store.base import dedup_rank_limit

    return dedup_rank_limit(
        ((s.trace_id, s.last_timestamp) for s in matched
         if s.last_timestamp is not None),
        limit,
    )

