"""SQL-backed SpanStore on stdlib sqlite3 (the anormdb-role backend).

Reference role: zipkin-anormdb (AnormSpanStore.scala:28, DB.scala:88-146)
— the "runs anywhere, no cluster" durable backend next to the device
store. The schema is redesigned rather than transcribed: spans get a
surrogate row key so annotations join to the *stored span occurrence*
(the reference joins on (span_id, trace_id), which conflates re-applied
spans), and write-time policy columns (lowercased names, indexability)
make the read queries pure SQL.

Tables:
  spans(row, trace_id, span_id, parent_id, has_parent, name, name_lc,
        debug, indexable, ts_first, ts_last, duration)
  annotations(span_row, seq, ts, value, is_core, service_lc, ipv4, port,
              service_raw, has_host)
  binary_annotations(span_row, seq, key, value BLOB, value_is_text,
                     ann_type, service_lc, ipv4, port, service_raw,
                     has_host)
  ttls(trace_id, ttl)
  dependencies(id, start_ts, end_ts) + dependency_links(dep_id, parent,
  child, m0..m4) — the Moments wire form (zipkinDependencies.thrift).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.models.dependencies import (
    Dependencies,
    DependencyLink,
    Moments,
)
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.store.base import (
    IndexedTraceId,
    SpanStore,
    TraceIdDuration,
    as_bytes,
    should_index,
)

_DDL = """
CREATE TABLE IF NOT EXISTS spans (
  row INTEGER PRIMARY KEY AUTOINCREMENT,
  trace_id INTEGER NOT NULL,
  span_id INTEGER NOT NULL,
  parent_id INTEGER,
  name TEXT NOT NULL,
  name_lc TEXT NOT NULL,
  debug INTEGER NOT NULL,
  indexable INTEGER NOT NULL,
  ts_first INTEGER,
  ts_last INTEGER,
  duration INTEGER
);
CREATE INDEX IF NOT EXISTS idx_spans_trace ON spans (trace_id);
CREATE TABLE IF NOT EXISTS annotations (
  span_row INTEGER NOT NULL,
  seq INTEGER NOT NULL,
  ts INTEGER NOT NULL,
  value TEXT NOT NULL,
  is_core INTEGER NOT NULL,
  has_host INTEGER NOT NULL,
  service_lc TEXT,
  service_raw TEXT,
  ipv4 INTEGER,
  port INTEGER
);
CREATE INDEX IF NOT EXISTS idx_ann_span ON annotations (span_row);
CREATE INDEX IF NOT EXISTS idx_ann_service ON annotations (service_lc);
CREATE TABLE IF NOT EXISTS binary_annotations (
  span_row INTEGER NOT NULL,
  seq INTEGER NOT NULL,
  key TEXT NOT NULL,
  value BLOB NOT NULL,
  value_is_text INTEGER NOT NULL,
  ann_type INTEGER NOT NULL,
  has_host INTEGER NOT NULL,
  service_lc TEXT,
  service_raw TEXT,
  ipv4 INTEGER,
  port INTEGER
);
CREATE INDEX IF NOT EXISTS idx_bann_span ON binary_annotations (span_row);
CREATE TABLE IF NOT EXISTS ttls (
  trace_id INTEGER PRIMARY KEY,
  ttl REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS dependencies (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  start_ts INTEGER NOT NULL,
  end_ts INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS dependency_links (
  dep_id INTEGER NOT NULL,
  parent TEXT NOT NULL,
  child TEXT NOT NULL,
  m0 REAL NOT NULL, m1 REAL NOT NULL, m2 REAL NOT NULL,
  m3 REAL NOT NULL, m4 REAL NOT NULL
);
"""


class SqliteSpanStore(SpanStore):
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)  # guarded-by: _lock
        self._lock = threading.Lock()  # lock-order: 10 encode
        with self._lock:
            self._conn.executescript(_DDL)
            self._conn.commit()
            # Monotonic admit counter for the flow estimator — COUNT(*)
            # would scan the whole table under the lock on every control
            # tick. Seeded from the table so reopened stores keep counting.
            row = self._conn.execute("SELECT COUNT(*) FROM spans").fetchone()
            self._stored = int(row[0])  # guarded-by: _lock

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def stored_span_count(self) -> float:
        with self._lock:
            return float(self._stored)

    # -- writes ---------------------------------------------------------

    def apply(self, spans: Sequence[Span]) -> None:
        with self._lock:
            cur = self._conn.cursor()
            for s in spans:
                cur.execute(
                    "INSERT OR REPLACE INTO ttls (trace_id, ttl) VALUES (?, "
                    "COALESCE((SELECT ttl FROM ttls WHERE trace_id = ?), 1.0))",
                    (s.trace_id, s.trace_id),
                )
                cur.execute(
                    "INSERT INTO spans (trace_id, span_id, parent_id, name,"
                    " name_lc, debug, indexable, ts_first, ts_last, duration)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?)",
                    (
                        s.trace_id, s.id, s.parent_id, s.name, s.name.lower(),
                        int(s.debug), int(should_index(s)),
                        s.first_timestamp, s.last_timestamp, s.duration,
                    ),
                )
                row = cur.lastrowid
                for i, a in enumerate(s.annotations):
                    cur.execute(
                        "INSERT INTO annotations (span_row, seq, ts, value,"
                        " is_core, has_host, service_lc, service_raw, ipv4,"
                        " port) VALUES (?,?,?,?,?,?,?,?,?,?)",
                        (
                            row, i, a.timestamp, a.value,
                            int(a.value in CORE_ANNOTATIONS),
                            int(a.host is not None),
                            a.host.service_name.lower() if a.host else None,
                            a.host.service_name if a.host else None,
                            a.host.ipv4 if a.host else None,
                            a.host.port if a.host else None,
                        ),
                    )
                for i, b in enumerate(s.binary_annotations):
                    is_text = isinstance(b.value, str)
                    cur.execute(
                        "INSERT INTO binary_annotations (span_row, seq, key,"
                        " value, value_is_text, ann_type, has_host,"
                        " service_lc, service_raw, ipv4, port)"
                        " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                        (
                            row, i, b.key, as_bytes(b.value), int(is_text),
                            int(b.annotation_type),
                            int(b.host is not None),
                            b.host.service_name.lower() if b.host else None,
                            b.host.service_name if b.host else None,
                            b.host.ipv4 if b.host else None,
                            b.host.port if b.host else None,
                        ),
                    )
            self._conn.commit()
            # Count only after the batch committed — a failed apply()
            # must not inflate the adaptive controller's flow source.
            self._stored += len(spans)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO ttls (trace_id, ttl) VALUES (?, ?)",
                (trace_id, ttl_seconds),
            )
            self._conn.commit()

    def get_time_to_live(self, trace_id: int) -> float:
        with self._lock:
            row = self._conn.execute(
                "SELECT ttl FROM ttls WHERE trace_id = ?", (trace_id,)
            ).fetchone()
        if row is None:
            raise KeyError(trace_id)
        return row[0]

    # -- reads ----------------------------------------------------------

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        if not trace_ids:
            return set()
        marks = ",".join("?" * len(trace_ids))
        with self._lock:
            rows = self._conn.execute(
                f"SELECT DISTINCT trace_id FROM spans WHERE trace_id IN ({marks})",
                list(trace_ids),
            ).fetchall()
        return {r[0] for r in rows}

    def _spans_for_rows(self, rows: List[tuple]) -> List[Span]:
        if not rows:
            return []
        row_ids = [r[0] for r in rows]
        marks = ",".join("?" * len(row_ids))
        with self._lock:
            anns = self._conn.execute(
                f"SELECT span_row, ts, value, has_host, service_raw, ipv4,"
                f" port FROM annotations WHERE span_row IN ({marks})"
                f" ORDER BY span_row, seq",
                row_ids,
            ).fetchall()
            banns = self._conn.execute(
                f"SELECT span_row, key, value, value_is_text, ann_type,"
                f" has_host, service_raw, ipv4, port FROM binary_annotations"
                f" WHERE span_row IN ({marks}) ORDER BY span_row, seq",
                row_ids,
            ).fetchall()
        ann_by_row: Dict[int, List[Annotation]] = {}
        for sr, ts, value, has_host, svc, ipv4, port in anns:
            host = Endpoint(ipv4, port, svc) if has_host else None
            ann_by_row.setdefault(sr, []).append(Annotation(ts, value, host))
        bann_by_row: Dict[int, List[BinaryAnnotation]] = {}
        for sr, key, value, is_text, ann_type, has_host, svc, ipv4, port in banns:
            host = Endpoint(ipv4, port, svc) if has_host else None
            v = bytes(value).decode("utf-8") if is_text else bytes(value)
            bann_by_row.setdefault(sr, []).append(
                BinaryAnnotation(key, v, AnnotationType(ann_type), host)
            )
        out = []
        for row, trace_id, span_id, parent_id, name, debug in rows:
            out.append(Span(
                trace_id=trace_id, name=name, id=span_id,
                parent_id=parent_id,
                annotations=tuple(ann_by_row.get(row, ())),
                binary_annotations=tuple(bann_by_row.get(row, ())),
                debug=bool(debug),
            ))
        return out

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]) -> List[List[Span]]:
        out = []
        for tid in trace_ids:
            with self._lock:
                rows = self._conn.execute(
                    "SELECT row, trace_id, span_id, parent_id, name, debug"
                    " FROM spans WHERE trace_id = ? ORDER BY row",
                    (tid,),
                ).fetchall()
            spans = self._spans_for_rows(rows)
            if spans:
                out.append(spans)
        return out

    def get_trace_ids_by_name(
        self, service_name: str, span_name: Optional[str],
        end_ts: int, limit: int,
    ) -> List[IndexedTraceId]:
        # One row per TRACE (max ts_last), so a hot trace fills one limit
        # slot — same dedup-before-limit semantics as the other stores.
        q = (
            "SELECT s.trace_id, MAX(s.ts_last) AS mts FROM spans s"
            " JOIN annotations a ON a.span_row = s.row"
            " WHERE s.indexable = 1 AND a.service_lc = ?"
            " AND s.ts_last IS NOT NULL AND s.ts_last <= ?"
        )
        args: List = [service_name.lower(), end_ts]
        if span_name is not None:
            q += " AND s.name_lc = ?"
            args.append(span_name.lower())
        q += " GROUP BY s.trace_id ORDER BY mts DESC LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [IndexedTraceId(tid, ts) for tid, ts in rows]

    def get_trace_ids_by_annotation(
        self, service_name: str, annotation: str, value: Optional[bytes],
        end_ts: int, limit: int,
    ) -> List[IndexedTraceId]:
        if annotation in CORE_ANNOTATIONS:
            return []
        svc = service_name.lower()
        base = (
            " FROM spans s WHERE s.indexable = 1"
            " AND s.ts_last IS NOT NULL AND s.ts_last <= ?"
            " AND EXISTS (SELECT 1 FROM annotations sv"
            "   WHERE sv.span_row = s.row AND sv.service_lc = ?)"
        )
        if value is not None:
            match = (
                " AND EXISTS (SELECT 1 FROM binary_annotations b"
                "   WHERE b.span_row = s.row AND b.key = ? AND b.value = ?)"
            )
            args: List = [end_ts, svc, annotation, as_bytes(value)]
        else:
            match = (
                " AND (EXISTS (SELECT 1 FROM annotations a"
                "   WHERE a.span_row = s.row AND a.value = ?)"
                " OR EXISTS (SELECT 1 FROM binary_annotations b"
                "   WHERE b.span_row = s.row AND b.key = ?))"
            )
            args = [end_ts, svc, annotation, annotation]
        q = (
            "SELECT s.trace_id, MAX(s.ts_last) AS mts" + base + match
            + " GROUP BY s.trace_id ORDER BY mts DESC LIMIT ?"
        )
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [IndexedTraceId(tid, ts) for tid, ts in rows]

    def get_traces_duration(self, trace_ids: Sequence[int]
                            ) -> List[TraceIdDuration]:
        out = []
        for tid in trace_ids:
            with self._lock:
                row = self._conn.execute(
                    "SELECT MIN(ts_first), MAX(ts_last) FROM spans"
                    " WHERE trace_id = ? AND ts_first IS NOT NULL",
                    (tid,),
                ).fetchone()
            if row and row[0] is not None:
                out.append(TraceIdDuration(tid, row[1] - row[0], row[0]))
        return out

    def get_all_service_names(self) -> Set[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT service_lc FROM annotations"
                " WHERE service_lc IS NOT NULL AND service_lc != ''"
            ).fetchall()
        return {r[0] for r in rows}

    def get_span_names(self, service: str) -> Set[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT s.name FROM spans s"
                " JOIN annotations a ON a.span_row = s.row"
                " WHERE s.indexable = 1 AND a.service_lc = ? AND s.name != ''",
                (service.lower(),),
            ).fetchall()
        return {r[0] for r in rows}

    # -- dependency aggregation (AnormAggregator role) -------------------

    def aggregate_dependencies(self) -> Dependencies:
        """SQL parent×child join + python Moments fold, persisted to the
        dependencies tables (AnormAggregator.scala:32-90 semantics,
        incremental: only spans newer than the last aggregated end_ts)."""
        with self._lock:
            last = self._conn.execute(
                "SELECT MAX(end_ts) FROM dependencies"
            ).fetchone()[0]
            q = (
                "SELECT p.row, c.row, c.duration, c.ts_first, c.ts_last"
                " FROM spans c JOIN spans p ON p.span_id = c.parent_id"
                "  AND p.trace_id = c.trace_id"
                " WHERE c.parent_id IS NOT NULL"
            )
            args: List = []
            if last is not None:
                q += " AND c.ts_last > ?"
                args.append(last)
            pairs = self._conn.execute(q, args).fetchall()
        if not pairs:
            return self.get_dependencies()
        # Owning service per span row (server-preferred) via span fetch.
        rows_needed = sorted({r for p in pairs for r in (p[0], p[1])})
        marks = ",".join("?" * len(rows_needed))
        with self._lock:
            raw = self._conn.execute(
                "SELECT row, trace_id, span_id, parent_id, name, debug"
                f" FROM spans WHERE row IN ({marks})", rows_needed,
            ).fetchall()
        spans = self._spans_for_rows(raw)
        svc_by_row = {r[0]: s.service_name for r, s in zip(raw, spans)}
        links: Dict[Tuple[str, str], Moments] = {}
        ts_min, ts_max = None, None
        for p_row, c_row, duration, ts_first, ts_last in pairs:
            parent, child = svc_by_row.get(p_row), svc_by_row.get(c_row)
            if parent is None or child is None:
                continue
            m = Moments.of(float(duration)) if duration is not None else Moments.zero()
            key = (parent, child)
            links[key] = links[key] + m if key in links else m
            if ts_first is not None:
                ts_min = ts_first if ts_min is None else min(ts_min, ts_first)
                ts_max = ts_last if ts_max is None else max(ts_max, ts_last)
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(
                "INSERT INTO dependencies (start_ts, end_ts) VALUES (?, ?)",
                (ts_min or 0, ts_max or 0),
            )
            dep_id = cur.lastrowid
            for (parent, child), m in links.items():
                cur.execute(
                    "INSERT INTO dependency_links (dep_id, parent, child,"
                    " m0, m1, m2, m3, m4) VALUES (?,?,?,?,?,?,?,?)",
                    (dep_id, parent, child, *m.to_central()),
                )
            self._conn.commit()
        return self.get_dependencies()

    def get_dependencies(self, start_ts=None, end_ts=None) -> Dependencies:
        """Aggregated links, optionally restricted to aggregation rows
        overlapping [start_ts, end_ts] — each `dependencies` row is one
        aggregation window, the zipkin_dependencies(start_ts, end_ts)
        rows of the anormdb schema (DB.scala:88-146)."""
        cond, args = [], []
        if end_ts is not None:
            cond.append("d.start_ts <= ?")
            args.append(end_ts)
        if start_ts is not None:
            cond.append("d.end_ts >= ?")
            args.append(start_ts)
        where = (" WHERE " + " AND ".join(cond)) if cond else ""
        with self._lock:
            deps = self._conn.execute(
                f"SELECT MIN(d.start_ts), MAX(d.end_ts)"
                f" FROM dependencies d{where}", args,
            ).fetchone()
            rows = self._conn.execute(
                f"SELECT l.parent, l.child, l.m0, l.m1, l.m2, l.m3, l.m4"
                f" FROM dependency_links l"
                f" JOIN dependencies d ON l.dep_id = d.id{where}", args,
            ).fetchall()
        if deps[0] is None:
            return Dependencies.zero()
        acc: Dict[Tuple[str, str], Moments] = {}
        for parent, child, m0, m1, m2, m3, m4 in rows:
            key = (parent, child)
            m = Moments.from_central(m0, m1, m2, m3, m4)
            acc[key] = acc[key] + m if key in acc else m
        return Dependencies(
            float(deps[0]), float(deps[1]),
            tuple(DependencyLink(p, c, m) for (p, c), m in acc.items()),
        )
