"""TpuSpanStore — the SpanStore SPI backed by the device columnar store.

The host side owns the dictionaries (strings never reach the device),
computes index policy bits (store.base.should_index, lowercased
span-name ids), pads batches, and decodes query results back into span
objects; everything between upload and the k winning rows runs on device
(store/device.py).

Plays the role of CassieSpanStore (the production backend,
zipkin-cassandra/.../CassieSpanStore.scala:55) and passes the same
conformance suite as the in-memory reference store.

Beyond the SPI it exposes the analytics the reference computes offline
(dependencies, percentiles, top annotations, cardinality) straight from
the streaming sketch state — see the ``analytics``-section methods.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.encode import SpanCodec
from zipkin_tpu.columnar.schema import SpanBatch
from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.models.dependencies import Dependencies, DependencyLink, Moments
from zipkin_tpu.models.span import Span
from zipkin_tpu.ops import hll
from zipkin_tpu.ops import quantile as Q
from zipkin_tpu.store import device as dev
from zipkin_tpu.columnar.encode import to_signed64
from zipkin_tpu.store.base import (
    IndexedTraceId,
    SpanStore,
    TraceIdDuration,
    as_bytes,
    should_index,
)

_BATCH_MIN = 64


def _next_pow2(n: int) -> int:
    p = _BATCH_MIN
    while p < n:
        p <<= 1
    return p


class TpuSpanStore(SpanStore):
    def __init__(self, config: Optional[dev.StoreConfig] = None,
                 codec: Optional[SpanCodec] = None):
        self.config = config or dev.StoreConfig()
        self.codec = codec or SpanCodec()
        self.state = dev.init_state(self.config)
        self._lock = threading.Lock()
        self.ttls: Dict[int, float] = {}
        # name_id -> lowercased-name id, maintained incrementally.
        self._name_lc: Dict[int, int] = {}

    @property
    def dicts(self) -> DictionarySet:
        return self.codec.dicts

    # -- writes ---------------------------------------------------------

    def _name_lc_ids(self, batch: SpanBatch) -> np.ndarray:
        d = self.dicts
        out = np.empty(batch.n_spans, np.int32)
        for i, nid in enumerate(batch.name_id):
            nid = int(nid)
            lc = self._name_lc.get(nid)
            if lc is None:
                name = d.span_names.decode(nid)
                lc = -1 if name == "" else d.span_names.encode(name.lower())
                self._name_lc[nid] = lc
            out[i] = lc
        return out

    # ItemQueue-aligned chunk bound: keeps jit shapes bounded and batches
    # well under any ring capacity.
    MAX_CHUNK = 4096
    # Bound on the host TTL map (pins + recent traces); ring eviction has
    # no host-side hook, so pruning happens on insert.
    MAX_TTL_ENTRIES = 1 << 20

    def apply(self, spans: Sequence[Span]) -> None:
        if not spans:
            return
        with self._lock:
            for span in spans:
                self.ttls[span.trace_id] = 1.0
            self._prune_ttls()
            # Chunk on whole-trace boundaries: the streaming dependency
            # join is within-batch, so splitting a trace across chunks
            # would silently drop its parent→child links.
            for part in self._chunk_by_trace(spans):
                batch = self.codec.encode(part)
                indexable = np.fromiter(
                    (should_index(s) for s in part), bool, len(part)
                )
                self.write_batch(batch, indexable)

    def _chunk_by_trace(self, spans: Sequence[Span]):
        chunk_size = min(self.MAX_CHUNK, self.config.capacity // 2 or 1)
        by_trace: Dict[int, List[Span]] = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        batch: List[Span] = []
        for trace_spans in by_trace.values():
            if batch and len(batch) + len(trace_spans) > chunk_size:
                yield batch
                batch = []
            batch.extend(trace_spans)
            # A single trace larger than the chunk is split (its
            # cross-chunk links fall to the offline recompute path).
            while len(batch) > chunk_size:
                yield batch[:chunk_size]
                batch = batch[chunk_size:]
        if batch:
            yield batch

    def _prune_ttls(self) -> None:
        """Drop oldest non-pinned TTL entries beyond the bound (ring
        eviction is the real retention; pins survive)."""
        excess = len(self.ttls) - self.MAX_TTL_ENTRIES
        if excess <= 0:
            return
        for tid in list(self.ttls):
            if excess <= 0:
                break
            if self.ttls[tid] <= 1.0:
                del self.ttls[tid]
                excess -= 1

    def write_thrift(self, payload: bytes) -> int:
        """Native fast path: raw thrift Span sequence → device, bypassing
        python span objects entirely. Returns the span count written.
        Raises zipkin_tpu.native.NativeUnavailable when g++ is absent —
        callers fall back to wire.thrift + apply()."""
        from zipkin_tpu import native

        with self._lock:
            batch, name_lc = native.parse_spans_columnar(
                payload, self.dicts, max_spans=self.MAX_CHUNK
            )
            if batch.n_spans == 0:
                return 0
            for tid in np.unique(batch.trace_id):
                self.ttls[int(tid)] = 1.0
            self._prune_ttls()
            indexable = native.indexable_from_batch(batch, self.dicts)
            db = dev.make_device_batch(
                batch, name_lc_id=name_lc, indexable=indexable,
                pad_spans=_next_pow2(batch.n_spans),
                pad_anns=_next_pow2(batch.n_annotations),
                pad_banns=_next_pow2(batch.n_binary),
            )
            self.state = dev.ingest_step(self.state, db)
            return batch.n_spans

    def write_batch(self, batch: SpanBatch, indexable: np.ndarray) -> None:
        """Upload one columnar batch and run the fused ingest step.

        A batch larger than a ring would scatter colliding slot indices in
        one launch (result order implementation-defined on TPU) — callers
        must chunk; ``apply`` does.
        """
        c = self.config
        if (batch.n_spans > c.capacity
                or batch.n_annotations > c.ann_capacity
                or batch.n_binary > c.bann_capacity):
            raise ValueError(
                f"batch ({batch.n_spans} spans / {batch.n_annotations} anns "
                f"/ {batch.n_binary} banns) exceeds ring capacity "
                f"({c.capacity}/{c.ann_capacity}/{c.bann_capacity}); "
                "split into smaller batches"
            )
        db = dev.make_device_batch(
            batch,
            name_lc_id=self._name_lc_ids(batch),
            indexable=indexable,
            pad_spans=_next_pow2(batch.n_spans),
            pad_anns=_next_pow2(batch.n_annotations),
            pad_banns=_next_pow2(batch.n_binary),
        )
        self.state = dev.ingest_step(self.state, db)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        with self._lock:
            self.ttls[trace_id] = ttl_seconds

    def get_time_to_live(self, trace_id: int) -> float:
        with self._lock:
            return self.ttls[trace_id]

    # -- id lookups -----------------------------------------------------

    def _svc_id(self, service_name: str) -> Optional[int]:
        return self.dicts.services.get(service_name.lower())

    def get_trace_ids_by_name(
        self, service_name: str, span_name: Optional[str],
        end_ts: int, limit: int,
    ) -> List[IndexedTraceId]:
        svc = self._svc_id(service_name)
        if svc is None or limit <= 0:
            return []
        if span_name is not None:
            name_lc = self.dicts.span_names.get(span_name.lower())
            if name_lc is None:
                return []
        else:
            name_lc = -1
        tids, tss, ok = dev.query_trace_ids_by_service(
            self.state, svc, name_lc, end_ts, limit
        )
        return [
            IndexedTraceId(int(t), int(ts))
            for t, ts, v in zip(np.asarray(tids), np.asarray(tss), np.asarray(ok))
            if v
        ]

    def get_trace_ids_by_annotation(
        self, service_name: str, annotation: str, value: Optional[bytes],
        end_ts: int, limit: int,
    ) -> List[IndexedTraceId]:
        if annotation in CORE_ANNOTATIONS or limit <= 0:
            return []
        svc = self._svc_id(service_name)
        if svc is None:
            return []
        d = self.dicts
        bann_key = d.binary_keys.get(annotation)
        bann_key = -1 if bann_key is None else bann_key
        if value is not None:
            # Value given: only binary annotations with that exact value
            # match (memory.py / CassieSpanStore binary index semantics).
            # The dictionary keys values in their original python form, so
            # probe both the bytes and the decoded-str representation.
            ann_value = -1
            vb = as_bytes(value)
            bann_value = d.binary_values.get(vb)
            try:
                bann_value2 = d.binary_values.get(vb.decode("utf-8"))
            except UnicodeDecodeError:
                bann_value2 = None
            bann_value = -1 if bann_value is None else bann_value
            bann_value2 = -1 if bann_value2 is None else bann_value2
            if (bann_value < 0 and bann_value2 < 0) or bann_key < 0:
                return []
        else:
            ann_value = d.annotations.get(annotation)
            ann_value = -1 if ann_value is None else ann_value
            bann_value = bann_value2 = -1
            if ann_value < 0 and bann_key < 0:
                return []
        tids, tss, ok = dev.query_trace_ids_by_annotation(
            self.state, svc, ann_value, bann_key, bann_value, bann_value2,
            end_ts, limit,
        )
        return [
            IndexedTraceId(int(t), int(ts))
            for t, ts, v in zip(np.asarray(tids), np.asarray(tss), np.asarray(ok))
            if v
        ]

    # -- trace reads ----------------------------------------------------

    @staticmethod
    def _canon_ids(trace_ids: Sequence[int]) -> Dict[int, int]:
        """signed-canonical id → caller's original id (ids ≥ 2^63 arrive
        unsigned on the wire but are stored signed)."""
        return {to_signed64(t): t for t in trace_ids}

    def _sorted_qids(self, trace_ids: Sequence[int]) -> np.ndarray:
        return np.sort(
            np.asarray([to_signed64(t) for t in trace_ids], np.int64)
        )

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        if not trace_ids:
            return set()
        canon = self._canon_ids(trace_ids)
        qids = self._sorted_qids(trace_ids)
        span_in, _, _ = dev.query_trace_membership(self.state, qids)
        present_tids = np.asarray(self.state.trace_id)[np.asarray(span_in)]
        return {
            canon[t] for t in np.unique(present_tids).tolist() if t in canon
        }

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]) -> List[List[Span]]:
        if not trace_ids:
            return []
        qids = self._sorted_qids(trace_ids)
        span_in, ann_in, bann_in = dev.query_trace_membership(self.state, qids)
        rows, spans = self._materialize(
            np.asarray(span_in), np.asarray(ann_in), np.asarray(bann_in)
        )
        by_tid: Dict[int, List[Span]] = {}
        for row, span in zip(rows, spans):
            by_tid.setdefault(span.trace_id, []).append(span)
        # One result per query id, duplicates included — matching the
        # in-memory reference store's behavior.
        return [
            by_tid[to_signed64(tid)]
            for tid in trace_ids
            if to_signed64(tid) in by_tid
        ]

    def _materialize(
        self, span_mask: np.ndarray, ann_mask: np.ndarray, bann_mask: np.ndarray
    ) -> Tuple[np.ndarray, List[Span]]:
        """Gather masked ring rows to host and decode to Span objects,
        ordered by insertion (global row id)."""
        st = self.state
        rows = np.flatnonzero(span_mask)
        if rows.size == 0:
            return rows, []
        gids = np.asarray(st.row_gid)[rows]
        order = np.argsort(gids, kind="stable")
        rows = rows[order]
        gids = gids[order]
        gid_to_local = {int(g): i for i, g in enumerate(gids)}

        def col(name, idx):
            return np.asarray(getattr(st, name))[idx]

        n = rows.size
        batch = SpanBatch.empty(n, 0, 0)
        for c in ("trace_id", "span_id", "parent_id", "name_id", "service_id",
                  "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first", "ts_last",
                  "duration"):
            setattr(batch, c, col(c, rows))
        batch.flags = col("flags", rows).astype(np.uint8)

        # Annotations, in ring-age order so per-span insert order survives.
        arows = np.flatnonzero(ann_mask)
        if arows.size:
            a_age = self._ring_age(arows, int(st.ann_write_pos),
                                   self.config.ann_capacity)
            arows = arows[np.argsort(a_age, kind="stable")]
            a_gid = col("ann_gid", arows)
            batch.ann_span_idx = np.array(
                [gid_to_local[int(g)] for g in a_gid], np.int32
            )
            batch.ann_ts = col("ann_ts", arows)
            batch.ann_value_id = col("ann_value_id", arows)
            batch.ann_service_id = col("ann_service_id", arows)
            batch.ann_endpoint_id = col("ann_endpoint_id", arows)
        brows = np.flatnonzero(bann_mask)
        if brows.size:
            b_age = self._ring_age(brows, int(st.bann_write_pos),
                                   self.config.bann_capacity)
            brows = brows[np.argsort(b_age, kind="stable")]
            b_gid = col("bann_gid", brows)
            batch.bann_span_idx = np.array(
                [gid_to_local[int(g)] for g in b_gid], np.int32
            )
            batch.bann_key_id = col("bann_key_id", brows)
            batch.bann_value_id = col("bann_value_id", brows)
            batch.bann_type = col("bann_type", brows).astype(np.uint8)
            batch.bann_service_id = col("bann_service_id", brows)
            batch.bann_endpoint_id = col("bann_endpoint_id", brows)
        return rows, self.codec.decode(batch)

    @staticmethod
    def _ring_age(slots: np.ndarray, write_pos: int, capacity: int) -> np.ndarray:
        """Insertion order of ring slots: oldest → 0. Valid for live rows."""
        head = write_pos % capacity
        return (slots - head) % capacity

    def get_traces_duration(
        self, trace_ids: Sequence[int]
    ) -> List[TraceIdDuration]:
        if not trace_ids:
            return []
        canon = self._canon_ids(trace_ids)
        qids = self._sorted_qids(trace_ids)
        found, min_first, max_last = dev.query_durations(self.state, qids)
        found = np.asarray(found)
        min_first = np.asarray(min_first)
        max_last = np.asarray(max_last)
        by_tid = {
            canon[int(q)]: TraceIdDuration(canon[int(q)], int(mx - mn), int(mn))
            for q, f, mn, mx in zip(qids, found, min_first, max_last)
            if f
        }
        return [by_tid[t] for t in trace_ids if t in by_tid]

    # -- name catalogs --------------------------------------------------

    def get_all_service_names(self) -> Set[str]:
        present = np.asarray(self.state.ann_svc_counts) > 0
        d = self.dicts.services
        return {
            d.decode(i) for i in np.flatnonzero(present)
            if i < len(d) and d.decode(i)
        }

    def get_span_names(self, service: str) -> Set[str]:
        svc = self._svc_id(service)
        if svc is None:
            return set()
        row = np.asarray(self.state.name_presence[svc]) > 0
        d = self.dicts.span_names
        return {
            d.decode(i) for i in np.flatnonzero(row)
            if i < len(d) and d.decode(i)
        }

    # -- analytics (the reference's offline aggregates, served live) ----

    def get_dependencies(self) -> Dependencies:
        """DependencyLinks from the streaming Moments bank — the live
        equivalent of Aggregates.getDependencies (Aggregates.scala:31)."""
        from zipkin_tpu.aggregate.job import dependencies_from_bank

        return dependencies_from_bank(
            self.state.dep_moments, self.dicts.services,
            self.config.max_services,
            float(self.state.ts_min), float(self.state.ts_max),
        )

    def service_duration_quantiles(
        self, service: str, qs: Sequence[float]
    ) -> Optional[List[float]]:
        svc = self._svc_id(service)
        if svc is None:
            return None
        hist = dev.svc_histogram(self.state)
        one = Q.LogHistogram(hist.counts[svc], hist.gamma, hist.min_value)
        return [float(Q.quantile(one, q)) for q in qs]

    def top_annotations(self, service: str, k: int = 10) -> List[Tuple[str, int]]:
        svc = self._svc_id(service)
        if svc is None:
            return []
        row = np.asarray(self.state.ann_value_counts[svc])
        order = np.argsort(-row)[:k]
        d = self.dicts.annotations
        return [
            (d.decode(int(i)), int(row[i]))
            for i in order
            if row[i] > 0 and i < len(d)
        ]

    def top_binary_keys(self, service: str, k: int = 10) -> List[Tuple[str, int]]:
        svc = self._svc_id(service)
        if svc is None:
            return []
        row = np.asarray(self.state.bann_key_counts[svc])
        order = np.argsort(-row)[:k]
        d = self.dicts.binary_keys
        return [
            (d.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(d)
        ]

    def estimated_unique_traces(self) -> float:
        return float(hll.estimate(hll.HyperLogLog(self.state.hll_traces)))

    def counters(self) -> Dict[str, float]:
        return {k: float(v) for k, v in self.state.counters.items()}
