"""TpuSpanStore — the SpanStore SPI backed by the device columnar store.

The host side owns the dictionaries (strings never reach the device),
computes index policy bits (store.base.should_index, lowercased
span-name ids), pads batches, and decodes query results back into span
objects; everything between upload and the k winning rows runs on device
(store/device.py).

Plays the role of CassieSpanStore (the production backend,
zipkin-cassandra/.../CassieSpanStore.scala:55) and passes the same
conformance suite as the in-memory reference store.

Beyond the SPI it exposes the analytics the reference computes offline
(dependencies, percentiles, top annotations, cardinality) straight from
the streaming sketch state — see the ``analytics``-section methods.
"""

from __future__ import annotations

import contextlib
import threading
import time as _time
from typing import (
    TYPE_CHECKING,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import jax
import jax.numpy as jnp
import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.encode import SpanCodec
from zipkin_tpu.columnar.schema import SpanBatch
from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.models.dependencies import Dependencies, DependencyLink, Moments
from zipkin_tpu.models.span import Span
from zipkin_tpu.ops import hll
from zipkin_tpu.ops import quantile as Q
from zipkin_tpu.store import device as dev

if TYPE_CHECKING:  # typing only — also feeds graftlint's call resolver
    from zipkin_tpu.wal.log import WriteAheadLog
from zipkin_tpu.store.pipeline import (
    EvictionSealer,
    IngestPipeline,
    IngestUnit,
)
from zipkin_tpu.columnar.encode import to_signed64
from zipkin_tpu.concurrency import RWLock
from zipkin_tpu.store.analytics import WindowedAnalytics
from zipkin_tpu.store.mirror import SketchMirror
from zipkin_tpu.store.paged import PagePlanner
from zipkin_tpu.testing.crash import kill_point
from zipkin_tpu.store.base import (
    MAX_TTL_ENTRIES,
    IndexedTraceId,
    PinBank,
    SpanStore,
    TraceIdDuration,
    apply_pin_merges,
    durations_from_mat,
    exist_from_duration_mat,
    fill_pin,
    gather_with_escalation,
    index_first_topk,
    index_topk_or_none,
    prune_ttls,
    resolve_annotation_query,
    service_scan_only,
    should_index,
    topk_ids_with_escalation,
)

_BATCH_MIN = 64


def resolve_multi_probes(config, dicts, queries):
    """Turn a ``get_trace_ids_multi`` query list into index-bucket probe
    rows (shared by the single-device and sharded stores).

    Returns (results, probes, limits, fallback):
    - ``results``: per-query list, pre-filled with [] for queries that
      resolve to nothing (unknown service/name/value), None otherwise;
    - ``probes``: (query_idx, fam_row, key1, key2, key3, three, is_svc,
      poison_on, end_ts) tuples — fam_row is a config.cand_layout row;
    - ``limits``: per-query limit;
    - ``fallback``: query indices that must use the singular path
      (mixed user-annotation + binary-key names: OR-across-families is
      scan-only semantics).
    """
    lay, _, _ = config.cand_layout
    results = [None] * len(queries)
    fallback: List[int] = []
    probes: List[tuple] = []
    limits = [0] * len(queries)
    for qi, q in enumerate(queries):
        if q[0] == "name":
            _, service, span_name, end_ts, limit = q
            limits[qi] = limit
            svc = dicts.services.get(service.lower())
            if svc is None or limit <= 0:
                results[qi] = []
                continue
            if service_scan_only(svc, config):
                fallback.append(qi)  # overflow service: scan-only
                continue
            if span_name is not None:
                name_lc = dicts.span_names.get(span_name.lower())
                if name_lc is None:
                    results[qi] = []
                    continue
                probes.append((qi, lay[dev.StoreConfig.CAND_NAME],
                               svc, name_lc, -1, False, False, False,
                               end_ts))
            else:
                probes.append((qi, lay[dev.StoreConfig.CAND_SVC],
                               svc, -1, -1, False, True, False, end_ts))
        else:
            _, service, annotation, value, end_ts, limit = q
            limits[qi] = limit
            if annotation in CORE_ANNOTATIONS or limit <= 0:
                results[qi] = []
                continue
            svc = dicts.services.get(service.lower())
            if svc is None:
                results[qi] = []
                continue
            if service_scan_only(svc, config):
                fallback.append(qi)  # overflow service: scan-only
                continue
            resolved = resolve_annotation_query(dicts, annotation, value)
            if resolved is None:
                results[qi] = []
                continue
            ann_value, bann_key, bann_value, bann_value2 = resolved
            if ann_value >= 0 and bann_key >= 0:
                fallback.append(qi)  # mixed: scan-only semantics
                continue
            if ann_value >= 0:
                probes.append((qi, lay[dev.StoreConfig.CAND_ANN],
                               svc, ann_value, -1, False, False, True,
                               end_ts))
                continue
            fam = lay[dev.StoreConfig.CAND_BANN]
            if bann_value < 0 and bann_value2 < 0:
                probes.append((qi, fam, svc, bann_key, -1, True, False,
                               True, end_ts))
                continue
            v1 = bann_value if bann_value >= 0 else bann_value2
            v2 = bann_value2 if bann_value2 >= 0 else bann_value
            probes.append((qi, fam, svc, bann_key, v1, True, False,
                           True, end_ts))
            if v2 != v1:
                probes.append((qi, fam, svc, bann_key, v2, True, False,
                               True, end_ts))
    return results, probes, limits, fallback


def build_probe_arrays(config, probes, limits):
    """Pack probe rows into the dtype-final numpy arrays
    dev._iq_multi_impl consumes, padded to a power-of-two probe count
    (bounds the compile cache). Padding probes are harmless service
    probes with end_ts=-1 (match nothing). Returns (arrays, k, k_eff):
    ``k`` the requested per-probe candidate count, ``k_eff`` the
    kernel's actual clamp (widest family depth)."""
    lay, _, _ = config.cand_layout
    k = max(1, max(limits[p[0]] for p in probes)) * 8
    n = _next_pow2(len(probes))
    pad_fam = lay[dev.StoreConfig.CAND_SVC]
    pad_row = (None, pad_fam, 0, -1, -1, False, True, False, -1)
    rows = probes + [pad_row] * (n - len(probes))
    arrs = {
        "b_base": np.asarray([r[1][0] for r in rows], np.int64),
        "s_base": np.asarray([r[1][1] for r in rows], np.int64),
        "n_b": np.asarray([r[1][2] for r in rows], np.int64),
        "depth": np.asarray([r[1][3] for r in rows], np.int64),
        "key1": np.asarray([r[2] for r in rows], np.int32),
        "key2": np.asarray([r[3] for r in rows], np.int32),
        "key3": np.asarray([r[4] for r in rows], np.int32),
        "three": np.asarray([r[5] for r in rows], bool),
        "is_svc": np.asarray([r[6] for r in rows], bool),
        "poison_on": np.asarray([r[7] for r in rows], bool),
        "end_ts": np.asarray([r[8] for r in rows], np.int64),
    }
    k_eff = min(k, max(fam[3] for fam in lay))
    return arrs, k, k_eff


def gate_multi_probes(probes, limits, per_probe):
    """Shared trust gating for batched index probes. ``per_probe`` is
    aligned with ``probes``: (candidates, complete, watermark,
    saturated) — saturated meaning the probe's effective window filled
    (its candidates may be truncated). Returns {query_idx: ids-or-None}
    where None = the query must fall back to its singular path."""
    by_q: Dict[int, list] = {}
    for pi, p in enumerate(probes):
        by_q.setdefault(p[0], []).append(pi)
    out = {}
    for qi, pis in by_q.items():
        cands = []
        complete = True
        wm = -(1 << 62)
        saturated = False
        win_total = 0
        for pi in pis:
            c_, comp_, wm_, sat_ = per_probe[pi]
            cands.extend(c_)
            complete = complete and comp_
            wm = max(wm, wm_)
            saturated |= sat_
            # window > len ⇔ unsaturated: the underfull-equals-complete
            # claim may only fire when NO probe truncated its window.
            win_total += len(c_) + (0 if sat_ else 1)
        if len(pis) > 1 and saturated:
            # Per-probe windows truncated independently: a trace cut
            # from one probe's top-k can outrank the other probe's
            # survivors, so no union-level claim is sound — unlike the
            # singular verify2 kernel, which top-k's over the
            # CONCATENATED buckets.
            out[qi] = None
        else:
            out[qi] = index_topk_or_none(
                limits[qi], win_total, cands, complete, wm
            )
    return out


def _next_pow2(n: int) -> int:
    p = _BATCH_MIN
    while p < n:
        p <<= 1
    return p


def name_lc_ids(batch: SpanBatch, dicts: DictionarySet,
                cache: Dict[int, int]) -> np.ndarray:
    """Lowercased span-name dictionary id per span (-1 for empty names),
    maintained incrementally through ``cache``."""
    out = np.empty(batch.n_spans, np.int32)
    for i, nid in enumerate(batch.name_id):
        nid = int(nid)
        lc = cache.get(nid)
        if lc is None:
            name = dicts.span_names.decode(nid)
            lc = -1 if name == "" else dicts.span_names.encode(name.lower())
            cache[nid] = lc
        out[i] = lc
    return out


def mats_to_batch(
    n_s: int, n_a: int, n_b: int,
    span_mat: np.ndarray, ann_mat: np.ndarray, bann_mat: np.ndarray,
) -> Tuple[SpanBatch, np.ndarray]:
    """(SpanBatch, per-row gids) from the stacked i64 matrices the
    gather/capture kernels produce (already compacted, spans in
    insertion order). Shared by the query decode paths and the
    cold-tier eviction capture (which seals the batch into a segment
    instead of decoding spans)."""
    batch = SpanBatch.empty(n_s, n_a, n_b)
    for i, col in enumerate(dev.SPAN_MAT_COLS[:-1]):  # row_gid is last
        tgt = getattr(batch, col)
        setattr(batch, col, span_mat[i, :n_s].astype(tgt.dtype))
    gids = span_mat[len(dev.SPAN_MAT_COLS) - 1, :n_s].astype(np.int64)
    gid_to_local = {int(g): i for i, g in enumerate(gids)}
    if n_a:
        a = {name: ann_mat[i, :n_a]
             for i, name in enumerate(dev.ANN_MAT_COLS)}
        batch.ann_span_idx = np.array(
            [gid_to_local.get(int(g), 0) for g in a["ann_gid"]], np.int32
        )
        batch.ann_ts = a["ann_ts"]
        batch.ann_value_id = a["ann_value_id"].astype(np.int32)
        batch.ann_service_id = a["ann_service_id"].astype(np.int32)
        batch.ann_endpoint_id = a["ann_endpoint_id"].astype(np.int32)
    if n_b:
        b = {name: bann_mat[i, :n_b]
             for i, name in enumerate(dev.BANN_MAT_COLS)}
        batch.bann_span_idx = np.array(
            [gid_to_local.get(int(g), 0) for g in b["bann_gid"]], np.int32
        )
        batch.bann_key_id = b["bann_key_id"].astype(np.int32)
        batch.bann_value_id = b["bann_value_id"].astype(np.int32)
        batch.bann_type = b["bann_type"].astype(np.uint8)
        batch.bann_service_id = b["bann_service_id"].astype(np.int32)
        batch.bann_endpoint_id = b["bann_endpoint_id"].astype(np.int32)
    return batch, gids


def decode_gathered(
    codec: SpanCodec, n_s: int, n_a: int, n_b: int,
    span_mat: np.ndarray, ann_mat: np.ndarray, bann_mat: np.ndarray,
) -> List[Span]:
    """Decode the stacked i64 matrices dev.gather_trace_rows produced
    into Span objects. Shared by the single-store and sharded read
    paths."""
    if n_s == 0:
        return []
    batch, _ = mats_to_batch(n_s, n_a, n_b, span_mat, ann_mat, bann_mat)
    return codec.decode(batch)


_SPAN_COLS = ("trace_id", "span_id", "parent_id", "name_id", "service_id",
              "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first", "ts_last",
              "duration", "flags")
_ANN_COLS = ("ann_ts", "ann_value_id", "ann_service_id", "ann_endpoint_id")
_BANN_COLS = ("bann_key_id", "bann_value_id", "bann_type",
              "bann_service_id", "bann_endpoint_id")




class TpuSpanStore(WindowedAnalytics, SpanStore):
    def __init__(self, config: Optional[dev.StoreConfig] = None,
                 codec: Optional[SpanCodec] = None,
                 registry=None):
        self.config = config or dev.StoreConfig()
        if self.config.layout not in ("ring", "paged"):
            raise ValueError(
                f"unknown layout {self.config.layout!r} "
                "(expected 'ring' or 'paged')")
        self.codec = codec or SpanCodec()
        self.state = dev.init_state(self.config)
        # Paged layout (ISSUE 19): the host page allocator. Slot/gid
        # assignment moves from the device's write_pos arithmetic to
        # the planner's per-unit claim plan (stage 1 of the pipeline);
        # the device kernels stay layout-blind because paged gids keep
        # the ring invariant slot == gid % capacity (epoch-encoded).
        self._planner = (PagePlanner(self.config)
                         if self.config.paged_enabled else None)
        # Serializes writers against each other (queue workers).
        self._lock = threading.Lock()  # lock-order: 10 encode
        # Guards the state swap: ingest_step donates the old state's
        # device buffers, so queries snapshot self.state under a read
        # lock and hold it across their kernels + host gathers, while
        # the donating step runs under the write lock (ADVICE r1 high).
        self._rw = RWLock()  # lock-order: 40 commit
        # Host mirrors of write_pos / last-bucket-close position, pacing
        # the dependency bucket rotation without a device sync per batch.
        self._wp = 0
        self._archived = 0
        # Eviction capture (cold tier, store/archive): when a sink is
        # attached, the write path pulls every ring row to the host
        # BEFORE any of the three rings can overwrite it. The mirrors
        # track each ring's write cursor (host-side, no device sync)
        # and the per-ring capture high-water marks; a capture window
        # is always [_cap_upto, _wp) with EXACTLY _awp - _cap_a
        # annotation rows (each batch's side rows belong to its own
        # spans), so the pull needs no count escalation in steady
        # state. sink(batch, gids, gid_lo, gid_hi, pull_seconds).
        self.eviction_sink = None
        self._awp = 0
        self._bwp = 0
        self._cap_upto = 0  # guarded-by: _cap_lock
        self._cap_a = 0  # guarded-by: _cap_lock
        self._cap_b = 0  # guarded-by: _cap_lock
        # Async eviction sealing (store/pipeline.EvictionSealer): with
        # capture_backlog > 0 the write path only PULLS a capture
        # window (read-only launch, ordering invariant intact) and a
        # background thread does the D2H + deflate + directory append.
        # _sealed_upto trails _cap_upto by exactly the in-flight
        # windows; checkpoint manifests cut at the SEALED frontier.
        # _cap_lock serializes window capture between the serial write
        # path (under _lock) and the pipeline's commit thread.
        self.capture_backlog = self.CAPTURE_BACKLOG
        self._sealer: Optional[EvictionSealer] = None
        self._sealed_upto = 0  # guarded-by: _cap_lock
        self._cap_lock = threading.Lock()  # lock-order: 30 capture
        # Pipelined ingest (store/pipeline.IngestPipeline), opt-in via
        # start_pipeline(): apply/write_thrift become stage 1 (encode +
        # pad under _lock) and the commit thread owns the device write
        # path (the _wp/_awp/_bwp mirrors, capture/archive triggers,
        # sweep cadence).
        self._pipeline: Optional[IngestPipeline] = None
        # Durable write-ahead log (zipkin_tpu.wal): when attached, every
        # planned launch group is journaled (stage-1 output + dictionary
        # delta) BEFORE its donating commit; _wal_applied tracks the
        # highest sequence whose unit has committed to the device
        # (advanced inside the commit's write-lock hold, so checkpoint
        # cuts read a sequence exactly consistent with the state), and
        # _wal_marks the dictionary high-water sizes of the last
        # journaled record (the next record's delta base).
        self.wal: Optional[WriteAheadLog] = None
        self._wal_applied = 0
        self._wal_marks = None
        # Batch lineage tracker (obs.fleet.LineageTracker): when
        # attached, _journal_group stamps each record's meta with a
        # commit timestamp (+ a sampled B3 context) and reports the
        # append so the unit's WAL append → fsync → ship → follower
        # apply shows up as one causally-linked self-trace.
        self.lineage = None
        # Host sketch mirror (store/mirror.SketchMirror): numpy twins
        # of the device's lifetime aggregate arrays AND the windowed
        # Moments-sketch arena, updated by each commit's delta inside
        # the write-lock hold — the query engine's zero-dispatch
        # sketch tier (docs/QUERY_ENGINE.md). The dictionary set
        # resolves the "error" convention ids for the window cells'
        # error counts.
        self.sketch_mirror = SketchMirror(self.config,
                                          dicts=self.codec.dicts)
        # Read-visibility epoch: bumped by host-side state that changes
        # query answers WITHOUT a device commit (pin/TTL mutations,
        # pin-bank arrivals). write_frontier() = (_step_seq, epoch) is
        # the result-cache key component.
        self._read_epoch = 0
        # Pending-sweep pacing: sweep every SWEEP_EVERY batches on the
        # write path (bounds how long a cross-batch child waits for its
        # link) and lazily before dependency reads — but only when
        # something was written since the last sweep, so read-only
        # dependency polling stays a pure read.
        self._batches_since_sweep = 0
        # Keyed by to_signed64(trace_id) — ids >= 2^63 arrive unsigned
        # on some write paths and signed on others.
        self.ttls: Dict[int, float] = {}
        # Eviction-exempt spans of pinned traces (see PinBank).
        self.pins = PinBank()
        # Annotation rows dropped because a single span carried more than
        # a ring's capacity (the maxTraceCols-style guard).
        self.anns_truncated = 0
        self.banns_truncated = 0
        # Index read-path outcome counters (surfaced via counters() →
        # /metrics): how often the fast path answered vs degraded to the
        # O(ring) scan kernels — the observable for the sparse-key
        # aliasing rate the per-key cursor table exists to shrink.
        self.index_hits = 0
        self.index_fallbacks = 0
        # name_id -> lowercased-name id, maintained incrementally.
        self._name_lc: Dict[int, int] = {}
        # Telemetry (zipkin_tpu.obs): the device counter block is
        # fetched at most ONCE per ingest progress — _step_seq bumps on
        # every state mutation and keys the memo, so metric scrapes
        # between ingest steps reuse the cached block instead of
        # launching a D2H each.
        self._step_seq = 0
        self._cblock_memo: Optional[tuple] = None
        from zipkin_tpu import obs

        reg = registry or obs.default_registry()
        self._registry = reg
        # Launch dispatch is ASYNC under JAX, so a per-step wall clock
        # only measures host dispatch. The true-latency sketch blocks
        # on a tiny scalar every INGEST_SYNC_EVERY-th launch (sampled
        # sync: negligible throughput tax, honest p50/p99); the
        # dispatch sketch keeps the old always-on host-side number.
        self._h_ingest = reg.register(obs.LatencySketch(
            "zipkin_store_ingest_step_seconds",
            "TRUE fused-step latency, dispatch through device "
            "completion (sampled: observed every "
            f"{self.INGEST_SYNC_EVERY}th launch via a scalar sync)"))
        self._h_dispatch = reg.register(obs.LatencySketch(
            "zipkin_store_ingest_dispatch_seconds",
            "Host dispatch time per fused step/chain (async: excludes "
            "device compute — see zipkin_store_ingest_step_seconds)"))
        self._c_launches = reg.register(obs.Counter(
            "zipkin_store_ingest_launches_total",
            "Device ingest launches (chained chunks count as one)"))
        self._launch_seq = 0
        reg.register(obs.Counter(
            "zipkin_store_jit_compiles_total",
            "Compiled variants across the ingest/staging/capture jits "
            "(dev.compile_count; steady-state pipelined ingest adds 0)",
            fn=lambda: float(dev.compile_count())))
        # Windowed Moments-sketch arena families (zipkin_window_*,
        # docs/OBSERVABILITY.md): fold counters are process-monotonic
        # mirror totals (never regress on ring self-clears or resync);
        # the cell gauge reads live occupancy.
        mirror = self.sketch_mirror
        reg.register(obs.Counter(
            "zipkin_window_spans_total",
            "Spans folded into the windowed (service × time-bucket) "
            "Moments-sketch cells since process start",
            fn=lambda: float(mirror.win_spans_total)))
        reg.register(obs.Counter(
            "zipkin_window_errors_total",
            "Error-flagged spans ('error' annotation value or binary "
            "key) folded into the windowed cells since process start",
            fn=lambda: float(mirror.win_errors_total)))
        reg.register(obs.Gauge(
            "zipkin_window_cells_active",
            "Occupied (service, time-bucket) cells in the windowed "
            "arena ring",
            fn=lambda: float(mirror.window_live_cells())))
        reg.register(obs.Gauge(
            "zipkin_window_retention_seconds",
            "Windowed-analytics retention: window_seconds × "
            "window_buckets (0 = arena disabled)",
            fn=lambda: float(
                self.config.window_seconds * self.config.window_buckets
                if self.config.window_enabled else 0.0)))
        # Paged-layout allocator occupancy (gauges read the planner's
        # host mirrors under its own lock — zero device traffic).
        if self._planner is not None:
            planner = self._planner
            reg.register(obs.Gauge(
                "zipkin_store_pages_active",
                "Device pages holding live spans (paged layout)",
                fn=lambda: float(planner.stats()["pages_active"])))
            reg.register(obs.Gauge(
                "zipkin_store_pages_free",
                "Device pages on the allocator free list (paged "
                "layout)",
                fn=lambda: float(planner.stats()["pages_free"])))
            reg.register(obs.Counter(
                "zipkin_store_page_reclaims_total",
                "Pages captured + recycled through the free list "
                "since process start (paged layout)",
                fn=lambda: float(planner.stats()["page_reclaims"])))
        # The zipkin_store_counter family is registered by ApiServer
        # from the generic counters() hook (one registration site for
        # every backend), not here.

    @property
    def dicts(self) -> DictionarySet:
        return self.codec.dicts

    # -- writes ---------------------------------------------------------

    def _name_lc_ids(self, batch: SpanBatch) -> np.ndarray:
        return name_lc_ids(batch, self.dicts, self._name_lc)

    # ItemQueue-aligned chunk bound: keeps jit shapes bounded and batches
    # well under any ring capacity.
    MAX_CHUNK = 4096
    # TTL-map bound (store/base.MAX_TTL_ENTRIES — shared with the
    # sharded and replica stores; kept as a class attr for callers).
    MAX_TTL_ENTRIES = MAX_TTL_ENTRIES
    # True-latency sampling cadence: every Nth launch blocks on one
    # scalar (write_pos) to observe dispatch->completion. The first
    # launch is always sampled so a single-write store still reports.
    INGEST_SYNC_EVERY = 32
    # Default prefetch depth for start_pipeline(None).
    PIPELINE_DEPTH = 8
    # Default staged-unit (H2D double-buffer) slots for start_pipeline.
    STAGE_BUFFERS = 2
    # Default async-seal backlog: 0 = seal inline on the write path
    # (bitwise-deterministic timing, the library default); deployments
    # that want capture off the critical path set capture_backlog > 0
    # (the daemon's --capture-backlog does).
    CAPTURE_BACKLOG = 0

    def apply(self, spans: Sequence[Span]) -> None:
        if not spans:
            return
        with self._lock:
            for span in spans:
                self.ttls.setdefault(to_signed64(span.trace_id), 1.0)
            if self.pins:
                # Pin-bank arrivals change read answers before the
                # commit bumps the frontier — invalidate cached reads.
                self._bump_read_epoch()
            self.pins.note_write(to_signed64, spans)
            self._prune_ttls()
            # Chunking keeps jit shapes bounded and batches under ring
            # capacity (a single launch must not scatter colliding
            # slots); trace grouping just keeps each trace's rows
            # adjacent in the ring. _chunk_columnar additionally guards
            # the annotation rings (one fat span's rows get truncated,
            # not the whole batch dropped). Multiple chunks chain into
            # one launch (_write_parts) to amortize the per-dispatch
            # floor.
            # Buffer at most one chain group (+ one trace chunk's worth)
            # of encoded columnar parts — a bulk apply() must not hold
            # the whole call's columnar copy in host memory at once.
            if self._pipeline is not None:
                self._apply_pipelined(spans)
                return
            parts = []
            for part in self._chunk_by_trace(spans):
                batch = self.codec.encode(part)
                indexable = np.fromiter(
                    (should_index(s) for s in part), bool, len(part)
                )
                name_lc = self._name_lc_ids(batch)
                parts.extend(self._chunk_columnar(
                    batch, name_lc, indexable
                ))
                if self.CHAIN_SIZES and len(parts) >= self.CHAIN_SIZES[0]:
                    self._write_parts(parts)
                    parts = []
            if parts:
                self._write_parts(parts)

    def _apply_pipelined(self, spans: Sequence[Span]) -> None:
        """Stage 1 of the ingest pipeline (caller thread, under the
        encode lock): encode + index bits + pow2 padding, feeding the
        prefetch queue. The chunk flush boundary, the CHAIN_SIZES
        grouping, and the pad buckets are IDENTICAL to the serial
        path's, so both modes cut the same launch units — the basis of
        the pipelined-equals-serial bitwise guarantee
        (tests/test_pipeline.py)."""
        pipe = self._pipeline
        self.ensure_writable()  # fail fast; the commit thread re-checks
        t0 = _time.perf_counter()
        stalled = 0.0
        parts = []
        for part in self._chunk_by_trace(spans):
            batch = self.codec.encode(part)
            indexable = np.fromiter(
                (should_index(s) for s in part), bool, len(part)
            )
            name_lc = self._name_lc_ids(batch)
            parts.extend(self._chunk_columnar(batch, name_lc, indexable))
            if self.CHAIN_SIZES and len(parts) >= self.CHAIN_SIZES[0]:
                stalled += self._feed_units(pipe, parts)
                parts = []
        if parts:
            stalled += self._feed_units(pipe, parts)
        pipe.h_encode.observe(
            max(_time.perf_counter() - t0 - stalled, 0.0))

    def _feed_units(self, pipe: IngestPipeline, parts) -> float:
        """Pad + enqueue one flushed part list as launch units; returns
        seconds spent blocked on pipeline backpressure (excluded from
        the encode sketch). With a WAL attached each group is journaled
        HERE — on the stage-1 caller thread, under the encode lock, so
        append order equals feed order equals (FIFO) commit order."""
        stalled = 0.0
        for group in self._plan_units(parts):
            # Journal BEFORE padding: _pad_unit's page planning (paged
            # layout) keys its claim plan to the unit's WAL sequence
            # ATOMICALLY under the planner lock, so a checkpoint's
            # planner snapshot can never see a plan without its seq
            # (the replay memo's integrity). Dictionaries grew in the
            # encode stage, so the journaled delta is pad-independent.
            seq = (self._journal_group(group)
                   if self.wal is not None else None)
            unit = self._pad_unit(group, wal_seq=seq)
            if seq is not None:
                unit = unit._replace(wal_seq=seq)
                kill_point("after-append")
            stalled += pipe.feed(unit)
        return stalled

    def _chunk_by_trace(self, spans: Sequence[Span]):
        chunk_size = self._max_chunk_spans()
        by_trace: Dict[int, List[Span]] = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        batch: List[Span] = []
        for trace_spans in by_trace.values():
            if batch and len(batch) + len(trace_spans) > chunk_size:
                yield batch
                batch = []
            batch.extend(trace_spans)
            # A single trace larger than the chunk is split; its links
            # still join via the resident-ring archive path.
            while len(batch) > chunk_size:
                yield batch[:chunk_size]
                batch = batch[chunk_size:]
        if batch:
            yield batch

    def _max_chunk_spans(self) -> int:
        """One-launch span bound: the span ring (colliding-slot scatter
        guard) AND the pending ring (a launch's unresolved children must
        fit without self-collision) both cap it. ``config.batch_spans``
        (the r12 batch-escalation knob) replaces the legacy MAX_CHUNK
        ceiling when set — bigger launches amortize the per-launch
        index-write entry costs; the ring guards still clamp."""
        c = self.config
        # <= 0 means "default" (and a negative knob value must never
        # reach the chunkers: a non-positive chunk size turns
        # _chunk_by_trace's split loop into an infinite empty-yield).
        limit = c.batch_spans if c.batch_spans > 0 else self.MAX_CHUNK
        # Paged layout: one launch's page demand is bounded by its span
        # count (<= ~4·spans/page_rows + 1 open pages), and every page
        # claimed inside a unit is reclaim-exempt for that unit (the
        # capture-before-reuse pull runs before the launch). capacity//8
        # keeps the worst-case demand under half the pool, so the
        # allocator always finds an untouched victim.
        span_cap = (max(1, c.capacity // 8) if c.paged_enabled
                    else c.capacity // 2 or 1)
        return max(1, min(limit, span_cap, c.pending_slots))

    def _prune_ttls(self) -> None:
        prune_ttls(self.ttls, self.MAX_TTL_ENTRIES)

    def write_thrift(self, payload: bytes,
                     sample_threshold: int = 0) -> Tuple[int, int, int]:
        """Native fast path: raw thrift Span sequence → device, bypassing
        python span objects entirely. Returns
        (written, dropped, written_debug).

        ``sample_threshold`` applies the sampler's trace-id test on the
        numeric columns BEFORE string interning (Sampler.scala:39-48
        semantics incl. the debug override, SpanSamplerFilter.scala:40-47)
        so the fast path neither bypasses sampling nor pollutes the
        dictionaries with sampled-out names; 0 keeps everything.
        ``written_debug`` counts kept debug spans (the slow path never
        runs those through the sampler's counters).

        Raises zipkin_tpu.native.NativeUnavailable when g++ is absent —
        callers fall back to wire.thrift + apply(); ParseCapacityError
        propagates for callers to chunk."""
        from zipkin_tpu import native

        with self._lock:
            t0 = _time.perf_counter()  # stage-1 clock (pipelined mode)
            batch, name_lc, dropped, kept_debug = (
                native.parse_spans_columnar_sampled(
                    payload, self.dicts, sample_threshold,
                    max_spans=self.MAX_CHUNK,
                )
            )
            if batch.n_spans == 0:
                return 0, dropped, 0
            for tid in np.unique(batch.trace_id):
                self.ttls.setdefault(int(tid), 1.0)
            if self.pins:
                # Fast-path arrivals for pinned traces must reach the
                # eviction-exempt bank too: decode just those rows.
                keep = np.isin(
                    batch.trace_id,
                    np.fromiter(self.pins.tids(), np.int64,
                                len(self.pins.tids())),
                )
                if keep.any():
                    pinned_part = self._select_batch(batch, keep)
                    self._bump_read_epoch()
                    self.pins.note_write(
                        to_signed64, self.codec.decode(pinned_part)
                    )
            self._prune_ttls()
            indexable = native.indexable_from_batch(batch, self.dicts)
            parts = list(self._chunk_columnar(batch, name_lc, indexable))
            pipe = self._pipeline
            if pipe is not None:
                # t0 opened before the native parse: the encode sketch
                # must cover the whole stage-1 body (parse + index
                # bits + chunking + padding), not just the pad tail.
                self.ensure_writable()
                stalled = self._feed_units(pipe, parts)
                pipe.h_encode.observe(
                    max(_time.perf_counter() - t0 - stalled, 0.0))
            else:
                self._write_parts(parts)
            return batch.n_spans, dropped, kept_debug

    def _chunk_columnar(self, batch: SpanBatch, name_lc: np.ndarray,
                        indexable: np.ndarray):
        """Split a parsed columnar batch so every chunk fits the ring
        capacities (a single launch must never scatter colliding slots —
        see write_batch). The common case (batch fits) costs nothing."""
        c = self.config
        max_spans = self._max_chunk_spans()
        if (batch.n_spans <= max_spans
                and batch.n_annotations <= c.ann_capacity
                and batch.n_binary <= c.bann_capacity):
            yield batch, name_lc, indexable
            return
        start = 0
        while start < batch.n_spans:
            stop = min(start + max_spans, batch.n_spans)
            # Shrink until the chunk's annotation rows fit their rings.
            while stop > start + 1:
                a_n = int(np.count_nonzero(
                    (batch.ann_span_idx >= start) & (batch.ann_span_idx < stop)
                ))
                b_n = int(np.count_nonzero(
                    (batch.bann_span_idx >= start)
                    & (batch.bann_span_idx < stop)
                ))
                if a_n <= c.ann_capacity and b_n <= c.bann_capacity:
                    break
                stop = start + (stop - start) // 2
            part = self._slice_batch(batch, start, stop)
            # A single span can carry more annotations than a ring holds;
            # yielding it as-is would wrap the ring and scatter colliding
            # slots nondeterministically in one launch. Truncate its
            # annotation rows instead (counted, like maxTraceCols drops).
            if part.n_annotations > c.ann_capacity:
                self.anns_truncated += part.n_annotations - c.ann_capacity
                part = self._truncate_anns(part, c.ann_capacity, binary=False)
            if part.n_binary > c.bann_capacity:
                self.banns_truncated += part.n_binary - c.bann_capacity
                part = self._truncate_anns(part, c.bann_capacity, binary=True)
            yield part, name_lc[start:stop], indexable[start:stop]
            start = stop

    @staticmethod
    def _truncate_anns(batch: SpanBatch, cap: int, binary: bool) -> SpanBatch:
        """Keep only the first ``cap`` (binary) annotation rows."""
        import dataclasses

        cols = SpanBatch.BANN_COLUMNS if binary else SpanBatch.ANN_COLUMNS
        return dataclasses.replace(
            batch, **{c: getattr(batch, c)[:cap] for c in cols}
        )

    @staticmethod
    def _select_batch(batch: SpanBatch, keep: np.ndarray) -> SpanBatch:
        """Columnar selection of arbitrary span rows (bool mask) with
        their annotation rows, span indices rebased."""
        idx = np.flatnonzero(keep)
        remap = np.full(batch.n_spans, -1, np.int32)
        remap[idx] = np.arange(idx.size, dtype=np.int32)
        a_sel = keep[batch.ann_span_idx] if batch.n_annotations else (
            np.zeros(0, bool)
        )
        b_sel = keep[batch.bann_span_idx] if batch.n_binary else (
            np.zeros(0, bool)
        )
        out = SpanBatch.empty(idx.size, int(a_sel.sum()), int(b_sel.sum()))
        for col in _SPAN_COLS:
            setattr(out, col, getattr(batch, col)[idx])
        out.ann_span_idx = remap[batch.ann_span_idx[a_sel]]
        for col in _ANN_COLS:
            setattr(out, col, getattr(batch, col)[a_sel])
        out.bann_span_idx = remap[batch.bann_span_idx[b_sel]]
        for col in _BANN_COLS:
            setattr(out, col, getattr(batch, col)[b_sel])
        return out

    @staticmethod
    def _slice_batch(batch: SpanBatch, start: int, stop: int) -> SpanBatch:
        """Columnar slice of span rows [start, stop) with their
        annotation/binary rows, span indices rebased."""
        a_sel = (batch.ann_span_idx >= start) & (batch.ann_span_idx < stop)
        b_sel = (batch.bann_span_idx >= start) & (batch.bann_span_idx < stop)
        out = SpanBatch.empty(
            stop - start, int(a_sel.sum()), int(b_sel.sum())
        )
        for col in _SPAN_COLS:
            setattr(out, col, getattr(batch, col)[start:stop])
        out.ann_span_idx = batch.ann_span_idx[a_sel] - start
        for col in _ANN_COLS:
            setattr(out, col, getattr(batch, col)[a_sel])
        out.bann_span_idx = batch.bann_span_idx[b_sel] - start
        for col in _BANN_COLS:
            setattr(out, col, getattr(batch, col)[b_sel])
        return out

    def write_batch(self, batch: SpanBatch, indexable: np.ndarray) -> None:
        """Upload one columnar batch and run the fused ingest step.

        A batch larger than a ring would scatter colliding slot indices in
        one launch (result order implementation-defined on TPU) — callers
        must chunk; ``apply`` does.
        """
        if self._pipeline is not None:
            # Committing on the caller thread while the pipeline's
            # commit thread is live would make two concurrent device
            # writers (racing the mirror bumps and capture clocks) —
            # the ring-scatter contract forbids it.
            raise RuntimeError(
                "write_batch commits inline and cannot run while an "
                "ingest pipeline is active; use apply()/write_thrift "
                "or stop_pipeline() first"
            )
        c = self.config
        if (batch.n_spans > min(c.capacity, c.pending_slots)
                or batch.n_annotations > c.ann_capacity
                or batch.n_binary > c.bann_capacity):
            raise ValueError(
                f"batch ({batch.n_spans} spans / {batch.n_annotations} anns "
                f"/ {batch.n_binary} banns) exceeds ring capacity "
                f"({min(c.capacity, c.pending_slots)}/{c.ann_capacity}/"
                f"{c.bann_capacity}); split into smaller batches"
            )
        self._write_device(batch, self._name_lc_ids(batch), indexable)

    # Chained-launch grouping: chunks per ingest_steps launch. Powers of
    # two only ({4, 8, 16}) so the scan length doesn't fragment the
    # compile cache; leftovers run singly.
    CHAIN_SIZES = (16, 8, 4)

    def _write_parts(self, parts) -> None:
        """Write a list of (batch, name_lc, indexable) chunks, chaining
        groups of equal-padded chunks into single ``dev.ingest_steps``
        launches — one ~100ms dispatch per GROUP instead of per chunk
        (NOTES_r03 §3 cost model; the ItemQueue batch-drain role,
        ItemQueue.scala:39)."""
        for group in self._plan_units(parts):
            self._commit_group(group)

    def _commit_group(self, group) -> None:
        """Journal (when a WAL is attached) then commit one planned
        launch group — the serial write path's ack-after-append point:
        by the time the donating swap runs, the group's record is in
        the log, so a crash between append and commit REPLAYS the
        group instead of losing it."""
        seq = None
        if self.wal is not None:
            kill_point("before-append")
            seq = self._journal_group(group)
        # Journal-before-pad: see _feed_units — the paged planner's
        # claim plan is keyed to ``seq`` inside _pad_unit.
        unit = self._pad_unit(group, wal_seq=seq)
        if seq is not None:
            unit = unit._replace(wal_seq=seq)
            kill_point("after-append")
        self._commit_unit(unit)
        kill_point("after-commit")

    def _plan_units(self, parts):
        """CHAIN_SIZES greedy grouping of chunker parts into launch
        units — ONE policy shared by the serial writer and the ingest
        pipeline's stage 1 (identical grouping is a precondition of
        the pipelined-equals-serial bitwise guarantee). Spans are
        bounded by capacity//2 so the archive cadence (one
        dependency-bucket close per half ring) can never be outrun
        inside one launch; annotation/binary rows are bounded by their
        FULL ring capacities — a group exceeding one would overwrite
        its own side rows mid-launch, where no capture hook can run
        (the pre-launch capture trigger already protects every OLDER
        uncaptured row up to exactly this bound). Yields part lists;
        singletons dispatch via ingest_step, larger groups chain
        through ingest_steps."""
        span_budget = (max(1, self.config.capacity // 8)
                       if self.config.paged_enabled
                       else max(1, self.config.capacity // 2))
        ann_budget = max(1, self.config.ann_capacity)
        bann_budget = max(1, self.config.bann_capacity)
        i = 0
        n = len(parts)
        while i < n:
            took = 1
            for size in self.CHAIN_SIZES:
                if i + size > n:
                    continue
                group = parts[i:i + size]
                if (sum(p[0].n_spans for p in group) <= span_budget
                        and sum(p[0].n_annotations for p in group)
                        <= ann_budget
                        and sum(p[0].n_binary for p in group)
                        <= bann_budget):
                    yield group
                    took = size
                    break
            else:
                yield parts[i:i + 1]
            i += took

    def _pad_unit(self, group, wal_seq: Optional[int] = None
                  ) -> IngestUnit:
        """Pad one planned group to its pow2 buckets (host numpy — the
        H2D copy is the pipeline's stage 2, or implicit at dispatch on
        the serial path). Chained groups pad every chunk to the group
        max and stack along a leading scan axis. pow2 bucketing bounds
        the jit compile cache, so a warmed steady state pads into
        already-compiled shapes only (dev.compile_count gates this).

        The per-span error bit (the window cells' error counts) is a
        pure function of (batch, dictionary state) — WAL replay
        rebuilds the dictionaries in append order, so a replayed unit
        recomputes identical flags (aggregate.windows)."""
        from zipkin_tpu.aggregate import windows as win_mod

        sketch = self.sketch_mirror.delta_of(group)
        # Paged layout: slot/gid claims are planned HERE — on the
        # stage-1 caller thread, under the encode lock — so claim
        # order equals feed order equals journal order (the planner's
        # determinism contract). ``wal_seq`` is only passed by WAL
        # replay, which re-reads recorded plans for already-planned
        # sequences instead of re-deriving them.
        plan = None
        if self._planner is not None:
            plan = self._planner.plan_unit(
                [np.asarray(b.trace_id) for b, _, _ in group],
                wal_seq=wal_seq)
        if self.config.window_enabled:
            ea, eb = win_mod.error_ids(self.dicts)
            err_of = lambda b: win_mod.span_error_flags(b, ea, eb)  # noqa: E731
        else:
            err_of = lambda b: None  # noqa: E731 — flag lowers out
        pad_rc = 1
        if plan is not None:
            pad_rc = _next_pow2(max(
                [1] + [len(c.reclaim_pages) for c in plan.chunks]))
        if len(group) == 1:
            b, lc, ix = group[0]
            cp = plan.chunks[0] if plan is not None else None
            db = dev.make_device_batch(
                b, name_lc_id=lc, indexable=ix,
                pad_spans=_next_pow2(b.n_spans),
                pad_anns=_next_pow2(b.n_annotations),
                pad_banns=_next_pow2(b.n_binary),
                error_flag=err_of(b),
                span_slot=None if cp is None else cp.span_slot,
                span_gid=None if cp is None else cp.span_gid,
                reclaim_pages=None if cp is None else cp.reclaim_pages,
                pad_reclaims=pad_rc,
            )
            return IngestUnit(db, b.n_spans, b.n_annotations,
                              b.n_binary, 1, False, sketch=sketch,
                              reclaims=plan.reclaims if plan else ())
        pad_s = _next_pow2(max(b.n_spans for b, _, _ in group))
        pad_a = _next_pow2(max(b.n_annotations for b, _, _ in group))
        pad_b = _next_pow2(max(b.n_binary for b, _, _ in group))
        dbs = [
            dev.make_device_batch(
                b, name_lc_id=lc, indexable=ix,
                pad_spans=pad_s, pad_anns=pad_a, pad_banns=pad_b,
                error_flag=err_of(b),
                span_slot=None if plan is None
                else plan.chunks[ci].span_slot,
                span_gid=None if plan is None
                else plan.chunks[ci].span_gid,
                reclaim_pages=None if plan is None
                else plan.chunks[ci].reclaim_pages,
                pad_reclaims=pad_rc,
            )
            for ci, (b, lc, ix) in enumerate(group)
        ]
        return IngestUnit(
            dev.stack_device_batches(dbs),
            sum(b.n_spans for b, _, _ in group),
            sum(b.n_annotations for b, _, _ in group),
            sum(b.n_binary for b, _, _ in group),
            len(group), True, sketch=sketch,
            reclaims=plan.reclaims if plan else (),
        )

    def _commit_unit(self, unit: IngestUnit) -> None:
        """Stage 3 — the ONE device-commit body behind both write
        modes: eviction-capture trigger, bucket-rotation trigger, the
        donating state swap under the write lock, host mirror bumps,
        and the sweep cadence. Serial writers run it inline under
        self._lock; the pipeline's commit thread runs it alone (it is
        the only device writer while a pipeline is active)."""
        self.ensure_writable()
        t0 = _time.perf_counter()
        if self._planner is not None:
            # Paged capture is at page granularity: the unit's plan
            # names exactly the pages it reclaims, and their rows are
            # pulled BEFORE the launch whose invalidation scatter
            # erases them (the per-page captured-before-overwrite
            # invariant). The ring-window trigger stays dormant — its
            # [cap_upto, wp) arithmetic is FIFO-gid arithmetic.
            if unit.reclaims:
                self._capture_pages(unit.reclaims)
        else:
            self._maybe_capture(unit.n_spans, unit.n_anns, unit.n_banns)
        self._maybe_archive(unit.n_spans)
        step = dev.ingest_steps if unit.chained else dev.ingest_step
        # The host mirrors, the WAL applied frontier, and the cadence
        # sweep all advance INSIDE the write-lock hold: a checkpoint's
        # state gather (under the read lock) then always pairs the
        # device cut with exactly-matching clocks — the invariant
        # deterministic replay (wal/recovery) rebuilds launches from.
        with self._rw.write():
            self.state = step(self.state, unit.db)
            # Mirror BEFORE the frontier bump: a sketch-tier read at
            # frontier F must already include commit F's delta.
            if unit.sketch is not None:
                self.sketch_mirror.apply(unit.sketch)
            self._wp += unit.n_spans
            self._awp += unit.n_anns
            self._bwp += unit.n_banns
            self._step_seq += 1
            if unit.wal_seq is not None:
                self._wal_applied = unit.wal_seq
            # Dispatch accounting stops HERE: the cadence sweep below
            # is its own launch, and folding it into the per-batch
            # dispatch sketch would plant a 1-in-64 outlier that reads
            # as an ingest regression.
            dispatch_s = _time.perf_counter() - t0
            self._batches_since_sweep += unit.n_parts
            if self._batches_since_sweep >= self.SWEEP_EVERY:
                self.state = dev.dep_sweep(self.state)
                self._step_seq += 1
                self._batches_since_sweep = 0
        self._observe_ingest(t0, dispatch_s)

    def _write_device_many(self, group) -> None:
        """One chained launch over ≥2 chunks: pad every chunk to the
        group's max shapes, stack, and scan (dev.ingest_steps). Each
        chunk individually satisfies the ring-capacity guards, and scan
        steps run sequentially, so per-launch invariants match the
        single-chunk path's."""
        self._commit_group(group)

    def _write_device(self, batch: SpanBatch, name_lc: np.ndarray,
                      indexable: np.ndarray) -> None:
        """Pad, upload, and run the fused ingest step for one chunk that
        already fits the ring capacities."""
        self._commit_group([(batch, name_lc, indexable)])

    def _observe_ingest(self, t0: float,
                        dispatch_s: Optional[float] = None) -> None:
        """Launch accounting: always-on dispatch time (``dispatch_s``
        when the caller clocked it before extra launches joined the
        window), plus the TRUE step latency every INGEST_SYNC_EVERY-th
        launch (block on the write_pos scalar — one tiny D2H, no ring
        traffic). The old single-sketch scheme timed only the async
        dispatch, so /metrics showed host dispatch cost as if it were
        device compute (the r9 underreporting fix)."""
        self._h_dispatch.observe(
            dispatch_s if dispatch_s is not None
            else _time.perf_counter() - t0)
        self._c_launches.inc()
        self._launch_seq += 1
        if self._launch_seq % self.INGEST_SYNC_EVERY == 1 \
                or self.INGEST_SYNC_EVERY == 1:
            # Under the read lock: a reader-triggered pending sweep
            # (get_dependencies) is a DONATING step — blocking on a
            # state the sweep just consumed would hit deleted buffers.
            with self._rw.read():
                jax.block_until_ready(self.state.write_pos)
            self._h_ingest.observe(_time.perf_counter() - t0)

    # Write-path sweep cadence (batches). Each sweep is one small launch
    # over the pending ring; 64 bounds a cross-batch child's link
    # latency to ~64 ItemQueue batches without taxing every write.
    SWEEP_EVERY = 64

    def _sweep_pending(self) -> None:
        """Resolve pending (late-parent) children now; see dev.dep_sweep.
        Clock reset rides the write-lock hold (checkpoint-cut
        consistency, see _commit_unit)."""
        self.ensure_writable()
        with self._rw.write():
            self.state = dev.dep_sweep(self.state)
            self._step_seq += 1
            self._batches_since_sweep = 0

    def _maybe_archive(self, incoming: int) -> None:
        """Close the current dependency time bucket on a span-volume
        cadence (one bucket per half ring capacity — the
        hourly-aggregation-timer role). Unlike the r2 watermark archive
        this is pure windowing policy: links resolve at ingest through
        the streaming hash join and never depend on ring residency."""
        cap = self.config.capacity
        if self._wp + incoming - self._archived <= cap:
            return
        self.ensure_writable()
        with self._rw.write():
            self.state = dev.dep_close_bucket(self.state)
            self._step_seq += 1
            self._batches_since_sweep = 0
            self._archived = min(
                self._wp,
                max(self._wp + incoming - cap, self._wp - cap // 2),
            )

    def _maybe_capture(self, n_s: int, n_a: int, n_b: int) -> None:
        """Eviction capture trigger, called BEFORE every device write
        with the incoming row counts: if the write would overwrite any
        uncaptured row in ANY of the three rings (the annotation rings
        lap faster than the span ring whenever spans average more side
        rows than the capacity ratio), pull the whole uncaptured window
        [_cap_upto, _wp) to the host and hand it to the sink. Riding
        the write path keeps the invariant simple — every captured row
        is still fully resident — and adds ZERO ops to the fused ingest
        step (the pull is its own read-only launch)."""
        sink = self.eviction_sink
        if sink is None:
            return
        c = self.config
        # Threshold check UNDER the capture lock: the clocks it reads
        # are _cap_lock-guarded, and the committing thread is the only
        # writer, so the uncontended acquire costs nothing while
        # keeping the read inside the lock's ownership (graftlint
        # guarded-by; the old lock-free early-out raced capture_now).
        with self._cap_lock:
            if (self._wp + n_s - self._cap_upto <= c.capacity
                    and self._awp + n_a - self._cap_a <= c.ann_capacity
                    and self._bwp + n_b - self._cap_b
                    <= c.bann_capacity):
                return
            self._capture_window()

    def _capture_window(self) -> None:  # called-under: _cap_lock
        """Pull the whole uncaptured window [cap_upto, wp) — the ONE
        capture body behind the write-path trigger and capture_now,
        serialized by _cap_lock (the serial writer holds self._lock
        too; the pipeline's commit thread holds only _cap_lock, and
        capture_now drains the pipeline before taking it).

        The PULL is synchronous — the captured-before-overwrite
        ordering invariant requires the read-only launch to complete
        before the overwriting step dispatches — but with
        capture_backlog > 0 the captured rows stay DEVICE-resident and
        the D2H + deflate + directory append move to the background
        sealer (store/pipeline.EvictionSealer), whose bounded queue is
        the only thing that can stall ingest. Capture outputs are
        fresh arrays no ingest step ever donates, so the sealer needs
        no store lock."""
        lo, hi = self._cap_upto, self._wp
        cap_anns = self._awp - self._cap_a
        cap_banns = self._bwp - self._cap_b
        if hi <= lo:
            self._cap_upto, self._cap_a, self._cap_b = (
                self._wp, self._awp, self._bwp)
            return
        t0 = _time.perf_counter()
        n_s, n_a, n_b, s_m, a_m, b_m = self._pull_evicted_rows(
            lo, hi, cap_anns, cap_banns)
        pull_s = _time.perf_counter() - t0
        if self.capture_backlog and self.capture_backlog > 0:
            if self._sealer is None:
                self._sealer = EvictionSealer(
                    self, backlog=self.capture_backlog,
                    registry=self._registry)
            self._sealer.submit(n_s, n_a, n_b, s_m, a_m, b_m,
                                lo, hi, pull_s)
        else:
            batch, gids = mats_to_batch(
                n_s, n_a, n_b, *jax.device_get((s_m, a_m, b_m)))
            kill_point("mid-seal")
            self.eviction_sink(batch, gids, lo, hi,
                               _time.perf_counter() - t0)
            self._note_sealed_locked(lo, hi)
        # Clocks advance only AFTER the pull succeeds: a transient
        # device error mid-pull leaves the window uncaptured-but-
        # resident, and the next write retries it — stamping first
        # would silently skip it forever. (An ASYNC seal failure after
        # a successful pull is counted + re-raised on the write path,
        # but its window cannot be retried — the rows may already be
        # overwritten; checkpoint cuts at the SEALED frontier so a
        # snapshot never claims an unsealed window.)
        self._cap_upto, self._cap_a, self._cap_b = (
            self._wp, self._awp, self._bwp)

    def _capture_pages(self, reclaims) -> None:
        """Paged-layout eviction capture: pull each reclaimed page's
        rows (one [lo, hi) = one page's gid range, hi - lo ==
        page_rows) through the same pull/seal machinery as the ring
        window, BEFORE the claiming unit's launch. Called on the
        committing thread only (serial writer under self._lock, or the
        pipeline's commit thread) — the same ordering position as
        _maybe_capture.

        The sealed frontier stays CONTIGUITY-gated: least-recently-
        written reclaim hands back pages out of gid order, so the
        frontier lags the newest sealed page until the older live
        pages below it are themselves reclaimed — conservative by
        design (a checkpoint cut never claims a live page's gids as
        cold-durable; the saved ring state still holds those rows)."""
        sink = self.eviction_sink
        if sink is None:
            return
        c = self.config
        with self._cap_lock:
            for lo, hi in reclaims:
                t0 = _time.perf_counter()
                n_s, n_a, n_b, s_m, a_m, b_m = self._pull_evicted_rows(
                    lo, hi, c.page_rows * 2, c.page_rows)
                pull_s = _time.perf_counter() - t0
                if self.capture_backlog and self.capture_backlog > 0:
                    if self._sealer is None:
                        self._sealer = EvictionSealer(
                            self, backlog=self.capture_backlog,
                            registry=self._registry)
                    self._sealer.submit(n_s, n_a, n_b, s_m, a_m, b_m,
                                        lo, hi, pull_s)
                else:
                    batch, gids = mats_to_batch(
                        n_s, n_a, n_b,
                        *jax.device_get((s_m, a_m, b_m)))
                    kill_point("mid-seal")
                    self.eviction_sink(batch, gids, lo, hi,
                                       _time.perf_counter() - t0)
                    self._note_sealed_locked(lo, hi)

    def _note_sealed(self, lo: int, hi: int) -> None:
        """Advance the sealed frontier — every gid below it is durable
        in the cold tier (called by the SEALER THREAD; the inline seal
        path, already under _cap_lock, uses the _locked twin).
        CONTIGUITY-GATED: if an earlier window's seal failed (a hole —
        its rows are lost from the cold tier), the frontier stays
        below the hole even as later windows seal, so a checkpoint cut
        never claims the hole and a restore can re-capture whatever of
        it the saved rings still held.

        The _cap_lock hold is load-bearing: the sealer thread races
        the commit thread's capture trigger and checkpoint's frontier
        cut, and an unlocked read-modify-write here could publish a
        torn frontier (graftlint guarded-by caught the old unlocked
        version)."""
        with self._cap_lock:
            self._note_sealed_locked(lo, hi)

    def _note_sealed_locked(self, lo: int, hi: int) -> None:  # called-under: _cap_lock
        if lo <= self._sealed_upto:
            self._sealed_upto = max(self._sealed_upto, hi)

    def sealed_frontier(self) -> int:
        """Cold-tier durability frontier (gid): every span below it is
        sealed into a cold segment. The sanctioned read for callers
        holding NO store lock (operator tooling, tests). NOT for code
        already under ``_rw`` — taking ``_cap_lock`` inside a read/
        write hold inverts the canonical capture(30) → commit(40)
        order; checkpoint's save path documents its deliberately
        unlocked reads for exactly that reason."""
        with self._cap_lock:
            return self._sealed_upto

    def seal_barrier(self) -> None:
        """Wait until every pulled capture window is sealed (no-op
        without an async sealer). Cold-tier reads and checkpoint cuts
        run behind this so a captured row is never invisible."""
        s = self._sealer
        if s is not None:
            s.drain()

    def capture_now(self) -> None:
        """Flush the uncaptured window [cap_upto, write_pos) through
        the eviction sink and wait for the seal — checkpoint restore
        uses this to re-align the capture clocks (the ann/bann mirrors
        don't survive a restart), and operators can call it to make
        the cold tier current before a planned shutdown."""
        with self._lock:
            if self.eviction_sink is None:
                return
            if self._planner is not None:
                # Paged stores capture at reclaim time only: every
                # page handed back to the free list was sealed before
                # reuse, and LIVE pages are never flushed early (their
                # rows are still fully resident and queryable — there
                # is no pending window to make current).
                self.drain_pipeline()
                self.seal_barrier()
                return
            self.drain_pipeline()
            with self._cap_lock:
                self._capture_window()
            self.seal_barrier()

    def _pull_evicted_rows(self, lo: int, hi: int, n_anns: int,
                           n_banns: int):
        """One capture window as (n_s, n_a, n_b, span_mat, ann_mat,
        bann_mat) with the row matrices still DEVICE-resident — only
        the [3] count vector syncs, so the write path never waits on
        the bulk D2H. The host mirrors predict the side-row counts
        exactly; the escalation loop is a belt-and-braces guard, not
        the steady state."""
        from zipkin_tpu.store.base import escalate_cap

        c = self.config
        k_s = min(_next_pow2(hi - lo), c.capacity)
        k_a = min(_next_pow2(max(n_anns, 1)), c.ann_capacity)
        k_b = min(_next_pow2(max(n_banns, 1)), c.bann_capacity)
        while True:
            with self._rw.read():
                counts, s_m, a_m, b_m = dev.capture_eviction_rows(
                    self.state, lo, hi, k_s, k_a, k_b)
                n_s, n_a, n_b = (
                    int(x) for x in jax.device_get(counts))
            if n_s <= k_s and n_a <= k_a and n_b <= k_b:
                return n_s, n_a, n_b, s_m, a_m, b_m
            k_s = escalate_cap(n_s, k_s, c.capacity)
            k_a = escalate_cap(n_a, k_a, c.ann_capacity)
            k_b = escalate_cap(n_b, k_b, c.bann_capacity)

    def adopt_state(self, state, spans_written: int,
                    archived: Optional[int] = None) -> None:
        """Adopt a device state produced OUTSIDE the store's write path
        (e.g. a benchmark streaming dev.ingest_step directly) and re-seed
        every host-side clock that paces sweeps and bucket rotation:

        - ``spans_written``: total spans ever written into the adopted
          state (its write_pos) — seeds the archive cadence.
        - ``archived``: span watermark of the last dependency-bucket
          close; defaults to ``spans_written`` ("just rotated").

        The sweep clock is marked dirty: the adopted state may carry
        unresolved pending children, so the first dependency read must
        run a pending sweep (the streaming-join contract) even though no
        store-mediated batch was ever written."""
        self.drain_pipeline()
        self.seal_barrier()
        self.ensure_writable()
        with self._rw.write():
            self.state = state
        self._step_seq += 1
        self._wp = int(spans_written)
        self._archived = self._wp if archived is None else int(archived)
        self._batches_since_sweep = 1
        # The adopted state's history predates the sink: re-seed the
        # capture clocks so only post-adoption evictions are captured.
        # The sealed frontier follows (nothing is pending: the barrier
        # above drained the sealer; the lock still owns the clocks).
        self._awp = self._bwp = 0
        with self._cap_lock:
            self._cap_upto = self._wp
            self._cap_a = self._cap_b = 0
            self._sealed_upto = self._cap_upto
        # The adopted state's aggregates were built outside the write
        # path: resync the sketch mirror lazily from the device.
        self.sketch_mirror.mark_cold()
        # Paged: the page table is a pure function of the resident
        # rows — rebuild it from the adopted columns (partial pages
        # stay closed; see PagePlanner.rebuild).
        if self._planner is not None:
            row_gid, trace_col = jax.device_get(
                (self.state.row_gid, self.state.trace_id))
            self._planner.rebuild(row_gid, trace_col,
                                  wal_applied=self._wal_applied)

    # -- durable write-ahead log (zipkin_tpu.wal) -----------------------

    def attach_wal(self, wal) -> None:
        """Journal every subsequent launch group into ``wal`` before
        its donating commit (the ack-after-append contract,
        docs/DURABILITY.md). Attach before live writes — groups
        committed earlier are only covered by checkpoints. The store
        does not own the log's lifecycle: callers close() it after the
        store is closed."""
        from zipkin_tpu.wal.record import dict_sizes

        with self._lock:
            self.wal = wal
            self._wal_marks = dict_sizes(self.dicts)
            if self.lineage is not None:
                wal.set_on_durable(self.lineage.on_durable)

    def attach_lineage(self, tracker) -> None:
        """Stamp every journaled launch group with lineage meta
        (obs.fleet.LineageTracker) and report its append/fsync
        progress to the tracker. Host-side only: stamps ride the WAL
        record's json header, which replay ignores — the device write
        path and step census are untouched. Order-independent with
        ``attach_wal``."""
        with self._lock:
            self.lineage = tracker
            if self.wal is not None:
                self.wal.set_on_durable(tracker.on_durable)

    def _journal_group(self, group) -> int:
        """Append one planned launch group (+ the dictionary entries
        its encode step added) to the WAL; returns the record's
        sequence. Runs on the encoding thread under self._lock, so
        append order == encode order == commit order — the property
        replay's dictionary-delta chain depends on.

        With a lineage tracker attached the record meta gains the
        commit timestamp (+ sampled B3 context) and the append is
        reported. The append runs inside ``tracker.suppressed()``:
        with fsync=off/batch the WAL's on_durable callback fires
        synchronously in ``wal.append`` while THIS thread holds the
        store's encode lock — a tracker flush there would re-enter
        ``store.apply`` and deadlock; suppression defers it to the
        next out-of-lock flush site."""
        from zipkin_tpu.wal.record import dump_dict_deltas, encode_unit

        sizes, deltas = dump_dict_deltas(self.dicts, self._wal_marks)
        lin = self.lineage
        if lin is not None:
            extra = lin.stamp()
            with lin.suppressed():
                seq = self.wal.append(encode_unit(
                    group, self._wal_marks, deltas, extra=extra))
            lin.note_append(seq, extra)
        else:
            seq = self.wal.append(encode_unit(group, self._wal_marks,
                                              deltas))
        self._wal_marks = sizes
        return seq

    def wal_sync(self) -> None:
        """Force the attached WAL's durable frontier to the append
        frontier (fsync); no-op without a WAL. Part of the shutdown
        ordering: drain-pipeline → seal-barrier → wal_sync →
        checkpoint."""
        if self.wal is not None:
            self.wal.sync()

    # -- pipelined ingest lifecycle (store/pipeline) --------------------

    def start_pipeline(self, depth: Optional[int] = None,
                       stage_buffers: Optional[int] = None
                       ) -> IngestPipeline:
        """Switch the write path to the three-stage ingest pipeline:
        apply/write_thrift become stage 1 (encode + pow2 pad, outside
        the device critical section), a stage thread device_puts into
        double-buffered staging slots, and a commit thread holds the
        write lock only for the donating swap. ``depth`` bounds the
        prefetch queue (the writer backpressure); ``stage_buffers``
        sizes the staged-unit queue (default STAGE_BUFFERS = 2, the
        classic double buffer — see IngestPipeline). Reads are
        untouched; they see a consistent, possibly a-few-batches-stale
        state until drain_pipeline(). See docs/INGEST_PIPELINE.md."""
        with self._lock:
            if self._pipeline is not None:
                raise RuntimeError("ingest pipeline already running")
            self._pipeline = IngestPipeline(
                self, depth or self.PIPELINE_DEPTH,
                registry=self._registry,
                stage_buffers=stage_buffers or self.STAGE_BUFFERS)
            return self._pipeline

    def drain_pipeline(self) -> None:
        """Block until every accepted batch is committed to the device
        (no-op when no pipeline is running); re-raises a parked
        pipeline error. After it returns, reads see everything
        apply() accepted before the call."""
        p = self._pipeline
        if p is not None:
            p.drain()

    def stop_pipeline(self, raise_errors: bool = True) -> None:
        """Drain, stop the pipeline threads, and return the store to
        the serial write path. The quiesce runs UNDER the encode lock
        with the pipeline still published: unpublishing first would
        let a writer blocked on _lock fall through to the serial path
        and commit concurrently with the commit thread's remaining
        queued units — two device writers, which the ring-scatter
        contract forbids. Writers block on _lock until the commit
        thread has fully stopped (it never takes _lock, so this cannot
        deadlock)."""
        with self._lock:
            p = self._pipeline
            if p is None:
                return
            p.stop()
            self._pipeline = None
        err = p.take_error()
        if raise_errors and err is not None:
            raise err

    def ingest_pipeline(self) -> Optional[IngestPipeline]:
        """The running ingest pipeline, or None on the serial path —
        the stall watchdog's probe handle (obs.fleet)."""
        return self._pipeline

    def eviction_sealer(self):
        """The async capture sealer, or None when sealing is inline —
        the backlog watchdog's probe handle (obs.fleet)."""
        return self._sealer

    @contextlib.contextmanager
    def pipelined(self, depth: Optional[int] = None):
        """Scoped pipelined ingest: ``with store.pipelined(8): ...`` —
        drains and stops on exit (re-raising any parked error)."""
        pipe = self.start_pipeline(depth)
        try:
            yield pipe
        finally:
            self.stop_pipeline()

    def close(self) -> None:
        """Stop the pipeline (draining accepted batches) and the
        capture sealer (sealing pulled windows), then force the WAL
        durable — nothing accepted or captured is dropped on an
        orderly shutdown. The WAL object itself stays open (its owner
        closes it, after any final checkpoint truncation)."""
        self.stop_pipeline(raise_errors=False)
        s, self._sealer = self._sealer, None
        if s is not None:
            s.stop()
        self.wal_sync()

    # TTLs above the per-write default mark a trace pinned: its spans are
    # materialized to the host pin bank so ring eviction can't drop them.
    DEFAULT_TTL_S = 1.0

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        tid = to_signed64(trace_id)
        with self._lock:
            self.ttls[tid] = ttl_seconds
            self._bump_read_epoch()
            pin = ttl_seconds > self.DEFAULT_TTL_S
            if not pin:
                self.pins.unpin(tid)
        if pin:
            fill_pin(self.pins, self._lock, tid, lambda: (
                self.get_spans_by_trace_ids([trace_id]) or [[]])[0])
            with self._lock:
                self._bump_read_epoch()  # bank filled: reads widened

    def get_time_to_live(self, trace_id: int) -> float:
        with self._lock:
            return self.ttls[to_signed64(trace_id)]

    # -- query-engine hooks (query/engine.py) ---------------------------

    def write_frontier(self) -> Tuple[int, int]:
        """Monotonic host-mirrored commit frontier — the result-cache
        key component. (_step_seq advances inside every donating
        write-lock hold: ingest commits, sweeps, bucket closes, state
        adoption — so ring eviction is a frontier advance too;
        _read_epoch covers host-only visibility changes: pin/TTL
        mutations and pin-bank arrivals.) No device traffic."""
        return (self._step_seq, self._read_epoch)

    def _bump_read_epoch(self) -> None:
        self._read_epoch += 1

    def ensure_sketch_mirror(self) -> SketchMirror:
        """The sketch mirror, resynced from the device aggregates if a
        state swap left it cold (checkpoint restore, adopt_state) —
        ONE batched D2H, after which incremental deltas keep it warm
        with zero device traffic. Lock order: _rw.read THEN the
        mirror's lock (the commit path takes _rw.write then the
        mirror's lock — same order, no inversion)."""
        m = self.sketch_mirror
        if not m.warm:
            with self._rw.read():
                st = self.state
                host = jax.device_get((
                    st.svc_hist, st.ann_svc_counts, st.name_presence,
                    st.ann_value_counts, st.bann_key_counts,
                    st.hll_traces, st.win_epoch, st.win_counts,
                    st.win_sums, st.win_mm,
                ))
                m.adopt(*host)
        return m

    # -- windowed analytics (aggregate/windows.py) ----------------------
    # windowed_quantiles / slo_burn / latency_heatmap come from the
    # WindowedAnalytics mixin (store/analytics.py): host-only reads
    # over the sketch mirror, shared verbatim with the device-free
    # ReplicaSpanStore (store/replica.py).

    # -- id lookups -----------------------------------------------------

    def _svc_id(self, service_name: str) -> Optional[int]:
        return self.dicts.services.get(service_name.lower())

    def get_trace_ids_by_name(
        self, service_name: str, span_name: Optional[str],
        end_ts: int, limit: int, force_scan: bool = False,
    ) -> List[IndexedTraceId]:
        """``force_scan`` pins the read to the O(ring) scan kernels —
        the on-device index-vs-scan exactness harness (bench.py
        --tpu-exactness) compares both paths on one live store."""
        svc = self._svc_id(service_name)
        if svc is None or limit <= 0:
            return []
        force_scan = force_scan or service_scan_only(svc, self.config)
        if span_name is not None:
            name_lc = self.dicts.span_names.get(span_name.lower())
            if name_lc is None:
                return []
        else:
            name_lc = -1

        def fetch(k):
            with self._rw.read():
                mat = jax.device_get(dev.query_trace_ids_by_service(
                    self.state, svc, name_lc, end_ts, k
                ))
            cands = [(int(t), int(ts))
                     for t, ts, v in zip(mat[0], mat[1], mat[2]) if v]
            return cands, len(cands) >= k

        def index_fetch(k):
            with self._rw.read():
                mat, complete, wm = jax.device_get(
                    dev.iquery_trace_ids_by_service(
                        self.state, svc, name_lc, end_ts, k
                    )
                )
            cands = [(int(t), int(ts))
                     for t, ts, v in zip(mat[0], mat[1], mat[2]) if v]
            return cands, bool(complete), int(wm), mat.shape[1]

        # Paged layout: the index read gates (wm < write_pos -
        # capacity trust checks) are FIFO-gid arithmetic, unsound
        # against epoch-encoded gids — id lookups take the exact
        # O(ring) scan (index WRITES still run, keeping the lowering
        # within one census table of the ring step).
        if (self.config.use_index and not force_scan
                and self._planner is None):
            return self._index_first(
                limit, self.config.ann_capacity, index_fetch, fetch
            )
        return topk_ids_with_escalation(
            limit, self.config.ann_capacity, fetch
        )

    def _index_first(self, limit, k_max, index_fetch, scan_fetch):
        """index_first_topk with hit/fallback accounting (→ /metrics)."""
        return index_first_topk(limit, k_max, index_fetch, scan_fetch,
                                stats=self)

    def get_trace_ids_by_annotation(
        self, service_name: str, annotation: str, value: Optional[bytes],
        end_ts: int, limit: int, force_scan: bool = False,
    ) -> List[IndexedTraceId]:
        if annotation in CORE_ANNOTATIONS or limit <= 0:
            return []
        svc = self._svc_id(service_name)
        if svc is None:
            return []
        force_scan = force_scan or service_scan_only(svc, self.config)
        resolved = resolve_annotation_query(self.dicts, annotation, value)
        if resolved is None:
            return []
        ann_value, bann_key, bann_value, bann_value2 = resolved

        def fetch(k):
            with self._rw.read():
                mat = jax.device_get(dev.query_trace_ids_by_annotation(
                    self.state, svc, ann_value, bann_key, bann_value,
                    bann_value2, end_ts, k,
                ))
            cands = [(int(t), int(ts))
                     for t, ts, v in zip(mat[0], mat[1], mat[2]) if v]
            return cands, len(cands) >= k

        def index_fetch(k):
            with self._rw.read():
                mat, complete, wm = jax.device_get(
                    dev.iquery_trace_ids_by_annotation(
                        self.state, svc, ann_value, bann_key, bann_value,
                        bann_value2, end_ts, k,
                    )
                )
            cands = [(int(t), int(ts))
                     for t, ts, v in zip(mat[0], mat[1], mat[2]) if v]
            return cands, bool(complete), int(wm), mat.shape[1]

        c = self.config
        # A name present BOTH as a user-annotation value and as a
        # binary key matches through either side in the scan (OR
        # semantics); the index families are per-side, so the rare
        # mixed case takes the scan.
        mixed = ann_value >= 0 and bann_key >= 0
        if (c.use_index and not mixed and not force_scan
                and self._planner is None):
            return self._index_first(
                limit, c.ann_capacity + c.bann_capacity, index_fetch,
                fetch,
            )
        return topk_ids_with_escalation(
            limit, c.ann_capacity + c.bann_capacity, fetch
        )

    def get_trace_ids_multi(self, queries) -> List[List[IndexedTraceId]]:
        """Batched index read: every query's bucket probe rides ONE
        kernel launch (dev._iq_multi_impl) instead of one ~100ms
        dispatch each; only unresolvable dictionary keys, mixed
        ann/binary names, and distrusted buckets drop to the singular
        paths. See SpanStore.get_trace_ids_multi for the query format."""
        c = self.config
        if not c.use_index or self._planner is not None or not queries:
            return super().get_trace_ids_multi(queries)
        results, probes, limits, fallback = resolve_multi_probes(
            c, self.dicts, queries
        )
        if probes:
            arrs, k, k_eff = build_probe_arrays(c, probes, limits)
            with self._rw.read():
                mats, completes, wms = jax.device_get(
                    dev.iquery_trace_ids_multi(self.state, arrs, k)
                )
            per_probe = []
            for pi, p in enumerate(probes):
                mat = mats[pi]
                cands = [
                    (int(t), int(ts))
                    for t, ts, v in zip(mat[0], mat[1], mat[2]) if v
                ]
                window_pi = min(k_eff, p[1][3])
                per_probe.append((
                    cands, bool(completes[pi]), int(wms[pi]),
                    len(cands) >= window_pi,
                ))
            gated = gate_multi_probes(probes, limits, per_probe)
            for qi, ids in gated.items():
                if ids is None:
                    fallback.append(qi)
                else:
                    self.index_hits += 1
                    results[qi] = ids
        for qi in fallback:
            q = queries[qi]
            if q[0] == "name":
                results[qi] = self.get_trace_ids_by_name(*q[1:])
            else:
                results[qi] = self.get_trace_ids_by_annotation(*q[1:])
        return [r if r is not None else [] for r in results]

    # -- trace reads ----------------------------------------------------

    @staticmethod
    def _canon_ids(trace_ids: Sequence[int]) -> Dict[int, int]:
        """signed-canonical id → caller's original id (ids ≥ 2^63 arrive
        unsigned on the wire but are stored signed)."""
        return {to_signed64(t): t for t in trace_ids}

    def _sorted_qids(self, trace_ids: Sequence[int]) -> np.ndarray:
        # Unique: duplicated request ids would double-count bucket
        # candidates on the index fast path (result duplication, and the
        # cap-escalation loop can never converge); downstream decode is
        # keyed by trace id, so duplicates reconstruct per request id.
        return np.unique(
            np.asarray([to_signed64(t) for t in trace_ids], np.int64)
        )

    def _durations_mat(self, qids: np.ndarray,
                       force_scan: bool = False) -> np.ndarray:
        """[4, nq] duration matrix: trace-membership fast path when its
        exactness gate holds, the full-ring scan otherwise."""
        with self._rw.read():
            if (self.config.use_index and not force_scan
                    and self._planner is None):
                mat, exact = jax.device_get(
                    dev.iquery_durations(self.state, qids)
                )
                if exact:
                    return mat
            return jax.device_get(dev.query_durations(self.state, qids))

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        if not trace_ids:
            return set()
        canon = self._canon_ids(trace_ids)
        qids = self._sorted_qids(trace_ids)
        mat = self._durations_mat(qids)
        return exist_from_duration_mat(canon, qids, mat[0], self.pins,
                                       self._lock)

    def _gather_trace_mats(self, trace_ids: Sequence[int],
                           force_scan: bool = False):
        """Shared ring gather for whole-trace reads: (n_s, n_a, n_b,
        span_mat, ann_mat, bann_mat)."""
        qids = self._sorted_qids(trace_ids)
        with self._rw.read():
            st = self.state
            payload = None
            if (self.config.use_index and not force_scan
                    and self._planner is None):
                payload = self._gather_via_index(st, qids)
            if self._planner is not None and not force_scan:
                payload = self._gather_via_pages(st, qids)
            if payload is None:
                def fetch(k_s, k_a, k_b):
                    counts, s_m, a_m, b_m = jax.device_get(
                        dev.gather_trace_rows(st, qids, k_s, k_a, k_b)
                    )
                    n_s, n_a, n_b = (int(x) for x in counts)
                    return n_s, n_a, n_b, (n_s, n_a, n_b, s_m, a_m, b_m)

                payload = gather_with_escalation(self.config, fetch)
        return payload

    def get_trace_rows(self, trace_ids: Sequence[int],
                       force_scan: bool = False
                       ) -> List[Tuple[int, Span]]:
        """Ring rows of the requested traces as (row gid, Span) pairs
        in insertion order, WITHOUT pin-bank merging — the hot-tier
        read the TieredSpanStore dedupes against cold segments by gid
        (a row captured before eviction exists identically in both
        tiers while it stays resident)."""
        if not trace_ids:
            return []
        n_s, n_a, n_b, span_mat, ann_mat, bann_mat = (
            self._gather_trace_mats(trace_ids, force_scan))
        if n_s == 0:
            return []
        batch, gids = mats_to_batch(
            n_s, n_a, n_b, span_mat, ann_mat, bann_mat)
        return [
            (int(g), s) for g, s in zip(gids, self.codec.decode(batch))
        ]

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int],
                               force_scan: bool = False
                               ) -> List[List[Span]]:
        if not trace_ids:
            return []
        n_s, n_a, n_b, span_mat, ann_mat, bann_mat = (
            self._gather_trace_mats(trace_ids, force_scan))
        spans = self._decode_gathered(
            n_s, n_a, n_b, span_mat, ann_mat, bann_mat
        )
        by_tid: Dict[int, List[Span]] = {}
        for span in spans:
            by_tid.setdefault(span.trace_id, []).append(span)
        # Pinned traces read through the eviction-exempt bank.
        with self._lock:
            apply_pin_merges(self.pins, by_tid, trace_ids, to_signed64)
        # One result per query id, duplicates included — matching the
        # in-memory reference store's behavior.
        return [
            by_tid[to_signed64(tid)]
            for tid in trace_ids
            if to_signed64(tid) in by_tid
        ]

    def _decode_gathered(
        self, n_s: int, n_a: int, n_b: int,
        span_mat: np.ndarray, ann_mat: np.ndarray, bann_mat: np.ndarray,
    ) -> List[Span]:
        return decode_gathered(
            self.codec, n_s, n_a, n_b, span_mat, ann_mat, bann_mat
        )

    def _gather_via_pages(self, st, qids: np.ndarray):
        """Whole-trace gather over the queried traces' PAGE CHAINS —
        the paged layout's answer to the index gather: the kernel
        touches K·page_rows candidate rows (dev.gather_paged_trace_rows,
        Pallas block-gather under the VMEM gate) instead of scanning
        the full arena. Returns None when a chain overflowed
        page_max_chain — those reads stay exact via the ring scan."""
        chains = self._planner.chains_for(qids)
        if chains is None:
            return None
        pages, epochs = chains
        # Pad the page list to a pow2 bucket (hole pages = -1 produce
        # zero rows) so steady-state reads hit compiled shapes only.
        k = _next_pow2(max(1, len(pages)))
        pg = np.full(k, -1, np.int32)
        ep = np.zeros(k, np.int64)
        pg[:len(pages)] = pages
        ep[:len(epochs)] = epochs

        def fetch(k_s, k_a, k_b):
            counts, s_m, a_m, b_m = jax.device_get(
                dev.gather_paged_trace_rows(st, qids, pg, ep,
                                            k_s, k_a, k_b)
            )
            n_s, n_a, n_b = (int(x) for x in counts)
            return n_s, n_a, n_b, (n_s, n_a, n_b, s_m, a_m, b_m)

        return gather_with_escalation(self.config, fetch)

    def _gather_via_index(self, st, qids: np.ndarray):
        """Whole-trace gather through the trace-membership buckets (see
        dev.iquery_gather_trace_rows). Returns the gather payload, or
        None when any queried bucket fails its exactness gate — the
        caller then runs the full-ring scan gather."""
        from zipkin_tpu.store.base import index_gather_with_escalation

        def fetch(k_s, k_a, k_b):
            counts, s_m, a_m, b_m, exact = jax.device_get(
                dev.iquery_gather_trace_rows(st, qids, k_s, k_a, k_b)
            )
            n_s, n_a, n_b = (int(x) for x in counts)
            return (bool(exact), n_s, n_a, n_b,
                    (n_s, n_a, n_b, s_m, a_m, b_m))

        return index_gather_with_escalation(self.config, len(qids), fetch)

    def get_traces_duration(
        self, trace_ids: Sequence[int], force_scan: bool = False
    ) -> List[TraceIdDuration]:
        if not trace_ids:
            return []
        canon = self._canon_ids(trace_ids)
        qids = self._sorted_qids(trace_ids)
        mat = self._durations_mat(qids, force_scan)
        return durations_from_mat(trace_ids, canon, qids, mat, self.pins,
                                  self._lock)

    # -- name catalogs --------------------------------------------------

    def get_all_service_names(self) -> Set[str]:
        with self._rw.read():
            present = jax.device_get(self.state.ann_svc_counts) > 0
        d = self.dicts.services
        out = {
            d.decode(i) for i in np.flatnonzero(present)
            if i < len(d) and d.decode(i)
        }
        # Dictionary-overflow services (id >= max_services) cannot mark
        # the presence array — list the ones the rings still hold as
        # annotation/binary hosts (the only data that exists for them;
        # ring-window semantics vs the indexed services' lifetime
        # counter, documented in dev.overflow_service_presence).
        S = self.config.max_services
        n_over = len(d) - S
        if n_over > 0:
            pad = 1 << max(0, (n_over - 1)).bit_length()
            with self._rw.read():
                pres = jax.device_get(
                    dev.overflow_service_presence(self.state, pad)
                )
            out.update(
                name for i in np.flatnonzero(pres[:n_over])
                if (name := d.decode(S + int(i)))
            )
        return out

    def _svc_catalog_scan(self, svc: int):
        """One-launch ring-scan catalog rows for an overflow service
        (see dev.svc_scan_catalog): (names, dur_hist, ann_values,
        bann_keys). The [max_services]-sized catalog arrays cannot
        represent these services, and a clamped gather would serve
        service max_services-1's data under the wrong name.

        The kernel computes all four rows per launch, so a one-entry
        memo keyed on (svc, write position) lets a UI service page that
        calls all four endpoints pay ONE O(ring) scan + D2H instead of
        four."""
        key = (svc, self._wp)
        cached = getattr(self, "_svc_scan_memo", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        with self._rw.read():
            rows = jax.device_get(dev.svc_scan_catalog(self.state, svc))
        self._svc_scan_memo = (key, rows)
        return rows

    def get_span_names(self, service: str) -> Set[str]:
        svc = self._svc_id(service)
        if svc is None:
            return set()
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[0] > 0
        else:
            with self._rw.read():
                row = jax.device_get(self.state.name_presence[svc]) > 0
        d = self.dicts.span_names
        return {
            d.decode(i) for i in np.flatnonzero(row)
            if i < len(d) and d.decode(i)
        }

    # -- analytics (the reference's offline aggregates, served live) ----

    def get_dependencies(self, start_ts: Optional[int] = None,
                         end_ts: Optional[int] = None) -> Dependencies:
        """DependencyLinks from the time-tagged banks + the accumulating
        window — Aggregates.getDependencies(startDate, endDate)
        (Aggregates.scala:26-31). Without a window, the all-time total;
        with one, only banks whose children overlap it (bucket-granular).
        A pending sweep runs first so children whose parent arrived in a
        later batch are linked before the read."""
        from zipkin_tpu.aggregate.job import dependencies_from_bank

        if self._batches_since_sweep:
            with self._lock:
                if self._batches_since_sweep:
                    self._sweep_pending()
        S = self.config.max_services
        k = min(S * S, 1 << 14)
        with self._rw.read():
            st = self.state
            # Device-side compaction: ship the k densest link cells
            # (~400 KB) instead of the full [S*S, 5] bank (~20 MB —
            # the tunnel D2H was the whole dependencies p99). If more
            # than k links are live, transfer the full bank instead:
            # compaction never drops a link.
            if start_ts is None and end_ts is None:
                nz, idx, rows, ts_min, ts_max = jax.device_get((
                    *dev.total_dep_moments_compact(
                        st.dep_moments, st.dep_banks, st.dep_window, k
                    ),
                    st.ts_min, st.ts_max,
                ))
                if int(nz) > k:
                    rows = None
                    bank = jax.device_get(dev.total_dep_moments(st))
            else:
                s = dev.I64_MIN if start_ts is None else int(start_ts)
                e = dev.I64_MAX if end_ts is None else int(end_ts)
                nz, idx, rows, ts_min, ts_max = jax.device_get((
                    *dev.dep_in_range_compact(
                        st.dep_moments, st.dep_banks, st.dep_bank_ts,
                        st.dep_overflow_ts, st.dep_window,
                        st.dep_window_ts, jnp.int64(s), jnp.int64(e), k,
                    ),
                    jnp.maximum(st.ts_min, jnp.int64(s)),
                    jnp.minimum(st.ts_max, jnp.int64(e)),
                ))
                if int(nz) > k:
                    rows = None
                    bank = jax.device_get(dev.dep_moments_in_range(
                        st, jnp.int64(s), jnp.int64(e)
                    ))
        if rows is not None:
            bank = np.zeros((S * S, rows.shape[1]), np.float32)
            bank[idx] = rows
        return dependencies_from_bank(
            bank, self.dicts.services, self.config.max_services,
            float(ts_min), float(ts_max),
        )

    def archive_now(self) -> None:
        """Close the current dependency time bucket immediately: sweep
        pending children, rotate the window into a time-tagged bank (the
        hourly-aggregation-timer role of zipkin-deployment-web's
        AnormAggregator schedule)."""
        with self._lock:
            self.drain_pipeline()
            self.ensure_writable()
            with self._rw.write():
                self.state = dev.dep_close_bucket(self.state)
            self._step_seq += 1
            self._archived = self._wp
            self._batches_since_sweep = 0

    def service_duration_quantiles(
        self, service: str, qs: Sequence[float]
    ) -> Optional[List[float]]:
        svc = self._svc_id(service)
        if svc is None:
            return None
        if service_scan_only(svc, self.config):
            counts = self._svc_catalog_scan(svc)[1]
            c = self.config
            gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
            return Q.quantiles_host(counts, gamma, 1.0, qs)
        with self._rw.read():
            hist = dev.svc_histogram(self.state)
            counts = jax.device_get(hist.counts[svc])
        return Q.quantiles_host(counts, hist.gamma, hist.min_value, qs)

    def top_annotations(self, service: str, k: int = 10) -> List[Tuple[str, int]]:
        svc = self._svc_id(service)
        if svc is None:
            return []
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[2]
        else:
            with self._rw.read():
                row = jax.device_get(self.state.ann_value_counts[svc])
        order = np.argsort(-row)[:k]
        d = self.dicts.annotations
        return [
            (d.decode(int(i)), int(row[i]))
            for i in order
            if row[i] > 0 and i < len(d)
        ]

    def top_binary_keys(self, service: str, k: int = 10) -> List[Tuple[str, int]]:
        svc = self._svc_id(service)
        if svc is None:
            return []
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[3]
        else:
            with self._rw.read():
                row = jax.device_get(self.state.bann_key_counts[svc])
        order = np.argsort(-row)[:k]
        d = self.dicts.binary_keys
        return [
            (d.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(d)
        ]

    def estimated_unique_traces(self) -> float:
        with self._rw.read():
            regs = jax.device_get(self.state.hll_traces)
        return float(hll.estimate(hll.HyperLogLog(regs)))

    def counter_block(self) -> Dict[str, int]:
        """The device counter block (dev.COUNTER_BLOCK_FIELDS): ring
        occupancy/laps, queue depths, poison census, and the ingest
        counters — ONE fused read-only launch + ONE scalar-vector D2H,
        memoized per ingest step (_step_seq), so any number of metric
        scrapes between steps costs zero device traffic. Maintaining
        the block adds no ops to the ingest step itself — the derived
        values are computed at fetch time from cursors the step already
        keeps (bench_smoke's census gate holds with telemetry on)."""
        key = self._step_seq
        memo = self._cblock_memo
        if memo is not None and memo[0] == key:
            return memo[1]
        with self._rw.read():
            vec = jax.device_get(dev.counter_block(self.state))
        blk = {
            name: int(v)
            for name, v in zip(dev.COUNTER_BLOCK_FIELDS, vec)
        }
        self._cblock_memo = (key, blk)
        return blk

    def step_census(self, n_spans: int = 256, n_anns: int = 512,
                    n_banns: int = 256) -> Dict[str, int]:
        """Scatter/gather/sort census of the fused ingest step's
        StableHLO lowering at the given pad shapes — the portable proxy
        for per-batch launch cost (NOTES_r03 §3; gated in tier-1 at
        95 scatters / 5 sorts). Memoized per shape; computed only when
        asked (a trace, not a compile) — metric scrapes never pay it."""
        key = (n_spans, n_anns, n_banns)
        memo = getattr(self, "_census_memo", None)
        if memo is not None and memo[0] == key:
            return memo[1]
        from zipkin_tpu.columnar.schema import SpanBatch

        batch = SpanBatch.empty(0, 0, 0)
        # Paged configs lower with planner-assigned slot/gid columns
        # (shape [P]); synthesize empty ones so the traced shapes
        # match what _pad_unit feeds the compiled step.
        paged_cols = (
            dict(span_slot=np.zeros(0, np.int32),
                 span_gid=np.zeros(0, np.int64),
                 reclaim_pages=np.zeros(0, np.int32))
            if self.config.paged_enabled else {})
        db = dev.make_device_batch(
            batch, name_lc_id=np.zeros(0, np.int32),
            indexable=np.zeros(0, bool),
            pad_spans=n_spans, pad_anns=n_anns, pad_banns=n_banns,
            **paged_cols,
        )
        with self._rw.read():
            text = dev.ingest_step.lower(self.state, db).as_text()
        census = dev.stablehlo_op_census(text)
        self._census_memo = (key, census)
        return census

    def counters(self) -> Dict[str, float]:
        out = {k: float(v) for k, v in self.counter_block().items()}
        # Host-side guards surface through the same hook (the API's
        # /metrics reads counters() generically).
        out["anns_truncated"] = float(self.anns_truncated)
        out["banns_truncated"] = float(self.banns_truncated)
        out["index_hits"] = float(self.index_hits)
        out["index_scan_fallbacks"] = float(self.index_fallbacks)
        # jit cache-miss tracking for the ingest/staging jits: a warmed
        # pipelined steady state must hold this flat (bench_smoke's
        # pipeline phase gates the delta at zero).
        out["jit_compiles"] = float(dev.compile_count())
        # The resident query programs' twin counter: flat in steady
        # state (every dispatch hits a compiled variant) — the query
        # engine's "zero steady-state recompiles" observable.
        out["query_jit_compiles"] = float(dev.query_compile_count())
        p = self._pipeline
        if p is not None:
            out["pipeline_prefetch_depth"] = float(p.queued())
        s = self._sealer
        if s is not None:
            out["capture_backlog"] = float(s.queued())
        # Active ingest kernel paths (r12): which rank / arena-scatter
        # implementations this config's compiled steps took, so every
        # /metrics scrape and bench record says which kernel produced
        # its numbers (dev.active_paths — trace-time records).
        paths = dev.active_paths(self.config)
        out["rank_path_counting"] = float(
            "counting" in paths.get("rank", ()))
        out["scatter_path_pallas"] = float(
            "pallas" in paths.get("scatter", ()))
        out["batch_spans_limit"] = float(self._max_chunk_spans())
        # Paged-layout allocator occupancy (host mirrors — the same
        # numbers the zipkin_store_pages_* gauges export).
        if self._planner is not None:
            pstats = self._planner.stats()
            out["pages_active"] = float(pstats["pages_active"])
            out["pages_free"] = float(pstats["pages_free"])
            out["page_reclaims_total"] = float(pstats["page_reclaims"])
        # Windowed-arena fold accounting (host-monotonic mirror
        # counters — zero device traffic, like every read above).
        out["window_spans"] = float(self.sketch_mirror.win_spans_total)
        out["window_errors"] = float(
            self.sketch_mirror.win_errors_total)
        return out

    def stored_span_count(self) -> float:
        """The DEVICE spans_seen counter — the adaptive controller's
        flow source reads the sketch state itself, not a host mirror.
        Served from the per-step counter block (at most one D2H per
        ingest step, shared with every other telemetry read)."""
        return float(self.counter_block()["spans_seen"])
