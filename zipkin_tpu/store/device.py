"""Device-resident columnar span store: state pytree + fused kernels.

The TPU replacement for the reference's scatter-indexes-into-a-DB design
(CassieSpanStore.scala:283-321 writes one batch per column family per
span batch; 5 index ops per span). Here a span batch is uploaded once as
padded columnar arrays and **one jitted ``ingest_step`` launch** updates:

- the span/annotation/binary-annotation ring buffers (the store, TTL by
  eviction — the analogue of Cassandra's span TTL, CassieSpanStore:47),
- the streaming dependency hash join (span table + pending ring +
  window bank — the ZipkinAggregateJob resolved at ingest time),
- the device index column families (service / span-name / annotation /
  binary / trace-membership bucket rings — the Cassandra index CFs),
- per-service latency histograms (p50/p95/p99 queries),
- per-service span counts, span-name presence, top-annotation counters
  (ServiceNames/SpanNames/TopAnnotations column families),
- a HyperLogLog of distinct trace ids and a count-min of spans/trace,
- ingest counters feeding the adaptive sampler.

Queries are separate jitted kernels: index reads touch O(bucket depth)
rows and carry exactness gates (never-wrapped cursor, overwrite
watermark, displaced-gid gate); the O(ring) scan kernels remain the
always-exact fallback. The host only receives the k winners.

State carries 64-bit ids/timestamps (x64 mode); all sketch state is
32-bit. Static configuration (capacities) is pytree aux data so jit
retraces only when shapes actually change.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zipkin_tpu.columnar.schema import SpanBatch
from zipkin_tpu.models.constants import FIRST_USER_ANNOTATION_ID
from zipkin_tpu.ops import cms, hll, join
from zipkin_tpu.ops import moments as M
from zipkin_tpu.ops import quantile as Q
from zipkin_tpu.ops.hashing import dev_split64

I64_MAX = np.int64(2**63 - 1)
I64_MIN = np.int64(-(2**63))
I32_MIN = np.int32(-(2**31))
NO_TS = -1


class StoreConfig(NamedTuple):
    """Static store geometry (hashable → usable as a jit static arg)."""

    capacity: int = 1 << 16  # span ring rows
    ann_capacity: int = 1 << 18
    bann_capacity: int = 1 << 17
    max_services: int = 256
    max_span_names: int = 2048
    max_annotation_values: int = 4096
    max_binary_keys: int = 1024
    cms_depth: int = 4
    cms_width: int = 1 << 16
    hll_p: int = 14
    # 2048 buckets at alpha=0.01 cover ~1 µs .. ~10^17 µs; fewer buckets
    # silently clip long durations into the top bucket.
    quantile_buckets: int = 2048
    quantile_alpha: float = 0.01
    # Ring of time-tagged dependency-link banks: closing a time bucket
    # (dep_close_bucket) rotates the accumulating window bank into its
    # own [S*S, 5] slot stamped with the resolved children's ts range,
    # so get_dependencies(start, end) can answer a window
    # (Aggregates.getDependencies(startDate, endDate),
    # Aggregates.scala:26-31). Banks older than the ring merge into a
    # tail bank (all-time totals never regress).
    dep_buckets: int = 16
    # Streaming-join state sizes (0 = derived from capacity). The span
    # hash table resolves child → parent service at INGEST time (the
    # device replacement for the Scalding parent×child shuffle join,
    # ZipkinAggregateJob.scala:26-33); the pending ring holds children
    # whose parent hasn't arrived yet, re-probed by dep_sweep.
    span_tab_slots: int = 0  # open-addressing slots; default 2*capacity
    pend_slots: int = 0  # pending-children ring; default capacity//4
    # Device index column families (the ServiceNameIndex /
    # ServiceSpanNameIndex / AnnotationsIndex roles,
    # cassandra-schema.txt:1-22): per-key FIFO bucket rings written by
    # batch scatters at ingest, so index queries read O(bucket depth)
    # rows instead of scanning the rings — on this device class every
    # HLO op costs ~25-100ms at ring size (NOTES_r03.md), which made
    # O(ring) index queries ~1s each. 0 = derived from capacity.
    use_index: bool = True
    idx_service_depth: int = 0
    idx_name_buckets: int = 0
    idx_name_depth: int = 0
    idx_ann_buckets: int = 0
    idx_ann_depth: int = 0
    idx_bann_buckets: int = 0
    idx_bann_depth: int = 0
    # Trace-membership gid index (whole-trace fetch + durations).
    # buckets * depth >= 2 * ring capacity keeps the exactness gate
    # (everything a bucket displaced is already evicted) true in steady
    # state — see the trace-segment gate in _index_write.
    idx_trace_buckets: int = 0
    # Per-key cursor table slots (0 = 2x total candidate buckets). See
    # StoreState.key_tab.
    idx_key_slots: int = 0
    # Route ingest scatter-adds through the VMEM-resident pallas
    # histogram kernels (ops/pallas_kernels.py) instead of XLA scatter.
    # Benchmarked on the real chip by bench.py --compare-kernels; arrays
    # whose size is not a multiple of 128 lanes fall back to XLA.
    # With r12 this also routes the index-arena entry scatter through
    # the grid-sequential claim+scatter kernel WHEN the arena fits VMEM
    # (pallas_kernels.arena_scatter_supported); bigger arenas keep the
    # XLA plane-scatter path (the NOTES_r06 §3 roofline boundary).
    use_pallas: bool = False
    # Host-side per-launch span bound (the ingest batch-escalation knob,
    # r12): 0 keeps the store's legacy MAX_CHUNK default (4096); larger
    # values let one launch carry more spans, amortizing the per-launch
    # scatter entry costs — re-measure the knee with
    # scripts/profile_ingest.py --batch-spans-sweep / bench.py
    # --ingest-matrix. The ring-capacity guards (capacity//2,
    # pending_slots, ann/bann rings) still clamp it per launch.
    batch_spans: int = 0
    # FIFO-rank computation for the unified index write (_index_write):
    # "argsort" = the r6 stable rank sort; "counting" = the r12
    # segmented counting rank (one scatter-add + cumsum + one gather —
    # no stablehlo.sort); "auto" picks counting on the TPU backend
    # whenever the coarse watermark regime is active (wm_shift > 0)
    # and the counting scratch fits its budget, argsort otherwise
    # (incl. everywhere on CPU, where the comparator sort is the
    # faster implementation — see rank_mode). Both paths are
    # BITWISE-identical (tests/test_rank_paths.py fuzzes this), so the
    # choice is pure perf policy and may vary per launch shape.
    rank_path: str = "auto"
    # Windowed Moments-sketch analytics arena (r13,
    # aggregate/windows.py): a dense [S, W, k] grid of INTEGER
    # Moments-sketch cells keyed by (service, time bucket) — per cell
    # the (total, error, duration) count triple, the power sums
    # Σx..Σx⁴ of the quantized log-duration x, and (min, max) of x.
    # Time buckets are ring-indexed with per-slot epoch stamps, so any
    # ad-hoc window is a cell-sum and stale slots self-clear on reuse.
    # window_seconds is the bucket width; window_buckets is the ring
    # length W, giving window_seconds * window_buckets of windowed
    # retention. OPT-IN at the library layer (default 0 = the arena's
    # step update lowers out entirely and the state arrays shrink to a
    # [S, 1, k] stub so the checkpoint schema stays uniform) — the
    # daemon enables it by default via --window-seconds (example.py),
    # and the census bump it spends inside the fused step is gated in
    # store/census.py (BASE vs BASE + WINDOW_BUMP lowerings).
    window_seconds: int = 0
    window_buckets: int = 64
    # Paged span storage (r19, the Ragged-Paged-Attention layout): the
    # span ring is carved into capacity/page_rows fixed-size pages
    # allocated from a host free-list (store/paged.PagePlanner) and
    # chained per trace, so wildly skewed trace sizes share one slot
    # pool without over-provisioning. gids stay epoch-encoded
    # (gid = page_epoch * capacity + slot), which keeps the
    # slot == gid % capacity liveness invariant — every ring-scan query
    # kernel works unchanged on a paged store. "ring" (default) is the
    # historical FIFO layout; its fused-step lowering is byte-identical
    # with these fields present (static branch, store/census.py BASE).
    layout: str = "ring"
    # Rows per device page. Power of two >= 8; multiples of 128 keep
    # the pallas page-gather kernel eligible (lane-aligned sublane
    # slices — see ops/pallas_kernels.paged_gather_supported).
    page_rows: int = 256
    # Host page-table chain bound per trace: a trace spanning more
    # pages than this stops being page-addressable and its reads fall
    # back to the exact ring-scan gather (bounded host memory; the
    # maxTraceCols-style guard at page granularity).
    page_max_chain: int = 64

    @property
    def paged_enabled(self) -> bool:
        return self.layout == "paged"

    @property
    def n_pages(self) -> int:
        return self.capacity // max(1, self.page_rows)

    @property
    def tab_slots(self) -> int:
        # Power of two: _tab_slots masks with n-1 and relies on an odd
        # double-hash step being coprime to the table size.
        return _next_pow2_int(self.span_tab_slots or 2 * self.capacity)

    @property
    def pending_slots(self) -> int:
        # Never smaller than a max-size ingest chunk: one launch's
        # unresolved children must fit without self-collision
        # (TpuSpanStore.write_batch validates this).
        return _next_pow2_int(self.pend_slots or max(1 << 16,
                                                     self.capacity // 4))

    def _derived(self, explicit: int, scale: int, lo: int,
                 hi: int) -> int:
        """Derived index geometry: total entries stay O(ring capacity)
        (the families mirror the rings they index; outsized arrays cost
        a full copy per step on backends without buffer donation)."""
        return _next_pow2_int(
            explicit or max(lo, min(hi, self.capacity // scale))
        )

    @property
    def svc_depth(self) -> int:
        return self._derived(self.idx_service_depth, 64, 64, 4096)

    @property
    def name_buckets(self) -> int:
        return self._derived(self.idx_name_buckets, 32, 256, 8192)

    @property
    def name_depth(self) -> int:
        return self._derived(self.idx_name_depth, 512, 64, 512)

    @property
    def ann_buckets(self) -> int:
        return self._derived(self.idx_ann_buckets, 16, 256, 16384)

    @property
    def ann_depth(self) -> int:
        return self._derived(self.idx_ann_depth, 512, 64, 512)

    @property
    def bann_buckets(self) -> int:
        return self._derived(self.idx_bann_buckets, 32, 256, 8192)

    @property
    def bann_depth(self) -> int:
        return self._derived(self.idx_bann_depth, 1024, 32, 256)

    # Trace-membership family: depths are fixed small constants (a
    # trace's rows per family), buckets scale so buckets*depth covers
    # 4x the corresponding ring (see the clumping note below).
    # Trace-membership rows cluster: one trace puts ALL its rows in one
    # bucket, so per-lap bucket traffic is Poisson over ~2 traces — far
    # lumpier than the per-row families. 2x-ring coverage left 13-30%
    # of buckets wrapping faster than a ring lap (gates closed, measured
    # round 4); 4x coverage via doubled depths buys the variance
    # headroom while bucket count (and the write path's rank-sort
    # geometry) stays put.
    TRACE_SPAN_DEPTH = 64
    TRACE_ANN_DEPTH = 128
    TRACE_BANN_DEPTH = 64

    @property
    def trace_buckets(self) -> int:
        return _next_pow2_int(
            self.idx_trace_buckets
            or max(256, 4 * self.capacity // self.TRACE_SPAN_DEPTH)
        )

    # -- unified index layouts -------------------------------------------
    # ALL index families — the four candidate families AND the three
    # trace-membership sub-families — live in ONE flat [slots, 3] entry
    # arena (and one cursor array + one watermark array), written by ONE
    # combined rank-sort + scatter pass per ingest step: per-family
    # writes cost ~33 fused kernels each on a backend where per-kernel
    # overhead dominates (NOTES_r03.md §3), and the r5 ablation put the
    # two separate write blocks at 380 ms of the 586 ms step. Layout per
    # family: (bucket_base, slot_base, n_buckets, depth). The candidate
    # families are the arena PREFIX, so probe-side consumers of
    # ``cand_layout`` see unchanged bases; the trace families follow
    # (their rows spend the verify/ts columns on a trace-mix word and
    # the row ts — the arena-tripling cost NOTES_r05 §2 priced in).

    @property
    def idx_layout(self):
        B = self.trace_buckets
        return _pack_layout((
            (self.max_services, self.svc_depth),
            (self.name_buckets, self.name_depth),
            (self.ann_buckets, self.ann_depth),
            (self.bann_buckets, self.bann_depth),
            (B, self.TRACE_SPAN_DEPTH),
            (B, self.TRACE_ANN_DEPTH),
            (B, self.TRACE_BANN_DEPTH),
        ))

    CAND_SVC, CAND_NAME, CAND_ANN, CAND_BANN = range(4)
    N_CAND_FAMILIES = 4

    @property
    def cand_layout(self):
        """The candidate-family prefix of the unified arena, in the
        historical (rows, total_buckets, total_slots) shape — totals
        count the CANDIDATE families only (key-table sizing and probe
        padding depend on them, not on the trace suffix)."""
        rows, _, _ = self.idx_layout
        cand = rows[: self.N_CAND_FAMILIES]
        b_base, s_base, n_b, depth = cand[-1]
        return cand, b_base + n_b, s_base + n_b * depth

    @property
    def key_slots(self) -> int:
        return _next_pow2_int(
            self.idx_key_slots or 2 * self.cand_layout[1]
        )

    @property
    def trace_layout(self):
        """Trace-membership rows of the unified arena: bases are GLOBAL
        (into cand_idx/cand_pos/cand_wm); totals are the unified
        totals."""
        rows, total_b, total_s = self.idx_layout
        return rows[self.N_CAND_FAMILIES:], total_b, total_s

    TR_SPAN, TR_ANN, TR_BANN = range(3)

    # -- windowed analytics arena geometry --------------------------------

    @property
    def window_us(self) -> int:
        return int(self.window_seconds) * 1_000_000

    @property
    def window_enabled(self) -> bool:
        return self.window_seconds > 0 and self.window_buckets > 0

    @property
    def win_slots(self) -> int:
        """Allocated ring length: the configured ring when the arena
        is enabled, a 1-slot stub otherwise (a disabled arena keeps a
        well-formed state schema without paying [S, W, k] memory)."""
        return max(1, self.window_buckets) if self.window_enabled else 1

    @property
    def win_x_shift(self) -> int:
        """Quantization shift: fine histogram bucket index >> shift
        keeps x < 2^MAX_X_BITS, bounding the int64 Σx⁴ cell sums.
        Delegates to the ONE definition site (aggregate.windows, the
        mirror's twin) so device and mirror can never disagree."""
        from zipkin_tpu.aggregate.windows import win_x_shift

        return win_x_shift(self.quantile_buckets)


def _next_pow2_int(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _pack_layout(fams):
    """((n_buckets, depth), ...) → (per-family (bucket_base, slot_base,
    n_buckets, depth), total_buckets, total_slots) — the shared packing
    of the unified index arrays."""
    out = []
    b_base = s_base = 0
    for n_b, depth in fams:
        out.append((b_base, s_base, n_b, depth))
        b_base += n_b
        s_base += n_b * depth
    return tuple(out), b_base, s_base


# -- fast scatter primitives -------------------------------------------------
#
# Measured on the real chip (scripts/profile_scatter*.py, round 4): any
# 64-bit scatter (set/add/min/max) on this backend serializes at
# ~100-125 ns/row — a 917k-row index write costs ~100 ms — while 1-D
# int32 scatter-set with unique indices vectorizes at ~4.5 ns/row, and
# 2-D scatters are slow in EVERY dtype. Sorts and elementwise i64 math
# are cheap. So the hot ingest writes route through these helpers:
# bitcast i64 arrays to two i32 bit-planes and issue two strided 1-D
# unique scatters (10.4 ms vs 116 ms for 917k rows into 8M, measured).
# Callers must guarantee uniqueness among the surviving (ok) indices;
# dropped rows are remapped to DISTINCT out-of-bounds slots so the
# promise holds for the whole index vector.


def _p32(x):
    """i64[...] -> i32[..., 2] bit-planes (free bitcast)."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _p64(p):
    """i32[..., 2] bit-planes -> i64[...] (free bitcast)."""
    return jax.lax.bitcast_convert_type(p, jnp.int64)


def _oob_unique(idx, ok, n_rows: int):
    """Remap ~ok rows to distinct OOB indices (>= n_rows) so a dropping
    scatter may honestly claim unique_indices."""
    n = idx.shape[0]
    return jnp.where(
        ok, idx.astype(jnp.int32),
        jnp.int32(n_rows) + jnp.arange(n, dtype=jnp.int32),
    )


def _uset(arr, idx, vals, ok):
    """arr.at[idx[ok]].set(vals[ok]) for a 1-D arr of any dtype; indices
    must be unique among ok rows. i64 goes via two i32 plane scatters
    (the only fast 64-bit scatter on this backend); other dtypes scatter
    directly with the uniqueness promise."""
    safe = _oob_unique(idx, ok, arr.shape[0])
    if arr.dtype == jnp.int64:
        p = _p32(arr)
        v = _p32(jnp.asarray(vals, jnp.int64))
        lo = p[:, 0].at[safe].set(v[:, 0], mode="drop",
                                  unique_indices=True)
        hi = p[:, 1].at[safe].set(v[:, 1], mode="drop",
                                  unique_indices=True)
        return _p64(jnp.stack([lo, hi], axis=-1))
    return arr.at[safe].set(jnp.asarray(vals, arr.dtype), mode="drop",
                            unique_indices=True)


def _uset_p(arr2, idx, vals, ok):
    """``arr2`` is an [M, 2] i32 PLANE-PAIR array (the bit-planes of a
    logical i64 vector, kept in plane form so every load is an 8-byte
    i32 row gather instead of an i64 gather — i64 gathers are the
    dominant cost class on this backend, NOTES_r05 §2). Scatter-set of
    logical i64 ``vals`` at unique ``idx`` among ok rows."""
    v = _p32(jnp.asarray(vals, jnp.int64))
    safe = _oob_unique(idx, ok, arr2.shape[0])
    lo = arr2[:, 0].at[safe].set(v[:, 0], mode="drop",
                                 unique_indices=True)
    hi = arr2[:, 1].at[safe].set(v[:, 1], mode="drop",
                                 unique_indices=True)
    return jnp.stack([lo, hi], axis=-1)


def _uset_cols64(arr, idx, vals, ok):
    """Row scatter ``arr.at[idx[ok]].set(vals[ok])`` for an [M, C] i64
    array via 2C strided 1-D i32 plane scatters (2-D scatters are slow
    in every dtype on this backend; 1-D unique i32 is ~4.5 ns/row)."""
    m, ncols = arr.shape
    p = _p32(arr)                          # [M, C, 2]
    v = _p32(jnp.asarray(vals, jnp.int64))  # [N, C, 2]
    safe = _oob_unique(idx, ok, m)
    planes = []
    for cdx in range(ncols):
        for pl in range(2):
            planes.append(p[:, cdx, pl].at[safe].set(
                v[:, cdx, pl], mode="drop", unique_indices=True))
    return _p64(jnp.stack(planes, axis=-1).reshape(m, ncols, 2))


# Per-key record table: i32 fingerprints (claims ride the vectorized
# duplicate-index i32 scatter-min; see _index_write). 0x7FFFFFFF is the
# empty sentinel — it loses every min-war and _fp31 never produces it.
# INT32_MIN is the restore tombstone: unclaimable (wins every min-war)
# and outside _fp31's range, so it matches no lookup (poison_ann_trust,
# checkpoint rev<9 migration).
_FP_EMPTY = jnp.int32(0x7FFFFFFF)
_FP_TOMB = jnp.int32(-0x80000000)
# Claim failure scales ~load^PROBES (slots only fill, so a key that
# fails all probes fails forever and its queries lose the per-key fast
# path). 3 probes at the bench's 0.25 load keeps misses under ~2% for
# one extra i32 gather+war round per ingest step.
_KEY_PROBES = 3


def _fp31(k48):
    """48-bit key -> 31-bit non-negative fingerprint (never _FP_EMPTY).
    Takes bits 17..47; _tab_slots consumes the low log2(T) bits for the
    probe sequence, so at T = 2^23 slots the two overlap by ~6 bits and
    same-slot keys already agree on that much of the fingerprint: the
    same-slot collision odds are ~2^-(31 - max(0, log2(T) - 17)), about
    2^-25 at bench geometry — NOT the full 2^-31. A collision only
    makes two keys share a record and merge watermarks (conservative:
    extra scan fallbacks, never a wrong answer), so the margin is spent
    on fallback rate, not correctness. (Kept as a plain shift rather
    than a mixed hash: the fingerprints live in checkpoints, and
    changing the function would tombstone every restored key table.)"""
    f = (k48 >> jnp.uint64(17)).astype(jnp.int32) & jnp.int32(0x7FFFFFFF)
    return jnp.minimum(f, jnp.int32(0x7FFFFFFE))


def _seg_reduce_sorted(segid, vals, op, identity):
    """Running segmented reduce over rows SORTED by segid (log-doubling:
    ~20 shifted elementwise steps, all vectorized — i64 elementwise is
    fine on this backend, only i64 SCATTER is serialized). Returns the
    running reduction; row i holds op over its segment's rows <= i, so
    each segment's LAST row holds the full segment reduction."""
    n = vals.shape[0]
    d = 1
    while d < n:
        shifted = jnp.concatenate(
            [jnp.full(d, identity, vals.dtype), vals[:-d]])
        same = jnp.concatenate(
            [jnp.zeros(d, bool), segid[d:] == segid[:-d]])
        vals = jnp.where(same, op(vals, shifted), vals)
        d *= 2
    return vals


def _unsort_i32(order, svals, fill=0):
    """Scatter sorted-space i32 values back to original row order (the
    permutation is unique by construction)."""
    n = order.shape[0]
    return jnp.full(n, fill, jnp.int32).at[order].set(
        svals, unique_indices=True)


def _slot_war(slot, packed, active, n_slots: int):
    """Explicit arbitration replacing a read-back scatter-min war: among
    ``active`` rows contending for the same slot, the numerically
    smallest ``packed`` wins — bitwise the same outcome as the old
    ``.at[slot].min(packed)`` + re-read, but built from sorts and
    elementwise ops (i64 scatters serialize at ~100 ns/row on this
    backend; sorts are nearly free — scripts/profile_scatter*.py).

    Returns (seg_min, write_row), both in ORIGINAL row order:
    ``seg_min`` is the minimum packed offered at the row's slot this
    round (I64_MAX for inactive rows), ``write_row`` marks exactly one
    row per contended slot (safe for a unique scatter)."""
    n = packed.shape[0]
    s = jnp.where(active, slot.astype(jnp.int32), jnp.int32(n_slots))
    # Lexicographic (slot, packed) via two stable argsorts.
    ord1 = jnp.argsort(packed, stable=True)
    ord2 = jnp.argsort(s[ord1], stable=True)
    order = ord1[ord2]
    ss = s[order]
    sp = jnp.where(active[order], packed[order], I64_MAX)
    # Sorted ascending by packed within each slot run, so the running
    # segmented min broadcasts the winner's word to every row of a run.
    seg_min_sorted = _seg_reduce_sorted(ss, sp, jnp.minimum, I64_MAX)
    first = jnp.concatenate([jnp.ones(1, bool), ss[1:] != ss[:-1]])
    write_sorted = first & active[order]
    inv = jnp.argsort(order)  # unsort permutation
    seg_min = seg_min_sorted[inv]
    write_row = _unsort_i32(order, write_sorted.astype(jnp.int32)) > 0
    return seg_min, write_row


_LO_FLIP = jnp.int32(-0x80000000)  # sign-flip: u32 order as i32 order

# Coarse gid-watermark granularity divisor: overstatement is bounded by
# capacity / 2^_WM_COARSE_FRAC_BITS (see _coarse_gid32 and the
# wm_shift derivation in ingest_step).
_WM_COARSE_FRAC_BITS = 8


def _war_max64(arr, idx, vals, ok):
    """``arr.at[idx[ok]].max(vals[ok])`` for an i64 WATERMARK array —
    EXACT — via a two-phase i32 plane war (duplicate indices allowed;
    i32 scatter-max vectorizes at ~9 ns/row on this backend while i64
    serializes at ~100 ns/row):

    1. hi planes war (signed i32 compare == i64 order on high words);
    2. one i32 gather reads each row's SETTLED hi;
    3. lo planes war, entered only by rows whose hi equals the settled
       hi, against a base that keeps the slot's old lo only where its
       hi survived (both conditions computable elementwise).

    The lo plane is sign-flipped so unsigned 32-bit order matches i32
    compare; I64_MIN's planes are (INT32_MIN, INT32_MIN) under the
    flip, losing every war — the empty sentinel round-trips bit-exact.
    Earlier conservative variants (independent plane maxes) overstated
    by up to a plane boundary and systematically closed the bucket
    gates in the round-4 bench — a watermark's VALUE is the product."""
    neg = jnp.int32(-0x80000000)
    p = _p32(arr)
    lo_arr = p[:, 0] ^ _LO_FLIP
    hi_arr = p[:, 1]
    v = _p32(jnp.asarray(vals, jnp.int64))
    safe = jnp.where(ok, idx.astype(jnp.int32), arr.shape[0])
    hi_off = jnp.where(ok, v[:, 1], neg)
    hi_after = hi_arr.at[safe].max(hi_off, mode="drop")
    settled = hi_after[jnp.where(ok, idx.astype(jnp.int32), 0)]
    lo_base = jnp.where(hi_after == hi_arr, lo_arr, neg)
    lo_off = jnp.where(ok & (v[:, 1] == settled),
                       v[:, 0] ^ _LO_FLIP, neg)
    lo_after = lo_base.at[safe].max(lo_off, mode="drop")
    return _p64(jnp.stack([lo_after ^ _LO_FLIP, hi_after], axis=-1))


def _war_min64(arr, idx, vals, ok):
    """Exact ``arr.at[idx[ok]].min(vals[ok])`` — bitwise NOT reverses
    i64 order without overflow, so a min-war is a max-war in the
    complemented domain (an I64_MAX empty sentinel complements to
    _war_max64's I64_MIN one)."""
    return ~_war_max64(~arr, idx, ~jnp.asarray(vals, jnp.int64), ok)


def _coarse_gid32(gids, ok, shift: int):
    """Per-row i32 contribution of a GID to the SHARED coarse watermark
    scatter (_index_write's unified war — one vectorized i32
    duplicate-index scatter-max instead of _war_max64's two plane wars
    + settled gather per family): ceil to the next 2^shift boundary, so
    the stored watermark OVERSTATES the true max displaced gid by
    < 2^shift — against trust margins of >= ring capacity (displaced
    entries are ring-laps old in steady state), callers pick shift so
    the overstatement is a sub-percent slice of the margin. Overstating
    a watermark costs scan fallbacks, never a wrong answer. ~ok rows
    contribute 0 (the zeroed scratch's no-op), so untouched slots keep
    their exact i64 value on fold-back — empty I64_MIN sentinels, and
    underfull-bucket trust before the first wrap, survive bit-exact.
    gids are non-negative; the coarse domain holds to 2^(31 + shift)
    spans of lifetime (2^45+ at bench shapes), and gids past it
    SATURATE to the domain ceiling — the watermark pins high and the
    gates stay conservatively closed, never silently re-open (an
    unclamped int32 cast would wrap negative and freeze the watermark
    instead). Callers must route shift == 0 through the exact
    _war_max64 path instead: the un-shifted domain saturates at ~2.1B
    lifetime spans, an unrecoverable cliff for long-lived small stores
    (ADVICE r5) — _index_write's exact_gid_wars branch does."""
    v = jnp.minimum(
        (jnp.asarray(gids, jnp.int64) >> shift) + 1,
        jnp.int64(0x7FFFFFFF),
    ).astype(jnp.int32)
    return jnp.where(ok & (jnp.asarray(gids, jnp.int64) >= 0), v, 0)


# Coarse-ts watermark granularity: candidate-family overwrite
# watermarks war in 2^_WM_TS_SHIFT-µs units (~1.05 s). The trust gate
# compares a query's limit-th candidate ts against the watermark;
# displaced entries are ring-laps (minutes+) older than any trusted
# candidate in steady state, so a <= 1.05 s ceil overstatement costs at
# most a rare extra scan fallback, never a wrong answer. Contributions
# past the coarse ceiling (ts >= 2^(31+shift) µs, ~year 2041) take the
# EXACT plane-war fallback below instead of saturating — saturation
# would close the bucket forever.
_WM_TS_SHIFT = 20


def _coarse_ts32(ts, ok, shift: int):
    """Per-row i32 contribution of a displaced TS to the shared coarse
    watermark scatter: ceil in 2^shift-µs units. Negative ts (the
    I64_MIN / NO_TS sentinels) contribute nothing — a displaced entry
    without a timestamp can never match a query (the kernels require
    ts >= 0), so omitting it cannot un-protect an answer. Rows at or
    past the coarse ceiling ALSO contribute nothing here; the caller
    MUST route exactly those rows (the overflow mask) through the
    exact war (_index_write's cond). The ceiling is
    (2^31 - 1) << shift, NOT 2^(31+shift): a ts in the last coarse
    unit below 2^(31+shift) would ceil to exactly 2^31, whose i32 cast
    wraps NEGATIVE — losing the scatter-max and silently UNDERSTATING
    the watermark, the one failure direction the gates can't absorb."""
    t = jnp.asarray(ts, jnp.int64)
    lim = jnp.int64((1 << 31) - 1) << shift
    in_dom = ok & (t >= 0) & (t < lim)
    v = ((t >> shift) + 1).astype(jnp.int32)
    return jnp.where(in_dom, v, 0), ok & (t >= lim)


def _ring(n, dtype, fill=0):
    return jnp.full((n,), fill, dtype)


@jax.tree_util.register_pytree_node_class
@dataclass
class StoreState:
    """The carried state pytree. All arrays; config is static aux."""

    config: StoreConfig

    # -- span ring ------------------------------------------------------
    trace_id: jnp.ndarray
    span_id: jnp.ndarray
    parent_id: jnp.ndarray
    name_id: jnp.ndarray  # original-case span-name dictionary id
    name_lc_id: jnp.ndarray  # lowercased id for matching; -1 = empty name
    service_id: jnp.ndarray  # owning service (server-preferred); -1 none
    ts_cs: jnp.ndarray
    ts_cr: jnp.ndarray
    ts_sr: jnp.ndarray
    ts_ss: jnp.ndarray
    ts_first: jnp.ndarray
    ts_last: jnp.ndarray
    duration: jnp.ndarray
    flags: jnp.ndarray
    indexable: jnp.ndarray  # bool: should_index() computed on host
    row_gid: jnp.ndarray  # global row id occupying each slot; -1 empty
    write_pos: jnp.ndarray  # scalar i64: total spans ever written

    # -- annotation ring ------------------------------------------------
    ann_gid: jnp.ndarray  # global span row the annotation belongs to; -1
    ann_ts: jnp.ndarray
    ann_value_id: jnp.ndarray
    ann_service_id: jnp.ndarray
    ann_endpoint_id: jnp.ndarray
    ann_write_pos: jnp.ndarray

    # -- binary-annotation ring -----------------------------------------
    bann_gid: jnp.ndarray
    bann_key_id: jnp.ndarray
    bann_value_id: jnp.ndarray
    bann_type: jnp.ndarray
    bann_service_id: jnp.ndarray
    bann_endpoint_id: jnp.ndarray
    bann_write_pos: jnp.ndarray

    # -- streaming aggregate state (never evicted) ----------------------
    # Dependency links resolve at INGEST time through a streaming hash
    # join: every span is inserted into ``span_tab`` (open addressing,
    # key = mix48(trace_id, span_id), payload = service); every child
    # batch row probes the table for its parent and, when found, its
    # duration folds into the accumulating window bank ``dep_window``
    # via the exact segmented-Moments reduction. Children whose parent
    # hasn't arrived yet wait in the pending ring and are re-probed by
    # ``dep_sweep``. This replaces the r2 eviction-watermark ring join,
    # whose O(ring) sort cost every read and archive pass paid —
    # measured 8.8s per get_dependencies at a 2^22 ring (NOTES_r03.md).
    # ``dep_close_bucket`` rotates the window into a time-tagged slot of
    # ``dep_banks`` (the hourly-Dependencies-rows role,
    # Dependencies.scala:59-67); displaced slots merge into the all-time
    # tail ``dep_moments``. All parts are disjoint:
    # total = combine(tail, banks, window).
    dep_moments: jnp.ndarray  # [S*S, 5] f32 — tail (pre-ring) link moments
    dep_banks: jnp.ndarray  # [K, S*S, 5] f32 — time-tagged bucket ring
    dep_bank_ts: jnp.ndarray  # [K, 2] i64 — (min first_ts, max last_ts)
    dep_overflow_ts: jnp.ndarray  # [2] i64 — ts range of the tail bank
    dep_bank_seq: jnp.ndarray  # scalar i64 — next bucket slot
    dep_window: jnp.ndarray  # [S*S, 5] f32 — accumulating current bucket
    dep_window_ts: jnp.ndarray  # [2] i64 — ts range folded into window
    # Dep-join hash table, stored as the [H, 2] i32 BIT-PLANES of the
    # logical packed word (mix48 << 16)|(svc+1 << 1)|1 (_TAB_EMPTY when
    # free): every probe round's load is then an 8-byte i32 row gather
    # instead of an i64 gather — the dominant cost class on this
    # backend (NOTES_r05 §2) — and every store a pair of vectorized
    # i32 plane scatters. Bitcast-identical to the old i64 column
    # (checkpoint revision 11 migrates by view, losslessly).
    span_tab: jnp.ndarray  # [H, 2] i32 — planes of the packed word
    pend_key: jnp.ndarray  # [Q] i64 — (mix48(tid,parent) << 16)|(csvc+1<<1)|1
    pend_dur: jnp.ndarray  # [Q] i64 — pending child duration
    pend_tsf: jnp.ndarray  # [Q] i64 — pending child first_ts
    pend_tsl: jnp.ndarray  # [Q] i64 — pending child last_ts
    pend_pos: jnp.ndarray  # scalar i64 — pending ring cursor

    # -- index column families -------------------------------------------
    # ALL seven index families — the four candidate families (service /
    # service+name / service+ann-value / service+binary) AND the three
    # trace-membership sub-families — share ONE flat [total_slots, 3]
    # i64 entry arena of (gid, verify, ts) rows, one [total_buckets]
    # i64 cursor array, and one watermark array, laid out per
    # StoreConfig.idx_layout (candidate families are the prefix; the
    # probe-side ``cand_layout`` view is unchanged). One combined
    # rank-sort + scatter pass serves every family (_index_write). A
    # bucket's FIFO ring never wrapping (cursor <= depth) means it
    # holds EVERY entry ever written for its key → an index read is
    # complete; a wrapped CANDIDATE bucket is still exact when the
    # query's last candidate ranks >= its ts watermark, and a wrapped
    # TRACE bucket when everything it displaced is already evicted
    # (gid watermark < write_pos - capacity) — the exactness gate for
    # whole-trace fetch and durations. The watermark array carries ts
    # values on the candidate prefix and gids on the trace suffix;
    # every query slices by family, never across the boundary.
    cand_idx: jnp.ndarray
    cand_pos: jnp.ndarray
    cand_wm: jnp.ndarray
    # Middle-host trust: annotation/binary index entries are written
    # under a span's (min, max) annotation-host pair, so a span whose
    # annotations span 3+ DISTINCT host services is never indexed under
    # its middle hosts. ann_poison[s] is the max span gid that had
    # service s as a middle host; annotation-family fast paths for s are
    # trusted only once that span is evicted (gid < write_pos -
    # capacity) — the same displaced-gid gate as tr_wm, self-healing as
    # the ring turns over.
    ann_poison: jnp.ndarray  # [S] i64, I64_MIN = never poisoned
    # Per-key record table (the device rendition of Cassandra's per-key
    # index rows, cassandra-schema.txt:4-8): open addressing keyed by a
    # 31-bit FINGERPRINT of the candidate families' verify word (i32 —
    # duplicate-index i32 scatter-min vectorizes on this backend where
    # the exact i64 word war serialized at ~100 ns/row). key_wm[slot] is
    # the max span gid attributed to an entry ever DISPLACED from a
    # recorded key's bucket window; a query whose key record shows
    # key_wm < write_pos - capacity holds every RESIDENT entry of that
    # key in the bucket window — complete even when bucket-mates wrapped
    # the bucket (the sparse-key aliasing fallback of NOTES_r03 §4).
    # Claim-on-empty ONLY, never stolen. Distinct keys may share a
    # (slot, fingerprint) — they then share a record and their
    # watermarks merge, which only OVERSTATES (extra fallbacks, never a
    # wrong answer); an absent record (congestion) degrades to the
    # per-bucket gates the same way.
    key_tab: jnp.ndarray  # [T] i32 — fp31(key48); _FP_EMPTY empty
    key_wm: jnp.ndarray  # [T] i64 — max displaced gid; I64_MIN none
    svc_hist: jnp.ndarray  # [S, B] f32 — per-service duration log-histogram
    svc_span_counts: jnp.ndarray  # [S] f32
    ann_svc_counts: jnp.ndarray  # [S] f32 — services seen on any annotation
    name_presence: jnp.ndarray  # [S, N] f32 — (ann-service, span-name)
    ann_value_counts: jnp.ndarray  # [S, A] f32 — top annotations per service
    bann_key_counts: jnp.ndarray  # [S, K] f32 — top binary keys per service
    hll_traces: jnp.ndarray  # [2^p] i32 — distinct trace ids
    cms_trace_spans: jnp.ndarray  # [depth, width] i32 — spans per trace
    ts_min: jnp.ndarray  # scalar i64 — earliest ts seen (ingest wall)
    ts_max: jnp.ndarray  # scalar i64
    # Windowed Moments-sketch arena (aggregate/windows.py): dense
    # (service × ring-indexed time bucket) integer cells updated inside
    # the fused step. win_epoch[w] stamps the ABSOLUTE time bucket a
    # slot currently holds (-1 = never used); a newer bucket landing
    # on the slot zeroes every service's cell row first (stale cells
    # self-clear, no sweep). All fields are integers accumulated by
    # scatter-add/-max so the host mirror twins match BITWISE.
    win_epoch: jnp.ndarray  # [W] i64 — absolute bucket per slot; -1 empty
    win_counts: jnp.ndarray  # [S, W, 3] i32 — (total, err, n_duration)
    win_sums: jnp.ndarray  # [S, W, 4] i64 — Σx, Σx², Σx³, Σx⁴
    win_mm: jnp.ndarray  # [S, W, 2] i32 — (max(-x), max(x)); I32_MIN empty
    counters: Dict[str, jnp.ndarray] = field(default_factory=dict)

    _FIELDS = (
        "trace_id", "span_id", "parent_id", "name_id", "name_lc_id",
        "service_id", "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first",
        "ts_last", "duration", "flags", "indexable", "row_gid", "write_pos",
        "ann_gid", "ann_ts", "ann_value_id", "ann_service_id",
        "ann_endpoint_id", "ann_write_pos",
        "bann_gid", "bann_key_id", "bann_value_id", "bann_type",
        "bann_service_id", "bann_endpoint_id", "bann_write_pos",
        "dep_moments", "dep_banks", "dep_bank_ts", "dep_overflow_ts",
        "dep_bank_seq", "dep_window", "dep_window_ts", "span_tab",
        "pend_key", "pend_dur", "pend_tsf", "pend_tsl", "pend_pos",
        "cand_idx", "cand_pos", "cand_wm",
        "ann_poison", "key_tab", "key_wm",
        "svc_hist", "svc_span_counts", "ann_svc_counts",
        "name_presence", "ann_value_counts", "bann_key_counts",
        "hll_traces", "cms_trace_spans", "ts_min", "ts_max",
        "win_epoch", "win_counts", "win_sums", "win_mm", "counters",
    )

    def tree_flatten(self):
        return tuple(getattr(self, f) for f in self._FIELDS), self.config

    @classmethod
    def tree_unflatten(cls, config, children):
        return cls(config, *children)

    def replace(self, **kw) -> "StoreState":
        return replace(self, **kw)


def init_state(config: StoreConfig = StoreConfig()) -> StoreState:
    c = config
    S = c.max_services
    return StoreState(
        config=c,
        trace_id=_ring(c.capacity, jnp.int64),
        span_id=_ring(c.capacity, jnp.int64),
        parent_id=_ring(c.capacity, jnp.int64),
        name_id=_ring(c.capacity, jnp.int32),
        name_lc_id=_ring(c.capacity, jnp.int32, -1),
        service_id=_ring(c.capacity, jnp.int32, -1),
        ts_cs=_ring(c.capacity, jnp.int64, NO_TS),
        ts_cr=_ring(c.capacity, jnp.int64, NO_TS),
        ts_sr=_ring(c.capacity, jnp.int64, NO_TS),
        ts_ss=_ring(c.capacity, jnp.int64, NO_TS),
        ts_first=_ring(c.capacity, jnp.int64, NO_TS),
        ts_last=_ring(c.capacity, jnp.int64, NO_TS),
        duration=_ring(c.capacity, jnp.int64, NO_TS),
        flags=_ring(c.capacity, jnp.int32),
        indexable=_ring(c.capacity, jnp.bool_, False),
        row_gid=_ring(c.capacity, jnp.int64, -1),
        write_pos=jnp.int64(0),
        ann_gid=_ring(c.ann_capacity, jnp.int64, -1),
        ann_ts=_ring(c.ann_capacity, jnp.int64, NO_TS),
        ann_value_id=_ring(c.ann_capacity, jnp.int32, -1),
        ann_service_id=_ring(c.ann_capacity, jnp.int32, -1),
        ann_endpoint_id=_ring(c.ann_capacity, jnp.int32, -1),
        ann_write_pos=jnp.int64(0),
        bann_gid=_ring(c.bann_capacity, jnp.int64, -1),
        bann_key_id=_ring(c.bann_capacity, jnp.int32, -1),
        bann_value_id=_ring(c.bann_capacity, jnp.int32, -1),
        bann_type=_ring(c.bann_capacity, jnp.int32),
        bann_service_id=_ring(c.bann_capacity, jnp.int32, -1),
        bann_endpoint_id=_ring(c.bann_capacity, jnp.int32, -1),
        bann_write_pos=jnp.int64(0),
        # Counting state is int32: float32 scatter-adds of 1.0 silently
        # freeze at 2^24 (~16.7M), far below the 1B-span target. int32 is
        # exact to 2.1e9 and psum-able. Only the Moments bank stays f32
        # (its combine adds batch-sized increments, not +1s).
        dep_moments=jnp.zeros((S * S, M.N_FIELDS), jnp.float32),
        dep_banks=jnp.zeros((c.dep_buckets, S * S, M.N_FIELDS), jnp.float32),
        dep_bank_ts=jnp.tile(
            jnp.array([[I64_MAX, I64_MIN]], jnp.int64), (c.dep_buckets, 1)
        ),
        dep_overflow_ts=jnp.array([I64_MAX, I64_MIN], jnp.int64),
        dep_bank_seq=jnp.int64(0),
        dep_window=jnp.zeros((S * S, M.N_FIELDS), jnp.float32),
        dep_window_ts=jnp.array([I64_MAX, I64_MIN], jnp.int64),
        span_tab=_p32(jnp.full(c.tab_slots, _TAB_EMPTY, jnp.int64)),
        pend_key=jnp.zeros(c.pending_slots, jnp.int64),
        pend_dur=jnp.zeros(c.pending_slots, jnp.int64),
        pend_tsf=jnp.zeros(c.pending_slots, jnp.int64),
        pend_tsl=jnp.zeros(c.pending_slots, jnp.int64),
        pend_pos=jnp.int64(0),
        # LOAD-BEARING init values: _index_write derives
        # slot occupancy from cursors (pos + rank >= depth), which
        # over-claims when an in-batch bucket overflow (cnt > depth)
        # skipped slots this cursor lap — such "occupied" slots still
        # hold these INIT entries, and the displacement path feeds them
        # into the watermark wars and the fp-key lookup. That is
        # harmless precisely because gid/ts = -1 / I64_MIN lose every
        # max-war and verify = -1 hashes to a fingerprint that matches
        # no claimed key. Changing these fills requires re-deriving that
        # argument (or adding an explicit old-entry validity check).
        cand_idx=jnp.full((c.idx_layout[2], 3), -1, jnp.int64),
        cand_pos=jnp.zeros(c.idx_layout[1], jnp.int64),
        cand_wm=jnp.full(c.idx_layout[1], I64_MIN, jnp.int64),
        ann_poison=jnp.full(S, I64_MIN, jnp.int64),
        key_tab=jnp.full(c.key_slots, _FP_EMPTY, jnp.int32),
        key_wm=jnp.full(c.key_slots, I64_MIN, jnp.int64),
        svc_hist=Q.init(
            shape=(S,), n_buckets=c.quantile_buckets, alpha=c.quantile_alpha,
            dtype=jnp.int32,
        ).counts,
        svc_span_counts=jnp.zeros(S, jnp.int32),
        ann_svc_counts=jnp.zeros(S, jnp.int32),
        name_presence=jnp.zeros((S, c.max_span_names), jnp.int32),
        ann_value_counts=jnp.zeros((S, c.max_annotation_values), jnp.int32),
        bann_key_counts=jnp.zeros((S, c.max_binary_keys), jnp.int32),
        hll_traces=hll.init(c.hll_p).registers,
        cms_trace_spans=cms.init(c.cms_depth, c.cms_width).counts,
        ts_min=jnp.int64(I64_MAX),
        ts_max=jnp.int64(I64_MIN),
        # Windowed Moments-sketch arena: integer cells (see the field
        # comments above). min/max planes start at I32_MIN (the
        # scatter-max empty sentinel — a zero fill would pin min_x at
        # 0 because min rides max(-x)); consumers ignore them while
        # the cell's duration count is 0.
        win_epoch=_ring(c.win_slots, jnp.int64, -1),
        win_counts=jnp.zeros((S, c.win_slots, 3), jnp.int32),
        win_sums=jnp.zeros((S, c.win_slots, 4), jnp.int64),
        win_mm=jnp.full((S, c.win_slots, 2), I32_MIN, jnp.int32),
        counters={
            "spans_seen": jnp.int64(0),
            "anns_seen": jnp.int64(0),
            "banns_seen": jnp.int64(0),
            "batches": jnp.int64(0),
            # Keyed index rows whose per-key claim exhausted its probes
            # (table congestion). While 0, an absent key record PROVES
            # the key was never indexed — the negative-lookup gate.
            "key_claim_drops": jnp.int64(0),
            # Pending-sweep count: the only mutation that moves no
            # write cursor, counted so checkpoint._state_generation
            # can detect it (staged-leaf reuse safety).
            "sweeps": jnp.int64(0),
        },
    )


def _scatter_add(counts, idx, weights, use_pallas: bool):
    """``counts.reshape(-1)[idx] += weights`` with idx < 0 dropped —
    the one primitive behind every ingest counter/presence/sketch
    update (the reference's 5-index-writes-per-span hot loop,
    processor/IndexService.scala:30-38). Dispatches to the
    VMEM-resident pallas kernel when enabled and lane-aligned."""
    from zipkin_tpu.ops import pallas_kernels as PK

    if use_pallas and counts.size % PK.LANES == 0:
        return PK.histogram_update(counts, idx, weights)
    return PK.scatter_histogram_xla(counts, idx, weights)


def svc_histogram(state: StoreState) -> Q.LogHistogram:
    c = state.config
    gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
    return Q.LogHistogram(state.svc_hist, gamma, 1.0)


@partial(jax.jit, static_argnums=(0,))
def _svc_scan_catalog_impl(dims, service_id_col, duration, row_gid,
                           ann_gid, ann_service_id, ann_value_id,
                           name_id_col, name_lc_col, indexable,
                           bann_gid, bann_service_id, bann_key_id, svc):
    cap, n_names, n_q, n_av, n_bk, gamma = dims

    def hadd(n, idx, ok):
        # -1-masked rows must go through the scratch-slot remap
        # (_scatter_add): a raw ``.at[-1].add`` WRAPS to the last
        # bucket (NumPy negative indexing), silently inflating it.
        ones = jnp.ones(idx.shape, jnp.int32)
        return _scatter_add(jnp.zeros(n, jnp.int32),
                            jnp.where(ok, idx, -1), ones, False)

    # Span-ring rows of this service: duration log-histogram.
    m_sp = (row_gid >= 0) & (service_id_col == svc) & (duration >= 0)
    hist = Q.LogHistogram(jnp.zeros(n_q, jnp.int32), gamma, 1.0)
    bidx = Q.bucket_index(hist, duration.astype(jnp.float32))
    dur_row = hadd(n_q, bidx, m_sp)
    # Annotation-ring rows hosted by this service.
    m_a = (ann_gid >= 0) & (ann_service_id == svc)
    slot, live = _span_slot(ann_gid, row_gid, cap)
    nm = name_id_col[slot]
    nm_ok = (
        m_a & live & indexable[slot] & (name_lc_col[slot] >= 0)
        & (nm >= 0) & (nm < n_names)
    )
    name_row = hadd(n_names, nm, nm_ok)
    av_ok = (
        m_a & (ann_value_id >= FIRST_USER_ANNOTATION_ID)
        & (ann_value_id < n_av)
    )
    ann_row = hadd(n_av, ann_value_id, av_ok)
    # Binary-annotation-ring rows hosted by this service.
    bk_ok = (
        (bann_gid >= 0) & (bann_service_id == svc)
        & (bann_key_id >= 0) & (bann_key_id < n_bk)
    )
    bkey_row = hadd(n_bk, bann_key_id, bk_ok)
    return name_row, dur_row, ann_row, bkey_row


@partial(jax.jit, static_argnums=(0, 1))
def _overflow_presence_impl(base, n_over, ann_gid, ann_service_id,
                            bann_gid, bann_service_id):
    pres = jnp.zeros(n_over, jnp.int32)
    for gid, svc in ((ann_gid, ann_service_id),
                     (bann_gid, bann_service_id)):
        ok = (gid >= 0) & (svc >= base)
        pres = _scatter_add(
            pres, jnp.where(ok, svc - base, -1),
            jnp.ones(svc.shape, jnp.int32), False,
        )
    return pres > 0


def overflow_service_presence(state: StoreState, n_over: int):
    """Which dictionary-overflow service ids (>= max_services) are
    present as annotation/binary-annotation hosts in the RINGS — the
    service-listing criterion for services no presence array can
    represent. Ring-resident (window) semantics, vs the lifetime
    ann_svc_counts of indexed services: the only data that exists for
    an overflow service lives in the raw ring columns. ``n_over`` is a
    static pad (next pow2 of the dictionary overflow count) so dict
    growth doesn't recompile per service."""
    return _overflow_presence_impl(
        state.config.max_services, n_over,
        state.ann_gid, state.ann_service_id,
        state.bann_gid, state.bann_service_id,
    )


def svc_scan_catalog(state: StoreState, svc_id: int):
    """Ring-scan catalog aggregates for ONE service id — the query path
    for dictionary-overflow services (id >= max_services), which no
    [max_services]-sized catalog array (name_presence, svc_hist,
    ann_value_counts, bann_key_counts) can represent: a clamped gather
    there would silently serve service max_services-1's data under the
    wrong name. Returns (span-name presence row, duration log-histogram
    row, annotation-value counts row, binary-key counts row), computed
    from ring-RESIDENT rows only — the indexed counterparts are
    lifetime counters, so the overflow path is window-bounded: slower
    and shorter-memoried, never wrong-service. All four aggregates ride
    one launch (i32 1-D scatter-adds, the vectorized class on this
    backend). Reference role: the per-service catalogs of
    CassieSpanStore.scala (ServiceNames/SpanNames column families)."""
    c = state.config
    gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
    return _svc_scan_catalog_impl(
        (c.capacity, c.max_span_names, c.quantile_buckets,
         c.max_annotation_values, c.max_binary_keys, gamma),
        state.service_id, state.duration, state.row_gid,
        state.ann_gid, state.ann_service_id, state.ann_value_id,
        state.name_id, state.name_lc_id, state.indexable,
        state.bann_gid, state.bann_service_id, state.bann_key_id,
        jnp.int32(svc_id),
    )


# ---------------------------------------------------------------------------
# Device batch (padded, fixed shape)
# ---------------------------------------------------------------------------


class DeviceBatch(NamedTuple):
    """A SpanBatch padded to static shape + host-computed index columns."""

    trace_id: jnp.ndarray
    span_id: jnp.ndarray
    parent_id: jnp.ndarray
    name_id: jnp.ndarray
    name_lc_id: jnp.ndarray
    service_id: jnp.ndarray
    ts_cs: jnp.ndarray
    ts_cr: jnp.ndarray
    ts_sr: jnp.ndarray
    ts_ss: jnp.ndarray
    ts_first: jnp.ndarray
    ts_last: jnp.ndarray
    duration: jnp.ndarray
    flags: jnp.ndarray
    has_parent: jnp.ndarray
    indexable: jnp.ndarray
    n_spans: jnp.ndarray

    ann_span_idx: jnp.ndarray
    ann_ts: jnp.ndarray
    ann_value_id: jnp.ndarray
    ann_service_id: jnp.ndarray
    ann_endpoint_id: jnp.ndarray
    n_anns: jnp.ndarray

    bann_span_idx: jnp.ndarray
    bann_key_id: jnp.ndarray
    bann_value_id: jnp.ndarray
    bann_type: jnp.ndarray
    bann_service_id: jnp.ndarray
    bann_endpoint_id: jnp.ndarray
    n_banns: jnp.ndarray

    # Per-span error flag ("error" annotation value / binary key),
    # computed on the HOST in stage 1 (aggregate.windows
    # span_error_flags — the dictionary lookup the device can't do) and
    # consumed by the windowed-arena error counts. Defaults to all
    # False for direct-device callers that don't track errors.
    error_flag: jnp.ndarray

    # Paged layout (r19) stage-1 page claims, planned on the HOST by
    # store/paged.PagePlanner (deterministic from the unit stream, so
    # WAL replay re-derives bitwise-identical claims). Ring batches
    # carry shape-(1,) placeholders; the ring lowering never touches
    # them (static branch → DCE, same discipline as error_flag before
    # the window arena existed).
    span_slot: jnp.ndarray      # i32 [P]  destination slot per span
    span_gid: jnp.ndarray       # i64 [P]  epoch-encoded gid per span
    reclaim_page: jnp.ndarray   # i32 [RC] page ids invalidated first (-1 pad)


def _pad(a: np.ndarray, n: int, fill=0, dtype=None) -> np.ndarray:
    dtype = dtype or a.dtype
    out = np.full(n, fill, dtype)
    out[: len(a)] = a
    return out


def make_device_batch(
    batch: SpanBatch,
    name_lc_id: np.ndarray,
    indexable: np.ndarray,
    pad_spans: int,
    pad_anns: int,
    pad_banns: int,
    error_flag: np.ndarray = None,
    span_slot: np.ndarray = None,
    span_gid: np.ndarray = None,
    reclaim_pages: np.ndarray = None,
    pad_reclaims: int = 1,
) -> DeviceBatch:
    """Host: pad a SpanBatch (+ index columns) to static shapes.

    ``name_lc_id`` is the lowercased span-name dictionary id (-1 for empty
    names); ``indexable`` is store.base.should_index computed per span;
    ``error_flag`` is the per-span error bit (windows.span_error_flags),
    all-False when the caller doesn't track errors.
    """
    from zipkin_tpu.columnar.schema import FLAG_HAS_PARENT

    if batch.n_spans > pad_spans or batch.n_annotations > pad_anns:
        raise ValueError("batch larger than device batch padding")
    if batch.n_binary > pad_banns:
        raise ValueError("batch larger than device batch padding")
    f = batch.flags.astype(np.int32)
    return DeviceBatch(
        trace_id=_pad(batch.trace_id, pad_spans),
        span_id=_pad(batch.span_id, pad_spans),
        parent_id=_pad(batch.parent_id, pad_spans),
        name_id=_pad(batch.name_id, pad_spans),
        name_lc_id=_pad(np.asarray(name_lc_id, np.int32), pad_spans, -1),
        service_id=_pad(batch.service_id, pad_spans, -1),
        ts_cs=_pad(batch.ts_cs, pad_spans, NO_TS),
        ts_cr=_pad(batch.ts_cr, pad_spans, NO_TS),
        ts_sr=_pad(batch.ts_sr, pad_spans, NO_TS),
        ts_ss=_pad(batch.ts_ss, pad_spans, NO_TS),
        ts_first=_pad(batch.ts_first, pad_spans, NO_TS),
        ts_last=_pad(batch.ts_last, pad_spans, NO_TS),
        duration=_pad(batch.duration, pad_spans, NO_TS),
        flags=_pad(f, pad_spans),
        has_parent=_pad(
            (f & int(FLAG_HAS_PARENT)).astype(bool), pad_spans, False
        ),
        indexable=_pad(np.asarray(indexable, bool), pad_spans, False),
        n_spans=np.int32(batch.n_spans),
        ann_span_idx=_pad(batch.ann_span_idx, pad_anns),
        ann_ts=_pad(batch.ann_ts, pad_anns, NO_TS),
        ann_value_id=_pad(batch.ann_value_id, pad_anns, -1),
        ann_service_id=_pad(batch.ann_service_id, pad_anns, -1),
        ann_endpoint_id=_pad(batch.ann_endpoint_id, pad_anns, -1),
        n_anns=np.int32(batch.n_annotations),
        bann_span_idx=_pad(batch.bann_span_idx, pad_banns),
        bann_key_id=_pad(batch.bann_key_id, pad_banns, -1),
        bann_value_id=_pad(batch.bann_value_id, pad_banns, -1),
        bann_type=_pad(batch.bann_type.astype(np.int32), pad_banns),
        bann_service_id=_pad(batch.bann_service_id, pad_banns, -1),
        bann_endpoint_id=_pad(batch.bann_endpoint_id, pad_banns, -1),
        n_banns=np.int32(batch.n_binary),
        error_flag=_pad(
            np.zeros(batch.n_spans, bool) if error_flag is None
            else np.asarray(error_flag, bool),
            pad_spans, False,
        ),
        # Ring batches keep shape-(1,) placeholders so every ring unit
        # shares one jit cache entry; paged batches pad the planner's
        # claims to the unit's static shapes.
        span_slot=(
            np.zeros(1, np.int32) if span_slot is None
            else _pad(np.asarray(span_slot, np.int32), pad_spans)
        ),
        span_gid=(
            np.zeros(1, np.int64) if span_gid is None
            else _pad(np.asarray(span_gid, np.int64), pad_spans, -1)
        ),
        reclaim_page=(
            np.full(1, -1, np.int32) if reclaim_pages is None
            else _pad(
                np.asarray(reclaim_pages, np.int32), pad_reclaims, -1
            )
        ),
    )


# ---------------------------------------------------------------------------
# Dependency-link kernel (shared by ingest_step and offline recompute)
# ---------------------------------------------------------------------------


def dep_link_moments(
    trace_id, span_id, parent_id, service_id, duration,
    build_valid, probe_valid, n_services: int,
):
    """[S*S, 5] Moments of child durations per (parent_svc, child_svc).

    The device-native ZipkinAggregateJob.scala:26-38: a sort-merge join
    of (trace_id, parent_id) against (trace_id, span_id) followed by a
    segmented moments reduction — no shuffles, one launch.
    """
    S = n_services
    found, parent_svc = join.lookup(
        (trace_id, span_id), build_valid, service_id,
        (trace_id, parent_id), probe_valid,
    )
    link_ok = (
        found
        & (parent_svc >= 0) & (service_id >= 0)
        & (parent_svc < S) & (service_id < S)
        & (duration >= 0)
    )
    link_id = jnp.where(link_ok, parent_svc.astype(jnp.int32) * S + service_id, 0)
    return M.segment_moments(
        duration.astype(jnp.float32), link_id, S * S, valid=link_ok
    )


@jax.jit
def recompute_dep_moments(state: "StoreState"):
    """Offline recompute over the live span ring (the rerunnable-batch-job
    analogue; parity check for the streaming archive+live path)."""
    from zipkin_tpu.columnar.schema import FLAG_HAS_PARENT

    live = state.row_gid >= 0
    has_parent = (state.flags & jnp.int32(int(FLAG_HAS_PARENT))) != 0
    return dep_link_moments(
        state.trace_id, state.span_id, state.parent_id, state.service_id,
        state.duration, live, live & has_parent, state.config.max_services,
    )


# -- streaming hash join ----------------------------------------------------
#
# The span hash table + pending ring resolve parent/child links at
# ingest time. Per-op cost on this class of device grows with operand
# ROWS (measured ~25-100ms per HLO op at 8M rows, NOTES_r03.md), so the
# r2 design — an O(ring) sort-join per archive pass and per
# get_dependencies — paid seconds per call; probing a hash table costs
# a handful of ops on BATCH-sized arrays instead.

_TAB_PROBES = 4
_SVC_MASK = 0x7FFF  # 15-bit service payload (svc + 1; 0 = missing)


def _mix48(a, b):
    """48-bit mixed key of two i64 columns (uint64 result < 2^48)."""
    from zipkin_tpu.ops.hashing import mix_keys64

    return mix_keys64([a, b]) >> jnp.uint64(16)


# Empty span-table sentinel: I64_MAX, so a plain scatter-MIN both fills
# empty slots and arbitrates every in-batch race deterministically (see
# _tab_insert). A packed word can never equal it: svc is clipped below
# the full 15-bit mask, so the low 16 bits are never all-ones.
_TAB_EMPTY = (1 << 63) - 1


def _tab_pack(key48, svc):
    """(key48, service) → occupied table word (never _TAB_EMPTY)."""
    s = (jnp.clip(svc, -1, _SVC_MASK - 2) + 1).astype(jnp.uint64)
    return ((key48 << jnp.uint64(16)) | (s << jnp.uint64(1))
            | jnp.uint64(1)).astype(jnp.int64)


def _tab_slots(key48, n_slots: int):
    """The probe sequence: double hashing over a power-of-two table."""
    h0 = key48 & jnp.uint64(n_slots - 1)
    step = ((key48 >> jnp.uint64(20)) << jnp.uint64(1)) | jnp.uint64(1)
    return [
        ((h0 + jnp.uint64(j) * step) & jnp.uint64(n_slots - 1)).astype(
            jnp.int32
        )
        for j in range(_TAB_PROBES)
    ]


def _tab_lookup(tab, key48):
    """(found, svc) per probe key — svc is -1 when absent/serviceless.
    ``tab`` is the [H, 2] i32 plane-pair table (StoreState.span_tab):
    each probe load is an 8-byte i32 row gather, bitcast locally back
    to the logical packed word."""
    found = jnp.zeros(key48.shape, bool)
    svc = jnp.full(key48.shape, -1, jnp.int32)
    for slot in _tab_slots(key48, tab.shape[0]):
        cur = _p64(tab[slot]).astype(jnp.uint64)
        hit = (cur != jnp.uint64(_TAB_EMPTY)) & (
            (cur >> jnp.uint64(16)) == key48)
        first = hit & ~found
        svc = jnp.where(
            first,
            ((cur >> jnp.uint64(1)) & jnp.uint64(_SVC_MASK)).astype(
                jnp.int32
            ) - 1,
            svc,
        )
        found |= hit
    return found, svc


def _tab_insert(tab, key48, svc, valid):
    """Insert (key48 → svc) rows. Each probe round is ONE scatter-MIN:
    the empty sentinel (_TAB_EMPTY = I64_MAX) loses to every packed
    word, and rows racing for one slot resolve to the numerically
    smallest word — so the client and server halves of an RPC, which
    share (trace_id, span_id), deterministically keep the LOWEST
    service id regardless of arrival order, in-batch or across batches.
    (The reference merges the halves before joining and picks one
    serviceName, ZipkinAggregateJob.scala mergeSpan; min-service-id is
    this store's deterministic analogue — divergence noted in
    COVERAGE.md row 3.) A different-key loser fails the read-back
    verify and retries its next probe; a key is only ever lost when all
    probes land on slots occupied by foreign keys — then the last slot
    is stolen (random-replacement eviction; the table outlives ring
    retention, bounded like the reference's index TTL,
    CassieSpanStore.scala:48)."""
    oob = tab.shape[0]
    packed = _tab_pack(key48, svc)
    placed = ~jnp.asarray(valid, bool)
    slots = _tab_slots(key48, tab.shape[0])
    # Each round's min-war is arbitrated EXPLICITLY (_slot_war sorts the
    # contenders) instead of by an i64 scatter-min + re-read — bitwise
    # the same winner (numerically smallest packed word), but built
    # from sorts and one unique plane scatter. The table itself lives
    # in i32 plane form (StoreState.span_tab): probe loads are i32 row
    # gathers, writes i32 plane scatters — i64 gathers/scatters are the
    # serialized class on this backend (profile_scatter*.py).
    for slot in slots:
        cur = _p64(tab[slot])
        curu = cur.astype(jnp.uint64)
        open_ = (curu == jnp.uint64(_TAB_EMPTY)) | (
            (curu >> jnp.uint64(16)) == key48
        )
        attempt = ~placed & open_
        seg_min, write_row = _slot_war(slot, packed, attempt, oob)
        after = jnp.minimum(cur, seg_min)  # inactive rows: seg_min=MAX
        tab = _uset_p(tab, slot, after, write_row)
        placed |= attempt & (
            (after.astype(jnp.uint64) >> jnp.uint64(16)) == key48)
    # Last-resort steal: the old state is discarded, so the winner is
    # simply the smallest packed word among same-slot stealers.
    seg_min, write_row = _slot_war(slots[-1], packed, ~placed, oob)
    return _uset_p(tab, slots[-1], seg_min, write_row)


# -- index column families ---------------------------------------------------
#
# Each family is a flat [B*K, 2] i64 array of (span gid, verify) entries
# in per-bucket FIFO rings plus a [B] i32 cursor — the device rendition
# of the reference's index column families (ServiceNameIndex /
# ServiceSpanNameIndex / AnnotationsIndex, CassieSpanStore.scala:168-251,
# cassandra-schema.txt). Written by batch-sized scatters inside
# ingest_step; read by O(depth) bucket slices. Entry liveness is checked
# against the span ring at query time (gid round-trip), so eviction
# needs no index maintenance.


def _fifo_ranks(bucket, valid, n_buckets: int):
    """Arrival-order rank of each row within its bucket. One stable
    single-key sort (bucket in the high bits, row index in the low bits)
    + a cummax segment-start fill — deterministic, so two ingests of the
    same batch produce bitwise-identical index state.

    The shift is derived from the (static) row count, so an
    annotation-heavy launch past 2^21 concatenated rows widens the key
    instead of tripping an assert; the static bucket-count bound keeps
    the sentinel (one past every real bucket id, 2^62 after shifting)
    from wrapping sign."""
    n = bucket.shape[0]
    shift = max((n - 1).bit_length(), 1)
    assert n_buckets < (1 << (62 - shift)), (
        f"rank key space exhausted: {n} rows x {n_buckets} buckets")
    key = jnp.where(valid, bucket.astype(jnp.int64),
                    jnp.int64(1) << (62 - shift))
    skey = (key << shift) | jnp.arange(n, dtype=jnp.int64)
    order = jnp.argsort(skey)
    sk = key[order]
    first = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    idxs = jnp.arange(n, dtype=jnp.int32)
    start = jax.lax.cummax(jnp.where(first, idxs, jnp.int32(-1)))
    rank = jnp.zeros(n, jnp.int32).at[order].set(idxs - start,
                                                 unique_indices=True)
    return rank


# -- segmented counting-sort ranks (r12) -------------------------------------
#
# The r12 alternative to _fifo_ranks' stable argsort: the within-bucket
# arrival rank decomposes as (same-bucket rows in EARLIER row blocks) +
# (same-bucket earlier rows in MY block). The first term is a counting
# sort — the per-(bucket, block) occupancy histogram is ONE i32
# duplicate-index scatter-add (the same vectorized class as the bucket
# count the write pass already pays) turned into prefixes by a cumsum
# along the block axis, read back by ONE gather; the second term is
# block-1 shifted elementwise equality tests. Net census vs the argsort
# path: -1 stablehlo.sort, ±0 scatters, ±0 gathers (the argsort path
# spends 1 scatter + 1 gather on its unsort), and the O(N log N)
# comparator sort disappears from the compile.
#
# The scratch is the dense [(n_buckets+1) x ceil(N/block)] histogram —
# it scales with buckets x rows, so huge-arena geometries (the 2^22
# bench rings, whose trace families alone carry ~800k buckets) blow any
# block size past the budget and statically keep the argsort path;
# rank_block_for is the feasibility oracle and docs/PERFORMANCE.md
# carries the arithmetic. Both paths are BITWISE-identical for every
# row (including the ~valid sentinel-bucket rows), fuzz-gated by
# tests/test_rank_paths.py.

# Block sizes tried smallest-first (each must be a power of two: block
# membership tests mask with block-1). Bigger blocks shrink the scratch
# but pay (block-1) shifted compares; past 64 the elementwise tail
# would dominate the sort it replaces.
_RANK_BLOCKS = (8, 16, 32, 64)
# Scratch budget in i32 elements (128 MiB transient): generous for
# smoke/test geometries and wide enough that MID-size bench rings
# (cap 2^16 at ~57k-row launches, block 64) still engage counting so
# the on-chip matrix arms can measure the sort-vs-counting delta; the
# 2^22 cert geometry (~800k buckets x ~2M rows) is out of reach for
# ANY block size — docs/PERFORMANCE.md carries the arithmetic — and
# statically keeps argsort.
_RANK_SCRATCH_ELEMS = 1 << 25


def rank_block_for(n_rows: int, n_buckets: int) -> int:
    """Smallest feasible counting-rank block size for a launch shape
    (0 = no block fits the scratch budget; take the argsort path)."""
    for blk in _RANK_BLOCKS:
        groups = -(-n_rows // blk)
        if (n_buckets + 1) * groups <= _RANK_SCRATCH_ELEMS:
            return blk
    return 0


def rank_mode(rank_path: str, n_rows: int, n_buckets: int,
              wm_shift: int):
    """Static rank-path decision for one launch shape: ("argsort", 0)
    or ("counting", block). The wm_shift == 0 small-store regime stays
    on argsort even when counting is requested — tiny rings mean tiny
    batches, where the counting pass's fixed overhead (scratch zeroing
    + shifted compares) buys nothing, and keeping one static policy per
    regime keeps the compile-cache story simple (mirrors the exact
    gid-war fallback in _index_write).

    "auto" is BACKEND-aware: the counting sort exists to delete a TPU
    sort bottleneck; on the CPU backend XLA's sort is fast and the
    counting scratch traffic measurably LOSES (~+11% on device-heavy
    tier-1 modules, r12 measurement), so auto picks counting only on
    TPU. An explicit "counting" is honored on every backend — that is
    what the CI equivalence/census gates pin the path with. The choice
    is always bitwise-neutral, so a checkpoint moving between backends
    never diverges."""
    if rank_path not in ("auto", "argsort", "counting"):
        raise ValueError(f"unknown rank_path {rank_path!r}")
    if rank_path == "argsort" or wm_shift == 0:
        return "argsort", 0
    if rank_path == "auto" and jax.default_backend() != "tpu":
        return "argsort", 0
    blk = rank_block_for(n_rows, n_buckets)
    if blk == 0:
        # Scratch infeasible at this geometry: "counting" degrades to
        # argsort rather than OOMing the device (recorded in the
        # active-paths registry so counters()/bench say what ran).
        return "argsort", 0
    return "counting", blk


def _fifo_ranks_counting(bucket, valid, n_buckets: int, block: int):
    """Counting-sort twin of _fifo_ranks: bitwise-identical rank vector
    (valid rows rank among same-bucket valid rows, ~valid rows among
    themselves via the sentinel bucket — exactly the argsort path's
    sentinel-key semantics), built from one duplicate-index i32
    scatter-add, one cumsum, one gather, and block-1 shifted compares.
    ``block`` must be a power of two (see _RANK_BLOCKS); valid rows
    must carry bucket in [0, n_buckets) — the same contract the argsort
    path's callers already honor (_index_write's seg() clips)."""
    n = bucket.shape[0]
    groups = -(-n // block)
    b_eff = jnp.where(
        valid, jnp.clip(bucket, 0, n_buckets - 1).astype(jnp.int32),
        jnp.int32(n_buckets),
    )
    rows = jnp.arange(n, dtype=jnp.int32)
    g = rows // jnp.int32(block)
    sidx = b_eff * jnp.int32(groups) + g
    # Per-(bucket, block) occupancy — duplicate-index i32 scatter-add,
    # the vectorized class (profile_scatter*.py); indices are in-range
    # by construction, mode="drop" is belt-and-braces.
    cnt = jnp.zeros((n_buckets + 1) * groups, jnp.int32).at[sidx].add(
        1, mode="drop")
    cnt2 = cnt.reshape(n_buckets + 1, groups)
    # Exclusive prefix along the block axis: same-bucket rows in
    # earlier blocks.
    prefix = (jnp.cumsum(cnt2, axis=1) - cnt2).reshape(-1)
    pre = prefix[sidx]
    # Same-bucket earlier rows within my block: block-1 shifted
    # equality tests, masked to block membership (blocks are aligned —
    # row i and i-d share a block iff i % block >= d).
    in_block = rows & jnp.int32(block - 1)
    w = jnp.zeros(n, jnp.int32)
    for d in range(1, min(block, n)):
        same = jnp.concatenate(
            [jnp.zeros(d, bool), b_eff[d:] == b_eff[:-d]])
        w = w + (same & (in_block >= d)).astype(jnp.int32)
    return pre + w


# Active-path registry: which rank / arena-scatter implementations each
# StoreConfig's compiled steps actually took (trace-time records — one
# entry per compile, so steady state writes nothing). Surfaced through
# TpuSpanStore.counters() -> /metrics and the bench JSON, so every
# recorded spans/s figure says which kernels produced it. The lock
# guards reads against a concurrent first-compile on another thread (a
# /metrics scrape during a pipelined store's new-shape trace must not
# see a set mid-mutation). Entries live as long as the process, keyed
# by config — the SAME lifecycle and sharing as the jit caches whose
# path choices they record: a new store reusing a config also reuses
# those compiled steps, so the inherited record is accurate for it.
_ACTIVE_PATHS: Dict[StoreConfig, Dict[str, set]] = {}
_ACTIVE_PATHS_LOCK = threading.Lock()  # lock-order: 85 trace-registry


def _note_path(config: StoreConfig, kind: str, value: str) -> None:
    with _ACTIVE_PATHS_LOCK:
        _ACTIVE_PATHS.setdefault(config, {}).setdefault(
            kind, set()).add(value)


def active_paths(config: StoreConfig) -> Dict[str, Tuple[str, ...]]:
    """{"rank": ("counting", ...), "scatter": ("xla", ...)} — every
    implementation this config's compiled ingest steps used (may hold
    both when different launch shapes picked different modes)."""
    with _ACTIVE_PATHS_LOCK:
        return {
            k: tuple(sorted(v))
            for k, v in _ACTIVE_PATHS.get(config, {}).items()
        }


def _index_write(entries, pos, wm, key_tab, key_wm, ann_poison,
                 gbucket, slot0, depth, gid, verify, ts, valid,
                 keyed_from: int, n_cand_rows: int, n_cand_buckets: int,
                 poison_bucket=None, poison_gid=None, poison_ok=None,
                 wm_shift: int = 0, ts_shift: int = _WM_TS_SHIFT,
                 rank_sel=("argsort", 0), scatter_mode: str = "xla"):
    """ONE combined append of (gid, verify, ts) rows into the UNIFIED
    index arena — candidate families and trace-membership families
    alike: ``gbucket`` is the global bucket id (addressing pos/wm),
    ``slot0`` the bucket's first entry row, and ``depth`` its FIFO
    depth — all per-row vectors, constant per concatenated family
    segment, so every family rides the same rank sort, count scatter,
    displaced-row gather, entry scatter, and cursor update (per-kernel
    overhead dominates on this backend, NOTES_r03.md §3; the r5 split
    cand/trace write blocks cost two of everything).

    Row sections (static slices of the concatenation):
    - ``[0:n_cand_rows)``  candidate-family rows. Their buckets' ``wm``
      is the overwrite TS watermark: the max ts ever displaced (by
      wraparound, or by in-batch overflow where one launch writes more
      than ``depth`` rows to a bucket and keeps the newest). Queries on
      a wrapped bucket are exact iff their last returned candidate
      still ranks >= the watermark. The war runs COARSE — one shared
      vectorized i32 duplicate-index scatter-max in 2^ts_shift-µs ceil
      units (see _WM_TS_SHIFT) — with an EXACT plane-war fallback,
      entered under lax.cond only when some contribution lies past the
      coarse domain (costs nothing on real traffic).
    - ``[n_cand_rows:)``  trace-membership rows. Their buckets' ``wm``
      is the max DISPLACED GID (ring overwrite order is oldest-first,
      so wm < write_pos - capacity proves the bucket holds every
      resident row of its traces). The war rides the SAME shared
      scatter in 2^wm_shift-gid units (except wm_shift == 0: exact —
      see _war_max_gid_coarse's small-store rationale).

    ``key_tab``/``key_wm`` is the per-key cursor table (see
    StoreState.key_tab); rows in ``[keyed_from:n_cand_rows)`` (the
    keyed families are a contiguous MIDDLE slice — the service family,
    whose bucket IS the key, leads, and the trace families trail) claim
    a record for their verify word, and every displaced or
    in-batch-dropped keyed entry maxes its span gid into its key's
    displaced watermark — through the same shared scatter. So do the
    middle-host ``ann_poison`` contributions (``poison_*``, per
    annotation row). Also returns the number of keyed rows whose claim
    found no slot (table congestion): while that count is ZERO over the
    store's lifetime, an ABSENT record proves its key was never indexed
    — the negative-lookup gate (see iquery wrappers)."""
    n_b = pos.shape[0]
    rank_kind, rank_blk = rank_sel
    if rank_kind == "counting":
        rank = _fifo_ranks_counting(gbucket, valid, n_b, rank_blk)
    else:
        rank = _fifo_ranks(gbucket, valid, n_b)
    b_c = jnp.clip(gbucket, 0, n_b - 1)
    oob_b = jnp.where(valid, b_c, n_b)
    cnt = jnp.zeros(n_b + 1, jnp.int32).at[oob_b].add(
        1, mode="drop")[:n_b]
    keep = valid & (rank >= cnt[b_c] - depth)
    # Cursor math runs in the i32 low plane: depths are powers of two
    # (StoreConfig._derived), so (pos + rank) % depth only needs the low
    # 32 bits, and the occupancy test only needs pos itself, which stays
    # far below 2^31 per bucket (total entries ever / n_buckets).
    pos_lo = _p32(pos)[:, 0]
    pos_b = pos_lo[b_c]
    slot = slot0.astype(jnp.int32) + ((pos_b + rank) % depth)
    # A kept write DISPLACES a previous entry iff its bucket has already
    # wrapped past this slot — pos + rank >= depth. NOT identical to the
    # old per-slot occupancy gather (gid >= 0): when an earlier batch
    # overflowed a bucket (cnt > depth), its dropped rows never wrote
    # their slots, so a cursor-"occupied" slot may still hold the INIT
    # entry — whose values are chosen to be inert here (they lose every
    # watermark war and match no key fingerprint; see init_state).
    occupied = keep & (pos_b + rank >= depth)
    gidx = jnp.where(keep, slot, 0)
    # ONE row gather of the displaced entries for ALL families:
    # profiled ~3x cheaper than per-column i64 gathers on this backend
    # (the [N, 3] rows are contiguous 24-byte reads;
    # scripts/profile_ingest.py arm 8b).
    old_rows = entries[gidx]
    cand = slice(0, n_cand_rows)
    trc = slice(n_cand_rows, None)
    old_ts_c = jnp.where(occupied[cand], old_rows[cand, 2], I64_MIN)
    # Old entry identity is consumed by the keyed-slice machinery below.
    sfx = slice(keyed_from, n_cand_rows)
    old_gid_s = old_rows[sfx, 0]
    old_verify_s = old_rows[sfx, 1]
    dropped_ts = jnp.where(
        valid[cand] & ~keep[cand],
        jnp.asarray(ts, jnp.int64)[cand], I64_MIN,
    )
    disp_ts = jnp.maximum(old_ts_c, dropped_ts)
    # Trace rows: the watermark needs the TRUE displaced gid (from the
    # shared old-row gather) — under continuous displacement the
    # displaced entry is ~2 window-laps old and already ring-evicted,
    # which is exactly what keeps the gate passing in steady state;
    # substituting the current row's (always-recent) gid would hold
    # every busy bucket's gate closed forever. In-batch dropped rows
    # carry their own gid.
    gid = jnp.asarray(gid, jnp.int64)
    tr_wmv = jnp.where(occupied[trc], old_rows[trc, 0], gid[trc])
    tr_ok = occupied[trc] | (valid[trc] & ~keep[trc])
    verify = jnp.asarray(verify, jnp.int64)
    vals = jnp.stack([gid, verify, jnp.asarray(ts, jnp.int64)], axis=-1)
    if scatter_mode == "pallas":
        # Grid-sequential fused claim+scatter (ops/pallas_kernels):
        # the kernel re-derives each row's FIFO slot from a
        # VMEM-resident cursor walk (claim) and writes ALL valid rows
        # in arrival order — in-batch overflow rows are overwritten by
        # their newest same-slot successor, which lands the bitwise
        # SAME final arena as the rank-gated unique scatter (every
        # dropped row's slot is rewritten by the rank+depth successor
        # that displaced it). `keep`/`rank` stay load-bearing for the
        # displacement bookkeeping above/below either way.
        from zipkin_tpu.ops import pallas_kernels as PK

        entries = PK.arena_claim_scatter(
            entries, b_c, pos_b, slot0, depth, vals, valid,
            n_buckets=n_b)
    else:
        entries = _uset_cols64(entries, slot, vals, keep)
    pos = pos + cnt.astype(pos.dtype)

    # -- per-key fingerprint records (suffix rows only) ----------------
    # 1. Claim records for this batch's keys: empty slots only, i32
    #    fingerprint min-war arbitration (duplicate-index i32 scatters
    #    vectorize; the old exact-word i64 war serialized at ~100 ns/row
    #    and dominated the whole ingest step). Records are NEVER stolen
    #    and never seeded on occupied-by-foreign probes. Two distinct
    #    keys may share (slot, fingerprint) — then they SHARE a record
    #    and their displaced watermarks merge, which can only overstate
    #    a watermark: extra fallbacks, never a wrong answer. The
    #    negative-lookup gate stays sound: an indexed key either placed
    #    a record its probes will find (fp match) or counted a drop.
    #
    #    All three probe slots are read in ONE stacked gather and the
    #    claim goes to the first EMPTY probe; rows that lose the
    #    in-batch min-war at their chosen slot retry (next empty probe
    #    under the updated table) in a lax.cond round that costs
    #    nothing once the key population is resident — the round-4
    #    3-sequential-probe loop paid 3 gather+scatter+gather rounds
    #    on EVERY step forever. Probe-exhaustion semantics (and the
    #    drop count) are identical: initial + 2 retries = 3 attempts.
    T = key_tab.shape[0]
    v_s = valid[sfx]
    verify_s = verify[sfx]
    k48n = verify_s.astype(jnp.uint64) >> jnp.uint64(16)
    fp = _fp31(k48n)
    slots3 = jnp.stack(_tab_slots(k48n, T)[:_KEY_PROBES])  # [3, M]

    def claim_round(key_tab, placed):
        cur = key_tab[slots3]                 # one gather, 3M rows
        already = (cur == fp[None, :]).any(0)
        empty = cur == _FP_EMPTY
        choose = jnp.full(fp.shape, T, jnp.int32)
        for i in range(_KEY_PROBES - 1, -1, -1):
            choose = jnp.where(empty[i], slots3[i], choose)
        attempt = v_s & ~placed & ~already & (choose < T)
        key_tab = key_tab.at[jnp.where(attempt, choose, T)].min(
            jnp.where(attempt, fp, _FP_EMPTY), mode="drop"
        )
        after = key_tab[jnp.where(attempt, choose, 0)]
        placed = placed | already | (attempt & (after == fp))
        # Lost the same-batch min-war at a still-open table: retryable.
        unresolved = attempt & ~placed
        return key_tab, placed, unresolved

    placed = jnp.zeros(fp.shape, bool)
    key_tab, placed, unresolved = claim_round(key_tab, placed)
    for _ in range(_KEY_PROBES - 1):
        key_tab, placed, unresolved = jax.lax.cond(
            unresolved.any(),
            claim_round,
            lambda kt, pl: (kt, pl, jnp.zeros_like(pl)),
            key_tab, placed,
        )
    # 2. Record displacements: bucket-wrap victims carry their OLD
    #    entry's (verify, gid); in-batch overflow drops carry their own.
    #    The displaced gid must be the TRUE old gid (not the current
    #    row's): a busy key's displaced entries are ~2 window-laps old
    #    and already evicted, which is exactly what keeps its record's
    #    eviction gate passing in steady state.
    keep_s = keep[sfx]
    disp_ok = (keep_s & occupied[sfx]) | (v_s & ~keep_s)
    disp_key = jnp.where(keep_s, old_verify_s, verify_s)
    disp_gid = jnp.where(keep_s, old_gid_s, gid[sfx])
    k48d = disp_key.astype(jnp.uint64) >> jnp.uint64(16)
    fpd = _fp31(k48d)
    dslots3 = jnp.stack(_tab_slots(k48d, T)[:_KEY_PROBES])
    dhit = key_tab[dslots3] == fpd[None, :]   # one gather, 3M rows
    dslot = jnp.full(k48d.shape, T, jnp.int32)
    for i in range(_KEY_PROBES - 1, -1, -1):
        dslot = jnp.where(dhit[i], dslots3[i], dslot)
    key_hit = disp_ok & dhit.any(0)

    # -- the SHARED watermark war --------------------------------------
    # Every watermark family — candidate ts watermarks, trace-family
    # displaced-gid watermarks, per-key displaced-gid watermarks, and
    # the middle-host ann_poison stamps — folds through ONE vectorized
    # i32 duplicate-index scatter-max over a partitioned scratch, each
    # contribution pre-encoded in its own family's coarse unit (the
    # buckets are disjoint, so mixed units can share a scatter). The r5
    # step paid one war per family (the "+ bucket wm war off" 73 ms
    # ablation slice plus three coarse gid scatters); this is one.
    valid_c = valid[cand]
    S_p = ann_poison.shape[0]
    n_scr = n_b + T + S_p + 1
    val_c, over_c = _coarse_ts32(disp_ts, valid_c, ts_shift)
    idx_c = jnp.where(valid_c, b_c[cand], n_scr - 1)
    parts_idx = [idx_c]
    parts_val = [val_c]
    exact_gid_wars = wm_shift == 0  # small-store satellite: no cliff
    if not exact_gid_wars:
        parts_idx.append(jnp.where(tr_ok, b_c[trc], n_scr - 1))
        parts_val.append(_coarse_gid32(tr_wmv, tr_ok, wm_shift))
        parts_idx.append(jnp.where(key_hit, n_b + dslot, n_scr - 1))
        parts_val.append(_coarse_gid32(disp_gid, key_hit, wm_shift))
        if poison_bucket is not None:
            parts_idx.append(jnp.where(
                poison_ok,
                n_b + T + jnp.clip(poison_bucket, 0, S_p - 1),
                n_scr - 1,
            ))
            parts_val.append(
                _coarse_gid32(poison_gid, poison_ok, wm_shift))
    scr = jnp.zeros(n_scr, jnp.int32).at[
        jnp.concatenate(parts_idx)
    ].max(jnp.concatenate(parts_val), mode="drop")
    # Fold back per segment: only slots the war actually raised touch
    # their exact i64 state (empty I64_MIN sentinels survive bit-exact).
    scr_b = scr[:n_b]
    ts_upd = jnp.where(scr_b > 0, scr_b.astype(jnp.int64) << ts_shift,
                       I64_MIN)
    if exact_gid_wars:
        wm = jnp.maximum(
            wm,
            jnp.where(jnp.arange(n_b) < n_cand_buckets, ts_upd, I64_MIN),
        )
        wm = _war_max64(wm, b_c[trc], tr_wmv, tr_ok)
        key_wm = _war_max64(key_wm, dslot, disp_gid, key_hit)
        if poison_bucket is not None:
            ann_poison = _war_max64(
                ann_poison, jnp.clip(poison_bucket, 0, S_p - 1),
                jnp.asarray(poison_gid, jnp.int64), poison_ok,
            )
    else:
        gid_upd = jnp.where(
            scr_b > 0, scr_b.astype(jnp.int64) << wm_shift, I64_MIN)
        wm = jnp.maximum(
            wm,
            jnp.where(jnp.arange(n_b) < n_cand_buckets, ts_upd, gid_upd),
        )
        scr_k = scr[n_b:n_b + T]
        key_wm = jnp.maximum(key_wm, jnp.where(
            scr_k > 0, scr_k.astype(jnp.int64) << wm_shift, I64_MIN))
        if poison_bucket is not None:
            scr_p = scr[n_b + T:n_b + T + S_p]
            ann_poison = jnp.maximum(ann_poison, jnp.where(
                scr_p > 0, scr_p.astype(jnp.int64) << wm_shift,
                I64_MIN))
    # Exact overflow fallback for the ts war: contributions past the
    # coarse ceiling run the exact plane war instead of saturating (a
    # saturated ts watermark would close its bucket forever). lax.cond
    # executes one branch at runtime, so real traffic (no overflow)
    # pays a scalar reduction, not the war.
    wm = jax.lax.cond(
        over_c.any(),
        lambda w: _war_max64(w, b_c[cand], disp_ts, over_c),
        lambda w: w,
        wm,
    )
    n_drops = (v_s & ~placed).sum().astype(jnp.int64)
    return entries, pos, wm, key_tab, key_wm, ann_poison, n_drops


def _span_host_range(ann_svc, ann_span_idx, valid_a, n_spans: int):
    """Per span: (min, max) service over its annotation hosts — the
    span's host SET for spans with at most two distinct hosts (the
    cs/cr-client + sr/ss-server shape of real traffic). Spans with more
    distinct hosts index under min/max only (counted nowhere: the scan
    fallback still finds them when a bucket is incomplete)."""
    big = jnp.int32(1 << 30)
    seg = jnp.where(valid_a, ann_span_idx, n_spans)
    mn = jnp.full(n_spans + 1, big, jnp.int32).at[seg].min(
        jnp.where(valid_a, ann_svc, big), mode="drop"
    )[:n_spans]
    mx = jnp.full(n_spans + 1, -1, jnp.int32).at[seg].max(
        jnp.where(valid_a, ann_svc, -1), mode="drop"
    )[:n_spans]
    return mn, mx


def _mixb(keys):
    from zipkin_tpu.ops.hashing import mix_keys64

    return mix_keys64([jnp.asarray(k, jnp.int64) for k in keys])


def _bucket_of(mixed, n_buckets: int):
    return (mixed & jnp.uint64(n_buckets - 1)).astype(jnp.int32)


def _verify_of(mixed):
    return mixed.astype(jnp.int64)


def _window_fold(window, window_ts, durations, link_id, ok, tsf, tsl, S):
    """Fold resolved links into the accumulating window bank (exact
    segmented Moments — same Chan/Pébay arithmetic as the host monoid,
    ZipkinAggregateJob.scala:36-46)."""
    bank = M.segment_moments(
        durations.astype(jnp.float32), link_id, S * S, valid=ok
    )
    new_window = M.combine(window, bank)
    any_ok = ok.any()
    ts_f = jnp.where(ok & (tsf >= 0), tsf, I64_MAX).min()
    ts_l = jnp.where(ok & (tsl >= 0), tsl, I64_MIN).max()
    new_ts = jnp.stack([
        jnp.minimum(window_ts[0], ts_f), jnp.maximum(window_ts[1], ts_l)
    ])
    return new_window, jnp.where(any_ok, new_ts, window_ts)


def _resolve_links(tab, trace_id, span_id, parent_id, svc, child_svc,
                   duration, build_ok, probe_ok, S):
    """Resolve each child's parent service: FIRST an exact within-batch
    sort-join (batch-sized, so same-batch parent/child pairs — the
    overwhelmingly common case — never depend on hash-table occupancy),
    THEN a span-table probe for parents from earlier batches. Returns
    (resolved, link_id, pending, ckey) — pending children found no
    parent anywhere and wait in the pending ring."""
    in_batch, psvc_b = join.lookup(
        (trace_id, span_id), build_ok, svc,
        (trace_id, parent_id), probe_ok,
    )
    ckey = _mix48(trace_id, parent_id)
    in_tab, psvc_t = _tab_lookup(tab, ckey)
    found = in_batch | in_tab
    psvc = jnp.where(in_batch, psvc_b, psvc_t)
    resolved = (
        probe_ok & found & (psvc >= 0) & (child_svc >= 0)
        & (child_svc < S) & (psvc < S) & (duration >= 0)
    )
    link_id = jnp.where(
        resolved, psvc * jnp.int32(S) + child_svc, 0
    )
    # A found parent without a service can never produce a link: drop
    # (matches the r2 join's link_ok gate), don't queue. Children whose
    # own service can't address a bank cell never queue either.
    pending = (probe_ok & ~found & (child_svc >= 0) & (child_svc < S)
               & (duration >= 0))
    return resolved, link_id, pending, ckey


def _sweep_core(state: "StoreState"):
    """Re-probe the pending ring; resolved children fold into the
    window. Returns the updated (window, window_ts, pend_key)."""
    S = state.config.max_services
    u = state.pend_key.astype(jnp.uint64)
    occupied = (u & jnp.uint64(1)) == 1
    ckey = u >> jnp.uint64(16)
    csvc = ((u >> jnp.uint64(1)) & jnp.uint64(_SVC_MASK)).astype(
        jnp.int32
    ) - 1
    found, psvc = _tab_lookup(state.span_tab, ckey)
    resolved = (occupied & found & (psvc >= 0) & (psvc < S)
                & (csvc >= 0) & (csvc < S))
    link_id = jnp.where(resolved, psvc * jnp.int32(S) + csvc, 0)
    window, window_ts = _window_fold(
        state.dep_window, state.dep_window_ts, state.pend_dur, link_id,
        resolved, state.pend_tsf, state.pend_tsl, S,
    )
    # Children whose parent arrived without a service — or whose own
    # service id can't address a bank cell — can never link: free their
    # slots too.
    drop = occupied & found & (
        (psvc < 0) | (psvc >= S) | (csvc < 0) | (csvc >= S)
    )
    cleared = jnp.where(resolved | drop, jnp.int64(0), state.pend_key)
    return window, window_ts, cleared


@partial(jax.jit, donate_argnums=(0,))
def dep_sweep(state: "StoreState") -> "StoreState":
    """Resolve pending children against the span table (the late-parent
    half of the streaming join). Cheap relative to ring size — all ops
    are pending-ring-sized. Called by the bucket close, before
    dependency reads, and on the collector's timer."""
    window, window_ts, cleared = _sweep_core(state)
    return state.replace(
        dep_window=window, dep_window_ts=window_ts, pend_key=cleared,
        # The sweep mutates state without moving any write cursor, so
        # it must bump a counter: checkpoint._state_generation decides
        # staged-leaf reuse from counters + cursors alone, and a sweep
        # between two save attempts would otherwise silently mix two
        # inconsistent cuts.
        counters={**state.counters,
                  "sweeps": state.counters["sweeps"] + 1},
    )


@partial(jax.jit, donate_argnums=(0,))
def dep_close_bucket(state: "StoreState") -> "StoreState":
    """Sweep, then rotate the window bank into a time-tagged slot of
    ``dep_banks`` — closing the current dependency time bucket (the
    hourly-aggregation-timer role of the reference's AnormAggregator
    schedule). The displaced slot merges into the all-time tail. An
    empty window only sweeps: rotating would displace one real
    time-tagged bank per idle tick and erode the windowing."""
    window, window_ts, cleared = _sweep_core(state)
    rotate = window[:, 0].sum() > 0
    K = state.config.dep_buckets
    slot = (state.dep_bank_seq % K).astype(jnp.int32)
    displaced = state.dep_banks[slot]
    displaced_ts = state.dep_bank_ts[slot]
    empty_ts = jnp.array([I64_MAX, I64_MIN], jnp.int64)
    return state.replace(
        dep_moments=jnp.where(
            rotate, M.combine(state.dep_moments, displaced),
            state.dep_moments,
        ),
        dep_overflow_ts=jnp.where(rotate, jnp.stack([
            jnp.minimum(state.dep_overflow_ts[0], displaced_ts[0]),
            jnp.maximum(state.dep_overflow_ts[1], displaced_ts[1]),
        ]), state.dep_overflow_ts),
        dep_banks=jnp.where(
            rotate, state.dep_banks.at[slot].set(window), state.dep_banks
        ),
        dep_bank_ts=jnp.where(
            rotate, state.dep_bank_ts.at[slot].set(window_ts),
            state.dep_bank_ts,
        ),
        dep_bank_seq=state.dep_bank_seq + rotate.astype(jnp.int64),
        dep_window=jnp.where(rotate, jnp.zeros_like(window), window),
        dep_window_ts=jnp.where(rotate, empty_ts, window_ts),
        pend_key=cleared,
        # An un-rotated close still sweeps — see dep_sweep's counter.
        counters={**state.counters,
                  "sweeps": state.counters["sweeps"] + 1},
    )


def poison_index_trust(state: "StoreState") -> "StoreState":
    """Mark every index bucket permanently untrusted (cursor past depth,
    watermark at +inf), forcing all reads through the exact scan
    kernels. Used when restoring snapshots that predate the index
    families: empty buckets with zero cursors would otherwise claim
    completeness and silently hide every restored span from the fast
    paths. New writes still append (cursors keep counting), but trust
    never returns for a poisoned bucket — the scan fallback serves the
    store's remaining lifetime, which is exactly the pre-index behavior
    the snapshot was taken under."""
    big = jnp.int64(1) << 60
    # One unified cursor/watermark pair covers every family now
    # (candidate prefix + trace suffix of the shared arena). Explicit
    # i64 (a legacy snapshot may restore other dtypes).
    return state.replace(
        cand_pos=jnp.full(state.cand_pos.shape, big, jnp.int64),
        cand_wm=jnp.full(state.cand_wm.shape, I64_MAX, jnp.int64),
    )


def poison_ann_trust(state: "StoreState") -> "StoreState":
    """Trust reset for snapshots predating revision 7, covering both
    rev-7 additions. Works on single and stacked sharded states alike.

    - ``ann_poison`` didn't exist: any restored resident span might
      have 3+ distinct annotation hosts, so stamp every service with
      the current write_pos — the annotation-family fast paths distrust
      their buckets until the ring has fully turned over, then
      self-heal.
    - ``key_tab`` didn't exist: the claim-with-clean-watermark
      invariant ("a fresh claim is the key's first record ever") does
      NOT hold across the restore boundary — pre-restore displacement
      history is lost, so a post-restore claim could certify a window
      missing displaced-but-resident restored spans. Permanently
      disable the table with a tombstone fingerprint (INT32_MIN: the
      i32 min-war can never overwrite it and _fp31 never produces it,
      so claims always fail → absent records → bucket gates serve,
      exactly the pre-upgrade behavior); key_wm is pinned at I64_MAX
      so even a fingerprint collision with the tombstone pattern reads
      as untrusted."""
    wp = jnp.asarray(state.write_pos, jnp.int64)
    counters = dict(state.counters)
    # A tombstoned table must also kill the NEGATIVE gate (absent record
    # ⇒ never indexed): pre-restore claims are lost, so absence proves
    # nothing. A nonzero drop counter disables it permanently.
    counters["key_claim_drops"] = jnp.maximum(
        jnp.asarray(counters.get("key_claim_drops", 0), jnp.int64),
        jnp.ones_like(wp),
    )
    return state.replace(
        ann_poison=jnp.broadcast_to(
            wp[..., None], state.ann_poison.shape
        ).astype(jnp.int64),
        key_tab=jnp.full(state.key_tab.shape, _FP_TOMB, jnp.int32),
        key_wm=jnp.full(state.key_wm.shape, I64_MAX, jnp.int64),
        counters=counters,
    )


@partial(jax.jit, donate_argnums=(0,))
def rebuild_span_tab(state: "StoreState") -> "StoreState":
    """(Re)insert every live resident span into the hash table. Used
    when restoring pre-revision-4 snapshots (whose schema had no table),
    so children arriving after the restore still find checkpointed
    parents — the case the retired resident-ring join covered."""
    live = state.row_gid >= 0
    key = _mix48(state.trace_id, state.span_id)
    return state.replace(
        span_tab=_tab_insert(state.span_tab, key, state.service_id, live)
    )


def dep_archive_step(state: "StoreState", w_new=None) -> "StoreState":
    """Compatibility alias from the r2 watermark-archive API: closing a
    bucket is the streaming join's analogue of an archive pass. The
    watermark argument is vestigial (links no longer depend on ring
    residency). NOTE: unlike the r2 original this DONATES ``state`` —
    reassign the result, don't keep using the argument."""
    del w_new
    return dep_close_bucket(state)


def dep_archive_auto(state: "StoreState", incoming=None) -> "StoreState":
    """Compatibility alias (see dep_archive_step; donates ``state``)."""
    del incoming
    return dep_close_bucket(state)


def stablehlo_op_census(stablehlo_text: str,
                        ops=("scatter", "gather", "sort")) -> dict:
    """Scatter/gather/sort census of a StableHLO lowering — the ONE
    counter behind the tier-1 95/5 ceiling (scripts/bench_smoke.py),
    TpuSpanStore.step_census, and the counter-block purity gate; keep a
    single definition so the gate and the runtime observable can never
    drift. Backend-independent: counts ops the program ISSUES, not what
    a backend fuses away."""
    import re

    return {
        op: len(re.findall(rf'"stablehlo\.{op}"', stablehlo_text))
        for op in ops
    }


# Telemetry counter block: every scalar the obs layer wants, packed
# into ONE [N] i64 vector so a metrics scrape costs one fused read-only
# launch + one D2H instead of a dict of tiny transfers. Derived values
# (occupancy, laps, poison census) are computed HERE at fetch time from
# cursors the ingest step already maintains — the block adds ZERO ops
# to the ingest step itself (scripts/bench_smoke.py asserts the step's
# scatter/sort census is unchanged and that this fetch lowers with no
# scatter/sort at all).
COUNTER_BLOCK_FIELDS = (
    "write_pos", "ann_write_pos", "bann_write_pos", "pend_pos",
    "dep_bank_seq", "ring_occupancy", "ring_laps", "ann_ring_occupancy",
    "bann_ring_occupancy", "pend_depth", "poisoned_services",
    "spans_seen", "anns_seen", "banns_seen", "batches",
    "key_claim_drops", "sweeps", "ts_min", "ts_max",
)


@jax.jit
def counter_block(state: StoreState) -> jnp.ndarray:
    """[len(COUNTER_BLOCK_FIELDS)] i64 — see COUNTER_BLOCK_FIELDS."""
    c = state.config
    wp = state.write_pos
    poisoned = jnp.sum(
        (state.ann_poison >= wp - c.capacity)
        & (state.ann_poison > I64_MIN)
    ).astype(jnp.int64)
    vals = {
        "write_pos": wp,
        "ann_write_pos": state.ann_write_pos,
        "bann_write_pos": state.bann_write_pos,
        "pend_pos": state.pend_pos,
        "dep_bank_seq": state.dep_bank_seq,
        "ring_occupancy": jnp.minimum(wp, c.capacity),
        "ring_laps": wp // c.capacity,
        "ann_ring_occupancy": jnp.minimum(state.ann_write_pos,
                                          c.ann_capacity),
        "bann_ring_occupancy": jnp.minimum(state.bann_write_pos,
                                           c.bann_capacity),
        "pend_depth": jnp.minimum(state.pend_pos, c.pending_slots),
        "poisoned_services": poisoned,
        "ts_min": state.ts_min,
        "ts_max": state.ts_max,
        **{k: state.counters[k] for k in (
            "spans_seen", "anns_seen", "banns_seen", "batches",
            "key_claim_drops", "sweeps",
        )},
    }
    return jnp.stack([
        jnp.asarray(vals[f], jnp.int64) for f in COUNTER_BLOCK_FIELDS
    ])


@jax.jit
def _total_dep_impl(dep_moments, dep_banks, dep_window):
    banks = M.reduce_moments(dep_banks, axis=0)
    return M.combine(M.combine(dep_moments, banks), dep_window)


def total_dep_moments(state: "StoreState"):
    """Tail + time-tagged banks + accumulating window: the complete link
    Moments bank. Callers wanting pending (late-parent) children
    included run dep_sweep first — TpuSpanStore.get_dependencies does."""
    return _total_dep_impl(
        state.dep_moments, state.dep_banks, state.dep_window
    )


@jax.jit
def _dep_in_range_impl(dep_moments, dep_banks, dep_bank_ts,
                       dep_overflow_ts, dep_window, dep_window_ts,
                       start_ts, end_ts):
    start_ts = jnp.asarray(start_ts, jnp.int64)
    end_ts = jnp.asarray(end_ts, jnp.int64)
    bmin = dep_bank_ts[:, 0]
    bmax = dep_bank_ts[:, 1]
    sel = (bmin <= end_ts) & (bmax >= start_ts)
    banks = jnp.where(sel[:, None, None], dep_banks, 0.0)
    total = M.reduce_moments(banks, axis=0)
    ov = (dep_overflow_ts[0] <= end_ts) & (dep_overflow_ts[1] >= start_ts)
    total = M.combine(total, jnp.where(ov, dep_moments, 0.0))
    w_ok = (dep_window_ts[0] <= end_ts) & (dep_window_ts[1] >= start_ts)
    return M.combine(total, jnp.where(w_ok, dep_window, 0.0))


def _compact_bank(bank, k: int):
    """(n_nonzero, row ids [k], rows [k, 5]) — top-k-by-count compaction
    of a [S*S, 5] Moments bank. Real deployments have O(S) live links,
    so shipping the k densest rows instead of the whole bank cuts the
    host transfer from ~20 MB to ~400 KB (the tunnel D2H was the entire
    dependencies-query p99). The caller must verify n_nonzero <= k and
    fall back to the full bank otherwise — compaction never silently
    drops a link."""
    counts = bank[:, 0]
    nz = (counts > 0).sum(dtype=jnp.int32)
    _, idx = jax.lax.top_k(counts, k)
    return nz, idx.astype(jnp.int32), bank[idx]


@partial(jax.jit, static_argnums=(3,))
def total_dep_moments_compact(dep_moments, dep_banks, dep_window,
                              k: int):
    """total_dep_moments fused with _compact_bank in one launch."""
    return _compact_bank(
        _total_dep_impl.__wrapped__(dep_moments, dep_banks, dep_window),
        k,
    )


@partial(jax.jit, static_argnums=(8,))
def dep_in_range_compact(dep_moments, dep_banks, dep_bank_ts,
                         dep_overflow_ts, dep_window, dep_window_ts,
                         start_ts, end_ts, k: int):
    """dep_moments_in_range fused with _compact_bank in one launch."""
    return _compact_bank(
        _dep_in_range_impl.__wrapped__(
            dep_moments, dep_banks, dep_bank_ts, dep_overflow_ts,
            dep_window, dep_window_ts, start_ts, end_ts,
        ),
        k,
    )


def dep_moments_in_range(state: "StoreState", start_ts, end_ts):
    """Link Moments restricted to banks (and the open window) whose
    children's ts range overlaps [start_ts, end_ts] — the device answer
    to Aggregates.getDependencies(startDate, endDate)
    (Aggregates.scala:26-31). Bucket-granular: a bank overlapping the
    window contributes whole (the reference's hourly Dependencies rows
    are equally coarse, Dependencies.scala:59-67)."""
    return _dep_in_range_impl(
        state.dep_moments, state.dep_banks, state.dep_bank_ts,
        state.dep_overflow_ts, state.dep_window, state.dep_window_ts,
        start_ts, end_ts,
    )


# ---------------------------------------------------------------------------
# ingest_step — ONE fused launch per batch
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0,))
def ingest_step(state: StoreState, b: DeviceBatch) -> StoreState:
    c = state.config
    S = c.max_services
    P = b.trace_id.shape[0]
    PA = b.ann_ts.shape[0]
    PB = b.bann_key_id.shape[0]

    # The ring writes assert unique_indices to XLA (duplicate slots
    # would be silent state corruption, not just nondeterminism). The
    # uniqueness invariant is on VALID rows only — n_spans <= capacity,
    # n_anns <= ann_capacity, n_banns <= bann_capacity, pending count
    # <= pending_slots — which are dynamic values the host chunker
    # enforces per batch (TpuSpanStore.write_batch raises on violation,
    # store/tpu.py). Padded rows past the valid count are remapped to
    # DISTINCT out-of-bounds slots by _uset, so P itself may exceed the
    # ring (tiny-ring tests pad well past capacity).
    mask = jnp.arange(P) < b.n_spans
    mask_a = jnp.arange(PA) < b.n_anns
    mask_b = jnp.arange(PB) < b.n_banns

    # -- span ring/page writes -----------------------------------------
    # Ring: consecutive slots mod capacity are unique within a batch
    # (P <= capacity, enforced by the host chunkers). Paged (r19): the
    # host PagePlanner pre-assigned each span a (slot, epoch-encoded
    # gid) pair with gid = page_epoch * capacity + slot — slots are
    # unique among valid rows by construction (pages fill
    # monotonically, pages are distinct), and slot == gid % capacity
    # still holds, so every liveness check downstream is layout-blind.
    # Either way the column writes ride the fast unique plane scatter
    # (_uset).
    if c.paged_enabled:
        R = c.page_rows
        RC = b.reclaim_page.shape[0]
        # Invalidate every row of the pages this unit reclaims BEFORE
        # the batch writes land (the functional update chain fixes the
        # order): the planner spliced these pages out of their owners'
        # chains, and a stale row_gid would keep the old spans visible
        # to the ring-scan kernels but not the page gather. The
        # reclaimed rows were captured host-side before this launch
        # (TpuSpanStore._capture_pages), so the captured-before-
        # overwrite invariant holds per page.
        r_slots = (
            b.reclaim_page[:, None] * R
            + jnp.arange(R, dtype=jnp.int32)[None, :]
        ).reshape(-1)
        r_ok = jnp.repeat(b.reclaim_page >= 0, R)
        row_gid0 = _uset(
            state.row_gid, r_slots, jnp.full(RC * R, -1, jnp.int64),
            r_ok,
        )
        gids = b.span_gid
        slots = b.span_slot
    else:
        row_gid0 = state.row_gid
        gids = state.write_pos + jnp.arange(P, dtype=jnp.int64)
        slots = (gids % c.capacity).astype(jnp.int32)
    upd = {}
    for col in (
        "trace_id", "span_id", "parent_id", "name_id", "name_lc_id",
        "service_id", "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first",
        "ts_last", "duration", "flags", "indexable",
    ):
        upd[col] = _uset(getattr(state, col), slots, getattr(b, col),
                         mask)
    upd["row_gid"] = _uset(row_gid0, slots, gids, mask)
    upd["write_pos"] = state.write_pos + b.n_spans.astype(jnp.int64)

    # -- annotation ring writes ----------------------------------------
    # Annotation/binary rings stay FIFO under BOTH layouts (ann rows
    # have no pages; their liveness rides the owning span's gid via
    # _span_slot), so ring-age ordering and the _iq freshness gates
    # keep working unchanged in paged mode.
    a_gids = state.ann_write_pos + jnp.arange(PA, dtype=jnp.int64)
    a_slots = (a_gids % c.ann_capacity).astype(jnp.int32)
    if c.paged_enabled:
        span_gid_of_ann = gids[b.ann_span_idx]
    else:
        span_gid_of_ann = state.write_pos + b.ann_span_idx.astype(jnp.int64)
    upd["ann_gid"] = _uset(
        state.ann_gid, a_slots, jnp.where(mask_a, span_gid_of_ann, -1),
        mask_a,
    )
    for col in ("ann_ts", "ann_value_id", "ann_service_id", "ann_endpoint_id"):
        upd[col] = _uset(getattr(state, col), a_slots, getattr(b, col),
                         mask_a)
    upd["ann_write_pos"] = state.ann_write_pos + b.n_anns.astype(jnp.int64)

    bb_gids = state.bann_write_pos + jnp.arange(PB, dtype=jnp.int64)
    bb_slots = (bb_gids % c.bann_capacity).astype(jnp.int32)
    if c.paged_enabled:
        span_gid_of_bann = gids[b.bann_span_idx]
    else:
        span_gid_of_bann = state.write_pos + b.bann_span_idx.astype(jnp.int64)
    upd["bann_gid"] = _uset(
        state.bann_gid, bb_slots,
        jnp.where(mask_b, span_gid_of_bann, -1), mask_b,
    )
    for col in (
        "bann_key_id", "bann_value_id", "bann_type", "bann_service_id",
        "bann_endpoint_id",
    ):
        upd[col] = _uset(getattr(state, col), bb_slots, getattr(b, col),
                         mask_b)
    upd["bann_write_pos"] = state.bann_write_pos + b.n_banns.astype(jnp.int64)

    # -- streaming dependency join -------------------------------------
    # Insert this batch's spans into the hash table FIRST so same-batch
    # parents resolve immediately, then probe each child for its parent
    # (ZipkinAggregateJob.scala:26-38 as a streaming hash join; r2's
    # O(ring) sort-join cost seconds per pass at scale, NOTES_r03.md).
    skey = _mix48(b.trace_id, b.span_id)
    tab = _tab_insert(state.span_tab, skey, b.service_id, mask)
    upd["span_tab"] = tab
    resolved, link_id, pending, ckey = _resolve_links(
        tab, b.trace_id, b.span_id, b.parent_id, b.service_id,
        b.service_id, b.duration, mask, mask & b.has_parent, S,
    )
    upd["dep_window"], upd["dep_window_ts"] = _window_fold(
        state.dep_window, state.dep_window_ts, b.duration, link_id,
        resolved, b.ts_first, b.ts_last, S,
    )
    # Children whose parent hasn't arrived yet wait in the pending ring
    # (re-probed by dep_sweep); the ring overwrites oldest-first, the
    # bounded-wait analogue of the reference's index TTL.
    Qp = state.pend_key.shape[0]
    rank = jnp.cumsum(pending.astype(jnp.int64)) - 1
    pslot = ((state.pend_pos + rank) % Qp).astype(jnp.int32)
    upd["pend_key"] = _uset(state.pend_key, pslot,
                            _tab_pack(ckey, b.service_id), pending)
    upd["pend_dur"] = _uset(state.pend_dur, pslot, b.duration, pending)
    upd["pend_tsf"] = _uset(state.pend_tsf, pslot, b.ts_first, pending)
    upd["pend_tsl"] = _uset(state.pend_tsl, pslot, b.ts_last, pending)
    upd["pend_pos"] = state.pend_pos + pending.sum(dtype=jnp.int64)

    # -- index column families -----------------------------------------
    # (written before the counter block; the ann-derived columns below
    # are shared with the presence/top-annotation updates further down)
    n_key_drops = jnp.int64(0)
    if c.use_index:
        lay, _, _ = c.idx_layout
        # Coarse-war granularity for ALL the gid watermarks in this
        # step (ann_poison, key_wm, the trace-segment wm): overstate by at most
        # capacity / 2^_WM_COARSE_FRAC_BITS — a sub-percent slice of
        # each gate's >= 1-ring trust margin (gates trust iff
        # wm < write_pos - capacity, and displaced entries are
        # ring-laps old whenever a gate is consulted in steady state).
        wm_shift = max(0, c.capacity.bit_length() - 1
                       - _WM_COARSE_FRAC_BITS)
        a_host = b.ann_service_id
        a_idx_ok = mask_a & (a_host >= 0) & (a_host < S)
        gid_a = jnp.where(a_idx_ok, span_gid_of_ann, -1)
        ts_a = b.ts_last[b.ann_span_idx]

        def seg(fam, local_bucket, gid, verify, ts, ok):
            """One concatenation segment of the combined write: global
            bucket, first-slot row, depth vectors + the entry payload.
            The service family is not per-key-tracked (its bucket IS the
            key — no aliasing — and its verify words are raw service ids
            whose key48 would all collide); it MUST stay the first
            segment — _index_write takes the keyed families as the
            suffix from ``keyed_from``."""
            b_base, s_base, n_b, depth = lay[fam]
            lb = jnp.clip(local_bucket, 0, n_b - 1)
            n = lb.shape[0]
            return fam, (
                lb.astype(jnp.int32) + jnp.int32(b_base),
                lb.astype(jnp.int64) * depth + jnp.int64(s_base),
                jnp.full(n, depth, jnp.int32),
                jnp.asarray(gid, jnp.int64),
                jnp.asarray(verify, jnp.int64),
                jnp.asarray(ts, jnp.int64),
                ok,
            )

        segments = []
        # Service family: bucket = the annotation's own host service —
        # exactly the rows the scan kernel matches for a service query.
        segments.append(seg(
            StoreConfig.CAND_SVC, a_host, gid_a, a_host, ts_a, a_idx_ok
        ))
        # (service, span name) family.
        ann_name_lc_i = b.name_lc_id[b.ann_span_idx]
        nm_ok = a_idx_ok & (ann_name_lc_i >= 0)
        nm_mix = _mixb([a_host, ann_name_lc_i])
        segments.append(seg(
            StoreConfig.CAND_NAME, _bucket_of(nm_mix, c.name_buckets),
            gid_a, _verify_of(nm_mix), ts_a, nm_ok,
        ))
        # (service, annotation value) family: a span's value can match a
        # query under ANY of its hosts (per-slot semantics of the scan /
        # the in-memory oracle), so entries are written under the span's
        # host-set (min, max) pair. Core annotations are never queryable
        # (SpanStore.scala:199) and are skipped.
        hmin, hmax = _span_host_range(a_host, b.ann_span_idx, a_idx_ok, P)
        h1 = hmin[b.ann_span_idx]
        h2 = hmax[b.ann_span_idx]
        # A 3+-distinct-host span is indexed under (min, max) only: its
        # MIDDLE hosts' annotation-family buckets could claim complete
        # answers that silently omit it. Record the span's gid against
        # each middle host; queries for that service distrust the
        # annotation fast paths until the span is evicted (see
        # StoreState.ann_poison).
        mid = a_idx_ok & (a_host != h1) & (a_host != h2)
        v_ok = (
            mask_a & (b.ann_value_id >= FIRST_USER_ANNOTATION_ID)
            & (b.ann_value_id < jnp.int32(1 << 30))
        )
        for h, extra in ((h1, None), (h2, h2 != h1)):
            ok = v_ok & (h >= 0) & (h < S)
            if extra is not None:
                ok &= extra
            mix = _mixb([h, b.ann_value_id])
            segments.append(seg(
                StoreConfig.CAND_ANN, _bucket_of(mix, c.ann_buckets),
                jnp.where(ok, span_gid_of_ann, -1), _verify_of(mix),
                ts_a, ok,
            ))
        # (service, binary key[, value]) family: two bucket keyings per
        # host — with the value (valued queries) and with a -1 sentinel
        # (key-only queries) — under the span's host-set pair.
        bh1 = hmin[b.bann_span_idx]
        bh2 = hmax[b.bann_span_idx]
        bk_idx_ok = mask_b & (b.bann_key_id >= 0)
        ts_b = b.ts_last[b.bann_span_idx]
        no_val = jnp.full(PB, -1, jnp.int32)
        for h, val, extra in (
            (bh1, b.bann_value_id, None), (bh2, b.bann_value_id, bh2 != bh1),
            (bh1, no_val, None), (bh2, no_val, bh2 != bh1),
        ):
            ok = bk_idx_ok & (h >= 0) & (h < S)
            if extra is not None:
                ok &= extra
            mix = _mixb([h, b.bann_key_id, val])
            segments.append(seg(
                StoreConfig.CAND_BANN, _bucket_of(mix, c.bann_buckets),
                jnp.where(ok, span_gid_of_bann, -1), _verify_of(mix),
                ts_b, ok,
            ))
        # keyed_from depends on the un-keyed SVC family being the SINGLE
        # leading segment; a reorder would silently poison the key table
        # (service verify words all collide in key48 space) — assert the
        # invariant structurally, at trace time.
        fams = [f for f, _ in segments]
        assert (fams[0] == StoreConfig.CAND_SVC
                and StoreConfig.CAND_SVC not in fams[1:]), fams
        n_cand_rows = sum(p[0].shape[0] for _, p in segments)
        # Trace-membership families trail the candidate segments in the
        # SAME unified concatenation: row gids bucketed by trace-id
        # hash, one sub-family per ring (whole-trace fetch + durations).
        # Verify carries the trace mix, ts the row's last_ts — the
        # arena rows are uniform (gid, verify, ts) triples.
        tb = _bucket_of(_mixb([b.trace_id]), c.trace_buckets)
        tmix = _verify_of(_mixb([b.trace_id]))
        NC = StoreConfig.N_CAND_FAMILIES
        segments.append(seg(
            NC + StoreConfig.TR_SPAN, tb, gids, tmix, b.ts_last, mask
        ))
        segments.append(seg(
            NC + StoreConfig.TR_ANN, tb[b.ann_span_idx], a_gids,
            tmix[b.ann_span_idx], ts_a, mask_a,
        ))
        segments.append(seg(
            NC + StoreConfig.TR_BANN, tb[b.bann_span_idx], bb_gids,
            tmix[b.bann_span_idx], b.ts_last[b.bann_span_idx], mask_b,
        ))
        cat = [jnp.concatenate(parts)
               for parts in zip(*(p for _, p in segments))]
        # Static per-shape path decisions (r12), recorded at trace time
        # so counters()/bench can report which kernels a config's
        # compiled steps actually used. Both rank paths are bitwise-
        # identical, so a mixed-shape store (different pad buckets
        # picking different modes) still lands one deterministic state.
        from zipkin_tpu.ops import pallas_kernels as PK

        rank_sel = rank_mode(
            c.rank_path, cat[0].shape[0], c.idx_layout[1], wm_shift)
        scatter_mode = (
            "pallas"
            if c.use_pallas and PK.arena_scatter_supported(
                c.idx_layout[2], c.idx_layout[1])
            else "xla"
        )
        _note_path(c, "rank", rank_sel[0])
        _note_path(c, "scatter", scatter_mode)
        (upd["cand_idx"], upd["cand_pos"], upd["cand_wm"],
         upd["key_tab"], upd["key_wm"], upd["ann_poison"],
         n_key_drops) = _index_write(
            state.cand_idx, state.cand_pos, state.cand_wm,
            state.key_tab, state.key_wm, state.ann_poison, *cat,
            keyed_from=segments[0][1][0].shape[0],
            n_cand_rows=n_cand_rows,
            n_cand_buckets=c.cand_layout[1],
            poison_bucket=a_host, poison_gid=span_gid_of_ann,
            poison_ok=mid,
            wm_shift=wm_shift,
            rank_sel=rank_sel, scatter_mode=scatter_mode,
        )

    # -- per-service latency histogram ---------------------------------
    hist = svc_histogram(state)
    svc_ok = mask & (b.service_id >= 0) & (b.service_id < S) & (b.duration >= 0)
    bidx = Q.bucket_index(hist, b.duration.astype(jnp.float32))
    g = jnp.clip(b.service_id, 0, S - 1)
    ones_p = jnp.ones(P, jnp.int32)
    ones_a = jnp.ones(PA, jnp.int32)
    upd["svc_hist"] = _scatter_add(
        state.svc_hist,
        jnp.where(svc_ok, g * c.quantile_buckets + bidx, -1),
        ones_p, c.use_pallas,
    )

    # -- counters / presence matrices ----------------------------------
    svc_cnt_ok = mask & (b.service_id >= 0) & (b.service_id < S)
    upd["svc_span_counts"] = _scatter_add(
        state.svc_span_counts, jnp.where(svc_cnt_ok, b.service_id, -1),
        ones_p, c.use_pallas,
    )
    a_svc = b.ann_service_id
    a_svc_ok = mask_a & (a_svc >= 0) & (a_svc < S)
    upd["ann_svc_counts"] = _scatter_add(
        state.ann_svc_counts, jnp.where(a_svc_ok, a_svc, -1),
        ones_a, c.use_pallas,
    )

    # span-name presence keyed by annotation-host service (the semantics
    # of getSpanNames: names of indexed spans for a service).
    ann_name = b.name_id[b.ann_span_idx]  # batch-local gather
    ann_name_lc = b.name_lc_id[b.ann_span_idx]
    ann_indexable = b.indexable[b.ann_span_idx]
    np_ok = (
        a_svc_ok & ann_indexable
        & (ann_name_lc >= 0) & (ann_name >= 0) & (ann_name < c.max_span_names)
    )
    upd["name_presence"] = _scatter_add(
        state.name_presence,
        jnp.where(np_ok, a_svc * c.max_span_names + ann_name, -1),
        ones_a, c.use_pallas,
    )

    # top annotations per service (user annotations only).
    av_ok = (
        a_svc_ok
        & (b.ann_value_id >= FIRST_USER_ANNOTATION_ID)
        & (b.ann_value_id < c.max_annotation_values)
    )
    upd["ann_value_counts"] = _scatter_add(
        state.ann_value_counts,
        jnp.where(av_ok, a_svc * c.max_annotation_values + b.ann_value_id, -1),
        ones_a, c.use_pallas,
    )

    bk_svc = b.bann_service_id
    bk_ok = (
        mask_b & (bk_svc >= 0) & (bk_svc < S)
        & (b.bann_key_id >= 0) & (b.bann_key_id < c.max_binary_keys)
    )
    upd["bann_key_counts"] = _scatter_add(
        state.bann_key_counts,
        jnp.where(bk_ok, bk_svc * c.max_binary_keys + b.bann_key_id, -1),
        jnp.ones(PB, jnp.int32), c.use_pallas,
    )

    # -- probabilistic state -------------------------------------------
    t_hi, t_lo = dev_split64(b.trace_id)
    upd["hll_traces"] = hll.update(
        hll.HyperLogLog(state.hll_traces), t_hi, t_lo, valid=mask
    ).registers
    cms_sketch = cms.CountMin(state.cms_trace_spans)
    cms_idx = cms._indices(cms_sketch, t_hi, t_lo)  # [depth, P]
    cms_flat = cms_idx + (
        jnp.arange(c.cms_depth, dtype=jnp.int32) * c.cms_width
    )[:, None]
    cms_flat = jnp.where(mask[None, :], cms_flat, -1).reshape(-1)
    upd["cms_trace_spans"] = _scatter_add(
        state.cms_trace_spans, cms_flat,
        jnp.ones(c.cms_depth * P, jnp.int32), c.use_pallas,
    )

    # -- windowed Moments-sketch arena ---------------------------------
    # (service × ring-indexed time bucket) integer cells; the host
    # mirror folds the SAME rows in numpy (aggregate.windows
    # apply_window_update) — every op here is an integer add/max so the
    # two agree bitwise regardless of accumulation order. Budget: +5
    # scatters (+1 of them the serialized i64 class, 4P rows), +2
    # gathers, 0 sorts — the store/census.py r13 bump.
    if c.window_enabled:
        Wn = c.win_slots
        w_ok = mask & (b.service_id >= 0) & (b.service_id < S) \
            & (b.ts_first >= 0)
        a_bkt = jnp.where(w_ok, b.ts_first, 0) // jnp.int64(c.window_us)
        slot = (a_bkt % Wn).astype(jnp.int32)
        slot = jnp.where(w_ok, slot, 0)
        # Epoch war: each touched slot advances to the max absolute
        # bucket offered this step; rows older than the winner (stale
        # lates, or the losers of an in-batch ring wrap) are dropped.
        new_epoch = _war_max64(state.win_epoch, slot, a_bkt, w_ok)
        upd["win_epoch"] = new_epoch
        stale = (new_epoch != state.win_epoch)[None, :, None]
        counts_w = jnp.where(stale, jnp.int32(0), state.win_counts)
        sums_w = jnp.where(stale, jnp.int64(0), state.win_sums)
        mm_w = jnp.where(stale, I32_MIN, state.win_mm)
        live = w_ok & (a_bkt == new_epoch[slot])
        cid = g * Wn + slot  # g = clip(service_id) — valid where live
        d_ok = live & (b.duration >= 0)
        x = (bidx >> c.win_x_shift).astype(jnp.int32)
        base3 = cid * 3
        idx_c = jnp.concatenate([
            jnp.where(live, base3, -1),
            jnp.where(live & b.error_flag, base3 + 1, -1),
            jnp.where(d_ok, base3 + 2, -1),
        ])
        upd["win_counts"] = _scatter_add(
            counts_w, idx_c, jnp.ones(3 * P, jnp.int32), c.use_pallas
        )
        flat_s = sums_w.reshape(-1)
        xi = x.astype(jnp.int64)
        base4 = cid * 4
        idx_s = jnp.concatenate([base4, base4 + 1, base4 + 2,
                                 base4 + 3])
        safe_s = jnp.where(jnp.tile(d_ok, 4), idx_s, flat_s.shape[0])
        vals_s = jnp.concatenate([xi, xi * xi, xi * xi * xi,
                                  xi * xi * xi * xi])
        upd["win_sums"] = flat_s.at[safe_s].add(
            vals_s, mode="drop").reshape(sums_w.shape)
        flat_m = mm_w.reshape(-1)
        base2 = cid * 2
        idx_m = jnp.concatenate([base2, base2 + 1])
        safe_m = jnp.where(jnp.tile(d_ok, 2), idx_m, flat_m.shape[0])
        vals_m = jnp.concatenate([-x, x])
        upd["win_mm"] = flat_m.at[safe_m].max(
            vals_m, mode="drop").reshape(mm_w.shape)

    # -- time range + counters -----------------------------------------
    firsts = jnp.where(mask & (b.ts_first >= 0), b.ts_first, I64_MAX)
    lasts = jnp.where(mask & (b.ts_last >= 0), b.ts_last, I64_MIN)
    upd["ts_min"] = jnp.minimum(state.ts_min, firsts.min())
    upd["ts_max"] = jnp.maximum(state.ts_max, lasts.max())
    # Spread-then-update: counters the step doesn't touch (sweeps)
    # must carry through, not silently reset to absent.
    upd["counters"] = {
        **state.counters,
        "spans_seen": state.counters["spans_seen"] + b.n_spans,
        "anns_seen": state.counters["anns_seen"] + b.n_anns,
        "banns_seen": state.counters["banns_seen"] + b.n_banns,
        "batches": state.counters["batches"] + 1,
        "key_claim_drops": state.counters["key_claim_drops"]
        + n_key_drops,
    }

    return state.replace(**upd)


@partial(jax.jit, donate_argnums=(0,))
def ingest_steps(state: StoreState, stacked: DeviceBatch) -> StoreState:
    """Chained ingest: run one fused step per leading-axis slice of
    ``stacked`` (a DeviceBatch whose every array carries a [k, ...]
    batch axis) inside a single jitted launch.

    On this backend one jitted CALL costs ~90-110 ms of dispatch
    regardless of work, while a ``lax.scan`` iteration costs ~5-7 ms
    (NOTES_r03.md §3) — so landing k batches per launch divides the
    per-batch dispatch floor by ~k. This is the device analogue of the
    reference collector draining several ItemQueue items per worker
    wake-up (ItemQueue.scala:39): amortize the fixed per-dispatch cost
    over many queued batches. Chunk boundaries, ring-capacity guards,
    and the sweep cadence are the CALLER's job, exactly as for
    ingest_step; every slice must satisfy the same capacity bounds."""
    state, _ = jax.lax.scan(
        lambda st, db: (ingest_step.__wrapped__(st, db), None),
        state, stacked,
    )
    return state


def stack_device_batches(dbs) -> DeviceBatch:
    """Stack equal-shape DeviceBatches along a new leading axis for
    ingest_steps (host-side; numpy arrays in, one stacked batch out)."""
    import numpy as np

    return DeviceBatch(*(
        np.stack([np.asarray(getattr(db, f)) for db in dbs])
        for f in DeviceBatch._fields
    ))


# ---------------------------------------------------------------------------
# Query kernels
# ---------------------------------------------------------------------------


def _span_slot(gid, row_gid, capacity: int):
    """Per annotation/binary ring row: (owning span's ring slot,
    row-still-live mask). Liveness = the span row at the slot still
    carries the gid this annotation was written under."""
    slot = jnp.clip((gid % capacity).astype(jnp.int32), 0, capacity - 1)
    return slot, (gid >= 0) & (row_gid[slot] == gid)


def _topk_candidates(tid, ts, valid, k: int):
    """Top-``k`` candidate rows by ts desc (validity folded into the
    key; valid rows have ts >= 0 by construction). Returns ONE stacked
    [3, k] i64 array (tid, ts, ok).

    Callers dedup candidates by trace id on the host
    (store.base.dedup_rank_limit) and re-query with a bigger ``k`` when
    the window may have truncated a hot trace's spans — the top-k
    primitive compiles in seconds where a full multi-key ring sort
    compiles for minutes at 2^23 rows on TPU, and executes in ~1ms.
    The escalation is exact: every trace missing from the candidate set
    has its best span below ALL k candidates, so any ``limit`` distinct
    traces found rank strictly above every excluded trace.
    """
    key = jnp.where(valid, ts, jnp.int64(-1))
    vals, idx = jax.lax.top_k(key, k)
    return jnp.stack([tid[idx], ts[idx], (vals >= 0).astype(jnp.int64)])


@partial(jax.jit, static_argnums=(7, 8))
def _q_by_service_impl(
    ann_gid, ann_service_id, row_gid, indexable, name_lc_col, trace_id,
    ts_last, capacity: int, k: int, svc_id, name_lc_id, end_ts,
):
    slot, live = _span_slot(ann_gid, row_gid, capacity)
    ok = live & (ann_service_id == svc_id)
    ok &= indexable[slot]
    ok &= (name_lc_id < 0) | (name_lc_col[slot] == name_lc_id)
    ts = ts_last[slot]
    ok &= (ts >= 0) & (ts <= end_ts)
    return _topk_candidates(trace_id[slot], ts, ok, k)


def query_trace_ids_by_service(
    state: StoreState, svc_id, name_lc_id, end_ts, k: int
):
    """Candidate spans of a service (any annotation host), optional
    span-name match, last_ts <= end_ts, top ``k`` by last_ts desc.

    Reference semantics: getTraceIdsByName (SpanStore.scala /
    CassieSpanStore.scala:366) with index ts = span last timestamp.
    Returns ONE stacked [3, k] i64 candidate array (see
    _topk_candidates). The jitted impl takes ONLY the seven columns it
    reads — tunneled devices charge per argument buffer per dispatch,
    and passing the whole 40-leaf state pytree made every index query
    pay ~0.8s of pure argument overhead.
    """
    return _q_by_service_impl(
        state.ann_gid, state.ann_service_id, state.row_gid,
        state.indexable, state.name_lc_id, state.trace_id, state.ts_last,
        state.config.capacity, k, svc_id, name_lc_id, end_ts,
    )


@partial(jax.jit, static_argnums=(10, 11))
def _q_by_annotation_impl(
    ann_gid, ann_service_id, ann_value_col, row_gid, indexable, ts_last,
    trace_id, bann_gid, bann_key_col, bann_value_col,
    capacity: int, k: int,
    svc_id, ann_value_id, bann_key_id, bann_value_id, bann_value_id2,
    end_ts,
):
    a_slot, a_live = _span_slot(ann_gid, row_gid, capacity)
    # Build: which span slots have an annotation hosted by svc_id.
    hit = a_live & (ann_service_id == svc_id)
    # i32 max instead of a bool scatter-set: bool scatters serialize on
    # this backend (ann-ring-sized rows), i32 dup-index max vectorizes.
    per_slot = jnp.zeros(capacity + 1, jnp.int32).at[
        jnp.where(hit, a_slot, capacity)
    ].max(hit.astype(jnp.int32), mode="drop")[:-1] > 0

    a_ok = (
        a_live
        & (ann_value_col == ann_value_id) & (ann_value_id >= 0)
        & indexable[a_slot]
        & per_slot[a_slot]
    )
    a_ts = ts_last[a_slot]
    a_ok &= (a_ts >= 0) & (a_ts <= end_ts)

    b_slot, b_live = _span_slot(bann_gid, row_gid, capacity)
    value_free = (bann_value_id < 0) & (bann_value_id2 < 0)
    value_hit = (
        ((bann_value_id >= 0) & (bann_value_col == bann_value_id))
        | ((bann_value_id2 >= 0) & (bann_value_col == bann_value_id2))
    )
    b_ok = (
        b_live
        & (bann_key_col == bann_key_id) & (bann_key_id >= 0)
        & (value_free | value_hit)
        & indexable[b_slot]
        & per_slot[b_slot]
    )
    b_ts = ts_last[b_slot]
    b_ok &= (b_ts >= 0) & (b_ts <= end_ts)

    tid = jnp.concatenate([trace_id[a_slot], trace_id[b_slot]])
    ts = jnp.concatenate([a_ts, b_ts])
    ok = jnp.concatenate([a_ok, b_ok])
    return _topk_candidates(tid, ts, ok, k)


def query_trace_ids_by_annotation(
    state: StoreState, svc_id, ann_value_id, bann_key_id, bann_value_id,
    bann_value_id2, end_ts, k: int,
):
    """Annotation-index query (CassieSpanStore AnnotationsIndex semantics).

    Matches spans of ``svc_id`` that carry the user annotation
    ``ann_value_id``, OR a binary annotation with ``bann_key_id``
    (and one of ``bann_value_id``/``bann_value_id2`` if >= 0 — two slots
    because the host dictionary may hold a value in both str and bytes
    form). Pass -1 to disable either side. The jitted impl takes only
    the ten columns it reads (see query_trace_ids_by_service).
    """
    return _q_by_annotation_impl(
        state.ann_gid, state.ann_service_id, state.ann_value_id,
        state.row_gid, state.indexable, state.ts_last, state.trace_id,
        state.bann_gid, state.bann_key_id, state.bann_value_id,
        state.config.capacity, k,
        svc_id, ann_value_id, bann_key_id, bann_value_id, bann_value_id2,
        end_ts,
    )


# -- index fast-path query kernels ------------------------------------------


def _iq_finish(entries, cnt, wm, row_gid, indexable, ts_last, trace_id,
               extra_ok, capacity: int, depth: int, k: int, end_ts):
    """Shared tail: entry liveness via the gid round-trip, span-level
    filters from the ring, top-k by ts. ``complete`` is True when no
    probed bucket ever wrapped — then the candidate set provably holds
    every matching span still resident, and the host can skip the
    O(ring) scan fallback. For wrapped buckets the returned watermark
    lets the host decide trust per query (store.base.index_first_topk)."""
    gid = entries[:, 0]
    slot = jnp.clip((gid % capacity).astype(jnp.int32), 0, capacity - 1)
    live = (gid >= 0) & (row_gid[slot] == gid)
    ok = live & indexable[slot] & extra_ok
    ts = ts_last[slot]
    ok &= (ts >= 0) & (ts <= end_ts)
    mat = _topk_candidates(trace_id[slot], ts, ok, k)
    return mat, cnt <= depth, wm


@partial(jax.jit, static_argnums=(7, 8, 9))
def _iq_service_impl(entries, pos, wm, row_gid, indexable, trace_id,
                     ts_last, capacity: int, layout, k: int,
                     svc, end_ts):
    # Span-name-filtered lookups route through the (service, name)
    # family (_iq_verify_impl), never through this bucket.
    b_base, s_base, n_b, depth = layout
    svc_i = jnp.clip(jnp.asarray(svc, jnp.int32), 0, n_b - 1)
    row = jax.lax.dynamic_slice(
        entries, (jnp.int32(s_base) + svc_i * depth, jnp.int32(0)),
        (depth, 3),
    )
    gb = jnp.int32(b_base) + svc_i
    ok = jnp.ones(depth, bool)
    return _iq_finish(row, pos[gb], wm[gb], row_gid, indexable, ts_last,
                      trace_id, ok, capacity, depth, k, end_ts)


def _key_lookup_wm(key_tab, key_wm, mixed):
    """Per-key record lookup (see StoreState.key_tab): (record found,
    max displaced gid) for the query key's verify word. Works on scalar
    or [N]-vector ``mixed``. Fingerprint matches may alias a different
    key's record — then the returned watermark is the shared (merged)
    one, which can only be LARGER than the key's true watermark:
    conservative for the completeness gate, and still sound for the
    negative gate (an indexed key's probes always find its fp record,
    or a drop was counted)."""
    T = key_tab.shape[0]
    k48 = mixed >> jnp.uint64(16)
    fp = _fp31(k48)
    found = jnp.zeros(jnp.shape(k48), bool)
    wmv = jnp.full(jnp.shape(k48), I64_MIN, jnp.int64)
    for slot in _tab_slots(k48, T)[:_KEY_PROBES]:
        hit = key_tab[slot] == fp
        wmv = jnp.where(hit & ~found, key_wm[slot], wmv)
        found |= hit
    return found, wmv


@partial(jax.jit, static_argnums=(7, 8, 9))
def _iq_verify_impl(entries, pos, wm, row_gid, indexable, trace_id,
                    ts_last, capacity: int, layout, k: int,
                    key_parts, end_ts, key_tab, key_wm, write_pos,
                    key_drops, poison=None):
    b_base, s_base, n_b, depth = layout
    mixed = _mixb(list(key_parts))
    lb = _bucket_of(mixed, n_b)
    row = jax.lax.dynamic_slice(
        entries, (jnp.int32(s_base) + lb * depth, jnp.int32(0)),
        (depth, 3),
    )
    gb = jnp.int32(b_base) + lb
    ver_ok = row[:, 1] == _verify_of(mixed)
    cnt, bwm = pos[gb], wm[gb]
    # Per-key completeness: every entry this key ever LOST from its
    # bucket is already evicted from the ring, so the verify-matched
    # window rows are the key's full resident entry set — exact even
    # when bucket-mates wrapped the bucket. Negative twin: while no
    # claim was ever dropped, an ABSENT record proves the key was never
    # indexed at all — the (empty) result is the true answer, the
    # reference's instant empty-row read.
    kfound, kwmv = _key_lookup_wm(key_tab, key_wm, mixed)
    key_complete = (kfound & (kwmv < write_pos - capacity)) | (
        ~kfound & (key_drops == 0)
    )
    if poison is not None:
        # Middle-host distrust (see StoreState.ann_poison): while a
        # 3+-distinct-host span with key_parts[0] as a middle host is
        # still resident, no completeness claim may be trusted — its
        # middle-host entries (and their key claims) were never
        # written, so even the absence proof doesn't hold.
        svc = jnp.clip(key_parts[0], 0, poison.shape[0] - 1)
        bad = poison[svc] >= write_pos - capacity
        cnt = jnp.where(bad, jnp.int64(depth + 1), cnt)
        bwm = jnp.where(bad, jnp.int64(I64_MAX), bwm)
        key_complete &= ~bad
    mat, complete, out_wm = _iq_finish(
        row, cnt, bwm, row_gid, indexable, ts_last, trace_id, ver_ok,
        capacity, depth, k, end_ts,
    )
    return mat, complete | key_complete, out_wm


@partial(jax.jit, static_argnums=(7, 8, 9))
def _iq_verify2_impl(entries, pos, wm, row_gid, indexable, trace_id,
                     ts_last, capacity: int, layout, k: int,
                     key_parts1, key_parts2, end_ts,
                     key_tab, key_wm, write_pos, key_drops,
                     poison=None):
    b_base, s_base, n_b, depth = layout
    m1 = _mixb(list(key_parts1))
    m2 = _mixb(list(key_parts2))
    lb1 = _bucket_of(m1, n_b)
    lb2 = _bucket_of(m2, n_b)
    r1 = jax.lax.dynamic_slice(
        entries, (jnp.int32(s_base) + lb1 * depth, jnp.int32(0)),
        (depth, 3),
    )
    r2 = jax.lax.dynamic_slice(
        entries, (jnp.int32(s_base) + lb2 * depth, jnp.int32(0)),
        (depth, 3),
    )
    row = jnp.concatenate([r1, r2])
    gb1 = jnp.int32(b_base) + lb1
    gb2 = jnp.int32(b_base) + lb2
    cnt = jnp.maximum(pos[gb1], pos[gb2])
    bwm = jnp.maximum(wm[gb1], wm[gb2])
    # Candidates span BOTH buckets, so per-key completeness needs both
    # keys' records to pass the displaced-gid gate.
    kf1, kw1 = _key_lookup_wm(key_tab, key_wm, m1)
    kf2, kw2 = _key_lookup_wm(key_tab, key_wm, m2)
    horizon = write_pos - capacity
    key_complete = (kf1 & kf2 & (kw1 < horizon) & (kw2 < horizon)) | (
        ~kf1 & ~kf2 & (key_drops == 0)
    )
    if poison is not None:
        svc = jnp.clip(key_parts1[0], 0, poison.shape[0] - 1)
        bad = poison[svc] >= horizon
        cnt = jnp.where(bad, jnp.int64(depth + 1), cnt)
        bwm = jnp.where(bad, jnp.int64(I64_MAX), bwm)
        key_complete &= ~bad
    ver_ok = (row[:, 1] == _verify_of(m1)) | (row[:, 1] == _verify_of(m2))
    mat, complete, out_wm = _iq_finish(
        row, cnt, bwm, row_gid, indexable, ts_last, trace_id, ver_ok,
        capacity, depth, k, end_ts,
    )
    return mat, complete | key_complete, out_wm


@partial(jax.jit, static_argnums=(7, 8, 9))
def _iq_multi_impl(entries, pos, wm, row_gid, indexable, trace_id,
                   ts_last, capacity: int, k: int, k_max: int,
                   b_base, s_base, n_b, depth,
                   key1, key2, key3, three, is_svc,
                   end_ts, poison_on, poison, write_pos,
                   key_tab, key_wm, key_drops):
    """N independent index-bucket probes in ONE launch.

    Every probe carries its own family geometry (b_base/s_base/n_b/
    depth, rows of config.cand_layout) and key parts as DATA, so one
    compiled kernel serves any mix of service / (service, span-name) /
    (service, annotation-value) / (service, binary-key[, value]) probes.
    On this backend a jitted call costs ~90-110 ms flat (NOTES_r03 §3);
    the reference pays one index read per slice of a query
    (ThriftQueryService.scala:166-196) — this folds all slices (and all
    queries of a batch) into a single dispatch. Returns ([N, 3, k]
    candidates, [N] complete, [N] watermark) with the same trust
    contract as _iq_verify_impl; ``k_max`` is the widest family depth
    (static pad for the per-probe bucket windows).

    - ``three``: probe keys are (key1, key2, key3) instead of (key1,
      key2) — the binary families mix three parts.
    - ``is_svc``: service-family probe; the bucket is key1 itself and
      entry verify words equal the host service id.
    - ``poison_on``: apply the middle-host ann_poison gate (see
      StoreState.ann_poison) with key1 as the service id.
    """
    m2 = _mixb([key1, key2])
    m3 = _mixb([key1, key2, key3])
    mixed = jnp.where(three, m3, m2)
    nb64 = n_b.astype(jnp.int64)
    lb = (mixed & (nb64 - 1).astype(jnp.uint64)).astype(jnp.int64)
    lb = jnp.where(is_svc, jnp.clip(key1.astype(jnp.int64), 0, nb64 - 1),
                   lb)
    gb = b_base + lb
    slot0 = s_base + lb * depth.astype(jnp.int64)
    rows = jnp.arange(k_max, dtype=jnp.int64)[None, :]
    valid_row = rows < depth[:, None]
    idx = jnp.where(valid_row, slot0[:, None] + rows, entries.shape[0])
    eg = entries[jnp.clip(idx, 0, entries.shape[0] - 1)]  # [N, Kmax, 3]
    exp_ver = jnp.where(is_svc, key1.astype(jnp.int64), _verify_of(mixed))
    ver_ok = valid_row & (eg[:, :, 1] == exp_ver[:, None])
    gid = eg[:, :, 0]
    slot = jnp.clip((gid % capacity).astype(jnp.int32), 0, capacity - 1)
    live = (gid >= 0) & (row_gid[slot] == gid)
    ok = live & indexable[slot] & ver_ok
    ts = ts_last[slot]
    ok &= (ts >= 0) & (ts <= end_ts[:, None])
    mat = jax.vmap(
        lambda t, s, o: _topk_candidates(t, s, o, k)
    )(trace_id[slot], ts, ok)
    cnt = pos[jnp.clip(gb, 0, pos.shape[0] - 1)]
    wmv = wm[jnp.clip(gb, 0, wm.shape[0] - 1)]
    horizon = write_pos - capacity
    bad = poison_on & (
        poison[jnp.clip(key1, 0, poison.shape[0] - 1)] >= horizon
    )
    cnt = jnp.where(bad, depth.astype(jnp.int64) + 1, cnt)
    wmv = jnp.where(bad, jnp.int64(I64_MAX), wmv)
    kfound, kwmv = _key_lookup_wm(key_tab, key_wm, mixed)
    key_complete = ~is_svc & ~bad & (
        (kfound & (kwmv < horizon))
        | (~kfound & (key_drops == 0))
    )
    return mat, (cnt <= depth) | key_complete, wmv


def iquery_trace_ids_multi(state: StoreState, probes, k: int):
    """Host wrapper for _iq_multi_impl: ``probes`` is a dict of equal-
    length numpy arrays (keys matching the kernel's probe operands).
    Returns device results ([N, 3, k], [N] complete, [N] wm)."""
    c = state.config
    k_max = max(fam[3] for fam in c.cand_layout[0])
    k = min(k, k_max)
    return _iq_multi_impl(
        state.cand_idx, state.cand_pos, state.cand_wm, state.row_gid,
        state.indexable, state.trace_id, state.ts_last,
        c.capacity, k, k_max,
        jnp.asarray(probes["b_base"], jnp.int64),
        jnp.asarray(probes["s_base"], jnp.int64),
        jnp.asarray(probes["n_b"], jnp.int64),
        jnp.asarray(probes["depth"], jnp.int64),
        jnp.asarray(probes["key1"], jnp.int32),
        jnp.asarray(probes["key2"], jnp.int32),
        jnp.asarray(probes["key3"], jnp.int32),
        jnp.asarray(probes["three"], bool),
        jnp.asarray(probes["is_svc"], bool),
        jnp.asarray(probes["end_ts"], jnp.int64),
        jnp.asarray(probes["poison_on"], bool),
        state.ann_poison, state.write_pos,
        state.key_tab, state.key_wm,
        state.counters["key_claim_drops"],
    )


def iquery_trace_ids_by_service(state: StoreState, svc_id, name_lc_id,
                                end_ts, k: int):
    """Index fast path for getTraceIdsByName: an O(depth) bucket read
    (service family, or the (service, span-name) family when a name is
    given) instead of the O(ring) scan. Returns (candidates [3, k],
    complete, entry_count); the host falls back to the scan kernel when
    the bucket wrapped and the result underfills (store.base gating)."""
    c = state.config
    lay, _, _ = c.cand_layout
    if name_lc_id is not None and name_lc_id >= 0:
        fam = lay[StoreConfig.CAND_NAME]
        return _iq_verify_impl(
            state.cand_idx, state.cand_pos, state.cand_wm,
            state.row_gid, state.indexable, state.trace_id, state.ts_last,
            c.capacity, fam, min(k, fam[3]),
            (jnp.int32(svc_id), jnp.int32(name_lc_id)), end_ts,
            state.key_tab, state.key_wm, state.write_pos,
            state.counters["key_claim_drops"],
        )
    fam = lay[StoreConfig.CAND_SVC]
    return _iq_service_impl(
        state.cand_idx, state.cand_pos, state.cand_wm,
        state.row_gid, state.indexable, state.trace_id, state.ts_last,
        c.capacity, fam, min(k, fam[3]), svc_id, end_ts,
    )


def iquery_trace_ids_by_annotation(state: StoreState, svc_id,
                                   ann_value_id, bann_key_id,
                                   bann_value_id, bann_value_id2,
                                   end_ts, k: int):
    """Index fast path for the annotation query (AnnotationsIndex role).
    Same contract as iquery_trace_ids_by_service."""
    c = state.config
    lay, _, _ = c.cand_layout
    if ann_value_id is not None and ann_value_id >= 0:
        fam = lay[StoreConfig.CAND_ANN]
        return _iq_verify_impl(
            state.cand_idx, state.cand_pos, state.cand_wm,
            state.row_gid, state.indexable, state.trace_id, state.ts_last,
            c.capacity, fam, min(k, fam[3]),
            (jnp.int32(svc_id), jnp.int32(ann_value_id)), end_ts,
            state.key_tab, state.key_wm, state.write_pos,
            state.counters["key_claim_drops"], state.ann_poison,
        )
    if bann_value_id is None or bann_value_id < 0:
        bann_value_id = -1
    if bann_value_id2 is None or bann_value_id2 < 0:
        bann_value_id2 = -1
    # A value may be dictionary-keyed in only one of its str/bytes
    # forms: any non-negative id makes this a VALUED query.
    if bann_value_id < 0 and bann_value_id2 >= 0:
        bann_value_id = bann_value_id2
    if bann_value_id >= 0 and bann_value_id2 < 0:
        bann_value_id2 = bann_value_id
    fam = lay[StoreConfig.CAND_BANN]
    if bann_value_id < 0:
        # Key-only query: the sentinel-keyed buckets.
        return _iq_verify_impl(
            state.cand_idx, state.cand_pos, state.cand_wm,
            state.row_gid, state.indexable, state.trace_id, state.ts_last,
            c.capacity, fam, min(k, fam[3]),
            (jnp.int32(svc_id), jnp.int32(bann_key_id), jnp.int32(-1)),
            end_ts, state.key_tab, state.key_wm, state.write_pos,
            state.counters["key_claim_drops"], state.ann_poison,
        )
    # The two-bucket probe's candidate window is 2*depth rows; clamping
    # k to depth would truncate valid candidates of never-wrapped
    # buckets and let the host's underfull-equals-complete gate trust a
    # silently cut window (caught by the 3-store oracle parity drive).
    return _iq_verify2_impl(
        state.cand_idx, state.cand_pos, state.cand_wm,
        state.row_gid, state.indexable, state.trace_id, state.ts_last,
        c.capacity, fam, min(k, 2 * fam[3]),
        (jnp.int32(svc_id), jnp.int32(bann_key_id),
         jnp.int32(bann_value_id)),
        (jnp.int32(svc_id), jnp.int32(bann_key_id),
         jnp.int32(bann_value_id2)),
        end_ts, state.key_tab, state.key_wm, state.write_pos,
        state.counters["key_claim_drops"], state.ann_poison,
    )


@partial(jax.jit, static_argnums=(8, 9))
def _iq_durations_impl(entries, pos, wm, trace_id, row_gid, ts_first,
                       ts_last, write_pos, capacity: int, layout,
                       sorted_qids):
    b_base, s_base, n_b, depth = layout
    nq = sorted_qids.shape[0]
    lb = _bucket_of(_mixb([sorted_qids]), n_b)
    qb = jnp.int32(b_base) + lb
    rows = (jnp.int32(s_base) + lb[:, None] * depth
            + jnp.arange(depth, dtype=jnp.int32)[None, :])
    # Unified arena rows are (gid, verify, ts) triples; the gid column
    # rides the contiguous [n, 3] row gather (the cheap shape class).
    gid = entries[rows.reshape(-1), 0].reshape(nq, depth)
    slot = jnp.clip((gid % capacity).astype(jnp.int32), 0, capacity - 1)
    live = (gid >= 0) & (row_gid[slot] == gid)
    match = live & (trace_id[slot] == sorted_qids[:, None])
    tf = ts_first[slot]
    tl = ts_last[slot]
    has_ts = match & (tf >= 0)
    firsts = jnp.where(has_ts, tf, I64_MAX).min(axis=1)
    lasts = jnp.where(match & (tl >= 0), tl, I64_MIN).max(axis=1)
    gate = (pos[qb] <= depth) | (wm[qb] < write_pos - capacity)
    mat = jnp.stack([
        match.any(axis=1).astype(jnp.int64),
        has_ts.any(axis=1).astype(jnp.int64),
        firsts, lasts,
    ])
    return mat, gate.all()


def iquery_durations(state: StoreState, sorted_qids):
    """Trace-membership fast path for getTracesDuration/tracesExist:
    candidate rows come from the queried traces' gid buckets (nq*depth
    rows) instead of a 4-scatter pass over the full span ring. Returns
    (mat [4, nq] — same layout as query_durations — , exact) where
    ``exact`` requires every queried bucket to pass the displaced-gid
    gate; the host falls back to the scan kernel otherwise."""
    c = state.config
    tlay, _, _ = c.trace_layout
    return _iq_durations_impl(
        state.cand_idx, state.cand_pos, state.cand_wm,
        state.trace_id, state.row_gid, state.ts_first, state.ts_last,
        state.write_pos, c.capacity, tlay[StoreConfig.TR_SPAN],
        sorted_qids,
    )


@partial(jax.jit, static_argnums=(10,))
def _iq_gather_impl(
    tr_entries, tr_pos, tr_wm,
    span_cols, ann_cols, bann_cols, sorted_qids,
    write_pos, ann_write_pos, bann_write_pos,
    statics,
):
    (capacity, ann_capacity, bann_capacity, lay_s, lay_a, lay_b,
     k_spans, k_anns, k_banns) = statics
    trace_id = span_cols[0]
    row_gid = span_cols[-1]
    ann_gid = ann_cols[0]
    bann_gid = bann_cols[0]
    nq = sorted_qids.shape[0]
    lb = _bucket_of(_mixb([sorted_qids]), lay_s[2])

    def family(layout, ring_wp, ring_cap):
        b_base, s_base, _, depth = layout
        qb = jnp.int32(b_base) + lb
        rows = (jnp.int32(s_base) + lb[:, None] * depth
                + jnp.arange(depth, dtype=jnp.int32)[None, :])
        gid = tr_entries[rows.reshape(-1), 0].reshape(nq, depth)
        gate = (tr_pos[qb] <= depth) | (tr_wm[qb] < ring_wp - ring_cap)
        return gid, gate.all()

    # Span rows: direct liveness + trace match.
    s_gid, gate_s = family(lay_s, write_pos, capacity)
    s_slot = jnp.clip((s_gid % capacity).astype(jnp.int32), 0,
                      capacity - 1)
    s_ok = ((s_gid >= 0) & (row_gid[s_slot] == s_gid)
            & (trace_id[s_slot] == sorted_qids[:, None]))
    count_s = s_ok.sum(dtype=jnp.int64)
    key_s = jnp.where(s_ok, I64_MAX - s_gid, jnp.int64(-1)).reshape(-1)
    vals_s, sel_s = jax.lax.top_k(key_s, k_spans)  # oldest gid first
    sslot = s_slot.reshape(-1)[sel_s]
    span_mat = jnp.stack([c[sslot].astype(jnp.int64) for c in span_cols])
    span_mat = jnp.where((vals_s >= 0)[None, :], span_mat, -1)

    def ragged(layout, ring_wp, ring_cap, owner_col, cols, k):
        """Annotation/binary rows: entry validity = the ring slot still
        holds this position (overwrite order) + owning span live and in
        the queried set."""
        gid, gate = family(layout, ring_wp, ring_cap)
        slot = jnp.clip((gid % ring_cap).astype(jnp.int32), 0,
                        ring_cap - 1)
        fresh = (gid >= 0) & (gid >= ring_wp - ring_cap)
        owner = owner_col[slot]
        oslot = jnp.clip((owner % capacity).astype(jnp.int32), 0,
                         capacity - 1)
        ok = (fresh & (owner >= 0) & (row_gid[oslot] == owner)
              & (trace_id[oslot] == sorted_qids[:, None]))
        count = ok.sum(dtype=jnp.int64)
        key = jnp.where(ok, I64_MAX - gid, jnp.int64(-1)).reshape(-1)
        vals, sel = jax.lax.top_k(key, k)
        rslot = slot.reshape(-1)[sel]
        mat = jnp.stack([c[rslot].astype(jnp.int64) for c in cols])
        return count, jnp.where((vals >= 0)[None, :], mat, -1), gate

    count_a, ann_mat, gate_a = ragged(
        lay_a, ann_write_pos, ann_capacity, ann_gid, ann_cols, k_anns,
    )
    count_b, bann_mat, gate_b = ragged(
        lay_b, bann_write_pos, bann_capacity, bann_gid, bann_cols,
        k_banns,
    )
    counts = jnp.stack([count_s, count_a, count_b])
    return counts, span_mat, ann_mat, bann_mat, gate_s & gate_a & gate_b


def iquery_gather_trace_rows(
    state: StoreState, sorted_qids, k_spans: int, k_anns: int,
    k_banns: int,
):
    """Trace-membership fast path for whole-trace materialization: the
    same four-array contract as gather_trace_rows plus an ``exact``
    flag; candidates come from the queried traces' gid buckets instead
    of full-ring scans. The host falls back to gather_trace_rows when
    any queried bucket fails the displaced-gid gate (hot traces beyond
    the per-family depths, or shuffled arrival near the gate)."""
    c = state.config
    tlay, _, _ = c.trace_layout
    statics = (c.capacity, c.ann_capacity, c.bann_capacity,
               tlay[StoreConfig.TR_SPAN], tlay[StoreConfig.TR_ANN],
               tlay[StoreConfig.TR_BANN], k_spans, k_anns, k_banns)
    return _iq_gather_impl(
        state.cand_idx, state.cand_pos, state.cand_wm,
        tuple(getattr(state, col) for col in SPAN_MAT_COLS),
        tuple(getattr(state, col) for col in ANN_MAT_COLS),
        tuple(getattr(state, col) for col in BANN_MAT_COLS),
        sorted_qids,
        state.write_pos, state.ann_write_pos, state.bann_write_pos,
        statics,
    )


@jax.jit
def _q_durations_impl(trace_id, row_gid, ts_first, ts_last, sorted_qids):
    nq = sorted_qids.shape[0]
    live = row_gid >= 0
    pos = jnp.searchsorted(sorted_qids, trace_id)
    pos_c = jnp.clip(pos, 0, nq - 1)
    match = live & (sorted_qids[pos_c] == trace_id)
    seg = jnp.where(match, pos_c, nq)
    has_ts = match & (ts_first >= 0)
    # Ring-sized i64/bool scatter-reductions serialize on this backend
    # (~100 ns/row — 4.2M rows cost ~420 ms EACH; this kernel was the
    # whole q_durations p99); the exact plane wars and i32 maxes
    # vectorize.
    min_first = _war_min64(
        jnp.full(nq + 1, I64_MAX, jnp.int64), seg, ts_first, has_ts
    )[:nq]
    max_last = _war_max64(
        jnp.full(nq + 1, I64_MIN, jnp.int64), seg, ts_last, has_ts
    )[:nq]
    found = jnp.zeros(nq + 1, jnp.int32).at[seg].max(
        has_ts.astype(jnp.int32), mode="drop")[:nq] > 0
    present = jnp.zeros(nq + 1, jnp.int32).at[seg].max(
        match.astype(jnp.int32), mode="drop")[:nq] > 0
    return jnp.stack([
        present.astype(jnp.int64), found.astype(jnp.int64), min_first, max_last
    ])


def query_durations(state: StoreState, sorted_qids):
    """Per queried trace id, ONE stacked [4, nq] i64 array:
    (present, found, min first_ts, max last_ts).

    ``present`` = any live row carries the id (traces_exist semantics);
    ``found`` additionally requires a timestamp (getTracesDuration,
    Index.scala:26: duration = max(last) - min(first)). ``sorted_qids``
    must be ascending (host sorts). The jitted impl takes only the four
    columns it reads (see query_trace_ids_by_service).
    """
    return _q_durations_impl(
        state.trace_id, state.row_gid, state.ts_first, state.ts_last,
        sorted_qids,
    )


# Column order of the stacked matrices gather_trace_rows returns; the
# host decodes by these names (row_gid last in SPAN_MAT_COLS).
SPAN_MAT_COLS = (
    "trace_id", "span_id", "parent_id", "name_id", "service_id",
    "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first", "ts_last",
    "duration", "flags", "row_gid",
)
ANN_MAT_COLS = ("ann_gid", "ann_ts", "ann_value_id", "ann_service_id",
                "ann_endpoint_id")
BANN_MAT_COLS = ("bann_gid", "bann_key_id", "bann_value_id", "bann_type",
                 "bann_service_id", "bann_endpoint_id")


@partial(jax.jit, static_argnums=(7, 8, 9, 10, 11, 12, 13))
def _gather_impl(
    span_cols, ann_cols, bann_cols, sorted_qids,
    write_pos, ann_write_pos, bann_write_pos,
    capacity: int, ann_capacity: int, bann_capacity: int,
    k_spans: int, k_anns: int, k_banns: int,
    paged: bool = False,
):
    trace_id = span_cols[0]
    row_gid = span_cols[-1]
    ann_gid = ann_cols[0]
    bann_gid = bann_cols[0]

    nq = sorted_qids.shape[0]
    live = row_gid >= 0
    pos = jnp.clip(jnp.searchsorted(sorted_qids, trace_id), 0, nq - 1)
    span_in = live & (sorted_qids[pos] == trace_id)

    a_slot, a_live = _span_slot(ann_gid, row_gid, capacity)
    ann_in = a_live & span_in[a_slot]
    b_slot, b_live = _span_slot(bann_gid, row_gid, capacity)
    bann_in = b_live & span_in[b_slot]

    def oldest_k(mask, wp, cap, k):
        """Indices of the k oldest matching ring slots (insertion
        order). top_k on an i32 freshness key — a full i64 ring argsort
        compiles for ~a minute per shape at 2^22 on TPU; top_k is
        seconds, and k rows are all a trace read needs."""
        head = (wp % cap).astype(jnp.int32)
        slots = jnp.arange(cap, dtype=jnp.int32)
        age = (slots - head) % jnp.int32(cap)
        key = jnp.where(mask, jnp.int32(cap) - age, 0)
        _, sel = jax.lax.top_k(key, k)
        return sel

    if paged:
        # Paged layout: slot position is a page assignment, not an
        # arrival rank — insertion order lives in the epoch-encoded
        # gid, so span rows sort by the i64 gid key directly (the
        # _iq_gather_impl idiom).
        skey = jnp.where(span_in, I64_MAX - row_gid, jnp.int64(-1))
        _, sel = jax.lax.top_k(skey, k_spans)
    else:
        sel = oldest_k(span_in, write_pos, capacity, k_spans)
    span_mat = jnp.stack([c[sel].astype(jnp.int64) for c in span_cols])

    a_sel = oldest_k(ann_in, ann_write_pos, ann_capacity, k_anns)
    ann_mat = jnp.stack([c[a_sel].astype(jnp.int64) for c in ann_cols])
    # Mask stale selections (when fewer than k_anns match).
    ann_mat = jnp.where(ann_in[a_sel][None, :], ann_mat, -1)

    b_sel = oldest_k(bann_in, bann_write_pos, bann_capacity, k_banns)
    bann_mat = jnp.stack([c[b_sel].astype(jnp.int64) for c in bann_cols])
    bann_mat = jnp.where(bann_in[b_sel][None, :], bann_mat, -1)

    counts = jnp.stack([
        span_in.sum(dtype=jnp.int64),
        ann_in.sum(dtype=jnp.int64),
        bann_in.sum(dtype=jnp.int64),
    ])
    return counts, span_mat, ann_mat, bann_mat


@partial(jax.jit, static_argnums=(8, 9, 10, 11, 12, 13, 14))
def _capture_impl(
    span_cols, ann_cols, bann_cols, lo, hi,
    write_pos, ann_write_pos, bann_write_pos,
    capacity: int, ann_capacity: int, bann_capacity: int,
    k_spans: int, k_anns: int, k_banns: int,
    paged: bool = False,
):
    row_gid = span_cols[-1]
    ann_gid = ann_cols[0]
    bann_gid = bann_cols[0]
    span_in = (row_gid >= lo) & (row_gid < hi)
    ann_in = (ann_gid >= lo) & (ann_gid < hi)
    bann_in = (bann_gid >= lo) & (bann_gid < hi)

    def oldest_k(mask, wp, cap, k):
        head = (wp % cap).astype(jnp.int32)
        slots = jnp.arange(cap, dtype=jnp.int32)
        age = (slots - head) % jnp.int32(cap)
        key = jnp.where(mask, jnp.int32(cap) - age, 0)
        _, sel = jax.lax.top_k(key, k)
        return sel

    if paged:
        # Page-granular capture: order the page's spans by gid (their
        # insertion order) so the sealed segment is bitwise-stable
        # regardless of slot placement inside the page.
        skey = jnp.where(span_in, I64_MAX - row_gid, jnp.int64(-1))
        _, sel = jax.lax.top_k(skey, k_spans)
    else:
        sel = oldest_k(span_in, write_pos, capacity, k_spans)
    span_mat = jnp.stack([c[sel].astype(jnp.int64) for c in span_cols])
    a_sel = oldest_k(ann_in, ann_write_pos, ann_capacity, k_anns)
    ann_mat = jnp.stack([c[a_sel].astype(jnp.int64) for c in ann_cols])
    ann_mat = jnp.where(ann_in[a_sel][None, :], ann_mat, -1)
    b_sel = oldest_k(bann_in, bann_write_pos, bann_capacity, k_banns)
    bann_mat = jnp.stack([c[b_sel].astype(jnp.int64) for c in bann_cols])
    bann_mat = jnp.where(bann_in[b_sel][None, :], bann_mat, -1)
    counts = jnp.stack([
        span_in.sum(dtype=jnp.int64),
        ann_in.sum(dtype=jnp.int64),
        bann_in.sum(dtype=jnp.int64),
    ])
    return counts, span_mat, ann_mat, bann_mat


def capture_eviction_rows(
    state: StoreState, lo: int, hi: int,
    k_spans: int, k_anns: int, k_banns: int,
):
    """Eviction capture: pull every ring row (span + annotation +
    binary) whose SPAN gid falls in [lo, hi), compacted to the front in
    insertion order — the cold tier's batched host pull. Same stacked
    matrix shape as gather_trace_rows so the host decode path is
    shared. A PURE READ: the fused ingest step's lowering is untouched
    (bench_smoke's 95/5/79 census gate holds with capture wired); the
    cold tier pays one extra read-only launch + one D2H per capture
    window on the existing archive cadence.

    The caller triggers the pull BEFORE any of the three rings can
    overwrite a row in the window (TpuSpanStore._maybe_capture tracks
    all three write cursors), so every captured span is complete —
    including side-table rows a faster-lapping annotation ring would
    have dropped first."""
    c = state.config
    return _capture_impl(
        tuple(getattr(state, col) for col in SPAN_MAT_COLS),
        tuple(getattr(state, col) for col in ANN_MAT_COLS),
        tuple(getattr(state, col) for col in BANN_MAT_COLS),
        jnp.int64(lo), jnp.int64(hi),
        state.write_pos, state.ann_write_pos, state.bann_write_pos,
        c.capacity, c.ann_capacity, c.bann_capacity,
        k_spans, k_anns, k_banns, c.paged_enabled,
    )


def gather_trace_rows(
    state: StoreState, sorted_qids, k_spans: int, k_anns: int, k_banns: int,
):
    """Device-side gather of every ring row belonging to ``sorted_qids``,
    compacted to the front in insertion order, returned as THREE stacked
    i64 matrices plus a [3] count vector — four arrays total, because
    host transfers pay a large per-array latency and the naive path
    (pull whole ring columns, mask on host) moves the entire store
    through the tunnel per trace read.

    Span rows sort by global row id (insertion order); annotation rows
    by ring age so per-span annotation insert order survives. Rows
    beyond the static ``k_*`` caps are dropped — counts tell the caller
    to escalate caps and retry (the maxTraceCols-style guard,
    CassieSpanStore.scala:50). The jitted impl takes only the columns
    it gathers (per-argument dispatch overhead on tunneled devices).
    """
    c = state.config
    return _gather_impl(
        tuple(getattr(state, col) for col in SPAN_MAT_COLS),
        tuple(getattr(state, col) for col in ANN_MAT_COLS),
        tuple(getattr(state, col) for col in BANN_MAT_COLS),
        sorted_qids,
        state.write_pos, state.ann_write_pos, state.bann_write_pos,
        c.capacity, c.ann_capacity, c.bann_capacity,
        k_spans, k_anns, k_banns, c.paged_enabled,
    )


@partial(jax.jit, static_argnums=(8, 9, 10, 11, 12, 13, 14, 15))
def _paged_gather_impl(
    span_cols, ann_cols, bann_cols, sorted_qids, pages, epochs,
    ann_write_pos, bann_write_pos,
    capacity: int, page_rows: int, ann_capacity: int, bann_capacity: int,
    k_spans: int, k_anns: int, k_banns: int, pallas: bool,
):
    """Paged trace assembly (r19): gather span rows from an explicit
    page list instead of scanning the whole ring.

    ``pages`` [K] i32 / ``epochs`` [K] i64 come from the host page
    table (store/paged.PagePlanner.chains_for) — every page any queried
    trace has rows in, -1-padded. Validity is per ROW, not per page:
    the expected gid of slot (p, j) is epoch*capacity + p*R + j, and a
    gathered row counts only when its live row_gid equals that AND its
    trace_id is one of ``sorted_qids`` (pages are shared by small
    traces, so a page may carry rows of non-queried traces). Both
    gather paths — the Pallas block-gather kernel and the XLA take
    fallback — feed the same mask, and the output span_mat is masked to
    -1 on dead rows, so the two are bitwise identical
    (tests/test_paged.py gates it).

    Annotation/binary rows stay on their FIFO rings (no pages), so
    their membership is the _gather_impl scan unchanged.
    """
    trace_col = span_cols[0]
    row_gid = span_cols[-1]
    ann_gid = ann_cols[0]
    bann_gid = bann_cols[0]
    nq = sorted_qids.shape[0]
    R = page_rows
    n_pages = capacity // R
    pg = jnp.clip(pages, 0, n_pages - 1)
    offs = jnp.arange(R, dtype=jnp.int32)[None, :]
    page_slots = pg[:, None] * R + offs                      # [K, R]
    expected = jnp.where(
        pages[:, None] >= 0,
        epochs[:, None] * jnp.int64(capacity)
        + page_slots.astype(jnp.int64),
        jnp.int64(-1),
    ).reshape(-1)                                            # [K*R]
    ncols = len(span_cols)
    if pallas:
        from zipkin_tpu.ops import pallas_kernels as PK

        cols64 = jnp.stack([col.astype(jnp.int64) for col in span_cols])
        planes = jnp.moveaxis(_p32(cols64), 2, 1).reshape(
            2 * ncols, capacity)
        out = PK.paged_page_gather(planes, pages, R)         # [2C, K*R]
        rows = _p64(jnp.moveaxis(out.reshape(ncols, 2, -1), 1, 2))
    else:
        slot = page_slots.reshape(-1)
        rows = jnp.stack(
            [col[slot].astype(jnp.int64) for col in span_cols])
    g_tid = rows[0]
    g_gid = rows[-1]
    g_live = (expected >= 0) & (g_gid == expected)
    g_pos = jnp.clip(jnp.searchsorted(sorted_qids, g_tid), 0, nq - 1)
    ok = g_live & (sorted_qids[g_pos] == g_tid)
    skey = jnp.where(ok, I64_MAX - expected, jnp.int64(-1))
    _, sel = jax.lax.top_k(skey, k_spans)
    span_mat = jnp.where(ok[sel][None, :], rows[:, sel], -1)

    # Ann/bann membership: owning-span liveness over the slot array,
    # exactly _gather_impl's scan (annotation rows are ringed, not
    # paged; ring age IS their insertion order in both layouts).
    live_r = row_gid >= 0
    pos_r = jnp.clip(jnp.searchsorted(sorted_qids, trace_col), 0, nq - 1)
    span_in = live_r & (sorted_qids[pos_r] == trace_col)
    a_slot, a_live = _span_slot(ann_gid, row_gid, capacity)
    ann_in = a_live & span_in[a_slot]
    b_slot, b_live = _span_slot(bann_gid, row_gid, capacity)
    bann_in = b_live & span_in[b_slot]

    def oldest_k(mask, wp, cap, k):
        head = (wp % cap).astype(jnp.int32)
        slots = jnp.arange(cap, dtype=jnp.int32)
        age = (slots - head) % jnp.int32(cap)
        key = jnp.where(mask, jnp.int32(cap) - age, 0)
        _, sel = jax.lax.top_k(key, k)
        return sel

    a_sel = oldest_k(ann_in, ann_write_pos, ann_capacity, k_anns)
    ann_mat = jnp.stack([c[a_sel].astype(jnp.int64) for c in ann_cols])
    ann_mat = jnp.where(ann_in[a_sel][None, :], ann_mat, -1)
    b_sel = oldest_k(bann_in, bann_write_pos, bann_capacity, k_banns)
    bann_mat = jnp.stack([c[b_sel].astype(jnp.int64) for c in bann_cols])
    bann_mat = jnp.where(bann_in[b_sel][None, :], bann_mat, -1)
    counts = jnp.stack([
        ok.sum(dtype=jnp.int64),
        ann_in.sum(dtype=jnp.int64),
        bann_in.sum(dtype=jnp.int64),
    ])
    return counts, span_mat, ann_mat, bann_mat


def gather_paged_trace_rows(
    state: StoreState, sorted_qids, pages, epochs,
    k_spans: int, k_anns: int, k_banns: int,
):
    """Paged twin of gather_trace_rows: span rows come from the page
    list (Pallas block-gather when eligible, XLA take fallback — the
    r12 arena_claim_scatter gating pattern), annotation rows from the
    ring scan. Same four-array contract, so the host decode and
    escalation paths are shared."""
    from zipkin_tpu.ops import pallas_kernels as PK

    c = state.config
    use_pallas = PK.paged_gather_supported(
        c.capacity, c.page_rows, len(SPAN_MAT_COLS),
        len(pages),
    ) and (c.use_pallas or jax.default_backend() == "tpu")
    return _paged_gather_impl(
        tuple(getattr(state, col) for col in SPAN_MAT_COLS),
        tuple(getattr(state, col) for col in ANN_MAT_COLS),
        tuple(getattr(state, col) for col in BANN_MAT_COLS),
        sorted_qids,
        jnp.asarray(pages, jnp.int32), jnp.asarray(epochs, jnp.int64),
        state.ann_write_pos, state.bann_write_pos,
        c.capacity, c.page_rows, c.ann_capacity, c.bann_capacity,
        k_spans, k_anns, k_banns, use_pallas,
    )



# ---------------------------------------------------------------------------
# Pipelined-ingest staging + jit-compile accounting
# ---------------------------------------------------------------------------


def stage_batch(db: DeviceBatch) -> DeviceBatch:
    """H2D staging of one padded batch: ``jax.device_put`` of the whole
    pytree, returned immediately (the transfer proceeds asynchronously)
    so the pipeline's stage thread can overlap the copy with the
    previous fused step's device compute. Placement is left uncommitted
    on the default device. NOTE: staged (device-resident) arguments
    key DIFFERENT jit cache rows than host numpy arguments on this jax
    version, so the first pipelined drive at a given pad bucket
    compiles its own entry even if the serial path warmed that shape —
    thereafter steady state is zero recompiles (gated via
    ``compile_count`` in bench_smoke's pipeline phase, warmed through
    the pipeline)."""
    return jax.device_put(db)


# The write-path jits whose compile-cache growth the ingest pipeline
# gates on: steady-state pipelined ingest must hit only pow2 pad
# buckets that warmup already compiled (zero recompiles). Query jits
# are deliberately excluded — their cache is keyed by request shapes
# the write path does not control.
_INGEST_JITS = (
    ingest_step, ingest_steps, dep_sweep, dep_close_bucket,
    rebuild_span_tab, _capture_impl,
)

# The resident query programs (query/engine.py's index tier): the
# batched multi-probe kernel plus every read kernel the engine's
# cached paths dispatch. A warmed steady state must hold their cache
# sizes flat — bench_smoke's query phase and bench.py's query-engine
# phase gate query_compile_count() deltas at ZERO.
_QUERY_JITS = (
    _iq_multi_impl, _iq_service_impl, _iq_verify_impl,
    _iq_verify2_impl, _iq_durations_impl, _iq_gather_impl,
    _q_by_service_impl, _q_by_annotation_impl, _q_durations_impl,
    _gather_impl, _paged_gather_impl, counter_block,
)


def compile_count() -> int:
    """Total compiled variants (jit cache entries) across the ingest /
    staging / capture jits — a process-wide monotone recompile counter.
    Surfaced through ``TpuSpanStore.counters()`` -> /metrics as
    ``jit_compiles``; bench_smoke's pipeline phase asserts its delta is
    ZERO across a warmed pipelined drive."""
    total = 0
    for fn in _INGEST_JITS:
        try:
            total += fn._cache_size()
        except Exception:  # pragma: no cover; graftlint: disable=swallowed-exception
            pass  # best-effort probe of a private jax API
    return total


def query_compile_count() -> int:
    """Compiled variants across the resident query kernels
    (_QUERY_JITS) — the query-path twin of ``compile_count``. A
    resident executor serving steady traffic must hold this flat:
    every dispatch hits an already-compiled program (pow2 probe
    padding bounds the shape space). Surfaced through
    ``TpuSpanStore.counters()`` → /metrics as ``query_jit_compiles``."""
    total = 0
    for fn in _QUERY_JITS:
        try:
            total += fn._cache_size()
        except Exception:  # pragma: no cover; graftlint: disable=swallowed-exception
            pass  # best-effort probe of a private jax API
    return total
