"""Windowed-analytics read mixin over a SketchMirror.

Every read here is HOST-ONLY: the mirror twins of the windowed
(service × time-bucket) Moments-sketch arena answer with zero device
round-trips (the PR 6 sub-10ms sketch tier). Window answers are
whole-bucket granular: [start_us, end_us) expands to the time buckets
it overlaps, and only buckets still live in the ring
(window_seconds × window_buckets of retention) contribute.

Mixed into ``TpuSpanStore`` (mirror fed by the fused ingest step's
commit deltas) AND ``ReplicaSpanStore`` (mirror fed by shipped WAL
records, store/replica.py) — one implementation, so a device-free read
replica answers windowed quantiles / burn rates / heatmaps bitwise the
way the primary does at the same applied frontier. Hosts must provide
``config`` (a StoreConfig), ``ensure_sketch_mirror()`` and
``_svc_id(name)``.
"""

from __future__ import annotations

import numpy as np


class WindowedAnalytics:
    """windowed_quantiles / slo_burn / latency_heatmap over the host's
    sketch mirror (see module docstring for the host contract)."""

    def _window_ctx(self, service: str):
        """(mirror, svc id) — or (None, None) when the arena can't
        represent the service (disabled arena, unknown name, or a
        dictionary-overflow id past max_services)."""
        c = self.config
        if not c.window_enabled:
            return None, None
        svc = self._svc_id(service)
        if svc is None or svc >= c.max_services:
            return None, None
        return self.ensure_sketch_mirror(), svc

    def _bucket_range(self, epoch, start_us, end_us):
        """[b0, b1] absolute-bucket span for a µs half-open window;
        None bounds default to the arena's live extent."""
        bucket_us = self.config.window_us
        live = epoch[epoch >= 0]
        if start_us is None:
            b0 = int(live.min()) if live.size else 0
        else:
            b0 = max(0, int(start_us) // bucket_us)
        if end_us is None:
            b1 = int(live.max()) if live.size else -1
        else:
            b1 = (max(0, int(end_us)) - 1) // bucket_us
        return b0, b1

    def windowed_quantiles(self, service: str, qs,
                           start_us=None, end_us=None):
        """Duration quantile estimates (µs) for ``service`` over the
        time window — a cell-sum + one Moments solve
        (windows.quantiles_from_sums; tolerance documented there).
        None when no duration-carrying span is in the window."""
        from zipkin_tpu.aggregate import windows as win_mod

        m, svc = self._window_ctx(service)
        if m is None:
            return None
        epoch, counts, sums, mm = m.window_row(svc)
        b0, b1 = self._bucket_range(epoch, start_us, end_us)
        ws = win_mod.merge_cells(epoch, counts, sums, mm, b0, b1)
        return win_mod.quantiles_from_sums(
            ws, list(qs), m.gamma, self.config.win_x_shift)

    def slo_burn(self, service: str, objective: float = None,
                 windows_s=None, now_us=None):
        """Multi-window error-budget burn rates: per lookback window,
        error rate over the covered cells divided by the budget
        (1 - objective). ``now_us`` defaults to the end of the arena's
        newest live bucket (data time, so replays and tests are
        deterministic). None when the arena can't serve the service."""
        from zipkin_tpu.aggregate import windows as win_mod

        objective = (win_mod.DEFAULT_OBJECTIVE if objective is None
                     else float(objective))
        windows_s = list(windows_s or win_mod.DEFAULT_BURN_WINDOWS_S)
        m, svc = self._window_ctx(service)
        if m is None:
            return None
        epoch, counts, sums, mm = m.window_row(svc)
        bucket_us = self.config.window_us
        live = epoch[epoch >= 0]
        if now_us is None:
            now_us = (int(live.max()) + 1) * bucket_us if live.size else 0
        budget = max(1.0 - objective, 1e-9)
        out = []
        for w_s in windows_s:
            b1 = (int(now_us) - 1) // bucket_us
            b0 = max(0, (int(now_us) - int(w_s) * 1_000_000)
                     // bucket_us)
            ws = win_mod.merge_cells(epoch, counts, sums, mm, b0, b1)
            rate = ws.error_rate
            out.append({
                "windowSeconds": int(w_s),
                "total": ws.total,
                "errors": ws.err,
                "errorRate": rate,
                "burnRate": rate / budget,
            })
        return {"serviceName": service, "objective": objective,
                "nowTs": int(now_us), "windows": out}

    def latency_heatmap(self, service: str, start_us=None, end_us=None,
                        bands: int = None):
        """Service × time × duration-bucket grid: one column per live
        time bucket in range, ``bands`` log-spaced duration bands,
        cell mass from each column's Moments solve. None when the
        arena can't serve the service."""
        from zipkin_tpu.aggregate import windows as win_mod

        bands = int(bands or win_mod.DEFAULT_HEATMAP_BANDS)
        m, svc = self._window_ctx(service)
        if m is None:
            return None
        epoch, counts, sums, mm = m.window_row(svc)
        b0, b1 = self._bucket_range(epoch, start_us, end_us)
        slots = win_mod.live_slots(epoch, b0, b1)
        order = np.argsort(epoch[slots])
        slots = slots[order]
        cells = win_mod.cell_sums(slots, counts, sums, mm)
        bucket_us = self.config.window_us
        shift = self.config.win_x_shift
        with_dur = [c for c in cells if c.n > 0]
        if with_dur:
            lo = min(c.min_x for c in with_dur)
            hi = max(c.max_x for c in with_dur)
        else:
            lo = hi = 0
        edges = win_mod.band_edges_x(lo, hi, bands)
        grid = [
            [round(v, 3) for v in win_mod.band_masses(c, edges)]
            for c in cells
        ]
        return {
            "serviceName": service,
            "bucketSeconds": self.config.window_seconds,
            "bucketStartsTs": [int(epoch[w]) * bucket_us
                               for w in slots],
            "bandEdgesMicros": [
                round(win_mod.x_edge_duration(int(e), m.gamma, shift),
                      1)
                for e in edges
            ],
            "cells": grid,
            "totals": [c.total for c in cells],
            "errors": [c.err for c in cells],
        }
