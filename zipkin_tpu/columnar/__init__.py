"""Columnar (structure-of-arrays) span representation.

This is the TPU-native wire between the host span model and the device:
strings are dictionary-encoded on the host (mirroring the reference's
HBase dictionary mappers, zipkin-hbase/.../mapping/ServiceMapper.scala),
and the device sees only fixed-width integer/float arrays.
"""

from zipkin_tpu.columnar.dictionary import Dictionary, DictionarySet  # noqa: F401
from zipkin_tpu.columnar.schema import (  # noqa: F401
    FLAG_DEBUG,
    FLAG_HAS_PARENT,
    NO_ENDPOINT,
    NO_SERVICE,
    NO_TS,
    SpanBatch,
)
from zipkin_tpu.columnar.encode import SpanCodec  # noqa: F401
