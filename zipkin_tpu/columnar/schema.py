"""SoA span-batch schema — what the device actually sees.

Design (SURVEY.md §7.1): a span batch is three flat tables of fixed-width
columns. Core RPC annotations (cs/cr/sr/ss) get dedicated timestamp
columns on the span row so duration/skew math vectorizes; everything else
(custom annotations, binary annotations) lives in ragged side tables tied
back to the span row by ``span_idx``.

All timestamps are microseconds (int64); ``NO_TS`` (-1) marks absence.
String-ish columns are dictionary ids (see columnar/dictionary.py);
``NO_SERVICE``/``NO_ENDPOINT`` (-1) mark absence.

Reference parity: the per-span columns carry exactly the information the
reference's stores index on — service name, span name, annotations and
binary annotations with timestamps (CassieSpanStore.scala:168-251), plus
the debug flag honoured by the sampler (SpanSamplerFilter.scala:40-47).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

import numpy as np

NO_TS = np.int64(-1)
NO_SERVICE = np.int32(-1)
NO_ENDPOINT = np.int32(-1)
NO_PARENT = np.int64(0)

FLAG_DEBUG = np.uint8(1)
FLAG_HAS_PARENT = np.uint8(2)

# Core-annotation timestamp column order (matches CORE_ANNOTATION_IDS).
CORE_TS_COLUMNS = ("ts_cs", "ts_cr", "ts_sr", "ts_ss")


@dataclass
class SpanBatch:
    """A batch of spans in columnar form (host: numpy; device: jax arrays).

    Span table (length ``n_spans``):
      trace_id, span_id: int64; parent_id: int64 (FLAG_HAS_PARENT gates);
      name_id, service_id: int32; ts_cs/ts_cr/ts_sr/ts_ss: int64 (NO_TS
      when absent); ts_first/ts_last: int64 over *all* annotations;
      duration: int64 = ts_last - ts_first (NO_TS when the span has no
      annotations; 0 when it has exactly one); flags: uint8.

    Annotation table (length ``n_annotations``):
      ann_span_idx: int32 row index into the span table;
      ann_ts: int64; ann_value_id: int32 (core ids < FIRST_USER_ANNOTATION_ID);
      ann_service_id: int32 (host's service, NO_SERVICE when hostless);
      ann_endpoint_id: int32 (NO_ENDPOINT when hostless).

    Binary-annotation table (length ``n_binary``):
      bann_span_idx: int32; bann_key_id: int32; bann_value_id: int32;
      bann_type: uint8 (AnnotationType); bann_service_id: int32;
      bann_endpoint_id: int32.
    """

    # span table
    trace_id: np.ndarray
    span_id: np.ndarray
    parent_id: np.ndarray
    name_id: np.ndarray
    service_id: np.ndarray
    ts_cs: np.ndarray
    ts_cr: np.ndarray
    ts_sr: np.ndarray
    ts_ss: np.ndarray
    ts_first: np.ndarray
    ts_last: np.ndarray
    duration: np.ndarray
    flags: np.ndarray

    # annotation table
    ann_span_idx: np.ndarray
    ann_ts: np.ndarray
    ann_value_id: np.ndarray
    ann_service_id: np.ndarray
    ann_endpoint_id: np.ndarray

    # binary-annotation table
    bann_span_idx: np.ndarray
    bann_key_id: np.ndarray
    bann_value_id: np.ndarray
    bann_type: np.ndarray
    bann_service_id: np.ndarray
    bann_endpoint_id: np.ndarray

    @property
    def n_spans(self) -> int:
        return int(self.trace_id.shape[0])

    @property
    def n_annotations(self) -> int:
        return int(self.ann_ts.shape[0])

    @property
    def n_binary(self) -> int:
        return int(self.bann_key_id.shape[0])

    SPAN_COLUMNS: Tuple[str, ...] = (
        "trace_id", "span_id", "parent_id", "name_id", "service_id",
        "ts_cs", "ts_cr", "ts_sr", "ts_ss", "ts_first", "ts_last",
        "duration", "flags",
    )
    ANN_COLUMNS: Tuple[str, ...] = (
        "ann_span_idx", "ann_ts", "ann_value_id", "ann_service_id",
        "ann_endpoint_id",
    )
    BANN_COLUMNS: Tuple[str, ...] = (
        "bann_span_idx", "bann_key_id", "bann_value_id", "bann_type",
        "bann_service_id", "bann_endpoint_id",
    )

    @staticmethod
    def empty(n_spans: int = 0, n_annotations: int = 0, n_binary: int = 0) -> "SpanBatch":
        return SpanBatch(
            trace_id=np.zeros(n_spans, np.int64),
            span_id=np.zeros(n_spans, np.int64),
            parent_id=np.full(n_spans, NO_PARENT, np.int64),
            name_id=np.zeros(n_spans, np.int32),
            service_id=np.full(n_spans, NO_SERVICE, np.int32),
            ts_cs=np.full(n_spans, NO_TS, np.int64),
            ts_cr=np.full(n_spans, NO_TS, np.int64),
            ts_sr=np.full(n_spans, NO_TS, np.int64),
            ts_ss=np.full(n_spans, NO_TS, np.int64),
            ts_first=np.full(n_spans, NO_TS, np.int64),
            ts_last=np.full(n_spans, NO_TS, np.int64),
            duration=np.full(n_spans, NO_TS, np.int64),
            flags=np.zeros(n_spans, np.uint8),
            ann_span_idx=np.zeros(n_annotations, np.int32),
            ann_ts=np.zeros(n_annotations, np.int64),
            ann_value_id=np.zeros(n_annotations, np.int32),
            ann_service_id=np.full(n_annotations, NO_SERVICE, np.int32),
            ann_endpoint_id=np.full(n_annotations, NO_ENDPOINT, np.int32),
            bann_span_idx=np.zeros(n_binary, np.int32),
            bann_key_id=np.zeros(n_binary, np.int32),
            bann_value_id=np.zeros(n_binary, np.int32),
            bann_type=np.zeros(n_binary, np.uint8),
            bann_service_id=np.full(n_binary, NO_SERVICE, np.int32),
            bann_endpoint_id=np.full(n_binary, NO_ENDPOINT, np.int32),
        )

    def concat(self, other: "SpanBatch") -> "SpanBatch":
        """Append ``other``'s rows after self's (span_idx refs re-based)."""
        out = {}
        for col in self.SPAN_COLUMNS:
            out[col] = np.concatenate([getattr(self, col), getattr(other, col)])
        base = self.n_spans
        for col in self.ANN_COLUMNS + self.BANN_COLUMNS:
            a, b = getattr(self, col), getattr(other, col)
            if col.endswith("span_idx"):
                b = b + np.int32(base)
            out[col] = np.concatenate([a, b])
        return SpanBatch(**out)

    def select(self, span_rows: np.ndarray) -> "SpanBatch":
        """Row-subset batch for the given span rows (bool mask or indices)."""
        if span_rows.dtype == np.bool_:
            span_rows = np.flatnonzero(span_rows)
        remap = np.full(self.n_spans, -1, np.int64)
        remap[span_rows] = np.arange(len(span_rows))
        out = {c: getattr(self, c)[span_rows] for c in self.SPAN_COLUMNS}
        ann_keep = remap[self.ann_span_idx] >= 0
        bann_keep = remap[self.bann_span_idx] >= 0
        for col in self.ANN_COLUMNS:
            v = getattr(self, col)[ann_keep]
            out[col] = remap[v].astype(np.int32) if col == "ann_span_idx" else v
        for col in self.BANN_COLUMNS:
            v = getattr(self, col)[bann_keep]
            out[col] = remap[v].astype(np.int32) if col == "bann_span_idx" else v
        return SpanBatch(**out)
