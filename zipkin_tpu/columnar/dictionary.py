"""Host-side dictionary encoding: strings/bytes/endpoints ↔ small ints.

Parity note: plays the role of the reference's HBase dictionary mappers
(zipkin-hbase/.../mapping/ServiceMapper.scala, SpanNameMapper.scala,
AnnotationMapper.scala with utils/IDGenerator.scala:8) — but as a plain
in-process map, since in this framework the dictionaries never leave the
host and the device only ever sees the ids.
"""

from __future__ import annotations

import threading
from typing import Dict, Hashable, List, Optional

from zipkin_tpu.models.constants import (
    CORE_ANNOTATION_IDS,
    FIRST_USER_ANNOTATION_ID,
)
from zipkin_tpu.models.span import Endpoint


class Dictionary:
    """Bidirectional value↔id map with dense int ids.

    Thread-safe (ingest workers encode concurrently). Ids are assigned
    densely from ``first_id`` in first-seen order, which keeps device-side
    arrays (e.g. per-service counters indexed by service_id) compact.
    """

    def __init__(self, first_id: int = 0, reserved: Optional[Dict[Hashable, int]] = None):
        self._lock = threading.Lock()  # lock-order: 78 dictionary
        self._to_id: Dict[Hashable, int] = {}
        self._values: List[Hashable] = []
        self._first_id = first_id
        if reserved:
            top = max(reserved.values()) + 1
            self._values = [None] * (max(top, first_id) - first_id)
            for value, vid in reserved.items():
                self._to_id[value] = vid
                self._values[vid - first_id] = value

    def encode(self, value: Hashable) -> int:
        """Return the id for ``value``, assigning a new one if unseen."""
        got = self._to_id.get(value)
        if got is not None:
            return got
        with self._lock:
            got = self._to_id.get(value)
            if got is not None:
                return got
            vid = self._first_id + len(self._values)
            self._values.append(value)
            self._to_id[value] = vid
            return vid

    def get(self, value: Hashable) -> Optional[int]:
        """Id for ``value`` or None if never seen (no assignment)."""
        return self._to_id.get(value)

    def decode(self, vid: int):
        return self._values[vid - self._first_id]

    def __len__(self) -> int:
        return len(self._values)

    def __contains__(self, value: Hashable) -> bool:
        return value in self._to_id

    def values(self) -> List[Hashable]:
        return list(self._values)

    def items(self):
        return [(v, self._first_id + i) for i, v in enumerate(self._values)]


class DictionarySet:
    """The full set of dictionaries one store/pipeline shares.

    - ``services``: lowercased service names
    - ``span_names``: span (rpc) names
    - ``annotations``: annotation values; core cs/cr/sr/ss/ca/sa ids are
      reserved (models/constants.CORE_ANNOTATION_IDS) so device kernels can
      exclude core annotations with ``id < FIRST_USER_ANNOTATION_ID``
    - ``binary_keys`` / ``binary_values``: binary-annotation key strings and
      value bytes (values dictionary-encoded so decode is lossless)
    - ``endpoints``: (ipv4, port, service_name) triples
    """

    def __init__(self):
        self.services = Dictionary()
        self.span_names = Dictionary()
        self.annotations = Dictionary(
            reserved=dict(CORE_ANNOTATION_IDS),
        )
        # Make sure user annotation values start at the reserved boundary.
        while len(self.annotations) < FIRST_USER_ANNOTATION_ID:
            self.annotations.encode(f"__reserved_{len(self.annotations)}__")
        self.binary_keys = Dictionary()
        self.binary_values = Dictionary()
        self.endpoints = Dictionary()

    def encode_endpoint(self, ep: Endpoint) -> int:
        return self.endpoints.encode((ep.ipv4, ep.port, ep.service_name))

    def decode_endpoint(self, eid: int) -> Endpoint:
        ipv4, port, service_name = self.endpoints.decode(eid)
        return Endpoint(ipv4=ipv4, port=port, service_name=service_name)
