"""Span ↔ SpanBatch codec.

Encoding happens on the host ingest path (the analogue of the reference's
thrift→common.Span ``SpanConvertingFilter``, ZipkinCollectorFactory.scala:30,
fused with the HBase-style dictionary mapping); decoding happens on the
query path when a trace is materialised back into span objects.

Lossless: every field of Span/Annotation/BinaryAnnotation survives a
roundtrip (binary-annotation values are dictionary-encoded, not hashed).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.schema import (
    FLAG_DEBUG,
    FLAG_HAS_PARENT,
    NO_ENDPOINT,
    NO_PARENT,
    NO_SERVICE,
    NO_TS,
    SpanBatch,
)
from zipkin_tpu.models.constants import (
    CLIENT_RECV,
    CLIENT_SEND,
    SERVER_RECV,
    SERVER_SEND,
)
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Span,
)

_CORE_TS_FIELD = {
    CLIENT_SEND: "ts_cs",
    CLIENT_RECV: "ts_cr",
    SERVER_RECV: "ts_sr",
    SERVER_SEND: "ts_ss",
}


def _norm_value(value: object, ann_type: AnnotationType):
    """Canonical hashable form for dictionary encoding of binary values."""
    if isinstance(value, bytearray):
        return bytes(value)
    return value


def to_signed64(x: int) -> int:
    """Canonicalise a python int to signed 64-bit (the wire interpretation)."""
    x &= 0xFFFFFFFFFFFFFFFF
    return x - 0x10000000000000000 if x >= 0x8000000000000000 else x


class SpanCodec:
    """Encode python spans into a SpanBatch and back, sharing dictionaries."""

    def __init__(self, dictionaries: Optional[DictionarySet] = None):
        self.dicts = dictionaries if dictionaries is not None else DictionarySet()

    # -- encode ---------------------------------------------------------

    def encode(self, spans: Sequence[Span]) -> SpanBatch:
        n = len(spans)
        n_ann = sum(len(s.annotations) for s in spans)
        n_bann = sum(len(s.binary_annotations) for s in spans)
        b = SpanBatch.empty(n, n_ann, n_bann)
        d = self.dicts
        ai = bi = 0
        for i, s in enumerate(spans):
            b.trace_id[i] = to_signed64(s.trace_id)
            b.span_id[i] = to_signed64(s.id)
            flags = 0
            if s.debug:
                flags |= int(FLAG_DEBUG)
            if s.parent_id is not None:
                flags |= int(FLAG_HAS_PARENT)
                b.parent_id[i] = to_signed64(s.parent_id)
            b.flags[i] = flags
            b.name_id[i] = d.span_names.encode(s.name)
            svc = s.service_name
            b.service_id[i] = (
                d.services.encode(svc.lower()) if svc is not None else NO_SERVICE
            )
            ts_first = ts_last = None
            for a in s.annotations:
                b.ann_span_idx[ai] = i
                b.ann_ts[ai] = a.timestamp
                b.ann_value_id[ai] = d.annotations.encode(a.value)
                if a.host is not None:
                    b.ann_service_id[ai] = d.services.encode(
                        a.host.service_name.lower()
                    )
                    b.ann_endpoint_id[ai] = d.encode_endpoint(a.host)
                ai += 1
                core_field = _CORE_TS_FIELD.get(a.value)
                if core_field is not None:
                    getattr(b, core_field)[i] = a.timestamp
                if ts_first is None or a.timestamp < ts_first:
                    ts_first = a.timestamp
                if ts_last is None or a.timestamp > ts_last:
                    ts_last = a.timestamp
            if ts_first is not None:
                b.ts_first[i] = ts_first
                b.ts_last[i] = ts_last
                b.duration[i] = ts_last - ts_first
            for ba in s.binary_annotations:
                b.bann_span_idx[bi] = i
                b.bann_key_id[bi] = d.binary_keys.encode(ba.key)
                b.bann_value_id[bi] = d.binary_values.encode(
                    _norm_value(ba.value, ba.annotation_type)
                )
                b.bann_type[bi] = int(ba.annotation_type)
                if ba.host is not None:
                    b.bann_service_id[bi] = d.services.encode(
                        ba.host.service_name.lower()
                    )
                    b.bann_endpoint_id[bi] = d.encode_endpoint(ba.host)
                bi += 1
        return b

    # -- decode ---------------------------------------------------------

    def decode(self, batch: SpanBatch) -> List[Span]:
        d = self.dicts
        n = batch.n_spans
        anns: List[list] = [[] for _ in range(n)]
        banns: List[list] = [[] for _ in range(n)]
        for j in range(batch.n_annotations):
            i = int(batch.ann_span_idx[j])
            eid = int(batch.ann_endpoint_id[j])
            host = d.decode_endpoint(eid) if eid != NO_ENDPOINT else None
            anns[i].append(
                Annotation(
                    timestamp=int(batch.ann_ts[j]),
                    value=d.annotations.decode(int(batch.ann_value_id[j])),
                    host=host,
                )
            )
        for j in range(batch.n_binary):
            i = int(batch.bann_span_idx[j])
            eid = int(batch.bann_endpoint_id[j])
            host = d.decode_endpoint(eid) if eid != NO_ENDPOINT else None
            banns[i].append(
                BinaryAnnotation(
                    key=d.binary_keys.decode(int(batch.bann_key_id[j])),
                    value=d.binary_values.decode(int(batch.bann_value_id[j])),
                    annotation_type=AnnotationType(int(batch.bann_type[j])),
                    host=host,
                )
            )
        out = []
        for i in range(n):
            flags = int(batch.flags[i])
            out.append(
                Span(
                    trace_id=int(batch.trace_id[i]),
                    name=d.span_names.decode(int(batch.name_id[i])),
                    id=int(batch.span_id[i]),
                    parent_id=(
                        int(batch.parent_id[i]) if flags & int(FLAG_HAS_PARENT) else None
                    ),
                    annotations=tuple(anns[i]),
                    binary_annotations=tuple(banns[i]),
                    debug=bool(flags & int(FLAG_DEBUG)),
                )
            )
        return out
