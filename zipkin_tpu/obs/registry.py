"""Metric types + registry (the stats-receiver role, host side).

Everything here is plain python/numpy and thread-safe: these objects
are bumped from collector queue workers, API handler threads, and the
store's write path concurrently. The latency sketch intentionally
reuses the repo's sketch math instead of inventing a third histogram:

- bucketing is the DDSketch log-histogram of ``ops.quantile`` (same
  gamma formula, same geometric-midpoint quantile read via
  ``quantiles_host``), so a host sketch and a device sketch with equal
  (alpha, min_value, n_buckets) merge by plain ``+``;
- central moments are ``models.dependencies.Moments`` (the algebird
  monoid, bit-identical to the device ``ops.moments.combine``), so
  mean/stddev come from the same arithmetic the dependency links use.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu.models.dependencies import Moments

DEFAULT_QUANTILES = (0.5, 0.99)
# 1024 buckets at alpha=0.01 span a ~8e8 relative range: 1 µs .. ~13 min
# when observing seconds with min_value=1e-6.
DEFAULT_ALPHA = 0.01
DEFAULT_BUCKETS = 1024


def _fmt(v) -> str:
    """Prometheus sample value: integers render bare, floats via repr
    (shortest round-trip), non-finite as NaN/+Inf/-Inf."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


class Metric:
    """Base: name, help, prometheus type, optional label dimensions.

    With ``labelnames`` set, the metric is a family: ``labels(k=v)``
    returns (creating on first use) the child for those label values;
    the parent itself carries no samples.
    """

    prom_type = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()  # lock-order: 90 metric (leaf)
        self._children: Dict[Tuple[str, ...], "Metric"] = {}  # guarded-by: _lock

    def labels(self, **kv) -> "Metric":
        if tuple(sorted(kv)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _make_child(self) -> "Metric":
        raise NotImplementedError

    def _child_items(self) -> List[Tuple[Tuple[Tuple[str, str], ...],
                                         "Metric"]]:
        with self._lock:
            return [
                (tuple(zip(self.labelnames, key)), child)
                for key, child in sorted(self._children.items())
            ]

    def samples(self) -> Iterable[Tuple[str, tuple, float]]:
        """(name_suffix, ((label, value), ...), value) triples."""
        if self.labelnames:
            for labels, child in self._child_items():
                for suffix, sub, v in child.samples():
                    yield suffix, labels + sub, v
            return
        yield from self._own_samples()

    def _own_samples(self):
        return ()


class Counter(Metric):
    """Monotonic counter. ``fn``-backed counters read an external
    monotonic source at scrape time (adapting pre-registry accounting
    like the sampler's allowed/denied) instead of owning the count."""

    prom_type = "counter"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help, labelnames)
        self._value = 0
        self._fn = fn

    def _make_child(self) -> "Counter":
        return Counter(self.name, self.help)

    def inc(self, n: float = 1) -> None:
        if self._fn is not None:
            raise RuntimeError(f"{self.name} is function-backed")
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self):
        if self._fn is not None:
            return self._fn()
        with self._lock:
            return self._value

    def _own_samples(self):
        yield "", (), self.value


class Gauge(Metric):
    """Point-in-time value; ``fn``-backed gauges read live state
    (queue depth, sampler rate) at scrape time."""

    prom_type = "gauge"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help, labelnames)
        self._value = 0.0
        self._fn = fn

    def _make_child(self) -> "Gauge":
        return Gauge(self.name, self.help)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            self._fn = None

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            try:
                return fn()
            except Exception:  # graftlint: disable=swallowed-exception
                return float("nan")  # NaN IS the broken-callback signal
        with self._lock:
            return self._value

    def _own_samples(self):
        yield "", (), self.value


class CallbackFamily(Metric):
    """A labeled gauge family whose samples come from one callback
    returning ``{label_value: number}`` — the adapter for existing
    snapshot hooks like ``SpanStore.counters()``, which already
    aggregate on their own locks and would be awkward to re-plumb as
    individual gauges."""

    prom_type = "gauge"

    def __init__(self, name: str, help: str, label: str,
                 fn: Callable[[], Dict[str, float]]):
        super().__init__(name, help, (label,))
        self._fn = fn

    def samples(self):
        try:
            values = self._fn()
        except Exception:  # graftlint: disable=swallowed-exception
            return  # absent family = the broken-callback signal
        label = self.labelnames[0]
        for k in sorted(values):
            yield "", ((label, str(k)),), values[k]


class LatencySketch(Metric):
    """Mergeable latency/size distribution: log-histogram buckets
    (ops.quantile math) + streaming central moments (the Moments
    monoid). Rendered as a Prometheus summary: one ``{quantile=...}``
    line per requested quantile plus ``_sum``/``_count``.

    ``observe`` takes seconds for latency metrics by convention
    (min_value 1e-6 = microsecond resolution); size distributions pass
    ``min_value=1.0``.
    """

    prom_type = "summary"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 alpha: float = DEFAULT_ALPHA,
                 n_buckets: int = DEFAULT_BUCKETS,
                 min_value: float = 1e-6,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES):
        super().__init__(name, help, labelnames)
        self.alpha = alpha
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.min_value = min_value
        self.quantiles = tuple(quantiles)
        self.counts = np.zeros(n_buckets, np.int64)
        self.moments = Moments.zero()
        self._sum = 0.0

    def _make_child(self) -> "LatencySketch":
        return LatencySketch(
            self.name, self.help, alpha=self.alpha,
            n_buckets=len(self.counts), min_value=self.min_value,
            quantiles=self.quantiles,
        )

    def observe(self, value: float) -> None:
        idx = math.ceil(
            math.log(max(value, self.min_value) / self.min_value)
            / self._log_gamma
        )
        idx = min(max(int(idx), 0), len(self.counts) - 1)
        with self._lock:
            self.counts[idx] += 1
            self.moments = self.moments + Moments.of(float(value))
            self._sum += float(value)

    def merge(self, other: "LatencySketch") -> None:
        """Fold another sketch in (same bucketing required) — the
        cross-process / cross-shard aggregation path."""
        if (other.gamma != self.gamma
                or other.min_value != self.min_value
                or len(other.counts) != len(self.counts)):
            raise ValueError("sketch layouts differ")
        with other._lock:
            counts = other.counts.copy()
            moments, s = other.moments, other._sum
        with self._lock:
            self.counts += counts
            self.moments = self.moments + moments
            self._sum += s

    @property
    def count(self) -> int:
        with self._lock:
            return int(self.moments.n)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile_values(self, qs: Optional[Sequence[float]] = None
                        ) -> List[float]:
        """Quantile estimates via the same host read the per-service
        duration histogram uses (ops.quantile.quantiles_host); NaN when
        empty."""
        from zipkin_tpu.ops.quantile import quantiles_host

        with self._lock:
            counts = self.counts.copy()
        return quantiles_host(
            counts, self.gamma, self.min_value, list(qs or self.quantiles)
        )

    def snapshot(self) -> Dict[str, float]:
        """Summary dict for BENCH json / as_dict."""
        with self._lock:
            m = self.moments
            s = self._sum
        out = {"count": float(m.n), "sum": s,
               "mean": m.mean if m.n else float("nan"),
               "stddev": (math.sqrt(m.m2 / m.n)
                          if m.n else float("nan"))}
        for q, v in zip(self.quantiles, self.quantile_values()):
            out[f"p{int(q * 100)}"] = float(v)
        return out

    def _own_samples(self):
        for q, v in zip(self.quantiles, self.quantile_values()):
            yield "", (("quantile", _fmt(q)),), v
        yield "_sum", (), self.sum
        yield "_count", (), self.count


class Registry:
    """Name → metric map with replace-on-reregister semantics."""

    def __init__(self):
        # Held only for map mutation/snapshot — samples() and gauge
        # callbacks run OUTSIDE it (collect() snapshots), so this is a
        # leaf despite exposition fanning out into other locks.
        self._lock = threading.Lock()  # lock-order: 84 registry
        self._metrics: Dict[str, Metric] = {}  # guarded-by: _lock

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            self._metrics[metric.name] = metric
        return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # -- views ----------------------------------------------------------

    def render_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for m in self.collect():
            lines.append(f"# HELP {m.name} {escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.prom_type}")
            for suffix, labels, value in m.samples():
                lines.append(
                    f"{m.name}{suffix}{_label_str(labels)} {_fmt(value)}"
                )
        return "\n".join(lines) + "\n"

    def as_dict(self) -> Dict[str, float]:
        """Flat snapshot: sample key → value (summary quantiles keyed
        like their exposition lines)."""
        out: Dict[str, float] = {}
        for m in self.collect():
            for suffix, labels, value in m.samples():
                try:
                    out[f"{m.name}{suffix}{_label_str(labels)}"] = float(
                        value
                    )
                except (TypeError, ValueError):
                    continue
        return out


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry every stage registers into by default."""
    return _DEFAULT
