"""Process-wide telemetry: counters, gauges, latency sketches.

The reference wires Finagle/Ostrich stats receivers through every
pipeline stage (ZipkinCollectorFactory's statsReceiver plumbing); this
package is that layer for the reproduction, built on the repo's own
sketch primitives: latency distributions are a host-side twin of
``ops.quantile``'s mergeable log-histogram plus ``models.dependencies``'
streaming Moments (the algebird monoid) — so per-stage sketches stay
mergeable across processes and (later) shards, exactly the
"disaggregation across time and space" property PAPERS.md motivates.

Three consumers:

- ``Registry.render_text()`` — Prometheus text exposition (the API's
  ``GET /metrics``; the JSON form stays at ``/metrics?format=json``);
- ``Registry.as_dict()`` — flat snapshot for BENCH json / debugging;
- self-tracing (api.server + ingest.collector) — the pipeline records
  genuine Zipkin spans about itself into its own store under the
  ``zipkin-tpu`` service name.

Components take a ``registry`` argument defaulting to the process-wide
instance (``default_registry()``); registering a name twice replaces
the earlier metric (newest pipeline object wins — the earlier one keeps
counting into its own, now-unscraped, object).

The fleet layer (``obs.fleet``, r17) extends all three consumers
across process boundaries: causal self-tracing over the ship
protocol, pushed-snapshot metrics federation (``/metrics?fleet=1``),
and the stall watchdog + flight recorder behind ``/api/health`` /
``/debug/events``.
"""

from zipkin_tpu.obs.fleet import (
    FleetObs,
    FlightRecorder,
    FollowerLineage,
    LineageTracker,
    Watchdog,
    merge_sketches,
    registry_snapshot,
    render_federated,
)
from zipkin_tpu.obs.registry import (
    CallbackFamily,
    Counter,
    Gauge,
    LatencySketch,
    Registry,
    default_registry,
)

__all__ = [
    "CallbackFamily",
    "Counter",
    "FleetObs",
    "FlightRecorder",
    "FollowerLineage",
    "Gauge",
    "LatencySketch",
    "LineageTracker",
    "Registry",
    "Watchdog",
    "default_registry",
    "merge_sketches",
    "registry_snapshot",
    "render_federated",
]
