"""Fleet observability: causal self-tracing across process
boundaries, metrics federation, and a stall watchdog.

PR 2 made the process observable (``obs/registry``); PRs 10–11 made
the deployment a fleet (shipped followers, sharded primaries) — this
module makes the FLEET observable, with the Dapper move the source
paper is built on: the tracer traces itself *causally across the ship
protocol*.

Four pieces, all host-side (zero new device ops — the step census is
unchanged, gated in ``bench_smoke.run_fleet_obs``):

**Lineage tracing** (``LineageTracker``, primary side). Every launch
unit's WAL record is stamped with its commit timestamp (``ts``), and a
sampled subset additionally carries a B3 context (``b3``) minted at
stage-1 encode. The tracker then emits genuine Zipkin spans into the
system's own store as the unit moves through the pipeline: an
``ingest unit`` root plus ``wal append`` / ``wal fsync`` / ``ship``
children on the primary, and — because the context rides the shipped
record itself — a ``replica apply`` / ``standby apply`` child minted
by the follower and BACKHAULED to the primary in FETCH request meta
(followers are read-only or bitwise-mirrored; they cannot write spans
locally). The result: one causally-linked trace per sampled unit
spanning encode→WAL→fsync→ship→apply, queryable through the system's
own ``/api/traces``, and ``/api/dependencies`` renders the live fleet
topology from the cross-service parent/child edges.

**Follower half** (``FollowerLineage``). Reads the lineage keys off
each shipped record (``wal.record.unit_meta``), derives the
commit-to-visible lag (``zipkin_replication_visible_lag_seconds`` +
a ``lagSeconds`` gauge), buffers apply spans for the next FETCH, and
throttles registry-snapshot pushes to the primary.

**Metrics federation** (``registry_snapshot`` / ``render_federated``).
The ship topology is follower-pulls, so the primary cannot scrape its
followers: followers *push* registry snapshots in FETCH meta instead.
The primary serves a merged ``/metrics?fleet=1`` — every sample from
every process, distinguished by injected ``role``/``follower`` labels
(label-distinguished = no double counting), values formatted through
the same ``_fmt`` as the per-process scrape (bitwise-consistent), one
HELP/TYPE line per family. Latency sketches additionally ship their
raw bucket counts + Moments so fleet roll-ups are a true monoid merge
(``merge_sketches``), per "Sketch Disaggregation Across Time and
Space".

**Watchdog + flight recorder** (``Watchdog``, ``FlightRecorder``).
Named probes over the async machinery (pipeline-prefetch stall,
parked fsync thread, sealer backlog at cap, dispatcher queue stuck,
follower lag past threshold) evaluated on demand — probes run
OUTSIDE the watchdog's own lock, because they acquire component locks
of every rank. ``/api/health`` serves liveness/readiness with
reasons; state *transitions* land in a bounded in-memory structured
event ring served at ``/debug/events``.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import random
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu.models.dependencies import Moments
from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.obs.registry import (
    Counter,
    Gauge,
    LatencySketch,
    Registry,
    _fmt,
    _label_str,
    escape_help,
)

# ---------------------------------------------------------------------------
# request-context propagation (API handler → dispatcher / downstream)
# ---------------------------------------------------------------------------

# (trace_id, span_id) of the request currently being served on this
# task — set by the API server around traced handlers so downstream
# machinery (the cross-shard dispatcher) can parent its spans.
_REQUEST_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "zipkin_tpu_fleet_b3", default=None)


def set_request_context(trace_id: int, span_id: int):
    """Bind the active request's B3 context; returns the reset token."""
    return _REQUEST_CTX.set((int(trace_id), int(span_id)))


def reset_request_context(token) -> None:
    _REQUEST_CTX.reset(token)


def current_request_context() -> Optional[Tuple[int, int]]:
    return _REQUEST_CTX.get()


# ---------------------------------------------------------------------------
# wire span codec (backhauled follower spans / dispatcher spans)
# ---------------------------------------------------------------------------

def make_span(trace_id: int, span_id: int, parent_id: Optional[int],
              name: str, service: str, start_us: int, duration_us: int,
              tags: Optional[Dict[str, str]] = None) -> Span:
    """A genuine server-side Zipkin span (sr/ss pair) for a fleet
    self-trace event."""
    ep = Endpoint(0, 0, service)
    return Span(
        int(trace_id), name, int(span_id),
        None if parent_id is None else int(parent_id),
        (Annotation(int(start_us), "sr", ep),
         Annotation(int(start_us) + max(int(duration_us), 1), "ss", ep)),
        tuple(BinaryAnnotation(k, str(v), host=ep)
              for k, v in sorted((tags or {}).items())),
    )


def span_to_wire(trace_id: int, span_id: int, parent_id: Optional[int],
                 name: str, service: str, start_us: int, duration_us: int,
                 tags: Optional[Dict[str, str]] = None) -> dict:
    """Compact JSON form for FETCH-meta backhaul (ints stay ints —
    json round-trips 64-bit span ids exactly)."""
    return {"traceId": int(trace_id), "id": int(span_id),
            "parentId": None if parent_id is None else int(parent_id),
            "name": name, "service": service, "ts": int(start_us),
            "dur": int(duration_us), "tags": dict(tags or {})}


def span_from_wire(d: dict) -> Span:
    """Inverse of ``span_to_wire`` (primary side). Raises on a
    malformed dict — callers isolate per span."""
    return make_span(d["traceId"], d["id"], d.get("parentId"),
                     str(d.get("name", "span")),
                     str(d.get("service", "zipkin-tpu")),
                     d["ts"], d.get("dur", 1), d.get("tags"))


def _new_id(rng: random.Random) -> int:
    return rng.getrandbits(63) + 1


# ---------------------------------------------------------------------------
# lineage tracing — primary side
# ---------------------------------------------------------------------------

class _UnitCtx:
    """Pending lineage state for one sampled launch unit."""

    __slots__ = ("trace_id", "root_id", "start_us", "append_us",
                 "durable_us")

    def __init__(self, trace_id: int, root_id: int, start_us: int):
        self.trace_id = trace_id
        self.root_id = root_id
        self.start_us = start_us
        self.append_us = start_us
        self.durable_us: Optional[int] = None


class LineageTracker:
    """Primary-side lineage tracer: stamps WAL records, emits the
    per-stage spans, ingests backhauled follower spans.

    ``sink`` is the span write target — ``store.apply`` in production
    (spans land in the system's own store, ride the WAL, and therefore
    replicate to standbys bitwise like any other span).

    Threading: ``stamp``/``note_append`` run on the encoding thread
    UNDER the store's encode lock, so they only ever buffer;
    ``on_durable`` runs on the WAL's group-commit thread (no locks
    held) — or, under ``fsync=off``/``batch``, synchronously inside
    ``wal.append`` while the encode lock is still held, which is why
    the store wraps the append in ``suppressed()`` (flushing there
    would re-enter the encode lock). Flushes happen from ``on_durable``
    (sync thread), ``note_shipped`` (ship handler thread), and
    ``flush()`` — all outside the store's write path. The sink call
    itself sets a thread-local ``emitting`` flag so the spans' own
    journaling is never sampled (no feedback trace)."""

    SAMPLE_EVERY = 64   # first unit always sampled
    FLUSH_AT = 32       # buffered spans per sink call (launch amortization)
    MAX_PENDING = 4096  # sampled units awaiting fsync/ship

    def __init__(self, sink: Callable[[List[Span]], None],
                 registry: Optional[Registry] = None,
                 service_name: str = "zipkin-tpu",
                 sample_every: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        self.sink = sink
        self.service_name = service_name
        self.sample_every = max(int(sample_every or self.SAMPLE_EVERY), 1)
        self._clock = clock
        self._lock = threading.Lock()  # lock-order: 82 fleet-trace
        self._tl = threading.local()
        self._rng = random.Random()          # guarded-by: _lock
        self._units = 0                      # guarded-by: _lock
        self._pending = collections.OrderedDict()  # guarded-by: _lock
        self._buf: List[Span] = []           # guarded-by: _lock
        reg = registry
        self._h_stage = None
        self._c_units = None
        self._c_drops = None
        if reg is not None:
            self._h_stage = reg.register(LatencySketch(
                "zipkin_lineage_stage_seconds",
                "Per-stage latency of sampled launch units "
                "(commit-to-visible decomposition)",
                labelnames=("stage",)))
            self._c_units = reg.register(Counter(
                "zipkin_lineage_units_total",
                "Launch units stamped with a sampled lineage context"))
            self._c_drops = reg.register(Counter(
                "zipkin_lineage_spans_dropped_total",
                "Lineage spans dropped (failed sink write or pending-"
                "table overflow)"))

    # -- stage-1 stamping (encode thread, under the store's encode lock)

    def stamp(self) -> Dict[str, object]:
        """Extra WAL-record meta for the unit being journaled: always
        the commit timestamp, plus a fresh B3 context for sampled
        units. Never samples the tracker's own span batches (the
        ``emitting`` flag breaks the feedback loop)."""
        now_us = int(self._clock() * 1e6)
        extra: Dict[str, object] = {"ts": now_us}
        if getattr(self._tl, "emitting", False):
            return extra
        with self._lock:
            n = self._units
            self._units += 1
            if n % self.sample_every:
                return extra
            tid = _new_id(self._rng)
            sid = _new_id(self._rng)
        extra["b3"] = [tid, sid]
        return extra

    def note_append(self, seq: int, extra: Dict[str, object]) -> None:
        """Record the appended unit's context + emit (buffer) the root
        and append spans. Called under the store's encode lock —
        buffers only, never flushes."""
        b3 = extra.get("b3") if extra else None
        if not b3:
            return
        now_us = int(self._clock() * 1e6)
        start_us = int(extra["ts"])
        ctx = _UnitCtx(int(b3[0]), int(b3[1]), start_us)
        ctx.append_us = now_us
        dropped = None
        with self._lock:
            self._pending[int(seq)] = ctx
            if len(self._pending) > self.MAX_PENDING:
                dropped = self._pending.popitem(last=False)
            append_id = _new_id(self._rng)
        if self._c_units is not None:
            self._c_units.inc()
        if dropped is not None and self._c_drops is not None:
            self._c_drops.inc()
        dur = max(now_us - start_us, 1)
        self._observe("append", dur)
        self._push([
            make_span(ctx.trace_id, ctx.root_id, None, "ingest unit",
                      self.service_name, start_us, dur,
                      {"wal.seq": str(seq)}),
            make_span(ctx.trace_id, append_id, ctx.root_id, "wal append",
                      self.service_name, start_us, dur,
                      {"wal.seq": str(seq)}),
        ], flush=False)

    @contextlib.contextmanager
    def suppressed(self):
        """No-flush guard for callbacks fired synchronously inside the
        store's write path (``fsync=off``/``batch`` appends invoke
        ``on_durable`` on the appending thread)."""
        prev = getattr(self._tl, "suppress", False)
        self._tl.suppress = True
        try:
            yield
        finally:
            self._tl.suppress = prev

    # -- downstream stages ----------------------------------------------

    def on_durable(self, durable_seq: int) -> None:
        """WAL durable-frontier callback: emit ``wal fsync`` children
        for every pending unit now covered. Runs on the group-commit
        thread (flushes) or inside an append under ``suppressed()``
        (buffers only)."""
        now_us = int(self._clock() * 1e6)
        spans: List[Span] = []
        with self._lock:
            for seq, ctx in self._pending.items():
                if seq > durable_seq or ctx.durable_us is not None:
                    continue
                ctx.durable_us = now_us
                spans.append((ctx, _new_id(self._rng), seq))
        for ctx, sid, seq in spans:
            dur = max(now_us - ctx.append_us, 1)
            self._observe("fsync", dur)
            self._push([make_span(
                ctx.trace_id, sid, ctx.root_id, "wal fsync",
                self.service_name, ctx.append_us, dur,
                {"wal.seq": str(seq)})], flush=False)
        if spans:
            self._maybe_flush()

    def ctx_for(self, seq: int) -> Optional[Tuple[int, int]]:
        """(trace_id, root_span_id) of a sampled record, for shippers."""
        with self._lock:
            ctx = self._pending.get(int(seq))
            return None if ctx is None else (ctx.trace_id, ctx.root_id)

    def note_shipped(self, seq: int, follower: str) -> None:
        """Emit the ``ship`` child for one sampled record sent to one
        follower (ship handler thread)."""
        now_us = int(self._clock() * 1e6)
        with self._lock:
            ctx = self._pending.get(int(seq))
            if ctx is None:
                return
            sid = _new_id(self._rng)
        from_us = ctx.durable_us or ctx.append_us
        dur = max(now_us - from_us, 1)
        self._observe("ship", dur)
        self._push([make_span(
            ctx.trace_id, sid, ctx.root_id, "ship",
            self.service_name, from_us, dur,
            {"wal.seq": str(seq), "follower": follower})])

    def ingest_remote_spans(self, follower: str,
                            wire_spans: Sequence[dict]) -> int:
        """Backhauled follower spans (FETCH meta) → the primary store.
        Malformed entries are dropped and counted, never raised."""
        spans: List[Span] = []
        for d in wire_spans:
            try:
                spans.append(span_from_wire(d))
                if d.get("name", "").endswith("apply"):
                    self._observe("apply", int(d.get("dur", 1)))
            except Exception:  # graftlint: disable=swallowed-exception
                if self._c_drops is not None:
                    self._c_drops.inc()
        if spans:
            self._push(spans)
        return len(spans)

    def record_span(self, trace_id: int, parent_id: Optional[int],
                    name: str, start_us: int, duration_us: int,
                    tags: Optional[Dict[str, str]] = None) -> int:
        """Generic child-span hook (the dispatcher's ``shard dispatch``
        spans); returns the new span id."""
        with self._lock:
            sid = _new_id(self._rng)
        self._push([make_span(trace_id, sid, parent_id, name,
                              self.service_name, start_us, duration_us,
                              tags)])
        return sid

    # -- buffering / emission -------------------------------------------

    def _observe(self, stage: str, dur_us: float) -> None:
        if self._h_stage is not None:
            self._h_stage.labels(stage=stage).observe(
                max(dur_us, 1) / 1e6)

    def _push(self, spans: List[Span], flush: bool = True) -> None:
        with self._lock:
            self._buf.extend(spans)
        if flush:
            self._maybe_flush()

    def _maybe_flush(self, force: bool = False) -> None:
        if getattr(self._tl, "suppress", False):
            return
        with self._lock:
            if not self._buf or (not force
                                 and len(self._buf) < self.FLUSH_AT):
                return
            batch, self._buf = self._buf, []
        self._tl.emitting = True
        try:
            self.sink(batch)
        except Exception:  # graftlint: disable=swallowed-exception
            # Self-tracing must never fail the pipeline it observes.
            if self._c_drops is not None:
                self._c_drops.inc(len(batch))
        finally:
            self._tl.emitting = False

    def flush(self) -> None:
        self._maybe_flush(force=True)

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)


# ---------------------------------------------------------------------------
# lineage — follower side
# ---------------------------------------------------------------------------

class FollowerLineage:
    """Follower half of the lineage trace: reads the stamped keys off
    each shipped record, derives visible lag, buffers apply spans for
    backhaul, and throttles registry-snapshot pushes."""

    MAX_BACKLOG = 512          # buffered apply spans awaiting a FETCH
    METRICS_PUSH_INTERVAL_S = 1.0

    def __init__(self, name: str, mode: str = "replica",
                 registry: Optional[Registry] = None,
                 service_name: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.name = name
        self.mode = mode
        self.service_name = service_name or f"zipkin-tpu-{name}"
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()  # lock-order: 81 follower-lineage
        self._rng = random.Random()    # guarded-by: _lock
        self._spans: List[dict] = []   # guarded-by: _lock
        self._lag_s: Optional[float] = None  # guarded-by: _lock
        self._last_push_s: Optional[float] = None  # guarded-by: _lock
        self._h_lag = None
        self._c_drops = None
        if registry is not None:
            self._h_lag = registry.register(LatencySketch(
                "zipkin_replication_visible_lag_seconds",
                "Primary-commit to visible-on-this-follower latency, "
                "per applied record"))
            registry.register(Gauge(
                "zipkin_replication_lag_seconds",
                "Last observed commit-to-visible lag on this follower",
                fn=self.lag_seconds_or_zero))
            self._c_drops = registry.register(Counter(
                "zipkin_lineage_spans_dropped_total",
                "Apply spans dropped by the bounded backhaul buffer"))

    def observe_record(self, seq: int, payload: bytes,
                       apply_s: float) -> None:
        """Called once per applied record with the apply duration.
        Parses the record meta header only; records without lineage
        keys (pre-r17 logs) are a no-op."""
        from zipkin_tpu.wal.record import unit_meta

        try:
            meta = unit_meta(payload)
        except Exception:  # graftlint: disable=swallowed-exception
            return  # the record already applied; meta is advisory
        now_us = int(self._clock() * 1e6)
        ts = meta.get("ts")
        if ts is not None:
            lag = max((now_us - int(ts)) / 1e6, 0.0)
            with self._lock:
                self._lag_s = lag
            if self._h_lag is not None:
                self._h_lag.observe(lag)
        b3 = meta.get("b3")
        if not b3:
            return
        dur_us = max(int(apply_s * 1e6), 1)
        with self._lock:
            sid = _new_id(self._rng)
            if len(self._spans) >= self.MAX_BACKLOG:
                self._spans.pop(0)
                if self._c_drops is not None:
                    self._c_drops.inc()
            self._spans.append(span_to_wire(
                int(b3[0]), sid, int(b3[1]), f"{self.mode} apply",
                self.service_name, now_us - dur_us, dur_us,
                {"wal.seq": str(seq), "follower": self.name}))

    def take_spans(self) -> List[dict]:
        """Drain the apply-span backlog for the next FETCH meta."""
        with self._lock:
            out, self._spans = self._spans, []
        return out

    def lag_seconds(self) -> Optional[float]:
        with self._lock:
            return self._lag_s

    def lag_seconds_or_zero(self) -> float:
        lag = self.lag_seconds()
        return 0.0 if lag is None else lag

    def maybe_metrics_snapshot(self) -> Optional[dict]:
        """A registry snapshot for FETCH meta, throttled to one per
        METRICS_PUSH_INTERVAL_S (None between pushes)."""
        if self.registry is None:
            return None
        now_s = self._clock()
        with self._lock:
            if (self._last_push_s is not None
                    and now_s - self._last_push_s
                    < self.METRICS_PUSH_INTERVAL_S):
                return None
            self._last_push_s = now_s
        return registry_snapshot(self.registry)


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------

def _sketch_state(sk: LatencySketch) -> dict:
    """Raw monoid state of one (child) sketch: sparse bucket counts +
    Moments + sum, with the layout needed to reconstruct and merge."""
    with sk._lock:
        counts = sk.counts.copy()
        m = sk.moments
        s = sk._sum
    nz = np.flatnonzero(counts)
    return {"alpha": sk.alpha, "min_value": sk.min_value,
            "n_buckets": int(len(counts)),
            "quantiles": list(sk.quantiles),
            "counts": [[int(i), int(counts[i])] for i in nz],
            "moments": [m.n, m.mean, m.m2, m.m3, m.m4], "sum": s}


def _sketch_states(sk: LatencySketch) -> dict:
    """State of a sketch metric incl. labeled children."""
    if sk.labelnames:
        return {"labelnames": list(sk.labelnames),
                "children": [
                    {"labels": [[k, v] for k, v in labels],
                     "state": _sketch_state(child)}
                    for labels, child in sk._child_items()
                ]}
    return {"labelnames": [], "state": _sketch_state(sk)}


def sketch_from_state(name: str, help_: str, state: dict) -> LatencySketch:
    """Reconstruct a mergeable sketch from its transported state."""
    sk = LatencySketch(name, help_, alpha=state["alpha"],
                       n_buckets=state["n_buckets"],
                       min_value=state["min_value"],
                       quantiles=tuple(state.get("quantiles")
                                       or (0.5, 0.99)))
    for i, c in state["counts"]:
        sk.counts[int(i)] = int(c)
    sk.moments = Moments(*state["moments"])
    sk._sum = float(state["sum"])
    return sk


def merge_sketches(name: str, help_: str,
                   states: Iterable[dict]) -> Optional[LatencySketch]:
    """Fold transported sketch states into one fleet-wide sketch (the
    monoid merge — bucket counts add, Moments combine). Layout
    mismatches raise, like ``LatencySketch.merge``."""
    merged: Optional[LatencySketch] = None
    for state in states:
        sk = sketch_from_state(name, help_, state)
        if merged is None:
            merged = sk
        else:
            merged.merge(sk)
    return merged


def registry_snapshot(registry: Registry) -> dict:
    """JSON-able snapshot of every metric's samples (plus raw sketch
    state for summaries). Values transport as floats — python json
    round-trips them exactly, so a federated render of this snapshot
    is bitwise-identical to the process's own scrape."""
    metrics = []
    for m in registry.collect():
        entry: Dict[str, object] = {
            "name": m.name, "type": m.prom_type, "help": m.help,
            "samples": [
                [suffix, [[k, v] for k, v in labels], float(value)]
                for suffix, labels, value in m.samples()
            ],
        }
        if isinstance(m, LatencySketch):
            entry["sketch"] = _sketch_states(m)
        metrics.append(entry)
    return {"v": 1, "metrics": metrics}


def render_federated(
        sources: Sequence[Tuple[Sequence[Tuple[str, str]], dict]]) -> str:
    """Merged Prometheus text over ``(extra_labels, snapshot)``
    sources. One HELP/TYPE pair per family (first source's wins);
    every sample line carries its source's injected labels prepended
    (``role``/``follower``), so identically-named samples from
    different processes stay distinct — label-distinguished, never
    summed, no double counting. Sample values go through the same
    ``_fmt`` as ``Registry.render_text`` → bitwise-consistent with
    each process's own scrape."""
    families: "collections.OrderedDict[str, dict]" = \
        collections.OrderedDict()
    for extra_labels, snap in sources:
        for m in snap.get("metrics", ()):
            fam = families.get(m["name"])
            if fam is None:
                fam = {"type": m["type"], "help": m["help"], "rows": []}
                families[m["name"]] = fam
            for suffix, labels, value in m["samples"]:
                merged = tuple(extra_labels) + tuple(
                    (k, v) for k, v in labels)
                fam["rows"].append((suffix, merged, value))
    lines: List[str] = []
    for name in sorted(families):
        fam = families[name]
        lines.append(f"# HELP {name} {escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for suffix, labels, value in fam["rows"]:
            lines.append(
                f"{name}{suffix}{_label_str(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded in-memory ring of structured events (watchdog
    transitions, operator-notable conditions) served at
    ``/debug/events``. Append-only, O(1), never blocks the paths that
    feed it."""

    def __init__(self, capacity: int = 256,
                 clock: Callable[[], float] = time.time):
        self._clock = clock
        self._lock = threading.Lock()  # lock-order: 88 flight-recorder
        self._ring = collections.deque(maxlen=max(int(capacity), 1))  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def record(self, kind: str, severity: str = "info",
               **fields) -> dict:
        evt = {"tsUs": int(self._clock() * 1e6), "kind": kind,
               "severity": severity, "fields": fields}
        with self._lock:
            evt["seq"] = self._seq
            self._seq += 1
            self._ring.append(evt)
        return evt

    def events(self, limit: Optional[int] = None) -> List[dict]:
        """Events oldest→newest (the bounded window)."""
        with self._lock:
            out = list(self._ring)
        if limit is not None:
            out = out[-max(int(limit), 0):]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Named liveness/readiness probes over the async machinery.

    A probe is ``fn() -> (ok, reason, value)``; probes run OUTSIDE the
    watchdog's lock (they acquire component locks across the whole
    rank spine — pipeline cond, WAL cond, follower stats). ``check()``
    evaluates everything, records state *transitions* into the flight
    recorder, and returns the health document ``/api/health`` serves:
    not-ready whenever any probe fails, with the failing probes'
    reasons."""

    def __init__(self, recorder: Optional[FlightRecorder] = None,
                 registry: Optional[Registry] = None):
        self.recorder = recorder
        self._lock = threading.Lock()  # lock-order: 87 watchdog
        self._probes: List[Tuple[str, Callable]] = []  # guarded-by: _lock
        self._failing: Dict[str, str] = {}  # guarded-by: _lock
        self._c_trips = None
        if registry is not None:
            registry.register(Gauge(
                "zipkin_watchdog_failing_probes",
                "Probes currently failing (0 = ready)",
                fn=lambda: float(len(self.failing()))))
            self._c_trips = registry.register(Counter(
                "zipkin_watchdog_trips_total",
                "Probe ok→failing transitions"))

    def add_probe(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._probes.append((name, fn))

    def failing(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._failing)

    def check(self) -> dict:
        with self._lock:
            probes = list(self._probes)
        results = []
        for name, fn in probes:  # probe calls: no watchdog lock held
            try:
                ok, reason, value = fn()
            except Exception as e:  # a broken probe is a failing probe
                ok, reason, value = False, f"probe error: {e}", None
            results.append((name, bool(ok), reason, value))
        tripped, cleared = [], []
        with self._lock:
            for name, ok, reason, value in results:
                was = self._failing.get(name)
                if ok and was is not None:
                    del self._failing[name]
                    cleared.append(name)
                elif not ok and was is None:
                    self._failing[name] = reason or name
                    tripped.append((name, reason, value))
        for name, reason, value in tripped:
            if self._c_trips is not None:
                self._c_trips.inc()
            if self.recorder is not None:
                self.recorder.record("watchdog_trip", severity="error",
                                     probe=name, reason=reason,
                                     value=value)
        for name in cleared:
            if self.recorder is not None:
                self.recorder.record("watchdog_clear", severity="info",
                                     probe=name)
        reasons = [{"probe": n, "reason": r, "value": v}
                   for n, ok, r, v in results if not ok]
        return {
            "live": True,
            "ready": not reasons,
            "reasons": reasons,
            "probes": {n: {"ok": ok, "reason": r, "value": v}
                       for n, ok, r, v in results},
        }


# -- probe factories --------------------------------------------------------

def pipeline_stall_probe(store, stall_after_s: float = 5.0) -> Callable:
    """Fails when the ingest pipeline holds queued units but has made
    no commit progress for ``stall_after_s``."""
    def probe():
        pipe = getattr(store, "ingest_pipeline", lambda: None)()
        if pipe is None:
            return True, None, 0.0
        age = pipe.progress_age_s()
        if age > stall_after_s:
            return (False,
                    f"ingest pipeline stalled: {pipe.queued()} queued "
                    f"units, no commit progress for {age:.1f}s", age)
        return True, None, age
    return probe


def fsync_parked_probe(wal) -> Callable:
    """Fails while the WAL's fsync machinery is parked on an error
    (the durable frontier cannot advance — acks will time out)."""
    def probe():
        err = wal.sync_error()
        if err is not None:
            return False, f"wal fsync parked: {err}", None
        return True, None, None
    return probe


def sealer_backlog_probe(store) -> Callable:
    """Fails when the async eviction sealer's bounded backlog is at
    cap (the next capture will stall the write path)."""
    def probe():
        sealer = getattr(store, "eviction_sealer", lambda: None)()
        if sealer is None:
            return True, None, 0.0
        depth = sealer.queued()
        if sealer.at_capacity():
            return (False,
                    f"sealer backlog at cap ({depth} windows queued)",
                    float(depth))
        return True, None, float(depth)
    return probe


def dispatcher_stuck_probe(dispatcher, stall_after_s: float = 5.0
                           ) -> Callable:
    """Fails when cross-shard requests have waited past
    ``stall_after_s`` without the executor draining them."""
    def probe():
        age = dispatcher.queue_age_s()
        if age > stall_after_s:
            return (False,
                    f"cross-shard dispatcher stuck: oldest queued "
                    f"request waited {age:.1f}s", age)
        return True, None, age
    return probe


def follower_lag_probe(status_fn: Callable[[], dict],
                       max_lag_records: int = 10000,
                       max_lag_seconds: float = 30.0) -> Callable:
    """Fails when replication lag passes either threshold (follower
    side: own applied lag; primary side: worst follower cursor)."""
    def probe():
        st = status_fn() or {}
        lag_r = st.get("lagRecords")
        lag_s = st.get("lagSeconds")
        if lag_r is not None and lag_r > max_lag_records:
            return (False,
                    f"replication lag {lag_r} records "
                    f"(> {max_lag_records})", float(lag_r))
        if lag_s is not None and lag_s > max_lag_seconds:
            return (False,
                    f"replication lag {lag_s:.1f}s "
                    f"(> {max_lag_seconds:.0f}s)", float(lag_s))
        return True, None, float(lag_r or 0)
    return probe


# ---------------------------------------------------------------------------
# per-process facade (what the API server serves)
# ---------------------------------------------------------------------------

class FleetObs:
    """One process's fleet-observability surface: role identity, the
    merged-metrics view, health, and the event ring — handed to
    ``ApiServer(fleet=...)`` and wired by the daemon.

    ``remote_sources`` returns ``[(extra_labels, snapshot), ...]`` for
    the other processes this one can see (the primary's shipper serves
    its followers' pushed snapshots); follower processes have none."""

    def __init__(self, role: str, name: str = "",
                 registry: Optional[Registry] = None,
                 tracker: Optional[LineageTracker] = None,
                 follower: Optional[FollowerLineage] = None,
                 watchdog: Optional[Watchdog] = None,
                 recorder: Optional[FlightRecorder] = None,
                 remote_sources: Optional[Callable[[], list]] = None,
                 replication: Optional[Callable[[], dict]] = None):
        self.role = role
        self.name = name
        self.registry = registry
        self.tracker = tracker
        self.follower = follower
        self.watchdog = watchdog
        self.recorder = recorder
        self.remote_sources = remote_sources
        self.replication = replication

    def _own_labels(self) -> Tuple[Tuple[str, str], ...]:
        labels: Tuple[Tuple[str, str], ...] = (("role", self.role),)
        if self.name:
            labels += (("follower", self.name),)
        return labels

    def sources(self) -> list:
        out = []
        if self.registry is not None:
            out.append((self._own_labels(),
                        registry_snapshot(self.registry)))
        if self.remote_sources is not None:
            out.extend(self.remote_sources())
        return out

    def federated_text(self) -> str:
        return render_federated(self.sources())

    def health(self) -> dict:
        if self.watchdog is None:
            return {"live": True, "ready": True, "reasons": [],
                    "probes": {}}
        return self.watchdog.check()

    def events(self, limit: Optional[int] = None) -> List[dict]:
        if self.recorder is None:
            return []
        return self.recorder.events(limit)

    def status(self) -> dict:
        """The ``/api/fleet`` document: roles, replication, lag, and
        fleet-wide monoid roll-ups of the lineage sketches."""
        out: Dict[str, object] = {"role": self.role}
        if self.name:
            out["name"] = self.name
        if self.replication is not None:
            out["replication"] = self.replication()
        if self.follower is not None:
            out["lagSeconds"] = self.follower.lag_seconds()
        sources = self.sources()
        out["processes"] = [dict(labels) for labels, _ in sources]
        merged = {}
        for sketch_name in ("zipkin_replication_visible_lag_seconds",
                            "zipkin_lineage_stage_seconds"):
            states = []
            for _, snap in sources:
                for m in snap.get("metrics", ()):
                    if m["name"] != sketch_name or "sketch" not in m:
                        continue
                    sk = m["sketch"]
                    if sk.get("labelnames"):
                        states.extend(c["state"]
                                      for c in sk["children"])
                    else:
                        states.append(sk["state"])
            if states:
                try:
                    agg = merge_sketches(sketch_name, "", states)
                except ValueError:
                    continue  # mixed layouts across versions: skip
                merged[sketch_name] = agg.snapshot()
        out["merged"] = merged
        if self.watchdog is not None:
            out["health"] = self.watchdog.check()
        return out
