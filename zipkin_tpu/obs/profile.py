"""On-demand ``jax.profiler`` capture (the ostrich /pprof role).

One capture at a time, process-wide: the jax profiler is a global
singleton, so a second concurrent start would abort the first trace.
The API exposes this as ``POST /debug/profile?seconds=N`` — the caller
blocks for the window (ThreadingHTTPServer gives it its own thread) and
gets back the trace directory, viewable with TensorBoard / Perfetto.
"""

from __future__ import annotations

import tempfile
import threading
import time
from typing import Optional

MAX_SECONDS = 120.0

_capture_lock = threading.Lock()  # lock-order: 86 profiler


class ProfilerBusy(RuntimeError):
    """A capture is already running."""


def capture(seconds: float, out_dir: Optional[str] = None
            ) -> "tuple[str, float]":
    """Trace device + host activity for ``seconds`` (clamped to
    [0.01, MAX_SECONDS] — the one clamp site); returns (trace
    directory, effective seconds). Raises ProfilerBusy when a capture
    is in flight, and propagates whatever ``jax.profiler`` raises when
    the backend can't trace (callers map that to a 503)."""
    seconds = min(max(float(seconds), 0.01), MAX_SECONDS)
    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusy("a profiler capture is already running")
    try:
        import jax

        out_dir = out_dir or tempfile.mkdtemp(prefix="zipkin-tpu-profile-")
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return out_dir, seconds
    finally:
        _capture_lock.release()
