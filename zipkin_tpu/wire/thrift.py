"""TBinaryProtocol codec for the zipkin Span wire struct.

Implements exactly the layout of zipkinCore.thrift (reference
zipkin-thrift/.../zipkinCore.thrift:27-57):

    Endpoint  { 1: i32 ipv4, 2: i16 port, 3: string service_name }
    Annotation{ 1: i64 timestamp, 2: string value,
                3: optional Endpoint host, 4: optional i32 duration }
    BinaryAnnotation { 1: string key, 2: binary value,
                       3: AnnotationType annotation_type,
                       4: optional Endpoint host }
    Span { 1: i64 trace_id, 3: string name, 4: i64 id,
           5: optional i64 parent_id, 6: list<Annotation> annotations,
           8: list<BinaryAnnotation> binary_annotations,
           9: optional bool debug }

Unknown fields are skipped (forward compat); the optional annotation
``duration`` field is accepted and ignored (the model derives durations
from timestamps). All integers big-endian, ids/timestamps signed 64-bit.
"""

from __future__ import annotations

import base64
import struct
from typing import List, Optional, Tuple

from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)

# TBinaryProtocol type codes.
T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15


class ThriftError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


def _w_field(out: List[bytes], ftype: int, fid: int) -> None:
    out.append(struct.pack(">bh", ftype, fid))


def _w_string(out: List[bytes], s) -> None:
    b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    out.append(struct.pack(">i", len(b)))
    out.append(b)


def _w_endpoint(out: List[bytes], ep: Endpoint) -> None:
    _w_field(out, T_I32, 1)
    out.append(struct.pack(">i", _i32(ep.ipv4)))
    _w_field(out, T_I16, 2)
    out.append(struct.pack(">h", _i16(ep.port)))
    _w_field(out, T_STRING, 3)
    _w_string(out, ep.service_name)
    out.append(b"\x00")


def _w_annotation(out: List[bytes], a: Annotation) -> None:
    _w_field(out, T_I64, 1)
    out.append(struct.pack(">q", a.timestamp))
    _w_field(out, T_STRING, 2)
    _w_string(out, a.value)
    if a.host is not None:
        _w_field(out, T_STRUCT, 3)
        _w_endpoint(out, a.host)
    out.append(b"\x00")


def _binary_value_bytes(b: BinaryAnnotation) -> bytes:
    v = b.value
    t = b.annotation_type
    if isinstance(v, bytes):
        return v
    if isinstance(v, bytearray):
        return bytes(v)
    if t == AnnotationType.STRING or isinstance(v, str):
        return str(v).encode("utf-8")
    if t == AnnotationType.BOOL:
        return b"\x01" if v else b"\x00"
    if t == AnnotationType.I16:
        return struct.pack(">h", int(v))
    if t == AnnotationType.I32:
        return struct.pack(">i", int(v))
    if t == AnnotationType.I64:
        return struct.pack(">q", int(v))
    if t == AnnotationType.DOUBLE:
        return struct.pack(">d", float(v))
    return bytes(v)


def _w_binary_annotation(out: List[bytes], b: BinaryAnnotation) -> None:
    _w_field(out, T_STRING, 1)
    _w_string(out, b.key)
    _w_field(out, T_STRING, 2)
    _w_string(out, _binary_value_bytes(b))
    _w_field(out, T_I32, 3)
    out.append(struct.pack(">i", int(b.annotation_type)))
    if b.host is not None:
        _w_field(out, T_STRUCT, 4)
        _w_endpoint(out, b.host)
    out.append(b"\x00")


def span_to_bytes(span: Span) -> bytes:
    out: List[bytes] = []
    _w_field(out, T_I64, 1)
    out.append(struct.pack(">q", _i64(span.trace_id)))
    _w_field(out, T_STRING, 3)
    _w_string(out, span.name)
    _w_field(out, T_I64, 4)
    out.append(struct.pack(">q", _i64(span.id)))
    if span.parent_id is not None:
        _w_field(out, T_I64, 5)
        out.append(struct.pack(">q", _i64(span.parent_id)))
    _w_field(out, T_LIST, 6)
    out.append(struct.pack(">bi", T_STRUCT, len(span.annotations)))
    for a in span.annotations:
        _w_annotation(out, a)
    _w_field(out, T_LIST, 8)
    out.append(struct.pack(">bi", T_STRUCT, len(span.binary_annotations)))
    for b in span.binary_annotations:
        _w_binary_annotation(out, b)
    _w_field(out, T_BOOL, 9)
    out.append(b"\x01" if span.debug else b"\x00")
    out.append(b"\x00")
    return b"".join(out)


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def take(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) != n:
            raise ThriftError("truncated thrift payload")
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def string(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise ThriftError("negative string length")
        return self.take(n)

    # Depth-bounded: crafted deeply nested containers on the
    # network-facing ingest path must fail the parse (ThriftError), not
    # exhaust the interpreter stack. Mirrors the native parser's bound.
    MAX_SKIP_DEPTH = 64

    def skip(self, ftype: int, depth: int = 0) -> None:
        if depth > self.MAX_SKIP_DEPTH:
            raise ThriftError("thrift container nesting too deep")
        if ftype == T_BOOL or ftype == T_BYTE:
            self.take(1)
        elif ftype == T_I16:
            self.take(2)
        elif ftype in (T_I32,):
            self.take(4)
        elif ftype in (T_I64, T_DOUBLE):
            self.take(8)
        elif ftype == T_STRING:
            self.string()
        elif ftype == T_STRUCT:
            while True:
                ft = self.u8()
                if ft == T_STOP:
                    break
                self.i16()
                self.skip(ft, depth + 1)
        elif ftype in (T_LIST, T_SET):
            et = self.u8()
            for _ in range(self.i32()):
                self.skip(et, depth + 1)
        elif ftype == T_MAP:
            kt, vt = self.u8(), self.u8()
            for _ in range(self.i32()):
                self.skip(kt, depth + 1)
                self.skip(vt, depth + 1)
        else:
            raise ThriftError(f"unknown thrift type {ftype}")


def _r_endpoint(r: _Reader) -> Endpoint:
    ipv4, port, service = 0, 0, "unknown"
    while True:
        ft = r.u8()
        if ft == T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ft == T_I32:
            ipv4 = r.i32()
        elif fid == 2 and ft == T_I16:
            port = r.i16() & 0xFFFF
        elif fid == 3 and ft == T_STRING:
            service = r.string().decode("utf-8", "replace")
        else:
            r.skip(ft)
    return Endpoint(ipv4=ipv4, port=port, service_name=service)


def _r_annotation(r: _Reader) -> Annotation:
    ts, value, host = 0, "", None
    while True:
        ft = r.u8()
        if ft == T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ft == T_I64:
            ts = r.i64()
        elif fid == 2 and ft == T_STRING:
            value = r.string().decode("utf-8", "replace")
        elif fid == 3 and ft == T_STRUCT:
            host = _r_endpoint(r)
        else:
            r.skip(ft)  # includes the optional i32 duration (fid 4)
    return Annotation(timestamp=ts, value=value, host=host)


def _decode_binary_value(raw: bytes, ann_type: AnnotationType):
    try:
        if ann_type == AnnotationType.STRING:
            return raw.decode("utf-8")
        if ann_type == AnnotationType.BOOL:
            return raw != b"\x00"
        if ann_type == AnnotationType.I16 and len(raw) == 2:
            return struct.unpack(">h", raw)[0]
        if ann_type == AnnotationType.I32 and len(raw) == 4:
            return struct.unpack(">i", raw)[0]
        if ann_type == AnnotationType.I64 and len(raw) == 8:
            return struct.unpack(">q", raw)[0]
        if ann_type == AnnotationType.DOUBLE and len(raw) == 8:
            return struct.unpack(">d", raw)[0]
    except (struct.error, UnicodeDecodeError):
        pass
    return raw


def _r_binary_annotation(r: _Reader) -> BinaryAnnotation:
    key, raw, ann_type, host = "", b"", AnnotationType.BYTES, None
    while True:
        ft = r.u8()
        if ft == T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ft == T_STRING:
            key = r.string().decode("utf-8", "replace")
        elif fid == 2 and ft == T_STRING:
            raw = r.string()
        elif fid == 3 and ft == T_I32:
            try:
                ann_type = AnnotationType(r.i32())
            except ValueError:
                ann_type = AnnotationType.BYTES
        elif fid == 4 and ft == T_STRUCT:
            host = _r_endpoint(r)
        else:
            r.skip(ft)
    return BinaryAnnotation(
        key=key, value=_decode_binary_value(raw, ann_type),
        annotation_type=ann_type, host=host,
    )


def span_from_bytes(data: bytes, pos: int = 0) -> Tuple[Span, int]:
    r = _Reader(data, pos)
    trace_id = span_id = 0
    name = ""
    parent_id: Optional[int] = None
    anns: List[Annotation] = []
    banns: List[BinaryAnnotation] = []
    debug = False
    while True:
        ft = r.u8()
        if ft == T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ft == T_I64:
            trace_id = r.i64()
        elif fid == 3 and ft == T_STRING:
            name = r.string().decode("utf-8", "replace")
        elif fid == 4 and ft == T_I64:
            span_id = r.i64()
        elif fid == 5 and ft == T_I64:
            parent_id = r.i64()
        elif fid == 6 and ft == T_LIST:
            et = r.u8()
            n = r.i32()
            if et != T_STRUCT:
                raise ThriftError("annotations must be a struct list")
            anns = [_r_annotation(r) for _ in range(n)]
        elif fid == 8 and ft == T_LIST:
            et = r.u8()
            n = r.i32()
            if et != T_STRUCT:
                raise ThriftError("binary annotations must be a struct list")
            banns = [_r_binary_annotation(r) for _ in range(n)]
        elif fid == 9 and ft == T_BOOL:
            debug = r.u8() != 0
        else:
            r.skip(ft)
    span = Span(
        trace_id=trace_id, name=name, id=span_id, parent_id=parent_id,
        annotations=tuple(anns), binary_annotations=tuple(banns), debug=debug,
    )
    return span, r.pos


def spans_from_bytes(data: bytes) -> List[Span]:
    """Parse a back-to-back sequence of Span structs."""
    out, pos = [], 0
    while pos < len(data):
        span, pos = span_from_bytes(data, pos)
        out.append(span)
    return out


# -- scribe framing ---------------------------------------------------------


def span_to_scribe_message(span: Span) -> str:
    """Span → base64 thrift, the LogEntry.message payload
    (ScribeSpanReceiver.scala:50-54)."""
    return base64.b64encode(span_to_bytes(span)).decode("ascii")


def scribe_message_to_span(message: str) -> Span:
    try:
        raw = base64.b64decode(message, validate=False)
    except Exception as e:  # binascii.Error subclasses ValueError
        raise ThriftError(f"bad base64 payload: {e}") from None
    span, _ = span_from_bytes(raw)
    return span


def _i64(x: int) -> int:
    x &= 0xFFFFFFFFFFFFFFFF
    return x - 0x10000000000000000 if x >= 0x8000000000000000 else x


def _i32(x: int) -> int:
    x &= 0xFFFFFFFF
    return x - 0x100000000 if x >= 0x80000000 else x


def _i16(x: int) -> int:
    x &= 0xFFFF
    return x - 0x10000 if x >= 0x8000 else x
