"""Wire compatibility: thrift binary span codec + scribe framing.

Instrumented apps emit spans as TBinaryProtocol-serialized thrift
structs, base64-wrapped in scribe LogEntry messages (reference:
zipkinCore.thrift:27-57, scribe.thrift:29, decoded at
ScribeSpanReceiver.scala:96-107). This package speaks that exact wire
format so existing zipkin clients can feed the TPU collector unchanged.
"""

from zipkin_tpu.wire.thrift import (  # noqa: F401
    ThriftError,
    scribe_message_to_span,
    span_from_bytes,
    span_to_bytes,
    span_to_scribe_message,
    spans_from_bytes,
)
