"""Client-side instrumentation + query client (the zipkin-gems role).

Reference: the Ruby ``ZipkinTracer::RackHandler``
(zipkin-gems/zipkin-tracer/lib/zipkin-tracer.rb:7-45) — B3 header
propagation, per-request server spans, percentage sampling, scribe
transport — re-expressed for python:

- ``B3Headers``: parse/emit X-B3-TraceId / X-B3-SpanId /
  X-B3-ParentSpanId / X-B3-Sampled
- ``Tracer``: span lifecycle + transport (any callable taking spans —
  a Collector.accept, an HTTP poster, or a scribe sender)
- ``ZipkinWSGIMiddleware``: wraps a WSGI app, continuing or starting a
  trace per request with sr/ss annotations
- ``QueryClient``: typed access to the HTTP query API
  (the zipkin-query gem role)
"""

from __future__ import annotations

import json
import random
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from zipkin_tpu.models.constants import SERVER_RECV, SERVER_SEND
from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span

TRACE_ID_HEADER = "X-B3-TraceId"
SPAN_ID_HEADER = "X-B3-SpanId"
PARENT_ID_HEADER = "X-B3-ParentSpanId"
SAMPLED_HEADER = "X-B3-Sampled"


def _new_id(rng: random.Random) -> int:
    return rng.getrandbits(63) + 1


@dataclass(frozen=True)
class B3Headers:
    trace_id: Optional[int] = None
    span_id: Optional[int] = None
    parent_id: Optional[int] = None
    sampled: Optional[bool] = None

    @staticmethod
    def parse(headers: Dict[str, str]) -> "B3Headers":
        # HTTP header names are case-insensitive (and WSGI's HTTP_*
        # environ keys arrive fully uppercased), so match on a
        # lowercased view of the mapping.
        lowered = {k.lower(): v for k, v in headers.items()}

        def hex_of(name):
            v = lowered.get(name.lower())
            if v is None:
                return None
            try:
                return int(v, 16)
            except ValueError:
                return None

        sampled_raw = lowered.get(SAMPLED_HEADER.lower())
        sampled = None
        if sampled_raw:
            sampled = sampled_raw in ("1", "true", "True")
        return B3Headers(
            trace_id=hex_of(TRACE_ID_HEADER),
            span_id=hex_of(SPAN_ID_HEADER),
            parent_id=hex_of(PARENT_ID_HEADER),
            sampled=sampled,
        )

    def emit(self) -> Dict[str, str]:
        out = {}
        if self.trace_id is not None:
            out[TRACE_ID_HEADER] = f"{self.trace_id & (2**64 - 1):x}"
        if self.span_id is not None:
            out[SPAN_ID_HEADER] = f"{self.span_id & (2**64 - 1):x}"
        if self.parent_id is not None:
            out[PARENT_ID_HEADER] = f"{self.parent_id & (2**64 - 1):x}"
        if self.sampled is not None:
            out[SAMPLED_HEADER] = "1" if self.sampled else "0"
        return out


class Tracer:
    """Creates spans and ships them through a transport callable."""

    def __init__(
        self,
        service_name: str,
        transport: Callable[[Sequence[Span]], None],
        sample_rate: float = 1.0,
        ipv4: int = 0x7F000001,
        port: int = 0,
        rng: Optional[random.Random] = None,
    ):
        self.endpoint = Endpoint(ipv4, port, service_name)
        self.transport = transport
        self.sample_rate = sample_rate
        self.rng = rng or random.Random()

    def should_sample(self, b3: B3Headers) -> bool:
        if b3.sampled is not None:
            return b3.sampled
        return self.rng.random() < self.sample_rate

    def resolve(self, b3: B3Headers, child: bool = False) -> B3Headers:
        """Pin the ids and sampling decision for one server request —
        THE single place the echo/record contract lives: the resolved
        headers are what the response echoes (so the devtools
        extension links real traces) and exactly what server_span
        records. Unsampled requests resolve with ids=None: nothing
        will be recorded, so echoing a trace id would hand out dead
        links — only X-B3-Sampled: 0 is emitted for them.

        ``child=False`` (the default) is the classic shared-span
        model: an inbound span id is REUSED, so the server span and
        the caller's client span are the same id (finagle-era B3).
        ``child=True`` joins the caller's trace as a proper CHILD:
        a fresh span id parented under the inbound span id — what
        the fleet self-tracing uses so an external probe's request
        and the API's own server span stay distinct spans in one
        trace. Without inbound ids the two modes are identical (a
        fresh root either way)."""
        sampled = self.should_sample(b3)
        if not sampled:
            return B3Headers(sampled=False)
        if child and b3.span_id is not None:
            return B3Headers(
                trace_id=(b3.trace_id if b3.trace_id is not None
                          else _new_id(self.rng)),
                span_id=_new_id(self.rng),
                parent_id=b3.span_id,
                sampled=True,
            )
        return B3Headers(
            trace_id=(b3.trace_id if b3.trace_id is not None
                      else _new_id(self.rng)),
            span_id=(b3.span_id if b3.span_id is not None
                     else _new_id(self.rng)),
            parent_id=b3.parent_id,
            sampled=True,
        )

    def server_span(
        self, name: str, b3: B3Headers,
        start_us: Optional[int] = None, end_us: Optional[int] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> Optional[Span]:
        """Record one server-side span (sr/ss) for a handled request."""
        if not self.should_sample(b3):
            return None
        trace_id = b3.trace_id if b3.trace_id is not None else _new_id(self.rng)
        span_id = b3.span_id if b3.span_id is not None else _new_id(self.rng)
        start_us = start_us or int(time.time() * 1e6)
        end_us = end_us or int(time.time() * 1e6)
        banns = tuple(
            BinaryAnnotation(k, v, host=self.endpoint)
            for k, v in (tags or {}).items()
        )
        span = Span(
            trace_id=trace_id, name=name, id=span_id, parent_id=b3.parent_id,
            annotations=(
                Annotation(start_us, SERVER_RECV, self.endpoint),
                Annotation(end_us, SERVER_SEND, self.endpoint),
            ),
            binary_annotations=banns,
        )
        self.transport([span])
        return span


class ZipkinWSGIMiddleware:
    """WSGI middleware: a server span per request (RackHandler role)."""

    def __init__(self, app, tracer: Tracer):
        self.app = app
        self.tracer = tracer

    def __call__(self, environ, start_response):
        headers = {
            k[5:].replace("_", "-"): v
            for k, v in environ.items() if k.startswith("HTTP_")
        }
        b3 = B3Headers.parse(headers)
        # Resolve ids and the sampling decision UP FRONT so the
        # response can echo X-B3-TraceId/-SpanId — the signal the
        # browser-extension role watches to link the current page's
        # trace into the UI (reference: zipkin-browser-extension's
        # request observer; ours reads these echoed headers in a
        # devtools panel, zipkin_tpu/web/extension/). The recorded
        # span reuses exactly the echoed ids; unsampled requests echo
        # only X-B3-Sampled: 0 (see Tracer.resolve).
        resolved = self.tracer.resolve(b3)
        start_us = int(time.time() * 1e6)
        path = environ.get("PATH_INFO", "/")
        method = environ.get("REQUEST_METHOD", "GET")
        status_holder: List[str] = []

        def capture_start_response(status, resp_headers, exc_info=None):
            status_holder.append(status)
            # Filter any pre-existing X-B3-* response headers (case-
            # insensitively) before appending ours: a nested tracing
            # middleware (or the wrapped app itself) may already have
            # emitted them, and a response carrying two conflicting
            # X-B3-TraceId values makes the devtools panel link
            # whichever it reads first (ADVICE r5). The OUTERMOST
            # middleware resolved the request's ids — its echo wins.
            resp_headers = [
                (k, v) for k, v in resp_headers
                if not k.lower().startswith("x-b3-")
            ] + list(resolved.emit().items())
            return start_response(status, resp_headers, exc_info)

        try:
            return self.app(environ, capture_start_response)
        finally:
            self.tracer.server_span(
                f"{method.lower()} {path}",
                resolved,
                start_us=start_us,
                end_us=int(time.time() * 1e6),
                tags={
                    "http.uri": path,
                    "http.method": method,
                    "http.status": (status_holder[0].split()[0]
                                    if status_holder else "?"),
                },
            )


def http_transport(base_url: str) -> Callable[[Sequence[Span]], None]:
    """Transport posting JSON spans to a collector's /api/spans door."""
    from zipkin_tpu.ingest.receiver import span_to_json

    def send(spans: Sequence[Span]) -> None:
        body = json.dumps([span_to_json(s) for s in spans]).encode()
        req = urllib.request.Request(
            base_url.rstrip("/") + "/api/spans", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=10).read()

    return send


class QueryClient:
    """Typed client for the HTTP query API (zipkin-query gem role)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        with urllib.request.urlopen(
            self.base_url + path, timeout=self.timeout
        ) as r:
            return json.loads(r.read())

    def services(self) -> List[str]:
        return self._get("/api/services")

    def span_names(self, service: str) -> List[str]:
        return self._get(f"/api/spans?serviceName={service}")

    def query(self, service: str, **params) -> dict:
        qs = "&".join(
            [f"serviceName={service}"]
            + [f"{k}={v}" for k, v in params.items()]
        )
        return self._get(f"/api/query?{qs}")

    def trace(self, trace_id) -> List[dict]:
        """``trace_id`` as int (formatted as unsigned hex, the URL
        convention) or an already-hex string from a query response."""
        if isinstance(trace_id, int):
            trace_id = f"{trace_id & (2**64 - 1):x}"
        return self._get(f"/api/trace/{trace_id}")

    def dependencies(self) -> dict:
        return self._get("/api/dependencies")

    def traces_exist(self, trace_ids) -> List[str]:
        """tracesExist over the HTTP surface: returns the unsigned-hex
        ids (the query-response form) that have any stored span."""
        ids = ",".join(
            f"{t & (2**64 - 1):x}" if isinstance(t, int) else str(t)
            for t in trace_ids
        )
        return self._get(f"/api/traces_exist?traceIds={ids}")["exist"]

    def span_durations(self, service: str, span_name: str,
                       time_stamp: Optional[int] = None) -> Dict:
        """getSpanDurations: {service name: [duration µs, ...]} for
        spans named ``span_name`` in traces the index matches."""
        qs = f"serviceName={service}&spanName={span_name}"
        if time_stamp is not None:
            qs += f"&timeStamp={time_stamp}"
        return self._get(f"/api/span_durations?{qs}")["durations"]

    def service_names_to_trace_ids(self, service: str,
                                   span_name: Optional[str] = None,
                                   time_stamp: Optional[int] = None
                                   ) -> Dict:
        """getServiceNamesToTraceIds: {participating service:
        [unsigned-hex trace ids]}."""
        qs = f"serviceName={service}"
        if span_name is not None:
            qs += f"&spanName={span_name}"
        if time_stamp is not None:
            qs += f"&timeStamp={time_stamp}"
        return self._get(
            f"/api/service_names_to_trace_ids?{qs}")["serviceNames"]

    def data_ttl(self) -> int:
        """getDataTimeToLive: the storage tier's retention (seconds)."""
        return self._get("/api/data_ttl")["dataTimeToLive"]
