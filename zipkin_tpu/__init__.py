"""zipkin-tpu: a TPU-native distributed-tracing analytics framework.

Re-implements the capability surface of Twitter Zipkin (reference:
/root/reference, Scala/Finagle) as an idiomatic JAX/XLA/Pallas design:

- span ingest with backpressure + adaptive sampling (zipkin-collector,
  zipkin-sampler)
- a pluggable ``SpanStore`` SPI (zipkin-common storage traits) with an
  in-memory reference store and a device-resident columnar store
- trace query with slice intersection + time-skew-adjusted assembly
  (zipkin-query)
- streaming dependency-link aggregation, latency percentiles, top-k and
  cardinality served from on-device sketch state (zipkin-aggregate)
- a JSON/HTTP API mirroring zipkin-web's routes, and a vectorized
  tracegen benchmark harness (zipkin-tracegen)

The compute path is JAX (jit/shard_map/pallas); strings live in a host
dictionary encoder, the device sees only fixed-width integers/floats.
"""

import os as _os

if _os.environ.get("ZIPKIN_TPU_X64", "1") != "0":
    # 64-bit trace/span ids and µs timestamps are core to the domain, so the
    # framework runs JAX in x64 mode. The performance-critical paths
    # (sketches, hashing) still use explicit 32-bit dtypes — see
    # ops/hashing.py — so only the id/timestamp columns pay the TPU's
    # int64 emulation cost, and only on the query path.
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

__version__ = "0.4.0"

from zipkin_tpu.models.span import (  # noqa: F401
    Annotation,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.models.trace import Trace  # noqa: F401
from zipkin_tpu.models.dependencies import (  # noqa: F401
    Dependencies,
    DependencyLink,
    Moments,
)
