"""Trace adjusters: clock-skew correction on assembled traces.

Reference semantics (TimeSkewAdjuster.scala:25-270, re-expressed):

An RPC span carries cs/cr stamped by the client's clock and sr/ss by the
server's. If the clocks disagree, children appear to start before their
parents. Using the one-way-latency symmetry assumption:

    latency = ((cr - cs) - (ss - sr)) / 2
    skew    = sr - latency - cs

every annotation stamped by the skewed endpoint is shifted by -skew, and
the correction propagates down the span tree (children were stamped by
the same skewed clock on their client side).

Rules preserved from the reference:
- no adjustment when the server interval exceeds the client's, or when
  the core annotations are already well-ordered (cs < sr and ss < cr);
- client-only spans (cs/cr but no sr/ss) with children get synthetic
  sr/ss at the cs/cr timestamps (warning recorded) and the skew for
  client-core children is computed manually against those;
- cs/cr annotations on the loopback address count as the skewed host.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

from zipkin_tpu.models.constants import (
    CLIENT_RECV,
    CLIENT_SEND,
    SERVER_RECV,
    SERVER_SEND,
)
from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.models.trace import Trace

LOCALHOST_LOOPBACK_IP = 0x7F000001

WARN_ADDED_SERVER_RECV = "TIME_SKEW_ADD_SERVER_RECV"
WARN_ADDED_SERVER_SEND = "TIME_SKEW_ADD_SERVER_SEND"


@dataclass(frozen=True)
class ClockSkew:
    endpoint: Endpoint
    skew: int


class TimeSkewAdjuster:
    """adjust(trace) → trace with per-endpoint clock skew corrected."""

    def __init__(self):
        self.warnings: List[str] = []

    def adjust(self, trace: Trace) -> Trace:
        root = trace.get_root_span()
        if root is None:
            return trace
        tree = trace.get_span_tree(root)
        adjusted = self._adjust_tree(tree, None)
        return Trace(_flatten(adjusted))

    # -- tree walk ------------------------------------------------------

    def _adjust_tree(self, node, inherited: Optional[ClockSkew]):
        span, children = node.span, list(node.children)
        if inherited is not None:
            span = _shift(span, inherited)
        span, children = self._synthesize_server_half(span, children)
        own = _clock_skew(span)
        if own is not None:
            span = _shift(span, own)
        return _Node(span, [self._adjust_tree(c, own) for c in children])

    def _synthesize_server_half(self, span: Span, children):
        """Client-only span with children → synthetic sr/ss + manual
        child skew propagation (validateSpan semantics)."""
        ann = span.annotations_as_map()
        client_only = (
            CLIENT_SEND in ann and CLIENT_RECV in ann
            and not (SERVER_SEND in ann and SERVER_RECV in ann)
        )
        if not (span.is_valid() and children and client_only):
            return span, children
        endpoint = None
        for a in children[0].span.client_side_annotations:
            endpoint = a.host
            break
        sr_ts = ann[CLIENT_SEND].timestamp
        ss_ts = ann[CLIENT_RECV].timestamp
        span = replace(
            span,
            annotations=span.annotations + (
                Annotation(sr_ts, SERVER_RECV, endpoint),
                Annotation(ss_ts, SERVER_SEND, endpoint),
            ),
        )
        self.warnings += [WARN_ADDED_SERVER_RECV, WARN_ADDED_SERVER_SEND]
        out = []
        for c in children:
            cann = c.span.annotations_as_map()
            if CLIENT_SEND in cann and CLIENT_RECV in cann and endpoint is not None:
                skew = _compute_skew(
                    sr_ts, ss_ts,
                    cann[CLIENT_SEND].timestamp, cann[CLIENT_RECV].timestamp,
                    endpoint,
                )
                if skew is not None:
                    out.append(_Node(_shift(c.span, skew), list(c.children)))
                    continue
            out.append(c)
        return span, out


class _Node:
    __slots__ = ("span", "children")

    def __init__(self, span, children):
        self.span = span
        self.children = children


def _flatten(node) -> List[Span]:
    out = [node.span]
    for c in node.children:
        out.extend(_flatten(c))
    return out


def _clock_skew(span: Span) -> Optional[ClockSkew]:
    ann = span.annotations_as_map()
    if not all(k in ann for k in (CLIENT_SEND, CLIENT_RECV, SERVER_RECV,
                                  SERVER_SEND)):
        return None
    endpoint = None
    for key in (SERVER_RECV, SERVER_SEND):
        if ann[key].host is not None:
            endpoint = ann[key].host
            break
    if endpoint is None:
        return None
    return _compute_skew(
        ann[CLIENT_SEND].timestamp, ann[CLIENT_RECV].timestamp,
        ann[SERVER_RECV].timestamp, ann[SERVER_SEND].timestamp,
        endpoint,
    )


def _compute_skew(
    client_send: int, client_recv: int, server_recv: int, server_send: int,
    endpoint: Endpoint,
) -> Optional[ClockSkew]:
    client_duration = client_recv - client_send
    server_duration = server_send - server_recv
    cs_ahead = client_send < server_recv
    cr_ahead = client_recv > server_send
    if server_duration > client_duration or (cs_ahead and cr_ahead):
        return None
    latency = (client_duration - server_duration) // 2
    skew = server_recv - latency - client_send
    return ClockSkew(endpoint, skew) if skew != 0 else None


def _shift(span: Span, skew: ClockSkew) -> Span:
    """Shift annotations stamped by the skewed endpoint by -skew."""
    if skew.skew == 0:
        return span
    out = []
    for a in span.annotations:
        ep = a.host
        if ep is not None and (
            ep.ipv4 == skew.endpoint.ipv4
            or (a.value in (CLIENT_SEND, CLIENT_RECV)
                and ep.ipv4 == LOCALHOST_LOOPBACK_IP)
        ):
            out.append(replace(a, timestamp=a.timestamp - skew.skew))
        else:
            out.append(a)
    return replace(span, annotations=tuple(out))
