"""Query request/response model.

Reference: QueryRequest/QueryResponse/Order in zipkin-common
(query/QueryRequest.scala, QueryResponse.scala, Order.scala) and the
thrift shapes in zipkinQuery.thrift:93-251.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple


class QueryException(Exception):
    """Raised for malformed queries (thrift QueryException analogue)."""


class Order(enum.Enum):
    NONE = "none"
    TIMESTAMP_DESC = "timestamp-desc"
    TIMESTAMP_ASC = "timestamp-asc"
    DURATION_DESC = "duration-desc"
    DURATION_ASC = "duration-asc"


@dataclass(frozen=True)
class BinaryAnnotationQuery:
    key: str
    value: bytes


@dataclass(frozen=True)
class QueryRequest:
    service_name: str
    span_name: Optional[str] = None
    annotations: Tuple[str, ...] = ()
    binary_annotations: Tuple[BinaryAnnotationQuery, ...] = ()
    end_ts: int = 0x7FFFFFFFFFFFFFFF
    limit: int = 100
    order: Order = Order.NONE

    def __post_init__(self):
        if not isinstance(self.annotations, tuple):
            object.__setattr__(self, "annotations", tuple(self.annotations))
        if not isinstance(self.binary_annotations, tuple):
            object.__setattr__(
                self, "binary_annotations", tuple(self.binary_annotations)
            )


@dataclass(frozen=True)
class QueryResponse:
    """Sorted trace ids + the time range covered, for pagination
    (QueryResponse.scala: pass ``start_ts`` back as the next end_ts)."""

    trace_ids: Tuple[int, ...] = ()
    start_ts: int = -1
    end_ts: int = -1

    def __post_init__(self):
        if not isinstance(self.trace_ids, tuple):
            object.__setattr__(self, "trace_ids", tuple(self.trace_ids))
