"""Resident query engine: three latency tiers over the SpanStore SPI.

Every on-device query used to pay the same ~105–115 ms p50 at 1B spans
regardless of work (BENCH_1B.json) — the cost is per-request dispatch
+ D2H, not compute. The engine splits the read path so most requests
never touch the device at all, and the ones that must share launches:

1. **Sketch tier** — quantiles, top-k annotations/keys, HLL
   cardinality, and the service/span-name catalogs answered entirely
   from the host sketch mirror (store/mirror.SketchMirror): numpy
   twins of the device's lifetime aggregate arrays, updated
   incrementally by the ingest commit stage inside the write-lock
   hold. ZERO device round-trips; answers are bitwise what the device
   read path returns (gated in tests/test_query_engine.py). On a
   TieredSpanStore the catalog federates the cold tier from zone-map
   metadata alone (tiered.cold_service_ids — host memory, no
   decompression).

2. **Index tier** — trace-id/candidate reads ride the standing
   executor (query/coalesce.ResidentCoalescer): one continuously
   running thread feeds every concurrent request's probes into ONE
   persistent compiled program (``dev.iquery_trace_ids_multi`` over
   the unified [slots,3] arena) with double-buffered staging, so N
   concurrent requests cost one launch + one D2H instead of N.

3. **Result cache** — host-side, keyed on ``(normalized query,
   store.write_frontier())``. The frontier is a host-mirrored
   monotonic commit counter (``TpuSpanStore._step_seq`` — advanced
   inside every donating write-lock hold, so ring eviction is a
   frontier advance — plus a read epoch covering pin/TTL mutations).
   No counter-block fetch; invalidation is precise: a cached entry is
   only ever served at the exact frontier it was computed at, and an
   entry is only STORED when the frontier did not move during its
   computation (so a result that raced a commit can be returned once
   but never pinned stale).

Stores without a frontier (memory/sql) bypass the cache — the
sharded store exports one (fleet step counter + read epoch) and caches
like the single-device store;
stores without a sketch mirror bypass tier 1 — the engine degrades to
a thin executor facade with identical semantics.

Observability (the PR 4 ingest split, applied to reads):
``zipkin_query_serve_seconds{tier=sketch|cache|index}`` is end-to-end
request service time including cache/sketch hits;
``zipkin_query_dispatch_seconds`` isolates actual device launch + D2H
time. Cache hits/misses and sketch answers are counters.

Lifecycle: the engine registers itself on the store
(``register_query_engine``), so ``Collector.flush``/``close`` and
``checkpoint.save`` join the executor into the ordered
drain-queries → drain-pipeline → seal-barrier → WAL-fsync →
checkpoint sequence — no query launch races the checkpoint gather.
After ``close()`` queries still answer (inline, uncoalesced).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Set

import numpy as np

from zipkin_tpu.query.coalesce import ResidentCoalescer
from zipkin_tpu.store.base import ReadSpanStore, service_scan_only

# Cross-request micro-batch window (s) for stores with a batched
# multi-probe kernel; host backends default to 0 (no sleep — see
# QueryEngine._default_window).
DEFAULT_COALESCE_WINDOW_S = 0.002

_MISS = object()


def _copy_json(v):
    """Cheap deep copy for the JSON trees the windowed endpoints
    return (dicts/lists of scalars) — cache hits must never alias a
    mutable value a caller can corrupt (the r11 quantiles lesson)."""
    if isinstance(v, dict):
        return {k: _copy_json(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_json(x) for x in v]
    return v


class _ResultCache:
    """Bounded LRU over ((method, args...), frontier) keys. Entries at
    a superseded frontier can never be served (the lookup key carries
    the CURRENT frontier) and age out of the LRU bound."""

    def __init__(self, entries: int = 1024):
        self.entries = entries
        self._lock = threading.Lock()  # lock-order: 70 result-cache
        self._map: "OrderedDict" = OrderedDict()  # guarded-by: _lock

    def get(self, key):
        with self._lock:
            v = self._map.get(key, _MISS)
            if v is not _MISS:
                self._map.move_to_end(key)
            return v

    def put(self, key, value) -> None:
        with self._lock:
            self._map[key] = value
            self._map.move_to_end(key)
            while len(self._map) > self.entries:
                self._map.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()


class QueryEngine:
    """The resident read path over one SpanStore (see module doc).

    Exposes the read SPI; anything else delegates to the wrapped
    store. QueryService routes every read through an engine;
    construct one directly to reuse across services."""

    def __init__(self, store, window_s: Optional[float] = None,
                 registry=None, cache_entries: int = 1024):
        from zipkin_tpu import obs

        self.store = store
        self.hot = getattr(store, "hot", store)
        reg = registry or obs.default_registry()
        if window_s is None:
            window_s = self._default_window(store)
        self.h_serve = reg.register(obs.LatencySketch(
            "zipkin_query_serve_seconds",
            "Query serve latency end-to-end, by answering tier "
            "(sketch/cache hits included — the user-visible number)",
            labelnames=("tier",)))
        self.h_dispatch = reg.register(obs.LatencySketch(
            "zipkin_query_dispatch_seconds",
            "Device launch + D2H time per query dispatch (the index "
            "tier's floor; sketch/cache answers never appear here)"))
        self.c_hits = reg.register(obs.Counter(
            "zipkin_query_cache_hits_total",
            "Reads answered from the frontier-keyed result cache"))
        self.c_misses = reg.register(obs.Counter(
            "zipkin_query_cache_misses_total",
            "Reads that missed the result cache (served by a lower "
            "tier, then cached when the frontier held still)"))
        self.c_sketch = reg.register(obs.Counter(
            "zipkin_query_sketch_answers_total",
            "Reads answered from host-mirrored sketches "
            "(zero device round-trips)"))
        self.h_window = reg.register(obs.LatencySketch(
            "zipkin_window_query_seconds",
            "Windowed-analytics serve latency by endpoint "
            "(windowed_quantiles / slo_burn / latency_heatmap — "
            "sketch-tier: mirror cells + Moments solve, no device)",
            labelnames=("endpoint",)))
        self.executor = ResidentCoalescer(
            store, window_s=window_s, registry=reg,
            dispatch_timer=self.h_dispatch.observe)
        self.cache = _ResultCache(cache_entries)
        reg.register(obs.Gauge(
            "zipkin_query_cache_entries",
            "Live result-cache entries (all frontiers, LRU-bounded)",
            fn=lambda: float(len(self.cache))))
        self._frontier_fn = getattr(store, "write_frontier", None)
        register = getattr(store, "register_query_engine", None)
        if register is not None:
            register(self)

    @staticmethod
    def _default_window(store) -> float:
        """The window only pays against a per-dispatch floor: stores
        overriding get_trace_ids_multi (the device stores' one-launch
        batched probe) get the 2 ms window; host backends keep 0 so a
        lone request pays no sleep (concurrency alone still builds
        batches while a launch is in flight)."""
        batched = (type(store).get_trace_ids_multi
                   is not ReadSpanStore.get_trace_ids_multi)
        return DEFAULT_COALESCE_WINDOW_S if batched else 0.0

    # -- window (runtime adjustable: daemon /vars/queryWindowMs) --------

    @property
    def window_s(self) -> float:
        return self.executor.window_s

    @window_s.setter
    def window_s(self, v: float) -> None:
        self.executor.window_s = float(v)

    # -- tier plumbing ---------------------------------------------------

    def _frontier(self):
        fn = self._frontier_fn
        return fn() if fn is not None else None

    def _serve(self, tier: str, t0: float) -> None:
        self.h_serve.labels(tier=tier).observe(time.perf_counter() - t0)

    def _cached(self, key: tuple, compute, copy=lambda v: v):
        """Frontier-keyed read-through: serve the cache at the current
        frontier, else compute (timing the store call as dispatch) and
        cache ONLY if the frontier held still across the computation —
        a result that raced a commit may be returned once but is never
        pinned."""
        t0 = time.perf_counter()
        f1 = self._frontier()
        if f1 is not None:
            v = self.cache.get((key, f1))
            if v is not _MISS:
                self.c_hits.inc()
                self._serve("cache", t0)
                return copy(v)
            self.c_misses.inc()
        td = time.perf_counter()
        value = compute()
        self.h_dispatch.observe(time.perf_counter() - td)
        if f1 is not None and self._frontier() == f1:
            self.cache.put((key, f1), value)
        self._serve("index", t0)
        return copy(value)

    def _sketch_mirror(self):
        """The hot store's WARM sketch mirror, or None when the store
        has no mirror (memory/sql/sharded backends)."""
        ensure = getattr(self.hot, "ensure_sketch_mirror", None)
        return ensure() if ensure is not None else None

    # -- index tier: trace-id lookups ------------------------------------

    def get_trace_ids_multi(self, queries) -> List[list]:
        """The read hub: per-query result cache in front of the
        standing executor; only misses ride a device launch. Results
        are exactly serial store execution's."""
        t0 = time.perf_counter()
        queries = [tuple(q) for q in queries]
        if not queries:
            return []
        f1 = self._frontier()
        results: List[Optional[list]] = [None] * len(queries)
        misses: List[int] = []
        if f1 is not None:
            for i, q in enumerate(queries):
                v = self.cache.get((("ids", q), f1))
                if v is _MISS:
                    misses.append(i)
                else:
                    results[i] = list(v)
            self.c_hits.inc(len(queries) - len(misses))
            self.c_misses.inc(len(misses))
        else:
            misses = list(range(len(queries)))
        if misses:
            fresh = self.executor.run([queries[i] for i in misses])
            cacheable = f1 is not None and self._frontier() == f1
            for i, r in zip(misses, fresh):
                results[i] = r
                if cacheable:
                    self.cache.put((("ids", queries[i]), f1), list(r))
        self._serve("cache" if not misses else "index", t0)
        return results  # type: ignore[return-value]

    def get_trace_ids_by_name(self, service_name, span_name, end_ts,
                              limit):
        return self.get_trace_ids_multi(
            [("name", service_name, span_name, end_ts, limit)])[0]

    def get_trace_ids_by_annotation(self, service_name, annotation,
                                    value, end_ts, limit):
        return self.get_trace_ids_multi(
            [("annotation", service_name, annotation, value, end_ts,
              limit)])[0]

    # -- index tier: row reads (frontier-cached) -------------------------

    def traces_exist(self, trace_ids: Sequence[int]) -> Set[int]:
        ids = tuple(trace_ids)
        return self._cached(("exist", ids),
                            lambda: self.store.traces_exist(ids),
                            copy=set)

    def get_spans_by_trace_ids(self, trace_ids: Sequence[int]):
        ids = tuple(trace_ids)
        return self._cached(
            ("spans", ids),
            lambda: self.store.get_spans_by_trace_ids(ids),
            copy=lambda v: [list(t) for t in v])

    def get_traces_duration(self, trace_ids: Sequence[int]):
        ids = tuple(trace_ids)
        return self._cached(
            ("durations", ids),
            lambda: self.store.get_traces_duration(ids), copy=list)

    def get_dependencies(self, start_ts=None, end_ts=None):
        # The first read after writes runs the store's pending sweep
        # (a frontier advance), so it computes-without-caching; repeat
        # reads of a quiet store hit the cache.
        # Dependencies is a frozen dataclass (tuple links) — immutable,
        # so the cached object is safe to hand out by reference.
        return self._cached(
            ("deps", start_ts, end_ts),
            lambda: self.store.get_dependencies(start_ts, end_ts))

    # -- sketch tier: catalogs + aggregates ------------------------------

    def get_all_service_names(self) -> Set[str]:
        t0 = time.perf_counter()
        m = self._sketch_mirror()
        hot = self.hot
        if m is None or len(hot.dicts.services) > hot.config.max_services:
            # Dictionary-overflow services live only in raw ring
            # columns (a device scan) — the store path handles them.
            return self._cached(
                ("service_names",),
                lambda: self.store.get_all_service_names(), copy=set)
        d = hot.dicts.services
        out = {
            d.decode(i) for i in np.flatnonzero(m.service_presence())
            if i < len(d) and d.decode(i)
        }
        cold_ids = getattr(self.store, "cold_service_ids", None)
        if cold_ids is not None:
            out.update(
                name for i in cold_ids()
                if i < len(d) and (name := d.decode(i))
            )
        self.c_sketch.inc()
        self._serve("sketch", t0)
        return out

    def get_span_names(self, service: str) -> Set[str]:
        t0 = time.perf_counter()
        m = self._sketch_mirror()
        hot = self.hot
        fallback = (m is None or hot is not self.store)
        svc = None
        if not fallback:
            svc = hot.dicts.services.get(service.lower())
            if svc is None:
                self.c_sketch.inc()
                self._serve("sketch", t0)
                return set()
            fallback = service_scan_only(svc, hot.config)
        if fallback:
            # Tiered stores decode cold segments for span names, and
            # overflow services need the ring scan — both store paths.
            return self._cached(
                ("span_names", service),
                lambda: self.store.get_span_names(service), copy=set)
        row = m.name_row(svc) > 0
        d = hot.dicts.span_names
        out = {
            d.decode(i) for i in np.flatnonzero(row)
            if i < len(d) and d.decode(i)
        }
        self.c_sketch.inc()
        self._serve("sketch", t0)
        return out

    def _scan_only(self, service: str):
        """(mirror, svc_id, scan_only) for a per-service aggregate —
        these delegate to the HOT store on every backend that has
        them, so the mirror serves tiered stores too."""
        m = self._sketch_mirror()
        if m is None:
            return None, None, True
        svc = self.hot.dicts.services.get(service.lower())
        if svc is None:
            return m, None, False
        return m, svc, service_scan_only(svc, self.hot.config)

    def service_duration_quantiles(self, service: str,
                                   qs: Sequence[float]):
        from zipkin_tpu.ops.quantile import quantiles_host

        t0 = time.perf_counter()
        m, svc, scan = self._scan_only(service)
        if scan:
            return self._cached(
                ("quantiles", service, tuple(qs)),
                lambda: self.store.service_duration_quantiles(
                    service, list(qs)),
                copy=lambda v: None if v is None else list(v))
        self.c_sketch.inc()
        if svc is None:
            self._serve("sketch", t0)
            return None
        vals = quantiles_host(m.hist_row(svc), m.gamma, 1.0, list(qs))
        self._serve("sketch", t0)
        return vals

    def _top_row(self, service: str, k: int, row_of, dictionary,
                 store_fn, kind: str):
        t0 = time.perf_counter()
        m, svc, scan = self._scan_only(service)
        if scan:
            return self._cached((kind, service, k),
                                lambda: store_fn(service, k), copy=list)
        self.c_sketch.inc()
        if svc is None:
            self._serve("sketch", t0)
            return []
        row = row_of(m, svc)
        order = np.argsort(-row)[:k]
        d = dictionary
        out = [
            (d.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(d)
        ]
        self._serve("sketch", t0)
        return out

    def top_annotations(self, service: str, k: int = 10):
        return self._top_row(
            service, k, lambda m, s: m.ann_value_row(s),
            self.hot.dicts.annotations,
            self.store.top_annotations, "top_ann")

    def top_binary_keys(self, service: str, k: int = 10):
        return self._top_row(
            service, k, lambda m, s: m.bann_key_row(s),
            self.hot.dicts.binary_keys,
            self.store.top_binary_keys, "top_bkey")

    def estimated_unique_traces(self) -> float:
        from zipkin_tpu.ops import hll

        t0 = time.perf_counter()
        m = self._sketch_mirror()
        if m is None:
            return self._cached(
                ("unique_traces",),
                lambda: self.store.estimated_unique_traces())
        # Same estimator code path as the store (identical float32
        # arithmetic on identical registers ⇒ identical estimate).
        est = float(hll.estimate(hll.HyperLogLog(m.hll_registers())))
        self.c_sketch.inc()
        self._serve("sketch", t0)
        return est

    # -- sketch tier: windowed analytics ---------------------------------
    # (aggregate/windows.py): the hot store's mirror answers windowed
    # quantiles / burn rates / heatmaps from the (service ×
    # time-bucket) Moments-sketch cells — host math only. Backends
    # without the arena (memory/sql) fall back to their own exact-scan
    # implementations through the frontier cache; stores with neither
    # answer None.

    def _window_call(self, endpoint: str, cache_key: tuple, args: tuple,
                     kwargs: dict, copy=lambda v: v):
        t0 = time.perf_counter()
        hot = self.hot
        fn = getattr(hot, endpoint, None)
        if fn is not None and hasattr(hot, "ensure_sketch_mirror"):
            out = fn(*args, **kwargs)
            if out is None:
                # Disabled arena / unknown service: a null body is not
                # a sketch answer — don't inflate the sketch counters.
                return None
            self.c_sketch.inc()
            self.h_window.labels(endpoint=endpoint).observe(
                time.perf_counter() - t0)
            self._serve("sketch", t0)
            return out
        store_fn = getattr(self.store, endpoint, None)
        if store_fn is None:
            return None
        out = self._cached(cache_key,
                           lambda: store_fn(*args, **kwargs),
                           copy=lambda v: v if v is None else copy(v))
        if out is not None:
            self.h_window.labels(endpoint=endpoint).observe(
                time.perf_counter() - t0)
        return out

    def windowed_quantiles(self, service: str, qs,
                           start_us=None, end_us=None):
        qs = list(qs)
        return self._window_call(
            "windowed_quantiles",
            ("win_q", service, tuple(qs), start_us, end_us),
            (service, qs), {"start_us": start_us, "end_us": end_us},
            copy=list)

    def slo_burn(self, service: str, objective=None, windows_s=None,
                 now_us=None):
        key = ("win_burn", service, objective,
               tuple(windows_s) if windows_s else None, now_us)
        return self._window_call(
            "slo_burn", key, (service,),
            {"objective": objective, "windows_s": windows_s,
             "now_us": now_us}, copy=_copy_json)

    def latency_heatmap(self, service: str, start_us=None, end_us=None,
                        bands=None):
        return self._window_call(
            "latency_heatmap",
            ("win_heat", service, start_us, end_us, bands),
            (service,),
            {"start_us": start_us, "end_us": end_us, "bands": bands},
            copy=_copy_json)

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        """Quiesce barrier: block until the standing executor has no
        launch in flight (Collector.flush / checkpoint.save ordering)."""
        self.executor.drain()

    def close(self) -> None:
        """Stop the executor thread; queries keep answering inline.
        Deregisters from the store so short-lived engines (tests,
        per-request embeddings) don't accumulate in its registry."""
        self.executor.close()
        engines = self.store.__dict__.get("_query_engines")
        if engines is not None and self in engines:
            engines.remove(self)

    # -- store passthrough ----------------------------------------------

    def __getattr__(self, name):
        # Reads the engine doesn't tier (TTL lookups are already
        # host-side) and store admin (counters, set_time_to_live, …)
        # delegate untouched. Only called when normal lookup fails.
        if name == "store":  # not yet bound (mid-__init__/unpickle)
            raise AttributeError(name)
        return getattr(self.store, name)
