"""Cross-request query coalescing: N concurrent trace-id queries share
ONE device launch.

On this device class a jitted call costs ~90-110 ms of dispatch
regardless of work (NOTES_r03 §3); the round-5 verdict measured every
on-device query — index hit or heavy merge alike — paying that launch
floor while the SQLite reference path answers in 2.8 ms. The store
already folds arbitrarily many index probes into one kernel
(SpanStore.get_trace_ids_multi → dev._iq_multi_impl), but only WITHIN
one call: the API server handles each HTTP request on its own thread
(ThreadingHTTPServer), so concurrent requests each paid their own
dispatch. QueryCoalescer adds the cross-request tier: the first
arriving thread becomes the micro-batch LEADER, waits ``window_s`` for
followers, then executes the union through one get_trace_ids_multi
call and hands each caller its slice. Aggregate query throughput then
scales with concurrency instead of serializing on the dispatch floor
(bench.py's batched-query phase measures exactly this).

Correctness: get_trace_ids_multi resolves every query independently
(data-independent probes in one kernel; per-query scan fallbacks run
their own singular paths), so coalesced results are identical to
serial execution — asserted by tests/test_coalesce.py, including a
bitwise batched-vs-unbatched determinism check on the 8-device CPU
mesh.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence


class _Slot:
    """One caller's queries + its rendezvous state."""

    __slots__ = ("queries", "results", "error", "done")

    def __init__(self, queries):
        self.queries = queries
        self.results = None
        self.error = None
        self.done = False


class ResidentCoalescer:
    """Standing micro-batch executor: the QueryCoalescer's leader
    election generalized into ONE continuously-running thread
    (query/engine.py's index tier rides this).

    Double-buffered staging: while the executor thread has a batch on
    the device, new arrivals accumulate in ``_pending`` (the second
    buffer); the thread swaps the buffers the moment the launch
    returns, so consecutive batches pipeline back-to-back with no
    leader re-election and no per-request window sleep once traffic is
    continuous — the Ragged-Paged-Attention dispatch shape (PAPERS.md):
    one persistent compiled program fed micro-batches.

    ``window_s`` only applies when the executor went idle: the first
    request of a quiet period waits at most one window for company.
    A batch that accumulated DURING a previous launch dispatches
    immediately (the launch itself was the window). The attribute is
    writable at runtime (daemon ``/vars/queryWindowMs``).

    ``run`` semantics, accounting fields, and error propagation match
    QueryCoalescer exactly (tests/test_coalesce.py drives both).
    After ``close()`` the thread is gone and ``run`` degrades to
    inline per-caller execution — queries still answer during and
    after an ordered shutdown.

    parallel/dispatch.CrossShardDispatcher is this executor's
    store-level twin for the sharded deployment: same standing-thread
    + double-buffer shape, applied one layer down so ALL cross-shard
    collectives (catalog psums included, not just index probes) fuse
    per micro-window.
    """

    def __init__(self, store, window_s: float = 0.0, registry=None,
                 dispatch_timer: Optional[Callable[[float], None]] = None):
        self.store = store
        self.window_s = window_s
        self._dispatch_timer = dispatch_timer
        self._cv = threading.Condition()  # lock-order: 15 coalesce
        self._pending: List[_Slot] = []  # guarded-by: _cv
        self._inflight = 0  # executing slots; guarded-by: _cv
        self._closed = False  # guarded-by: _cv
        self.batches = 0
        self.queries = 0
        self.launches_saved = 0
        self.max_batch = 0
        from zipkin_tpu import obs

        reg = registry or obs.default_registry()
        self._h_batch = reg.register(obs.LatencySketch(
            "zipkin_query_coalesce_batch_queries",
            "Queries per coalesced device launch (size distribution)",
            min_value=1.0))
        # Requests (slots) per launch — the amortization observable:
        # mean > 1 means concurrent requests genuinely shared launches.
        self._h_size = reg.register(obs.LatencySketch(
            "zipkin_query_coalesce_batch_size",
            "Concurrent requests sharing one coalesced device launch",
            min_value=1.0))
        # Started lazily on the first coalesced run(): a QueryService
        # constructed for a handful of reads (tests, read-only library
        # embedding) never pays a standing thread it didn't use.
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self) -> None:
        # Caller holds _cv and has checked not-closed.
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="zipkin-query-exec", daemon=True)
            self._thread.start()

    def run(self, queries: Sequence[tuple]) -> List[list]:
        """Resolve ``queries`` (SpanStore.get_trace_ids_multi tuples),
        sharing the standing executor's next launch with every
        concurrent caller. Results are exactly serial execution's."""
        queries = list(queries)
        if not queries:
            return []
        slot = _Slot(queries)
        with self._cv:
            if not self._closed:
                self._ensure_thread()
                self._pending.append(slot)
                self._cv.notify_all()
                while not slot.done:
                    self._cv.wait()
                if slot.error is not None:
                    raise slot.error
                return slot.results
        # Executor stopped (ordered shutdown): inline fallback.
        self._execute([slot])
        if slot.error is not None:
            raise slot.error
        return slot.results

    # -- executor thread -------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                waited = False
                while not self._pending and not self._closed:
                    self._cv.wait()
                    waited = True
                if self._closed and not self._pending:
                    return
            # Idle-entry window only: a batch built while the previous
            # launch ran needs no extra wait (see class docstring).
            w = self.window_s
            if waited and w and w > 0:
                time.sleep(w)
            with self._cv:
                batch, self._pending = self._pending, []
                self._inflight = len(batch)
            try:
                self._execute(batch)
            finally:
                with self._cv:
                    self._inflight = 0
                    self._cv.notify_all()

    def _execute(self, batch: List[_Slot]) -> None:
        """Run one batch through ONE get_trace_ids_multi call and
        resolve every slot (on error: every slot, same error)."""
        err = None
        try:
            flat = [q for s in batch for q in s.queries]
            t0 = time.perf_counter()
            res = self.store.get_trace_ids_multi(flat)
            if self._dispatch_timer is not None:
                self._dispatch_timer(time.perf_counter() - t0)
            i = 0
            for s in batch:
                s.results = res[i:i + len(s.queries)]
                i += len(s.queries)
        except BaseException as e:  # noqa: BLE001 — delivered per slot
            err = e
        with self._cv:
            n_q = 0
            for s in batch:
                if s.results is None and s.error is None:
                    s.error = err or RuntimeError("executor died")
                s.done = True
                n_q += len(s.queries)
            self.batches += 1
            self.queries += n_q
            self.launches_saved += len(batch) - 1
            self.max_batch = max(self.max_batch, len(batch))
            self._cv.notify_all()
        self._h_batch.observe(max(n_q, 1))
        self._h_size.observe(max(len(batch), 1))

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        """Block until the executor is idle: nothing pending, nothing
        in flight. The quiesce barrier Collector.flush/checkpoint.save
        use — after it returns, no query launch predating the call is
        still on the device."""
        with self._cv:
            while self._pending or self._inflight:
                self._cv.wait(timeout=0.5)

    def close(self) -> None:
        """Stop the executor thread (processing everything already
        queued); later run() calls execute inline."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed


class QueryCoalescer:
    """Leader-based micro-batcher over ``store.get_trace_ids_multi``.

    ``window_s`` is the cross-request batching window: the leader
    sleeps that long before draining the queue, trading a bounded
    latency add for sharing one device launch among every request that
    arrives inside it (the ItemQueue batch-drain role, applied to the
    read path). ``window_s=0`` still coalesces whatever queued while a
    previous batch executed — concurrency alone builds batches, the
    window just widens them.
    """

    def __init__(self, store, window_s: float = 0.002, registry=None):
        self.store = store
        self.window_s = window_s
        self._cv = threading.Condition()  # lock-order: 15 coalesce
        self._pending: List[_Slot] = []  # guarded-by: _cv
        self._leader_active = False  # guarded-by: _cv
        # Observability (surfaced via /metrics): launches_saved is the
        # number of device dispatches coalescing removed vs one-call-
        # per-request; the sketch is the full batch-size distribution
        # (queries per coalesced launch).
        self.batches = 0
        self.queries = 0
        self.launches_saved = 0
        self.max_batch = 0
        from zipkin_tpu import obs

        reg = registry or obs.default_registry()
        self._h_batch = reg.register(obs.LatencySketch(
            "zipkin_query_coalesce_batch_queries",
            "Queries per coalesced device launch (size distribution)",
            min_value=1.0))
        self._h_size = reg.register(obs.LatencySketch(
            "zipkin_query_coalesce_batch_size",
            "Concurrent requests sharing one coalesced device launch",
            min_value=1.0))

    def run(self, queries: Sequence[tuple]) -> List[list]:
        """Resolve ``queries`` (SpanStore.get_trace_ids_multi tuples),
        sharing a launch with any concurrent callers. Returns one id
        list per query, exactly as the store would serially."""
        queries = list(queries)
        if not queries:
            return []
        slot = _Slot(queries)
        with self._cv:
            self._pending.append(slot)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if not lead:
            with self._cv:
                while not slot.done:
                    self._cv.wait()
            if slot.error is not None:
                raise slot.error
            return slot.results
        # Leader path: from election on, EVERY exit (including an async
        # exception in the sleep or an allocation failure building the
        # flat list) must release leadership and resolve every enqueued
        # slot — a leader that dies without doing both wedges all
        # present AND future callers (followers wait on done; new
        # arrivals defer to the stuck leader flag).
        batch = []
        err = None
        try:
            if self.window_s > 0:
                time.sleep(self.window_s)
            with self._cv:
                batch = self._pending
                self._pending = []
                # New arrivals elect a fresh leader while this batch is
                # on the device — batches pipeline behind the store's
                # own read lock, nothing serializes on this object.
                self._leader_active = False
            flat = [q for s in batch for q in s.queries]
            res = self.store.get_trace_ids_multi(flat)
            i = 0
            for s in batch:
                s.results = res[i:i + len(s.queries)]
                i += len(s.queries)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            err = e
        finally:
            with self._cv:
                if self._leader_active:
                    # Died before the drain: take the queue now so the
                    # waiters fail fast instead of hanging leaderless.
                    batch = batch + self._pending
                    self._pending = []
                    self._leader_active = False
                fail = err or RuntimeError("coalesce leader died")
                n_q = 0
                for s in batch:
                    if s.results is None and s.error is None:
                        s.error = fail
                    s.done = True
                    n_q += len(s.queries)
                self.batches += 1
                self.queries += n_q
                self.launches_saved += len(batch) - 1
                self.max_batch = max(self.max_batch, len(batch))
                self._cv.notify_all()
            self._h_batch.observe(max(n_q, 1))
            self._h_size.observe(max(len(batch), 1))
        if slot.error is not None:
            raise slot.error
        return slot.results
