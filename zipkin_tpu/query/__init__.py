"""Query layer: the ZipkinQuery service semantics over any SpanStore.

Reference parity: zipkin-query (ThriftQueryService.scala:32) — slice
queries with aligned-timestamp intersection, timestamp/duration
ordering, trace assembly with pluggable adjusters (TimeSkewAdjuster),
and summary/timeline/combo projections — re-hosted as a plain python
service over the SpanStore SPI (the RPC surface lives in zipkin_tpu.api).
"""

from zipkin_tpu.query.request import (  # noqa: F401
    BinaryAnnotationQuery,
    Order,
    QueryException,
    QueryRequest,
    QueryResponse,
)
from zipkin_tpu.query.adjusters import TimeSkewAdjuster  # noqa: F401
from zipkin_tpu.query.coalesce import (  # noqa: F401
    QueryCoalescer,
    ResidentCoalescer,
)
from zipkin_tpu.query.engine import QueryEngine  # noqa: F401
from zipkin_tpu.query.service import QueryService  # noqa: F401
