"""QueryService: getTraceIds slice/intersect/order semantics + trace reads.

Reference: ThriftQueryService.scala:32-197 and the older
QueryService.scala:39-511, re-expressed over the SpanStore SPI. The RPC
framing (thrift) is replaced by plain python + the JSON HTTP layer in
zipkin_tpu.api; the semantics — slice queries, probe-then-align
intersection with the one-minute pad, order-by with batched duration
fetches — carry over.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from zipkin_tpu.models.span import Span
from zipkin_tpu.models.trace import Trace, TraceCombo, TraceSummary, TraceTimeline
from zipkin_tpu.query.adjusters import TimeSkewAdjuster
from zipkin_tpu.query.engine import DEFAULT_COALESCE_WINDOW_S, QueryEngine
from zipkin_tpu.query.request import (
    Order,
    QueryException,
    QueryRequest,
    QueryResponse,
)
from zipkin_tpu.store.base import IndexedTraceId, SpanStore

# Reference constants (zipkin-query/.../Constants.scala:26,
# ThriftQueryService.scala:33).
TRACE_TIMESTAMP_PADDING_US = 60 * 1_000_000
DURATION_FETCH_BATCH = 500

__all__ = [
    "DEFAULT_COALESCE_WINDOW_S", "DURATION_FETCH_BATCH", "QueryService",
    "TRACE_TIMESTAMP_PADDING_US",
]


class QueryService:
    def __init__(
        self,
        store: SpanStore,
        adjust_clock_skew: bool = True,
        duration_batch: int = DURATION_FETCH_BATCH,
        coalesce_window_s: Optional[float] = None,
        registry=None,
        engine: Optional[QueryEngine] = None,
    ):
        self.store = store
        self.adjust_clock_skew = adjust_clock_skew
        self.duration_batch = duration_batch
        # EVERY read routes through the resident query engine
        # (query/engine.py): sketch-answerable queries come off the
        # host mirror with zero device round-trips, trace-id lookups
        # share the standing executor's launches, and repeat reads hit
        # the frontier-keyed result cache — with answers exactly equal
        # to direct store execution's. ``coalesce_window_s`` is the
        # executor's idle-entry micro-batch window (None = 2 ms for
        # batched device stores, 0 for host backends).
        self.engine = engine or QueryEngine(
            store, window_s=coalesce_window_s, registry=registry)
        # Back-compat alias: the executor exposes the coalescer's
        # run()/accounting surface (ApiServer's gauges read it).
        self.coalescer = self.engine.executor

    def close(self) -> None:
        """Stop the engine's standing executor thread and deregister
        it from the store. Library consumers embedding a QueryService
        without a Collector own this call; under the daemon,
        Collector.close() reaches the same engines via the store
        registry, so both orders are safe (close is idempotent)."""
        self.engine.close()

    def _multi(self, queries) -> List[List[IndexedTraceId]]:
        return self.engine.get_trace_ids_multi(queries)

    # -- getTraceIds ----------------------------------------------------

    def get_trace_ids(self, qr: QueryRequest) -> QueryResponse:
        if not qr.service_name:
            raise QueryException("No service name provided")
        slices = self._slice_queries(qr)
        if not slices:
            ids = self._multi(
                [("name", qr.service_name, None, qr.end_ts, qr.limit)]
            )[0]
            return self._response(ids, qr)
        if len(slices) == 1:
            return self._response(self._query_slices(slices, qr), qr)
        # Multi-slice: probe each slice at limit 1 to find the latest
        # timestamp they can all reach, pad by one minute, re-query all
        # slices aligned there, then intersect. Both rounds ride the
        # store's batched multi-query path (one device launch per round
        # on the TPU store, instead of one per slice) — and the
        # cross-request coalescer on top of it.
        probes = [
            i for ids in self._multi(
                [self._multi_query(s, qr, qr.end_ts, 1) for s in slices]
            ) for i in ids
        ]
        probe_ts = [i.timestamp for i in probes]
        aligned = (min(probe_ts) if probe_ts else 0) + TRACE_TIMESTAMP_PADDING_US
        per_slice = self._multi([
            self._multi_query(s, qr, aligned, qr.limit) for s in slices
        ])
        common = _intersect(per_slice)
        if not common:
            # Nothing common: report the best next endTs for pagination.
            mins = [
                min((i.timestamp for i in ids), default=0) for ids in per_slice
            ]
            return self._response([], qr, end_ts=max(mins, default=0))
        return self._response(common, qr)

    def _slice_queries(self, qr: QueryRequest) -> List[tuple]:
        slices: List[tuple] = []
        if qr.span_name:
            slices.append(("span", qr.span_name, None))
        for a in qr.annotations:
            slices.append(("annotation", a, None))
        for b in qr.binary_annotations:
            slices.append(("annotation", b.key, b.value))
        return slices

    @staticmethod
    def _multi_query(s, qr: QueryRequest, end_ts: int, limit: int) -> tuple:
        """One slice as a SpanStore.get_trace_ids_multi query tuple."""
        kind, key, value = s
        if kind == "span":
            return ("name", qr.service_name, key, end_ts, limit)
        return ("annotation", qr.service_name, key, value, end_ts, limit)

    def _query_slices(self, slices, qr: QueryRequest, limit: Optional[int] = None
                      ) -> List[IndexedTraceId]:
        per_slice = self._multi([
            self._multi_query(s, qr, qr.end_ts, limit or qr.limit)
            for s in slices
        ])
        return [i for ids in per_slice for i in ids]

    def _response(self, ids: Sequence[IndexedTraceId], qr: QueryRequest,
                  end_ts: int = -1) -> QueryResponse:
        sorted_ids = self._sorted_trace_ids(ids, qr.limit, qr.order)
        if not sorted_ids:
            return QueryResponse((), -1, end_ts)
        ts = [i.timestamp for i in ids]
        return QueryResponse(tuple(sorted_ids), min(ts), max(ts))

    def _sorted_trace_ids(self, ids: Sequence[IndexedTraceId], limit: int,
                          order: Order) -> List[int]:
        if order is Order.NONE:
            return [i.trace_id for i in ids][:limit]
        if order in (Order.TIMESTAMP_DESC, Order.TIMESTAMP_ASC):
            rev = order is Order.TIMESTAMP_DESC
            return [
                i.trace_id
                for i in sorted(ids, key=lambda x: x.timestamp, reverse=rev)
            ][:limit]
        # Duration orders: fetch durations in batches of 500
        # (ThriftQueryService.scala:33, QueryService.scala:493-511).
        tids = [i.trace_id for i in ids]
        durations = []
        for i in range(0, len(tids), self.duration_batch):
            durations.extend(
                self.engine.get_traces_duration(
                    tids[i:i + self.duration_batch])
            )
        rev = order is Order.DURATION_DESC
        return [
            d.trace_id
            for d in sorted(durations, key=lambda x: x.duration, reverse=rev)
        ][:limit]

    # -- trace reads ----------------------------------------------------

    def get_traces_by_ids(self, trace_ids: Sequence[int],
                          adjust: Optional[bool] = None) -> List[Trace]:
        adjust = self.adjust_clock_skew if adjust is None else adjust
        found = self.engine.get_spans_by_trace_ids(trace_ids)
        traces = [Trace(spans) for spans in found]
        if adjust:
            adjuster = TimeSkewAdjuster()
            traces = [adjuster.adjust(t) for t in traces]
        return traces

    def get_trace_summaries_by_ids(self, trace_ids, adjust=None
                                   ) -> List[TraceSummary]:
        out = []
        for t in self.get_traces_by_ids(trace_ids, adjust):
            s = TraceSummary.from_trace(t)
            if s is not None:
                out.append(s)
        return out

    def get_trace_timelines_by_ids(self, trace_ids, adjust=None
                                   ) -> List[TraceTimeline]:
        out = []
        for t in self.get_traces_by_ids(trace_ids, adjust):
            tl = TraceTimeline.from_trace(t)
            if tl is not None:
                out.append(tl)
        return out

    def get_trace_combos_by_ids(self, trace_ids, adjust=None
                                ) -> List[TraceCombo]:
        return [
            TraceCombo.from_trace(t)
            for t in self.get_traces_by_ids(trace_ids, adjust)
        ]

    def trace_exists(self, trace_id: int) -> bool:
        return bool(self.engine.traces_exist([trace_id]))

    def traces_exist(self, trace_ids: Sequence[int]):
        """Which of ``trace_ids`` have any stored span — the thrift
        ``tracesExist(ids)`` method (zipkinQuery.thrift:154), served by
        every backend's batched membership read (the TPU store answers
        through the trace-membership gid buckets when their exactness
        gate holds)."""
        return self.engine.traces_exist(trace_ids)

    # -- catalogs / aggregates -----------------------------------------

    def get_service_names(self):
        return self.engine.get_all_service_names()

    def get_span_names(self, service: str):
        return self.engine.get_span_names(service)

    def get_dependencies(self, start_ts: Optional[int] = None,
                         end_ts: Optional[int] = None):
        """Dependencies from the store's aggregate state, optionally
        restricted to [start_ts, end_ts]
        (Aggregates.getDependencies(startDate, endDate),
        Aggregates.scala:26-31; QueryService.scala:393).

        Stores without dependency aggregation (the in-memory reference
        store) behave like NullAggregates and return zero."""
        from zipkin_tpu.models.dependencies import Dependencies

        if not hasattr(self.engine.store, "get_dependencies"):
            return Dependencies.zero()
        return self.engine.get_dependencies(start_ts, end_ts)

    def get_top_annotations(self, service: str, k: int = 10) -> List[str]:
        if not hasattr(self.engine.store, "top_annotations"):
            return []
        return [a for a, _ in self.engine.top_annotations(service, k)]

    def get_top_key_value_annotations(self, service: str, k: int = 10
                                      ) -> List[str]:
        if not hasattr(self.engine.store, "top_binary_keys"):
            return []
        return [a for a, _ in self.engine.top_binary_keys(service, k)]

    def get_service_duration_quantiles(self, service: str, qs):
        """Per-service latency percentiles off the device histogram
        (BASELINE config #4; the aggregates-page data the reference
        computed offline). Stores without the histogram return None."""
        if not hasattr(self.engine.store,
                       "service_duration_quantiles"):
            return None
        return self.engine.service_duration_quantiles(service, list(qs))

    # -- windowed analytics (aggregate/windows.py) ----------------------
    # Time-scoped latency/error analytics off the windowed
    # Moments-sketch arena — the engine's sketch tier on device
    # stores, the backend's exact scan elsewhere; None when neither
    # can serve.

    def get_windowed_quantiles(self, service: str, qs,
                               start_us=None, end_us=None):
        return self.engine.windowed_quantiles(
            service, list(qs), start_us=start_us, end_us=end_us)

    def get_slo_burn(self, service: str, objective=None,
                     windows_s=None, now_us=None):
        return self.engine.slo_burn(
            service, objective=objective, windows_s=windows_s,
            now_us=now_us)

    def get_latency_heatmap(self, service: str, start_us=None,
                            end_us=None, bands=None):
        return self.engine.latency_heatmap(
            service, start_us=start_us, end_us=end_us, bands=bands)

    def set_trace_time_to_live(self, trace_id: int, ttl_s: float) -> None:
        self.store.set_time_to_live(trace_id, ttl_s)

    def get_trace_time_to_live(self, trace_id: int) -> float:
        return self.store.get_time_to_live(trace_id)

    # -- remaining thrift surface (zipkinQuery.thrift) -----------------

    # Candidate window for the duration/service aggregation methods —
    # the reference aggregates over the traces its index returns for
    # the slice, bounded like any index read.
    SLICE_AGG_LIMIT = 100

    def _slice_trace_spans(self, time_stamp: int, service_name: str,
                           rpc_name: Optional[str], limit: int):
        """Traces matched by the (service, rpc) name index at or before
        ``time_stamp`` — the shared fetch behind getSpanDurations and
        getServiceNamesToTraceIds. Rides the coalescer like every other
        trace-id lookup."""
        if not service_name:
            raise QueryException("No service name provided")
        ids = self._multi([
            ("name", service_name, rpc_name, time_stamp, limit)
        ])[0]
        return self.engine.get_spans_by_trace_ids(
            [i.trace_id for i in ids])

    def get_span_durations(self, time_stamp: int, service_name: str,
                           rpc_name: str,
                           limit: Optional[int] = None
                           ) -> Dict[str, List[int]]:
        """``getSpanDurations(time_stamp, server_service_name,
        rpc_name)`` (zipkinQuery.thrift): for the traces the name index
        matches, the durations (µs) of every span named ``rpc_name``,
        grouped by the span's owning service — the data behind the
        reference's duration-histogram aggregation page."""
        wanted = rpc_name.lower()
        out: Dict[str, List[int]] = {}
        for spans in self._slice_trace_spans(
                time_stamp, service_name, rpc_name,
                limit or self.SLICE_AGG_LIMIT):
            for s in spans:
                if s.name.lower() != wanted or s.duration is None:
                    continue
                svc = s.service_name
                if svc is not None:
                    out.setdefault(svc.lower(), []).append(s.duration)
        return out

    def get_service_names_to_trace_ids(self, time_stamp: int,
                                       service_name: str,
                                       rpc_name: Optional[str],
                                       limit: Optional[int] = None
                                       ) -> Dict[str, List[int]]:
        """``getServiceNamesToTraceIds`` (zipkinQuery.thrift): for the
        traces the (service, rpc) index matches, every service name
        participating in each trace, mapped to the trace ids it appears
        in — the cross-service fan-out view."""
        out: Dict[str, List[int]] = {}
        for spans in self._slice_trace_spans(
                time_stamp, service_name, rpc_name,
                limit or self.SLICE_AGG_LIMIT):
            if not spans:
                continue
            tid = spans[0].trace_id
            names = set()
            for s in spans:
                names.update(s.service_names)
            for n in sorted(names):
                out.setdefault(n, []).append(tid)
        return out

    def get_data_time_to_live(self) -> int:
        """``getDataTimeToLive`` (zipkinQuery.thrift): the storage
        tier's span retention in seconds. Backends with a configured
        TTL expose ``data_ttl_s``; the device ring (eviction-retained)
        and the reference default both answer the Cassandra span TTL
        (CassieSpanStore.scala:47)."""
        from zipkin_tpu.store.base import DEFAULT_SPAN_TTL_S

        ttl = getattr(self.store, "data_ttl_s", None)
        return int(ttl if ttl is not None else DEFAULT_SPAN_TTL_S)


def _intersect(per_slice: List[List[IndexedTraceId]]) -> List[IndexedTraceId]:
    """Ids present in every slice, stamped with their max timestamp
    (traceIdsIntersect, ThriftQueryService.scala:92)."""
    if not per_slice:
        return []
    maps: List[Dict[int, List[int]]] = []
    for ids in per_slice:
        m: Dict[int, List[int]] = {}
        for i in ids:
            m.setdefault(i.trace_id, []).append(i.timestamp)
        maps.append(m)
    common = set(maps[0])
    for m in maps[1:]:
        common &= set(m)
    return [
        IndexedTraceId(tid, max(ts for m in maps for ts in m[tid]))
        for tid in common
    ]
