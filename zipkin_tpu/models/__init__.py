"""Data model: spans, traces, dependency links, and the columnar schema."""
