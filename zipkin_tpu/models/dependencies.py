"""Dependency-link algebra: Moments, DependencyLink, Dependencies.

Parity targets (reference):
- ``DependencyLink(parent, child, durationMoments)`` + Semigroup —
  zipkin-common/.../common/Dependencies.scala:34,38
- ``Dependencies`` Monoid (zero = Time.Top/Bottom, link-map merge) —
  Dependencies.scala:59,67
- algebird ``Moments`` — like algebird, we keep the *central* form
  (n, mean, M2, M3, M4 — Mk = Σ(x-mean)^k) and merge with the
  Chan/Pébay pairwise-combine formulas. Central sums avoid the
  catastrophic cancellation that raw power sums (Σx, Σx², ...) suffer for
  realistic microsecond durations (mean ~1e7, σ ~1e3). The same combine
  runs vectorized on device (zipkin_tpu.ops.sketches.moments_combine).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterable, Sequence, Tuple

_TIME_TOP = float("inf")
_TIME_BOTTOM = float("-inf")


@dataclass(frozen=True)
class Moments:
    """Streaming central moments of a scalar distribution.

    Fields mirror algebird Moments / the thrift wire form m0..m4
    (zipkinDependencies.thrift): ``n`` count, ``mean``, and central sums
    ``m2 = Σ(x-mean)²``, ``m3``, ``m4``.
    """

    n: float = 0.0
    mean: float = 0.0
    m2: float = 0.0
    m3: float = 0.0
    m4: float = 0.0

    @staticmethod
    def of(x: float) -> "Moments":
        return Moments(1.0, x, 0.0, 0.0, 0.0)

    @staticmethod
    def of_many(xs: Iterable[float]) -> "Moments":
        m = Moments.zero()
        for x in xs:
            m = m + Moments.of(x)
        return m

    @staticmethod
    def zero() -> "Moments":
        return Moments()

    def __add__(self, other: "Moments") -> "Moments":
        """Pairwise combine (Chan et al. / Pébay 2008), numerically stable."""
        na, nb = self.n, other.n
        if na == 0:
            return other
        if nb == 0:
            return self
        n = na + nb
        delta = other.mean - self.mean
        d_n = delta / n
        mean = self.mean + nb * d_n
        m2 = self.m2 + other.m2 + delta * d_n * na * nb
        m3 = (
            self.m3
            + other.m3
            + delta * d_n * d_n * na * nb * (na - nb)
            + 3.0 * d_n * (na * other.m2 - nb * self.m2)
        )
        m4 = (
            self.m4
            + other.m4
            + delta * d_n ** 3 * na * nb * (na * na - na * nb + nb * nb)
            + 6.0 * d_n * d_n * (na * na * other.m2 + nb * nb * self.m2)
            + 4.0 * d_n * (na * other.m3 - nb * self.m3)
        )
        return Moments(n, mean, m2, m3, m4)

    # -- derived views --------------------------------------------------

    @property
    def count(self) -> int:
        return int(self.n)

    @property
    def variance(self) -> float:
        return self.m2 / self.n if self.n > 0 else 0.0

    @property
    def stddev(self) -> float:
        return math.sqrt(max(self.variance, 0.0))

    @property
    def skewness(self) -> float:
        if self.n <= 0 or self.m2 <= 0:
            return 0.0
        return math.sqrt(self.n) * self.m3 / self.m2 ** 1.5

    @property
    def kurtosis(self) -> float:
        """Excess kurtosis."""
        if self.n <= 0 or self.m2 <= 0:
            return 0.0
        return self.n * self.m4 / (self.m2 * self.m2) - 3.0

    def to_central(self) -> Tuple[float, float, float, float, float]:
        """(m0..m4) as on the thrift wire (zipkinDependencies.thrift)."""
        return (self.n, self.mean, self.m2, self.m3, self.m4)

    @staticmethod
    def from_central(m0: float, m1: float, m2: float, m3: float, m4: float) -> "Moments":
        return Moments(m0, m1, m2, m3, m4)


@dataclass(frozen=True)
class DependencyLink:
    """One service calling another (Dependencies.scala:34)."""

    parent: str
    child: str
    duration_moments: Moments = field(default_factory=Moments.zero)

    def __add__(self, other: "DependencyLink") -> "DependencyLink":
        if (self.parent, self.child) != (other.parent, other.child):
            raise ValueError("DependencyLink parent/child must match to merge")
        return DependencyLink(
            self.parent, self.child, self.duration_moments + other.duration_moments
        )


def merge_dependency_links(links: Sequence[DependencyLink]) -> list:
    """Group by (parent, child) and sum (Dependencies.scala:45-51)."""
    acc: Dict[Tuple[str, str], DependencyLink] = {}
    for link in links:
        key = (link.parent, link.child)
        acc[key] = acc[key] + link if key in acc else link
    return list(acc.values())


@dataclass(frozen=True)
class Dependencies:
    """All dependency links over a time period (Dependencies.scala:59).

    Monoid: zero has an empty-inverted time range; plus takes the inclusive
    span of both ranges and merges links by (parent, child).
    """

    start_time: float = _TIME_TOP  # microseconds; inf == Time.Top (zero elt)
    end_time: float = _TIME_BOTTOM
    links: Tuple[DependencyLink, ...] = ()

    def __post_init__(self):
        if not isinstance(self.links, tuple):
            object.__setattr__(self, "links", tuple(self.links))

    @staticmethod
    def zero() -> "Dependencies":
        return Dependencies()

    def __add__(self, other: "Dependencies") -> "Dependencies":
        return Dependencies(
            min(self.start_time, other.start_time),
            max(self.end_time, other.end_time),
            tuple(merge_dependency_links(tuple(self.links) + tuple(other.links))),
        )
