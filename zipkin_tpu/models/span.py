"""Immutable span model.

Parity targets (reference, /root/reference):
- ``Span`` trait — zipkin-common/src/main/scala/com/twitter/zipkin/common/Span.scala:89
  (serviceName preference :125, mergeSpan :148, duration :228, isValid :236)
- ``Annotation`` — common/Annotation.scala:27
- ``BinaryAnnotation`` — common/BinaryAnnotation.scala:21
- ``Endpoint`` — common/Endpoint.scala:35

Timestamps are microseconds since epoch throughout, as in the reference.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence, Tuple

from zipkin_tpu.models.constants import (
    CORE_ANNOTATIONS,
    CORE_CLIENT,
    CORE_SERVER,
)


@dataclass(frozen=True, order=True)
class Endpoint:
    """A network endpoint: service name + ipv4 + port.

    Reference: common/Endpoint.scala:35. ipv4 is the packed signed-int form
    used on the wire; port is the unsigned 16-bit port stored in a signed
    short on the wire (we keep it as a plain int 0..65535).
    """

    ipv4: int = 0
    port: int = 0
    service_name: str = "unknown"

    def ipv4_str(self) -> str:
        v = self.ipv4 & 0xFFFFFFFF
        return ".".join(str((v >> s) & 0xFF) for s in (24, 16, 8, 0))


@dataclass(frozen=True)
class Annotation:
    """A timestamped event in a span (reference: common/Annotation.scala:27)."""

    timestamp: int  # microseconds since epoch
    value: str
    host: Optional[Endpoint] = None

    def sort_key(self) -> Tuple[int, str]:
        return (self.timestamp, self.value)


class AnnotationType(enum.IntEnum):
    """Binary annotation value types (reference: zipkinCore.thrift:27-38)."""

    BOOL = 0
    BYTES = 1
    I16 = 2
    I32 = 3
    I64 = 4
    DOUBLE = 5
    STRING = 6


@dataclass(frozen=True)
class BinaryAnnotation:
    """A key/value span tag (reference: common/BinaryAnnotation.scala:21)."""

    key: str
    value: object
    annotation_type: AnnotationType = AnnotationType.STRING
    host: Optional[Endpoint] = None


@dataclass(frozen=True)
class Span:
    """A single RPC span (reference: common/Span.scala:89).

    ``trace_id`` / ``id`` / ``parent_id`` are 64-bit ints (python ints,
    interpreted as signed on the wire). ``annotations`` are kept in insert
    order; ordering-sensitive accessors sort by timestamp like the reference.
    """

    trace_id: int
    name: str
    id: int
    parent_id: Optional[int] = None
    annotations: Tuple[Annotation, ...] = field(default_factory=tuple)
    binary_annotations: Tuple[BinaryAnnotation, ...] = field(default_factory=tuple)
    debug: bool = False

    def __post_init__(self):
        # Normalise sequences to tuples so the dataclass stays hashable.
        if not isinstance(self.annotations, tuple):
            object.__setattr__(self, "annotations", tuple(self.annotations))
        if not isinstance(self.binary_annotations, tuple):
            object.__setattr__(
                self, "binary_annotations", tuple(self.binary_annotations)
            )

    # -- naming ---------------------------------------------------------

    @property
    def service_names(self) -> frozenset:
        """All (lowercased) service names of annotation hosts (Span.scala:120)."""
        return frozenset(
            a.host.service_name.lower() for a in self.annotations if a.host is not None
        )

    @property
    def service_name(self) -> Optional[str]:
        """Best-effort owning service: server-side host, else client-side
        (Span.scala:125)."""
        if not self.annotations:
            return None
        for pool in (self.server_side_annotations, self.client_side_annotations):
            for a in pool:
                if a.host is not None:
                    return a.host.service_name
        return None

    # -- annotation access ----------------------------------------------

    def get_annotation(self, value: str) -> Optional[Annotation]:
        for a in self.annotations:
            if a.value == value:
                return a
        return None

    def get_binary_annotation(self, key: str) -> Optional[BinaryAnnotation]:
        for b in self.binary_annotations:
            if b.key == key:
                return b
        return None

    @property
    def client_side_annotations(self) -> Tuple[Annotation, ...]:
        return tuple(a for a in self.annotations if a.value in CORE_CLIENT)

    @property
    def server_side_annotations(self) -> Tuple[Annotation, ...]:
        return tuple(a for a in self.annotations if a.value in CORE_SERVER)

    def is_client_side(self) -> bool:
        return any(a.value in CORE_CLIENT for a in self.annotations)

    @property
    def first_annotation(self) -> Optional[Annotation]:
        return min(self.annotations, key=Annotation.sort_key, default=None)

    @property
    def last_annotation(self) -> Optional[Annotation]:
        return max(self.annotations, key=Annotation.sort_key, default=None)

    @property
    def first_timestamp(self) -> Optional[int]:
        a = self.first_annotation
        return None if a is None else a.timestamp

    @property
    def last_timestamp(self) -> Optional[int]:
        a = self.last_annotation
        return None if a is None else a.timestamp

    @property
    def endpoints(self) -> frozenset:
        return frozenset(a.host for a in self.annotations if a.host is not None)

    @property
    def client_side_endpoint(self) -> Optional[Endpoint]:
        for a in self.client_side_annotations:
            if a.host is not None:
                return a.host
        return None

    # -- algebra --------------------------------------------------------

    @property
    def duration(self) -> Optional[int]:
        """Microseconds between first and last annotation (Span.scala:228)."""
        first, last = self.first_timestamp, self.last_timestamp
        if first is None or last is None:
            return None
        return last - first

    def is_valid(self) -> bool:
        """True iff at most one of each core annotation (Span.scala:236)."""
        for c in CORE_ANNOTATIONS:
            if sum(1 for a in self.annotations if a.value == c) > 1:
                return False
        return True

    def merge(self, other: "Span") -> "Span":
        """Merge two halves (client/server) of the same span (Span.scala:148)."""
        if self.id != other.id:
            raise ValueError("Span ids must match")
        name = self.name
        if name in ("", "Unknown"):
            name = other.name
        return replace(
            self,
            name=name,
            annotations=self.annotations + other.annotations,
            binary_annotations=self.binary_annotations + other.binary_annotations,
            debug=self.debug or other.debug,
        )

    def annotations_as_map(self) -> dict:
        return {a.value: a for a in self.annotations}


def merge_by_span_id(spans: Sequence[Span]) -> list:
    """Group spans by id and merge each group (query/Trace.scala:178)."""
    by_id: dict = {}
    order: list = []
    for s in spans:
        if s.id in by_id:
            by_id[s.id] = by_id[s.id].merge(s)
        else:
            by_id[s.id] = s
            order.append(s.id)
    return [by_id[i] for i in order]
