"""Trace assembly & projections.

Parity targets (reference):
- ``Trace`` incl. mergeBySpanId / getSpanTree / toSpanDepths —
  zipkin-common/.../query/Trace.scala:36,178,211,147
- ``SpanTreeEntry`` — query/SpanTreeEntry.scala
- ``TraceSummary`` — query/TraceSummary.scala:26,53
- ``TraceTimeline`` — query/TraceTimeline.scala
- ``TraceCombo`` — query/TraceCombo.scala
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span, merge_by_span_id


@dataclass
class SpanTreeEntry:
    """A span plus its children, forming the trace tree."""

    span: Span
    children: List["SpanTreeEntry"] = field(default_factory=list)

    def to_list(self) -> List[Span]:
        out = [self.span]
        for c in self.children:
            out.extend(c.to_list())
        return out

    def depths(self, start_depth: int = 1) -> Dict[int, int]:
        """span id -> depth, root at ``start_depth`` (SpanTreeEntry.depths)."""
        out = {self.span.id: start_depth}
        for c in self.children:
            out.update(c.depths(start_depth + 1))
        return out


@dataclass(frozen=True)
class Trace:
    """A bundle of spans belonging to one trace (query/Trace.scala:36).

    ``spans`` is the merged-by-span-id list sorted by first-annotation
    timestamp (missing timestamps sort last), as in Trace.scala:38-44.
    """

    spans: Tuple[Span, ...]

    def __init__(self, spans: Sequence[Span]):
        merged = merge_by_span_id(spans)
        merged.sort(
            key=lambda s: s.first_timestamp
            if s.first_timestamp is not None
            else float("inf")
        )
        object.__setattr__(self, "spans", tuple(merged))

    @property
    def id(self) -> Optional[int]:
        return self.spans[0].trace_id if self.spans else None

    def get_root_span(self) -> Optional[Span]:
        for s in self.spans:
            if s.parent_id is None:
                return s
        return None

    def get_root_most_span(self) -> Optional[Span]:
        """Root span, or the span closest to the root if the root is missing
        (Trace.scala getRootMostSpan)."""
        root = self.get_root_span()
        if root is not None:
            return root
        if not self.spans:
            return None
        by_id = self.id_to_span_map()
        span = self.spans[0]
        seen = set()
        while (
            span.parent_id is not None
            and span.parent_id in by_id
            and span.id not in seen
        ):
            seen.add(span.id)
            span = by_id[span.parent_id]
        return span

    def get_span_by_id(self, span_id: int) -> Optional[Span]:
        for s in self.spans:
            if s.id == span_id:
                return s
        return None

    def id_to_span_map(self) -> Dict[int, Span]:
        return {s.id: s for s in self.spans}

    # -- time ----------------------------------------------------------

    def start_and_end_timestamp(self) -> Optional[Tuple[int, int]]:
        ts = [a.timestamp for s in self.spans for a in s.annotations]
        if not ts:
            return None
        return (min(ts), max(ts))

    @property
    def duration(self) -> int:
        se = self.start_and_end_timestamp()
        return 0 if se is None else se[1] - se[0]

    # -- structure ------------------------------------------------------

    @property
    def endpoints(self) -> frozenset:
        return frozenset(e for s in self.spans for e in s.endpoints)

    @property
    def services(self) -> frozenset:
        return frozenset(n for s in self.spans for n in s.service_names)

    def service_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.spans:
            for n in s.service_names:
                out[n] = out.get(n, 0) + 1
        return out

    def get_span_tree(
        self,
        root: Span,
        children_index: Optional[Dict[int, List[Span]]] = None,
        _visited: Optional[set] = None,
    ) -> SpanTreeEntry:
        """Build the tree under ``root`` (Trace.scala:211).

        Malformed traces can contain parent-id cycles; the visited guard
        breaks them instead of recursing forever.
        """
        if children_index is None:
            children_index = {}
            for s in self.spans:
                if s.parent_id is not None:
                    children_index.setdefault(s.parent_id, []).append(s)
        if _visited is None:
            _visited = set()
        _visited.add(root.id)
        entry = SpanTreeEntry(root)
        for child in children_index.get(root.id, ()):  # insertion (time) order
            if child.id in _visited:
                continue
            entry.children.append(
                self.get_span_tree(child, children_index, _visited)
            )
        return entry

    def to_span_depths(self) -> Optional[Dict[int, int]]:
        """span id -> depth map from the root-most span (Trace.scala:147)."""
        root = self.get_root_most_span()
        if root is None:
            return None
        return self.get_span_tree(root).depths()


# ---------------------------------------------------------------------------
# Projections


@dataclass(frozen=True)
class SpanTimestamp:
    """Per-span-name start/end used by summary aggregation
    (query/TraceSummary.scala SpanTimestamp)."""

    name: str
    start_timestamp: int
    end_timestamp: int


@dataclass(frozen=True)
class TraceSummary:
    """Condensed trace view (query/TraceSummary.scala:26): trace id, time
    range, per-span timestamps, and involved endpoints. ``service_counts``
    is an extra convenience for the web UI's summary rendering."""

    trace_id: int
    start_timestamp: int
    end_timestamp: int
    duration_micro: int
    span_timestamps: Tuple[SpanTimestamp, ...]
    endpoints: Tuple[Endpoint, ...]
    service_counts: Tuple[Tuple[str, int], ...]

    @staticmethod
    def from_trace(trace: Trace) -> Optional["TraceSummary"]:
        if trace.id is None:
            return None
        se = trace.start_and_end_timestamp()
        if se is None:
            return None
        span_ts = tuple(
            SpanTimestamp(s.name, s.first_timestamp, s.last_timestamp)
            for s in trace.spans
            if s.first_timestamp is not None
        )
        return TraceSummary(
            trace.id,
            se[0],
            se[1],
            se[1] - se[0],
            span_ts,
            tuple(sorted(trace.endpoints)),
            tuple(sorted(trace.service_counts().items())),
        )


@dataclass(frozen=True)
class TimelineAnnotation:
    timestamp: int
    value: str
    host: Optional[Endpoint]
    span_id: int
    parent_id: Optional[int]
    service_name: str
    span_name: str


@dataclass(frozen=True)
class TraceTimeline:
    """Flat, time-ordered view of all annotations (query/TraceTimeline.scala)."""

    trace_id: int
    root_span_id: int
    annotations: Tuple[TimelineAnnotation, ...]
    binary_annotations: Tuple[BinaryAnnotation, ...]

    @staticmethod
    def from_trace(trace: Trace) -> Optional["TraceTimeline"]:
        if not trace.spans:
            return None
        root = trace.get_root_most_span()
        anns = []
        bins: List[BinaryAnnotation] = []
        for s in trace.spans:
            bins.extend(s.binary_annotations)
            for a in s.annotations:
                anns.append(
                    TimelineAnnotation(
                        a.timestamp,
                        a.value,
                        a.host,
                        s.id,
                        s.parent_id,
                        (a.host.service_name if a.host else s.service_name) or "unknown",
                        s.name,
                    )
                )
        anns.sort(key=lambda t: (t.timestamp, t.value))
        return TraceTimeline(
            trace.id, root.id if root else 0, tuple(anns), tuple(bins)
        )


@dataclass(frozen=True)
class TraceCombo:
    """Trace + summary + timeline + depth map bundle (query/TraceCombo.scala)."""

    trace: Trace
    summary: Optional[TraceSummary]
    timeline: Optional[TraceTimeline]
    span_depths: Optional[Dict[int, int]]

    @staticmethod
    def from_trace(trace: Trace) -> "TraceCombo":
        return TraceCombo(
            trace,
            TraceSummary.from_trace(trace),
            TraceTimeline.from_trace(trace),
            trace.to_span_depths(),
        )
