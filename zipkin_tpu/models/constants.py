"""Core annotation constants.

Parity with the reference's ``zipkin-common`` Constants
(/root/reference/zipkin-common/src/main/scala/com/twitter/zipkin/Constants.scala:20-36):
the four core RPC annotations (client send/recv, server send/recv) plus the
client/server address binary-annotation keys.
"""

CLIENT_SEND = "cs"
CLIENT_RECV = "cr"
SERVER_SEND = "ss"
SERVER_RECV = "sr"

CLIENT_ADDR = "ca"
SERVER_ADDR = "sa"

CORE_CLIENT = frozenset((CLIENT_SEND, CLIENT_RECV))
CORE_SERVER = frozenset((SERVER_SEND, SERVER_RECV))
CORE_ANNOTATIONS = CORE_CLIENT | CORE_SERVER
CORE_ADDRESS = frozenset((CLIENT_ADDR, SERVER_ADDR))

# Stable small ids for core annotations in the columnar dictionary space.
# The host DictionaryEncoder reserves these so device-side queries can
# exclude/include core annotations with integer compares.
CORE_ANNOTATION_IDS = {
    CLIENT_SEND: 0,
    CLIENT_RECV: 1,
    SERVER_RECV: 2,
    SERVER_SEND: 3,
    CLIENT_ADDR: 4,
    SERVER_ADDR: 5,
}
FIRST_USER_ANNOTATION_ID = 8
