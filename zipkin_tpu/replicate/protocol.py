"""WAL-ship wire protocol: length-framed, CRC-checked JSON + blobs.

Rides the same framed-TCP shape as the rest of the wire layer (the
scribe server's ``u32 length | payload`` framing,
ingest/scribe_server.py) with the WAL's integrity discipline: every
frame carries a CRC32 over its body, and a bad CRC drops the
connection rather than desyncing the stream.

Frame layout::

    u32 frame_len | u8 msg_type | u32 crc32(body) | body
    body = u32 meta_len | meta json | blobs back-to-back

``meta`` describes the blobs (names/sizes) exactly like wal/record.py
describes its column planes — no per-blob framing. Messages:

client → server
    HELLO  {proto, follower, mode}        — once per connection
    FETCH  {cursor, ack, max_bytes}       — cursor = highest applied
           seq (read position); ack = highest LOCALLY-DURABLE seq
           (retention pin; defaults to cursor). A warm standby acks
           its checkpointed frontier, not its volatile applied one, so
           a crashed standby can always re-replay from its checkpoint.
    ANCHOR {}                             — request a bootstrap anchor

server → client
    HELLO_OK {config, last_seq, durable_seq, first_seq}
    RECORDS  {seqs: [s0, n], sizes: [...], last_seq, durable_seq}
             + the n record payloads as blobs (may be n = 0: heartbeat)
    ANCHOR_OK {applied_seq, wp, dicts, arrays: [[name, dtype, shape]..]}
             + the mirror arrays as blobs
    NEED_ANCHOR {first_seq}               — cursor precedes the log
    ERR      {error}

The FETCH ack advances the follower's retention pin
(wal.register_cursor), so truncation never outruns the slowest
registered follower's DURABLE frontier. RECORDS only ever
carries records at or below the primary's DURABLE frontier — a
follower can never apply what the primary could still lose, which is
what makes "un-acked tail absent in full" hold across the pair.

Fleet-observability ride-alongs (r17, all OPTIONAL meta keys an older
peer simply ignores — the codec passes unknown keys through):

- FETCH may carry ``spans`` (a list of wire-form self-trace spans,
  obs.fleet.span_to_wire) — the follower's apply spans backhauled to
  the primary, which owns the writable store and stitches them into
  the batch-lineage trace; and ``metrics`` (a registry snapshot,
  obs.fleet.registry_snapshot, throttled to ~1/s) — the follower's
  half of the ``/metrics?fleet=1`` federation.
- Record PAYLOADS may carry lineage meta (``ts``, sampled ``b3``) in
  their WAL json header (wal/record.encode_unit extra); followers
  read them with wal/record.unit_meta. Replay ignores the keys, so
  shipped bytes stay bitwise-deterministic inputs to apply.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

PROTO_VERSION = 1

# Message types.
HELLO = 1
FETCH = 2
ANCHOR = 3
HELLO_OK = 16
RECORDS = 17
ANCHOR_OK = 18
NEED_ANCHOR = 19
ERR = 20

_FRAME = struct.Struct(">IBI")  # frame_len covers type+crc+body
_LEN = struct.Struct(">I")
# A frame past this is a desynced/hostile stream, not a message (the
# scribe server's MAX_FRAME role).
MAX_FRAME = 256 << 20


class ShipProtocolError(RuntimeError):
    """Framing/CRC/lineage violation on the ship stream — the
    connection is dropped and re-established rather than resynced."""


def encode_msg(msg_type: int, meta: dict,
               blobs: Tuple[bytes, ...] = ()) -> bytes:
    mjson = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    body = b"".join((_LEN.pack(len(mjson)), mjson, *blobs))
    return _FRAME.pack(
        1 + 4 + len(body), msg_type, zlib.crc32(body)) + body


def decode_msg(frame: bytes) -> Tuple[int, dict, bytes]:
    """(msg_type, meta, blob_bytes) from one frame body (the caller
    already stripped the u32 length word)."""
    if len(frame) < 1 + 4:
        raise ShipProtocolError("short ship frame")
    msg_type = frame[0]
    (crc,) = _LEN.unpack_from(frame, 1)
    body = frame[5:]
    if zlib.crc32(body) != crc:
        raise ShipProtocolError("ship frame CRC mismatch")
    if len(body) < _LEN.size:
        raise ShipProtocolError("truncated ship meta")
    (mlen,) = _LEN.unpack_from(body, 0)
    if mlen > len(body) - _LEN.size:
        raise ShipProtocolError("truncated ship meta")
    meta = json.loads(body[_LEN.size:_LEN.size + mlen].decode("utf-8"))
    return msg_type, meta, body[_LEN.size + mlen:]


def read_msg(sock) -> Optional[Tuple[int, dict, bytes]]:
    """Read one framed message; None on orderly disconnect."""
    from zipkin_tpu.ingest.scribe_server import read_exact

    header = read_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    if n < 5 or n > MAX_FRAME:
        raise ShipProtocolError(f"bad ship frame length {n}")
    frame = read_exact(sock, n)
    if frame is None:
        return None
    return decode_msg(frame)


# -- records ----------------------------------------------------------


def encode_records(records: List[Tuple[int, bytes]], last_seq: int,
                   durable_seq: int) -> bytes:
    meta = {
        "seqs": [records[0][0] if records else 0, len(records)],
        "sizes": [len(p) for _, p in records],
        "last_seq": int(last_seq),
        "durable_seq": int(durable_seq),
    }
    return encode_msg(RECORDS, meta,
                      tuple(p for _, p in records))


def decode_records(meta: dict, blob: bytes
                   ) -> Tuple[List[Tuple[int, bytes]], int, int]:
    s0, n = meta["seqs"]
    sizes = meta["sizes"]
    if len(sizes) != n or sum(sizes) != len(blob):
        raise ShipProtocolError("RECORDS blob/size mismatch")
    out = []
    off = 0
    for i, size in enumerate(sizes):
        out.append((s0 + i, blob[off:off + size]))
        off += size
    return out, int(meta["last_seq"]), int(meta["durable_seq"])


# -- anchors ----------------------------------------------------------


def encode_anchor(applied_seq: int, wp: int, config_dict: dict,
                  dict_values: dict, arrays: List[np.ndarray]) -> bytes:
    specs = []
    blobs = []
    for i, a in enumerate(arrays):
        a = np.ascontiguousarray(a)
        specs.append([f"a{i}", a.dtype.str, list(a.shape)])
        blobs.append(a.tobytes())
    meta = {
        "applied_seq": int(applied_seq), "wp": int(wp),
        "config": config_dict, "dicts": dict_values, "arrays": specs,
    }
    return encode_msg(ANCHOR_OK, meta, tuple(blobs))


def decode_anchor(meta: dict, blob: bytes):
    """(applied_seq, wp, config_dict, dict_values, arrays)."""
    arrays = []
    off = 0
    for _name, dtype, shape in meta["arrays"]:
        dt = np.dtype(dtype)
        count = int(np.prod(shape)) if shape else 1
        nbytes = dt.itemsize * count
        arrays.append(np.frombuffer(
            blob, dtype=dt, count=count, offset=off
        ).reshape(shape).copy())
        off += nbytes
    return (int(meta["applied_seq"]), int(meta["wp"]), meta["config"],
            meta["dicts"], arrays)


# -- config -----------------------------------------------------------


def config_to_dict(config) -> dict:
    """A StoreConfig as a JSON-safe dict (NamedTuple of scalars)."""
    return {k: v for k, v in config._asdict().items()}


def config_from_dict(d: dict):
    from zipkin_tpu.store.device import StoreConfig

    base = StoreConfig()._asdict()
    # Ignore fields this build doesn't know (forward compat) and let
    # the defaults fill ones the primary didn't send.
    base.update({k: v for k, v in d.items() if k in base})
    return StoreConfig(**base)
