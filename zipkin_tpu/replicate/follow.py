"""Follower: pull shipped WAL records and apply them locally.

One ``Follower`` thread drives one target from one primary:

- **device-free replica** (``ReplicaTarget``) — applies records into a
  ``ReplicaSpanStore`` (store/replica.py): sketch mirror + cold
  segments, no TPU. Bootstraps from a primary anchor when its cursor
  precedes the retained log.

- **warm standby** (``StandbyTarget``) — applies records through a
  full device store's NORMAL commit body (wal.apply_record_into — the
  same code crash recovery runs), so the standby's device state is
  bitwise the primary's at every applied sequence. ``promote()``
  detaches the follower and returns the store ready to own writes
  (attach a fresh WAL, open ports); the measured promote latency is
  the failover RTO the bench records.

The fetch loop is pull-based over replicate/protocol.py: each FETCH
carries the cursor (= the ack that advances the primary's retention
pin) and returns durable records only. Disconnects back off and
reconnect; a follower that is AHEAD of the primary's log (the primary
lost un-durable tail the follower somehow applied — impossible under
the durable-only ship rule, so: wrong primary or wiped log) parks a
lineage error instead of diverging silently.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from zipkin_tpu.replicate import protocol as P
from zipkin_tpu.wal.record import WalReplayError


class ShipClient:
    """Minimal blocking client for the ship endpoint."""

    def __init__(self, host: str, port: int, follower: str,
                 mode: str = "replica", timeout_s: float = 30.0):
        self.addr = (host, port)
        self.follower = follower
        self.mode = mode
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self.hello_meta: Optional[dict] = None

    def connect(self) -> dict:
        self.close()
        self._sock = socket.create_connection(self.addr, self.timeout_s)
        self._sock.settimeout(self.timeout_s)
        self._sock.sendall(P.encode_msg(P.HELLO, {
            "proto": P.PROTO_VERSION, "follower": self.follower,
            "mode": self.mode,
        }))
        msg = P.read_msg(self._sock)
        if msg is None or msg[0] != P.HELLO_OK:
            raise P.ShipProtocolError("ship HELLO failed")
        self.hello_meta = msg[1]
        return msg[1]

    def _roundtrip(self, frame: bytes):
        if self._sock is None:
            self.connect()
        self._sock.sendall(frame)
        msg = P.read_msg(self._sock)
        if msg is None:
            raise ConnectionError("ship server closed connection")
        return msg

    def fetch(self, cursor: int, max_bytes: int = 8 << 20,
              ack: Optional[int] = None,
              extra_meta: Optional[dict] = None):
        """(records, last_seq, durable_seq) or None when the primary
        says the cursor needs an anchor bootstrap. ``ack`` moves the
        retention pin (defaults to cursor server-side).
        ``extra_meta`` merges fleet-observability ride-alongs into the
        FETCH frame (``spans`` backhaul, ``metrics`` snapshot — see
        replicate/protocol.py); an older primary ignores them."""
        meta = {"cursor": int(cursor), "max_bytes": int(max_bytes)}
        if ack is not None:
            meta["ack"] = int(ack)
        if extra_meta:
            meta.update(extra_meta)
        msg_type, meta, blob = self._roundtrip(
            P.encode_msg(P.FETCH, meta))
        if msg_type == P.NEED_ANCHOR:
            return None
        if msg_type != P.RECORDS:
            raise P.ShipProtocolError(
                f"unexpected ship reply {msg_type}: {meta}")
        return P.decode_records(meta, blob)

    def anchor(self):
        msg_type, meta, blob = self._roundtrip(
            P.encode_msg(P.ANCHOR, {}))
        if msg_type != P.ANCHOR_OK:
            raise P.ShipProtocolError(
                f"unexpected anchor reply {msg_type}: {meta}")
        return P.decode_anchor(meta, blob)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class ReplicaTarget:
    """Apply shipped records into a device-free ReplicaSpanStore."""

    def __init__(self, store):
        self.store = store

    def applied_seq(self) -> int:
        return self.store.applied_seq()

    def ack_seq(self) -> int:
        """The retention pin the primary may truncate up to. A replica
        re-anchors after total loss BY DESIGN (its state is memory),
        so its applied frontier is its ack."""
        return self.store.applied_seq()

    def apply(self, seq: int, payload: bytes) -> int:
        return self.store.apply_record(seq, payload)

    def adopt_anchor(self, anchor) -> None:
        applied_seq, wp, _config, dict_values, arrays = anchor
        self.store.adopt_anchor(applied_seq, wp, dict_values, arrays)


class StandbyTarget:
    """Apply shipped records through a full device store's normal
    commit body — the warm-standby half of failover."""

    def __init__(self, store):
        from zipkin_tpu.wal.recovery import pin_tids_of

        self.store = store
        self.hot = getattr(store, "hot", store)
        self._pin_tids = pin_tids_of(self.hot)
        # The DURABLE frontier this standby can recover to on its own
        # (its restored checkpoint; 0 for a from-genesis standby).
        # Acking the volatile applied frontier instead would let the
        # primary truncate records a crashed standby still needs —
        # and a standby cannot anchor-bootstrap out of that hole.
        self._ckpt_applied = int(self.hot._wal_applied)

    def applied_seq(self) -> int:
        return int(self.hot._wal_applied)

    def ack_seq(self) -> int:
        return self._ckpt_applied

    def note_checkpointed(self, seq: Optional[int] = None) -> None:
        """Advance the durable ack after a successful LOCAL checkpoint
        save (the follower daemon calls this; without checkpoints the
        standby pins the primary's log at its bootstrap frontier —
        bound it with --wal-retain-bytes or run checkpoints)."""
        seq = self.applied_seq() if seq is None else int(seq)
        self._ckpt_applied = max(self._ckpt_applied, seq)

    def apply(self, seq: int, payload: bytes) -> int:
        from zipkin_tpu.wal.recovery import apply_record_into

        if seq <= self.applied_seq():
            return 0  # reconnect overlap
        return apply_record_into(self.hot, seq, payload,
                                 self._pin_tids)

    def adopt_anchor(self, anchor) -> None:
        raise WalReplayError(
            "warm standby cannot bootstrap from a sketch anchor — "
            "restore a checkpoint of the primary (or start both from "
            "genesis) so the WAL tail covers the gap")


class Follower:
    """The standing fetch-apply loop (see module docstring)."""

    def __init__(self, target, client: ShipClient,
                 poll_interval_s: float = 0.02,
                 max_fetch_bytes: int = 8 << 20,
                 registry=None, lineage=None):
        from zipkin_tpu import obs

        self.target = target
        self.client = client
        # Fleet-observability half (obs.fleet.FollowerLineage): times
        # each record's apply against its shipped commit timestamp
        # (lag seconds), buffers apply spans for the FETCH backhaul,
        # and throttles metric snapshots for federation. None = the
        # pre-r17 wire behavior, byte for byte.
        self.lineage = lineage
        self.poll_interval_s = max(1e-3, float(poll_interval_s))
        self.max_fetch_bytes = int(max_fetch_bytes)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # lock-order: 80 follower-stats
        self._primary_durable = 0  # guarded-by: _lock
        self._primary_last = 0  # guarded-by: _lock
        self._connected = False  # guarded-by: _lock
        self._fetched_bytes = 0  # guarded-by: _lock
        self._applied_records = 0  # guarded-by: _lock
        self._last_apply_ts = 0.0  # guarded-by: _lock
        # Completed fetches that returned NO records (the primary had
        # nothing past our cursor): drain()'s freshness witness.
        self._idle_fetches = 0  # guarded-by: _lock
        self._error: Optional[BaseException] = None  # guarded-by: _lock
        reg = registry or obs.default_registry()
        self._registry = reg
        self.g_lag = reg.register(obs.Gauge(
            "zipkin_replication_lag_records",
            "Durable primary records not yet applied locally",
            fn=lambda: float(self.lag_records())))
        self.g_applied = reg.register(obs.Gauge(
            "zipkin_replication_applied_seq",
            "Highest WAL sequence applied from the primary",
            fn=lambda: float(self.target.applied_seq())))
        self.c_fetched = reg.register(obs.Counter(
            "zipkin_replication_fetched_bytes_total",
            "WAL record bytes fetched from the primary"))
        self.c_applied = reg.register(obs.Counter(
            "zipkin_replication_applied_records_total",
            "Shipped WAL records applied locally"))

    # -- status ----------------------------------------------------------

    def lag_records(self) -> int:
        with self._lock:
            durable = self._primary_durable
        return max(0, durable - self.target.applied_seq())

    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._error

    def status(self) -> dict:
        with self._lock:
            durable = self._primary_durable
            connected = self._connected
            fetched = self._fetched_bytes
            applied_n = self._applied_records
            err = self._error
        return {
            "role": ("standby"
                     if isinstance(self.target, StandbyTarget)
                     else "replica"),
            "primary": "%s:%d" % self.client.addr,
            "connected": connected,
            "appliedSeq": self.target.applied_seq(),
            "primaryDurableSeq": durable,
            "lagRecords": max(0, durable - self.target.applied_seq()),
            "fetchedBytes": fetched,
            "appliedRecords": applied_n,
            "lagSeconds": (self.lineage.lag_seconds()
                           if self.lineage is not None else None),
            "error": repr(err) if err is not None else None,
        }

    # -- loop ------------------------------------------------------------

    def start(self) -> "Follower":
        if self._thread is not None:
            raise RuntimeError("follower already running")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="zipkin-follower")
        self._thread.start()
        return self

    def _run(self) -> None:
        backoff = self.poll_interval_s
        while not self._stop.is_set():
            try:
                made_progress = self.step()
                with self._lock:
                    self._connected = True
                backoff = self.poll_interval_s
                if not made_progress:
                    self._stop.wait(self.poll_interval_s)
            except WalReplayError as e:
                # Lineage divergence is terminal: applying anything
                # further would corrupt the replica. Park and stop.
                with self._lock:
                    self._error = e
                    self._connected = False
                return
            except Exception as e:  # noqa: BLE001 — transient I/O:
                # disconnects/timeouts back off and reconnect; the
                # last error stays visible in status().
                with self._lock:
                    self._error = e
                    self._connected = False
                self.client.close()
                self._stop.wait(backoff)
                backoff = min(2.0, backoff * 2)

    def step(self) -> bool:
        """One fetch-apply round on the caller's thread (the loop and
        the tests share it). Returns True when records were applied."""
        cursor = self.target.applied_seq()
        ack_fn = getattr(self.target, "ack_seq", None)
        extra = None
        lin = self.lineage
        if lin is not None:
            extra = {}
            spans = lin.take_spans()
            if spans:
                extra["spans"] = spans
            snap = lin.maybe_metrics_snapshot()
            if snap is not None:
                extra["metrics"] = snap
        got = self.client.fetch(
            cursor, self.max_fetch_bytes,
            ack=ack_fn() if ack_fn is not None else None,
            extra_meta=extra or None)
        if got is None:
            # Cursor precedes the retained log: bootstrap. "AHEAD of
            # the primary" is judged against the FRESHEST last_seq we
            # have seen (hello OR any RECORDS response) — the
            # connect-time hello alone goes stale the moment records
            # flow, and would misread a legitimate re-anchor (operator
            # dropped our pin + truncated) as lineage divergence.
            with self._lock:
                primary_last = self._primary_last
            primary_last = max(
                primary_last,
                int((self.client.hello_meta or {}).get("last_seq", 0)))
            if cursor > primary_last:
                raise WalReplayError(
                    f"follower at seq {cursor} is AHEAD of the "
                    f"primary's log (last_seq {primary_last}) — wrong "
                    f"primary or wiped log")
            self.target.adopt_anchor(self.client.anchor())
            return True
        records, last, durable = got
        with self._lock:
            self._primary_durable = max(self._primary_durable, durable)
            self._primary_last = max(self._primary_last, last)
            self._error = None
        nbytes = 0
        for seq, payload in records:
            t0 = time.perf_counter()
            self.target.apply(seq, payload)
            nbytes += len(payload)
            if lin is not None:
                lin.observe_record(seq, payload,
                                   time.perf_counter() - t0)
        if records:
            self.c_applied.inc(len(records))
            self.c_fetched.inc(nbytes)
            with self._lock:
                self._applied_records += len(records)
                self._fetched_bytes += nbytes
                self._last_apply_ts = time.time()
        else:
            with self._lock:
                self._idle_fetches += 1
        return bool(records)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until the follower is provably current: an EMPTY
        fetch completed AFTER this call began (the primary reported
        nothing past our cursor) and the lag reads zero. Requiring the
        fresh idle fetch closes the TOCTOU where lag-vs-the-LAST-
        response is already 0 while newer appends sit unfetched.
        Callers quiesce primary writes first (the fixed-frontier
        gate). False on timeout."""
        with self._lock:
            mark0 = self._idle_fetches
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            # Capture ONCE (a concurrent successful fetch clears the
            # parked error between a check and a re-read), and raise
            # only when it is TERMINAL — the loop thread is gone.
            # Transient disconnects are the loop's job to retry; drain
            # just keeps waiting them out inside the timeout.
            err = self.error()
            if err is not None:
                t = self._thread
                if t is None or not t.is_alive():
                    raise err
            with self._lock:
                idle = self._idle_fetches
            if idle > mark0 and self.lag_records() == 0:
                return True
            time.sleep(min(self.poll_interval_s, 0.01))
        return False

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        self.client.close()

    def promote(self):
        """Failover: stop following and hand back the target store,
        ready to own writes. The caller attaches a fresh WAL and opens
        intake — the elapsed time of (stop + final state visibility)
        is the RTO the bench measures."""
        self.stop()
        for m in (self.g_lag, self.g_applied, self.c_fetched,
                  self.c_applied):
            if self._registry.get(m.name) is m:
                self._registry.unregister(m.name)
        return self.target.store

    def close(self) -> None:
        self.stop()
        for m in (self.g_lag, self.g_applied, self.c_fetched,
                  self.c_applied):
            if self._registry.get(m.name) is m:
                self._registry.unregister(m.name)
