"""WAL-shipped replication: warm standbys + device-free read replicas.

The production shape for "millions of concurrent viewers"
(docs/REPLICATION.md): one chip owns the write path, sealed WAL
records stream to followers over the framed-TCP wire layer, and
followers run as either

- a **warm standby** — a full device store replaying through the
  normal commit body (bitwise the primary, measured-RTO failover), or
- a **device-free replica** (store/replica.py) — SketchMirror +
  cold-tier segments on plain CPUs, serving the whole sketch tier and
  zone-map-pruned row queries behind the unchanged SpanStore SPI.

Pieces: ``protocol`` (frames), ``ship`` (primary-side shipper +
server, retention-pinned in the WAL), ``follow`` (the fetch-apply
loop + targets).
"""

from zipkin_tpu.replicate.follow import (  # noqa: F401
    Follower,
    ReplicaTarget,
    ShipClient,
    StandbyTarget,
)
from zipkin_tpu.replicate.protocol import ShipProtocolError  # noqa: F401
from zipkin_tpu.replicate.ship import ShipServer, WalShipper  # noqa: F401

__all__ = [
    "Follower",
    "ReplicaTarget",
    "ShipClient",
    "ShipProtocolError",
    "ShipServer",
    "StandbyTarget",
    "WalShipper",
]
